"""Physical operators over device Batches.

TPU-native replacements for the reference's operator set
(presto-main-base/.../operator/: HashAggregationOperator.java:56,
LookupJoinOperator.java:53, HashBuilderOperator.java:56, TopNOperator.java:32,
OrderByOperator.java:43, LimitOperator.java).  Design per SURVEY.md §7:
static shapes everywhere; selection via the batch mask; aggregation via an
open-addressing scatter table with linear probing unrolled into a fixed
number of vectorized rounds (host doubles the table if a batch exhausts the
rounds); joins via sorted-build + vectorized binary search instead of
pointer-chasing hash tables.  All functions here are jax-traceable; host
drivers sit in pipeline.py.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .batch import Batch, Column

INT64_MIN = jnp.iinfo(jnp.int64).min
INT64_MAX = jnp.iinfo(jnp.int64).max


# ---------------------------------------------------------------------------
# hashing
# ---------------------------------------------------------------------------

def splitmix64(x):
    x = x.astype(jnp.uint64)
    x = (x + jnp.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def hash_columns(cols: List[Column], salt: int = 0):
    """Combined 64-bit hash of key columns (nulls hash distinctly)."""
    h = jnp.full(cols[0].values.shape, jnp.uint64(salt + 1), dtype=jnp.uint64)
    for c in cols:
        v = c.values
        if v.dtype == jnp.float64:
            v = jax.lax.bitcast_convert_type(v, jnp.int64)
        elif v.dtype == jnp.float32:
            v = jax.lax.bitcast_convert_type(v, jnp.int32).astype(jnp.int64)
        elif v.dtype == jnp.bool_:
            v = v.astype(jnp.int64)
        hv = splitmix64(v.astype(jnp.int64).view(jnp.uint64)
                        if hasattr(v, "view") else v)
        if c.nulls is not None:
            hv = jnp.where(c.nulls, jnp.uint64(0x9E3779B97F4A7C15), hv)
        h = splitmix64(h * jnp.uint64(31) + hv)
    return h


# ---------------------------------------------------------------------------
# filter / project
# ---------------------------------------------------------------------------

def apply_filter(batch: Batch, predicate: Column) -> Batch:
    """SQL filter: keep rows where predicate is TRUE (not false, not null)."""
    keep = predicate.values.astype(bool)
    if predicate.nulls is not None:
        keep = keep & ~predicate.nulls
    return batch.with_mask(batch.mask & keep)


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class AggSpec:
    """One aggregate: function name, whether input is float, output column.
    param carries a constant argument (approx_percentile's p)."""
    name: str          # sum/count/count_star/min/max/avg/stddev*/var*/
    #                    corr/covar_pop/covar_samp/approx_percentile
    output: str
    is_float: bool = False
    param: object = None


# aggregates every execution mode supports; anything else routes through
# the scatter-hash or sort paths (run_fused / run_once gate on this)
BASIC_AGGS = {"sum", "avg", "count", "count_star", "min", "max"}
# moment-based aggregates (sum / sum-of-squares / cross-moment state)
MOMENT_AGGS = {"stddev", "stddev_pop", "stddev_samp", "variance",
               "var_pop", "var_samp"}
CORR_AGGS = {"corr", "covar_pop", "covar_samp"}
# aggregates only the sort path implements (need value-ordered segments)
SORT_ONLY_AGGS = {"approx_percentile"}
# HyperLogLog sketch aggregates (dense register arrays, scatter-max)
HLL_AGGS = {"approx_distinct"}

# Dense HLL with 2^11 registers: standard error 1.04/sqrt(2048) = 2.3%,
# the reference's default approx_distinct error bound
# (ApproximateCountDistinctAggregations.java DEFAULT_STANDARD_ERROR=0.023).
HLL_DEFAULT_BUCKETS = 2048
# reference bound on approx_distinct(x, e): lowest/highest accepted max
# standard error (HyperLogLogUtils / NumberOfBuckets limits)
HLL_MIN_STANDARD_ERROR = 0.0040625
HLL_MAX_STANDARD_ERROR = 0.26


def hll_buckets_for_error(e: float) -> int:
    """max-standard-error -> power-of-two register count m with
    1.04/sqrt(m) <= e, clamped to [2^4, 2^16] like the reference."""
    if not (HLL_MIN_STANDARD_ERROR <= e <= HLL_MAX_STANDARD_ERROR):
        raise ValueError(
            f"approx_distinct standard error {e} out of range "
            f"[{HLL_MIN_STANDARD_ERROR}, {HLL_MAX_STANDARD_ERROR}]")
    m = 16
    while 1.04 / math.sqrt(m) > e and m < (1 << 16):
        m *= 2
    return m


def _bit_length64(x):
    """Per-element bit length of a uint64 array (0 for 0)."""
    bl = jnp.zeros(x.shape, dtype=jnp.int32)
    for s in (32, 16, 8, 4, 2, 1):
        big = x >= (jnp.uint64(1) << jnp.uint64(s))
        bl = bl + jnp.where(big, s, 0)
        x = jnp.where(big, x >> jnp.uint64(s), x)
    return bl + (x > 0).astype(jnp.int32)


def _hll_bucket_rank(h, m: int):
    """uint64 hash -> (bucket index int32, rank int8).

    Bucket = low log2(m) bits; rank = leading-zero count of the remaining
    64-p bits + 1 (the HyperLogLog rho function over disjoint bit ranges)."""
    p = m.bit_length() - 1
    bucket = (h & jnp.uint64(m - 1)).astype(jnp.int32)
    rem = h >> jnp.uint64(p)
    rank = ((64 - p) - _bit_length64(rem) + 1).astype(jnp.int8)
    return bucket, rank


def _hll_alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1 + 1.079 / m)


def _hll_estimate(registers, m: int):
    """(G, m) int8 register array -> int64 cardinality estimates (G,).

    Flajolet et al. HyperLogLog with the small-range linear-counting
    correction, the same estimator family as the reference's airlift
    HyperLogLog (ApproximateCountDistinctAggregations.java)."""
    R = registers.reshape(-1, m).astype(jnp.float64)
    Z = jnp.sum(jnp.exp2(-R), axis=1)
    E = _hll_alpha(m) * m * m / Z
    V = jnp.sum(R == 0.0, axis=1)
    lin = m * jnp.log(m / jnp.maximum(V.astype(jnp.float64), 1.0))
    est = jnp.where((E <= 2.5 * m) & (V > 0), lin, E)
    return jnp.round(est).astype(jnp.int64)


def hll_state_bytes(specs) -> int:
    """Extra per-slot accumulator bytes for HLL register arrays."""
    return sum((s.param or HLL_DEFAULT_BUCKETS)
               for s in specs if s.name in HLL_AGGS)


def _chan_merge(na, ma, m2a, nb, mb, m2b):
    """Chan et al. parallel merge of central-moment states (n, mean, M2).

    Numerically stable (no large-magnitude cancellation), and exact at the
    boundaries: an empty side contributes nothing because its mean is 0 and
    the delta term is scaled by na*nb.  Matches the reference's
    CentralMomentsState merge (VarianceAggregation)."""
    n = na + nb
    nf = jnp.maximum(n.astype(jnp.float64), 1.0)
    naf = na.astype(jnp.float64)
    nbf = nb.astype(jnp.float64)
    delta = mb - ma
    mean = ma + delta * nbf / nf
    m2 = m2a + m2b + delta * delta * naf * nbf / nf
    return n, mean, m2


def _moment_finalize(name, mean, m2, n):
    """(value, is_null) for a variance-family aggregate from the central
    moments (mean, M2=Σ(x-mean)², count).  `mean` is unused by the formula
    but kept in the signature for symmetry with the accumulator state."""
    del mean
    nf = n.astype(jnp.float64)
    pop = name in ("stddev_pop", "var_pop")
    denom = jnp.where(pop, jnp.maximum(nf, 1.0),
                      jnp.maximum(nf - 1.0, 1.0))
    var = jnp.maximum(m2, 0.0) / denom
    if name.startswith("stddev"):
        var = jnp.sqrt(var)
    null = n < (1 if pop else 2)
    return var, null


def _corr_finalize(name, m2x, m2y, cxy, n):
    """(value, is_null) from central cross-moments: M2x=Σ(x-mx)²,
    M2y=Σ(y-my)², Cxy=Σ(x-mx)(y-my)."""
    nf = n.astype(jnp.float64)
    if name == "corr":
        den = jnp.sqrt(jnp.maximum(m2x, 0.0) * jnp.maximum(m2y, 0.0))
        null = (n < 1) | (den == 0)
        return cxy / jnp.where(den == 0, 1.0, den), null
    if name == "covar_samp":
        return cxy / jnp.maximum(nf - 1.0, 1.0), n < 2
    return cxy / jnp.maximum(nf, 1.0), n < 1


# numpy (not jnp) scalar: it embeds as a jaxpr literal, so kernel code
# tracing under pallas_call (exec/kernels/grouped.py) can reference it
# without capturing a device-array constant
EMPTY_SLOT = np.uint64(0xFFFFFFFFFFFFFFFF)
PROBE_ROUNDS = 16


def agg_init(num_slots: int, specs: Tuple[AggSpec, ...],
             key_names: Tuple[str, ...], key_dtypes) -> dict:
    """Fresh accumulator state (a pytree dict)."""
    state = {
        "__keyhash": jnp.full(num_slots, EMPTY_SLOT, dtype=jnp.uint64),
        "__occupied": jnp.zeros(num_slots, dtype=bool),
        "__collision": jnp.zeros((), dtype=bool),
    }
    for name, dtype in zip(key_names, key_dtypes):
        state[f"__key_{name}"] = jnp.zeros(num_slots, dtype=dtype)
        state[f"__keynull_{name}"] = jnp.zeros(num_slots, dtype=bool)
    for spec in specs:
        if spec.name in ("count", "count_star"):
            state[spec.output] = jnp.zeros(num_slots, dtype=jnp.int64)
        elif spec.name == "avg":
            dt = jnp.float64 if spec.is_float else jnp.int64
            state[spec.output + "$sum"] = jnp.zeros(num_slots, dtype=dt)
            state[spec.output + "$count"] = jnp.zeros(num_slots, dtype=jnp.int64)
        elif spec.name == "sum":
            dt = jnp.float64 if spec.is_float else jnp.int64
            state[spec.output] = jnp.zeros(num_slots, dtype=dt)
            state[spec.output + "$count"] = jnp.zeros(num_slots, dtype=jnp.int64)
        elif spec.name in ("min", "max"):
            dt = jnp.float64 if spec.is_float else jnp.int64
            init = (jnp.inf if spec.name == "min" else -jnp.inf) if spec.is_float \
                else (INT64_MAX if spec.name == "min" else INT64_MIN)
            state[spec.output] = jnp.full(num_slots, init, dtype=dt)
            state[spec.output + "$count"] = jnp.zeros(num_slots, dtype=jnp.int64)
        elif spec.name in MOMENT_AGGS:
            for suffix in ("$mean", "$m2"):
                state[spec.output + suffix] = jnp.zeros(num_slots,
                                                        dtype=jnp.float64)
            state[spec.output + "$count"] = jnp.zeros(num_slots,
                                                      dtype=jnp.int64)
        elif spec.name in CORR_AGGS:
            for suffix in ("$mx", "$my", "$m2x", "$m2y", "$cxy"):
                state[spec.output + suffix] = jnp.zeros(num_slots,
                                                        dtype=jnp.float64)
            state[spec.output + "$count"] = jnp.zeros(num_slots,
                                                      dtype=jnp.int64)
        elif spec.name in HLL_AGGS:
            m = spec.param or HLL_DEFAULT_BUCKETS
            # flat (num_slots * m) register file: one scatter-max per batch
            state[spec.output + "$hll"] = jnp.zeros(num_slots * m,
                                                    dtype=jnp.int8)
        else:
            raise NotImplementedError(f"aggregate {spec.name}")
    return state


def agg_update(state: dict, batch: Batch, key_cols: List[Column],
               agg_inputs: Dict[str, Optional[Column]],
               specs: Tuple[AggSpec, ...], num_slots: int, salt: int,
               key_names: Tuple[str, ...] = (),
               agg_inputs2: Optional[Dict[str, Column]] = None) -> dict:
    """Scatter one batch into the accumulator table.

    Open addressing, linear probing vectorized as PROBE_ROUNDS scatter rounds:
    each round, still-pending rows propose their keyhash for their current
    slot; a scatter-min picks one winner per free slot; rows whose keyhash now
    matches the slot's keyhash are placed (this includes rows whose key was
    already resident); the rest advance one slot.  Distinct keys are assumed
    to have distinct 64-bit hashes (collision probability ~G²/2⁶⁵).  Rows
    still pending after all rounds set __collision; the host re-runs the
    aggregation with a doubled table (classic table growth, amortized by the
    driver's conservative initial sizing).
    """
    mask = batch.mask
    out = dict(state)

    if key_cols:
        kh = hash_columns(key_cols, salt)
        # reserve the EMPTY sentinel
        kh = jnp.where(kh == EMPTY_SLOT, jnp.uint64(0), kh)
    else:
        kh = jnp.zeros(mask.shape, dtype=jnp.uint64)
    slot = (kh % jnp.uint64(num_slots)).astype(jnp.int32)

    table = state["__keyhash"]
    pending = mask
    placed_slot = jnp.zeros(mask.shape, dtype=jnp.int32)
    for _ in range(PROBE_ROUNDS):
        prop = jnp.where(pending, kh, EMPTY_SLOT)
        attempt = jnp.full(num_slots, EMPTY_SLOT).at[slot].min(prop)
        table = jnp.where(table == EMPTY_SLOT, attempt, table)
        win = pending & (table[slot] == kh)
        placed_slot = jnp.where(win, slot, placed_slot)
        pending = pending & ~win
        slot = jnp.where(pending, (slot + 1) % num_slots, slot)
    out["__collision"] = state["__collision"] | jnp.any(pending)
    out["__keyhash"] = table
    out["__occupied"] = table != EMPTY_SLOT
    mask = mask & ~pending          # drop unplaced rows (retry will redo all)
    # masked rows must not write anywhere: send them out of range + mode=drop
    # (a masked row scattering "current value" into a live slot would race
    # with the real write and could revert it)
    slot = jnp.where(mask, placed_slot, num_slots)

    # representative key values per slot (all rows in a slot share the key).
    # NOTE: pair by explicit key_names — jit round-trips dicts in sorted-key
    # order, so deriving the pairing from state's iteration order misaligns.
    for kname, col in zip(key_names, key_cols):
        name = f"__key_{kname}"
        out[name] = state[name].at[slot].set(col.values, mode="drop")
        if col.nulls is not None:
            out[f"__keynull_{kname}"] = state[f"__keynull_{kname}"].at[slot].set(
                col.nulls, mode="drop")

    for spec in specs:
        if spec.name == "count_star":
            out[spec.output] = state[spec.output].at[slot].add(
                mask.astype(jnp.int64), mode="drop")
            continue
        col = agg_inputs[spec.output]
        valid = mask & ~col.null_mask()
        if spec.name == "count":
            out[spec.output] = state[spec.output].at[slot].add(
                valid.astype(jnp.int64), mode="drop")
            continue
        if spec.name in MOMENT_AGGS:
            # Two scatter passes per batch: batch-local (n, mean), then
            # batch-local M2 around that mean; fold into the running state
            # with the stable Chan merge (no sum-of-squares cancellation).
            x = col.values.astype(jnp.float64)
            vslot = jnp.where(valid, slot, num_slots)
            gslot = jnp.where(valid, slot, 0)
            nb = jnp.zeros(num_slots, jnp.int64).at[vslot].add(
                jnp.ones_like(vslot, dtype=jnp.int64), mode="drop")
            sb = jnp.zeros(num_slots, jnp.float64).at[vslot].add(
                x, mode="drop")
            mb = sb / jnp.maximum(nb.astype(jnp.float64), 1.0)
            cx = jnp.where(valid, x - mb[gslot], 0.0)
            m2b = jnp.zeros(num_slots, jnp.float64).at[vslot].add(
                cx * cx, mode="drop")
            n, mean, m2 = _chan_merge(
                state[spec.output + "$count"], state[spec.output + "$mean"],
                state[spec.output + "$m2"], nb, mb, m2b)
            out[spec.output + "$count"] = n
            out[spec.output + "$mean"] = mean
            out[spec.output + "$m2"] = m2
            continue
        if spec.name in CORR_AGGS:
            c2 = agg_inputs2[spec.output]
            valid = valid & ~c2.null_mask()
            x = col.values.astype(jnp.float64)
            y = c2.values.astype(jnp.float64)
            vslot = jnp.where(valid, slot, num_slots)
            gslot = jnp.where(valid, slot, 0)
            ones = jnp.ones_like(vslot, dtype=jnp.int64)
            nb = jnp.zeros(num_slots, jnp.int64).at[vslot].add(
                ones, mode="drop")
            nbf = jnp.maximum(nb.astype(jnp.float64), 1.0)
            mxb = jnp.zeros(num_slots, jnp.float64).at[vslot].add(
                x, mode="drop") / nbf
            myb = jnp.zeros(num_slots, jnp.float64).at[vslot].add(
                y, mode="drop") / nbf
            cx = jnp.where(valid, x - mxb[gslot], 0.0)
            cy = jnp.where(valid, y - myb[gslot], 0.0)
            zeros = jnp.zeros(num_slots, jnp.float64)
            m2xb = zeros.at[vslot].add(cx * cx, mode="drop")
            m2yb = zeros.at[vslot].add(cy * cy, mode="drop")
            cxyb = zeros.at[vslot].add(cx * cy, mode="drop")
            na = state[spec.output + "$count"]
            n, mx, m2x = _chan_merge(na, state[spec.output + "$mx"],
                                     state[spec.output + "$m2x"],
                                     nb, mxb, m2xb)
            _, my, m2y = _chan_merge(na, state[spec.output + "$my"],
                                     state[spec.output + "$m2y"],
                                     nb, myb, m2yb)
            nf = jnp.maximum(n.astype(jnp.float64), 1.0)
            dx = mxb - state[spec.output + "$mx"]
            dy = myb - state[spec.output + "$my"]
            cxy = (state[spec.output + "$cxy"] + cxyb
                   + dx * dy * na.astype(jnp.float64)
                   * nb.astype(jnp.float64) / nf)
            out[spec.output + "$count"] = n
            out[spec.output + "$mx"] = mx
            out[spec.output + "$my"] = my
            out[spec.output + "$m2x"] = m2x
            out[spec.output + "$m2y"] = m2y
            out[spec.output + "$cxy"] = cxy
            continue
        if spec.name in HLL_AGGS:
            m = spec.param or HLL_DEFAULT_BUCKETS
            # salt-free value hash so register content is identical across
            # probe-salt retries and across tables merged by agg_merge
            bucket, rank = _hll_bucket_rank(hash_columns([col]), m)
            idx = jnp.where(valid, slot * m + bucket, num_slots * m)
            key = spec.output + "$hll"
            out[key] = state[key].at[idx].max(rank, mode="drop")
            continue
        v = col.values
        if spec.is_float and v.dtype != jnp.float64:
            v = v.astype(jnp.float64)
        if not spec.is_float and v.dtype != jnp.int64:
            v = v.astype(jnp.int64)
        if spec.name == "sum" or spec.name == "avg":
            key = spec.output if spec.name == "sum" else spec.output + "$sum"
            out[key] = state[key].at[slot].add(jnp.where(valid, v, 0), mode="drop")
            ckey = spec.output + ("$count" if spec.name == "sum" else "$count")
            out[ckey] = state[ckey].at[slot].add(valid.astype(jnp.int64), mode="drop")
        elif spec.name == "min":
            fill = jnp.inf if spec.is_float else INT64_MAX
            out[spec.output] = state[spec.output].at[slot].min(
                jnp.where(valid, v, fill), mode="drop")
            out[spec.output + "$count"] = state[spec.output + "$count"].at[slot].add(
                valid.astype(jnp.int64), mode="drop")
        elif spec.name == "max":
            fill = -jnp.inf if spec.is_float else INT64_MIN
            out[spec.output] = state[spec.output].at[slot].max(
                jnp.where(valid, v, fill), mode="drop")
            out[spec.output + "$count"] = state[spec.output + "$count"].at[slot].add(
                valid.astype(jnp.int64), mode="drop")
    return out


def agg_merge(a: dict, b: dict, specs: Tuple[AggSpec, ...],
              key_names: Tuple[str, ...], num_slots: int) -> dict:
    """Merge accumulator state `b` into `a` (partial->final combining).

    With probing, the same key can occupy different slots in the two tables,
    so b's occupied slots are re-inserted into a as a pseudo-batch: the slot
    arrays of b become "rows" whose values are b's accumulators.
    """
    out = dict(a)
    mask = b["__occupied"]
    kh = b["__keyhash"]
    slot = (kh % jnp.uint64(num_slots)).astype(jnp.int32)
    table = a["__keyhash"]
    pending = mask
    placed_slot = jnp.zeros(mask.shape, dtype=jnp.int32)
    for _ in range(PROBE_ROUNDS):
        prop = jnp.where(pending, kh, EMPTY_SLOT)
        attempt = jnp.full(num_slots, EMPTY_SLOT).at[slot].min(prop)
        table = jnp.where(table == EMPTY_SLOT, attempt, table)
        win = pending & (table[slot] == kh)
        placed_slot = jnp.where(win, slot, placed_slot)
        pending = pending & ~win
        slot = jnp.where(pending, (slot + 1) % num_slots, slot)
    out["__collision"] = a["__collision"] | b["__collision"] | jnp.any(pending)
    out["__keyhash"] = table
    out["__occupied"] = table != EMPTY_SLOT
    mask = mask & ~pending
    slot = jnp.where(mask, placed_slot, num_slots)

    for kname in key_names:
        out[f"__key_{kname}"] = a[f"__key_{kname}"].at[slot].set(
            b[f"__key_{kname}"], mode="drop")
        out[f"__keynull_{kname}"] = a[f"__keynull_{kname}"].at[slot].set(
            b[f"__keynull_{kname}"], mode="drop")

    def _add(key):
        out[key] = a[key].at[slot].add(
            jnp.where(mask, b[key], jnp.zeros((), b[key].dtype)), mode="drop")

    def _realign(key, dtype=jnp.float64):
        # b's per-slot values re-addressed to a's slot space; distinct keys
        # land on distinct slots, so add-into-zeros is an exact placement
        return jnp.zeros(num_slots, dtype).at[slot].add(
            jnp.where(mask, b[key], jnp.zeros((), b[key].dtype)),
            mode="drop")

    for spec in specs:
        if spec.name in MOMENT_AGGS:
            nb = _realign(spec.output + "$count", jnp.int64)
            n, mean, m2 = _chan_merge(
                a[spec.output + "$count"], a[spec.output + "$mean"],
                a[spec.output + "$m2"], nb,
                _realign(spec.output + "$mean"),
                _realign(spec.output + "$m2"))
            out[spec.output + "$count"] = n
            out[spec.output + "$mean"] = mean
            out[spec.output + "$m2"] = m2
        elif spec.name in CORR_AGGS:
            na = a[spec.output + "$count"]
            nb = _realign(spec.output + "$count", jnp.int64)
            mxb = _realign(spec.output + "$mx")
            myb = _realign(spec.output + "$my")
            n, mx, m2x = _chan_merge(na, a[spec.output + "$mx"],
                                     a[spec.output + "$m2x"], nb, mxb,
                                     _realign(spec.output + "$m2x"))
            _, my, m2y = _chan_merge(na, a[spec.output + "$my"],
                                     a[spec.output + "$m2y"], nb, myb,
                                     _realign(spec.output + "$m2y"))
            nf = jnp.maximum(n.astype(jnp.float64), 1.0)
            dx = mxb - a[spec.output + "$mx"]
            dy = myb - a[spec.output + "$my"]
            cxy = (a[spec.output + "$cxy"] + _realign(spec.output + "$cxy")
                   + dx * dy * na.astype(jnp.float64)
                   * nb.astype(jnp.float64) / nf)
            out[spec.output + "$count"] = n
            out[spec.output + "$mx"] = mx
            out[spec.output + "$my"] = my
            out[spec.output + "$m2x"] = m2x
            out[spec.output + "$m2y"] = m2y
            out[spec.output + "$cxy"] = cxy
        elif spec.name in ("count", "count_star"):
            _add(spec.output)
        elif spec.name == "avg":
            _add(spec.output + "$sum")
            _add(spec.output + "$count")
        elif spec.name == "sum":
            _add(spec.output)
            _add(spec.output + "$count")
        elif spec.name == "min":
            fill = jnp.asarray(jnp.inf if spec.is_float else INT64_MAX,
                               a[spec.output].dtype)
            out[spec.output] = a[spec.output].at[slot].min(
                jnp.where(mask, b[spec.output], fill), mode="drop")
            _add(spec.output + "$count")
        elif spec.name == "max":
            fill = jnp.asarray(-jnp.inf if spec.is_float else INT64_MIN,
                               a[spec.output].dtype)
            out[spec.output] = a[spec.output].at[slot].max(
                jnp.where(mask, b[spec.output], fill), mode="drop")
            _add(spec.output + "$count")
        elif spec.name in HLL_AGGS:
            m = spec.param or HLL_DEFAULT_BUCKETS
            key = spec.output + "$hll"
            breg = b[key].reshape(-1, m)
            rows = jnp.where(mask, slot, a["__keyhash"].shape[0])
            out[key] = a[key].reshape(-1, m).at[rows].max(
                jnp.where(mask[:, None], breg, jnp.int8(0)),
                mode="drop").reshape(-1)
    return out


# ---------------------------------------------------------------------------
# direct (small-domain) aggregation: when every group key is a closed-domain
# dictionary/bool column, the combined code IS the slot index — no hashing,
# no probing, no scatter.  Per batch this is G masked reductions, which XLA
# fuses into single passes; on TPU this is ~50x faster than the scatter
# table for the TPC-H Q1 shape (6 groups over 6M rows).
# ---------------------------------------------------------------------------

DIRECT_AGG_MAX_GROUPS = 64
# max accumulator length for span-direct (scatter-indexed) aggregation
SPAN_AGG_MAX_GROUPS = 1 << 26


def agg_direct_init(G: int, specs: Tuple[AggSpec, ...]) -> dict:
    state = {"__seen": jnp.zeros(G, dtype=jnp.int64)}
    for spec in specs:
        if spec.name in ("count", "count_star"):
            state[spec.output] = jnp.zeros(G, dtype=jnp.int64)
        elif spec.name == "avg":
            dt = jnp.float64 if spec.is_float else jnp.int64
            state[spec.output + "$sum"] = jnp.zeros(G, dtype=dt)
            state[spec.output + "$count"] = jnp.zeros(G, dtype=jnp.int64)
        elif spec.name == "sum":
            dt = jnp.float64 if spec.is_float else jnp.int64
            state[spec.output] = jnp.zeros(G, dtype=dt)
            state[spec.output + "$count"] = jnp.zeros(G, dtype=jnp.int64)
        elif spec.name in ("min", "max"):
            dt = jnp.float64 if spec.is_float else jnp.int64
            init = (jnp.inf if spec.name == "min" else -jnp.inf) \
                if spec.is_float \
                else (INT64_MAX if spec.name == "min" else INT64_MIN)
            state[spec.output] = jnp.full(G, init, dtype=dt)
            state[spec.output + "$count"] = jnp.zeros(G, dtype=jnp.int64)
        else:
            raise NotImplementedError(spec.name)
    return state


def agg_direct_update(state: dict, batch: Batch, codes,
                      agg_inputs: Dict[str, Optional[Column]],
                      specs: Tuple[AggSpec, ...], G: int) -> dict:
    """codes: combined group code per row (int, < G).  A Pallas MXU
    grouped-sum kernel was benchmarked here and DELETED: the one-hot grid
    below fuses into the surrounding program and measured faster on chip
    (0.166s vs 0.191s, TPC-H Q1 SF10 warm)."""
    grid = (codes[None, :] == jnp.arange(G, dtype=codes.dtype)[:, None]) \
        & batch.mask[None, :]
    out = dict(state)
    out["__seen"] = state["__seen"] + grid.sum(axis=1)
    for spec in specs:
        if spec.name == "count_star":
            out[spec.output] = state[spec.output] + grid.sum(axis=1)
            continue
        col = agg_inputs[spec.output]
        sel = grid if col.nulls is None else grid & ~col.nulls[None, :]
        nn = sel.sum(axis=1)
        x = col.values
        if x.dtype == jnp.bool_:
            x = x.astype(jnp.int8)
        if spec.name == "count":
            out[spec.output] = state[spec.output] + nn
        elif spec.name in ("sum", "avg"):
            dt = jnp.float64 if spec.is_float else jnp.int64
            xs = jnp.where(sel, x[None, :].astype(dt), 0).sum(axis=1)
            if spec.name == "avg":
                out[spec.output + "$sum"] = state[spec.output + "$sum"] + xs
            else:
                out[spec.output] = state[spec.output] + xs
            out[spec.output + "$count"] = \
                state[spec.output + "$count"] + nn
        elif spec.name in ("min", "max"):
            is_min = spec.name == "min"
            if spec.is_float:
                ident = jnp.array(jnp.inf if is_min else -jnp.inf,
                                  jnp.float64)
                xv = x.astype(jnp.float64)
            else:
                ident = jnp.array(INT64_MAX if is_min else INT64_MIN,
                                  jnp.int64)
                xv = x.astype(jnp.int64)
            vals = jnp.where(sel, xv[None, :], ident)
            red = vals.min(axis=1) if is_min else vals.max(axis=1)
            out[spec.output] = (jnp.minimum if is_min else jnp.maximum)(
                state[spec.output], red)
            out[spec.output + "$count"] = \
                state[spec.output + "$count"] + nn
    return out


def agg_span_init(G: int, specs: Tuple[AggSpec, ...]) -> dict:
    """State for span-direct aggregation: integer group codes in [0, G)
    index the accumulators directly (code = combined key - base) — no
    hashing, no probing, no collision retries.  The TPU-native replacement
    for the scatter hash table whenever the key span is bounded (dense PK
    group-bys like TPC-H Q3/Q18's l_orderkey).  Group keys are not stored:
    the caller reconstructs them from the slot index (see
    agg_span_finalize)."""
    state = agg_direct_init(G, specs)
    return state


def agg_span_update(state: dict, batch: Batch, codes,
                    agg_inputs: Dict[str, Optional[Column]],
                    specs: Tuple[AggSpec, ...], G: int) -> dict:
    """codes: per-row group index (int, in [0, G) for live rows); masked
    rows are routed out of range and dropped.

    All accumulator columns of one op/dtype class are packed into a single
    (N, k) -> (G, k) scatter: TPU scatters cost per-INDEX, so a scalar
    scatter wastes the lane dimension — one packed scatter of k columns
    runs ~k times faster than k scalar scatters (measured 5.5x for k=6 at
    4M rows).  NULL handling folds into the updates (add of 0 / min of
    +inf is a no-op), so every column shares one slot vector."""
    mask = batch.mask
    slot = jnp.where(mask, codes, G).astype(jnp.int32)
    out = dict(state)
    ones = mask.astype(jnp.int64)

    adds_i: List[Tuple[str, jnp.ndarray]] = [("__seen", ones)]
    adds_f: List[Tuple[str, jnp.ndarray]] = []
    mins: List[Tuple[str, jnp.ndarray]] = []
    maxs: List[Tuple[str, jnp.ndarray]] = []
    for spec in specs:
        if spec.name == "count_star":
            adds_i.append((spec.output, ones))
            continue
        col = agg_inputs[spec.output]
        valid = mask & ~col.null_mask()
        vones = valid.astype(jnp.int64)
        if spec.name == "count":
            adds_i.append((spec.output, vones))
            continue
        v = col.values
        if spec.is_float and v.dtype != jnp.float64:
            v = v.astype(jnp.float64)
        if not spec.is_float and v.dtype != jnp.int64:
            v = v.astype(jnp.int64)
        if spec.name in ("sum", "avg"):
            key = spec.output if spec.name == "sum" else spec.output + "$sum"
            (adds_f if spec.is_float else adds_i).append(
                (key, jnp.where(valid, v, jnp.zeros((), v.dtype))))
            adds_i.append((spec.output + "$count", vones))
        elif spec.name in ("min", "max"):
            is_min = spec.name == "min"
            ident = ((jnp.inf if is_min else -jnp.inf) if spec.is_float
                     else (INT64_MAX if is_min else INT64_MIN))
            upd = jnp.where(valid, v, jnp.asarray(ident, v.dtype))
            (mins if is_min else maxs).append((spec.output, upd))
            adds_i.append((spec.output + "$count", vones))

    def apply(group, op):
        if not group:
            return
        if len(group) == 1:
            key, upd = group[0]
            out[key] = getattr(state[key].at[slot], op)(upd, mode="drop")
            return
        acc = jnp.stack([state[k] for k, _ in group], axis=1)
        upd = jnp.stack([u for _, u in group], axis=1)
        acc = getattr(acc.at[slot], op)(upd, mode="drop")
        for i, (key, _) in enumerate(group):
            out[key] = acc[:, i]

    apply(adds_i, "add")
    apply(adds_f, "add")
    # min/max need dtype-uniform packing; split by dtype
    for group, op in ((mins, "min"), (maxs, "max")):
        by_dt: Dict = {}
        for key, upd in group:
            by_dt.setdefault(upd.dtype, []).append((key, upd))
        for sub in by_dt.values():
            apply(sub, op)
    return out


def agg_span_finalize(state: dict, specs: Tuple[AggSpec, ...],
                      key_names: Tuple[str, ...],
                      key_arrays: Dict[str, jnp.ndarray],
                      key_dicts: Dict[str, Tuple[str, ...]],
                      key_lazy: Optional[Dict[str, Tuple]] = None,
                      key_nulls: Optional[Dict[str, jnp.ndarray]] = None
                      ) -> Batch:
    """key_arrays: slot-index -> key value per key (reconstructed by the
    caller, e.g. base + arange(G) for a single-int-key span)."""
    fake = dict(state)
    fake["__occupied"] = state["__seen"] > 0
    G = state["__seen"].shape[0]
    for k in key_names:
        fake[f"__key_{k}"] = key_arrays[k]
        fake[f"__keynull_{k}"] = (key_nulls or {}).get(
            k, jnp.zeros(G, dtype=bool))
    return agg_finalize(fake, specs, key_names, key_dicts, key_lazy)


def _depkey_as_int64(col: Column):
    """A grouping key's values as an exact int64 representation (floats
    bitcast — the dependency check needs per-group CONSTANCY, and rows of
    one underlying source row carry bit-identical values)."""
    v = col.values
    if v.dtype == jnp.float64:
        return jax.lax.bitcast_convert_type(v, jnp.int64)
    if v.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(v, jnp.int32).astype(jnp.int64)
    if v.dtype == jnp.bool_:
        return v.astype(jnp.int64)
    return v.astype(jnp.int64)


def _depkey_restore(minv, dtype):
    if dtype == jnp.float64:
        return jax.lax.bitcast_convert_type(minv, jnp.float64)
    if dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(
            minv.astype(jnp.int32), jnp.float32)
    return minv.astype(dtype)


def depkey_init(G: int, names: Tuple[str, ...]) -> dict:
    """Accumulators verifying that grouping keys are CONSTANT within each
    anchor-key group (the runtime-span multi-key scheme: group by one
    integer anchor, prove the other keys functionally dependent)."""
    st = {}
    for k in names:
        st[f"__dep_{k}$min"] = jnp.full(G, INT64_MAX, dtype=jnp.int64)
        st[f"__dep_{k}$max"] = jnp.full(G, INT64_MIN, dtype=jnp.int64)
        st[f"__dep_{k}$nulls"] = jnp.zeros(G, dtype=jnp.int64)
    return st


def depkey_update(st: dict, batch: Batch, codes, key_cols: Dict[str, Column],
                  G: int) -> dict:
    """Constancy tracking for the dependent grouping keys in as few
    scatters as possible: min and NEGATED max share one packed min-scatter
    (max(x) == -min(-x); identities chosen so INT64_MIN never negates),
    and null counting is skipped entirely for columns with no null mask
    (lazy row-ids / dictionary codes — the common case)."""
    out = dict(st)
    if not key_cols:
        return out
    mask = batch.mask
    slot = jnp.where(mask, codes, G).astype(jnp.int32)
    names = list(key_cols)
    mins, nulls_names, nulls = [], [], []
    for k in names:
        c = key_cols[k]
        v = _depkey_as_int64(c)
        if c.nulls is None:
            valid = mask
        else:
            valid = mask & ~c.nulls
            nulls_names.append(k)
            nulls.append((mask & c.nulls).astype(jnp.int64))
        mins.append(jnp.where(valid, v, INT64_MAX))
        # negated-max lane: min over (-v) recovers max; clamp so the
        # identity never overflows on negation
        mins.append(jnp.where(valid, -jnp.maximum(v, -INT64_MAX),
                              INT64_MAX))
    acc = jnp.stack(
        [st[f"__dep_{k}$min"] for k in names]
        + [-jnp.maximum(st[f"__dep_{k}$max"], -INT64_MAX) for k in names],
        axis=1)
    # interleave is (min_0, negmax_0, min_1, negmax_1, ...) for updates but
    # (mins..., negmaxs...) for state — align both as [mins..., negmaxs...]
    upd = jnp.stack([mins[2 * i] for i in range(len(names))]
                    + [mins[2 * i + 1] for i in range(len(names))], axis=1)
    acc = acc.at[slot].min(upd, mode="drop")
    for i, k in enumerate(names):
        out[f"__dep_{k}$min"] = acc[:, i]
        out[f"__dep_{k}$max"] = -acc[:, len(names) + i]
    if nulls:
        nacc = jnp.stack([st[f"__dep_{k}$nulls"] for k in nulls_names],
                         axis=1)
        nacc = nacc.at[slot].add(jnp.stack(nulls, axis=1), mode="drop")
        for i, k in enumerate(nulls_names):
            out[f"__dep_{k}$nulls"] = nacc[:, i]
    return out


def depkey_verify(st: dict, seen, names: Tuple[str, ...]):
    """All-groups scalar: every dependent key is uniform (one non-null
    value, or all NULL) within every occupied group."""
    ok = jnp.ones((), dtype=bool)
    for k in names:
        minv = st[f"__dep_{k}$min"]
        maxv = st[f"__dep_{k}$max"]
        nc = st[f"__dep_{k}$nulls"]
        uniform = ((nc == 0) & (minv == maxv)) | (nc == seen)
        ok = ok & jnp.all(uniform | (seen == 0))
    return ok


def _decimal_avg(s, cnt, empty):
    """Presto decimal avg: round-half-away-from-zero integer division at
    the input scale (single definition shared by the hash, window, and
    sort aggregation paths)."""
    safe = jnp.where(empty, 1, cnt)
    q = jnp.sign(s) * ((jnp.abs(s) + safe // 2) // safe)
    return q.astype(jnp.int64)


def _packed_gather(columns: List[Column], perm) -> Dict[int, Column]:
    """Gather columns through one permutation with dtype-packed indexing:
    same-dtype value arrays stack into an (N, k) matrix gathered ONCE
    (TPU gathers cost per-index — k packed lanes are ~3x faster than k
    scalar gathers), null masks pack as their own bool group.  Returns
    {id(original column): gathered Column}."""
    by_dtype: Dict = {}
    for c in columns:
        by_dtype.setdefault(c.values.dtype, []).append(c)
    out_vals: Dict[int, jnp.ndarray] = {}
    for items in by_dtype.values():
        if len(items) == 1:
            out_vals[id(items[0])] = items[0].values[perm]
        else:
            stacked = jnp.stack([c.values for c in items], axis=1)[perm]
            for i, c in enumerate(items):
                out_vals[id(c)] = stacked[:, i]
    nullable = [c for c in columns if c.nulls is not None]
    out_nulls: Dict[int, jnp.ndarray] = {}
    if len(nullable) == 1:
        out_nulls[id(nullable[0])] = nullable[0].nulls[perm]
    elif nullable:
        stacked = jnp.stack([c.nulls for c in nullable], axis=1)[perm]
        for i, c in enumerate(nullable):
            out_nulls[id(c)] = stacked[:, i]
    return {id(c): Column(out_vals[id(c)], out_nulls.get(id(c)),
                          c.dictionary, c.lazy) for c in columns}


# ---------------------------------------------------------------------------
# streaming quantile summary for global approx_percentile
#
# The reference streams t-digest state
# (ApproximateLongPercentileAggregations.java); the XLA-friendly mergeable
# summary here is the classic equal-weight quantile summary: each input
# batch is reduced to its m equi-spaced order statistics plus its row
# count (one device sort per batch, static shapes), and the final
# percentile is the weighted nearest-rank over the union of all batch
# summaries — each summary point stands for count/m rows.  Rank error is
# bounded by the within-batch summarization only: <= 1/(2m) of each
# batch's weight, so <= 1/(2m) overall (m=8192 -> 0.006% rank error);
# the final union step is exact, so error does NOT grow with batch count.
# Summaries from disjoint spill buckets merge by concatenation, the same
# property the reference gets from t-digest merge.
# ---------------------------------------------------------------------------

PERCENTILE_SKETCH_POINTS = 8192


def percentile_batch_summary(values, alive, m: int = PERCENTILE_SKETCH_POINTS):
    """(values, alive mask) -> (points: (m,) float64, count: int64).
    Points are the m equi-spaced order statistics of the alive values
    (all-NaN when count == 0).  Jit-safe, static shapes."""
    v = values.astype(jnp.float64)
    # alive rows first, ordered by value (flag sort keeps NaN payloads of
    # dead lanes out of the prefix)
    perm = jnp.lexsort((v, ~alive))
    vs = v[perm]
    cnt = jnp.sum(alive.astype(jnp.int64))
    j = jnp.arange(m)
    # equi-spaced ranks over [0, cnt-1]; cnt==0 -> gather index 0, masked
    # by the NaN fill below
    pos = jnp.floor(j * jnp.maximum(cnt - 1, 0) / (m - 1) + 0.5) \
        .astype(jnp.int32)
    pts = vs[jnp.clip(pos, 0, vs.shape[0] - 1)]
    pts = jnp.where(cnt > 0, pts, jnp.nan)
    return pts, cnt


def percentile_union_value(points, counts, p: float):
    """(B, m) batch summary points + (B,) counts -> (value, is_null).
    Weighted nearest-rank over the union: point i of batch b represents
    counts[b]/m rows.  Exact given the summaries."""
    B, m = points.shape
    w = jnp.repeat(counts.astype(jnp.float64) / m, m)     # (B*m,)
    flat = points.reshape(-1)
    valid = ~jnp.isnan(flat)
    w = jnp.where(valid, w, 0.0)
    order = jnp.lexsort((flat, ~valid))
    fv, fw = flat[order], w[order]
    cum = jnp.cumsum(fw)
    total = jnp.sum(counts)
    # nearest-rank in row space (same rounding as the sort path's
    # floor(p*(cnt-1)+0.5)): the answer is the first summary point whose
    # cumulative weight exceeds the target row index
    target = jnp.floor(p * jnp.maximum(total - 1, 0).astype(jnp.float64)
                       + 0.5)
    idx = jnp.searchsorted(cum, target, side="right")
    val = fv[jnp.clip(idx, 0, fv.shape[0] - 1)]
    return val, total == 0


def sort_group_aggregate(batch: Batch, key_names: Tuple[str, ...],
                         agg_inputs: Dict[str, Optional[Column]],
                         specs: Tuple[AggSpec, ...],
                         agg_inputs2: Optional[Dict[str, Column]] = None
                         ) -> Batch:
    """Grouped aggregation by SORT + segmented scans — argsort, gathers,
    cumsums and associative scans only, NO scatters.  On TPU a scatter
    costs ~100ms per million rows while sorts and scans stream at memory
    bandwidth, so this is the high-cardinality replacement for the
    scatter hash table (the reference's HashAggregationOperator falls
    back to no such trick — this is the TPU-native formulation).

    Groups by the combined 64-bit key hash (distinct keys assumed to have
    distinct hashes — the same assumption the scatter table makes).
    Output: capacity == input capacity, one live row per group at its
    segment-start position."""
    if key_names:
        kh = _orderable_hash(hash_columns(
            [batch.columns[k] for k in key_names]))
    else:
        # global aggregation: every live row in one segment
        kh = jnp.zeros(batch.mask.shape, dtype=jnp.int64)
    kh = jnp.where(batch.mask, kh, INT64_MAX)
    perm = jnp.argsort(kh).astype(jnp.int32)
    khs = kh[perm]
    n = khs.shape[0]
    live = khs != INT64_MAX
    is_start = live & jnp.concatenate(
        [jnp.ones(1, dtype=bool), khs[1:] != khs[:-1]])
    if not key_names:
        # SQL: a global aggregate yields one row even over empty input
        # (the dead row-0 segment has zero contributions -> NULL/0 row)
        is_start = is_start.at[0].set(True)
    # int32 index math: int64-indexed gathers are ~8x slower on TPU and
    # n is far below 2^31 (SORT_AGG_MAX_BYTES bound)
    idx = jnp.arange(n, dtype=jnp.int32)
    # exclusive end of each segment = next segment start (suffix-min)
    nxt = jnp.flip(jax.lax.cummin(jnp.flip(
        jnp.where(is_start, idx, n))))
    seg_end = jnp.concatenate([nxt[1:], jnp.full(1, n, dtype=jnp.int32)])
    seg_end = jnp.where(live, seg_end, idx + 1)
    s_lo = idx
    s_hi = jnp.clip(seg_end, 0, n).astype(jnp.int32)
    # per-row segment START (for whole-group values at interior rows)
    seg_start_row = jax.lax.cummax(jnp.where(is_start, idx, 0)) \
        .astype(jnp.int32)

    # -- packed gathers: the permutation gather is the dominant cost here
    # (TPU gathers pay per-index; one (N, k) gather of k same-dtype
    # columns runs ~3x faster than k scalar gathers), so key and input
    # columns are stacked by dtype and gathered once per dtype
    gather_cols: Dict[int, Column] = {}
    for k in key_names:
        gather_cols[id(batch.columns[k])] = batch.columns[k]
    for spec in specs:
        if spec.name not in ("count_star", "approx_percentile"):
            c = agg_inputs[spec.output]
            gather_cols[id(c)] = c
    if agg_inputs2:
        for c in agg_inputs2.values():
            gather_cols[id(c)] = c
    gathered = _packed_gather(list(gather_cols.values()), perm)

    # -- packed segment counts/sums: every spec needs its segment count,
    # sum/avg need a value sum — ONE stacked cumsum per dtype class
    # replaces a cumsum per spec
    i64_items: List[jnp.ndarray] = []
    f64_items: List[jnp.ndarray] = []
    plan = []           # (spec, contrib, x, cnt_idx, sum_idx, is_f64)
    for spec in specs:
        if spec.name in ("count_star", "approx_percentile"):
            contrib, x = live, None
        else:
            c = gathered[id(agg_inputs[spec.output])]
            contrib = live & ~c.null_mask()
            x = c.values
        cnt_idx = len(i64_items)
        i64_items.append(contrib.astype(jnp.int64))
        sum_idx = None
        is_f64 = False
        if spec.name in ("sum", "avg"):
            dt = jnp.float64 if spec.is_float else jnp.int64
            xv = jnp.where(contrib, x, 0).astype(dt)
            is_f64 = spec.is_float
            if is_f64:
                sum_idx = len(f64_items)
                f64_items.append(xv)
            else:
                sum_idx = len(i64_items)
                i64_items.append(xv)
        plan.append((spec, contrib, x, cnt_idx, sum_idx, is_f64))

    def _seg(items, dt):
        if not items:
            return None
        m = jnp.stack(items)                              # (k, N)
        p = jnp.concatenate([jnp.zeros((len(items), 1), dtype=dt),
                             jnp.cumsum(m, axis=1)], axis=1)
        return p[:, s_hi] - p[:, s_lo]                    # (k, N)

    seg_i = _seg(i64_items, jnp.int64)
    seg_f = _seg(f64_items, jnp.float64)

    cols: Dict[str, Column] = {}
    for k in key_names:
        cols[k] = gathered[id(batch.columns[k])]
    for spec, contrib, x, cnt_idx, sum_idx, is_f64 in plan:
        cnt = seg_i[cnt_idx]
        if spec.name in ("count", "count_star"):
            cols[spec.output] = Column(cnt, None)
            continue
        empty = cnt == 0
        if spec.name in ("sum", "avg"):
            s = (seg_f if is_f64 else seg_i)[sum_idx]
            if spec.name == "sum":
                cols[spec.output] = Column(s, empty)
            else:
                if spec.is_float:
                    safe = jnp.where(empty, 1, cnt)
                    cols[spec.output] = Column(s / safe, empty)
                else:
                    cols[spec.output] = Column(_decimal_avg(s, cnt, empty),
                                               empty)
        elif spec.name in ("min", "max"):
            is_min = spec.name == "min"
            if spec.is_float:
                ident = jnp.array(jnp.inf if is_min else -jnp.inf,
                                  jnp.float64)
                xv = x.astype(jnp.float64)
            else:
                ident = jnp.array(INT64_MAX if is_min else INT64_MIN,
                                  jnp.int64)
                xv = x.astype(jnp.int64)
            xv = jnp.where(contrib, xv, ident)

            def comb(a, b, _min=is_min):
                fa, va = a
                fb, vb = b
                m = jnp.minimum(va, vb) if _min else jnp.maximum(va, vb)
                return (fa | fb, jnp.where(fb, vb, m))

            _, run = jax.lax.associative_scan(comb, (is_start, xv))
            vals = run[jnp.clip(s_hi - 1, 0, n - 1)]
            cols[spec.output] = Column(vals, empty)
        elif spec.name in MOMENT_AGGS:
            # numerically stable two-pass: the group mean comes from the
            # first prefix sum IN THE SAME program, then the second pass
            # accumulates centered squares (the reference's
            # VarianceAggregation keeps central moments for the same
            # reason)
            xf = jnp.where(contrib, x.astype(jnp.float64), 0.0)
            ps = jnp.concatenate([jnp.zeros(1), jnp.cumsum(xf)])
            c0m = jnp.concatenate([jnp.zeros(1, dtype=jnp.int64),
                                   jnp.cumsum(contrib.astype(jnp.int64))])
            g_sum = ps[s_hi] - ps[seg_start_row]     # whole-group, per row
            g_cnt = c0m[s_hi] - c0m[seg_start_row]
            mean_row = g_sum / jnp.maximum(g_cnt, 1)
            d = jnp.where(contrib, x.astype(jnp.float64) - mean_row, 0.0)
            ps2 = jnp.concatenate([jnp.zeros(1), jnp.cumsum(d * d)])
            m2 = ps2[s_hi] - ps2[s_lo]
            pop = spec.name in ("stddev_pop", "var_pop")
            denom = jnp.maximum(cnt if pop else cnt - 1, 1) \
                .astype(jnp.float64)
            v = m2 / denom
            if spec.name.startswith("stddev"):
                v = jnp.sqrt(v)
            null = cnt < (1 if pop else 2)
            cols[spec.output] = Column(v, null)
        elif spec.name in CORR_AGGS:
            c2 = gathered[id(agg_inputs2[spec.output])]
            contrib2 = contrib & ~c2.null_mask()
            c0 = jnp.concatenate([jnp.zeros(1, dtype=jnp.int64),
                                  jnp.cumsum(contrib2.astype(jnp.int64))])
            n2 = c0[s_hi] - c0[s_lo]
            xf = jnp.where(contrib2, x.astype(jnp.float64), 0.0)
            yf = jnp.where(contrib2, c2.values.astype(jnp.float64), 0.0)
            # two-pass centered cross-moments (same stability rationale as
            # the MOMENT branch); stacked cumsums keep the HLO op count low
            stack1 = jnp.stack([xf, yf])
            p1 = jnp.concatenate(
                [jnp.zeros((2, 1)), jnp.cumsum(stack1, axis=1)], axis=1)
            g_cnt = jnp.maximum(c0[s_hi] - c0[seg_start_row], 1)
            mean_x = (p1[0, s_hi] - p1[0, seg_start_row]) / g_cnt
            mean_y = (p1[1, s_hi] - p1[1, seg_start_row]) / g_cnt
            dx = jnp.where(contrib2, x.astype(jnp.float64) - mean_x, 0.0)
            dy = jnp.where(contrib2,
                           c2.values.astype(jnp.float64) - mean_y, 0.0)
            stack2 = jnp.stack([dx * dx, dy * dy, dx * dy])
            p2 = jnp.concatenate(
                [jnp.zeros((3, 1)), jnp.cumsum(stack2, axis=1)], axis=1)
            seg = p2[:, s_hi] - p2[:, s_lo]
            v, null = _corr_finalize(spec.name, seg[0], seg[1], seg[2], n2)
            cols[spec.output] = Column(v, null)
        elif spec.name == "approx_percentile":
            # value-ordered secondary sort: NULL/dead rows sort last
            # within their key-hash segment, then the nearest-rank element
            # is one gather at fs + round(p * (cnt-1))
            p = float(spec.param if spec.param is not None else 0.5)
            xc = agg_inputs[spec.output]
            vx = xc.values
            alive = batch.mask & ~xc.null_mask()
            # dead/NULL rows ordered by an explicit flag (not an in-band
            # value sentinel, which legitimate inf/INT64_MAX values or
            # NaN would interleave with)
            perm_p = jnp.lexsort((vx, ~alive, kh)).astype(jnp.int32)
            vx_sorted = vx[perm_p]
            alive_p = alive[perm_p]
            a0 = jnp.concatenate([jnp.zeros(1, dtype=jnp.int64),
                                  jnp.cumsum(alive_p.astype(jnp.int64))])
            cntp = a0[s_hi] - a0[s_lo]
            pos = s_lo + jnp.floor(
                p * jnp.maximum(cntp - 1, 0) + 0.5).astype(jnp.int32)
            vals = vx_sorted[jnp.clip(pos, 0, n - 1)]
            cols[spec.output] = Column(vals, cntp == 0, xc.dictionary,
                                       xc.lazy)
        else:
            raise NotImplementedError(spec.name)
    return Batch(cols, is_start)


def agg_direct_finalize(state: dict, specs: Tuple[AggSpec, ...],
                        key_names: Tuple[str, ...],
                        key_doms: Tuple[int, ...],
                        key_dtypes,
                        key_dicts: Dict[str, Tuple[str, ...]],
                        force_row: bool = False) -> Batch:
    """Decode slot index -> key codes, then reuse agg_finalize.
    force_row: a global aggregation yields one row even over no input."""
    G = 1
    for d in key_doms:
        G *= d
    fake = dict(state)
    fake["__occupied"] = (state["__seen"] > 0) | force_row
    slot = jnp.arange(G, dtype=jnp.int64)
    stride = G
    for k, dom, dt in zip(key_names, key_doms, key_dtypes):
        stride //= dom
        code = (slot // stride) % dom
        fake[f"__key_{k}"] = code.astype(dt)
        fake[f"__keynull_{k}"] = jnp.zeros(G, dtype=bool)
    return agg_finalize(fake, specs, key_names, key_dicts)


def agg_finalize(state: dict, specs: Tuple[AggSpec, ...],
                 key_names: Tuple[str, ...],
                 key_dicts: Dict[str, Tuple[str, ...]],
                 key_lazy: Optional[Dict[str, Tuple]] = None) -> Batch:
    """Accumulator table -> output Batch (capacity == num_slots, mask ==
    occupied).  Runs under jit; host later compacts via batch_to_page.

    key_lazy carries late-materialization tags for open-domain string keys:
    such keys group by row identity (their values are source row ids), which
    is exact whenever a unique key is also in the grouping set (the TPC-H
    Q10 shape: c_custkey determines c_address/c_comment)."""
    occupied = state["__occupied"]
    cols: Dict[str, Column] = {}
    for name in key_names:
        cols[name] = Column(state[f"__key_{name}"],
                            state.get(f"__keynull_{name}"),
                            key_dicts.get(name),
                            (key_lazy or {}).get(name))
    for spec in specs:
        if spec.name in ("count", "count_star"):
            cols[spec.output] = Column(state[spec.output], None)
        elif spec.name == "sum":
            # SQL: sum of zero non-null inputs is NULL
            empty = state[spec.output + "$count"] == 0
            cols[spec.output] = Column(state[spec.output], empty)
        elif spec.name == "avg":
            s = state[spec.output + "$sum"]
            c = state[spec.output + "$count"]
            empty = c == 0
            safe_c = jnp.where(empty, 1, c)
            if spec.is_float:
                cols[spec.output] = Column(s / safe_c, empty)
            else:
                cols[spec.output] = Column(_decimal_avg(s, c, empty), empty)
        elif spec.name in ("min", "max"):
            empty = state[spec.output + "$count"] == 0
            cols[spec.output] = Column(state[spec.output], empty)
        elif spec.name in MOMENT_AGGS:
            v, null = _moment_finalize(
                spec.name, state[spec.output + "$mean"],
                state[spec.output + "$m2"],
                state[spec.output + "$count"])
            cols[spec.output] = Column(v, null)
        elif spec.name in CORR_AGGS:
            v, null = _corr_finalize(
                spec.name, state[spec.output + "$m2x"],
                state[spec.output + "$m2y"], state[spec.output + "$cxy"],
                state[spec.output + "$count"])
            cols[spec.output] = Column(v, null)
        elif spec.name in HLL_AGGS:
            m = spec.param or HLL_DEFAULT_BUCKETS
            # approx_distinct is never NULL: 0 over empty/all-null input
            cols[spec.output] = Column(
                _hll_estimate(state[spec.output + "$hll"], m), None)
    return Batch(cols, occupied)


# ---------------------------------------------------------------------------
# join: sorted build + vectorized binary search probe
# ---------------------------------------------------------------------------

def _orderable_hash(kh):
    """uint64 hash -> order-preserving int64 (searchsorted on uint64 may go
    through float64 and lose low bits; int64 compares exactly)."""
    return (kh ^ jnp.uint64(0x8000000000000000)).astype(jnp.int64)


@dataclass
class BuildTable:
    """Materialized, hash-sorted build side (pytree)."""
    keyhash_sorted: jnp.ndarray      # order-preserving int64, padding = max
    perm: jnp.ndarray                # sort permutation (int32)
    columns: Dict[str, Column]       # original (unsorted) build columns
    valid_count: jnp.ndarray         # scalar int32
    run_len: jnp.ndarray             # per-position equal-key run length

    def tree_flatten(self):
        names = tuple(sorted(self.columns))
        return ((self.keyhash_sorted, self.perm,
                 tuple(self.columns[n] for n in names), self.valid_count,
                 self.run_len),
                names)

    @classmethod
    def tree_unflatten(cls, names, children):
        kh, perm, cols, vc, rl = children
        return cls(kh, perm, dict(zip(names, cols)), vc, rl)


jax.tree_util.register_pytree_node_class(BuildTable)


def build_table(batch: Batch, key_names: List[str], salt: int = 0) -> BuildTable:
    """Sort the build side by key hash (padding rows sort to the end).

    Also precomputes per-position run lengths so the probe can derive match
    counts from ONE searchsorted (searchsorted is the most expensive
    primitive in the probe on TPU; see probe_join).  All index arrays are
    int32: int64-indexed gathers are ~8x slower on TPU."""
    key_cols = [batch.columns[k] for k in key_names]
    kh = _orderable_hash(hash_columns(key_cols, salt))
    kh = jnp.where(batch.mask, kh, jnp.iinfo(jnp.int64).max)
    perm = jnp.argsort(kh).astype(jnp.int32)
    kh_sorted = kh[perm]
    n = kh_sorted.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    is_start = jnp.concatenate([jnp.ones(1, dtype=bool),
                                kh_sorted[1:] != kh_sorted[:-1]])
    run_start = jax.lax.cummax(jnp.where(is_start, pos, 0))
    run_len = _run_end(is_start, n) - run_start
    return BuildTable(kh_sorted, perm, dict(batch.columns),
                      jnp.sum(batch.mask).astype(jnp.int32),
                      run_len)


def _run_end(is_start, n):
    """Per-position exclusive end of the containing equal-key run: the next
    run's start, filled backwards (reverse cummin of start positions)."""
    pos = jnp.arange(n, dtype=jnp.int32)
    starts_rev = jnp.where(is_start, pos, n)[::-1]
    return jnp.concatenate(
        [jax.lax.cummin(starts_rev)[::-1][1:],
         jnp.full(1, n, dtype=jnp.int32)])


def probe_join(batch: Batch, table: BuildTable, probe_keys: List[str],
               build_output: List[str], out_capacity: int,
               salt: int = 0, join_type: str = "INNER", filter_fn=None,
               matched=None):
    """Equi-join probe: returns (joined Batch, overflow flag, total).

    Output columns = all probe columns + build_output (renamed by caller).
    INNER: one output row per (probe row, matching build row) passing the
    optional non-equi `filter_fn` (a Batch -> Column predicate over the
    expanded rows).
    LEFT: probe rows with NO surviving match (the filter applies to pairs
    BEFORE null-extension, per SQL ON semantics) produce one row with nulls
    on the build side; output capacity is out_capacity + batch.capacity.
    """
    # ONE searchsorted (the dominant primitive cost on TPU): the left
    # insertion point plus the build side's precomputed run lengths give
    # the match count; int32 index math keeps gathers ~8x faster than
    # int64-indexed ones.
    kh = _orderable_hash(hash_columns(
        [batch.columns[k] for k in probe_keys], salt))
    nb = table.perm.shape[0]
    # scan_unrolled: ~2x the default scan method's throughput on TPU
    lo = jnp.searchsorted(table.keyhash_sorted, kh, side="left",
                          method="scan_unrolled").astype(jnp.int32)
    lo_c = jnp.clip(lo, 0, nb - 1)
    hit = table.keyhash_sorted[lo_c] == kh
    # SQL equi-join: a NULL key never matches (exec/reference.py:452-457)
    for k in probe_keys:
        nn = batch.columns[k].nulls
        if nn is not None:
            hit = hit & ~nn
    counts = jnp.where(batch.mask & hit, table.run_len[lo_c], 0)
    offsets = jnp.cumsum(counts.astype(jnp.int64))
    total = offsets[-1]
    overflow = total > out_capacity
    starts = (offsets - counts).astype(jnp.int32)

    j = jnp.arange(out_capacity, dtype=jnp.int32)
    # which probe row does output j belong to?  scatter each row's index at
    # its start slot, then forward-fill (cummax) — replaces a searchsorted
    # of out_capacity lookups, the old hot spot
    rows32 = jnp.arange(batch.capacity, dtype=jnp.int32)
    rowmark = jnp.zeros(out_capacity, dtype=jnp.int32).at[
        jnp.where(counts > 0, starts, out_capacity)
    ].max(rows32, mode="drop")
    row = jax.lax.cummax(rowmark)
    k = j - starts[row]                      # match ordinal within the row
    build_pos = jnp.clip(lo[row] + k, 0, nb - 1)
    build_idx = table.perm[build_pos]
    out_mask = j < total

    out_cols: Dict[str, Column] = {}
    pg = _packed_gather(list(batch.columns.values()), row)
    for name, col in batch.columns.items():
        out_cols[name] = pg[id(col)]
    bg = _packed_gather([table.columns[n] for n in build_output], build_idx)
    for name in build_output:
        out_cols[name] = bg[id(table.columns[name])]
    pairs = Batch(out_cols, out_mask)
    if filter_fn is not None:
        pred = filter_fn(pairs)
        keep = pred.values.astype(bool)
        if pred.nulls is not None:
            keep = keep & ~pred.nulls
        pairs = pairs.with_mask(pairs.mask & keep)
    if matched is not None:
        # FULL: record which build rows found a surviving match
        matched = matched.at[build_idx].max(pairs.mask, mode="drop")
    if join_type == "INNER":
        return pairs, overflow, total, matched

    # LEFT/FULL: append one null-extended row per probe row without a
    # surviving match (extra region of batch.capacity rows)
    has_match = jnp.zeros(batch.capacity, dtype=bool).at[row].max(
        pairs.mask, mode="drop")
    extra_mask = batch.mask & ~has_match
    final_cols: Dict[str, Column] = {}
    for name, col in batch.columns.items():
        pc = pairs.columns[name]
        values = jnp.concatenate([pc.values, col.values])
        nulls = None
        if pc.nulls is not None or col.nulls is not None:
            nulls = jnp.concatenate([pc.null_mask(), col.null_mask()])
        final_cols[name] = Column(values, nulls, col.dictionary, col.lazy)
    for name in build_output:
        pc = pairs.columns[name]
        src = table.columns[name]
        pad = jnp.zeros(batch.capacity, dtype=pc.values.dtype)
        values = jnp.concatenate([pc.values, pad])
        nulls = jnp.concatenate([pc.null_mask(),
                                 jnp.ones(batch.capacity, dtype=bool)])
        final_cols[name] = Column(values, nulls, src.dictionary, src.lazy)
    final_mask = jnp.concatenate([pairs.mask, extra_mask])
    # the returned count is the LIVE row total of the emitted batch (pairs
    # + null-extended rows) so callers can right-size compaction; overflow
    # is still judged against the pair region alone
    return (Batch(final_cols, final_mask), overflow,
            total + jnp.sum(extra_mask), matched)


def direct_lookup(batch: Batch, dt, probe_key: str):
    """(hit, build_row_index) for a direct-address table lookup —
    THE single definition of the slot math shared by the fused chain
    (fused.probe_direct), the streaming direct join, and the direct semi
    marker.  Misses return index 0 (in-bounds garbage; callers mask/null
    those rows); NULL probe keys never match."""
    col = batch.columns[probe_key]
    v = col.values.astype(jnp.int64)
    size = dt.slots.shape[0]
    k = v - dt.base
    inb = (k >= 0) & (k < size)
    slot = dt.slots[jnp.clip(k, 0, size - 1).astype(jnp.int32)]
    hit = inb & (slot >= 0)
    if col.nulls is not None:
        hit = hit & ~col.nulls
    return hit, jnp.where(hit, slot, 0)


def probe_join_direct(batch: Batch, dt, probe_key: str,
                      build_output: List[str], join_type: str = "INNER",
                      filter_fn=None, matched=None):
    """Fanout-1 equi-join probe against a direct-address table
    (fused.DirectTable): ONE int32 gather instead of a searchsorted, and —
    because each probe row yields at most one output row — the output
    capacity equals the probe capacity, so there is no overflow flag, no
    live-count compaction, and ZERO host syncs per batch.  Mirrors
    probe_join's semantics: the ON-filter applies to pairs BEFORE
    null-extension; `matched` (FULL joins) records surviving build rows."""
    hit, bidx = direct_lookup(batch, dt, probe_key)
    hit = hit & batch.mask
    bidx = jnp.where(hit, bidx, 0)

    out_cols: Dict[str, Column] = dict(batch.columns)
    bg = _packed_gather([dt.columns[n] for n in build_output], bidx)
    for name in build_output:
        out_cols[name] = bg[id(dt.columns[name])]
    pairs = Batch(out_cols, hit)
    if filter_fn is not None:
        pred = filter_fn(pairs)
        keep = pred.values.astype(bool)
        if pred.nulls is not None:
            keep = keep & ~pred.nulls
        hit = hit & keep
        pairs = pairs.with_mask(hit)
    if matched is not None:
        nbuild = matched.shape[0]
        vslot = jnp.where(hit, bidx, nbuild)
        matched = matched.at[vslot].max(hit, mode="drop")
    if join_type == "INNER":
        return pairs, matched
    # LEFT/FULL: rows without a surviving match keep their probe columns
    # and read NULL on the build side (in-place, no extra row region —
    # fanout is 1, so the null-extended row IS the probe row)
    final_cols = dict(batch.columns)
    for name in build_output:
        c = pairs.columns[name]
        nulls = ~hit if c.nulls is None else (~hit | c.nulls)
        final_cols[name] = Column(c.values, nulls, c.dictionary, c.lazy)
    return Batch(final_cols, batch.mask), matched


def semi_join_mark_direct(batch: Batch, dt, probe_key: str,
                          build_has_null=False) -> Column:
    """semi_join_mark against a direct-address table: one int32 gather per
    probe batch, same three-valued semantics."""
    hit, _ = direct_lookup(batch, dt, probe_key)
    probe_null = batch.columns[probe_key].nulls
    if probe_null is None and isinstance(build_has_null, bool) \
            and not build_has_null:
        return Column(hit, None)
    nulls = ~hit & build_has_null
    if probe_null is not None:
        nulls = nulls | probe_null
    return Column(hit, nulls)


def semi_join_mark(batch: Batch, table: BuildTable, probe_keys: List[str],
                   salt: int = 0, build_has_null=False) -> Column:
    """SemiJoin marker with SQL three-valued semantics (reference
    HashSemiJoinOperator): TRUE on a match, FALSE on a definite miss, NULL
    when the probe key is NULL or when there is no match but the build side
    contained a NULL key (x IN (..., NULL) is UNKNOWN, never FALSE).
    Callers exclude NULL build keys before building and pass
    `build_has_null` (python bool or traced scalar) to report them."""
    kh = _orderable_hash(hash_columns(
        [batch.columns[k] for k in probe_keys], salt))
    lo = jnp.clip(jnp.searchsorted(table.keyhash_sorted, kh, side="left",
                                   method="scan_unrolled")
                  .astype(jnp.int32), 0, table.perm.shape[0] - 1)
    hit = table.keyhash_sorted[lo] == kh
    probe_null = None
    for k in probe_keys:
        nn = batch.columns[k].nulls
        if nn is not None:
            hit = hit & ~nn
            probe_null = nn if probe_null is None else probe_null | nn
    if probe_null is None and isinstance(build_has_null, bool) \
            and not build_has_null:
        return Column(hit, None)
    nulls = ~hit & build_has_null
    if probe_null is not None:
        nulls = nulls | probe_null
    return Column(hit, nulls)


# ---------------------------------------------------------------------------
# sort / topn / limit
# ---------------------------------------------------------------------------

def sort_indices(batch: Batch, keys: List[Tuple[str, str]]):
    """Stable sort permutation honoring sort orders; padding rows last.
    keys: [(column, ASC_NULLS_FIRST|...)]."""
    arrays = []
    # lexsort: last key is primary -> reverse
    for name, order in reversed(keys):
        col = batch.columns[name]
        v = col.values
        desc = order.startswith("DESC")
        if col.lazy is not None:
            from ..connectors import catalog as _catalog
            _, table, column, _sf = col.lazy
            if (table, column) not in _catalog.ROWID_ORDERED:
                raise NotImplementedError(
                    "ORDER BY on a late-materialized string column")
            # values are row ids; generator guarantees id order == lex order
        if col.dictionary is not None:
            # codes -> lexical ranks (host-precomputed, static)
            rank = np.argsort(np.argsort(np.array(col.dictionary)))
            v = jnp.asarray(rank.astype(np.int64))[v]
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.int8)
        if jnp.issubdtype(v.dtype, jnp.floating):
            v = jnp.where(jnp.isnan(v), jnp.inf, v)  # NaN sorts as largest (Presto)
            key = -v if desc else v
            nullv = jnp.inf
        else:
            # Narrow ints promote to int64 when a sentinel or negation
            # could wrap: the INT64_MAX null sentinel would truncate to -1
            # in an int32 key (q14_1 NULLS LAST bug), and DESC negates the
            # key, where -INT_MIN wraps to itself at the narrow width.
            # ASC non-null keys keep their width (nothing can wrap).
            if (col.nulls is not None or desc) and v.dtype != jnp.int64:
                v = v.astype(jnp.int64)
            key = -v if desc else v
            nullv = INT64_MAX
        if col.nulls is not None:
            nulls_first = order.endswith("NULLS_FIRST")
            key = jnp.where(col.nulls, (-nullv if nulls_first else nullv), key)
        arrays.append(key)
    # padding sorts after everything
    pad_key = (~batch.mask).astype(jnp.int8)
    return jnp.lexsort(tuple(arrays) + (pad_key,))


def topn(batch: Batch, keys: List[Tuple[str, str]], n: int) -> Batch:
    """Take first n rows by sort order; result capacity = n."""
    perm = sort_indices(batch, keys)[:n]
    cols = {name: c.gather(perm) for name, c in batch.columns.items()}
    return Batch(cols, batch.mask[perm])


def sort_batch(batch: Batch, keys: List[Tuple[str, str]]) -> Batch:
    perm = sort_indices(batch, keys)
    cols = {name: c.gather(perm) for name, c in batch.columns.items()}
    return Batch(cols, batch.mask[perm])


# ---------------------------------------------------------------------------
# window functions
# (reference: presto-main-base/.../operator/WindowOperator.java:69; default
#  frame RANGE UNBOUNDED PRECEDING .. CURRENT ROW, i.e. running aggregates
#  include the current row's full peer group)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class WindowSpec:
    """One window function over the node's shared (partition, order) spec.

    frame: None = default (RANGE UNBOUNDED PRECEDING .. CURRENT ROW) or a
    normalized tuple (type, start_kind, start_off, end_kind, end_off) per
    the reference WindowFrame (presto-main-base/.../operator/window/).
    extra: constant arguments (lag/lead offset + default, nth_value n,
    ntile n)."""
    name: str
    output: str
    arg: Optional[str] = None   # input column (None for ranking / count(*))
    is_float: bool = False      # float accumulation (vs int64 / decimal)
    frame: Optional[tuple] = None
    extra: tuple = ()


def _row_change(col: Column) -> jnp.ndarray:
    """[i] = row i differs from row i-1 (null-aware: two NULLs are equal,
    NaN equals NaN — grouping semantics, not comparison semantics)."""
    v = col.values
    a, b = v[1:], v[:-1]
    if jnp.issubdtype(v.dtype, jnp.floating):
        eq = (a == b) | (jnp.isnan(a) & jnp.isnan(b))
    else:
        eq = a == b
    if col.nulls is not None:
        na, nb = col.nulls[1:], col.nulls[:-1]
        eq = jnp.where(na | nb, na & nb, eq)
    return jnp.concatenate([jnp.ones(1, dtype=bool), ~eq])


def _range_reduce(x, fs, fe, is_min: bool, ident):
    """Per-row min/max of x over index range [fs, fe] (sparse doubling
    table: log2(n) precomputed levels, two gathers per query row).  Empty
    ranges (fe < fs) return ident."""
    n = x.shape[0]
    levels = [x]
    size = 1
    while size < n:
        cur = levels[-1]
        pad = jnp.full((size,), ident, x.dtype)
        shifted = jnp.concatenate([cur[size:], pad])
        levels.append(jnp.minimum(cur, shifted) if is_min
                      else jnp.maximum(cur, shifted))
        size <<= 1
    stacked = jnp.stack(levels)                         # (L, n)
    length = jnp.maximum(fe - fs + 1, 1)
    j = (63 - jax.lax.clz(length.astype(jnp.uint64))).astype(jnp.int32)
    fs_c = jnp.clip(fs, 0, n - 1).astype(jnp.int32)
    hi = jnp.clip(fe - (jnp.int64(1) << j.astype(jnp.int64)) + 1,
                  0, n - 1).astype(jnp.int32)
    a = stacked[j, fs_c]
    b = stacked[j, hi]
    r = jnp.minimum(a, b) if is_min else jnp.maximum(a, b)
    return jnp.where(fe < fs, ident, r)


def window_batch(batch: Batch, partition_names: Tuple[str, ...],
                 orderings: Tuple[Tuple[str, str], ...],
                 specs: Tuple[WindowSpec, ...]) -> Batch:
    """Evaluate all window functions sharing one (partition, order) spec.

    Sorts the whole batch by (partition keys, order keys) — padding rows
    last, forming their own segment — then computes every function with
    segmented prefix scans / sparse-table range reductions: no
    per-partition loop, so partition count and sizes stay out of the
    compiled shape.  Frames per reference WindowOperator.java:69 +
    operator/window/: ROWS with offsets, RANGE with
    unbounded/current-row bounds.  Output row order is the sorted order
    (SQL does not guarantee WindowNode output order)."""
    sort_keys = [(p, "ASC_NULLS_FIRST") for p in partition_names] + list(orderings)
    perm = sort_indices(batch, sort_keys)   # [] keys still sorts padding last
    cols = {n: c.gather(perm) for n, c in batch.columns.items()}
    mask = batch.mask[perm]

    n = batch.capacity
    idx = jnp.arange(n, dtype=jnp.int64)

    part_start = jnp.zeros(n, dtype=bool).at[0].set(True)
    # the valid->padding transition starts a segment so padding never joins
    # (or extends the frame of) the last real partition
    part_start = part_start | jnp.concatenate(
        [jnp.zeros(1, dtype=bool), mask[1:] != mask[:-1]])
    for p in partition_names:
        part_start = part_start | _row_change(cols[p])
    peer_start = part_start
    for o, _ in orderings:
        peer_start = peer_start | _row_change(cols[o])

    seg_start = jax.lax.cummax(jnp.where(part_start, idx, 0))
    peer_start_idx = jax.lax.cummax(jnp.where(peer_start, idx, 0))
    # frame end = last row of the current peer group: one before the next
    # peer-group start (suffix-min of start indices, shifted left)
    at_or_after = jnp.flip(jax.lax.cummin(jnp.flip(
        jnp.where(peer_start, idx, n))))
    peer_end = jnp.concatenate(
        [at_or_after[1:], jnp.full(1, n, dtype=jnp.int64)]) - 1
    at_or_after_p = jnp.flip(jax.lax.cummin(jnp.flip(
        jnp.where(part_start, idx, n))))
    seg_end = jnp.concatenate(
        [at_or_after_p[1:], jnp.full(1, n, dtype=jnp.int64)]) - 1

    def frame_bounds(spec: WindowSpec):
        """(fs, fe) row index bounds of the spec's frame, clamped to the
        partition; empty frames have fe < fs."""
        f = spec.frame
        if f is None:
            return seg_start, peer_end
        ftype, sk, so, ek, eo = f
        if ftype == "RANGE":
            fs = {"UNBOUNDED_PRECEDING": seg_start,
                  "CURRENT": peer_start_idx}.get(sk)
            fe = {"CURRENT": peer_end,
                  "UNBOUNDED_FOLLOWING": seg_end}.get(ek)
            if fs is None or fe is None:
                raise NotImplementedError(
                    "RANGE frame bounds with offsets")
            return fs, fe
        fs = {"UNBOUNDED_PRECEDING": seg_start, "CURRENT": idx,
              "PRECEDING": idx - (so or 0),
              "FOLLOWING": idx + (so or 0),
              "UNBOUNDED_FOLLOWING": seg_end + 1}[sk]
        fe = {"UNBOUNDED_FOLLOWING": seg_end, "CURRENT": idx,
              "PRECEDING": idx - (eo or 0),
              "FOLLOWING": idx + (eo or 0),
              "UNBOUNDED_PRECEDING": seg_start - 1}[ek]
        return jnp.maximum(fs, seg_start), jnp.minimum(fe, seg_end)

    out = dict(cols)
    for spec in specs:
        if spec.name == "row_number":
            out[spec.output] = Column(idx - seg_start + 1, None)
            continue
        if spec.name == "rank":
            out[spec.output] = Column(peer_start_idx - seg_start + 1, None)
            continue
        if spec.name == "dense_rank":
            cp = jnp.cumsum(peer_start.astype(jnp.int64))
            out[spec.output] = Column(cp - cp[seg_start] + 1, None)
            continue
        if spec.name == "percent_rank":
            size = seg_end - seg_start + 1
            rank = peer_start_idx - seg_start + 1
            denom = jnp.maximum(size - 1, 1)
            v = (rank - 1).astype(jnp.float64) / denom
            out[spec.output] = Column(jnp.where(size <= 1, 0.0, v), None)
            continue
        if spec.name == "cume_dist":
            size = seg_end - seg_start + 1
            thru = peer_end - seg_start + 1
            out[spec.output] = Column(
                thru.astype(jnp.float64) / jnp.maximum(size, 1), None)
            continue
        if spec.name == "ntile":
            nt = jnp.int64(spec.extra[0])
            size = seg_end - seg_start + 1
            rn = idx - seg_start
            q, r = size // nt, size % nt
            big = r * (q + 1)
            bucket = jnp.where(
                rn < big, rn // jnp.maximum(q + 1, 1),
                r + (rn - big) // jnp.maximum(q, 1))
            out[spec.output] = Column(bucket + 1, None)
            continue

        if spec.name in ("lag", "lead", "first_value", "last_value",
                         "nth_value"):
            col = cols[spec.arg]
            fs, fe = frame_bounds(spec)
            if spec.name in ("lag", "lead"):
                off = jnp.int64(spec.extra[0] if spec.extra else 1)
                src = idx - off if spec.name == "lag" else idx + off
                valid = (src >= seg_start) & (src <= seg_end) & mask
            elif spec.name == "first_value":
                src = fs
                valid = (fe >= fs) & mask
            elif spec.name == "last_value":
                src = fe
                valid = (fe >= fs) & mask
            else:   # nth_value(x, k)
                k = jnp.int64(spec.extra[0] if spec.extra else 1)
                src = fs + k - 1
                valid = (src >= fs) & (src <= fe) & mask
            src_c = jnp.clip(src, 0, n - 1)
            vals = col.values[src_c]
            nulls = col.null_mask()[src_c] | ~valid
            default = spec.extra[1] if (spec.name in ("lag", "lead")
                                        and len(spec.extra) > 1) else None
            if default is not None:
                if col.dictionary is not None or col.lazy is not None:
                    raise NotImplementedError(
                        "lag/lead default over string columns")
                vals = jnp.where(valid, vals,
                                 jnp.asarray(default, vals.dtype))
                nulls = jnp.where(valid, col.null_mask()[src_c], False)
            out[spec.output] = Column(vals, nulls, col.dictionary,
                                      col.lazy)
            continue

        # frame aggregates
        fs, fe = frame_bounds(spec)
        empty = fe < fs
        fs_c = jnp.clip(fs, 0, n - 1)
        fe_c = jnp.clip(fe, 0, n - 1)
        if spec.name == "count_star":
            contrib = mask
            x = contrib.astype(jnp.int64)
        else:
            c = cols[spec.arg]
            contrib = mask if c.nulls is None else (mask & ~c.nulls)
            x = c.values
        cnt0 = jnp.concatenate([jnp.zeros(1, dtype=jnp.int64),
                                jnp.cumsum(contrib.astype(jnp.int64))])
        frame_cnt = jnp.where(empty, 0, cnt0[fe_c + 1] - cnt0[fs_c])
        if spec.name in ("count", "count_star"):
            out[spec.output] = Column(frame_cnt, None)
        elif spec.name in ("sum", "avg"):
            dt = jnp.float64 if spec.is_float else jnp.int64
            xv = jnp.where(contrib, x, 0).astype(dt)
            ps0 = jnp.concatenate([jnp.zeros(1, dtype=dt), jnp.cumsum(xv)])
            frame_sum = jnp.where(empty, jnp.zeros((), dt),
                                  ps0[fe_c + 1] - ps0[fs_c])
            isempty = frame_cnt == 0     # SQL: aggregate of no rows is NULL
            safe = jnp.where(isempty, 1, frame_cnt)
            if spec.name == "sum":
                out[spec.output] = Column(frame_sum, isempty)
            elif spec.is_float:
                out[spec.output] = Column(frame_sum / safe, isempty)
            else:
                out[spec.output] = Column(
                    _decimal_avg(frame_sum, frame_cnt, isempty), isempty)
        elif spec.name in ("min", "max"):
            is_min = spec.name == "min"
            was_bool = x.dtype == jnp.bool_
            col = cols[spec.arg]
            # string columns: dictionary codes compare by LEXICAL rank, not
            # code value; min/max over lazy row ids is valid only for
            # ROWID_ORDERED columns (the compiler encodes others first)
            code_of_rank = None
            if col.dictionary is not None:
                d = np.array(col.dictionary)
                rank_of_code = np.argsort(np.argsort(d)).astype(np.int64)
                code_of_rank = jnp.asarray(np.argsort(rank_of_code))
                x = jnp.asarray(rank_of_code)[x]
            if was_bool:
                x = x.astype(jnp.int8)
            if jnp.issubdtype(x.dtype, jnp.floating):
                ident = jnp.array(jnp.inf if is_min else -jnp.inf, x.dtype)
            else:
                ident = jnp.array(jnp.iinfo(x.dtype).max if is_min
                                  else jnp.iinfo(x.dtype).min, x.dtype)
            xv = jnp.where(contrib, x, ident)
            vals = _range_reduce(xv, fs, fe, is_min, ident)
            isempty = frame_cnt == 0
            if was_bool:
                vals = vals.astype(jnp.bool_)
            if col.dictionary is not None:
                # rank -> code; empty frames hold the identity sentinel,
                # clamp before the gather (result is NULL there anyway)
                vals = code_of_rank[jnp.where(isempty, 0, vals)]
                out[spec.output] = Column(vals, isempty, col.dictionary)
            elif col.lazy is not None:
                vals = jnp.where(isempty, 0, vals)
                out[spec.output] = Column(vals, isempty, None, col.lazy)
            else:
                out[spec.output] = Column(vals, isempty)
        else:
            raise NotImplementedError(f"window function {spec.name}")
    return Batch(out, mask)


def limit(batch: Batch, n: int, already_consumed) -> Tuple[Batch, jnp.ndarray]:
    """Keep first n valid rows across batches; returns new consumed count."""
    rank = jnp.cumsum(batch.mask) + already_consumed  # 1-based rank
    keep = batch.mask & (rank <= n)
    return batch.with_mask(keep), already_consumed + jnp.sum(batch.mask.astype(jnp.int64))


def distinct(batch: Batch, key_names: List[str], state_kh, salt: int = 0):
    """Streaming DISTINCT via seen-hash table (exact up to 64-bit hash).
    state_kh: sorted uint64 array of seen hashes (padded with max)."""
    raise NotImplementedError("distinct handled via grouped agg for now")


# ---------------------------------------------------------------------------
# compaction: gather valid rows to the front (host boundary / exchange prep)
# ---------------------------------------------------------------------------

def compact(batch: Batch, out_capacity: Optional[int] = None) -> Batch:
    """Move live rows to a contiguous prefix (stable).  cumsum + scatter
    rather than argsort: sort kernels cost tens of seconds of XLA compile
    time per shape on TPU, while scatter compiles in ~1s."""
    cap = out_capacity or batch.capacity
    pos = jnp.cumsum(batch.mask) - 1
    idx = jnp.where(batch.mask, pos, cap).astype(jnp.int32)

    def scat(v):
        out = jnp.zeros((cap,) + v.shape[1:], v.dtype)
        return out.at[idx].set(v, mode="drop")

    cols = {name: Column(scat(c.values),
                         None if c.nulls is None else scat(c.nulls),
                         c.dictionary, c.lazy,
                         None if c.lengths is None else scat(c.lengths))
            for name, c in batch.columns.items()}
    mask = jnp.zeros(cap, dtype=bool).at[idx].set(batch.mask, mode="drop")
    return Batch(cols, mask)
