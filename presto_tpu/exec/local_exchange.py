"""Intra-task local exchange: repartition batches between pipelines
inside one task.

The analog of the reference's LocalExchange
(presto-main-base/.../operator/exchange/LocalExchange.java:62 with
PartitioningExchanger / BroadcastExchanger / round-robin) plus the
`task_concurrency` driver model (SqlTaskExecution.java:548 enqueues one
driver per split; TaskExecutor time-slices them).  Here a "driver" is a
Python thread draining one sub-pipeline: device dispatches are async, so
threads overlap HOST work (page serialization, split staging, host
string generation) with DEVICE work and with each other — the useful
concurrency on a single chip, where the accelerator itself serializes
kernels anyway.

LocalExchange is the single producer/consumer mechanism: bounded queues,
producer-finished accounting (LocalExchangeMemoryManager's bounded-buffer
role), and a close() path that unblocks producers when the consumer
stops early (downstream LIMIT, task cancellation, error) — producers use
timed puts and observe the stop flag, so no thread is ever left blocked
on a full queue.  background_drain and parallel_drain are thin drivers
over it.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, List, Optional

import jax.numpy as jnp

from . import operators as ops


class LocalExchange:
    """Bounded multi-producer multi-consumer batch router.

    partitioning: "ROUND_ROBIN" | "HASH" | "BROADCAST"
    (LocalPartitioningExchanger / BroadcastExchanger shapes).  HASH
    routes by key-hash % M so downstream consumers see disjoint key
    sets, the contract grouped consumers rely on.

    Exceptions may be pushed as items; consumers re-raise them.  close()
    stops producers (their next push returns False) and drains the
    queues so a blocked producer wakes up."""

    _DONE = object()

    def __init__(self, n_consumers: int, partitioning: str = "ROUND_ROBIN",
                 keys: Optional[List[str]] = None, capacity: int = 4):
        self.n_consumers = n_consumers
        self.partitioning = partitioning
        self.keys = keys or []
        self.queues = [queue.Queue(maxsize=capacity)
                       for _ in range(n_consumers)]
        self._rr = 0
        self._lock = threading.Lock()
        self._producers = 0
        self._finished = False
        self._stop = threading.Event()

    # -- producer side ----------------------------------------------------
    def add_producer(self) -> None:
        with self._lock:
            self._producers += 1

    def producer_finished(self) -> None:
        with self._lock:
            self._producers -= 1
            if self._producers == 0 and not self._finished:
                self._finished = True
                for q in self.queues:
                    self._put(q, self._DONE)

    def _put(self, q: "queue.Queue", item) -> bool:
        """Timed put observing the stop flag; False = exchange closed."""
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def push(self, batch) -> bool:
        """Route one batch; returns False when the exchange was closed
        (the producer should stop draining its pipeline)."""
        if self.partitioning == "BROADCAST":
            ok = True
            for q in self.queues:
                ok = self._put(q, batch) and ok
            return ok
        if self.partitioning == "HASH" and self.keys:
            import numpy as np
            cols = [batch.columns[k] for k in self.keys]
            h = np.asarray(ops.hash_columns(cols, 0x10CA1)) \
                % np.uint64(self.n_consumers)
            mask = np.asarray(batch.mask)
            ok = True
            for p in range(self.n_consumers):
                keep = jnp.asarray(mask & (h == p))
                ok = self._put(self.queues[p],
                               batch.with_mask(batch.mask & keep)) and ok
            return ok
        with self._lock:
            p = self._rr
            self._rr = (self._rr + 1) % self.n_consumers
        return self._put(self.queues[p], batch)

    # -- consumer side ----------------------------------------------------
    def consume(self, consumer: int) -> Iterator:
        q = self.queues[consumer]
        while True:
            item = q.get()
            if item is self._DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def close(self) -> None:
        """Consumer-side shutdown: stop producers and drain the queues so
        any producer blocked on a full queue wakes up and exits."""
        self._stop.set()
        for q in self.queues:
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break


def background_drain(it: Iterator, wall_out: Optional[list] = None,
                     capacity: int = 4):
    """Drain `it` on a background thread, yielding items as they arrive —
    the two-pipeline producer/consumer shape (pipeline drain overlapping
    serialization).  The producer's wall lands in wall_out[0] BEFORE the
    done signal, so a consumer that observed completion also observes the
    wall.  Closing the returned generator (early exit, cancellation)
    stops and unblocks the producer."""
    ex = LocalExchange(1, "ROUND_ROBIN", capacity=capacity)
    ex.add_producer()

    def producer():
        t0 = time.perf_counter()  # lint: allow-wall-clock
        try:
            for item in it:
                if not ex.push(item):
                    return
        except BaseException as e:     # relayed to the consumer
            ex.push(e)
        finally:
            if wall_out is not None:
                wall_out[0] = time.perf_counter() - t0  # lint: allow-wall-clock
            ex.producer_finished()

    threading.Thread(target=producer, daemon=True,
                     name="local-exchange-drain").start()

    def gen():
        try:
            yield from ex.consume(0)
        finally:
            ex.close()
    return gen()


def parallel_drain(sources: List[Callable[[], Iterator]],
                   concurrency: int, stats: Optional[dict] = None):
    """Drain `sources` (thunks returning batch iterators) on up to
    `concurrency` driver threads through one LocalExchange, yielding
    batches as they arrive.

    Per-driver wall times land in stats["driver_walls"] (each written
    before its driver signals completion); sum(driver walls) - consumer
    wall > 0 is the measured overlap surfaced in EXPLAIN ANALYZE /
    TaskInfo, the same per-driver accounting TaskStats carries."""
    if concurrency <= 1 or len(sources) <= 1:
        for thunk in sources:
            yield from thunk()
        return
    n_threads = min(concurrency, len(sources))
    ex = LocalExchange(1, "ROUND_ROBIN", capacity=concurrency * 2)
    walls = [0.0] * len(sources)
    idx_q: "queue.Queue" = queue.Queue()
    for i in range(len(sources)):
        idx_q.put(i)

    def driver():
        while True:
            try:
                i = idx_q.get_nowait()
            except queue.Empty:
                return
            t0 = time.perf_counter()  # lint: allow-wall-clock
            try:
                for b in sources[i]():
                    if not ex.push(b):
                        return
            except BaseException as e:
                ex.push(e)
                return
            finally:
                walls[i] = time.perf_counter() - t0  # lint: allow-wall-clock

    for _ in range(n_threads):
        ex.add_producer()

    def run_driver():
        try:
            driver()
        finally:
            ex.producer_finished()

    threads = []
    for _ in range(n_threads):
        t = threading.Thread(target=run_driver, daemon=True,
                             name="local-exchange-driver")
        threads.append(t)
        t.start()
    try:
        yield from ex.consume(0)
    finally:
        ex.close()
        if stats is not None:
            # drivers observe the stop flag within one timed-put window;
            # join briefly so every wall entry is final before snapshot
            for t in threads:
                t.join(timeout=1.0)
            stats["driver_walls"] = [round(w, 4) for w in walls]
