"""RowExpression -> XLA lowering.

The TPU replacement for the reference's JVM bytecode expression JIT
(presto-main-base/.../sql/gen/ExpressionCompiler.java:63 /
PageFunctionCompiler.java:127) and for Velox expression eval on the native
worker: expressions become jax functions over Batch columns, fused by XLA into
the surrounding pipeline.

Semantics notes:
- Null propagation: scalar functions return NULL if any input is NULL
  (result nulls = OR of arg nulls); AND/OR use Kleene 3-valued logic.
- Decimals are unscaled int64; scale bookkeeping uses the expression types
  (planner-computed), matching reference DecimalOperators semantics.
- Dictionary-encoded varchar: predicates against literals are precomputed
  host-side into per-code boolean tables (static), then gathered on device —
  the string never reaches the TPU.
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional


def like_matcher(pattern: str, escape: Optional[str] = None):
    """SQL LIKE pattern -> predicate.  Unlike a naive fnmatch translation,
    glob metacharacters in the pattern stay literal; only % and _ are
    wildcards (reference LikeFunctions semantics)."""
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    rx = re.compile("".join(out), re.DOTALL)
    return lambda s: rx.fullmatch(s) is not None

import jax.numpy as jnp
import numpy as np

from ..common.types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, BooleanType,
                            CharType, DateType, DecimalType, DoubleType,
                            IntegerType, RealType, Type, VarcharType)
from ..spi.expr import (CallExpression, ConstantExpression, RowExpression,
                        SpecialFormExpression, VariableReferenceExpression)
from .batch import Batch, Column

# Canonical scalar function names; presto internal operator handles map here.
_CANONICAL = {
    "$operator$add": "add", "$operator$subtract": "subtract",
    "$operator$multiply": "multiply", "$operator$divide": "divide",
    "$operator$modulus": "modulus", "$operator$negation": "negate",
    "$operator$equal": "eq", "$operator$not_equal": "neq",
    "$operator$less_than": "lt", "$operator$less_than_or_equal": "lte",
    "$operator$greater_than": "gt", "$operator$greater_than_or_equal": "gte",
    "$operator$between": "between", "$operator$cast": "cast",
    "presto.default.$operator$add": "add",
    "not": "not",
}


def canonical_name(name: str) -> str:
    n = name.lower()
    return _CANONICAL.get(n, n.split(".")[-1])


def _scale_of(t: Type) -> Optional[int]:
    return t.scale if isinstance(t, DecimalType) else None


def _pow10(k: int):
    return 10 ** k


def _is_decimal(t):
    return isinstance(t, DecimalType)


def _combine_nulls(*cols) -> Optional[jnp.ndarray]:
    masks = [c.nulls for c in cols if c.nulls is not None]
    if not masks:
        return None
    out = masks[0]
    for m in masks[1:]:
        out = out | m
    return out


def _numeric(col: Column, typ: Type):
    """Values ready for arithmetic: decimals stay unscaled ints."""
    return col.values


def _rescale(values, from_scale: int, to_scale: int):
    if to_scale == from_scale:
        return values
    if to_scale > from_scale:
        return values * _pow10(to_scale - from_scale)
    # scale down with round-half-up (reference decimal semantics)
    f = _pow10(from_scale - to_scale)
    return _div_round_half_up(values, f)


def _div_round_half_up(num, den_const: int):
    """Divide by positive constant, rounding half away from zero."""
    return (jnp.sign(num) * ((jnp.abs(num) + den_const // 2) // den_const)
            ).astype(num.dtype)


def _to_common_numeric(col: Column, typ: Type, target: Type):
    """Coerce values of `typ` to the numeric domain of `target` for comparison
    or arithmetic: decimal scales aligned, ints widened, doubles floated."""
    v = col.values
    if _is_decimal(target):
        if _is_decimal(typ):
            return _rescale(v, typ.scale, target.scale)
        return v * _pow10(target.scale)  # integer -> decimal
    if isinstance(target, (DoubleType, RealType)):
        if _is_decimal(typ):
            return v.astype(jnp.float64) / _pow10(typ.scale)
        return v.astype(jnp.float64 if isinstance(target, DoubleType) else jnp.float32)
    return v


def _common_super(t1: Type, t2: Type) -> Type:
    if isinstance(t1, (DoubleType,)) or isinstance(t2, (DoubleType,)):
        return DOUBLE
    if isinstance(t1, RealType) or isinstance(t2, RealType):
        return DOUBLE
    if _is_decimal(t1) and _is_decimal(t2):
        s = max(t1.scale, t2.scale)
        return DecimalType(38, s)
    if _is_decimal(t1):
        return DecimalType(38, t1.scale)
    if _is_decimal(t2):
        return DecimalType(38, t2.scale)
    return BIGINT


# ---------------------------------------------------------------------------
# constant encoding
# ---------------------------------------------------------------------------

def constant_device_value(value, typ: Type):
    """Python literal -> device scalar in the column's logical domain."""
    if value is None:
        return None
    if isinstance(typ, DecimalType):
        from decimal import Decimal
        if isinstance(value, Decimal):
            return int(value.scaleb(typ.scale).to_integral_value())
        if isinstance(value, str):
            return int(Decimal(value).scaleb(typ.scale).to_integral_value())
        return int(value)  # already unscaled
    if isinstance(typ, DateType) and isinstance(value, str):
        return int(np.datetime64(value, "D").astype(np.int64))
    return value


# ---------------------------------------------------------------------------
# main lowering
# ---------------------------------------------------------------------------

class Lowering:
    """Compiles a RowExpression tree to a function Batch -> Column."""

    def __init__(self):
        pass

    def compile(self, expr: RowExpression) -> Callable[[Batch], Column]:
        def fn(batch: Batch) -> Column:
            return self.eval(expr, batch)
        return fn

    def eval(self, expr: RowExpression, batch: Batch) -> Column:
        if isinstance(expr, VariableReferenceExpression):
            return batch.column(expr.name)
        if isinstance(expr, ConstantExpression):
            return self._constant(expr, batch)
        if isinstance(expr, CallExpression):
            return self._call(expr, batch)
        if isinstance(expr, SpecialFormExpression):
            return self._special(expr, batch)
        raise NotImplementedError(type(expr).__name__)

    # -- constants --------------------------------------------------------
    def _constant(self, expr: ConstantExpression, batch: Batch) -> Column:
        cap = batch.capacity
        if expr.value is None:
            if isinstance(expr.type, (VarcharType, CharType)):
                # typed NULL string: all-null dictionary column so string
                # consumers (union dictionary merge, output blocks) work
                return Column(jnp.zeros(cap, dtype=jnp.int32),
                              jnp.ones(cap, dtype=bool), ("",))
            z = jnp.zeros(cap, dtype=_jnp_dtype(expr.type))
            return Column(z, jnp.ones(cap, dtype=bool))
        v = constant_device_value(expr.value, expr.type)
        if isinstance(expr.type, (VarcharType, CharType)):
            # string literal: single-entry dictionary, code 0 everywhere
            return Column(jnp.zeros(cap, dtype=jnp.int32), None, (str(v),))
        arr = jnp.full(cap, v, dtype=_jnp_dtype(expr.type))
        return Column(arr, None)

    # -- calls ------------------------------------------------------------
    def _call(self, expr: CallExpression, batch: Batch) -> Column:
        name = canonical_name(expr.display_name)
        args = expr.arguments

        if name in ("add", "subtract", "multiply", "divide", "modulus"):
            return self._arith(name, expr, batch)
        if name in ("eq", "neq", "lt", "lte", "gt", "gte"):
            return self._compare(name, args[0], args[1], batch)
        if name == "between":
            lo = self._compare("gte", args[0], args[1], batch)
            hi = self._compare("lte", args[0], args[2], batch)
            return _kleene_and(lo, hi)
        if name == "not":
            c = self.eval(args[0], batch)
            return Column(~c.values.astype(bool), c.nulls)
        if name == "negate":
            c = self.eval(args[0], batch)
            return Column(-c.values, c.nulls)
        if name == "abs":
            c = self.eval(args[0], batch)
            return Column(jnp.abs(c.values), c.nulls)
        if name in ("year", "month", "day", "quarter"):
            c = self.eval(args[0], batch)
            y, m, d = _civil_from_days(c.values)
            part = {"year": y, "month": m, "day": d, "quarter": (m + 2) // 3}[name]
            return Column(part.astype(jnp.int64), c.nulls)
        if name == "cast":
            return self._cast(args[0], expr.type, batch)
        if name == "like":
            return self._like(args[0], args[1], batch)
        if name == "substr":
            return self._substr(expr, batch)
        if name == "length":
            c = self.eval(args[0], batch)
            if c.dictionary is None:
                raise NotImplementedError("length on non-dictionary varchar")
            table = jnp.asarray(np.array([len(s) for s in c.dictionary],
                                         dtype=np.int64))
            return Column(table[c.values], c.nulls)
        if name in ("coalesce",):
            return self._coalesce([self.eval(a, batch) for a in args])
        raise NotImplementedError(f"scalar function {expr.display_name!r}")

    def _arith(self, name, expr: CallExpression, batch: Batch) -> Column:
        a_expr, b_expr = expr.arguments
        a, b = self.eval(a_expr, batch), self.eval(b_expr, batch)
        ta, tb, tr = a_expr.type, b_expr.type, expr.type
        nulls = _combine_nulls(a, b)

        if isinstance(tr, (DoubleType, RealType)):
            av = _to_common_numeric(a, ta, tr)
            bv = _to_common_numeric(b, tb, tr)
            op = {"add": jnp.add, "subtract": jnp.subtract,
                  "multiply": jnp.multiply, "divide": jnp.divide,
                  "modulus": jnp.mod}[name]
            return Column(op(av, bv), nulls)

        if _is_decimal(tr):
            rs = tr.scale
            sa = ta.scale if _is_decimal(ta) else 0
            sb = tb.scale if _is_decimal(tb) else 0
            av, bv = a.values, b.values
            if name == "multiply":
                out = av * bv  # scale sa+sb
                return Column(_rescale(out, sa + sb, rs), nulls)
            if name == "divide":
                # numerator scaled to rs + sb, then round-half-up divide
                num = _rescale(av, sa, rs + sb)
                safe_b = jnp.where(bv == 0, 1, bv)
                q = jnp.sign(num) * jnp.sign(safe_b) * (
                    (jnp.abs(num) + jnp.abs(safe_b) // 2) // jnp.abs(safe_b))
                nulls = _or_null(nulls, bv == 0)
                return Column(q.astype(av.dtype), nulls)
            av = _rescale(av, sa, rs)
            bv = _rescale(bv, sb, rs)
            op = {"add": jnp.add, "subtract": jnp.subtract,
                  "modulus": jnp.mod}[name]
            return Column(op(av, bv), nulls)

        # integer domain
        av, bv = a.values, b.values
        if name == "divide":
            safe_b = jnp.where(bv == 0, 1, bv)
            # SQL integer division truncates toward zero
            q = (jnp.sign(av) * jnp.sign(safe_b)
                 * (jnp.abs(av) // jnp.abs(safe_b))).astype(av.dtype)
            return Column(q, _or_null(nulls, bv == 0))
        if name == "modulus":
            safe_b = jnp.where(bv == 0, 1, bv)
            r = (jnp.sign(av) * (jnp.abs(av) % jnp.abs(safe_b))).astype(av.dtype)
            return Column(r, _or_null(nulls, bv == 0))
        op = {"add": jnp.add, "subtract": jnp.subtract,
              "multiply": jnp.multiply}[name]
        return Column(op(av, bv), nulls)

    def _compare(self, name, a_expr, b_expr, batch: Batch) -> Column:
        a, b = self.eval(a_expr, batch), self.eval(b_expr, batch)
        nulls = _combine_nulls(a, b)

        # dictionary-coded strings
        if a.dictionary is not None or b.dictionary is not None:
            return self._compare_strings(name, a, b, nulls)

        common = _common_super(a_expr.type, b_expr.type)
        av = _to_common_numeric(a, a_expr.type, common)
        bv = _to_common_numeric(b, b_expr.type, common)
        op = {"eq": jnp.equal, "neq": jnp.not_equal, "lt": jnp.less,
              "lte": jnp.less_equal, "gt": jnp.greater,
              "gte": jnp.greater_equal}[name]
        return Column(op(av, bv), nulls)

    def _compare_strings(self, name, a: Column, b: Column, nulls) -> Column:
        if a.dictionary is None or b.dictionary is None:
            raise NotImplementedError("string comparison requires dictionaries")
        if len(b.dictionary) == 1:
            # column vs literal: precompute per-code truth table (host)
            lit = b.dictionary[0]
            import operator as _op
            pyop = {"eq": _op.eq, "neq": _op.ne, "lt": _op.lt,
                    "lte": _op.le, "gt": _op.gt, "gte": _op.ge}[name]
            table = jnp.asarray(np.array([pyop(s, lit) for s in a.dictionary],
                                         dtype=bool))
            return Column(table[a.values], nulls)
        if len(a.dictionary) == 1:
            flip = {"eq": "eq", "neq": "neq", "lt": "gt", "lte": "gte",
                    "gt": "lt", "gte": "lte"}[name]
            return self._compare_strings(flip, b, a, nulls)
        if a.dictionary == b.dictionary:
            op = {"eq": jnp.equal, "neq": jnp.not_equal, "lt": jnp.less,
                  "lte": jnp.less_equal, "gt": jnp.greater,
                  "gte": jnp.greater_equal}[name]
            if name in ("eq", "neq"):
                return Column(op(a.values, b.values), nulls)
            # order comparisons need rank order == code order; our dictionaries
            # are sorted at build time (batch.py), so codes are rank codes.
            return Column(op(a.values, b.values), nulls)
        # different dictionaries: map b's codes into a's dictionary (host)
        index = {s: i for i, s in enumerate(a.dictionary)}
        remap = jnp.asarray(np.array(
            [index.get(s, -1) for s in b.dictionary], dtype=np.int32))
        bv = remap[b.values]
        if name == "eq":
            return Column((a.values == bv) & (bv >= 0), nulls)
        if name == "neq":
            return Column((a.values != bv) | (bv < 0), nulls)
        raise NotImplementedError("ordering across distinct dictionaries")

    def _like(self, value_expr, pattern_expr, batch: Batch) -> Column:
        if not isinstance(pattern_expr, ConstantExpression):
            raise NotImplementedError("LIKE with non-constant pattern")
        c = self.eval(value_expr, batch)
        if c.dictionary is None:
            raise NotImplementedError("LIKE on non-dictionary varchar")
        match = like_matcher(str(pattern_expr.value))
        table = jnp.asarray(np.array(
            [match(s) for s in c.dictionary], dtype=bool))
        return Column(table[c.values], c.nulls)

    def _substr(self, expr: CallExpression, batch: Batch) -> Column:
        args = expr.arguments
        c = self.eval(args[0], batch)
        if c.dictionary is None:
            raise NotImplementedError("substr on non-dictionary varchar")
        if not all(isinstance(a, ConstantExpression) for a in args[1:]):
            raise NotImplementedError("substr with non-constant bounds")
        start = int(args[1].value)
        length = int(args[2].value) if len(args) > 2 else None
        def sub(s):
            i = start - 1 if start > 0 else len(s) + start
            return s[i:i + length] if length is not None else s[i:]
        new_values = [sub(s) for s in c.dictionary]
        uniq = sorted(set(new_values))
        remap = jnp.asarray(np.array([uniq.index(v) for v in new_values],
                                     dtype=np.int32))
        return Column(remap[c.values], c.nulls, tuple(uniq))

    def _cast(self, arg: RowExpression, to: Type, batch: Batch) -> Column:
        c = self.eval(arg, batch)
        frm = arg.type
        if frm.signature == to.signature:
            return c
        if isinstance(to, DoubleType):
            if _is_decimal(frm):
                return Column(c.values.astype(jnp.float64) / _pow10(frm.scale),
                              c.nulls)
            return Column(c.values.astype(jnp.float64), c.nulls)
        if _is_decimal(to):
            if _is_decimal(frm):
                return Column(_rescale(c.values, frm.scale, to.scale), c.nulls)
            if isinstance(frm, (DoubleType, RealType)):
                scaled = c.values * _pow10(to.scale)
                return Column(jnp.round(scaled).astype(jnp.int64), c.nulls)
            return Column(c.values.astype(jnp.int64) * _pow10(to.scale), c.nulls)
        if isinstance(to, (IntegerType,)):
            return Column(c.values.astype(jnp.int32), c.nulls)
        if to.signature == "bigint":
            if _is_decimal(frm):
                return Column(_rescale(c.values, frm.scale, 0), c.nulls)
            return Column(c.values.astype(jnp.int64), c.nulls)
        if isinstance(to, (VarcharType, CharType)) and c.dictionary is not None:
            return c
        raise NotImplementedError(f"cast {frm} -> {to}")

    def _coalesce(self, cols: List[Column]) -> Column:
        out_v = cols[-1].values
        out_n = cols[-1].null_mask()
        for c in reversed(cols[:-1]):
            isnull = c.null_mask()
            out_v = jnp.where(isnull, out_v, c.values)
            out_n = isnull & out_n
        has = any(c.nulls is not None for c in cols)
        return Column(out_v, out_n if has else None)

    # -- special forms ----------------------------------------------------
    def _special(self, expr: SpecialFormExpression, batch: Batch) -> Column:
        form = expr.form
        args = expr.arguments
        if form == "AND":
            cols = [self.eval(a, batch) for a in args]
            out = cols[0]
            for c in cols[1:]:
                out = _kleene_and(out, c)
            return out
        if form == "OR":
            cols = [self.eval(a, batch) for a in args]
            out = cols[0]
            for c in cols[1:]:
                out = _kleene_or(out, c)
            return out
        if form == "IS_NULL":
            c = self.eval(args[0], batch)
            return Column(c.null_mask(), None)
        if form == "IF":
            cond = self.eval(args[0], batch)
            t = self.eval(args[1], batch)
            f = self.eval(args[2], batch)
            pred = cond.values.astype(bool) & ~cond.null_mask()
            t, f = _merge_dictionaries(t, f)
            values = jnp.where(pred, t.values, f.values)
            nulls = jnp.where(pred, t.null_mask(), f.null_mask())
            has = t.nulls is not None or f.nulls is not None
            return Column(values, nulls if has else None, t.dictionary)
        if form == "COALESCE":
            return self._coalesce([self.eval(a, batch) for a in args])
        if form == "IN":
            return self._in(args[0], args[1:], batch)
        if form == "NULL_IF":
            a = self.eval(args[0], batch)
            b = self.eval(args[1], batch)
            # NULLIF(x, y) is x unless x == y with both non-null
            eq = (a.values == b.values) & ~a.null_mask() & ~b.null_mask()
            return Column(a.values, _or_null(a.nulls, eq))
        raise NotImplementedError(f"special form {form}")

    def _in(self, value_expr, list_exprs, batch: Batch) -> Column:
        c = self.eval(value_expr, batch)
        consts = [e for e in list_exprs if isinstance(e, ConstantExpression)]
        if len(consts) != len(list_exprs):
            raise NotImplementedError("IN with non-constant list")
        if c.dictionary is not None:
            values = {str(e.value) for e in consts}
            table = jnp.asarray(np.array([s in values for s in c.dictionary],
                                         dtype=bool))
            return Column(table[c.values], c.nulls)
        out = jnp.zeros(batch.capacity, dtype=bool)
        for e in consts:
            v = constant_device_value(e.value, value_expr.type)
            out = out | (c.values == v)
        return Column(out, c.nulls)


def _merge_dictionaries(a: Column, b: Column):
    """Remap two dictionary-coded columns onto one union dictionary (static,
    host-side) so their codes are directly comparable/mixable."""
    if a.dictionary is None or b.dictionary is None or \
            a.dictionary == b.dictionary:
        return a, b
    union = tuple(sorted(set(a.dictionary) | set(b.dictionary)))
    index = {s: i for i, s in enumerate(union)}
    remap_a = jnp.asarray(np.array([index[s] for s in a.dictionary],
                                   dtype=np.int32))
    remap_b = jnp.asarray(np.array([index[s] for s in b.dictionary],
                                   dtype=np.int32))
    return (Column(remap_a[a.values], a.nulls, union),
            Column(remap_b[b.values], b.nulls, union))


def _or_null(nulls, extra_mask):
    if nulls is None:
        return extra_mask
    return nulls | extra_mask


def _kleene_and(a: Column, b: Column) -> Column:
    av = a.values.astype(bool)
    bv = b.values.astype(bool)
    an, bn = a.null_mask(), b.null_mask()
    value = (av | an) & (bv | bn)  # true unless a definite false
    nulls = value & (an | bn)      # null if not definitively false
    has = a.nulls is not None or b.nulls is not None
    return Column(av & bv if not has else (value & ~nulls), nulls if has else None)


def _kleene_or(a: Column, b: Column) -> Column:
    av = a.values.astype(bool)
    bv = b.values.astype(bool)
    an, bn = a.null_mask(), b.null_mask()
    definite_true = (av & ~an) | (bv & ~bn)
    nulls = ~definite_true & (an | bn)
    has = a.nulls is not None or b.nulls is not None
    return Column(definite_true if has else (av | bv), nulls if has else None)


def _jnp_dtype(typ: Type):
    if isinstance(typ, DoubleType):
        return jnp.float64
    if isinstance(typ, RealType):
        return jnp.float32
    if isinstance(typ, BooleanType):
        return jnp.bool_
    if isinstance(typ, IntegerType) or isinstance(typ, DateType):
        return jnp.int32
    return jnp.int64


def _civil_from_days(z):
    """Days-since-epoch -> (year, month, day); Hinnant's algorithm, integer
    ops only so XLA fuses it."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d
