"""RowExpression -> XLA lowering.

The TPU replacement for the reference's JVM bytecode expression JIT
(presto-main-base/.../sql/gen/ExpressionCompiler.java:63 /
PageFunctionCompiler.java:127) and for Velox expression eval on the native
worker: expressions become jax functions over Batch columns, fused by XLA into
the surrounding pipeline.

Semantics notes:
- Null propagation: scalar functions return NULL if any input is NULL
  (result nulls = OR of arg nulls); AND/OR use Kleene 3-valued logic.
- Decimals are unscaled int64; scale bookkeeping uses the expression types
  (planner-computed), matching reference DecimalOperators semantics.
- Dictionary-encoded varchar: predicates against literals are precomputed
  host-side into per-code boolean tables (static), then gathered on device —
  the string never reaches the TPU.
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional


def like_matcher(pattern: str, escape: Optional[str] = None):
    """SQL LIKE pattern -> predicate.  Unlike a naive fnmatch translation,
    glob metacharacters in the pattern stay literal; only % and _ are
    wildcards (reference LikeFunctions semantics)."""
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    rx = re.compile("".join(out), re.DOTALL)
    return lambda s: rx.fullmatch(s) is not None

import jax
import jax.numpy as jnp
import numpy as np

from ..common.types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, BooleanType,
                            CharType, DateType, DecimalType, DoubleType,
                            IntegerType, RealType, Type, VarcharType)
from ..spi.expr import (BoundParameterExpression, CallExpression,
                        ConstantExpression, RowExpression,
                        SpecialFormExpression, VariableReferenceExpression)
from .batch import Batch, Column

# Canonical scalar function names; presto internal operator handles map here.
_CANONICAL = {
    "$operator$add": "add", "$operator$subtract": "subtract",
    "$operator$multiply": "multiply", "$operator$divide": "divide",
    "$operator$modulus": "modulus", "$operator$negation": "negate",
    "$operator$equal": "eq", "$operator$not_equal": "neq",
    "$operator$less_than": "lt", "$operator$less_than_or_equal": "lte",
    "$operator$greater_than": "gt", "$operator$greater_than_or_equal": "gte",
    "$operator$between": "between", "$operator$cast": "cast",
    "presto.default.$operator$add": "add",
    "not": "not",
}


def canonical_name(name: str) -> str:
    n = name.lower()
    return _CANONICAL.get(n, n.split(".")[-1])


def _scale_of(t: Type) -> Optional[int]:
    return t.scale if isinstance(t, DecimalType) else None


def _pow10(k: int):
    return 10 ** k


def _is_decimal(t):
    return isinstance(t, DecimalType)


def _combine_nulls(*cols) -> Optional[jnp.ndarray]:
    masks = [c.nulls for c in cols if c.nulls is not None]
    if not masks:
        return None
    out = masks[0]
    for m in masks[1:]:
        out = out | m
    return out


def _numeric(col: Column, typ: Type):
    """Values ready for arithmetic: decimals stay unscaled ints."""
    return col.values


def _rescale(values, from_scale: int, to_scale: int):
    if to_scale == from_scale:
        return values
    if to_scale > from_scale:
        return values * _pow10(to_scale - from_scale)
    # scale down with round-half-up (reference decimal semantics)
    f = _pow10(from_scale - to_scale)
    return _div_round_half_up(values, f)


def _div_round_half_up(num, den_const: int):
    """Divide by positive constant, rounding half away from zero."""
    return (jnp.sign(num) * ((jnp.abs(num) + den_const // 2) // den_const)
            ).astype(num.dtype)


def _to_common_numeric(col: Column, typ: Type, target: Type):
    """Coerce values of `typ` to the numeric domain of `target` for comparison
    or arithmetic: decimal scales aligned, ints widened, doubles floated."""
    v = col.values
    if _is_decimal(target):
        if _is_decimal(typ):
            return _rescale(v, typ.scale, target.scale)
        return v * _pow10(target.scale)  # integer -> decimal
    if isinstance(target, (DoubleType, RealType)):
        if _is_decimal(typ):
            return v.astype(jnp.float64) / _pow10(typ.scale)
        return v.astype(jnp.float64 if isinstance(target, DoubleType) else jnp.float32)
    return v


def _common_super(t1: Type, t2: Type) -> Type:
    if isinstance(t1, (DoubleType,)) or isinstance(t2, (DoubleType,)):
        return DOUBLE
    if isinstance(t1, RealType) or isinstance(t2, RealType):
        return DOUBLE
    if _is_decimal(t1) and _is_decimal(t2):
        s = max(t1.scale, t2.scale)
        return DecimalType(38, s)
    if _is_decimal(t1):
        return DecimalType(38, t1.scale)
    if _is_decimal(t2):
        return DecimalType(38, t2.scale)
    return BIGINT


# ---------------------------------------------------------------------------
# constant encoding
# ---------------------------------------------------------------------------

def constant_device_value(value, typ: Type):
    """Python literal -> device scalar in the column's logical domain."""
    if value is None:
        return None
    if isinstance(typ, DecimalType):
        from decimal import Decimal
        if isinstance(value, Decimal):
            return int(value.scaleb(typ.scale).to_integral_value())
        if isinstance(value, str):
            return int(Decimal(value).scaleb(typ.scale).to_integral_value())
        return int(value)  # already unscaled
    if isinstance(typ, DateType) and isinstance(value, str):
        return int(np.datetime64(value, "D").astype(np.int64))
    return value


# ---------------------------------------------------------------------------
# main lowering
# ---------------------------------------------------------------------------

def expr_has_params(expr: RowExpression) -> bool:
    """Whether a RowExpression tree contains serving-tier bound-parameter
    leaves (pipeline/fused use this at compile time to decide whether a
    step takes the parameter vector as a jit argument)."""
    if isinstance(expr, BoundParameterExpression):
        return True
    if isinstance(expr, (CallExpression, SpecialFormExpression)):
        return any(expr_has_params(a) for a in expr.arguments)
    return False


class Lowering:
    """Compiles a RowExpression tree to a function Batch -> Column."""

    def __init__(self):
        pass

    def compile(self, expr: RowExpression) -> Callable[[Batch], Column]:
        def fn(batch: Batch) -> Column:
            return self.eval(expr, batch)
        return fn

    def eval(self, expr: RowExpression, batch: Batch) -> Column:
        if isinstance(expr, VariableReferenceExpression):
            return batch.column(expr.name)
        if isinstance(expr, ConstantExpression):
            return self._constant(expr, batch)
        if isinstance(expr, CallExpression):
            return self._call(expr, batch)
        if isinstance(expr, SpecialFormExpression):
            return self._special(expr, batch)
        if isinstance(expr, BoundParameterExpression):
            return self._parameter(expr, batch)
        raise NotImplementedError(type(expr).__name__)

    # -- constants --------------------------------------------------------
    def _constant(self, expr: ConstantExpression, batch: Batch) -> Column:
        cap = batch.capacity
        if expr.value is None:
            if isinstance(expr.type, (VarcharType, CharType)):
                # typed NULL string: all-null dictionary column so string
                # consumers (union dictionary merge, output blocks) work
                return Column(jnp.zeros(cap, dtype=jnp.int32),
                              jnp.ones(cap, dtype=bool), ("",))
            z = jnp.zeros(cap, dtype=_jnp_dtype(expr.type))
            return Column(z, jnp.ones(cap, dtype=bool))
        v = constant_device_value(expr.value, expr.type)
        if isinstance(expr.type, (VarcharType, CharType)):
            # string literal: single-entry dictionary, code 0 everywhere
            return Column(jnp.zeros(cap, dtype=jnp.int32), None, (str(v),))
        arr = jnp.full(cap, v, dtype=_jnp_dtype(expr.type))
        return Column(arr, None)

    def _parameter(self, expr: BoundParameterExpression, batch: Batch) -> Column:
        if batch.params is None:
            raise RuntimeError(
                f"BoundParameterExpression ?{expr.index} evaluated on a batch "
                "with no bound-parameter vector attached (serving bug: the "
                "step was compiled without params plumbing)")
        v = batch.params[expr.index]
        arr = jnp.full(batch.capacity, v, dtype=_jnp_dtype(expr.type))
        return Column(arr, None)

    # -- calls ------------------------------------------------------------
    def _call(self, expr: CallExpression, batch: Batch) -> Column:
        name = canonical_name(expr.display_name)
        args = expr.arguments

        if name in ("add", "subtract", "multiply", "divide", "modulus"):
            return self._arith(name, expr, batch)
        if name in ("eq", "neq", "lt", "lte", "gt", "gte"):
            return self._compare(name, args[0], args[1], batch)
        if name == "between":
            lo = self._compare("gte", args[0], args[1], batch)
            hi = self._compare("lte", args[0], args[2], batch)
            return _kleene_and(lo, hi)
        if name == "not":
            c = self.eval(args[0], batch)
            return Column(~c.values.astype(bool), c.nulls)
        if name == "negate":
            c = self.eval(args[0], batch)
            return Column(-c.values, c.nulls)
        if name == "abs":
            c = self.eval(args[0], batch)
            return Column(jnp.abs(c.values), c.nulls)
        if name in ("year", "month", "day", "quarter"):
            c = self.eval(args[0], batch)
            y, m, d = _civil_from_days(c.values)
            part = {"year": y, "month": m, "day": d, "quarter": (m + 2) // 3}[name]
            return Column(part.astype(jnp.int64), c.nulls)
        if name == "cast":
            return self._cast(args[0], expr.type, batch)
        if name == "like":
            return self._like(args[0], args[1], batch)
        if name == "substr":
            return self._substr(expr, batch)
        if name == "length":
            c = self.eval(args[0], batch)
            if c.dictionary is None:
                raise NotImplementedError("length on non-dictionary varchar")
            table = jnp.asarray(np.array([len(s) for s in c.dictionary],
                                         dtype=np.int64))
            return Column(table[c.values], c.nulls)
        if name in ("coalesce",):
            return self._coalesce([self.eval(a, batch) for a in args])
        if name in _DOUBLE_FNS:
            c = self.eval(args[0], batch)
            v = _to_common_numeric(c, args[0].type, DoubleType())
            if name == "power":
                b = self.eval(args[1], batch)
                bv = _to_common_numeric(b, args[1].type, DoubleType())
                return Column(jnp.power(v, bv), _combine_nulls(c, b))
            return Column(_DOUBLE_FNS[name](v), c.nulls)
        if name in ("ceiling", "ceil", "floor"):
            c = self.eval(args[0], batch)
            t = args[0].type
            if isinstance(t, (DoubleType, RealType)):
                f = jnp.ceil if name != "floor" else jnp.floor
                return Column(f(c.values), c.nulls)
            if _is_decimal(t) and t.scale > 0:
                den = 10 ** t.scale
                v = c.values
                out = (-((-v) // den)) if name != "floor" else (v // den)
                return Column(out, c.nulls)
            return Column(c.values, c.nulls)
        if name == "sign":
            c = self.eval(args[0], batch)
            return Column(jnp.sign(c.values), c.nulls)
        if name == "truncate":
            c = self.eval(args[0], batch)
            v = _to_common_numeric(c, args[0].type, DoubleType())
            return Column(jnp.trunc(v), c.nulls)
        if name == "round":
            c = self.eval(args[0], batch)
            if len(args) > 1 and not isinstance(args[1],
                                                ConstantExpression):
                raise NotImplementedError(
                    "round with non-constant digits")
            digits = int(args[1].value) if len(args) > 1 else 0
            if _is_decimal(expr.type):
                s = args[0].type.scale if _is_decimal(args[0].type) else 0
                v = c.values
                if digits < s:
                    den = 10 ** (s - digits)
                    q = jnp.sign(v) * ((jnp.abs(v) + den // 2) // den) * den
                    v = q.astype(c.values.dtype)
                return Column(_rescale(v, s, expr.type.scale), c.nulls)
            v = _to_common_numeric(c, args[0].type, DoubleType())
            scale = 10.0 ** digits
            # SQL rounds half AWAY from zero (jnp.round is half-even)
            out = jnp.sign(v) * jnp.floor(jnp.abs(v) * scale + 0.5) / scale
            if isinstance(expr.type, (DoubleType, RealType)):
                return Column(out, c.nulls)
            return Column(out.astype(c.values.dtype), c.nulls)
        if name in ("greatest", "least"):
            cols = [self.eval(a, batch) for a in args]
            # compare/return in the DECLARED result type so the emitted
            # scaled values match the planner's precision/scale
            vals = [_to_common_numeric(c, a.type, expr.type)
                    for c, a in zip(cols, args)]
            op = jnp.maximum if name == "greatest" else jnp.minimum
            out = vals[0]
            for v in vals[1:]:
                out = op(out, v)
            return Column(out, _combine_nulls(*cols))
        if name in _STRING_TO_STRING or name in _STRING_TO_VALUE \
                or name == "concat":
            return self._string_fn(name, expr, batch)
        if name in ("date_trunc", "date_add", "date_diff", "day_of_week",
                    "day_of_year", "week"):
            return self._date_fn(name, expr, batch)
        if name in ("array_constructor", "subscript", "element_at",
                    "cardinality", "contains", "array_max", "array_min",
                    "array_position", "repeat", "sequence"):
            return self._array_fn(name, expr, batch)
        # -- math/bitwise breadth (MathFunctions.java, BitwiseFunctions.java)
        if name == "log":
            b = self.eval(args[0], batch)
            x = self.eval(args[1], batch)
            bv = _to_common_numeric(b, args[0].type, DoubleType())
            xv = _to_common_numeric(x, args[1].type, DoubleType())
            return Column(jnp.log(xv) / jnp.log(bv), _combine_nulls(b, x))
        if name == "atan2":
            y = self.eval(args[0], batch)
            x = self.eval(args[1], batch)
            yv = _to_common_numeric(y, args[0].type, DoubleType())
            xv = _to_common_numeric(x, args[1].type, DoubleType())
            return Column(jnp.arctan2(yv, xv), _combine_nulls(y, x))
        if name in ("is_nan", "is_finite", "is_infinite"):
            c = self.eval(args[0], batch)
            v = _to_common_numeric(c, args[0].type, DoubleType())
            out = {"is_nan": jnp.isnan, "is_finite": jnp.isfinite,
                   "is_infinite": jnp.isinf}[name](v)
            return Column(out, c.nulls)
        if name in ("bitwise_and", "bitwise_or", "bitwise_xor"):
            a = self.eval(args[0], batch)
            b = self.eval(args[1], batch)
            op = {"bitwise_and": jnp.bitwise_and,
                  "bitwise_or": jnp.bitwise_or,
                  "bitwise_xor": jnp.bitwise_xor}[name]
            return Column(op(a.values.astype(jnp.int64),
                             b.values.astype(jnp.int64)),
                          _combine_nulls(a, b))
        if name == "bitwise_not":
            c = self.eval(args[0], batch)
            return Column(~c.values.astype(jnp.int64), c.nulls)
        if name in ("bitwise_left_shift", "bitwise_right_shift",
                    "bitwise_arithmetic_shift_right"):
            a = self.eval(args[0], batch)
            b = self.eval(args[1], batch)
            av = a.values.astype(jnp.int64)
            shv = b.values.astype(jnp.int64)
            # int64 shift semantics: counts >= 64 shift everything out
            # (0 for left/logical-right; arithmetic-right saturates to
            # the sign fill); Presto ERRORS on negative counts, relaxed
            # to NULL here (error->NULL convention, width_bucket-style)
            big = shv >= 64
            sh = jnp.clip(shv, 0, 63)
            if name == "bitwise_left_shift":
                out = jnp.where(big, 0, av << sh)
            elif name == "bitwise_arithmetic_shift_right":
                out = av >> jnp.where(big, 63, sh)
            else:       # logical right shift
                out = jnp.where(big, 0,
                                jax.lax.shift_right_logical(av, sh))
            nulls = _combine_nulls(a, b)
            bad = shv < 0
            nulls = bad if nulls is None else (nulls | bad)
            return Column(out, nulls)
        if name == "width_bucket":
            x = self.eval(args[0], batch)
            lo = self.eval(args[1], batch)
            hi = self.eval(args[2], batch)
            n = self.eval(args[3], batch)
            xv = _to_common_numeric(x, args[0].type, DoubleType())
            lov = _to_common_numeric(lo, args[1].type, DoubleType())
            hiv = _to_common_numeric(hi, args[2].type, DoubleType())
            nv = n.values.astype(jnp.int64)
            span = jnp.where(hiv == lov, 1.0, hiv - lov)
            v = (xv - lov) * nv / span
            # 1-ulp tolerance before the floor: XLA's CPU fast-math may
            # reassociate a*n/b as a*(n/b), landing a hair under exact
            # bucket edges; the oracle applies the same nudge, making the
            # edge definition shared rather than compiler-dependent
            bucket = jnp.floor(v * (1 + 2.0 ** -40)).astype(jnp.int64) + 1
            out = jnp.clip(bucket, 0, jnp.maximum(nv + 1, 0))
            # Presto ERRORS on bucketCount <= 0; relaxed to NULL here
            # (the documented error->NULL convention), oracle-mirrored
            nulls = _combine_nulls(x, lo, hi, n)
            bad = nv <= 0
            nulls = bad if nulls is None else (nulls | bad)
            return Column(out, nulls)
        raise NotImplementedError(f"scalar function {expr.display_name!r}")

    # -- array functions (fixed-width (capacity, W) representation) --------
    def _array_fn(self, name: str, expr: CallExpression,
                  batch: Batch) -> Column:
        """Array kernels over the padded (capacity, W) element matrix
        (reference ArrayFunctions.java / ArraySubscriptOperator.java;
        element NULLs inside arrays are not represented yet — Presto's
        out-of-bounds subscript ERROR is relaxed to NULL, element_at
        semantics)."""
        args = expr.arguments
        if name == "array_constructor":
            cols = [self.eval(a, batch) for a in args]
            if not cols:
                return Column(jnp.zeros((batch.capacity, 0),
                                        dtype=jnp.int64),
                              None, None, None,
                              jnp.zeros(batch.capacity, dtype=jnp.int32))
            if any(c.dictionary is not None or c.lazy is not None
                   or c.lengths is not None for c in cols):
                raise NotImplementedError(
                    "array elements must be scalar numerics")
            if any(c.nulls is not None for c in cols):
                raise NotImplementedError(
                    "NULL array elements not supported")
            dt = jnp.result_type(*[c.values.dtype for c in cols])
            vals = jnp.stack([c.values.astype(dt) for c in cols], axis=1)
            lengths = jnp.full(batch.capacity, len(cols), dtype=jnp.int32)
            return Column(vals, None, None, None, lengths)
        arr = self.eval(args[0], batch)
        if name == "repeat":
            elem = arr      # repeat(x, n): x is scalar, n constant
            if not isinstance(args[1], ConstantExpression):
                raise NotImplementedError("repeat with non-constant count")
            # negative count clamps to the empty array (Presto ERRORS;
            # relaxed per the error->NULL/identity convention, and the
            # oracle clamps identically)
            n = max(int(args[1].value), 0)
            vals = jnp.tile(elem.values[:, None], (1, max(n, 1)))
            if n == 0:
                vals = vals[:, :0]
            return Column(vals, elem.nulls, None, None,
                          jnp.full(batch.capacity, n, dtype=jnp.int32))
        if name == "sequence":
            if not all(isinstance(a, ConstantExpression) for a in args):
                raise NotImplementedError(
                    "sequence with non-constant bounds")
            lo, hi = int(args[0].value), int(args[1].value)
            step = int(args[2].value) if len(args) > 2 else 1
            seq = jnp.arange(lo, hi + (1 if step > 0 else -1), step,
                             dtype=jnp.int64)
            vals = jnp.tile(seq[None, :], (batch.capacity, 1))
            return Column(vals, None, None, None,
                          jnp.full(batch.capacity, seq.shape[0],
                                   dtype=jnp.int32))
        if arr.lengths is None:
            raise NotImplementedError(f"{name} on non-array input")
        W = arr.values.shape[1]
        lens = arr.lengths
        if name == "cardinality":
            return Column(lens.astype(jnp.int64), arr.nulls)
        if name in ("subscript", "element_at"):
            idx = self.eval(args[1], batch)
            raw = idx.values.astype(jnp.int64)
            if name == "element_at":
                # element_at(-n) indexes from the end (ArrayFunctions.java)
                raw = jnp.where(raw < 0, lens.astype(jnp.int64) + raw + 1,
                                raw)
            i0 = raw - 1                                   # 1-based
            oob = (i0 < 0) | (i0 >= lens.astype(jnp.int64))
            safe = jnp.clip(i0, 0, max(W - 1, 0))
            if W == 0:
                out = jnp.zeros(batch.capacity, dtype=arr.values.dtype)
            else:
                out = jnp.take_along_axis(
                    arr.values, safe[:, None], axis=1)[:, 0]
            nulls = oob | arr.null_mask()
            if idx.nulls is not None:
                nulls = nulls | idx.nulls
            return Column(out, nulls)
        live = jnp.arange(W, dtype=jnp.int32)[None, :] \
            < lens[:, None]                                 # (cap, W)
        if name == "contains":
            x = self.eval(args[1], batch)
            hit = jnp.any(live & (arr.values == x.values[:, None]), axis=1)
            nulls = arr.nulls
            if x.nulls is not None:
                nulls = x.nulls if nulls is None else nulls | x.nulls
            return Column(hit, nulls)
        if name in ("array_max", "array_min"):
            big = jnp.asarray(
                jnp.inf if jnp.issubdtype(arr.values.dtype, jnp.floating)
                else jnp.iinfo(arr.values.dtype).max, arr.values.dtype)
            ident = big if name == "array_min" else (
                -big if jnp.issubdtype(arr.values.dtype, jnp.floating)
                else jnp.asarray(jnp.iinfo(arr.values.dtype).min,
                                 arr.values.dtype))
            masked = jnp.where(live, arr.values, ident)
            red = jnp.min if name == "array_min" else jnp.max
            out = red(masked, axis=1) if W else \
                jnp.zeros(batch.capacity, dtype=arr.values.dtype)
            empty = lens == 0
            nulls = empty | arr.null_mask()
            return Column(out, nulls)
        if name == "array_position":
            x = self.eval(args[1], batch)
            eq = live & (arr.values == x.values[:, None])
            first = jnp.argmax(eq, axis=1)
            found = jnp.any(eq, axis=1)
            out = jnp.where(found, first + 1, 0).astype(jnp.int64)
            nulls = arr.nulls
            if x.nulls is not None:
                nulls = x.nulls if nulls is None else nulls | x.nulls
            return Column(out, nulls)
        raise NotImplementedError(name)

    # -- string functions over dictionary columns -------------------------
    def _string_fn(self, name: str, expr: CallExpression,
                   batch: Batch) -> Column:
        """String functions computed host-side over the (static) dictionary
        and applied as a code remap / lookup — the dictionary-encoding
        equivalent of the reference's per-row varchar kernels
        (presto-main-base/.../operator/scalar/StringFunctions.java)."""
        args = expr.arguments
        if name == "concat":
            return self._concat(args, batch)
        c = self.eval(args[0], batch)
        if c.dictionary is None:
            raise NotImplementedError(f"{name} on non-dictionary varchar")
        extra = []
        for a in args[1:]:
            if not isinstance(a, ConstantExpression):
                raise NotImplementedError(f"{name} with non-constant args")
            extra.append(a.value)
        if name in _STRING_TO_STRING:
            fn = _STRING_TO_STRING[name]
            mapped = [fn(s, *extra) for s in c.dictionary]
            return _reencode(c, mapped)
        fn, dtype = _STRING_TO_VALUE[name]
        raw = [fn(s, *extra) for s in c.dictionary]
        table = jnp.asarray(np.array([0 if v is None else v for v in raw],
                                     dtype=dtype))
        out_nulls = c.nulls
        if any(v is None for v in raw):
            null_tab = jnp.asarray(np.array([v is None for v in raw]))
            out_nulls = null_tab[c.values] if out_nulls is None \
                else (null_tab[c.values] | out_nulls)
        return Column(table[c.values], out_nulls)

    def _concat(self, args, batch: Batch) -> Column:
        cols = [self.eval(a, batch) for a in args]
        dict_cols = [c for c in cols if c.dictionary is not None
                     and len(c.dictionary) > 1]
        if any(c.dictionary is None for c in cols):
            raise NotImplementedError("concat on non-dictionary varchar")
        if len(dict_cols) > 2 or (
                len(dict_cols) == 2
                and len(dict_cols[0].dictionary)
                * len(dict_cols[1].dictionary) > 65536):
            raise NotImplementedError("concat dictionary product too large")
        nulls = None
        for c in cols:
            if c.nulls is not None:
                nulls = _or_null(nulls, c.nulls)
        if any(not c.dictionary for c in cols):
            # an empty dictionary (empty table / all-null column, e.g.
            # after an empty CTAS) has no representable value: emit an
            # all-null empty-dictionary result instead of indexing [0]
            ref = cols[0]
            return Column(jnp.zeros_like(ref.values),
                          jnp.ones(ref.values.shape, dtype=bool),
                          ("",))
        if len(dict_cols) <= 1:
            base = dict_cols[0] if dict_cols else cols[0]
            mapped = ["".join(c.dictionary[0] if c is not base else s
                              for c in cols)
                      for s in base.dictionary]
            return _reencode(Column(base.values, nulls, base.dictionary),
                             mapped)
        a, b = dict_cols
        nb = len(b.dictionary)
        product = []
        for sa in a.dictionary:
            for sb in b.dictionary:
                parts = []
                for c in cols:
                    if c is a:
                        parts.append(sa)
                    elif c is b:
                        parts.append(sb)
                    else:
                        parts.append(c.dictionary[0])
                product.append("".join(parts))
        codes = a.values * nb + b.values
        return _reencode(Column(codes, nulls, tuple(product)), product)

    # -- date functions ---------------------------------------------------
    def _date_fn(self, name: str, expr: CallExpression,
                 batch: Batch) -> Column:
        args = expr.arguments
        if name in ("day_of_week", "day_of_year", "week"):
            c = self.eval(args[0], batch)
            days = c.values.astype(jnp.int64)
            if name == "day_of_week":
                return Column((days + 3) % 7 + 1, c.nulls)
            y, m, d = _civil_from_days(days)
            doy = days - _days_from_civil(y, jnp.ones_like(m),
                                          jnp.ones_like(d)) + 1
            if name == "day_of_year":
                return Column(doy, c.nulls)
            dow = (days + 3) % 7 + 1
            w0 = (10 + doy - dow) // 7
            # nested on the ORIGINAL w: a w0<1 resolved to last year's 53
            # must not be re-clamped by this year's 52-week count
            w = jnp.where(w0 < 1, _iso_weeks_in_year(y - 1),
                          jnp.where(w0 > _iso_weeks_in_year(y), 1, w0))
            return Column(w, c.nulls)
        unit = str(args[0].value).lower()
        if name == "date_trunc":
            c = self.eval(args[1], batch)
            days = c.values.astype(jnp.int64)
            if unit == "day":
                return Column(days.astype(c.values.dtype), c.nulls)
            if unit == "week":
                return Column((days - (days + 3) % 7)
                              .astype(c.values.dtype), c.nulls)
            y, m, _d = _civil_from_days(days)
            if unit == "quarter":
                m = ((m - 1) // 3) * 3 + 1
            elif unit == "year":
                m = jnp.ones_like(m)
            out = _days_from_civil(y, m, jnp.ones_like(m))
            return Column(out.astype(c.values.dtype), c.nulls)
        if name == "date_add":
            n = self.eval(args[1], batch).values.astype(jnp.int64)
            c = self.eval(args[2], batch)
            days = c.values.astype(jnp.int64)
            if unit in ("day", "week"):
                out = days + n * (7 if unit == "week" else 1)
                return Column(out.astype(c.values.dtype), c.nulls)
            months = n * {"month": 1, "quarter": 3, "year": 12}[unit]
            out = _add_months(days, months)
            return Column(out.astype(c.values.dtype), c.nulls)
        # date_diff(unit, a, b) = b - a in whole units, truncated toward 0
        a = self.eval(args[1], batch)
        b = self.eval(args[2], batch)
        nulls = _combine_nulls(a, b)
        da = a.values.astype(jnp.int64)
        db = b.values.astype(jnp.int64)
        if unit in ("day", "week"):
            diff = db - da
            den = 7 if unit == "week" else 1
            out = jnp.sign(diff) * (jnp.abs(diff) // den)
            return Column(out, nulls)
        ya, ma, dda = _civil_from_days(da)
        yb, mb, ddb = _civil_from_days(db)
        months = (yb * 12 + mb) - (ya * 12 + ma)
        # partial months don't count: back off one when the day-of-month
        # hasn't been reached yet (sign-aware)
        months = jnp.where((months > 0) & (ddb < dda), months - 1, months)
        months = jnp.where((months < 0) & (ddb > dda), months + 1, months)
        den = {"month": 1, "quarter": 3, "year": 12}[unit]
        out = jnp.sign(months) * (jnp.abs(months) // den)
        return Column(out, nulls)

    def _arith(self, name, expr: CallExpression, batch: Batch) -> Column:
        a_expr, b_expr = expr.arguments
        a, b = self.eval(a_expr, batch), self.eval(b_expr, batch)
        ta, tb, tr = a_expr.type, b_expr.type, expr.type
        nulls = _combine_nulls(a, b)

        if isinstance(tr, (DoubleType, RealType)):
            av = _to_common_numeric(a, ta, tr)
            bv = _to_common_numeric(b, tb, tr)
            op = {"add": jnp.add, "subtract": jnp.subtract,
                  "multiply": jnp.multiply, "divide": jnp.divide,
                  "modulus": jnp.mod}[name]
            return Column(op(av, bv), nulls)

        if _is_decimal(tr):
            rs = tr.scale
            sa = ta.scale if _is_decimal(ta) else 0
            sb = tb.scale if _is_decimal(tb) else 0
            av, bv = a.values, b.values
            if name == "multiply":
                out = av * bv  # scale sa+sb
                return Column(_rescale(out, sa + sb, rs), nulls)
            if name == "divide":
                # numerator scaled to rs + sb, then round-half-up divide
                num = _rescale(av, sa, rs + sb)
                safe_b = jnp.where(bv == 0, 1, bv)
                q = jnp.sign(num) * jnp.sign(safe_b) * (
                    (jnp.abs(num) + jnp.abs(safe_b) // 2) // jnp.abs(safe_b))
                nulls = _or_null(nulls, bv == 0)
                return Column(q.astype(av.dtype), nulls)
            av = _rescale(av, sa, rs)
            bv = _rescale(bv, sb, rs)
            if name == "modulus":
                # same contract as the integer path: dividend-sign result,
                # NULL on a zero divisor (jnp.mod's divisor-sign
                # convention differs from SQL's)
                safe_b = jnp.where(bv == 0, 1, bv)
                r = (jnp.sign(av)
                     * (jnp.abs(av) % jnp.abs(safe_b))).astype(av.dtype)
                return Column(r, _or_null(nulls, bv == 0))
            op = {"add": jnp.add, "subtract": jnp.subtract}[name]
            return Column(op(av, bv), nulls)

        # integer domain
        av, bv = a.values, b.values
        if name == "divide":
            safe_b = jnp.where(bv == 0, 1, bv)
            # SQL integer division truncates toward zero
            q = (jnp.sign(av) * jnp.sign(safe_b)
                 * (jnp.abs(av) // jnp.abs(safe_b))).astype(av.dtype)
            return Column(q, _or_null(nulls, bv == 0))
        if name == "modulus":
            safe_b = jnp.where(bv == 0, 1, bv)
            r = (jnp.sign(av) * (jnp.abs(av) % jnp.abs(safe_b))).astype(av.dtype)
            return Column(r, _or_null(nulls, bv == 0))
        op = {"add": jnp.add, "subtract": jnp.subtract,
              "multiply": jnp.multiply}[name]
        return Column(op(av, bv), nulls)

    def _compare(self, name, a_expr, b_expr, batch: Batch) -> Column:
        a, b = self.eval(a_expr, batch), self.eval(b_expr, batch)
        nulls = _combine_nulls(a, b)

        # dictionary-coded strings
        if a.dictionary is not None or b.dictionary is not None:
            return self._compare_strings(name, a, b, nulls)

        common = _common_super(a_expr.type, b_expr.type)
        av = _to_common_numeric(a, a_expr.type, common)
        bv = _to_common_numeric(b, b_expr.type, common)
        op = {"eq": jnp.equal, "neq": jnp.not_equal, "lt": jnp.less,
              "lte": jnp.less_equal, "gt": jnp.greater,
              "gte": jnp.greater_equal}[name]
        return Column(op(av, bv), nulls)

    def _compare_strings(self, name, a: Column, b: Column, nulls) -> Column:
        if a.dictionary is None or b.dictionary is None:
            raise NotImplementedError("string comparison requires dictionaries")
        if len(b.dictionary) == 1:
            # column vs literal: precompute per-code truth table (host)
            lit = b.dictionary[0]
            import operator as _op
            pyop = {"eq": _op.eq, "neq": _op.ne, "lt": _op.lt,
                    "lte": _op.le, "gt": _op.gt, "gte": _op.ge}[name]
            table = jnp.asarray(np.array([pyop(s, lit) for s in a.dictionary],
                                         dtype=bool))
            return Column(table[a.values], nulls)
        if len(a.dictionary) == 1:
            flip = {"eq": "eq", "neq": "neq", "lt": "gt", "lte": "gte",
                    "gt": "lt", "gte": "lte"}[name]
            return self._compare_strings(flip, b, a, nulls)
        if a.dictionary == b.dictionary:
            op = {"eq": jnp.equal, "neq": jnp.not_equal, "lt": jnp.less,
                  "lte": jnp.less_equal, "gt": jnp.greater,
                  "gte": jnp.greater_equal}[name]
            if name in ("eq", "neq"):
                return Column(op(a.values, b.values), nulls)
            # order comparisons need rank order == code order; our dictionaries
            # are sorted at build time (batch.py), so codes are rank codes.
            return Column(op(a.values, b.values), nulls)
        # different dictionaries: map b's codes into a's dictionary (host)
        index = {s: i for i, s in enumerate(a.dictionary)}
        remap = jnp.asarray(np.array(
            [index.get(s, -1) for s in b.dictionary], dtype=np.int32))
        bv = remap[b.values]
        if name == "eq":
            return Column((a.values == bv) & (bv >= 0), nulls)
        if name == "neq":
            return Column((a.values != bv) | (bv < 0), nulls)
        raise NotImplementedError("ordering across distinct dictionaries")

    def _like(self, value_expr, pattern_expr, batch: Batch) -> Column:
        if not isinstance(pattern_expr, ConstantExpression):
            raise NotImplementedError("LIKE with non-constant pattern")
        c = self.eval(value_expr, batch)
        if c.dictionary is None:
            raise NotImplementedError("LIKE on non-dictionary varchar")
        match = like_matcher(str(pattern_expr.value))
        table = jnp.asarray(np.array(
            [match(s) for s in c.dictionary], dtype=bool))
        return Column(table[c.values], c.nulls)

    def _substr(self, expr: CallExpression, batch: Batch) -> Column:
        args = expr.arguments
        c = self.eval(args[0], batch)
        if c.dictionary is None:
            raise NotImplementedError("substr on non-dictionary varchar")
        if not all(isinstance(a, ConstantExpression) for a in args[1:]):
            raise NotImplementedError("substr with non-constant bounds")
        start = int(args[1].value)
        length = int(args[2].value) if len(args) > 2 else None
        def sub(s):
            i = start - 1 if start > 0 else len(s) + start
            return s[i:i + length] if length is not None else s[i:]
        new_values = [sub(s) for s in c.dictionary]
        uniq = sorted(set(new_values))
        remap = jnp.asarray(np.array([uniq.index(v) for v in new_values],
                                     dtype=np.int32))
        return Column(remap[c.values], c.nulls, tuple(uniq))

    def _cast(self, arg: RowExpression, to: Type, batch: Batch) -> Column:
        c = self.eval(arg, batch)
        frm = arg.type
        if frm.signature == to.signature:
            return c
        if isinstance(to, DoubleType):
            if _is_decimal(frm):
                return Column(c.values.astype(jnp.float64) / _pow10(frm.scale),
                              c.nulls)
            return Column(c.values.astype(jnp.float64), c.nulls)
        if _is_decimal(to):
            if _is_decimal(frm):
                return Column(_rescale(c.values, frm.scale, to.scale), c.nulls)
            if isinstance(frm, (DoubleType, RealType)):
                scaled = c.values * _pow10(to.scale)
                return Column(jnp.round(scaled).astype(jnp.int64), c.nulls)
            return Column(c.values.astype(jnp.int64) * _pow10(to.scale), c.nulls)
        if isinstance(to, (IntegerType,)):
            return Column(c.values.astype(jnp.int32), c.nulls)
        if to.signature == "bigint":
            if _is_decimal(frm):
                return Column(_rescale(c.values, frm.scale, 0), c.nulls)
            return Column(c.values.astype(jnp.int64), c.nulls)
        if isinstance(to, (VarcharType, CharType)) and c.dictionary is not None:
            return c
        raise NotImplementedError(f"cast {frm} -> {to}")

    def _coalesce(self, cols: List[Column]) -> Column:
        out_v = cols[-1].values
        out_n = cols[-1].null_mask()
        for c in reversed(cols[:-1]):
            isnull = c.null_mask()
            out_v = jnp.where(isnull, out_v, c.values)
            out_n = isnull & out_n
        has = any(c.nulls is not None for c in cols)
        return Column(out_v, out_n if has else None)

    # -- special forms ----------------------------------------------------
    def _special(self, expr: SpecialFormExpression, batch: Batch) -> Column:
        form = expr.form
        args = expr.arguments
        if form == "AND":
            cols = [self.eval(a, batch) for a in args]
            out = cols[0]
            for c in cols[1:]:
                out = _kleene_and(out, c)
            return out
        if form == "OR":
            cols = [self.eval(a, batch) for a in args]
            out = cols[0]
            for c in cols[1:]:
                out = _kleene_or(out, c)
            return out
        if form == "IS_NULL":
            c = self.eval(args[0], batch)
            return Column(c.null_mask(), None)
        if form == "IF":
            cond = self.eval(args[0], batch)
            t = self.eval(args[1], batch)
            f = self.eval(args[2], batch)
            pred = cond.values.astype(bool) & ~cond.null_mask()
            t, f = _merge_dictionaries(t, f)
            values = jnp.where(pred, t.values, f.values)
            nulls = jnp.where(pred, t.null_mask(), f.null_mask())
            has = t.nulls is not None or f.nulls is not None
            return Column(values, nulls if has else None, t.dictionary)
        if form == "COALESCE":
            return self._coalesce([self.eval(a, batch) for a in args])
        if form == "IN":
            return self._in(args[0], args[1:], batch)
        if form == "NULL_IF":
            a = self.eval(args[0], batch)
            b = self.eval(args[1], batch)
            # NULLIF(x, y) is x unless x == y with both non-null
            eq = (a.values == b.values) & ~a.null_mask() & ~b.null_mask()
            return Column(a.values, _or_null(a.nulls, eq))
        raise NotImplementedError(f"special form {form}")

    def _in(self, value_expr, list_exprs, batch: Batch) -> Column:
        c = self.eval(value_expr, batch)
        consts = [e for e in list_exprs if isinstance(e, ConstantExpression)]
        if len(consts) != len(list_exprs):
            raise NotImplementedError("IN with non-constant list")
        if c.dictionary is not None:
            values = {str(e.value) for e in consts}
            table = jnp.asarray(np.array([s in values for s in c.dictionary],
                                         dtype=bool))
            return Column(table[c.values], c.nulls)
        out = jnp.zeros(batch.capacity, dtype=bool)
        for e in consts:
            v = constant_device_value(e.value, value_expr.type)
            out = out | (c.values == v)
        return Column(out, c.nulls)


def _merge_dictionaries(a: Column, b: Column):
    """Remap two dictionary-coded columns onto one union dictionary (static,
    host-side) so their codes are directly comparable/mixable."""
    if a.dictionary is None or b.dictionary is None or \
            a.dictionary == b.dictionary:
        return a, b
    union = tuple(sorted(set(a.dictionary) | set(b.dictionary)))
    index = {s: i for i, s in enumerate(union)}
    remap_a = jnp.asarray(np.array([index[s] for s in a.dictionary],
                                   dtype=np.int32))
    remap_b = jnp.asarray(np.array([index[s] for s in b.dictionary],
                                   dtype=np.int32))
    return (Column(remap_a[a.values], a.nulls, union),
            Column(remap_b[b.values], b.nulls, union))


def _or_null(nulls, extra_mask):
    if nulls is None:
        return extra_mask
    return nulls | extra_mask


def _kleene_and(a: Column, b: Column) -> Column:
    av = a.values.astype(bool)
    bv = b.values.astype(bool)
    an, bn = a.null_mask(), b.null_mask()
    value = (av | an) & (bv | bn)  # true unless a definite false
    nulls = value & (an | bn)      # null if not definitively false
    has = a.nulls is not None or b.nulls is not None
    return Column(av & bv if not has else (value & ~nulls), nulls if has else None)


def _kleene_or(a: Column, b: Column) -> Column:
    av = a.values.astype(bool)
    bv = b.values.astype(bool)
    an, bn = a.null_mask(), b.null_mask()
    definite_true = (av & ~an) | (bv & ~bn)
    nulls = ~definite_true & (an | bn)
    has = a.nulls is not None or b.nulls is not None
    return Column(definite_true if has else (av | bv), nulls if has else None)


def _jnp_dtype(typ: Type):
    if isinstance(typ, DoubleType):
        return jnp.float64
    if isinstance(typ, RealType):
        return jnp.float32
    if isinstance(typ, BooleanType):
        return jnp.bool_
    if isinstance(typ, IntegerType) or isinstance(typ, DateType):
        return jnp.int32
    return jnp.int64


_DOUBLE_FNS = {
    "sqrt": jnp.sqrt, "exp": jnp.exp, "ln": jnp.log,
    "log2": lambda v: jnp.log(v) / jnp.log(2.0),
    "log10": lambda v: jnp.log(v) / jnp.log(10.0),
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "cbrt": jnp.cbrt, "degrees": jnp.degrees, "radians": jnp.radians,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "power": None,     # binary; handled inline
}


def _lpad(s, n, fill=" "):
    """Presto lpad: pad cycles from the START of the fill string."""
    n, fill = int(n), str(fill)
    if len(s) >= n:
        return s[:n]
    pad = n - len(s)
    return (fill * (pad // len(fill) + 1))[:pad] + s


def _rpad(s, n, fill=" "):
    n, fill = int(n), str(fill)
    if len(s) >= n:
        return s[:n]
    pad = n - len(s)
    return s + (fill * (pad // len(fill) + 1))[:pad]


def _replace(s, find, repl=""):
    return s.replace(str(find), str(repl))


# -- regexp / URL / JSON / split scalar kernels (pure python over
# dictionary entries or host-materialized strings; the per-entry
# semantics follow the reference's operator/scalar implementations:
# RegexpFunctions (re2j semantics approximated by `re`),
# UrlFunctions.java, JsonFunctions.java, StringFunctions.split_part).
# A kernel may return None = SQL NULL; the dictionary remap carries it
# into the null mask.

def _re_compiled(pattern):
    import re
    return re.compile(str(pattern))


def _regexp_like(s, pattern):
    return _re_compiled(pattern).search(s) is not None


def _regexp_extract(s, pattern, group=0):
    m = _re_compiled(pattern).search(s)
    if m is None:
        return None
    try:
        return m.group(int(group))
    except IndexError:
        return None


def _regexp_replace(s, pattern, repl=""):
    import re
    # Presto replacement references are $N / ${name}; python wants \N
    py = re.sub(r"\$(\d+)", r"\\\1", str(repl))
    py = re.sub(r"\$\{(\w+)\}", r"\\g<\1>", py)
    return _re_compiled(pattern).sub(py, s)


def _split_part(s, delim, index):
    parts = s.split(str(delim))
    i = int(index)
    if i < 1 or i > len(parts):
        return None
    return parts[i - 1]


def _url_parts(s):
    from urllib.parse import urlparse
    return urlparse(s)


def _json_extract_scalar(s, path):
    """Subset of the reference JsonExtract path language:
    $.a.b[0].c — object fields and array subscripts."""
    import json as _json
    import re
    try:
        v = _json.loads(s)
    except (ValueError, TypeError):
        return None
    p = str(path)
    if not p.startswith("$"):
        return None
    for tok in re.findall(r"\.([A-Za-z_][\w]*)|\[(\d+)\]|\[\"([^\"]+)\"\]",
                          p[1:]):
        field, idx, qfield = tok
        key = field or qfield
        if key:
            if not isinstance(v, dict) or key not in v:
                return None
            v = v[key]
        else:
            if not isinstance(v, list) or int(idx) >= len(v):
                return None
            v = v[int(idx)]
    if isinstance(v, (dict, list)) or v is None:
        return None          # scalar extraction only (reference contract)
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


_STRING_TO_STRING = {
    "upper": lambda s: s.upper(),
    "lower": lambda s: s.lower(),
    "trim": lambda s: s.strip(),
    "ltrim": lambda s: s.lstrip(),
    "rtrim": lambda s: s.rstrip(),
    "reverse": lambda s: s[::-1],
    "replace": _replace,
    "lpad": _lpad,
    "rpad": _rpad,
    "regexp_extract": _regexp_extract,
    "regexp_replace": _regexp_replace,
    "split_part": _split_part,
    "url_extract_protocol": lambda s: _url_parts(s).scheme or None,
    "url_extract_host": lambda s: _url_parts(s).hostname or None,
    "url_extract_path": lambda s: _url_parts(s).path,
    "url_extract_query": lambda s: _url_parts(s).query or None,
    "url_extract_fragment": lambda s: _url_parts(s).fragment or None,
    "json_extract_scalar": _json_extract_scalar,
}

_STRING_TO_VALUE = {
    # name -> (fn(entry, *const_args), numpy dtype)
    "strpos": (lambda s, sub: s.find(str(sub)) + 1, np.int64),
    "starts_with": (lambda s, p: s.startswith(str(p)), bool),
    "ends_with": (lambda s, p: s.endswith(str(p)), bool),
    "regexp_like": (_regexp_like, bool),
    "codepoint": (lambda s: ord(s[0]) if s else None, np.int64),
    "url_extract_port": (lambda s: _url_port(s), np.int64),
}


def _url_port(s):
    try:
        return _url_parts(s).port       # None when absent
    except ValueError:                  # malformed port -> NULL (Presto
        return None                     # UrlFunctions returns null)


def _reencode(c: Column, mapped) -> Column:
    """Remap a dictionary column through transformed entries, dedup+sort the
    result so codes stay rank codes (grouping and order comparisons depend
    on it).  None entries become NULL rows."""
    uniq = tuple(sorted({s for s in mapped if s is not None}))
    index = {s: i for i, s in enumerate(uniq)}
    remap = jnp.asarray(np.array([0 if s is None else index[s]
                                  for s in mapped], dtype=np.int32))
    if any(s is None for s in mapped):
        null_tab = jnp.asarray(np.array([s is None for s in mapped]))
        nulls = null_tab[c.values]
        if c.nulls is not None:
            nulls = nulls | c.nulls
        return Column(remap[c.values], nulls, uniq or ("",))
    return Column(remap[c.values], c.nulls, uniq or ("",))


def _civil_from_days(z):
    """Days-since-epoch -> (year, month, day); Hinnant's algorithm, integer
    ops only so XLA fuses it."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _days_from_civil(y, m, d):
    """(year, month, day) -> days since epoch; inverse of
    _civil_from_days (Hinnant)."""
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _iso_weeks_in_year(y):
    """52 or 53 (ISO-8601): 53 iff Jan 1 or Dec 31 falls on Thursday."""
    jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    dec31 = _days_from_civil(y, jnp.full_like(y, 12), jnp.full_like(y, 31))
    thu = lambda days: (days + 3) % 7 + 1 == 4  # noqa: E731
    return jnp.where(thu(jan1) | thu(dec31), 53, 52)


def _add_months(days, months):
    """Calendar month addition with end-of-month clamping (Presto
    date_add('month'): Jan 31 + 1 month = Feb 28/29)."""
    y, m, d = _civil_from_days(days)
    total = (m - 1) + months
    y2 = y + total // 12
    m2 = total % 12 + 1
    first = _days_from_civil(y2, m2, jnp.ones_like(m2))
    nxt_total = total + 1
    next_first = _days_from_civil(y + nxt_total // 12,
                                  nxt_total % 12 + 1, jnp.ones_like(m2))
    dim = next_first - first
    return first + jnp.minimum(d, dim) - 1
