"""Numpy reference executor: interprets the same plan IR on the host.

The differential-testing oracle, playing the role H2 plays in the reference's
QueryAssertions (presto-tests/.../tests/QueryAssertions.java:52,
H2QueryRunner.java:105): every conformance test runs a query on the TPU engine
and on this interpreter over identical generated data and diffs results.
Implementation is deliberately simple row/column numpy code sharing nothing
with the device engine (batch.py / operators.py / lowering.py) except the plan
IR and the data generator.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.types import (ArrayType, BooleanType, CharType, DateType,
                            DecimalType, DoubleType, RealType, Type,
                            VarcharType)
from ..connectors import catalog, tpch
from ..spi import plan as P
from ..spi.expr import (CallExpression, ConstantExpression, RowExpression,
                        SpecialFormExpression, VariableReferenceExpression)
from .lowering import canonical_name, constant_device_value

Col = Tuple[np.ndarray, Optional[np.ndarray]]  # (values, nulls|None)


class Table:
    """name -> (values, nulls). Strings are object arrays, decimals unscaled
    int64 (object for >int64), dates int days."""

    def __init__(self, cols: Dict[str, Col], n: int):
        self.cols = cols
        self.n = n

    def mask(self, keep: np.ndarray) -> "Table":
        return Table({k: (v[keep], None if m is None else m[keep])
                      for k, (v, m) in self.cols.items()}, int(keep.sum()))

    def take(self, idx: np.ndarray) -> "Table":
        return Table({k: (v[idx], None if m is None else m[idx])
                      for k, (v, m) in self.cols.items()}, len(idx))


# when set (execute_reference(stats=...)), _exec fills it with one
# entry per plan node id: {"rows", "wall_s", "batches", "operatorType"}
# — the oracle-side twin of the engine's OperatorStats spine, so
# differential tests can diff the stats SURFACE, not just result rows
_ACTIVE_STATS: Optional[Dict[str, dict]] = None


def execute_reference(node: P.PlanNode,
                      stats: Optional[Dict[str, dict]] = None) -> List[List]:
    """Run a plan, return rows of python values (Decimal for decimals).

    Pass a dict as `stats` to collect per-node operator stats: rows is
    the node's output cardinality, wall_s its INCLUSIVE interpretation
    wall (the interpreter recurses, so a node's wall covers its
    subtree), batches is always 1 (the oracle is single-batch)."""
    global _ACTIVE_STATS
    prev = _ACTIVE_STATS
    _ACTIVE_STATS = stats
    try:
        table = _exec(node)
    finally:
        _ACTIVE_STATS = prev
    names = [v.name for v in node.output_variables]
    types = [v.type for v in node.output_variables]
    return _to_rows(table, names, types)


def _to_rows(table: Table, names, types) -> List[List]:
    from decimal import Decimal
    out = []
    for i in range(table.n):
        row = []
        for name, typ in zip(names, types):
            v, m = table.cols[name]
            if m is not None and m[i]:
                row.append(None)
            elif isinstance(typ, ArrayType):
                row.append(None if v[i] is None
                           else [_py_element(typ.element, e) for e in v[i]])
            elif isinstance(typ, DecimalType):
                row.append(Decimal(int(v[i])) / (10 ** typ.scale))
            elif isinstance(typ, DoubleType):
                row.append(float(v[i]))
            elif isinstance(typ, BooleanType):
                row.append(bool(v[i]))
            elif isinstance(typ, (VarcharType, CharType)):
                row.append(str(v[i]))
            elif isinstance(typ, DateType):
                row.append(str(np.datetime64(int(v[i]), "D")))
            else:
                row.append(int(v[i]))
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# node execution
# ---------------------------------------------------------------------------

def _py_element(etyp: Type, e):
    """Array element -> plain python value (mirrors block_to_values)."""
    if e is None:
        return None
    if isinstance(etyp, (DoubleType, RealType)):
        return float(e)
    if isinstance(etyp, BooleanType):
        return bool(e)
    if isinstance(etyp, (VarcharType, CharType)):
        return str(e)
    if isinstance(etyp, DateType):
        return str(np.datetime64(int(e), "D"))
    from decimal import Decimal
    if isinstance(etyp, DecimalType):
        return Decimal(int(e)) / (10 ** etyp.scale)
    return int(e)


def _exec(node: P.PlanNode) -> Table:
    fn = globals().get("_exec_" + type(node).__name__)
    if fn is None:
        raise NotImplementedError(type(node).__name__)
    if _ACTIVE_STATS is None:
        return fn(node)
    import time
    t0 = time.perf_counter()  # lint: allow-wall-clock
    table = fn(node)
    wall = time.perf_counter() - t0  # lint: allow-wall-clock
    nid = getattr(node, "id", None)
    if nid is not None:
        _ACTIVE_STATS[str(nid)] = {
            "rows": int(table.n),
            "wall_s": wall,
            "batches": 1,
            "operatorType": type(node).__name__.replace("Node", ""),
        }
    return table


def _exec_TableScanNode(node: P.TableScanNode) -> Table:
    th = node.table
    sf = dict(th.extra).get("scaleFactor", 0.01)
    n = catalog.table_row_count(th.table_name, sf, th.connector_id)
    cols = {}
    for v in node.outputs:
        cname = node.assignments[v].name
        raw = catalog.generate_column(th.table_name, cname, sf, 0, n,
                                      th.connector_id)
        nulls = None
        if isinstance(raw, catalog.HostColumn):
            nulls = raw.nulls
            raw = raw.values
        if isinstance(raw, tuple):
            codes, values = raw
            arr = np.array(values, dtype=object)[codes]
        elif isinstance(raw, list):
            arr = np.array(raw, dtype=object)
        else:
            arr = raw
        if nulls is not None and arr.dtype == object:
            # null strings surface as None VALUES too: grouping compares
            # values, so a masked row must not alias its code-0 entry
            arr = arr.copy()
            arr[nulls] = None
        cols[v.name] = (arr, nulls)
    return Table(cols, n)


def _exec_ValuesNode(node: P.ValuesNode) -> Table:
    cols = {}
    for i, v in enumerate(node.outputs):
        vals, nulls = [], []
        for row in node.rows:
            c = row[i]
            val = constant_device_value(c.value, v.type)
            nulls.append(val is None)
            vals.append(0 if val is None else val)
        cols[v.name] = (np.array(vals, dtype=object),
                        np.array(nulls) if any(nulls) else None)
    return Table(cols, len(node.rows))


def _exec_FilterNode(node: P.FilterNode) -> Table:
    t = _exec(node.source)
    v, m = _eval(node.predicate, t)
    keep = v.astype(bool)
    if m is not None:
        keep = keep & ~m
    return t.mask(keep)


def _exec_ProjectNode(node: P.ProjectNode) -> Table:
    t = _exec(node.source)
    cols = {}
    for var, expr in node.assignments.items():
        cols[var.name] = _eval(expr, t)
    return Table(cols, t.n)


def _exec_OutputNode(node: P.OutputNode) -> Table:
    t = _exec(node.source)
    inner = [v.name for v in node.source.output_variables]
    cols = {o.name: t.cols[i] for i, o in zip(inner, node.outputs)}
    return Table(cols, t.n)


def _exec_LimitNode(node: P.LimitNode) -> Table:
    t = _exec(node.source)
    idx = np.arange(min(node.count, t.n))
    return t.take(idx)


def _exec_ExchangeNode(node: P.ExchangeNode) -> Table:
    parts = []
    for i, s in enumerate(node.exchange_sources):
        t = _exec(s)
        if node.inputs:
            mapping = {o.name: iv.name for o, iv in
                       zip(node.partitioning_scheme.output_layout,
                           node.inputs[i])}
            t = Table({o: t.cols[iv] for o, iv in mapping.items()}, t.n)
        parts.append(t)
    if len(parts) == 1:
        return parts[0]
    names = list(parts[0].cols)
    cols = {}
    for nm in names:
        vals = np.concatenate([p.cols[nm][0] for p in parts])
        if any(p.cols[nm][1] is not None for p in parts):
            nulls = np.concatenate([
                p.cols[nm][1] if p.cols[nm][1] is not None
                else np.zeros(p.n, bool) for p in parts])
        else:
            nulls = None
        cols[nm] = (vals, nulls)
    return Table(cols, sum(p.n for p in parts))


def _sort_key_arrays(t: Table, orderings) -> list:
    arrays = []
    for var, order in reversed(orderings):
        v, m = t.cols[var.name]
        desc = order.startswith("DESC")
        if v.dtype == object:
            # rank-encode object values; masked payloads may hold
            # type-mismatched fill (a grouping-set union's null branch
            # fills varchar keys with int zeros) — treat them as None
            items = v.tolist()
            if m is not None:
                items = [None if m[i] else x for i, x in enumerate(items)]
            uniq = sorted(set(items), key=lambda x: (x is None, x))
            rank = {u: i for i, u in enumerate(uniq)}
            v = np.array([rank[x] for x in items], dtype=np.int64)
        vv = v.astype(np.float64) if v.dtype != np.float64 else v.copy()
        vv = np.where(np.isnan(vv), np.inf, vv)
        key = -vv if desc else vv
        if m is not None:
            nulls_first = order.endswith("NULLS_FIRST")
            key = np.where(m, -np.inf if nulls_first else np.inf, key)
        arrays.append(key)
    return arrays


def _exec_SortNode(node: P.SortNode) -> Table:
    t = _exec(node.source)
    idx = np.lexsort(tuple(_sort_key_arrays(t, node.ordering_scheme.orderings)))
    return t.take(idx)


def _exec_TopNNode(node: P.TopNNode) -> Table:
    t = _exec(node.source)
    idx = np.lexsort(tuple(_sort_key_arrays(t, node.ordering_scheme.orderings)))
    return t.take(idx[:node.count])


def _exec_UnionNode(node: P.UnionNode) -> Table:
    tables = [_exec(s) for s in node.inputs]
    cols: Dict[str, Col] = {}
    for v in node.outputs:
        n = v.name
        vals = [t.cols[n][0] for t in tables]
        nulls = [t.cols[n][1] for t in tables]
        if any(x.dtype == object for x in vals):
            vv = np.concatenate([np.asarray(x, dtype=object) for x in vals])
        else:
            vv = np.concatenate(vals)
        if any(m is not None for m in nulls):
            mm = np.concatenate([np.zeros(len(x), dtype=bool)
                                 if m is None else m
                                 for x, m in zip(vals, nulls)])
        else:
            mm = None
        cols[n] = (vv, mm)
    return Table(cols, sum(t.n for t in tables))


def _exec_WindowNode(node: P.WindowNode) -> Table:
    """Per-partition python loop (independent of the device engine's
    segmented-scan formulation).  Supports ranking functions
    (row_number/rank/dense_rank/ntile/percent_rank/cume_dist), value
    functions (lag/lead/first_value/last_value/nth_value) and frame
    aggregates with ROWS offset frames and RANGE
    unbounded/current-row frames (reference WindowOperator.java:69 +
    operator/window/)."""
    t = _exec(node.source)
    n = t.n
    part_vars = node.partition_by
    orderings = list(node.ordering_scheme.orderings) \
        if node.ordering_scheme else []
    sort_specs = [(v, "ASC_NULLS_FIRST") for v in part_vars] + orderings
    if sort_specs and n:
        t = t.take(np.lexsort(tuple(_sort_key_arrays(t, sort_specs))))

    def change_flags(names) -> np.ndarray:
        d = np.zeros(n, dtype=bool)
        if n:
            d[0] = True
        for name in names:
            v, m = t.cols[name]
            a, b = v[1:], v[:-1]
            if v.dtype == np.float64:
                eq = (a == b) | (np.isnan(a) & np.isnan(b))
            else:
                eq = np.asarray(a == b, dtype=bool)
            if m is not None:
                eq = np.where(m[1:] | m[:-1], m[1:] & m[:-1], eq)
            d[1:] |= ~np.asarray(eq, dtype=bool)
        return d

    part_start = change_flags([v.name for v in part_vars])
    peer_start = part_start | change_flags([v.name for v, _ in orderings])
    bounds = np.append(np.flatnonzero(part_start), n)

    def peer_range(s, e, i):
        """[gs, ge) peer group of row i within partition [s, e)."""
        gs = i
        while gs > s and not peer_start[gs]:
            gs -= 1
        ge = i + 1
        while ge < e and not peer_start[ge]:
            ge += 1
        return gs, ge

    def frame_rows(frame, s, e, i):
        """Row index list of the frame of row i in partition [s, e)."""
        if frame is None:
            _gs, ge = peer_range(s, e, i)
            return range(s, ge)
        ftype = frame["type"]
        sk, so = frame["startKind"], frame["startOffset"]
        ek, eo = frame["endKind"], frame["endOffset"]
        if ftype == "RANGE":
            gs, ge = peer_range(s, e, i)
            lo = s if sk == "UNBOUNDED_PRECEDING" else gs
            hi = ge if ek == "CURRENT" else e
            return range(lo, hi)
        lo = {"UNBOUNDED_PRECEDING": s, "CURRENT": i,
              "PRECEDING": i - (so or 0), "FOLLOWING": i + (so or 0),
              "UNBOUNDED_FOLLOWING": e}[sk]
        hi = {"UNBOUNDED_FOLLOWING": e - 1, "CURRENT": i,
              "PRECEDING": i - (eo or 0), "FOLLOWING": i + (eo or 0),
              "UNBOUNDED_PRECEDING": s - 1}[ek]
        return range(max(lo, s), min(hi, e - 1) + 1)

    new_cols = dict(t.cols)
    for var, wf in node.window_functions.items():
        fname = canonical_name(wf.call.display_name)
        args = wf.call.arguments
        frame = wf.frame

        if fname in ("row_number", "rank", "dense_rank", "ntile",
                     "percent_rank", "cume_dist"):
            is_f = fname in ("percent_rank", "cume_dist")
            out = np.zeros(n, dtype=np.float64 if is_f else np.int64)
            for s, e in zip(bounds[:-1], bounds[1:]):
                size = e - s
                if fname == "ntile":
                    nt = int(args[0].value)
                    q, r = divmod(size, nt)
                    for i in range(s, e):
                        rn = i - s
                        big = r * (q + 1)
                        out[i] = (rn // (q + 1) if rn < big
                                  else r + (rn - big) // max(q, 1)) + 1
                    continue
                rk = dr = 0
                for i in range(s, e):
                    if peer_start[i] or i == s:
                        rk = i - s + 1
                        dr += 1
                    if fname == "row_number":
                        out[i] = i - s + 1
                    elif fname == "rank":
                        out[i] = rk
                    elif fname == "dense_rank":
                        out[i] = dr
                    elif fname == "percent_rank":
                        out[i] = 0.0 if size <= 1 else (rk - 1) / (size - 1)
                    else:   # cume_dist
                        _gs, ge = peer_range(s, e, i)
                        out[i] = (ge - s) / size
            new_cols[var.name] = (out, None)
            continue

        if fname in ("lag", "lead", "first_value", "last_value",
                     "nth_value"):
            vals, nulls = t.cols[args[0].name]
            from .lowering import constant_device_value
            outv = (np.zeros(n, dtype=vals.dtype) if vals.dtype != object
                    else np.empty(n, dtype=object))
            outn = np.zeros(n, dtype=bool)
            for s, e in zip(bounds[:-1], bounds[1:]):
                for i in range(s, e):
                    if fname in ("lag", "lead"):
                        off = int(args[1].value) if len(args) > 1 else 1
                        src_i = i - off if fname == "lag" else i + off
                        if s <= src_i < e:
                            outv[i] = vals[src_i]
                            outn[i] = bool(nulls[src_i]) if nulls is not None \
                                else False
                        elif len(args) > 2:
                            dv = constant_device_value(args[2].value,
                                                       args[2].type)
                            if dv is None:
                                outn[i] = True
                            else:
                                outv[i] = dv
                        else:
                            outn[i] = True
                        continue
                    rows = list(frame_rows(frame, s, e, i))
                    if fname == "first_value":
                        src_i = rows[0] if rows else None
                    elif fname == "last_value":
                        src_i = rows[-1] if rows else None
                    else:
                        k = int(args[1].value) if len(args) > 1 else 1
                        src_i = rows[k - 1] if len(rows) >= k else None
                    if src_i is None:
                        outn[i] = True
                    else:
                        outv[i] = vals[src_i]
                        outn[i] = bool(nulls[src_i]) if nulls is not None \
                            else False
            new_cols[var.name] = (outv, outn if outn.any() else None)
            continue

        star = fname == "count" and not args
        if star:
            vals, nulls = np.ones(n, dtype=np.int64), None
        else:
            vals, nulls = t.cols[args[0].name]
        notnull = np.ones(n, dtype=bool) if nulls is None else ~nulls
        out_is_float = isinstance(wf.call.type, (DoubleType, RealType))
        if fname == "count":
            outv = np.zeros(n, dtype=np.int64)
        elif fname in ("min", "max") or not out_is_float:
            outv = np.zeros(n, dtype=vals.dtype)
        else:
            outv = np.zeros(n, dtype=np.float64)
        outn = np.zeros(n, dtype=bool)
        for s, e in zip(bounds[:-1], bounds[1:]):
            for i in range(s, e):
                rows = [j for j in frame_rows(frame, s, e, i)
                        if star or notnull[j]]
                cnt = len(rows)
                if fname == "count":
                    outv[i] = cnt
                    continue
                if cnt == 0:
                    outn[i] = True      # aggregate of no rows is NULL
                    continue
                xs = [vals[j] for j in rows]
                if fname == "sum":
                    outv[i] = sum(xs)
                elif fname == "avg":
                    sm = sum(xs)
                    if out_is_float:
                        outv[i] = sm / cnt
                    else:
                        si = int(sm)    # decimal: round-half-up
                        sign = -1 if si < 0 else 1
                        outv[i] = sign * ((abs(si) + cnt // 2) // cnt)
                elif fname == "min":
                    outv[i] = min(xs)
                elif fname == "max":
                    outv[i] = max(xs)
                else:
                    raise NotImplementedError(fname)
        new_cols[var.name] = (outv, outn if outn.any() else None)
    return Table(new_cols, n)


def _exec_AggregationNode(node: P.AggregationNode) -> Table:
    t = _exec(node.source)
    key_names = [v.name for v in node.grouping_keys]
    if key_names:
        key_cols = [t.cols[k] for k in key_names]
        combo = np.empty(t.n, dtype=object)
        for i in range(t.n):
            # group identity is null-aware and sortable: a NULL key
            # (None value or set mask bit) is one group, distinct from
            # every real value — (is_null, value) keeps np.unique's sort
            # total even when a column mixes None with strings
            combo[i] = tuple(
                (True, "") if (a[i] is None
                               or (m is not None and bool(m[i])))
                else (False, a[i])
                for a, m in key_cols)
        uniq, inverse = np.unique(combo, return_inverse=True)
        n_groups = len(uniq)
    else:
        inverse = np.zeros(t.n, dtype=np.int64)
        n_groups = 1
    cols: Dict[str, Col] = {}
    for k in key_names:
        src, m = t.cols[k]
        first = np.zeros(n_groups, dtype=src.dtype) if src.dtype != object \
            else np.empty(n_groups, dtype=object)
        firstm = np.zeros(n_groups, dtype=bool)
        for i in range(t.n - 1, -1, -1):
            first[inverse[i]] = src[i]
            if m is not None:
                firstm[inverse[i]] = m[i]
        cols[k] = (first, firstm if m is not None and firstm.any() else None)

    # group slices once: rows sorted by group id, reduceat over boundaries
    order = np.argsort(inverse, kind="stable")
    sorted_inv = inverse[order]
    # boundary start index of each present group; absent groups impossible
    # (inverse comes from np.unique)
    starts = np.zeros(n_groups, dtype=np.int64)
    if t.n:
        boundaries = np.flatnonzero(np.diff(sorted_inv)) + 1
        starts[sorted_inv[0]] = 0
        starts = np.concatenate([[0], boundaries]) if n_groups > 1 else starts[:1]

    for var, agg in node.aggregations.items():
        fname = canonical_name(agg.call.display_name)
        if agg.call.arguments:
            av, am = _eval(agg.call.arguments[0], t)
        else:
            av, am = np.ones(t.n, dtype=np.int64), None
        valid = np.ones(t.n, dtype=bool) if am is None else ~am
        sv = av[order]
        svalid = valid[order]
        counts = np.add.reduceat(svalid.astype(np.int64), starts) \
            if t.n else np.zeros(n_groups, dtype=np.int64)
        outm = counts == 0
        if fname == "count":
            cols[var.name] = (counts.astype(object), None)
            continue
        # exact integer sums via object dtype; floats stay float64
        if sv.dtype != object and not np.issubdtype(sv.dtype, np.floating):
            sv = sv.astype(object)
        if fname in ("sum", "avg"):
            zero = 0.0 if np.issubdtype(np.asarray(sv[:1]).dtype, np.floating) \
                and sv.dtype != object else 0
            masked = np.where(svalid, sv, zero)
            sums = np.add.reduceat(masked, starts) if t.n else \
                np.zeros(n_groups, dtype=object)
            if fname == "sum":
                cols[var.name] = (np.asarray(sums, dtype=object),
                                  outm if outm.any() else None)
            else:
                safe = np.where(outm, 1, counts)
                if isinstance(var.type, DoubleType):
                    out = np.array([float(s) / int(c)
                                    for s, c in zip(sums, safe)])
                else:
                    out = np.empty(n_groups, dtype=object)
                    for g in range(n_groups):
                        s, c = int(sums[g]), int(safe[g])
                        q = (abs(s) + c // 2) // c
                        out[g] = q if s >= 0 else -q
                cols[var.name] = (out, outm if outm.any() else None)
        elif fname in ("min", "max"):
            big = float("inf") if fname == "min" else float("-inf")
            masked = np.where(svalid, sv, big)
            red = np.minimum.reduceat if fname == "min" else np.maximum.reduceat
            vals = red(masked, starts) if t.n else np.full(n_groups, big)
            cols[var.name] = (np.asarray(vals, dtype=object),
                              outm if outm.any() else None)
        elif fname in ("stddev", "stddev_pop", "stddev_samp", "variance",
                       "var_pop", "var_samp"):
            pop = fname in ("stddev_pop", "var_pop")
            sqrt = fname.startswith("stddev")
            out = np.zeros(n_groups, dtype=np.float64)
            outm = np.zeros(n_groups, dtype=bool)
            ends = np.append(starts[1:], t.n)
            for g in range(n_groups):
                xs = [float(sv[i]) for i in range(starts[g], ends[g])
                      if svalid[i]] if t.n else []
                k = len(xs)
                if k < (1 if pop else 2):
                    outm[g] = True
                    continue
                m = sum(xs) / k
                m2 = sum((x - m) ** 2 for x in xs)
                v = m2 / (k if pop else k - 1)
                out[g] = v ** 0.5 if sqrt else v
            cols[var.name] = (out, outm if outm.any() else None)
        elif fname in ("corr", "covar_pop", "covar_samp"):
            bv, bm = _eval(agg.call.arguments[1], t)
            bvalid = np.ones(t.n, dtype=bool) if bm is None else ~bm
            sb = bv[order]
            sbvalid = (svalid & bvalid[order])
            out = np.zeros(n_groups, dtype=np.float64)
            outm = np.zeros(n_groups, dtype=bool)
            ends = np.append(starts[1:], t.n)
            for g in range(n_groups):
                pairs = [(float(sv[i]), float(sb[i]))
                         for i in range(starts[g], ends[g])
                         if sbvalid[i]] if t.n else []
                k = len(pairs)
                if fname == "corr":
                    if k < 1:
                        outm[g] = True
                        continue
                    sx = sum(x for x, _ in pairs)
                    sy = sum(y for _, y in pairs)
                    sxy = sum(x * y for x, y in pairs)
                    sx2 = sum(x * x for x, _ in pairs)
                    sy2 = sum(y * y for _, y in pairs)
                    den = ((k * sx2 - sx * sx) * (k * sy2 - sy * sy)) ** 0.5
                    if den == 0:
                        outm[g] = True
                        continue
                    out[g] = (k * sxy - sx * sy) / den
                    continue
                need = 1 if fname == "covar_pop" else 2
                if k < need:
                    outm[g] = True
                    continue
                mx = sum(x for x, _ in pairs) / k
                my = sum(y for _, y in pairs) / k
                c = sum((x - mx) * (y - my) for x, y in pairs)
                out[g] = c / (k if fname == "covar_pop" else k - 1)
            cols[var.name] = (out, outm if outm.any() else None)
        elif fname == "approx_distinct":
            # oracle returns the EXACT distinct count; tests comparing the
            # engine's HLL estimate must tolerate the documented standard
            # error (1.04/sqrt(buckets)) rather than assert equality
            out = np.zeros(n_groups, dtype=np.int64)
            ends = np.append(starts[1:], t.n)
            for g in range(n_groups):
                out[g] = len({sv[i] for i in range(starts[g], ends[g])
                              if svalid[i]}) if t.n else 0
            cols[var.name] = (out, None)
        elif fname == "approx_percentile":
            p = float(agg.call.arguments[1].value) \
                if len(agg.call.arguments) > 1 else 0.5
            outv = np.empty(n_groups, dtype=object)
            outm = np.zeros(n_groups, dtype=bool)
            ends = np.append(starts[1:], t.n)
            for g in range(n_groups):
                xs = sorted(sv[i] for i in range(starts[g], ends[g])
                            if svalid[i]) if t.n else []
                if not xs:
                    outm[g] = True
                    outv[g] = 0
                    continue
                # nearest rank, matching ops.sort_group_aggregate:
                # round-half-up of p * (n-1)
                import math
                outv[g] = xs[int(math.floor(p * (len(xs) - 1) + 0.5))]
            cols[var.name] = (outv, outm if outm.any() else None)
        else:
            raise NotImplementedError(fname)
    return Table(cols, n_groups)


def _exec_JoinNode(node: P.JoinNode) -> Table:
    left = _exec(node.left)
    right = _exec(node.right)
    lkeys = [l.name for l, r in node.criteria]
    rkeys = [r.name for l, r in node.criteria]
    index: Dict[tuple, list] = {}
    for i in range(right.n):
        key = tuple(right.cols[k][0][i] for k in rkeys)
        if any(right.cols[k][1] is not None and right.cols[k][1][i]
               for k in rkeys):
            continue
        index.setdefault(key, []).append(i)
    # 1. matched pairs (INNER expansion)
    li, ri = [], []
    for i in range(left.n):
        key = tuple(left.cols[k][0][i] for k in lkeys)
        matches = index.get(key, [])
        if any(left.cols[k][1] is not None and left.cols[k][1][i]
               for k in lkeys):
            matches = []
        for j in matches:
            li.append(i)
            ri.append(j)
    li = np.array(li, dtype=np.int64)
    ri = np.array(ri, dtype=np.int64)
    cols = {}
    for name, (v, m) in left.cols.items():
        cols[name] = (v[li], None if m is None else m[li])
    for name, (v, m) in right.cols.items():
        cols[name] = (v[ri], None if m is None else m[ri])
    out_names = [v.name for v in node.outputs]
    # the ON filter may read columns pruned from the output list: evaluate
    # over the full pair table, project to out_names after
    keep_names = list(out_names)
    if node.filter is not None:
        from ..spi.expr import free_variables
        for fv in free_variables(node.filter):
            if fv.name in cols and fv.name not in keep_names:
                keep_names.append(fv.name)
    pairs = Table({n: cols[n] for n in keep_names}, len(li))

    # 2. ON filter applies to pairs BEFORE null-extension (SQL semantics)
    keep = np.ones(pairs.n, dtype=bool)
    if node.filter is not None and pairs.n:
        v, m = _eval(node.filter, pairs)
        keep = v.astype(bool)
        if m is not None:
            keep &= ~m
    pairs = pairs.mask(keep)
    pairs = Table({n: pairs.cols[n] for n in out_names}, pairs.n)

    if node.join_type not in (P.LEFT, P.FULL):
        return pairs

    # 3. LEFT/FULL: null-extend rows of the preserved side(s) with no
    # surviving match
    def extend(side: Table, other: Table, kept_idx: np.ndarray) -> Table:
        surviving = set(kept_idx.tolist())
        miss = np.array([i for i in range(side.n) if i not in surviving],
                        dtype=np.int64)
        cols = {}
        for n in out_names:
            if n in side.cols:
                v, m = side.cols[n]
                cols[n] = (v[miss], None if m is None else m[miss])
            else:
                v, _ = other.cols[n]
                ev = np.zeros(len(miss), dtype=v.dtype) \
                    if v.dtype != object \
                    else np.empty(len(miss), dtype=object)
                cols[n] = (ev, np.ones(len(miss), dtype=bool))
        return Table(cols, len(miss))

    parts = [pairs, extend(left, right, li[keep])]
    if node.join_type == P.FULL:
        parts.append(extend(right, left, ri[keep]))
    cols = {}
    for n in out_names:
        vals = np.concatenate([p.cols[n][0] for p in parts])
        if any(p.cols[n][1] is not None for p in parts):
            nm = np.concatenate([p.cols[n][1] if p.cols[n][1] is not None
                                 else np.zeros(p.n, dtype=bool)
                                 for p in parts])
        else:
            nm = None
        cols[n] = (vals, nm)
    return Table(cols, sum(p.n for p in parts))


def _exec_DistinctLimitNode(node: P.DistinctLimitNode) -> Table:
    """First `count` distinct rows in scan order (DistinctLimitOperator)."""
    src = _exec(node.source)
    names = [v.name for v in node.distinct_variables]
    seen = set()
    take: List[int] = []
    for i in range(src.n):
        key = tuple(
            None if (src.cols[n][1] is not None and src.cols[n][1][i])
            else src.cols[n][0][i]
            for n in names)
        if key not in seen:
            seen.add(key)
            take.append(i)
            if len(take) >= node.count:
                break
    return src.take(np.array(take, dtype=np.int64))


def _exec_AssignUniqueIdNode(node: P.AssignUniqueIdNode) -> Table:
    t = _exec(node.source)
    cols = dict(t.cols)
    cols[node.id_variable.name] = (np.arange(t.n, dtype=np.int64), None)
    return Table(cols, t.n)


def _exec_EnforceSingleRowNode(node: P.EnforceSingleRowNode) -> Table:
    t = _exec(node.source)
    if t.n > 1:
        raise RuntimeError("scalar subquery produced more than one row")
    return t


def _exec_SemiJoinNode(node: P.SemiJoinNode) -> Table:
    """Three-valued marker (reference HashSemiJoinOperator): TRUE on match,
    NULL when the probe key is NULL or the build side contains NULL and
    there is no match, FALSE only on a definite miss."""
    src = _exec(node.source)
    filt = _exec(node.filtering_source)
    fv, fm = filt.cols[node.filtering_source_join_variable.name]
    fvals = {x for i, x in enumerate(fv.tolist())
             if fm is None or not fm[i]}     # NULL keys never match
    build_has_null = fm is not None and bool(np.any(fm))
    sv, sm = src.cols[node.source_join_variable.name]
    marker = np.zeros(src.n, dtype=bool)
    nulls = np.zeros(src.n, dtype=bool)
    for i, x in enumerate(sv.tolist()):
        if sm is not None and sm[i]:
            nulls[i] = True
        elif x in fvals:
            marker[i] = True
        elif build_has_null:
            nulls[i] = True
    cols = dict(src.cols)
    cols[node.semi_join_output.name] = (marker, nulls if nulls.any() else None)
    return Table(cols, src.n)


# ---------------------------------------------------------------------------
# expression interpreter
# ---------------------------------------------------------------------------

def _eval(expr: RowExpression, t: Table) -> Col:
    if isinstance(expr, VariableReferenceExpression):
        return t.cols[expr.name]
    if isinstance(expr, ConstantExpression):
        val = constant_device_value(expr.value, expr.type)
        if val is None:
            return (np.zeros(t.n, dtype=object), np.ones(t.n, dtype=bool))
        if isinstance(expr.type, (VarcharType, CharType)):
            return (np.array([str(val)] * t.n, dtype=object), None)
        return (np.full(t.n, val, dtype=object
                        if isinstance(val, int) and abs(val) > 2**62
                        else np.int64
                        if isinstance(val, (int, np.integer)) else np.float64),
                None)
    if isinstance(expr, CallExpression):
        return _eval_call(expr, t)
    if isinstance(expr, SpecialFormExpression):
        return _eval_special(expr, t)
    raise NotImplementedError(type(expr).__name__)


def _both(a: Col, b: Col):
    m = None
    if a[1] is not None or b[1] is not None:
        m = (a[1] if a[1] is not None else np.zeros(len(a[0]), bool)) | \
            (b[1] if b[1] is not None else np.zeros(len(b[0]), bool))
    return a[0], b[0], m


def _scale_factor(expr: RowExpression) -> int:
    return expr.type.scale if isinstance(expr.type, DecimalType) else 0


def _to_scale(values: np.ndarray, frm: int, to: int):
    if to == frm:
        return values
    if to > frm:
        return values * (10 ** (to - frm))
    den = 10 ** (frm - to)
    out = np.empty(len(values), dtype=object)
    for i, x in enumerate(values.tolist()):
        q = (abs(int(x)) + den // 2) // den
        out[i] = q if x >= 0 else -q
    return out


def _numeric_domain(expr: RowExpression, col: Col, target_float: bool,
                    target_scale: int) -> np.ndarray:
    v = col[0]
    if target_float:
        s = _scale_factor(expr)
        return np.array([float(x) / 10**s for x in v.tolist()], dtype=np.float64) \
            if s else v.astype(np.float64)
    return _to_scale(v, _scale_factor(expr), target_scale)


def _eval_call(expr: CallExpression, t: Table) -> Col:
    name = canonical_name(expr.display_name)
    args = expr.arguments
    if name in ("array_constructor", "subscript", "element_at",
                "cardinality", "contains", "array_max", "array_min",
                "array_position", "repeat", "sequence"):
        return _eval_array_fn(name, expr, t)
    if name in ("add", "subtract", "multiply", "divide", "modulus"):
        a = _eval(args[0], t)
        b = _eval(args[1], t)
        av, bv, m = _both(a, b)
        is_float = isinstance(expr.type, (DoubleType, RealType))
        if is_float:
            af = _numeric_domain(args[0], a, True, 0)
            bf = _numeric_domain(args[1], b, True, 0)
            op = {"add": np.add, "subtract": np.subtract,
                  "multiply": np.multiply, "divide": np.divide,
                  "modulus": np.mod}[name]
            return (op(af, bf), m)
        rs = _scale_factor(expr)
        sa, sb = _scale_factor(args[0]), _scale_factor(args[1])
        ai = [int(x) for x in av.tolist()]
        bi = [int(x) for x in bv.tolist()]
        out = np.empty(len(ai), dtype=object)
        div0 = None
        for i in range(len(ai)):
            x, y = ai[i], bi[i]
            if name == "add":
                out[i] = x * 10**(rs - sa) + y * 10**(rs - sb)
            elif name == "subtract":
                out[i] = x * 10**(rs - sa) - y * 10**(rs - sb)
            elif name == "multiply":
                p = x * y  # scale sa+sb
                out[i] = _round_to(p, sa + sb, rs)
            elif name == "divide":
                if y == 0:
                    # engine semantics: integer/decimal division by zero
                    # yields NULL (a data-dependent raise cannot live
                    # inside jit; the engine documents NULL instead)
                    out[i] = 0
                    div0 = np.zeros(len(ai), bool) if div0 is None else div0
                    div0[i] = True
                    continue
                num = x * 10**(rs + sb - sa)
                if isinstance(expr.type, DecimalType):
                    # decimal divide rounds half-up at the result scale
                    q = (abs(num) + abs(y) // 2) // abs(y)
                else:
                    # SQL integer division truncates toward zero
                    q = abs(num) // abs(y)
                out[i] = q * (1 if (num >= 0) == (y >= 0) else -1)
            elif name == "modulus":
                if y == 0:
                    out[i] = 0
                    div0 = np.zeros(len(ai), bool) if div0 is None else div0
                    div0[i] = True
                    continue
                xs, ys = x * 10**(rs - sa), y * 10**(rs - sb)
                out[i] = int(np.sign(xs)) * (abs(xs) % abs(ys))
        if div0 is not None:
            m = div0 if m is None else (m | div0)
        return (out, m)
    if name in ("eq", "neq", "lt", "lte", "gt", "gte"):
        a, b = _eval(args[0], t), _eval(args[1], t)
        av, bv, m = _both(a, b)
        if av.dtype == object and isinstance(av[0] if len(av) else "", str):
            import operator as op_
            ops = {"eq": op_.eq, "neq": op_.ne, "lt": op_.lt,
                   "lte": op_.le, "gt": op_.gt, "gte": op_.ge}
            return (np.array([ops[name](str(x), str(y))
                              for x, y in zip(av, bv)]), m)
        sa, sb = _scale_factor(args[0]), _scale_factor(args[1])
        s = max(sa, sb)
        fa = isinstance(args[0].type, (DoubleType, RealType))
        fb = isinstance(args[1].type, (DoubleType, RealType))
        if fa or fb:
            an = _numeric_domain(args[0], a, True, 0)
            bn = _numeric_domain(args[1], b, True, 0)
        else:
            an = _to_scale(av, sa, s)
            bn = _to_scale(bv, sb, s)
        ops = {"eq": np.equal, "neq": np.not_equal, "lt": np.less,
               "lte": np.less_equal, "gt": np.greater,
               "gte": np.greater_equal}
        an = np.array([int(x) for x in an.tolist()], dtype=object) \
            if an.dtype == object else an
        return (ops[name](an, bn), m)
    if name == "between":
        # Kleene: x BETWEEN lo AND hi == (x >= lo) AND (x <= hi); a NULL
        # bound still yields FALSE when the other comparison is FALSE
        # (fuzzer-found: the old null-if-any-null shortcut was wrong)
        return _eval_special(SpecialFormExpression(
            "AND", expr.type,
            [CallExpression("gte", expr.type, [args[0], args[1]]),
             CallExpression("lte", expr.type, [args[0], args[2]])]), t)
    if name == "not":
        v, m = _eval(args[0], t)
        return (~v.astype(bool), m)
    if name == "negate":
        v, m = _eval(args[0], t)
        return (np.array([-x for x in v.tolist()], dtype=v.dtype), m)
    if name == "abs":
        v, m = _eval(args[0], t)
        return (np.array([abs(x) for x in v.tolist()], dtype=v.dtype), m)
    if name in ("year", "month", "day", "quarter"):
        v, m = _eval(args[0], t)
        dates = v.astype("datetime64[D]")
        y = dates.astype("datetime64[Y]").astype(np.int64) + 1970
        mo = dates.astype("datetime64[M]").astype(np.int64) % 12 + 1
        d = (dates - dates.astype("datetime64[M]").astype("datetime64[D]")
             ).astype(np.int64) + 1
        part = {"year": y, "month": mo, "day": d, "quarter": (mo + 2) // 3}[name]
        return (part, m)
    if name == "cast":
        return _eval_cast(args[0], expr.type, t)
    if name == "like":
        from .lowering import like_matcher
        v, m = _eval(args[0], t)
        match = like_matcher(str(args[1].value))
        return (np.array([match(str(x)) for x in v]), m)
    if name == "substr":
        v, m = _eval(args[0], t)
        start = int(args[1].value)
        length = int(args[2].value) if len(args) > 2 else None

        def sub(s):
            i = start - 1 if start > 0 else len(s) + start
            return s[i:i + length] if length is not None else s[i:]
        return (np.array([sub(str(x)) for x in v], dtype=object), m)
    if name == "length":
        v, m = _eval(args[0], t)
        return (np.array([len(str(x)) for x in v], dtype=np.int64), m)
    if name in _REF_DOUBLE_FNS:
        fn = _REF_DOUBLE_FNS[name]
        acol = _eval(args[0], t)
        a = _numeric_domain(args[0], acol, True, 0)
        if name == "power":
            bcol = _eval(args[1], t)
            b = _numeric_domain(args[1], bcol, True, 0)
            m = acol[1]
            if bcol[1] is not None:
                m = bcol[1] if m is None else (m | bcol[1])
            return (np.array([fn(x, y) for x, y in zip(a, b)],
                             dtype=np.float64), m)
        return (np.array([fn(x) for x in a], dtype=np.float64), acol[1])
    if name in ("ceiling", "floor", "sign", "truncate"):
        import math as _math
        col = _eval(args[0], t)
        a = _numeric_domain(args[0], col, True, 0)
        fn = {"ceiling": _math.ceil, "floor": _math.floor,
              "truncate": _math.trunc,
              "sign": lambda x: (x > 0) - (x < 0)}[name]
        out = [fn(x) for x in a]
        if isinstance(expr.type, (DoubleType, RealType)):
            return (np.array(out, dtype=np.float64), col[1])
        return (np.array(out, dtype=np.int64), col[1])
    if name == "round":
        col = _eval(args[0], t)
        digits = int(args[1].value) if len(args) > 1 else 0
        if isinstance(expr.type, DecimalType):
            s = _scale_factor(args[0])
            rs = expr.type.scale
            out = np.empty(t.n, dtype=object)
            for i, x in enumerate(col[0].tolist()):
                x = int(x)
                if digits < s:
                    den = 10 ** (s - digits)
                    q = (abs(x) + den // 2) // den * den
                    x = q if x >= 0 else -q
                out[i] = _round_to(x, s, rs)
            return (out, col[1])
        a = _numeric_domain(args[0], col, True, 0)
        scale = 10.0 ** digits

        def r(x):
            import math as _math
            return _math.copysign(_math.floor(abs(x) * scale + 0.5),
                                  x) / scale
        out = np.array([r(x) for x in a], dtype=np.float64)
        if isinstance(expr.type, (DoubleType, RealType)):
            return (out, col[1])
        return (out.astype(np.int64), col[1])
    if name in ("greatest", "least"):
        cols = [_eval(a, t) for a in args]
        vals = [_numeric_domain(a, c, True, 0)
                for a, c in zip(args, cols)]
        out = vals[0]
        for v in vals[1:]:
            out = np.maximum(out, v) if name == "greatest" \
                else np.minimum(out, v)
        m = None
        for c in cols:
            if c[1] is not None:
                m = c[1] if m is None else (m | c[1])
        if isinstance(expr.type, (DoubleType, RealType)):
            return (out, m)
        sc = _scale_factor(expr)
        return (np.array([int(round(x * 10**sc)) for x in out],
                         dtype=object), m)
    if name in ("upper", "lower", "trim", "ltrim", "rtrim", "reverse",
                "replace", "lpad", "rpad"):
        v, m = _eval(args[0], t)
        extra = [a.value for a in args[1:]]
        fn = {
            "upper": lambda s: s.upper(),
            "lower": lambda s: s.lower(),
            "trim": lambda s: s.strip(),
            "ltrim": lambda s: s.lstrip(),
            "rtrim": lambda s: s.rstrip(),
            "reverse": lambda s: s[::-1],
            "replace": lambda s: s.replace(
                str(extra[0]), str(extra[1]) if len(extra) > 1 else ""),
            "lpad": lambda s: _ref_pad(s, extra, left=True),
            "rpad": lambda s: _ref_pad(s, extra, left=False),
        }[name]
        return (np.array([fn(str(x)) for x in v], dtype=object), m)
    if name == "concat":
        cols = [_eval(a, t) for a in args]
        m = None
        for c in cols:
            if c[1] is not None:
                m = c[1] if m is None else (m | c[1])
        out = np.array(["".join(str(c[0][i]) for c in cols)
                        for i in range(t.n)], dtype=object)
        return (out, m)
    if name == "strpos":
        v, m = _eval(args[0], t)
        sub = str(args[1].value)
        return (np.array([str(x).find(sub) + 1 for x in v],
                         dtype=np.int64), m)
    if name == "starts_with":
        v, m = _eval(args[0], t)
        p = str(args[1].value)
        return (np.array([str(x).startswith(p) for x in v]), m)
    if name in ("day_of_week", "day_of_year", "week", "date_trunc",
                "date_add", "date_diff"):
        return _eval_date_fn(name, expr, t)
    if name in ("regexp_like", "regexp_extract", "regexp_replace",
                "split_part", "ends_with", "codepoint",
                "url_extract_protocol", "url_extract_host",
                "url_extract_path", "url_extract_query",
                "url_extract_fragment", "url_extract_port",
                "json_extract_scalar"):
        return _eval_string_breadth(name, expr, t)
    if name in ("log", "atan2"):
        acol = _eval(args[0], t)
        a = _numeric_domain(args[0], acol, True, 0)
        bcol = _eval(args[1], t)
        b = _numeric_domain(args[1], bcol, True, 0)
        m = acol[1]
        if bcol[1] is not None:
            m = bcol[1] if m is None else (m | bcol[1])
        if name == "log":
            out = [_m.log(y) / _m.log(x) for x, y in zip(a, b)]
        else:
            out = [_m.atan2(x, y) for x, y in zip(a, b)]
        return (np.array(out, dtype=np.float64), m)
    if name in ("sinh", "cosh", "tanh"):
        col = _eval(args[0], t)
        a = _numeric_domain(args[0], col, True, 0)
        fn = {"sinh": _m.sinh, "cosh": _m.cosh, "tanh": _m.tanh}[name]
        return (np.array([fn(x) for x in a], dtype=np.float64), col[1])
    if name in ("is_nan", "is_finite", "is_infinite"):
        col = _eval(args[0], t)
        a = _numeric_domain(args[0], col, True, 0)
        fn = {"is_nan": _m.isnan, "is_finite": _m.isfinite,
              "is_infinite": _m.isinf}[name]
        return (np.array([fn(x) for x in a]), col[1])
    if name.startswith("bitwise_") or name == "width_bucket":
        cols = [_eval(a, t) for a in args]
        m = None
        for c in cols:
            if c[1] is not None:
                m = c[1] if m is None else (m | c[1])
        av = [int(x) for x in cols[0][0]]
        if name == "bitwise_not":
            return (np.array([~x for x in av], dtype=np.int64), m)
        if name == "width_bucket":
            xs = _numeric_domain(args[0], cols[0], True, 0)
            los = _numeric_domain(args[1], cols[1], True, 0)
            his = _numeric_domain(args[2], cols[2], True, 0)
            ns = [int(x) for x in cols[3][0]]
            out = []
            bad = np.zeros(t.n, dtype=bool)
            for i, (x, lo, hi, n) in enumerate(zip(xs, los, his, ns)):
                if n <= 0:         # error->NULL relaxation (engine mirror)
                    bad[i] = True
                    out.append(0)
                    continue
                span = (hi - lo) or 1.0
                # 1-ulp edge tolerance shared with the engine (see
                # lowering.py width_bucket)
                v = (x - lo) * n / span
                b = int(_m.floor(v * (1 + 2.0 ** -40))) + 1
                out.append(max(0, min(b, n + 1)))
            if bad.any():
                m = bad if m is None else (m | bad)
            return (np.array(out, dtype=np.int64), m)
        bv = [int(x) for x in cols[1][0]]
        if name in ("bitwise_left_shift", "bitwise_right_shift",
                    "bitwise_arithmetic_shift_right"):
            # int64 shift semantics shared with the engine (lowering.py):
            # counts >= 64 shift everything out (arithmetic-right
            # saturates to the sign fill); negative counts -> NULL
            out = []
            bad = np.zeros(t.n, dtype=bool)
            for i, (x, y) in enumerate(zip(av, bv)):
                if y < 0:
                    bad[i] = True
                    out.append(0)
                elif name == "bitwise_left_shift":
                    out.append(_i64(x << y) if y < 64 else 0)
                elif name == "bitwise_arithmetic_shift_right":
                    out.append(x >> min(y, 63))
                else:
                    out.append((x & 0xFFFFFFFFFFFFFFFF) >> y
                               if y < 64 else 0)
            if bad.any():
                m = bad if m is None else (m | bad)
            return (np.array([_i64(x) for x in out], dtype=np.int64), m)
        ops_map = {
            "bitwise_and": lambda x, y: x & y,
            "bitwise_or": lambda x, y: x | y,
            "bitwise_xor": lambda x, y: x ^ y,
        }
        fn = ops_map[name]
        return (np.array([_i64(fn(x, y)) for x, y in zip(av, bv)],
                         dtype=np.int64), m)
    raise NotImplementedError(f"reference fn {name}")


def _i64(x: int) -> int:
    """Wrap to signed 64-bit (python ints are unbounded)."""
    x &= 0xFFFFFFFFFFFFFFFF
    return x - (1 << 64) if x >= (1 << 63) else x


def _eval_string_breadth(name: str, expr: CallExpression, t: Table) -> Col:
    """regexp / URL / JSON / split scalar functions: row-at-a-time over
    python strings, sharing the per-entry kernels with the engine's
    dictionary path (exec/lowering.py — both sides wrap the same stdlib
    primitives, like both reference engines wrap the same libc)."""
    from .lowering import _STRING_TO_STRING, _STRING_TO_VALUE
    args = expr.arguments
    v, m = _eval(args[0], t)
    extra = [a.value for a in args[1:]]
    if name in _STRING_TO_VALUE:
        fn, dtype = _STRING_TO_VALUE[name]
        raw = [fn(str(x), *extra) for x in v]
        nulls = np.array([r is None for r in raw])
        out = np.array([0 if r is None else r for r in raw], dtype=dtype)
        if nulls.any():
            m = nulls if m is None else (m | nulls)
        return (out, m)
    fn = _STRING_TO_STRING[name]
    raw = [fn(str(x), *extra) for x in v]
    nulls = np.array([r is None for r in raw])
    out = np.array(["" if r is None else r for r in raw], dtype=object)
    if nulls.any():
        m = nulls if m is None else (m | nulls)
    return (out, m)


def _ref_pad(s: str, extra, left: bool) -> str:
    """Presto lpad/rpad: truncate to n when already longer, else pad with
    the fill string repeated from its start."""
    n = int(extra[0])
    fill = str(extra[1]) if len(extra) > 1 else " "
    if len(s) >= n:
        return s[:n]
    pad = (fill * (n - len(s)))[:n - len(s)]
    return pad + s if left else s + pad


import math as _m  # noqa: E402

_REF_DOUBLE_FNS = {
    "sqrt": _m.sqrt, "exp": _m.exp, "ln": _m.log, "log2": _m.log2,
    "log10": _m.log10, "sin": _m.sin, "cos": _m.cos, "tan": _m.tan,
    "asin": _m.asin, "acos": _m.acos, "atan": _m.atan,
    "cbrt": lambda x: _m.copysign(abs(x) ** (1 / 3), x),
    "degrees": _m.degrees, "radians": _m.radians, "power": _m.pow,
}


def _eval_array_fn(name: str, expr: CallExpression, t: Table) -> Col:
    """Array functions over object arrays of python tuples (independent of
    the engine's fixed-width device layout).  Subscript relaxes Presto's
    out-of-bounds ERROR to NULL, matching the engine (element_at
    semantics)."""
    args = expr.arguments
    if name == "array_constructor":
        items = [_eval(a, t) for a in args]
        out = np.empty(t.n, dtype=object)
        for i in range(t.n):
            out[i] = tuple(
                None if (m is not None and m[i]) else v[i]
                for v, m in items)
        return (out, None)
    if name == "repeat":
        x = _eval(args[0], t)
        counts = _eval(args[1], t)[0]
        out = np.empty(t.n, dtype=object)
        for i in range(t.n):
            # negative counts clamp to empty (engine mirror, lowering.py)
            out[i] = (x[0][i],) * max(int(counts[i]), 0)
        return (out, x[1])
    if name == "sequence":
        lo = _eval(args[0], t)[0]
        hi = _eval(args[1], t)[0]
        step = _eval(args[2], t)[0] if len(args) > 2 else np.ones(t.n)
        out = np.empty(t.n, dtype=object)
        for i in range(t.n):
            s = int(step[i])
            out[i] = tuple(range(int(lo[i]),
                                 int(hi[i]) + (1 if s > 0 else -1), s))
        return (out, None)
    arr, am = _eval(args[0], t)
    if name == "cardinality":
        return (np.array([0 if v is None else len(v) for v in arr],
                         dtype=np.int64), am)
    if name in ("subscript", "element_at"):
        idx, im = _eval(args[1], t)
        out = np.zeros(t.n, dtype=object)
        nulls = np.zeros(t.n, dtype=bool)
        for i in range(t.n):
            if (am is not None and am[i]) or (im is not None and im[i]):
                nulls[i] = True
                continue
            k = int(idx[i])
            a = arr[i]
            if a is not None and name == "element_at" and k < 0:
                k = len(a) + k + 1      # element_at(-n): from the end
            if a is None or k < 1 or k > len(a):
                nulls[i] = True
            else:
                out[i] = a[k - 1]
        return (out, nulls)
    if name == "contains":
        x, xm = _eval(args[1], t)
        hit = np.array([False if a is None else (x[i] in a)
                        for i, a in enumerate(arr)])
        m = am
        if xm is not None:
            m = xm if m is None else (m | xm)
        return (hit, m)
    if name in ("array_max", "array_min"):
        f = max if name == "array_max" else min
        out = np.zeros(t.n, dtype=object)
        nulls = np.zeros(t.n, dtype=bool)
        for i, a in enumerate(arr):
            if a is None or (am is not None and am[i]) or not len(a):
                nulls[i] = True
            else:
                out[i] = f(a)
        return (out, nulls)
    if name == "array_position":
        x, xm = _eval(args[1], t)
        out = np.zeros(t.n, dtype=np.int64)
        for i, a in enumerate(arr):
            if a is not None:
                for j, v in enumerate(a):
                    if v == x[i]:
                        out[i] = j + 1
                        break
        m = am
        if xm is not None:
            m = xm if m is None else (m | xm)
        return (out, m)
    raise NotImplementedError(name)


def _exec_UnnestNode(node: P.UnnestNode) -> Table:
    """One row per zipped element position, source columns replicated
    (UnnestOperator.java semantics: multiple arrays align by position,
    shorter ones null-extended)."""
    src = _exec(node.source)
    rep = [v.name for v in node.replicate_variables]
    arrays = [(av.name, elems[0].name)
              for av, elems in node.unnest_variables]
    take: List[int] = []
    elem_cols = {en: [] for _an, en in arrays}
    elem_nulls = {en: [] for _an, en in arrays}
    ords: List[int] = []
    for i in range(src.n):
        rowlen = 0
        vals = {}
        for an, en in arrays:
            v, m = src.cols[an]
            a = None if (m is not None and m[i]) else v[i]
            vals[en] = a
            rowlen = max(rowlen, 0 if a is None else len(a))
        for j in range(rowlen):
            take.append(i)
            ords.append(j + 1)
            for _an, en in arrays:
                a = vals[en]
                if a is None or j >= len(a):
                    elem_cols[en].append(0)
                    elem_nulls[en].append(True)
                else:
                    elem_cols[en].append(a[j])
                    elem_nulls[en].append(False)
    idx = np.array(take, dtype=np.int64)
    cols = {}
    for name in rep:
        v, m = src.cols[name]
        cols[name] = (v[idx], None if m is None else m[idx])
    for _an, en in arrays:
        vals = np.array(elem_cols[en], dtype=object)
        nulls = np.array(elem_nulls[en], dtype=bool)
        cols[en] = (vals, nulls if nulls.any() else None)
    if node.ordinality_variable is not None:
        cols[node.ordinality_variable.name] = (
            np.array(ords, dtype=np.int64), None)
    return Table(cols, len(idx))


def _eval_date_fn(name: str, expr: CallExpression, t: Table) -> Col:
    """Date functions via python's datetime — an implementation independent
    of the engine's integer civil-calendar kernels, so differential tests
    catch either side's mistakes."""
    import datetime as _dt
    args = expr.arguments
    epoch = _dt.date(1970, 1, 1).toordinal()

    def to_date(days):
        return _dt.date.fromordinal(int(days) + epoch)

    if name in ("day_of_week", "day_of_year", "week"):
        v, m = _eval(args[0], t)
        if name == "day_of_week":
            out = [to_date(x).isoweekday() for x in v]
        elif name == "day_of_year":
            out = [to_date(x).timetuple().tm_yday for x in v]
        else:
            out = [to_date(x).isocalendar()[1] for x in v]
        return (np.array(out, dtype=np.int64), m)
    unit = str(args[0].value).lower()
    if name == "date_trunc":
        v, m = _eval(args[1], t)

        def trunc(days):
            d = to_date(days)
            if unit == "day":
                pass
            elif unit == "week":
                d = d - _dt.timedelta(days=d.weekday())
            elif unit == "month":
                d = d.replace(day=1)
            elif unit == "quarter":
                d = d.replace(month=((d.month - 1) // 3) * 3 + 1, day=1)
            elif unit == "year":
                d = d.replace(month=1, day=1)
            return d.toordinal() - epoch
        return (np.array([trunc(x) for x in v], dtype=np.int64), m)
    if name == "date_add":
        nv, nm = _eval(args[1], t)
        v, m = _eval(args[2], t)
        mm = m if nm is None else (nm if m is None else (m | nm))

        def add(days, n):
            n = int(n)
            if unit == "day":
                return int(days) + n
            if unit == "week":
                return int(days) + 7 * n
            d = to_date(days)
            months = n * {"month": 1, "quarter": 3, "year": 12}[unit]
            total = d.month - 1 + months
            y, mo = d.year + total // 12, total % 12 + 1
            import calendar
            day = min(d.day, calendar.monthrange(y, mo)[1])
            return _dt.date(y, mo, day).toordinal() - epoch
        return (np.array([add(x, n) for x, n in zip(v, nv)],
                         dtype=np.int64), mm)
    # date_diff
    av, am = _eval(args[1], t)
    bv, bm = _eval(args[2], t)
    mm = am if bm is None else (bm if am is None else (am | bm))

    def diff(a, b):
        if unit == "day":
            return int(b) - int(a)
        if unit == "week":
            d = int(b) - int(a)
            return d // 7 if d >= 0 else -((-d) // 7)
        da, db = to_date(a), to_date(b)
        months = (db.year * 12 + db.month) - (da.year * 12 + da.month)
        if months > 0 and db.day < da.day:
            months -= 1
        elif months < 0 and db.day > da.day:
            months += 1
        den = {"month": 1, "quarter": 3, "year": 12}[unit]
        return months // den if months >= 0 else -((-months) // den)
    return (np.array([diff(a, b) for a, b in zip(av, bv)],
                     dtype=np.int64), mm)


def _round_to(value: int, frm: int, to: int) -> int:
    if to == frm:
        return value
    if to > frm:
        return value * 10**(to - frm)
    den = 10**(frm - to)
    q = (abs(value) + den // 2) // den
    return q if value >= 0 else -q


def _eval_cast(arg: RowExpression, to: Type, t: Table) -> Col:
    v, m = _eval(arg, t)
    frm = arg.type
    if isinstance(to, DoubleType):
        s = _scale_factor(arg)
        return (np.array([float(x) / 10**s for x in v.tolist()],
                         dtype=np.float64), m)
    if isinstance(to, DecimalType):
        if isinstance(frm, DecimalType):
            return (_to_scale(v, frm.scale, to.scale), m)
        if isinstance(frm, (DoubleType, RealType)):
            return (np.array([_round_to(int(round(float(x) * 10**to.scale)), to.scale, to.scale)
                              for x in v.tolist()], dtype=object), m)
        return (np.array([int(x) * 10**to.scale for x in v.tolist()],
                         dtype=object), m)
    if to.signature in ("bigint", "integer"):
        if isinstance(frm, DecimalType):
            return (_to_scale(v, frm.scale, 0), m)
        return (v.astype(np.int64), m)
    if isinstance(to, (VarcharType, CharType)):
        return (np.array([str(x) for x in v], dtype=object), m)
    raise NotImplementedError(f"reference cast {frm} -> {to}")


def _eval_special(expr: SpecialFormExpression, t: Table) -> Col:
    form = expr.form
    args = expr.arguments
    if form == "AND":
        va, ma = _eval(args[0], t)
        vb, mb = _eval(args[1], t)
        a = va.astype(bool)
        b = vb.astype(bool)
        an = ma if ma is not None else np.zeros(t.n, bool)
        bn = mb if mb is not None else np.zeros(t.n, bool)
        value = (a | an) & (b | bn)
        nulls = value & (an | bn)
        has = ma is not None or mb is not None
        return ((value & ~nulls) if has else (a & b), nulls if has else None)
    if form == "OR":
        va, ma = _eval(args[0], t)
        vb, mb = _eval(args[1], t)
        a, b = va.astype(bool), vb.astype(bool)
        an = ma if ma is not None else np.zeros(t.n, bool)
        bn = mb if mb is not None else np.zeros(t.n, bool)
        definite = (a & ~an) | (b & ~bn)
        nulls = ~definite & (an | bn)
        has = ma is not None or mb is not None
        return (definite if has else (a | b), nulls if has else None)
    if form == "IS_NULL":
        v, m = _eval(args[0], t)
        return ((m if m is not None else np.zeros(t.n, bool)).copy(), None)
    if form == "IN":
        v, m = _eval(args[0], t)
        vals = {constant_device_value(a.value, args[0].type) for a in args[1:]}
        if v.dtype == object and len(v) and isinstance(v[0], str):
            vals = {str(x) for x in vals}
            return (np.array([x in vals for x in v]), m)
        sa = _scale_factor(args[0])
        return (np.array([x in vals for x in v.tolist()]), m)
    if form == "IF":
        c, cm = _eval(args[0], t)
        tv, tm = _eval(args[1], t)
        fv, fm = _eval(args[2], t)
        pred = c.astype(bool)
        if cm is not None:
            pred = pred & ~cm
        out = np.where(pred, tv, fv)
        m = None
        if tm is not None or fm is not None:
            m = np.where(pred,
                         tm if tm is not None else False,
                         fm if fm is not None else False)
        return (out, m)
    if form == "COALESCE":
        v, m = _eval(args[0], t)
        out_v, out_m = v.copy(), (m.copy() if m is not None
                                  else np.zeros(t.n, bool))
        for a in args[1:]:
            av, am = _eval(a, t)
            take = out_m
            out_v = np.where(take, av, out_v)
            out_m = take & (am if am is not None else np.zeros(t.n, bool))
        return (out_v, out_m if out_m.any() else None)
    if form == "NULL_IF":
        av, am = _eval(args[0], t)
        bv, bm = _eval(args[1], t)
        eq = av == bv
        if bm is not None:
            eq = eq & ~bm
        if am is not None:
            eq = eq & ~am
        m = eq if am is None else (am | eq)
        return (av, m)
    raise NotImplementedError(f"reference special {form}")
