"""Device-side columnar batch model.

The TPU analog of the reference's Page-in-the-Driver-loop (Driver.java:421-451):
a Batch is a fixed-capacity set of device arrays plus a row-validity mask.
Everything is static-shaped so XLA compiles each pipeline once per capacity
class (SURVEY.md §7 hard part 3: padded fixed-size batches + validity masks).

Columns:
  values      jnp array, logical dtype (int64 / int32 / float64 / bool)
  nulls       optional bool array (True == SQL NULL)
  dictionary  optional tuple of python strings: `values` are int32 codes into
              it.  Static metadata (pytree aux), so string predicates are
              precomputed host-side into code sets and stay out of the traced
              computation.

The row mask subsumes both selection (filters clear bits) and padding (the
tail of a partially-filled batch).  Operators never compact; aggregations and
outputs read the mask.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common.block import (DictionaryBlock, FixedWidthBlock, RunLengthBlock,
                            VariableWidthBlock, decode_to_flat)
from ..common.page import Page
from ..common.types import (BooleanType, DateType, DecimalType, DoubleType,
                            IntegerType, RealType, Type, VarcharType, CharType)


class Column:
    def __init__(self, values, nulls=None,
                 dictionary: Optional[Tuple[str, ...]] = None,
                 lazy: Optional[Tuple] = None, lengths=None):
        self.values = values
        self.nulls = nulls
        self.dictionary = dictionary
        # late materialization: ("tpch", table, column, sf) — `values` are
        # global row indices; strings realized at output boundaries
        self.lazy = lazy
        # ARRAY columns: values has shape (capacity, W) — W the static
        # per-column element capacity — and `lengths` (capacity,) holds
        # each row's live element count.  Fixed-width padding instead of
        # offsets keeps shapes static for XLA (the ragged ArrayBlock form
        # exists only at host/page boundaries; reference Block model:
        # presto-common/.../block/ArrayBlock)
        self.lengths = lengths

    def tree_flatten(self):
        tag = ("nulls" if self.nulls is not None else "no_nulls",
               "len" if self.lengths is not None else "no_len")
        children = (self.values,)
        if self.nulls is not None:
            children += (self.nulls,)
        if self.lengths is not None:
            children += (self.lengths,)
        return children, (tag, self.dictionary, self.lazy)

    @classmethod
    def tree_unflatten(cls, aux, children):
        (ntag, ltag), dictionary, lazy = aux
        i = 1
        nulls = None
        if ntag == "nulls":
            nulls = children[i]
            i += 1
        lengths = children[i] if ltag == "len" else None
        return cls(children[0], nulls, dictionary, lazy, lengths)

    def null_mask(self):
        if self.nulls is None:
            return jnp.zeros(self.values.shape[:1], dtype=bool)
        return self.nulls

    def gather(self, idx) -> "Column":
        """Row gather preserving dictionary/lazy metadata."""
        return Column(self.values[idx],
                      None if self.nulls is None else self.nulls[idx],
                      self.dictionary, self.lazy,
                      None if self.lengths is None else self.lengths[idx])

    def slice_rows(self, lo, hi) -> "Column":
        return Column(self.values[lo:hi],
                      None if self.nulls is None else self.nulls[lo:hi],
                      self.dictionary, self.lazy,
                      None if self.lengths is None else self.lengths[lo:hi])

    def __repr__(self):
        d = f", dict[{len(self.dictionary)}]" if self.dictionary else ""
        return f"Column({self.values.dtype}{self.values.shape}{d})"


jax.tree_util.register_pytree_node_class(Column)


class Batch:
    def __init__(self, columns: Dict[str, Column], mask):
        self.columns = columns
        self.mask = mask
        # Bound-parameter vector (serving tier): a tuple of device scalars
        # read by Lowering for BoundParameterExpression.  NOT part of the
        # pytree: parameterized steps take the vector as an explicit jit
        # argument and attach it inside the trace (Batch.with_params), so a
        # flatten/unflatten round trip intentionally drops it — params never
        # bake into a cached executable.
        self.params = None

    def tree_flatten(self):
        names = tuple(sorted(self.columns))
        return tuple(self.columns[n] for n in names) + (self.mask,), names

    @classmethod
    def tree_unflatten(cls, names, children):
        return cls(dict(zip(names, children[:-1])), children[-1])

    @property
    def capacity(self) -> int:
        return int(self.mask.shape[0])

    def column(self, name: str) -> Column:
        return self.columns[name]

    def with_columns(self, new: Dict[str, Column]) -> "Batch":
        cols = dict(self.columns)
        cols.update(new)
        return Batch(cols, self.mask)

    def select(self, names) -> "Batch":
        return Batch({n: self.columns[n] for n in names}, self.mask)

    def with_mask(self, mask) -> "Batch":
        return Batch(self.columns, mask)

    def with_params(self, params) -> "Batch":
        out = Batch(self.columns, self.mask)
        out.params = params
        return out

    def row_count(self):
        return jnp.sum(self.mask)

    def __repr__(self):
        return f"Batch({list(self.columns)}, capacity={self.capacity})"


jax.tree_util.register_pytree_node_class(Batch)


# ---------------------------------------------------------------------------
# host <-> device conversion
# ---------------------------------------------------------------------------

def _logical_np(typ: Type, values: np.ndarray) -> np.ndarray:
    """Storage-dtype numpy array -> logical-dtype numpy array."""
    if isinstance(typ, DoubleType):
        return values.view(np.float64) if values.dtype != np.float64 else values
    if isinstance(typ, RealType):
        return values.view(np.float32) if values.dtype != np.float32 else values
    if isinstance(typ, BooleanType):
        return values.astype(bool)
    return values


def block_to_column(typ: Type, block, capacity: int) -> Column:
    """Host block -> padded device column."""
    dictionary = None
    if isinstance(block, DictionaryBlock):
        flat = decode_to_flat(block.dictionary)
        if isinstance(flat, VariableWidthBlock):
            dictionary = tuple(flat.to_pylist())
            codes = np.zeros(capacity, dtype=np.int32)
            codes[:block.position_count] = block.ids
            nulls = None
            if flat.nulls is not None:
                nm = np.zeros(capacity, dtype=bool)
                nm[:block.position_count] = flat.null_mask()[block.ids]
                nulls = jnp.asarray(nm)
            return Column(jnp.asarray(codes), nulls, dictionary)
        block = decode_to_flat(block)
    else:
        block = decode_to_flat(block)

    if isinstance(block, VariableWidthBlock):
        # Dictionary-encode on the host: device sees int32 codes.
        strings = block.to_pylist()
        uniq = sorted({s for s in strings if s is not None})
        index = {s: i for i, s in enumerate(uniq)}
        codes = np.zeros(capacity, dtype=np.int32)
        codes[:len(strings)] = [0 if s is None else index[s] for s in strings]
        nulls = None
        if block.nulls is not None:
            nm = np.zeros(capacity, dtype=bool)
            nm[:len(strings)] = block.null_mask()
            nulls = jnp.asarray(nm)
        return Column(jnp.asarray(codes), nulls, tuple(uniq))

    from ..common.block import Int128Block
    if isinstance(block, Int128Block):
        # device holds long decimals narrowed to int64 (batch_to_page widens
        # on the way back out); values beyond int64 would need Pallas i128
        ints = block.to_pylist()
        vals = np.zeros(capacity, dtype=np.int64)
        nm = np.zeros(capacity, dtype=bool)
        for i, v in enumerate(ints):
            if v is None:
                nm[i] = True
            else:
                vals[i] = v
        nulls = jnp.asarray(nm) if nm.any() else None
        return Column(jnp.asarray(vals), nulls)

    from ..common.block import ArrayBlock
    if isinstance(block, ArrayBlock):
        # ragged ArrayBlock -> fixed-width (capacity, W) element matrix
        from ..common.types import ArrayType
        etyp = typ.element if isinstance(typ, ArrayType) else typ
        inner = decode_to_flat(block.elements)
        if not isinstance(inner, FixedWidthBlock):
            raise NotImplementedError("nested/varchar array elements")
        flat = _logical_np(etyp, inner.values)
        offs = block.offsets.astype(np.int64)
        lens = offs[1:] - offs[:-1]
        W = max(1, 1 << int(max(1, lens.max(initial=1)) - 1).bit_length())
        mat = np.zeros((capacity, W), dtype=flat.dtype)
        nrows = len(lens)
        live = np.arange(W)[None, :] < lens[:, None]
        base = int(offs[0])                 # offsets are contiguous
        mat[:nrows][live] = flat[base:base + int(lens.sum())]
        lenbuf = np.zeros(capacity, dtype=np.int32)
        lenbuf[:len(lens)] = lens
        nulls = None
        if block.nulls is not None:
            nm = np.zeros(capacity, dtype=bool)
            nm[:block.position_count] = block.nulls
            nulls = jnp.asarray(nm)
        return Column(jnp.asarray(mat), nulls, None, None,
                      jnp.asarray(lenbuf))
    if not isinstance(block, FixedWidthBlock):
        raise NotImplementedError(
            f"device column from {type(block).__name__} not supported yet")

    logical = _logical_np(typ, block.values)
    padded = np.zeros(capacity, dtype=logical.dtype)
    padded[:len(logical)] = logical
    nulls = None
    if block.nulls is not None:
        nm = np.zeros(capacity, dtype=bool)
        nm[:block.position_count] = block.nulls
        nulls = jnp.asarray(nm)
    return Column(jnp.asarray(padded), nulls)


def _element_block(etyp: Type, flat: np.ndarray) -> FixedWidthBlock:
    """Flat array-element values -> a storage-dtype FixedWidthBlock (the
    same logical->storage rules as scalar columns in batch_to_page)."""
    if isinstance(etyp, BooleanType):
        flat = flat.astype(np.int8)
    elif isinstance(etyp, (DoubleType, RealType)):
        pass                        # float bits pass through
    elif flat.dtype not in (np.int8, np.int16, np.int32, np.int64):
        flat = flat.astype(etyp.np_dtype)
    if isinstance(etyp, (IntegerType, DateType)):
        flat = flat.astype(np.int32)
    return FixedWidthBlock(flat)


def page_to_batch(page: Page, names, types, capacity: int) -> Batch:
    """Host page -> device batch (pads to capacity)."""
    if page.position_count > capacity:
        raise ValueError(f"page of {page.position_count} rows > capacity {capacity}")
    cols = {}
    for name, typ, block in zip(names, types, page.blocks):
        cols[name] = block_to_column(typ, block, capacity)
    mask = np.zeros(capacity, dtype=bool)
    mask[:page.position_count] = True
    return Batch(cols, jnp.asarray(mask))


def batch_to_page(batch: Batch, names, types) -> Page:
    """Device batch -> host page (drops masked-out rows).

    All device->host copies are issued as ONE async batch (jax.device_get
    starts every transfer before awaiting any): per-transfer round-trip
    latency dominates serially-fetched columns by orders of magnitude when
    the device is remote.  Large batches check the mask first so fully
    filtered-out batches (common in selective streaming pipelines) don't pay
    for full-capacity column transfers; small batches take the single
    combined fetch since round-trips dominate their bytes."""
    def column_fetch():
        fetch = {}
        for name in names:
            col = batch.columns.get(name)
            if col is None:
                continue
            fetch["v." + name] = col.values
            if col.nulls is not None:
                fetch["n." + name] = col.nulls
            if col.lengths is not None:
                fetch["l." + name] = col.lengths
        return fetch

    combined = batch.capacity <= (1 << 16)
    fetch = {"__mask": batch.mask}
    if combined:
        fetch.update(column_fetch())
    host = jax.device_get(fetch)  # lint: allow-host-sync
    mask = host["__mask"]
    keep = np.flatnonzero(mask)
    if keep.size == 0:
        from ..common.block import block_from_values
        return Page([block_from_values(t, []) for t in types], 0)
    if not combined:
        if keep.size <= (1 << 16) and keep.size * 4 <= batch.capacity:
            # sparse large batch (an aggregation finalize holds a few
            # live rows in a table-capacity layout): compact ON DEVICE
            # and transfer only the live bucket — a full-capacity column
            # fetch through a remote-device link costs ~10-100x the
            # compact dispatch (this was most of TPC-H Q1's wall at SF10)
            # reuse the process-wide compact jit + coarse bucket set
            # (pipeline._COMPACT_BUCKETS) so this fetch site adds no new
            # compiled shape variants
            from .pipeline import _bucket_for, _jit_compact
            bucket = _bucket_for(keep.size) \
                or 1 << int(keep.size - 1).bit_length()
            batch = _jit_compact(batch, bucket)
            host = jax.device_get({"__mask": batch.mask,  # lint: allow-host-sync
                                   **column_fetch()})
            mask = host["__mask"]
            keep = np.flatnonzero(mask)
        else:
            host.update(jax.device_get(column_fetch()))  # lint: allow-host-sync
    blocks = []
    for name, typ in zip(names, types):
        col = batch.columns[name]
        values = host["v." + name][keep]
        nulls = None if col.nulls is None else host["n." + name][keep]
        if col.lazy is not None:
            from ..connectors import catalog as _catalog
            cid, table, column, sf = col.lazy
            strings = _catalog.generate_values_at(table, column, sf, values,
                                                  cid)
            if nulls is not None:
                strings = [None if n else s for s, n in zip(strings, nulls)]
            from ..common.block import VariableWidthBlock as VB
            blocks.append(VB.from_strings(strings))
            continue
        if col.dictionary is not None:
            from ..common.block import DictionaryBlock as HB, VariableWidthBlock as VB
            ids = values.astype(np.int32)
            entries = list(col.dictionary)
            if nulls is not None and nulls.any():
                # DictionaryBlock carries nulls via its dictionary entries:
                # route NULL rows to an appended None entry.
                ids[nulls] = len(entries)
                entries.append(None)
            dict_block = VB.from_strings(entries)
            blocks.append(HB(ids, dict_block))
            continue
        if col.lengths is not None:
            # ARRAY column: (rows, W) padded element matrix + live lengths
            # -> ragged ArrayBlock (offsets into a flat element block)
            from ..common.block import ArrayBlock
            from ..common.types import ArrayType
            lens = host["l." + name][keep].astype(np.int64)
            W = values.shape[1] if values.ndim > 1 else 0
            lens = np.clip(lens, 0, W)
            if nulls is not None:
                lens = np.where(nulls, 0, lens)
            elem2d = values.reshape(len(keep), W) if W else \
                values.reshape(len(keep), 0)
            live = np.arange(W)[None, :] < lens[:, None]
            flat = elem2d[live]
            offsets = np.zeros(len(keep) + 1, dtype=np.int32)
            np.cumsum(lens, out=offsets[1:])
            etyp = typ.element if isinstance(typ, ArrayType) else typ
            blocks.append(ArrayBlock(offsets,
                                     _element_block(etyp, flat), nulls))
            continue
        if isinstance(typ, (VarcharType, CharType)):
            raise NotImplementedError("varchar column without dictionary")
        if isinstance(typ, DecimalType) and not typ.is_short:
            # device accumulates long decimals in int64; widen on the host
            from ..common.block import Int128Block
            ints = [None if (nulls is not None and nulls[i]) else int(v)
                    for i, v in enumerate(values)]
            blocks.append(Int128Block.from_ints(ints, nulls))
            continue
        if isinstance(typ, BooleanType):
            values = values.astype(np.int8)
        elif isinstance(typ, (DoubleType, RealType)):
            pass  # float bits pass through FixedWidthBlock
        elif values.dtype not in (np.int8, np.int16, np.int32, np.int64):
            values = values.astype(typ.np_dtype)
        if isinstance(typ, (IntegerType, DateType)):
            values = values.astype(np.int32)
        blocks.append(FixedWidthBlock(values, nulls))
    return Page(blocks, len(keep))


def pages_to_batches(pages, names, types, capacity):
    """Host pages (exchange input) -> device batches with STABLE dictionaries.

    Pages arriving from different producer tasks carry independent
    dictionaries; jitted consumers (agg tables, concat for joins) need one
    dictionary per column across all batches, so string columns are remapped
    to a union dictionary first.  Pages larger than `capacity` are chunked.
    """
    from ..common.block import block_to_values

    string_cols = [i for i, t in enumerate(types)
                   if isinstance(t, (VarcharType, CharType))]
    if not string_cols:
        # numeric-only schema: stream page by page
        for page in pages:
            for lo in range(0, page.position_count, capacity):
                n = min(capacity, page.position_count - lo)
                cols = {}
                for name, typ, block in zip(names, types, page.blocks):
                    chunk = block if (lo == 0 and n == page.position_count) \
                        else block.take(np.arange(lo, lo + n))
                    cols[name] = block_to_column(typ, chunk, capacity)
                mask = np.zeros(capacity, dtype=bool)
                mask[:n] = True
                yield Batch(cols, jnp.asarray(mask))
        return

    pages = [p for p in pages if p.position_count]
    if not pages:
        return
    # union dictionary per string column; cache the decoded strings for reuse
    unions = {}
    decoded = {}  # (page index, col index) -> list of strings
    for i in string_cols:
        seen = set()
        for pi, page in enumerate(pages):
            strings = block_to_values(types[i], page.blocks[i])
            decoded[(pi, i)] = strings
            seen.update(s for s in strings if s is not None)
        uniq = tuple(sorted(seen))
        unions[i] = (uniq, {s: j for j, s in enumerate(uniq)})

    for pi, page in enumerate(pages):
        for lo in range(0, page.position_count, capacity):
            n = min(capacity, page.position_count - lo)
            cols = {}
            for i, (name, typ) in enumerate(zip(names, types)):
                block = page.blocks[i]
                if i in unions:
                    uniq, index = unions[i]
                    strings = decoded[(pi, i)][lo:lo + n]
                    codes = np.zeros(capacity, dtype=np.int32)
                    nm = np.zeros(capacity, dtype=bool)
                    for j, s in enumerate(strings):
                        if s is None:
                            nm[j] = True
                        else:
                            codes[j] = index[s]
                    nulls = jnp.asarray(nm) if nm.any() else None
                    cols[name] = Column(jnp.asarray(codes), nulls, uniq)
                else:
                    chunk = block if (lo == 0 and n == page.position_count) \
                        else block.take(np.arange(lo, lo + n))
                    cols[name] = block_to_column(typ, chunk, capacity)
            mask = np.zeros(capacity, dtype=bool)
            mask[:n] = True
            yield Batch(cols, jnp.asarray(mask))
