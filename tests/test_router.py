"""Query router + plan-check gating + retry classification + UI endpoint
(reference analogs: presto-router, presto-plan-checker-router-plugin,
presto-spark ErrorClassifier, presto-ui — SURVEY.md §2.11)."""
import json
import urllib.request

import pytest

from presto_tpu.client import StatementClient
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.worker import WorkerServer
from presto_tpu.worker.router import QueryRouter, plan_checks
from presto_tpu.worker.statement import _is_retryable


@pytest.fixture(scope="module")
def cluster():
    a = WorkerServer(coordinator=True, environment="test",
                     config=ExecutionConfig(batch_rows=1 << 13))
    b = WorkerServer(coordinator=True, environment="test",
                     config=ExecutionConfig(batch_rows=1 << 13))
    yield a, b
    a.close()
    b.close()


def test_plan_checks():
    assert plan_checks("SELECT count(*) c FROM lineitem") is None
    assert plan_checks("SELECT broken syntax FROM FROM") is not None
    assert plan_checks("SELECT no_such_fn(quantity) x FROM lineitem") \
        is not None


def test_round_robin_routing(cluster):
    a, b = cluster
    router = QueryRouter([a.uri, b.uri])
    try:
        targets = {router.route("SELECT 1 x") for _ in range(4)}
        assert targets == {a.uri, b.uri}
        # end-to-end through the redirect: the client follows the 307
        c = StatementClient(router.uri, schema="sf0.01")
        r = c.execute("SELECT count(*) c FROM orders")
        assert r.rows[0][0] > 0
    finally:
        router.close()


def test_plan_check_fallback(cluster):
    a, b = cluster
    router = QueryRouter([a.uri], scheduler="plan_check", fallback=b.uri)
    try:
        assert router.route("SELECT count(*) c FROM orders") == a.uri
        # unplannable: goes to the fallback cluster
        assert router.route("SELECT wat(no) FROM nowhere") == b.uri
    finally:
        router.close()


def test_plan_check_sidecar_endpoint(cluster):
    a, _ = cluster
    req = urllib.request.Request(
        f"{a.uri}/v1/plan-check", data=b"SELECT count(*) c FROM orders",
        method="POST")
    assert json.loads(urllib.request.urlopen(req).read())["ok"] is True
    req = urllib.request.Request(
        f"{a.uri}/v1/plan-check", data=b"SELECT nope(1) FROM nope",
        method="POST")
    out = json.loads(urllib.request.urlopen(req).read())
    assert out["ok"] is False and "error" in out


def test_router_clusters_endpoint(cluster):
    a, b = cluster
    router = QueryRouter([a.uri, b.uri])
    try:
        with urllib.request.urlopen(
                f"{router.uri}/v1/router/clusters") as resp:
            info = json.loads(resp.read())
        assert set(info["clusters"]) == {a.uri, b.uri}
    finally:
        router.close()


def test_retry_classification():
    assert _is_retryable(ConnectionRefusedError("connection refused"))
    assert _is_retryable(RuntimeError("no live workers"))
    assert not _is_retryable(ValueError("column 'x' not found"))


def test_ui_page(cluster):
    a, _ = cluster
    StatementClient(a.uri, schema="sf0.01").execute("SELECT 1 x")
    html = urllib.request.urlopen(f"{a.uri}/ui").read().decode()
    assert "presto-tpu coordinator" in html
    assert "FINISHED" in html
