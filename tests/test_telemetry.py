"""Telemetry export pipeline tests (tier-1).

Covers the PR's acceptance surface:
  * OTLP golden schemas: span / metric payload shapes out of the pure
    converters, deterministic (token, name) -> id stitching.
  * exporter backpressure: the bounded queue DROPS (metered) and never
    blocks the caller; sink outages retry under the jittered error
    budget, then drop.
  * history retention: count + age eviction (injectable clock), restart
    reload from the JSONL spool, malformed-line tolerance.
  * /v1/cluster + /v1/query?state=... + history survival across a
    coordinator restart, over real loopback HTTP.
  * the end-to-end distributed trace: a client trace token yields ONE
    OTLP trace holding coordinator query/fragment spans and worker
    task/operator spans.
  * per-query device profiler capture smoke under the CPU backend.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from presto_tpu.telemetry import (CollectorSink, HistoryEventListener,
                                  JsonlFileSink, QueryHistoryStore,
                                  TelemetryExporter, make_sink,
                                  metrics_to_resource_metrics,
                                  profile_capture, scrape_metric_points,
                                  span_id_for, spans_to_resource_spans,
                                  trace_id_for)
from presto_tpu.utils.runtime_stats import Span


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


# ---------------------------------------------------------------------------
# OTLP golden schemas
# ---------------------------------------------------------------------------

def test_trace_and_span_ids_deterministic():
    assert trace_id_for("tok") == trace_id_for("tok")
    assert trace_id_for("tok") != trace_id_for("tok2")
    assert len(trace_id_for("tok")) == 32          # 16 bytes hex
    assert len(span_id_for("tok", "query")) == 16  # 8 bytes hex
    # the stitching property: two processes that only share the token
    # agree on every span id
    assert span_id_for("tok", "fragment 1") == span_id_for("tok",
                                                           "fragment 1")


def test_spans_to_resource_spans_golden_shape():
    spans = [
        Span("query", "", start=10.0, end=11.5,
             attributes={"sql": "select 1", "rows": 3, "ok": True,
                         "frac": 0.5}),
        Span("fragment 0", "query", start=10.1, end=11.0),
    ]
    payload = spans_to_resource_spans("tok", spans,
                                      resource={"service.name": "p"})
    (rs,) = payload["resourceSpans"]
    assert rs["resource"]["attributes"] == [
        {"key": "service.name", "value": {"stringValue": "p"}}]
    (ss,) = rs["scopeSpans"]
    assert ss["scope"]["name"] == "presto_tpu.telemetry"
    root, frag = ss["spans"]
    assert root["traceId"] == frag["traceId"] == trace_id_for("tok")
    assert root["parentSpanId"] == ""
    assert frag["parentSpanId"] == root["spanId"]
    assert root["spanId"] == span_id_for("tok", "query")
    assert root["startTimeUnixNano"] == str(int(10.0 * 1e9))
    assert root["endTimeUnixNano"] == str(int(11.5 * 1e9))
    attrs = {a["key"]: a["value"] for a in root["attributes"]}
    # OTLP/JSON AnyValue: intValue is a decimal STRING; bools are bools
    assert attrs["sql"] == {"stringValue": "select 1"}
    assert attrs["rows"] == {"intValue": "3"}
    assert attrs["ok"] == {"boolValue": True}
    assert attrs["frac"] == {"doubleValue": 0.5}
    json.dumps(payload)   # wire-encodable as-is


def test_metrics_payload_golden_shape():
    payload = metrics_to_resource_metrics(
        [("presto_tpu.exchange.bytes", 42.0, {}),
         ("presto_tpu.kernel.declined", 2.0, {"reason": "Backend"})],
        time_unix_nano=123, resource={"service.name": "p"})
    (rm,) = payload["resourceMetrics"]
    (sm,) = rm["scopeMetrics"]
    m0, m1 = sm["metrics"]
    assert m0["name"] == "presto_tpu.exchange.bytes"
    assert m0["gauge"]["dataPoints"] == [
        {"timeUnixNano": "123", "asDouble": 42.0}]
    (dp,) = m1["gauge"]["dataPoints"]
    assert dp["attributes"] == [
        {"key": "reason", "value": {"stringValue": "Backend"}}]
    json.dumps(payload)


def test_scrape_covers_every_registry():
    names = {n for n, _v, _a in scrape_metric_points()}
    for prefix in ("presto_tpu.exchange.", "presto_tpu.exchange_fabric.",
                   "presto_tpu.serving.", "presto_tpu.storage.",
                   "presto_tpu.kernel.", "presto_tpu.memory."):
        assert any(n.startswith(prefix) for n in names), prefix
    assert "presto_tpu.kernel.scan_programs" in names
    assert "presto_tpu.memory.spilled_bytes" in names


def test_make_sink_dispatch(tmp_path):
    assert make_sink("none") is None
    assert make_sink("") is None
    assert isinstance(make_sink("collector"), CollectorSink)
    assert isinstance(make_sink("jsonl", path=str(tmp_path / "t.jsonl")),
                      JsonlFileSink)
    with pytest.raises(ValueError):
        make_sink("jsonl")             # needs a path
    with pytest.raises(ValueError):
        make_sink("http")              # needs an endpoint
    with pytest.raises(ValueError):
        make_sink("bogus")


# ---------------------------------------------------------------------------
# exporter: batching, backpressure, retry budget
# ---------------------------------------------------------------------------

def test_exporter_delivers_spans_and_metrics():
    sink = CollectorSink()
    exp = TelemetryExporter(sink, queue_bound=16, flush_interval_s=0.02)
    try:
        exp.export_spans("tok", [Span("query", "", start=1.0, end=2.0)],
                         resource={"presto.role": "coordinator"})
        exp.scrape_metrics()
        assert exp.flush(timeout_s=5.0)
        assert sink.trace_ids() == [trace_id_for("tok")]
        assert "presto_tpu.serving.planCacheHits" in sink.metric_names()
        c = exp.counters()
        assert c["enqueued"] == 2 and c["exported"] == 2
        assert c["dropped"] == 0 and c["queue_depth"] == 0
    finally:
        exp.close()


def test_exporter_backpressure_drops_metered_never_blocks():
    """A wedged sink must not wedge the query path: enqueue stays
    wait-free, overflow is dropped and counted."""
    release = threading.Event()

    class StallingSink(CollectorSink):
        def export(self, payload):
            release.wait(10)
            super().export(payload)

    exp = TelemetryExporter(StallingSink(), queue_bound=4,
                            flush_interval_s=0.01)
    try:
        t0 = time.perf_counter()
        results = [exp.enqueue({"resourceSpans": [], "i": i})
                   for i in range(32)]
        elapsed = time.perf_counter() - t0
        assert elapsed < 1.0, "enqueue must never block on a stalled sink"
        c = exp.counters()
        # bound + at most one in flight survive; the rest dropped
        assert c["dropped"] >= 32 - 4 - 1
        assert c["dropped"] + c["enqueued"] == 32
        assert results.count(False) == c["dropped"]
        release.set()
        assert exp.flush(timeout_s=5.0)
        assert exp.counters()["exported"] == c["enqueued"]
    finally:
        release.set()
        exp.close()


def test_exporter_retry_budget_then_drop():
    """Sink failures retry with backoff under the error budget, then the
    payload is dropped (metered) instead of wedging the flush thread."""
    class DeadSink(CollectorSink):
        def __init__(self):
            super().__init__()
            self.attempts = 0

        def export(self, payload):
            self.attempts += 1
            raise OSError("collector down")

    sink = DeadSink()
    exp = TelemetryExporter(sink, queue_bound=4, flush_interval_s=0.01,
                            max_error_duration_s=0.3)
    try:
        assert exp.enqueue({"resourceSpans": []})
        deadline = time.monotonic() + 10
        while (exp.counters()["dropped_after_retry"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.02)
        c = exp.counters()
        assert c["dropped_after_retry"] == 1
        assert c["retries"] >= 1 and sink.attempts >= 2
        assert c["exported"] == 0
    finally:
        exp.close()


def test_exporter_rejects_unbounded_queue():
    with pytest.raises(ValueError):
        TelemetryExporter(CollectorSink(), queue_bound=0)


def test_jsonl_sink_appends_lines(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    exp = TelemetryExporter(JsonlFileSink(path), queue_bound=8,
                            flush_interval_s=0.01)
    try:
        exp.export_spans("tok", [Span("query", "", start=1.0, end=2.0)])
        exp.export_spans("tok2", [Span("query", "", start=1.0, end=2.0)])
        assert exp.flush(timeout_s=5.0)
    finally:
        exp.close()
    lines = [json.loads(l) for l in open(path) if l.strip()]
    assert len(lines) == 2
    assert all("resourceSpans" in l for l in lines)


# ---------------------------------------------------------------------------
# history store: retention + restart reload
# ---------------------------------------------------------------------------

def _rec(qid, state="FINISHED", **kw):
    return {"queryId": qid, "state": state, "query": f"select {qid}", **kw}


def test_history_count_eviction():
    store = QueryHistoryStore(max_count=3)
    for i in range(5):
        store.record(_rec(f"q{i}"))
    assert len(store) == 3
    assert [r["queryId"] for r in store.list()] == ["q4", "q3", "q2"]
    assert store.evicted == 2


def test_history_age_eviction_with_injected_clock():
    now = [1000.0]
    store = QueryHistoryStore(max_count=100, max_age_s=60.0,
                              clock=lambda: now[0])
    store.record(_rec("old"))
    now[0] += 120.0
    store.record(_rec("fresh"))
    assert [r["queryId"] for r in store.list()] == ["fresh"]
    assert store.evicted == 1
    assert store.counts_by_state() == {"FINISHED": 1}


def test_history_state_filter_and_rerecord():
    store = QueryHistoryStore(max_count=10)
    store.record(_rec("a", state="FAILED"))
    store.record(_rec("b"))
    store.record(_rec("a", state="FINISHED"))   # supersedes
    assert [r["queryId"] for r in store.list(state="finished")] == ["a",
                                                                    "b"]
    assert store.list(state="FAILED") == []
    assert store.get("a")["state"] == "FINISHED"


def test_history_restart_reload(tmp_path):
    path = str(tmp_path / "history.jsonl")
    store = QueryHistoryStore(path, max_count=10)
    store.record(_rec("q1"))
    store.record(_rec("q2", state="FAILED", errorMessage="boom"))
    del store

    reloaded = QueryHistoryStore(path, max_count=10)
    assert reloaded.loaded == 2
    assert reloaded.get("q2")["errorMessage"] == "boom"
    assert [r["queryId"] for r in reloaded.list()] == ["q2", "q1"]
    # retention applies at reload too: a tighter bound compacts the spool
    tight = QueryHistoryStore(path, max_count=1)
    assert len(tight) == 1 and tight.get("q2") is not None
    assert sum(1 for _ in open(path)) == 1      # compacted on load


def test_history_tolerates_malformed_lines(tmp_path):
    path = str(tmp_path / "history.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(_rec("good")) + "\n")
        f.write("{not json\n")
        f.write(json.dumps({"noQueryId": True}) + "\n")
    store = QueryHistoryStore(path, max_count=10)
    assert store.loaded == 1 and store.load_errors == 2
    assert store.get("good") is not None


def test_history_listener_records_completed_events():
    from presto_tpu.worker.events import QueryCompletedEvent
    store = QueryHistoryStore(max_count=10)
    listener = HistoryEventListener(
        store, extra_fields=lambda ev: {"profileTraceDir": "/tmp/x"})
    listener.query_completed(QueryCompletedEvent(
        query_id="q1", sql="select 1", user="u", state="FINISHED",
        create_time=1.0, end_time=2.0, wall_time_s=1.0, queued_time_s=0.0,
        rows=1, trace_token="tok", resource_group="global"))
    rec = store.get("q1")
    assert rec["traceToken"] == "tok"
    assert rec["resourceGroup"] == "global"
    assert rec["profileTraceDir"] == "/tmp/x"


# ---------------------------------------------------------------------------
# profiler capture (CPU-backend smoke)
# ---------------------------------------------------------------------------

def test_profile_capture_disabled_paths(tmp_path):
    with profile_capture(str(tmp_path), "q", enabled=False) as d:
        assert d is None
    with profile_capture(None, "q", enabled=True) as d:
        assert d is None


def test_profile_capture_smoke(tmp_path):
    import jax
    import jax.numpy as jnp
    with profile_capture(str(tmp_path), "q0.1", enabled=True) as d:
        assert d is not None and d.startswith(str(tmp_path))
        jax.jit(lambda x: x * 2)(jnp.arange(8)).block_until_ready()  # lint: allow-host-sync
    assert os.path.isdir(d)
    # jax wrote SOMETHING under the capture dir (plugin layout varies)
    assert any(files for _root, _dirs, files in os.walk(d))


def test_profile_capture_concurrent_loser_degrades(tmp_path):
    with profile_capture(str(tmp_path), "winner", enabled=True) as d1:
        assert d1 is not None
        with profile_capture(str(tmp_path), "loser", enabled=True) as d2:
            assert d2 is None   # singleton profiler session: no queueing


def test_explain_analyze_footer_reports_profile_dir(tmp_path):
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.exec.runner import LocalQueryRunner
    runner = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        profile=True, profile_dir=str(tmp_path)))
    res = runner.execute("EXPLAIN ANALYZE select count(*) from nation")
    text = res.rows[0][0]
    assert "Device profile: " in text
    reported = text.split("Device profile: ", 1)[1].splitlines()[0]
    assert os.path.isdir(reported)


def test_query_result_carries_profile_trace_dir(tmp_path):
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.exec.runner import LocalQueryRunner
    runner = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        profile=True, profile_dir=str(tmp_path)))
    res = runner.execute("select count(*) from nation")
    assert res.profile_trace_dir and os.path.isdir(res.profile_trace_dir)
    # and off by default
    res2 = LocalQueryRunner("sf0.01").execute("select 1")
    assert res2.profile_trace_dir is None


# ---------------------------------------------------------------------------
# server integration: /v1/cluster, /v1/query, restart survival, e2e trace
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def traced_cluster():
    """Coordinator (collector-sinked telemetry + history) + 2 workers."""
    from presto_tpu.worker.server import WorkerServer
    sink = CollectorSink()
    coordinator = WorkerServer(coordinator=True, environment="test",
                               telemetry_sink=sink,
                               telemetry_flush_interval_s=0.02)
    workers = [WorkerServer(discovery_uri=coordinator.uri,
                            announce_interval_s=0.1,
                            environment="test") for _ in range(2)]
    deadline = time.time() + 10
    while len(coordinator.worker_uris()) < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert len(coordinator.worker_uris()) == 2, "workers failed to announce"
    yield coordinator, workers, sink
    for w in workers:
        w.close()
    coordinator.close()


def test_end_to_end_distributed_trace(traced_cluster):
    """The acceptance bar: a client-supplied X-Presto-Trace-Token yields
    ONE OTLP trace containing the coordinator's query/fragment spans AND
    the workers' task/operator spans, with nothing dropped."""
    from presto_tpu.client import StatementClient
    coordinator, _workers, sink = traced_cluster
    token = "e2e-trace-0001"
    client = StatementClient(coordinator.uri, schema="sf0.01",
                             trace_token=token)
    res = client.execute(
        "select n_regionkey, count(*) from nation group by n_regionkey")
    assert len(res.rows) == 5
    assert coordinator.telemetry.flush(timeout_s=10.0)

    spans = [s for s in sink.spans()
             if s["traceId"] == trace_id_for(token)]
    by_name = {s["name"]: s for s in spans}
    assert "query" in by_name, sorted(by_name)
    fragments = [s for s in spans if s["name"].startswith("fragment ")]
    tasks = [s for s in spans if s["name"].startswith("task ")]
    operators = [s for s in spans if s["name"].startswith("operator ")]
    assert fragments and tasks and operators
    # stitch check: every fragment hangs off the query root; every task's
    # parent id equals SOME exported fragment span id even though the
    # worker slice was exported by a different server object
    qid = by_name["query"]["spanId"]
    assert all(f["parentSpanId"] == qid for f in fragments)
    frag_ids = {f["spanId"] for f in fragments}
    assert all(t["parentSpanId"] in frag_ids for t in tasks)
    task_ids = {t["spanId"] for t in tasks}
    assert all(o["parentSpanId"] in task_ids for o in operators)
    # distributed provenance: coordinator and worker resource slices
    roles = set()
    for p in sink.payloads:
        for rs in p.get("resourceSpans", []):
            for a in rs["resource"]["attributes"]:
                if a["key"] == "presto.role":
                    roles.add(a["value"]["stringValue"])
    assert {"coordinator", "worker"} <= roles
    c = coordinator.telemetry.counters()
    assert c["dropped"] == 0 and c["dropped_after_retry"] == 0


def test_http_explain_analyze_profile_footer(traced_cluster, tmp_path):
    """`profile=true` captures through the HTTP-distributed ANALYZE path
    (coordinator _explain_http), not just the local runner."""
    from presto_tpu.client import StatementClient
    coordinator, _workers, _sink = traced_cluster
    client = StatementClient(coordinator.uri, schema="sf0.01",
                             session={"profile": "true"})
    res = client.execute("EXPLAIN ANALYZE select count(*) from nation")
    text = res.rows[0][0]
    assert "Device profile: " in text, text[-300:]
    reported = text.split("Device profile: ", 1)[1].splitlines()[0]
    assert os.path.isdir(reported)


def test_cluster_endpoint_shape(traced_cluster):
    coordinator, _workers, _sink = traced_cluster
    info = _get_json(f"{coordinator.uri}/v1/cluster")
    for key in ("runningQueries", "queuedQueries", "blockedQueries",
                "finishedQueries", "failedQueries", "activeWorkers",
                "runningTasks", "totalTasks", "reservedMemoryBytes",
                "fabricByteRates", "historyEntries", "telemetry"):
        assert key in info, key
    assert info["activeWorkers"] == 2
    assert info["finishedQueries"] >= 1   # the e2e query above
    assert isinstance(info["fabricByteRates"], dict)
    assert info["telemetry"]["queue_bound"] > 0


def test_cluster_endpoint_is_coordinator_only(traced_cluster):
    _coordinator, workers, _sink = traced_cluster
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(f"{workers[0].uri}/v1/cluster", timeout=10)
    assert e.value.code == 404


def test_query_list_state_filter(traced_cluster):
    coordinator, _workers, _sink = traced_cluster
    finished = _get_json(f"{coordinator.uri}/v1/query?state=FINISHED")
    assert finished and all(q["state"] == "FINISHED" for q in finished)
    assert not _get_json(f"{coordinator.uri}/v1/query?state=CANCELED")


def test_history_survives_coordinator_restart(tmp_path):
    from presto_tpu.client import StatementClient
    from presto_tpu.worker.server import WorkerServer
    hist = str(tmp_path / "history.jsonl")
    server = WorkerServer(coordinator=True, environment="test",
                          history_path=hist)
    try:
        client = StatementClient(server.uri, schema="sf0.01")
        res = client.execute("select count(*) from nation")
        assert res.rows == [[25]]
        qids = [q["queryId"] for q in
                _get_json(f"{server.uri}/v1/query?state=FINISHED")]
        assert len(qids) == 1
    finally:
        server.close()

    revived = WorkerServer(coordinator=True, environment="test",
                           history_path=hist)
    try:
        assert revived.history.loaded == 1
        listed = _get_json(f"{revived.uri}/v1/query?state=FINISHED")
        assert [q["queryId"] for q in listed] == qids
        # /v1/query/{id} falls back to the durable record
        rec = _get_json(f"{revived.uri}/v1/query/{qids[0]}")
        assert rec["source"] == "history"
        assert rec["state"] == "FINISHED"
    finally:
        revived.close()


def test_server_metrics_expose_telemetry_counters(traced_cluster):
    coordinator, _workers, _sink = traced_cluster
    with urllib.request.urlopen(f"{coordinator.uri}/v1/metrics",
                                timeout=10) as resp:
        body = resp.read().decode()
    assert "presto_tpu_telemetry_enqueued_total" in body
    assert "presto_tpu_telemetry_dropped_total 0" in body
    assert "presto_tpu_history_entries" in body
