"""PlanCheck conformance: every check code fires on a hand-built broken
plan, and no check fires on any TPC-H / TPC-DS suite plan at any of the
three wired stages (post-plan, post-optimize, post-fragment).

Reference: sql/planner/sanity/PlanChecker.java and its checker suite
(ValidateDependenciesChecker, NoDuplicatePlanNodeIdsChecker,
TypeValidator) — the point of the tests is the same as the reference's
TestValidateDependenciesChecker etc.: a checker that never fires is
indistinguishable from no checker.
"""
import pytest

from presto_tpu.analysis import (CHECK_DANGLING_VARIABLE,
                                 CHECK_DUPLICATE_NODE_ID,
                                 CHECK_EXCHANGE_LAYOUT,
                                 CHECK_FRAGMENT_BOUNDARY,
                                 CHECK_GROUPED_EXECUTION,
                                 CHECK_JOIN_KEY_TYPE, CHECK_PARTITIONING,
                                 CHECK_SCAN_PUSHDOWN,
                                 CHECK_TYPE_MISMATCH, VALIDATION_OFF,
                                 check_plan, check_subplan,
                                 use_validation_mode, validate_plan,
                                 validation_mode)
from presto_tpu.benchmarks.tpch_queries import ALL as TPCH_QUERIES
from presto_tpu.common.errors import (PLAN_VALIDATION, PlanValidationError,
                                      is_retryable, is_retryable_type,
                                      parse_error_type)
from presto_tpu.common.types import (BigintType, BooleanType, DoubleType,
                                     VarcharType)
from presto_tpu.spi import plan as P
from presto_tpu.spi.expr import ConstantExpression
from presto_tpu.spi.expr import VariableReferenceExpression as V
from presto_tpu.sql.fragmenter import plan_distributed
from presto_tpu.sql.planner import Planner

from test_tpcds_queries import QUERIES as TPCDS_QUERIES

BIGINT = BigintType()
DOUBLE = DoubleType()
BOOLEAN = BooleanType()
VARCHAR = VarcharType()


def _values(nid, **cols):
    return P.ValuesNode(nid, [V(n, t) for n, t in cols.items()])


def _codes(diags):
    return {d.code for d in diags}


# ---------------------------------------------------------------------------
# one intentional violation per check code
# ---------------------------------------------------------------------------

def test_clean_plan_has_no_diagnostics():
    vals = _values("v0", a=BIGINT)
    out = P.OutputNode("o0", vals, ["a"], [V("a", BIGINT)])
    assert check_plan(out) == []


def test_dangling_variable_fires():
    vals = _values("v0", a=BIGINT)
    proj = P.ProjectNode("p0", vals, {V("x", BIGINT): V("ghost", BIGINT)})
    diags = check_plan(proj)
    assert CHECK_DANGLING_VARIABLE in _codes(diags)
    assert any("ghost" in d.message for d in diags)


def test_duplicate_node_id_fires_on_structurally_different_nodes():
    vals = _values("n1", a=BOOLEAN)
    filt = P.FilterNode("n1", vals, V("a", BOOLEAN))
    diags = check_plan(filt)
    assert CHECK_DUPLICATE_NODE_ID in _codes(diags)


def test_duplicate_node_id_allows_structurally_identical_copies():
    """Decorrelated deep copies deliberately share plan-node ids (the
    pipeline compiler memoizes per id); only structurally DIFFERENT
    nodes sharing an id are a bug."""
    left = _values("shared", a=BIGINT)
    right = _values("shared", a=BIGINT)
    union = P.UnionNode("u0", [left, right], [V("a", BIGINT)])
    assert check_plan(union) == []


def test_structural_key_ignores_dynamic_filter_ids():
    """Regression for the duplicate-id false positives on TPC-H q21 /
    TPC-DS q16: plan_dynamic_filters numbers filter ids per join
    INSTANCE after rule-driven deep copies, so two decorrelated copies
    differ only in `df_N_i` bookkeeping.  structural_key must blank the
    ids (like node ids) while canonicalizing the probe-column names."""
    def join(df):
        return P.JoinNode(
            "j0", P.INNER, _values("l0", a=BIGINT), _values("r0", b=BIGINT),
            [(V("a", BIGINT), V("b", BIGINT))], [V("a", BIGINT)],
            dynamic_filters=df)

    assert (P.structural_key(join({"a": "df_3_0"}))
            == P.structural_key(join({"a": "df_11_0"})))
    # but a different probe COLUMN is a different plan
    j2 = P.JoinNode(
        "j0", P.INNER, _values("l0", a=BIGINT, c=BIGINT),
        _values("r0", b=BIGINT), [(V("a", BIGINT), V("b", BIGINT))],
        [V("a", BIGINT)], dynamic_filters={"c": "df_3_0"})
    assert P.structural_key(join({"a": "df_3_0"})) != P.structural_key(j2)


def test_type_mismatch_fires():
    vals = _values("v0", a=BIGINT)
    proj = P.ProjectNode(
        "p0", vals, {V("x", VARCHAR): ConstantExpression(1, BIGINT)})
    diags = check_plan(proj)
    assert CHECK_TYPE_MISMATCH in _codes(diags)


def test_filter_predicate_must_be_boolean():
    vals = _values("v0", a=BIGINT)
    filt = P.FilterNode("f0", vals, V("a", BIGINT))
    assert CHECK_TYPE_MISMATCH in _codes(check_plan(filt))


def test_join_key_type_fires():
    join = P.JoinNode(
        "j0", P.INNER, _values("l0", a=BIGINT), _values("r0", b=VARCHAR),
        [(V("a", BIGINT), V("b", VARCHAR))], [V("a", BIGINT)])
    assert CHECK_JOIN_KEY_TYPE in _codes(check_plan(join))


def test_int_family_widening_is_compatible():
    """bigint vs integer keys are layout-compatible, not a diagnostic."""
    from presto_tpu.common.types import IntegerType
    join = P.JoinNode(
        "j0", P.INNER, _values("l0", a=BIGINT),
        _values("r0", b=IntegerType()),
        [(V("a", BIGINT), V("b", IntegerType()))], [V("a", BIGINT)])
    assert check_plan(join) == []


def test_exchange_layout_fires_on_union_branch_drift():
    union = P.UnionNode(
        "u0", [_values("v0", a=BIGINT), _values("v1", b=BIGINT)],
        [V("a", BIGINT)])
    diags = check_plan(union)
    assert CHECK_EXCHANGE_LAYOUT in _codes(diags)


def test_exchange_layout_fires_on_column_type_drift():
    src = _values("v0", a=VARCHAR)
    ex = P.ExchangeNode(
        "e0", P.GATHER, P.LOCAL,
        P.PartitioningScheme(P.SINGLE_DISTRIBUTION, [], [V("x", BIGINT)]),
        [src], [[V("a", VARCHAR)]])
    assert CHECK_EXCHANGE_LAYOUT in _codes(check_plan(ex))


def test_partitioning_fires_on_ungrounded_hash_column():
    src = _values("v0", a=BIGINT)
    ex = P.ExchangeNode(
        "e0", P.REPARTITION, P.LOCAL,
        P.PartitioningScheme(P.FIXED_HASH_DISTRIBUTION,
                             [V("ghost", BIGINT)], [V("a", BIGINT)]),
        [src], [[V("a", BIGINT)]])
    assert CHECK_PARTITIONING in _codes(check_plan(ex))


def test_partitioning_fires_on_hash_without_columns():
    src = _values("v0", a=BIGINT)
    ex = P.ExchangeNode(
        "e0", P.REPARTITION, P.LOCAL,
        P.PartitioningScheme(P.FIXED_HASH_DISTRIBUTION, [],
                             [V("a", BIGINT)]),
        [src], [[V("a", BIGINT)]])
    assert CHECK_PARTITIONING in _codes(check_plan(ex))


def _single_fragment(fid, root, layout):
    return P.PlanFragment(
        fid, root, P.SINGLE_DISTRIBUTION,
        P.PartitioningScheme(P.SINGLE_DISTRIBUTION, [], layout))


def test_fragment_boundary_fires_on_unknown_fragment():
    remote = P.RemoteSourceNode("r0", ["99"], [V("a", BIGINT)])
    sub = P.SubPlan(_single_fragment("0", remote, [V("a", BIGINT)]), [])
    assert CHECK_FRAGMENT_BOUNDARY in _codes(check_subplan(sub))


def test_fragment_boundary_fires_on_column_order_drift():
    child_root = _values("v0", a=BIGINT, b=BIGINT)
    child = P.SubPlan(_single_fragment(
        "1", child_root, [V("b", BIGINT), V("a", BIGINT)]), [])
    remote = P.RemoteSourceNode(
        "r0", ["1"], [V("a", BIGINT), V("b", BIGINT)])
    sub = P.SubPlan(_single_fragment("0", remote, [V("a", BIGINT)]),
                    [child])
    diags = check_subplan(sub)
    assert CHECK_FRAGMENT_BOUNDARY in _codes(diags)
    assert any("drift" in d.message for d in diags)


def test_fragment_boundary_fires_on_unconsumed_child():
    child = P.SubPlan(_single_fragment(
        "1", _values("v0", a=BIGINT), [V("a", BIGINT)]), [])
    root = _values("v1", b=BIGINT)
    sub = P.SubPlan(_single_fragment("0", root, [V("b", BIGINT)]), [child])
    diags = check_subplan(sub)
    assert CHECK_FRAGMENT_BOUNDARY in _codes(diags)
    assert any("no consuming" in d.message for d in diags)


def test_grouped_execution_fires_on_corrupted_fragment():
    """Plan a genuinely grouped-eligible stage, then corrupt the
    fragment's distribution: the claim (stage_shards_lifespans) no longer
    matches the fragment the scheduler would run."""
    from presto_tpu.exec.pipeline import ExecutionConfig
    cfg = ExecutionConfig(grouped_lifespans=4)
    root = Planner("sf0.01", "tpch").plan(
        "SELECT l_orderkey, count(*) FROM lineitem GROUP BY l_orderkey")
    sub = plan_distributed(root, exec_config=cfg)
    from presto_tpu.exec.grouped import stage_shards_lifespans
    eligible = [sp for sp in _walk_subplans(sub)
                if stage_shards_lifespans(sp.fragment.root, cfg)]
    assert eligible, "fixture query must be grouped-eligible"
    assert check_subplan(sub, exec_config=cfg) == []
    eligible[0].fragment.partitioning = P.SINGLE_DISTRIBUTION
    eligible[0].fragment.partitioned_sources = []
    diags = check_subplan(sub, exec_config=cfg)
    assert CHECK_GROUPED_EXECUTION in _codes(diags)
    # and PARTITIONING notices the scan stranded in a SINGLE fragment
    assert CHECK_PARTITIONING in _codes(diags)


def _walk_subplans(sp):
    yield sp
    for c in sp.children:
        yield from _walk_subplans(c)


def test_union_branches_are_fragmented():
    """Regression for the FRAGMENT_BOUNDARY violation the checker caught
    on distributed set operations: Fragmenter._rewrite skipped
    UnionNode.inputs, so the REMOTE gathers the ExchangeInserter puts
    under each distributed branch survived fragmentation and the whole
    union — scans included — ran inlined in the consuming fragment."""
    from presto_tpu.sql.fragmenter import FragmenterConfig
    root = Planner("sf0.01", "tpch").plan(
        "SELECT o_orderstatus FROM orders "
        "UNION ALL SELECT o_orderpriority FROM orders")
    sub = plan_distributed(root, FragmenterConfig())
    assert check_subplan(sub) == []
    frags = sub.all_fragments()
    assert len(frags) >= 3  # consumer + one SOURCE fragment per branch
    for node in P.walk_plan(sub.fragment.root):
        assert not (isinstance(node, P.ExchangeNode)
                    and node.scope == P.REMOTE)


# ---------------------------------------------------------------------------
# modes, error taxonomy, wiring surfaces
# ---------------------------------------------------------------------------

def _broken_plan():
    vals = _values("v0", a=BIGINT)
    return P.ProjectNode("p0", vals, {V("x", BIGINT): V("ghost", BIGINT)})


def test_validate_plan_raises_plan_validation_error():
    with pytest.raises(PlanValidationError) as ei:
        validate_plan(_broken_plan(), "post-plan")
    assert ei.value.diagnostics
    assert "[PLAN_VALIDATION]" in str(ei.value)


def test_validation_mode_off_silences():
    with use_validation_mode(VALIDATION_OFF):
        assert validation_mode() == VALIDATION_OFF
        validate_plan(_broken_plan(), "post-plan")  # no raise
    assert validation_mode() == "on"


def test_validation_mode_rejects_unknown():
    with pytest.raises(ValueError):
        with use_validation_mode("loud"):
            pass


def test_plan_validation_is_not_retryable():
    """Satellite: a malformed plan re-plans identically on retry, so the
    dispatcher's retry gate must fail fast (contrast EXTERNAL)."""
    assert not is_retryable_type(PLAN_VALIDATION)
    assert not is_retryable(PlanValidationError("bad plan"))
    # the tag survives string-typed failure chains across the HTTP hop
    assert parse_error_type(
        "task q.0.0 failed [PLAN_VALIDATION]: bad plan") == PLAN_VALIDATION


def test_strict_mode_validates_each_rule_firing():
    """strict validates the replacement subtree after every iterative
    rule firing; a healthy plan passes all of them."""
    with use_validation_mode("strict"):
        root = Planner("sf0.01", "tpch").plan(TPCH_QUERIES[3])
    assert check_plan(root) == []


def test_session_property_controls_validation():
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.worker.protocol import apply_session_properties
    cfg = apply_session_properties(ExecutionConfig(),
                                   {"plan_validation": "strict"})
    assert cfg.plan_validation == "strict"
    with pytest.raises(ValueError):
        apply_session_properties(ExecutionConfig(),
                                 {"plan_validation": "shouty"})


def test_config_property_controls_validation():
    from presto_tpu.worker.properties import execution_config_from_properties
    cfg = execution_config_from_properties({"task.plan-validation": "off"})
    assert cfg.plan_validation == "off"
    with pytest.raises(ValueError):
        execution_config_from_properties({"task.plan-validation": "nope"})


def test_explain_type_validate_surface():
    from presto_tpu.exec.runner import LocalQueryRunner
    r = LocalQueryRunner("sf0.01")
    res = r.execute("EXPLAIN (TYPE VALIDATE) "
                    "SELECT count(*) FROM lineitem WHERE l_quantity < 10")
    text = res.rows[0][0]
    for stage in ("post-plan", "post-optimize", "post-fragment"):
        assert f"== {stage} ==" in text
    assert "plan validation PASSED" in text


def test_explain_type_validate_rejects_bad_type():
    from presto_tpu.sql.parser import parse_sql
    with pytest.raises(Exception):
        parse_sql("EXPLAIN (TYPE SIDEWAYS) SELECT 1")


# ---------------------------------------------------------------------------
# SCAN_PUSHDOWN: a scan's pushed-down predicates must re-derive from its
# direct parent filter (storage/pushdown.py skips chunks on their word)
# ---------------------------------------------------------------------------

def _pushdown_plan(pushdown, predicate=None, with_filter=True):
    from presto_tpu.spi.expr import call
    v = V("l_orderkey_0", BIGINT)
    scan = P.TableScanNode(
        "s0", P.TableHandle("tpch", "tpch", "lineitem",
                            (("scaleFactor", 0.01),)),
        [v], {v: P.ColumnHandle("orderkey", BIGINT)}, list(pushdown))
    if not with_filter:
        return P.OutputNode("o0", scan, ["l_orderkey"], [v])
    if predicate is None:
        predicate = call("lt", BOOLEAN, v, ConstantExpression(5, BIGINT))
    filt = P.FilterNode("f0", scan, predicate)
    return P.OutputNode("o0", filt, ["l_orderkey"], [v])


def test_scan_pushdown_valid_claim_passes():
    out = _pushdown_plan([{"column": "orderkey", "op": "lt", "value": 5}])
    assert check_plan(out) == []


def test_scan_pushdown_fires_on_bad_op():
    out = _pushdown_plan([{"column": "orderkey", "op": "neq", "value": 5}])
    diags = check_plan(out)
    assert CHECK_SCAN_PUSHDOWN in _codes(diags)
    assert any("neq" in d.message for d in diags)


def test_scan_pushdown_fires_on_unassigned_column():
    out = _pushdown_plan([{"column": "shipdate", "op": "lt", "value": 5}])
    diags = check_plan(out)
    assert CHECK_SCAN_PUSHDOWN in _codes(diags)
    assert any("does not assign" in d.message for d in diags)


def test_scan_pushdown_fires_on_non_numeric_literal():
    out = _pushdown_plan([{"column": "orderkey", "op": "lt", "value": "x"}])
    diags = check_plan(out)
    assert CHECK_SCAN_PUSHDOWN in _codes(diags)
    assert any("non-numeric" in d.message for d in diags)


def test_scan_pushdown_fires_without_parent_filter():
    out = _pushdown_plan([{"column": "orderkey", "op": "lt", "value": 5}],
                         with_filter=False)
    diags = check_plan(out)
    assert CHECK_SCAN_PUSHDOWN in _codes(diags)
    assert any("not a Filter" in d.message for d in diags)


def test_scan_pushdown_fires_when_not_derivable_from_filter():
    # the filter says > 5; a claimed < 5 pushdown would skip chunks the
    # residual filter still wants
    from presto_tpu.spi.expr import call
    v = V("l_orderkey_0", BIGINT)
    pred = call("gt", BOOLEAN, v, ConstantExpression(5, BIGINT))
    out = _pushdown_plan([{"column": "orderkey", "op": "lt", "value": 5}],
                         predicate=pred)
    diags = check_plan(out)
    assert CHECK_SCAN_PUSHDOWN in _codes(diags)
    assert any("does not appear" in d.message for d in diags)


def test_optimizer_populates_pushdown_that_validates():
    """plan_scan_pushdown's own output must satisfy the checker, and the
    VALIDATE explain must surface the per-scan decisions."""
    from presto_tpu.exec.runner import LocalQueryRunner
    r = LocalQueryRunner("sf0.01")
    res = r.execute(
        "EXPLAIN (TYPE VALIDATE) SELECT count(*) FROM lineitem "
        "WHERE l_orderkey < 40 AND l_shipdate >= DATE '1994-01-01'")
    text = res.rows[0][0]
    assert "plan validation PASSED" in text
    assert "== scan-pushdown ==" in text
    assert "orderkey lt 40" in text
    assert "shipdate gte 8766" in text     # epoch days, column units


# ---------------------------------------------------------------------------
# suite conformance: zero diagnostics at all three stages
# ---------------------------------------------------------------------------

def _assert_all_stages_clean(sql, schema, catalog):
    planner = Planner(schema, catalog)
    from presto_tpu.sql.optimizer import optimize
    import presto_tpu.sql.parser as A
    node, names, out_vars = planner.plan_query_any(A.parse_sql(sql))
    out = P.OutputNode(planner.new_id("output"), node, names, out_vars)
    for stage, root in (("post-plan", out), ("post-optimize", None)):
        if root is None:
            out = optimize(out)
            root = out
        diags = check_plan(root, stage)
        assert diags == [], "\n".join(str(d) for d in diags)
    sub = plan_distributed(out)
    diags = check_subplan(sub, "post-fragment")
    assert diags == [], "\n".join(str(d) for d in diags)


@pytest.mark.parametrize("qid", sorted(TPCH_QUERIES))
def test_tpch_suite_plans_validate(qid):
    _assert_all_stages_clean(TPCH_QUERIES[qid], "sf0.01", "tpch")


@pytest.mark.parametrize("qid", sorted(TPCDS_QUERIES))
def test_tpcds_suite_plans_validate(qid):
    _assert_all_stages_clean(TPCDS_QUERIES[qid], "sf0.01", "tpcds")
