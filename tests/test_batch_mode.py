"""Batch-mode execution — the Presto-on-Spark analog (SURVEY.md §2.7:
PrestoSparkQueryExecutionFactory.java:164, PrestoSparkRunner.java:55) and
recoverable execution (RECOVERABLE_GROUPED_EXECUTION,
SystemSessionProperties.java:106,493): materialized inter-stage shuffle
files + per-task retry from durable inputs."""
import os

import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import (BatchQueryRunner, LocalQueryRunner,
                                    _assert_rows_equal)

Q_JOIN_AGG = """
select o_orderstatus, count(*) c, sum(l_quantity) q
from lineitem join orders on l_orderkey = o_orderkey
where l_shipdate > date '1995-03-15'
group by o_orderstatus
"""


@pytest.fixture(scope="module")
def cfg():
    return ExecutionConfig(batch_rows=1 << 13, join_out_capacity=1 << 15)


def test_batch_mode_parity(cfg):
    batch = BatchQueryRunner("sf0.01", config=cfg, n_tasks=2)
    local = LocalQueryRunner("sf0.01", config=cfg)
    got = batch.execute(Q_JOIN_AGG)
    exp = local.execute_reference(Q_JOIN_AGG)
    _assert_rows_equal(got, exp, False)


def test_batch_mode_materializes_shuffle_files(cfg, tmp_path):
    batch = BatchQueryRunner("sf0.01", config=cfg, n_tasks=2,
                             temp_dir=str(tmp_path))
    got = batch.execute(Q_JOIN_AGG)
    assert got.rows
    shuffle_files = [os.path.join(r, f)
                     for r, _d, fs in os.walk(tmp_path)
                     for f in fs if f.endswith(".shuffle")]
    # every non-root stage spilled its exchange durably
    assert len(shuffle_files) >= 2
    assert any(os.path.getsize(f) > 0 for f in shuffle_files)


def test_task_failure_retries_from_materialized_inputs(cfg):
    """Inject one failure into a mid-plan task attempt: the task must
    re-run from the already-materialized child shuffle and the query
    result stay exact (the reference's ErrorClassifier retryable path)."""
    failures = []

    def inject(fragment_id, task_index, attempt):
        # fail the FIRST attempt of one mid-stage task, exactly once
        if attempt == 0 and task_index == 0 and fragment_id != "0" \
                and not failures:
            failures.append((fragment_id, task_index))
            raise RuntimeError("injected executor loss")

    batch = BatchQueryRunner("sf0.01", config=cfg, n_tasks=2,
                             task_retries=2, fault_injector=inject)
    local = LocalQueryRunner("sf0.01", config=cfg)
    got = batch.execute(Q_JOIN_AGG)
    assert failures, "the injector never fired"
    _assert_rows_equal(got, local.execute_reference(Q_JOIN_AGG), False)


def test_retries_exhausted_fails_query(cfg):
    def always_fail(fragment_id, task_index, attempt):
        raise RuntimeError("permanent task failure")

    batch = BatchQueryRunner("sf0.01", config=cfg, n_tasks=2,
                             task_retries=1, fault_injector=always_fail)
    with pytest.raises(RuntimeError, match="permanent task failure"):
        batch.execute("select count(*) from nation")


def test_retry_does_not_duplicate_rows(cfg):
    """A failed attempt that already buffered output must not double rows
    after retry (OutputBuffers.reset_task)."""
    calls = {}

    def inject(fragment_id, task_index, attempt):
        # fail every task's first attempt
        key = (fragment_id, task_index)
        if calls.setdefault(key, 0) == 0:
            calls[key] = 1
            raise RuntimeError("flaky")

    batch = BatchQueryRunner("sf0.01", config=cfg, n_tasks=2,
                             task_retries=3, fault_injector=inject)
    local = LocalQueryRunner("sf0.01", config=cfg)
    got = batch.execute("select count(*) c, sum(n_nationkey) s from nation")
    _assert_rows_equal(
        got, local.execute_reference(
            "select count(*) c, sum(n_nationkey) s from nation"), False)
