"""Memory / blackhole / system-table connectors (SURVEY §2.8 utility
connectors: presto-memory MemoryPagesStore, presto-blackhole, and the
system runtime tables presto-main-base/.../connector/system/)."""
import pytest

from presto_tpu.connectors import catalog
from presto_tpu.connectors.memory import BlackholeConnector, MemoryConnector
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner


@pytest.fixture()
def runner():
    catalog.register_connector("memory", MemoryConnector())
    catalog.register_connector("blackhole", BlackholeConnector())
    try:
        yield LocalQueryRunner("sf0.01", config=ExecutionConfig(
            batch_rows=1 << 13, join_out_capacity=1 << 15))
    finally:
        catalog.unregister_connector("memory")
        catalog.unregister_connector("blackhole")


def test_memory_ctas_round_trip(runner):
    runner.execute("CREATE TABLE mem_orders AS "
                   "SELECT orderkey, totalprice, orderpriority, orderdate "
                   "FROM orders WHERE orderkey < 200")
    got = runner.execute(
        "SELECT count(*), sum(totalprice) FROM mem_orders")
    want = runner.execute(
        "SELECT count(*), sum(totalprice) FROM orders WHERE orderkey < 200")
    assert got.rows == want.rows
    # joins against generated tables work too
    j = runner.execute(
        "SELECT count(*) FROM mem_orders m JOIN orders o "
        "ON m.orderkey = o.orderkey")
    assert j.rows[0][0] == want.rows[0][0]


def test_memory_insert_appends(runner):
    runner.execute("CREATE TABLE mem_t AS "
                   "SELECT orderkey FROM orders WHERE orderkey < 100")
    before = runner.execute("SELECT count(*) FROM mem_t").rows[0][0]
    runner.execute("INSERT INTO mem_t "
                   "SELECT orderkey FROM orders WHERE orderkey < 100")
    after = runner.execute("SELECT count(*) FROM mem_t").rows[0][0]
    assert after == 2 * before > 0
    runner.execute("DROP TABLE mem_t")
    with pytest.raises(Exception):
        runner.execute("SELECT count(*) FROM mem_t")


def test_memory_nulls_and_strings(runner):
    runner.execute("CREATE TABLE mem_c AS "
                   "SELECT clerk, CASE WHEN orderkey % 3 = 0 THEN NULL "
                   "ELSE totalprice END AS tp "
                   "FROM orders WHERE orderkey < 300")
    got = runner.execute("SELECT count(*), count(tp), count(DISTINCT clerk)"
                         " FROM mem_c")
    want = runner.execute(
        "SELECT count(*), count(CASE WHEN orderkey % 3 = 0 THEN NULL "
        "ELSE totalprice END), count(DISTINCT clerk) "
        "FROM orders WHERE orderkey < 300")
    assert got.rows == want.rows


def test_system_runtime_tables():
    from presto_tpu.worker.server import WorkerServer
    from presto_tpu.client import StatementClient
    s = WorkerServer(coordinator=True)   # serves from its own thread
    try:
        c = StatementClient(s.uri, schema="sf0.01")
        c.execute("SELECT 1")
        r = c.execute("SELECT node_id, coordinator, state "
                      "FROM runtime_nodes")
        assert any(row[0] == s.node_id and row[1] for row in r.rows)
        r = c.execute("SELECT query_id, state FROM runtime_queries")
        assert len(r.rows) >= 1          # includes at least the SELECT 1
        assert all(row[1] in ("QUEUED", "RUNNING", "FINISHED", "FAILED",
                              "CANCELED") for row in r.rows)
    finally:
        s.close()
