"""RuntimeStats metric map + tracer SPI (§5.1 analog: RuntimeStats.java,
TracerProviderManager/SimpleTracer) and their flow through the runner and
the statement protocol's query info."""
import json
import urllib.request

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner
from presto_tpu.utils.runtime_stats import (Metric, RuntimeStats,
                                            SimpleTracer, TracerProvider)


def test_metric_merge():
    a, b = RuntimeStats(), RuntimeStats()
    a.add("x", 5)
    b.add("x", 7)
    b.add("y", 1)
    a.merge(b)
    m = a.get("x")
    assert m.sum == 12 and m.count == 2 and m.min == 5 and m.max == 7
    assert a.get("y").sum == 1


def test_record_wall():
    s = RuntimeStats()
    with s.record_wall("phase"):
        pass
    m = s.get("phaseWallNanos")
    assert m is not None and m.count == 1 and m.sum >= 0


def test_runner_records_phases():
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13))
    res = r.execute("SELECT count(*) c FROM orders")
    assert "queryParseWallNanos" in res.runtime_stats
    assert "queryExecuteWallNanos" in res.runtime_stats
    # first run plans; cached re-run may skip the plan phase
    assert "queryPlanWallNanos" in res.runtime_stats


def test_simple_tracer_through_runner():
    tp = TracerProvider("simple")
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13), tracer_provider=tp)
    sql = "SELECT count(*) c FROM orders"
    r.execute(sql)
    trace = tp.get_trace(sql)
    assert isinstance(trace, SimpleTracer)
    anns = trace.annotations()
    assert anns[0] == "query parsed"
    assert anns[-1] == "query finished"


def test_runtime_stats_in_query_info():
    from presto_tpu.client import StatementClient
    from presto_tpu.worker import WorkerServer
    server = WorkerServer(coordinator=True, environment="test",
                          config=ExecutionConfig(batch_rows=1 << 13))
    try:
        c = StatementClient(server.uri, schema="sf0.01")
        r = c.execute("SELECT count(*) c FROM orders")
        with urllib.request.urlopen(
                f"{server.uri}/v1/query/{r.query_id}") as resp:
            info = json.loads(resp.read())
        assert "runtimeStats" in info
        assert "queryExecuteWallNanos" in info["runtimeStats"]
    finally:
        server.close()


def test_grouped_bucket_walls_exposed():
    """Grouped execution reports per-bucket generation and compute walls
    plus the whole-run wall, keyed by lifespan count."""
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.exec.runner import LocalQueryRunner
    r = LocalQueryRunner("sf0.01",
                         config=ExecutionConfig(grouped_lifespans=4))
    res = r.execute(
        "select l_orderkey, sum(l_quantity) q from lineitem "
        "group by l_orderkey order by q desc limit 5")
    stats = res.runtime_stats
    assert stats["groupedBucketGenWallNanos"]["count"] == 4
    assert stats["groupedBucketComputeWallNanos"]["count"] == 4
    assert stats["groupedBucketGenWallNanos"]["sum"] > 0
    assert stats["groupedBucketComputeWallNanos"]["sum"] > 0
    assert stats["groupedRunWallNanos"]["count"] == 1
    assert stats["groupedRunWallNanos"]["sum"] >= \
        stats["groupedBucketComputeWallNanos"]["sum"]
