"""Internal-communication JWT auth (VERDICT r3 missing #6, TLS/JWT half:
reference InternalAuthenticationFilter.cpp decision table, HS256 over
SHA256(shared secret), X-Presto-Internal-Bearer header)."""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from presto_tpu.worker import auth
from presto_tpu.worker.server import WorkerServer


@pytest.fixture(autouse=True)
def _reset_process_auth():
    yield
    auth.set_process_auth(auth._DISABLED)


def test_jwt_round_trip_and_claims():
    tok = auth.jwt_encode("secret", "node-1", 60)
    claims = auth.jwt_verify(tok, "secret")
    assert claims["sub"] == "node-1"
    assert claims["exp"] > time.time()


def test_jwt_rejects_bad_signature_and_expiry():
    tok = auth.jwt_encode("secret", "node-1", 60)
    with pytest.raises(auth.AuthError, match="signature"):
        auth.jwt_verify(tok, "other-secret")
    old = auth.jwt_encode("secret", "node-1", -10)
    with pytest.raises(auth.AuthError, match="expired"):
        auth.jwt_verify(old, "secret")
    # empty subject is rejected (reference :147-152)
    import base64
    h, p, s = auth.jwt_encode("secret", "x", 60).split(".")
    import hashlib, hmac
    payload = base64.urlsafe_b64encode(
        json.dumps({"sub": "", "exp": time.time() + 60}).encode()
    ).rstrip(b"=").decode()
    sig = base64.urlsafe_b64encode(hmac.new(
        hashlib.sha256(b"secret").digest(),
        f"{h}.{payload}".encode(), hashlib.sha256).digest()
    ).rstrip(b"=").decode()
    with pytest.raises(auth.AuthError, match="subject"):
        auth.jwt_verify(f"{h}.{payload}.{sig}", "secret")


def test_signing_key_is_sha256_of_secret():
    # the reference derives the HS256 key as SHA256(secret), not the raw
    # secret (InternalAuthenticationFilter.cpp:133-144)
    import hashlib
    assert auth._signing_key("abc") == hashlib.sha256(b"abc").digest()


def _get(url, token=None):
    headers = {}
    if token is not None:
        headers[auth.BEARER_HEADER] = token
    return urllib.request.urlopen(
        urllib.request.Request(url, headers=headers), timeout=10)


def test_worker_enforces_reference_decision_table():
    w = WorkerServer(jwt_enabled=True, jwt_secret="cluster-secret")
    threading.Thread(target=w.httpd.serve_forever, daemon=True).start()
    try:
        # token absent, enabled -> 401 (internal route)
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{w.uri}/v1/task/x.0.0.0.0/status")
        assert e.value.code == 401
        # bad token -> 401
        bad = auth.jwt_encode("wrong-secret", "n")
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{w.uri}/v1/task/x.0.0.0.0/status", bad)
        assert e.value.code == 401
        # valid token -> routed (404: unknown task, but PAST the filter)
        ok = auth.jwt_encode("cluster-secret", "coordinator-1")
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{w.uri}/v1/task/x.0.0.0.0/status", ok)
        assert e.value.code == 404
        # client-facing endpoints stay reachable WITHOUT a token
        assert json.load(_get(f"{w.uri}/v1/info"))["environment"]
    finally:
        w.shutdown()


def test_worker_rejects_token_when_disabled():
    # misconfiguration surface: token present but JWT disabled -> 401
    w = WorkerServer()
    threading.Thread(target=w.httpd.serve_forever, daemon=True).start()
    try:
        tok = auth.jwt_encode("whatever", "n")
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{w.uri}/v1/task/x.0.0.0.0/status", tok)
        assert e.value.code == 401
        # and no token passes (404: past the filter, unknown task)
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{w.uri}/v1/task/x.0.0.0.0/status")
        assert e.value.code == 404
    finally:
        w.shutdown()


def test_etc_config_maps_jwt_keys(tmp_path):
    from presto_tpu.worker.properties import server_kwargs_from_etc
    etc = tmp_path / "etc"
    etc.mkdir()
    (etc / "config.properties").write_text(
        "internal-communication.jwt.enabled=true\n"
        "internal-communication.shared-secret=s3cret\n"
        "internal-communication.jwt.expiration-seconds=120\n")
    kwargs, _ = server_kwargs_from_etc(str(etc))
    assert kwargs["jwt_enabled"] is True
    assert kwargs["jwt_secret"] == "s3cret"
    assert kwargs["jwt_expiration_s"] == 120


def test_jwt_enabled_cluster_runs_distributed_query():
    """A fully JWT-enabled cluster (coordinator + workers sharing the
    secret) schedules and completes a distributed query: every internal
    call — announcements, task updates, status long-polls, exchange
    pulls — carries and validates bearers."""
    from presto_tpu.worker import HttpQueryRunner

    secret = "cluster-secret-42"
    coordinator = WorkerServer(coordinator=True, environment="test",
                               jwt_enabled=True, jwt_secret=secret)
    workers = [WorkerServer(discovery_uri=coordinator.uri,
                            jwt_enabled=True, jwt_secret=secret)
               for _ in range(2)]
    threads = [threading.Thread(target=s.httpd.serve_forever, daemon=True)
               for s in [coordinator] + workers]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 20
        while len(coordinator.worker_uris()) < 2 \
                and time.time() < deadline:
            time.sleep(0.05)
        assert len(coordinator.worker_uris()) == 2, \
            "announcements rejected by the JWT filter"
        runner = HttpQueryRunner([w.uri for w in workers], "sf0.01",
                                 n_tasks=2)
        res = runner.execute("SELECT count(*) FROM nation")
        assert res.rows == [[25]]
    finally:
        for s in [coordinator] + workers:
            s.shutdown()


# ---------------------------------------------------------------------------
# TLS listener (reference https-cert-path / https-key-path)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tls_cert(tmp_path_factory):
    import subprocess
    d = tmp_path_factory.mktemp("tls")
    cert, key = str(d / "node.crt"), str(d / "node.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "1",
         "-subj", "/CN=127.0.0.1"], check=True, capture_output=True)
    return cert, key


def test_https_worker_end_to_end(tls_cert):
    """Worker on TLS: announcements/status/results ride HTTPS with the
    internal trust anchor; plain HTTP clients cannot connect."""
    import ssl
    cert, key = tls_cert
    w = WorkerServer(https_cert_path=cert, https_key_path=key)
    threading.Thread(target=w.httpd.serve_forever, daemon=True).start()
    try:
        assert w.uri.startswith("https://")
        ctx = ssl.create_default_context(cafile=cert)
        ctx.check_hostname = False
        info = json.load(urllib.request.urlopen(
            f"{w.uri}/v1/info", timeout=10, context=ctx))
        assert info["environment"] == "test"
        # untrusting client is refused by the TLS handshake
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(
                f"{w.uri}/v1/info", timeout=10,
                context=ssl.create_default_context())
    finally:
        w.shutdown()


def test_https_with_jwt_combined(tls_cert):
    """TLS transport + JWT authentication together (the reference's full
    internal-communication posture)."""
    import ssl
    cert, key = tls_cert
    w = WorkerServer(https_cert_path=cert, https_key_path=key,
                     jwt_enabled=True, jwt_secret="s")
    threading.Thread(target=w.httpd.serve_forever, daemon=True).start()
    try:
        ctx = ssl.create_default_context(cafile=cert)
        ctx.check_hostname = False
        tok = auth.jwt_encode("s", "peer")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                f"{w.uri}/v1/task/x.0.0.0.0/status"),
                timeout=10, context=ctx)
        assert e.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                f"{w.uri}/v1/task/x.0.0.0.0/status",
                headers={auth.BEARER_HEADER: tok}),
                timeout=10, context=ctx)
        assert e.value.code == 404          # past the filter
    finally:
        w.shutdown()


def test_etc_config_maps_https_keys(tmp_path, tls_cert):
    from presto_tpu.worker.properties import server_kwargs_from_etc
    cert, key = tls_cert
    etc = tmp_path / "etc"
    etc.mkdir()
    (etc / "config.properties").write_text(
        f"http-server.https.enabled=true\n"
        f"https-cert-path={cert}\n"
        f"https-key-path={key}\n")
    kwargs, _ = server_kwargs_from_etc(str(etc))
    assert kwargs["https_cert_path"] == cert
    assert kwargs["https_key_path"] == key


def test_shutdown_endpoint_requires_auth_when_enabled():
    """PUT /v1/info/state is state-mutating: it must sit behind the
    internal filter, or anyone can drain a JWT-protected worker."""
    w = WorkerServer(jwt_enabled=True, jwt_secret="s")
    threading.Thread(target=w.httpd.serve_forever, daemon=True).start()
    try:
        req = urllib.request.Request(
            f"{w.uri}/v1/info/state", data=b'"SHUTTING_DOWN"',
            method="PUT", headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 401
        assert w.state == "ACTIVE"
    finally:
        w.shutdown()
