"""Exchange fabric selection as a first-class planner/scheduler concern
(parallel/fabric.py resolve_fabric; scheduler._plan_fabrics;
fragmenter.annotate_exchange_fabrics): `exchange.fabric = auto|http|ici`
picks per-edge between the HTTP page shuffle and the chunked ICI
all_to_all, EXPLAIN and the EXCHANGE_FABRIC validation check surface the
choice, and FABRIC_METRICS reports per-fabric bytes/walls/overlap.

Mesh-backed tests run on the 8-device virtual CPU mesh
(tests/conftest.py sets xla_force_host_platform_device_count=8); the
end-to-end 8-task executions carry @pytest.mark.slow (the marker
test_grouped / test_tpcds use for heavy runs) so the smoke tier keeps
its time budget — `pytest tests/test_exchange_fabric.py` runs them all.
"""
import jax
import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import (DistributedQueryRunner,
                                    LocalQueryRunner, _assert_rows_equal)
from presto_tpu.parallel.fabric import (FABRIC_HTTP, FABRIC_ICI,
                                        FABRIC_METRICS, resolve_fabric)

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

GROUPBY = """
SELECT o.custkey, count(*) AS c, sum(o.totalprice) AS s
FROM orders o GROUP BY o.custkey
"""

Q3 = """
SELECT l.orderkey, sum(l.extendedprice * (1 - l.discount)) AS revenue,
       o.orderdate, o.shippriority
FROM customer c, orders o, lineitem l
WHERE c.mktsegment = 'BUILDING' AND c.custkey = o.custkey
  AND l.orderkey = o.orderkey
  AND o.orderdate < DATE '1995-03-15' AND l.shipdate > DATE '1995-03-15'
GROUP BY l.orderkey, o.orderdate, o.shippriority
ORDER BY revenue DESC, o.orderdate
LIMIT 10
"""


def make_mesh():
    from presto_tpu.parallel.mesh import WORKER_AXIS
    return jax.sharding.Mesh(jax.devices()[:8], (WORKER_AXIS,))


def _runner(fabric="auto", mesh="default", n_tasks=8, **cfg_kw):
    cfg = ExecutionConfig(batch_rows=1 << 13, join_out_capacity=1 << 15,
                          exchange_fabric=fabric, **cfg_kw)
    m = make_mesh() if mesh == "default" else mesh
    return DistributedQueryRunner("sf0.01", config=cfg, n_tasks=n_tasks,
                                  mesh=m)


def _local():
    return LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13, join_out_capacity=1 << 15))


_GROUPBY_EXP = []


def groupby_expected():
    """GROUPBY through the local engine + numpy oracle, computed once
    for the whole module (four tests compare against it)."""
    if not _GROUPBY_EXP:
        _GROUPBY_EXP.append(
            _local().assert_same_as_reference(GROUPBY, ordered=False))
    return _GROUPBY_EXP[0]


class _IciSpy:
    """Counts _ici_exchange engagements (device path actually taken)."""

    def __init__(self):
        self.engaged = 0
        self.called = 0

    def __enter__(self):
        from presto_tpu.exec import scheduler as S
        self._S, self._orig = S, S.InProcessScheduler._ici_exchange
        spy = self

        def wrapper(sched, stage, task_batches, keys):
            spy.called += 1
            ok = spy._orig(sched, stage, task_batches, keys)
            if ok and stage.device_out is not None:
                spy.engaged += 1
            return ok
        S.InProcessScheduler._ici_exchange = wrapper
        return self

    def __exit__(self, *exc):
        self._S.InProcessScheduler._ici_exchange = self._orig


# ---------------------------------------------------------------------------
# resolve_fabric: the shared decision table
# ---------------------------------------------------------------------------

def test_resolve_fabric_decision_table():
    def r(req="auto", handle="FIXED_HASH", prod="SOURCE",
          cons="FIXED_HASH", mesh=8, batch=False):
        return resolve_fabric(req, handle=handle,
                              producer_partitioning=prod,
                              consumer_partitioning=cons,
                              mesh_size=mesh, batch_mode=batch)

    assert r() == (FABRIC_ICI, "mesh-eligible hash edge")
    assert r(req="ici")[0] == FABRIC_ICI
    assert r(req="http") == (FABRIC_HTTP, "requested")
    # None == auto (un-annotated edge resolved from config default)
    assert r(req=None)[0] == FABRIC_ICI
    # ineligibility demotes even an explicit ici request, with a reason
    for kw in ({"handle": "SINGLE"}, {"handle": "FIXED_BROADCAST"},
               {"mesh": 0}, {"mesh": 1}, {"batch": True},
               {"prod": "SINGLE"}, {"cons": "SINGLE"}):
        fabric, why = r(req="ici", **kw)
        assert fabric == FABRIC_HTTP, kw
        assert why and why != "requested", kw


# ---------------------------------------------------------------------------
# scheduler fabric planning (mesh-backed)
# ---------------------------------------------------------------------------

@needs_mesh
@pytest.mark.slow
def test_auto_selection_chooses_mesh_task_count():
    """With a 3-task runner over an 8-device mesh, _plan_fabrics must
    CHOOSE 8 tasks for the eligible hashed edge (the generalization over
    the old n_tasks == mesh_size accident) and the exchange must ride
    the mesh."""
    with _IciSpy() as spy:
        got = _runner(n_tasks=3).execute(GROUPBY)
    _assert_rows_equal(got, groupby_expected(), ordered=False)
    assert spy.engaged >= 1, "ICI fabric never engaged"


@needs_mesh
@pytest.mark.slow
def test_forced_http_disables_ici():
    with _IciSpy() as spy:
        got = _runner(fabric="http").execute(GROUPBY)
    _assert_rows_equal(got, groupby_expected(), ordered=False)
    assert spy.called == 0, "forced http still took the device path"


def test_forced_ici_without_mesh_falls_back():
    """exchange.fabric=ici with no mesh degrades gracefully to the page
    shuffle (resolve_fabric: 'no mesh') instead of failing the query."""
    with _IciSpy() as spy:
        got = _runner(fabric="ici", mesh=None, n_tasks=2).execute(GROUPBY)
    _assert_rows_equal(got, groupby_expected(), ordered=False)
    assert spy.called == 0


@needs_mesh
@pytest.mark.slow
def test_fabric_differential_stats():
    """Both fabrics agree on rows; ici moves device bytes with ZERO
    host bytes and reports a sane chunked overlap fraction, http meters
    its page bytes — the xchg-bench comparison in miniature."""
    FABRIC_METRICS.reset()
    got_ici = _runner().execute(Q3)
    fi = FABRIC_METRICS.snapshot()["ici"]

    FABRIC_METRICS.reset()
    got_http = _runner(fabric="http").execute(Q3)
    fh = FABRIC_METRICS.snapshot()["http"]

    _assert_rows_equal(got_ici, got_http, ordered=True)
    assert fi["exchanges"] >= 1 and fi["chunks"] >= 1
    assert fi["bytes_moved"] > 0
    assert fi["host_bytes"] == 0, "ici fabric staged bytes through host"
    assert 0.0 <= fi["overlap_fraction"] <= 1.0
    assert fh["exchanges"] >= 1 and fh["bytes_moved"] > 0
    assert fh["host_bytes"] == fh["bytes_moved"]
    # stats parity: the same counters ride QueryResult.runtime_stats
    rs = got_ici.runtime_stats
    assert rs.get("exchangeFabricIciBytes", {}).get("sum", 0) > 0
    assert "exchangeFabricIciChunks" in rs


@needs_mesh
@pytest.mark.slow
def test_metadata_mismatch_falls_back_to_pages():
    """When per-task batch metadata disagrees with what the exchange
    kernel can carry, the stage demotes to the page fabric at runtime:
    correct rows, fallback metered."""
    from presto_tpu.exec import scheduler as S
    orig = S._batch_meta
    S._batch_meta = lambda b: object()   # never equal across calls
    FABRIC_METRICS.reset()
    try:
        with _IciSpy() as spy:
            got = _runner().execute(GROUPBY)
    finally:
        S._batch_meta = orig
    _assert_rows_equal(got, groupby_expected(), ordered=False)
    assert spy.called >= 1 and spy.engaged == 0
    assert FABRIC_METRICS.snapshot()["ici"]["fallbacks"] >= 1
    assert got.runtime_stats.get(
        "exchangeFabricIciFallbacks", {}).get("sum", 0) >= 1


@needs_mesh
@pytest.mark.slow
def test_failed_sibling_aborts_ici_stage():
    """A terminally-failing task stops its stage before the collective:
    the query raises and the ICI exchange is never dispatched with a
    missing sibling (which would hang or ship garbage)."""

    class Boom(RuntimeError):
        pass

    def inject(fragment_id, task_index, attempt):
        if task_index == 1:
            raise Boom(f"injected failure in fragment {fragment_id}")

    class FaultyRunner(DistributedQueryRunner):
        def _scheduler_config(self):
            cfg = super()._scheduler_config()
            cfg.fault_injector = inject
            return cfg

    runner = FaultyRunner(
        "sf0.01", config=ExecutionConfig(batch_rows=1 << 13,
                                         join_out_capacity=1 << 15),
        n_tasks=8, mesh=make_mesh())
    with _IciSpy() as spy:
        with pytest.raises(Boom):
            runner.execute(GROUPBY)
    assert spy.engaged == 0, "ICI exchange ran despite a failed sibling"


# ---------------------------------------------------------------------------
# EXPLAIN + validation surface
# ---------------------------------------------------------------------------

@needs_mesh
def test_explain_shows_chosen_fabric():
    text = _runner().execute("EXPLAIN " + GROUPBY).rows[0][0]
    assert "fabric=ici" in text, text
    text = _runner(fabric="http").execute("EXPLAIN " + GROUPBY).rows[0][0]
    assert "fabric=http" in text and "fabric=ici" not in text, text


def test_explain_no_mesh_is_all_http():
    text = DistributedQueryRunner("sf0.01", n_tasks=2) \
        .execute("EXPLAIN " + GROUPBY).rows[0][0]
    assert "fabric=ici" not in text, text


def test_validate_check_flags_bad_fabric_annotations():
    from presto_tpu.analysis.checker import (CHECK_EXCHANGE_FABRIC,
                                             check_subplan)
    from presto_tpu.common.types import BigintType
    from presto_tpu.spi import plan as P
    from presto_tpu.spi.expr import VariableReferenceExpression as V

    v = V("a", BigintType())

    def subplan_with(fabric, handle=P.FIXED_HASH_DISTRIBUTION,
                     producer=P.SOURCE_DISTRIBUTION,
                     consumer=P.FIXED_HASH_DISTRIBUTION):
        child_root = P.ValuesNode("v0", [v])
        scheme = P.PartitioningScheme(handle, [v] if
                                      handle == P.FIXED_HASH_DISTRIBUTION
                                      else [], [v])
        scheme.fabric = fabric
        child = P.SubPlan(P.PlanFragment("1", child_root, producer,
                                         scheme), [])
        remote = P.RemoteSourceNode("r0", ["1"], [v])
        root = P.PlanFragment(
            "0", remote, consumer,
            P.PartitioningScheme(P.SINGLE_DISTRIBUTION, [], [v]))
        return P.SubPlan(root, [child])

    def codes(sub):
        return {d.code for d in check_subplan(sub)}

    # well-formed annotations pass
    assert CHECK_EXCHANGE_FABRIC not in codes(subplan_with("http"))
    assert CHECK_EXCHANGE_FABRIC not in codes(subplan_with(None))
    assert CHECK_EXCHANGE_FABRIC not in codes(subplan_with("ici"))
    # unresolved / unknown fabric must not reach execution
    assert CHECK_EXCHANGE_FABRIC in codes(subplan_with("auto"))
    assert CHECK_EXCHANGE_FABRIC in codes(subplan_with("warp"))
    # ici on a non-hash edge
    assert CHECK_EXCHANGE_FABRIC in codes(
        subplan_with("ici", handle=P.SINGLE_DISTRIBUTION))
    # ici endpoints must be multi-taskable
    assert CHECK_EXCHANGE_FABRIC in codes(
        subplan_with("ici", producer=P.SINGLE_DISTRIBUTION))
    assert CHECK_EXCHANGE_FABRIC in codes(
        subplan_with("ici", consumer=P.SINGLE_DISTRIBUTION))


@needs_mesh
def test_explain_validate_accepts_annotated_plan():
    """EXPLAIN (TYPE VALIDATE) runs the EXCHANGE_FABRIC check over the
    fabric-annotated fragmented plan and reports no diagnostics for a
    plan the runner itself produced."""
    text = _runner().execute("EXPLAIN (TYPE VALIDATE) " + GROUPBY) \
        .rows[0][0]
    assert "EXCHANGE_FABRIC" not in text, text


# ---------------------------------------------------------------------------
# configuration surface
# ---------------------------------------------------------------------------

def test_exchange_fabric_properties_parsing():
    from presto_tpu.worker.properties import (
        SystemConfig, execution_config_from_properties)
    cfg = execution_config_from_properties(
        {"exchange.fabric": "ICI", "exchange.ici-chunk-rows": "2048"})
    assert cfg.exchange_fabric == "ici"
    assert cfg.ici_chunk_rows == 2048
    with pytest.raises(ValueError):
        execution_config_from_properties({"exchange.fabric": "warp"})
    with pytest.raises(ValueError):
        execution_config_from_properties(
            {"exchange.ici-chunk-rows": "0"})
    sc = SystemConfig({})
    assert sc.get("exchange.fabric") == "auto"
    # default is 0 = auto-tune (parallel/fabric.py IciChunkTuner);
    # explicit values still must be >= 1 (the ValueError above)
    assert sc.get("exchange.ici-chunk-rows") == 0


def test_execution_config_defaults():
    cfg = ExecutionConfig()
    assert cfg.exchange_fabric == "auto"
    assert cfg.ici_chunk_rows == 0  # 0 = tuner-driven


def test_ici_chunk_tuner_feedback():
    """Multiplicative feedback: poor overlap shrinks the chunk (finer
    pipelining), near-perfect overlap grows it (amortized dispatch),
    mid-range holds steady, and both directions clamp."""
    from presto_tpu.parallel.fabric import IciChunkTuner
    t = IciChunkTuner()
    assert t.chunk_rows() == IciChunkTuner.DEFAULT_ROWS
    t.observe(0.1)
    assert t.chunk_rows() == IciChunkTuner.DEFAULT_ROWS // 2
    t.observe(0.7)  # hysteresis band: unchanged
    assert t.chunk_rows() == IciChunkTuner.DEFAULT_ROWS // 2
    t.observe(0.95)
    assert t.chunk_rows() == IciChunkTuner.DEFAULT_ROWS
    for _ in range(30):
        t.observe(0.0)
    assert t.chunk_rows() == IciChunkTuner.MIN_ROWS
    for _ in range(30):
        t.observe(1.0)
    assert t.chunk_rows() == IciChunkTuner.MAX_ROWS
    t.reset()
    assert t.chunk_rows() == IciChunkTuner.DEFAULT_ROWS


@needs_mesh
@pytest.mark.slow
def test_chunk_rows_drive_chunk_count():
    """Tiny exchange.ici-chunk-rows must split the same shuffle into
    more collective dispatches (the compute/collective overlap knob)."""
    FABRIC_METRICS.reset()
    _runner(ici_chunk_rows=256).execute(GROUPBY)
    chunks_small = FABRIC_METRICS.snapshot()["ici"]["chunks"]

    FABRIC_METRICS.reset()
    _runner(ici_chunk_rows=1 << 14).execute(GROUPBY)
    chunks_big = FABRIC_METRICS.snapshot()["ici"]["chunks"]
    assert chunks_small > chunks_big >= 1
