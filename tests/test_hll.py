"""approx_distinct is a real dense HyperLogLog sketch (VERDICT r2 #7):
2^11 registers by default (standard error 1.04/sqrt(2048) = 2.3%, the
reference ApproximateCountDistinctAggregations.java default), updated by
one scatter-max per batch on device — NOT an exact count(DISTINCT)
rewrite.  The oracle computes the exact distinct count; every comparison
here tolerates the documented error bound.
"""
import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner

# 4x the standard error: a deterministic sketch (fixed hash) either passes
# forever or is actually broken — there is no flake margin to leave
DEFAULT_TOL = 4 * 1.04 / (2048 ** 0.5)


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13, join_out_capacity=1 << 15))


def _exact(runner, sql_distinct):
    return runner.execute(sql_distinct).rows


def test_global_within_error_bound(runner):
    est = runner.execute(
        "SELECT approx_distinct(custkey) FROM orders").rows[0][0]
    exact = runner.execute(
        "SELECT count(DISTINCT custkey) FROM orders").rows[0][0]
    assert exact > 500  # meaningful cardinality at sf0.01
    assert abs(est - exact) <= DEFAULT_TOL * exact


def test_not_the_exact_rewrite(runner):
    """The estimate comes from a sketch: across several cardinalities at
    least one estimate differs from exact (an exact-rewrite masquerading
    as HLL would match everywhere)."""
    diffs = []
    for pred in ("custkey < 300", "custkey < 700", "custkey < 1100",
                 "1 = 1"):
        est = runner.execute(
            f"SELECT approx_distinct(custkey) FROM orders "
            f"WHERE {pred}").rows[0][0]
        exact = runner.execute(
            f"SELECT count(DISTINCT custkey) FROM orders "
            f"WHERE {pred}").rows[0][0]
        assert abs(est - exact) <= DEFAULT_TOL * max(exact, 1)
        diffs.append(est != exact)
    assert any(diffs), "every estimate exactly equal to exact: still a rewrite?"


def test_grouped_within_error_bound(runner):
    est = dict((r[0], r[1]) for r in runner.execute(
        "SELECT orderpriority, approx_distinct(custkey) FROM orders "
        "GROUP BY orderpriority").rows)
    exact = dict((r[0], r[1]) for r in runner.execute(
        "SELECT orderpriority, count(DISTINCT custkey) FROM orders "
        "GROUP BY orderpriority").rows)
    assert est.keys() == exact.keys()
    for k in exact:
        assert abs(est[k] - exact[k]) <= DEFAULT_TOL * max(exact[k], 1), k


def test_varchar_input(runner):
    est = runner.execute(
        "SELECT approx_distinct(clerk) FROM orders").rows[0][0]
    exact = runner.execute(
        "SELECT count(DISTINCT clerk) FROM orders").rows[0][0]
    assert abs(est - exact) <= DEFAULT_TOL * max(exact, 1)


def test_custom_standard_error(runner):
    """approx_distinct(x, e): more registers, tighter bound (reference
    two-argument form)."""
    exact = runner.execute(
        "SELECT count(DISTINCT custkey) FROM orders").rows[0][0]
    est = runner.execute(
        "SELECT approx_distinct(custkey, 0.01) FROM orders").rows[0][0]
    assert abs(est - exact) <= 4 * 0.01 * exact


def test_invalid_standard_error_rejected(runner):
    with pytest.raises(Exception):
        runner.execute("SELECT approx_distinct(custkey, 0.5) FROM orders")
    with pytest.raises(Exception):
        runner.execute("SELECT approx_distinct(custkey, 0.001) FROM orders")


def test_empty_and_null_inputs(runner):
    assert runner.execute(
        "SELECT approx_distinct(custkey) FROM orders WHERE 1 = 0"
    ).rows[0][0] == 0
    # shipinstruct IS NULL never true in tpch; use a null-producing CASE
    assert runner.execute(
        "SELECT approx_distinct(CASE WHEN custkey < 0 THEN custkey END) "
        "FROM orders").rows[0][0] == 0


def test_alongside_other_aggregates(runner):
    row = runner.execute(
        "SELECT count(*), approx_distinct(custkey), sum(totalprice) "
        "FROM orders").rows[0]
    exact = runner.execute(
        "SELECT count(*), count(DISTINCT custkey), sum(totalprice) "
        "FROM orders").rows[0]
    assert row[0] == exact[0]
    assert abs(row[1] - exact[1]) <= DEFAULT_TOL * exact[1]
    assert abs(float(row[2]) - float(exact[2])) <= 1e-6 * float(exact[2])


def test_estimator_unit_known_registers():
    """_hll_estimate anchors: all-zero registers -> 0; the estimator is
    the Flajolet alpha_m * m^2 / sum(2^-R) form with linear counting."""
    import jax.numpy as jnp
    import math
    from presto_tpu.exec.operators import _hll_estimate

    m = 2048
    zeros = jnp.zeros((1, m), dtype=jnp.int8)
    assert int(_hll_estimate(zeros, m)[0]) == 0
    # one register set -> linear counting m*ln(m/(m-1)) ~= 1
    one = zeros.at[0, 7].set(3)
    assert int(_hll_estimate(one, m)[0]) == round(m * math.log(m / (m - 1)))


def test_hll_merge_equals_union():
    """agg_merge on HLL states == sketch of the union (register max)."""
    import jax.numpy as jnp
    import numpy as np
    from presto_tpu.exec.batch import Batch, Column
    from presto_tpu.exec import operators as ops

    spec = (ops.AggSpec("approx_distinct", "d", False,
                        ops.HLL_DEFAULT_BUCKETS),)
    slots = 64

    def table(values):
        st = ops.agg_init(slots, spec, (), ())
        col = Column(jnp.asarray(values, dtype=jnp.int64), None)
        b = Batch({"x": col}, jnp.ones(len(values), dtype=bool))
        return ops.agg_update(st, b, [], {"d": col}, spec, slots, 0, ())

    a = table(np.arange(0, 4000))
    b = table(np.arange(2000, 6000))
    merged = ops.agg_merge(a, b, spec, (), slots)
    both = table(np.arange(0, 6000))
    # same union of values -> identical register content in the live slot
    ma = np.asarray(merged["d$hll"]).reshape(slots, -1)
    mb = np.asarray(both["d$hll"]).reshape(slots, -1)
    assert (ma.max(axis=0) == mb.max(axis=0)).all()


def test_mixed_with_approx_percentile_clear_error(runner):
    """percentile (sort path) + HLL (hash path) in one aggregation is
    unsupported — must fail with a clear message, not a deep crash."""
    with pytest.raises(Exception, match="same aggregation"):
        runner.execute(
            "SELECT approx_percentile(totalprice, 0.5), "
            "approx_distinct(custkey) FROM orders")
