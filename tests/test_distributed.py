"""Distributed execution tests: fragmenter + in-process multi-task scheduler
vs the numpy reference interpreter (the analog of the reference's
DistributedQueryRunner-based AbstractTestDistributedQueries suites)."""
import pytest

from presto_tpu.exec.runner import DistributedQueryRunner
from presto_tpu.spi import plan as P

from test_queries import TPCH_Q1, TPCH_Q3, TPCH_Q5, TPCH_Q6


@pytest.fixture(scope="module")
def runner():
    # broadcast joins (everything under threshold at sf0.01)
    return DistributedQueryRunner("sf0.01", n_tasks=2)


@pytest.fixture(scope="module")
def part_runner():
    # force hash-partitioned joins + exchanges everywhere
    return DistributedQueryRunner("sf0.01", n_tasks=3, broadcast_threshold=0)


def check(r, sql, ordered=False):
    return r.assert_same_as_reference(sql, ordered=ordered)


# ---------------------------------------------------------------------------
# fragmentation shape
# ---------------------------------------------------------------------------

def test_group_by_splits_partial_final(runner):
    sub, _, _ = runner.plan_subplan(
        "select o_orderstatus, count(*) from orders group by o_orderstatus")
    frags = sub.all_fragments()
    assert len(frags) == 3  # root gather, final agg (hash), partial agg (source)
    parts = {f.fragment_id: f.partitioning for f in frags}
    assert parts["2"] == P.SOURCE_DISTRIBUTION
    assert parts["1"] == P.FIXED_HASH_DISTRIBUTION
    assert parts["0"] == P.SINGLE_DISTRIBUTION
    steps = [n.step for f in frags for n in P.walk_plan(f.root)
             if isinstance(n, P.AggregationNode)]
    assert sorted(steps) == [P.FINAL, P.PARTIAL]


def test_partitioned_join_repartitions_both_sides(part_runner):
    sub, _, _ = part_runner.plan_subplan(
        "select n_name, r_name from nation join region "
        "on n_regionkey = r_regionkey")
    frags = sub.all_fragments()
    hash_outputs = [f for f in frags
                    if f.output_partitioning_scheme.handle
                    == P.FIXED_HASH_DISTRIBUTION]
    assert len(hash_outputs) == 2


def test_broadcast_join_keeps_probe_in_place(runner):
    sub, _, _ = runner.plan_subplan(
        "select n_name, r_name from nation join region "
        "on n_regionkey = r_regionkey")
    frags = sub.all_fragments()
    bcast = [f for f in frags
             if f.output_partitioning_scheme.handle
             == P.FIXED_BROADCAST_DISTRIBUTION]
    assert len(bcast) == 1


# ---------------------------------------------------------------------------
# correctness vs reference
# ---------------------------------------------------------------------------

def test_global_agg(runner):
    check(runner, "select count(*), sum(l_quantity), avg(l_extendedprice), "
                  "min(l_discount), max(l_tax) from lineitem")


def test_group_by(runner):
    check(runner, "select o_orderstatus, count(*), sum(o_totalprice), "
                  "avg(o_totalprice) from orders group by o_orderstatus")


def test_group_by_high_cardinality(part_runner):
    check(part_runner, "select l_orderkey, count(*), sum(l_quantity) "
                       "from lineitem group by l_orderkey")


def test_join_broadcast(runner):
    check(runner, "select n_name, r_name from nation "
                  "join region on n_regionkey = r_regionkey")


def test_join_partitioned(part_runner):
    check(part_runner, "select c_custkey, o_orderkey from customer "
                       "join orders on c_custkey = o_custkey")


def test_left_join_partitioned(part_runner):
    check(part_runner, """
        select c_custkey, o_orderkey from customer
        left join orders on c_custkey = o_custkey
        where c_custkey < 50""")


def test_string_group_keys_cross_task(part_runner):
    # dictionary codes differ per producer task; exchange must hash values
    check(part_runner, "select c_mktsegment, count(*) from customer "
                       "group by c_mktsegment")


def test_order_by_limit(runner):
    check(runner, "select c_custkey, c_acctbal from customer "
                  "order by c_acctbal desc, c_custkey limit 20", ordered=True)


def test_distinct(part_runner):
    check(part_runner, "select distinct o_orderstatus from orders")


def test_tpch_q1(runner):
    res = check(runner, TPCH_Q1, ordered=True)
    assert len(res.rows) == 4


def test_tpch_q3(runner):
    res = check(runner, TPCH_Q3, ordered=True)
    assert len(res.rows) == 10


def test_tpch_q3_partitioned(part_runner):
    check(part_runner, TPCH_Q3, ordered=True)


def test_tpch_q5(runner):
    check(runner, TPCH_Q5, ordered=True)


def test_tpch_q5_partitioned(part_runner):
    check(part_runner, TPCH_Q5, ordered=True)


def test_tpch_q6(runner):
    check(runner, TPCH_Q6)


def test_left_join_empty_build_varchar(part_runner):
    # build side yields zero pages in a partition; varchar build columns must
    # null-extend with a valid dictionary (review regression)
    check(part_runner, """
        select c_custkey, o_orderstatus from customer
        left join (select o_custkey, o_orderstatus from orders
                   where o_totalprice < 0) t
        on c_custkey = o_custkey where c_custkey < 5""")


def test_window_repartitioned_by_partition_keys(part_runner):
    # WindowNode over a distributed source: fragmenter must hash-repartition
    # on the window partition keys so each task sees whole partitions
    check(part_runner, """
        select o_custkey, o_orderkey,
               row_number() over (partition by o_custkey order by o_orderkey),
               sum(o_totalprice) over (partition by o_custkey)
        from orders where o_custkey < 200""")


def test_window_no_partition_gathers_single(part_runner):
    check(part_runner, """
        select c_custkey,
               rank() over (order by c_acctbal desc)
        from customer where c_custkey < 100""")


def test_union_all_distributed(part_runner):
    check(part_runner, """
        select n_regionkey k from nation
        union all select r_regionkey from region
        union all select o_custkey from orders where o_orderkey < 50""")


def test_union_distinct_distributed(part_runner):
    check(part_runner, """
        select o_orderstatus from orders
        union select o_orderpriority from orders""")


def test_intersect_distributed(part_runner):
    check(part_runner, """
        select n_nationkey from nation
        intersect select c_nationkey from customer where c_custkey < 40""")


def test_partition_hash_matches_scalar_fnv():
    """The vectorized exchange-path string hash (one numpy pass per byte
    position) must equal the scalar FNV-1a spec byte for byte, and the
    dictionary path must agree with the flat path so both sides of an
    exchange partition identically."""
    import numpy as np

    from presto_tpu.common.block import (DictionaryBlock,
                                         VariableWidthBlock)
    from presto_tpu.common.types import VARCHAR
    from presto_tpu.exec.scheduler import _hash_block

    def scalar_fnv(data: bytes) -> int:
        h = 0xCBF29CE484222325
        for b in data:
            h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
        return h

    strings = ["", "a", "hello world", "x" * 200, "unicode: déjà vu",
               None, "PROMO BURNISHED"]
    flat = VariableWidthBlock.from_strings(strings)
    got = _hash_block(VARCHAR, flat, len(strings))
    for s, h in zip(strings, got):
        if s is not None:
            assert int(h) == scalar_fnv(s.encode("utf-8")), s
    entries = [s for s in strings if s is not None]
    ids = np.array([0, 2, 1, 4, 3, 0], dtype=np.int32)
    dict_block = DictionaryBlock(
        ids, VariableWidthBlock.from_strings(entries))
    got_d = _hash_block(VARCHAR, dict_block, len(ids))
    want = _hash_block(VARCHAR,
                       VariableWidthBlock.from_strings(
                           [entries[i] for i in ids]), len(ids))
    assert (got_d == want).all()


def test_varwidth_take_vectorized():
    from presto_tpu.common.block import VariableWidthBlock
    strings = ["alpha", "", "bravo charlie", "δ", "e" * 99]
    blk = VariableWidthBlock.from_strings(strings)
    import numpy as np
    taken = blk.take(np.array([4, 0, 2, 2, 1]))
    assert taken.to_pylist() == [strings[4], strings[0], strings[2],
                                 strings[2], strings[1]]
