"""Distributed execution tests: fragmenter + in-process multi-task scheduler
vs the numpy reference interpreter (the analog of the reference's
DistributedQueryRunner-based AbstractTestDistributedQueries suites)."""
import pytest

from presto_tpu.exec.runner import DistributedQueryRunner
from presto_tpu.spi import plan as P

from test_queries import TPCH_Q1, TPCH_Q3, TPCH_Q5, TPCH_Q6


@pytest.fixture(scope="module")
def runner():
    # broadcast joins (everything under threshold at sf0.01)
    return DistributedQueryRunner("sf0.01", n_tasks=2)


@pytest.fixture(scope="module")
def part_runner():
    # force hash-partitioned joins + exchanges everywhere
    return DistributedQueryRunner("sf0.01", n_tasks=3, broadcast_threshold=0)


def check(r, sql, ordered=False):
    return r.assert_same_as_reference(sql, ordered=ordered)


# ---------------------------------------------------------------------------
# fragmentation shape
# ---------------------------------------------------------------------------

def test_group_by_splits_partial_final(runner):
    sub, _, _ = runner.plan_subplan(
        "select o_orderstatus, count(*) from orders group by o_orderstatus")
    frags = sub.all_fragments()
    assert len(frags) == 3  # root gather, final agg (hash), partial agg (source)
    parts = {f.fragment_id: f.partitioning for f in frags}
    assert parts["2"] == P.SOURCE_DISTRIBUTION
    assert parts["1"] == P.FIXED_HASH_DISTRIBUTION
    assert parts["0"] == P.SINGLE_DISTRIBUTION
    steps = [n.step for f in frags for n in P.walk_plan(f.root)
             if isinstance(n, P.AggregationNode)]
    assert sorted(steps) == [P.FINAL, P.PARTIAL]


def test_partitioned_join_repartitions_both_sides(part_runner):
    sub, _, _ = part_runner.plan_subplan(
        "select n_name, r_name from nation join region "
        "on n_regionkey = r_regionkey")
    frags = sub.all_fragments()
    hash_outputs = [f for f in frags
                    if f.output_partitioning_scheme.handle
                    == P.FIXED_HASH_DISTRIBUTION]
    assert len(hash_outputs) == 2


def test_broadcast_join_keeps_probe_in_place(runner):
    sub, _, _ = runner.plan_subplan(
        "select n_name, r_name from nation join region "
        "on n_regionkey = r_regionkey")
    frags = sub.all_fragments()
    bcast = [f for f in frags
             if f.output_partitioning_scheme.handle
             == P.FIXED_BROADCAST_DISTRIBUTION]
    assert len(bcast) == 1


# ---------------------------------------------------------------------------
# correctness vs reference
# ---------------------------------------------------------------------------

def test_global_agg(runner):
    check(runner, "select count(*), sum(l_quantity), avg(l_extendedprice), "
                  "min(l_discount), max(l_tax) from lineitem")


def test_group_by(runner):
    check(runner, "select o_orderstatus, count(*), sum(o_totalprice), "
                  "avg(o_totalprice) from orders group by o_orderstatus")


def test_group_by_high_cardinality(part_runner):
    check(part_runner, "select l_orderkey, count(*), sum(l_quantity) "
                       "from lineitem group by l_orderkey")


def test_join_broadcast(runner):
    check(runner, "select n_name, r_name from nation "
                  "join region on n_regionkey = r_regionkey")


def test_join_partitioned(part_runner):
    check(part_runner, "select c_custkey, o_orderkey from customer "
                       "join orders on c_custkey = o_custkey")


def test_left_join_partitioned(part_runner):
    check(part_runner, """
        select c_custkey, o_orderkey from customer
        left join orders on c_custkey = o_custkey
        where c_custkey < 50""")


def test_string_group_keys_cross_task(part_runner):
    # dictionary codes differ per producer task; exchange must hash values
    check(part_runner, "select c_mktsegment, count(*) from customer "
                       "group by c_mktsegment")


def test_order_by_limit(runner):
    check(runner, "select c_custkey, c_acctbal from customer "
                  "order by c_acctbal desc, c_custkey limit 20", ordered=True)


def test_distinct(part_runner):
    check(part_runner, "select distinct o_orderstatus from orders")


def test_tpch_q1(runner):
    res = check(runner, TPCH_Q1, ordered=True)
    assert len(res.rows) == 4


def test_tpch_q3(runner):
    res = check(runner, TPCH_Q3, ordered=True)
    assert len(res.rows) == 10


def test_tpch_q3_partitioned(part_runner):
    check(part_runner, TPCH_Q3, ordered=True)


def test_tpch_q5(runner):
    check(runner, TPCH_Q5, ordered=True)


def test_tpch_q5_partitioned(part_runner):
    check(part_runner, TPCH_Q5, ordered=True)


def test_tpch_q6(runner):
    check(runner, TPCH_Q6)


def test_left_join_empty_build_varchar(part_runner):
    # build side yields zero pages in a partition; varchar build columns must
    # null-extend with a valid dictionary (review regression)
    check(part_runner, """
        select c_custkey, o_orderstatus from customer
        left join (select o_custkey, o_orderstatus from orders
                   where o_totalprice < 0) t
        on c_custkey = o_custkey where c_custkey < 5""")


def test_window_repartitioned_by_partition_keys(part_runner):
    # WindowNode over a distributed source: fragmenter must hash-repartition
    # on the window partition keys so each task sees whole partitions
    check(part_runner, """
        select o_custkey, o_orderkey,
               row_number() over (partition by o_custkey order by o_orderkey),
               sum(o_totalprice) over (partition by o_custkey)
        from orders where o_custkey < 200""")


def test_window_no_partition_gathers_single(part_runner):
    check(part_runner, """
        select c_custkey,
               rank() over (order by c_acctbal desc)
        from customer where c_custkey < 100""")


def test_union_all_distributed(part_runner):
    check(part_runner, """
        select n_regionkey k from nation
        union all select r_regionkey from region
        union all select o_custkey from orders where o_orderkey < 50""")


def test_union_distinct_distributed(part_runner):
    check(part_runner, """
        select o_orderstatus from orders
        union select o_orderpriority from orders""")


def test_intersect_distributed(part_runner):
    check(part_runner, """
        select n_nationkey from nation
        intersect select c_nationkey from customer where c_custkey < 40""")


def test_partition_hash_matches_scalar_fnv():
    """The vectorized exchange-path string hash (one numpy pass per byte
    position) must equal the scalar FNV-1a spec byte for byte, and the
    dictionary path must agree with the flat path so both sides of an
    exchange partition identically."""
    import numpy as np

    from presto_tpu.common.block import (DictionaryBlock,
                                         VariableWidthBlock)
    from presto_tpu.common.types import VARCHAR
    from presto_tpu.exec.scheduler import _hash_block

    def scalar_fnv(data: bytes) -> int:
        h = 0xCBF29CE484222325
        for b in data:
            h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
        return h

    strings = ["", "a", "hello world", "x" * 200, "unicode: déjà vu",
               None, "PROMO BURNISHED"]
    flat = VariableWidthBlock.from_strings(strings)
    got = _hash_block(VARCHAR, flat, len(strings))
    for s, h in zip(strings, got):
        if s is not None:
            assert int(h) == scalar_fnv(s.encode("utf-8")), s
    entries = [s for s in strings if s is not None]
    ids = np.array([0, 2, 1, 4, 3, 0], dtype=np.int32)
    dict_block = DictionaryBlock(
        ids, VariableWidthBlock.from_strings(entries))
    got_d = _hash_block(VARCHAR, dict_block, len(ids))
    want = _hash_block(VARCHAR,
                       VariableWidthBlock.from_strings(
                           [entries[i] for i in ids]), len(ids))
    assert (got_d == want).all()


def test_varwidth_take_vectorized():
    from presto_tpu.common.block import VariableWidthBlock
    strings = ["alpha", "", "bravo charlie", "δ", "e" * 99]
    blk = VariableWidthBlock.from_strings(strings)
    import numpy as np
    taken = blk.take(np.array([4, 0, 2, 2, 1]))
    assert taken.to_pylist() == [strings[4], strings[0], strings[2],
                                 strings[2], strings[1]]


# ---------------------------------------------------------------------------
# fault tolerance over the HTTP task protocol (chaos tests)
# ---------------------------------------------------------------------------
# The analog of the reference's TestDistributedQueriesWithTaskRetries /
# presto-spark retry suites: inject worker death and task failures into a
# real loopback cluster and require oracle-correct, exactly-once output.

def _reference(sql, ordered=False):
    from presto_tpu.exec.runner import LocalQueryRunner
    return LocalQueryRunner("sf0.01").execute_reference(sql)


def _assert_same(got, sql, ordered=False):
    from presto_tpu.exec.runner import _assert_rows_equal
    _assert_rows_equal(got, _reference(sql), ordered)


def _metric(uri, name):
    import urllib.request
    with urllib.request.urlopen(uri + "/v1/metrics", timeout=5) as r:
        text = r.read().decode()
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return None


CHAOS_SQL = ("select o_orderstatus, count(*), sum(o_totalprice) "
             "from orders, customer where c_custkey = o_custkey "
             "group by o_orderstatus")


def test_chaos_worker_killed_mid_query_recovers():
    """Kill a worker the moment it starts running a task: the coordinator
    must classify the loss as retryable, reschedule the lost lineages onto
    the survivors, and still return oracle-correct rows exactly once."""
    import threading
    from presto_tpu.common.errors import InjectedTaskFailure
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w1, w2, w3 = WorkerServer(), WorkerServer(), WorkerServer()
    killed = threading.Event()

    def kill_on_first_task(task_id):
        if not killed.is_set():
            killed.set()
            threading.Thread(target=w2.close, daemon=True).start()
            raise InjectedTaskFailure(
                f"chaos: worker dying under task {task_id}")

    w2.task_manager.fault_injector = kill_on_first_task
    try:
        r = HttpQueryRunner(
            [w1.uri, w2.uri, w3.uri], "sf0.01", n_tasks=2,
            session={"exchange_max_error_duration": "5s"})
        got = r.execute(CHAOS_SQL)
        _assert_same(got, CHAOS_SQL)
        assert killed.is_set(), "chaos hook never fired"
        assert r.tasks_retried >= 1
        # retry attempts land on the survivors with .rN lineage ids and
        # show up in their metrics
        retried = sum(w.task_manager.tasks_retried for w in (w1, w3))
        assert retried >= 1
        assert any(_metric(w.uri, "presto_tpu_task_retries_total") >= 1
                   for w in (w1, w3))
    finally:
        for w in (w1, w2, w3):
            w.close()


def test_chaos_injected_failure_exactly_once():
    """A transient (retryable) injected task failure: the query output must
    match the oracle exactly — no dropped and no duplicated pages — and the
    failure/retry counters must be visible in /v1/metrics."""
    from presto_tpu.common.errors import InjectedTaskFailure
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w1, w2 = WorkerServer(), WorkerServer()
    flaked = []

    def flaky_once(task_id):
        if not flaked:
            flaked.append(task_id)
            raise InjectedTaskFailure(f"chaos: flaky task {task_id}")

    w1.task_manager.fault_injector = flaky_once
    w2.task_manager.fault_injector = flaky_once
    try:
        r = HttpQueryRunner([w1.uri, w2.uri], "sf0.01", n_tasks=2)
        got = r.execute(CHAOS_SQL)
        _assert_same(got, CHAOS_SQL)
        assert len(flaked) == 1
        assert r.tasks_retried >= 1
        failed = sum(_metric(w.uri, "presto_tpu_tasks_failed_total")
                     for w in (w1, w2))
        retried = sum(_metric(w.uri, "presto_tpu_task_retries_total")
                      for w in (w1, w2))
        assert failed >= 1 and retried >= 1
    finally:
        w1.close()
        w2.close()


def test_chaos_user_error_fails_fast_without_retry():
    """A USER_ERROR-shaped failure must fail the query immediately: no task
    retry attempts anywhere, and the typed error survives the HTTP hop."""
    from presto_tpu.common.errors import PrestoUserError
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    calls = []

    def user_bug(task_id):
        calls.append(task_id)
        raise ValueError("chaos: user's input is malformed")

    w.task_manager.fault_injector = user_bug
    try:
        r = HttpQueryRunner([w.uri], "sf0.01", n_tasks=1)
        with pytest.raises(PrestoUserError):
            r.execute("select count(*) from nation")
        assert r.tasks_retried == 0
        assert w.task_manager.tasks_retried == 0
        assert all(".r" not in t for t in calls)
    finally:
        w.close()


def test_chaos_retry_budget_exhausts():
    """A permanently failing task consumes its attempt budget and then
    fails the query with a typed error instead of retrying forever."""
    from presto_tpu.common.errors import (InjectedTaskFailure,
                                          PrestoQueryError)
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    calls = []

    def always_fail(task_id):
        calls.append(task_id)
        raise InjectedTaskFailure(f"chaos: permanent failure {task_id}")

    w.task_manager.fault_injector = always_fail
    try:
        r = HttpQueryRunner(
            [w.uri], "sf0.01", n_tasks=1,
            session={"remote_task_retry_attempts": "1"})
        with pytest.raises(PrestoQueryError, match="retry attempt"):
            r.execute("select count(*) from region")
        # initial attempt + exactly one budgeted retry reached the worker
        assert w.task_manager.tasks_retried == 1
    finally:
        w.close()


def test_probabilistic_fault_injection_session_property():
    """fault_injection_probability=1.0 via session property trips the
    deterministic sha256 roll on every attempt; with retry disabled the
    query fails on the first injected fault."""
    from presto_tpu.common.errors import PrestoQueryError
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    try:
        r = HttpQueryRunner(
            [w.uri], "sf0.01", n_tasks=1,
            session={"fault_injection_probability": "1.0",
                     "remote_task_retry_attempts": "0"})
        with pytest.raises(PrestoQueryError):
            r.execute("select count(*) from region")
        assert w.task_manager.tasks_failed >= 1
    finally:
        w.close()


def test_task_manager_abort_hook_and_counters():
    from presto_tpu.worker.protocol import (OutputBuffersSpec,
                                            TaskUpdateRequest)
    from presto_tpu.worker.task import TaskManager

    tm = TaskManager()
    tm.create_or_update(TaskUpdateRequest(
        "qx.0.0", 0, None, [], OutputBuffersSpec("PARTITIONED", 1)))
    tm.abort("qx.0.0", "chaos abort")
    st = tm.get("qx.0.0").status()
    assert st.state == "FAILED"
    assert st.error_type == "INTERNAL_ERROR"
    counts = tm.counts()
    assert counts["failed"] == 1 and counts["retried"] == 0
    # retry-suffixed creations are counted as coordinator retry attempts
    tm.create_or_update(TaskUpdateRequest(
        "qx.0.0.r1", 0, None, [], OutputBuffersSpec("PARTITIONED", 1)))
    assert tm.counts()["retried"] == 1


def test_task_manager_periodic_reaper():
    """Terminal tasks are evicted by the background reaper even when no new
    create_or_update call ever arrives (PeriodicTaskManager analog)."""
    import time
    from presto_tpu.worker.protocol import (OutputBuffersSpec,
                                            TaskUpdateRequest)
    from presto_tpu.worker.task import TaskManager

    tm = TaskManager()
    tm.TASK_TTL_S = 0.05
    tm.create_or_update(TaskUpdateRequest(
        "qr.0.0", 0, None, [], OutputBuffersSpec("PARTITIONED", 1)))
    tm.abort("qr.0.0")
    tm.start_reaper(interval_s=0.05)
    try:
        deadline = time.time() + 5
        while "qr.0.0" in tm.tasks and time.time() < deadline:
            time.sleep(0.02)
        assert "qr.0.0" not in tm.tasks
    finally:
        tm.stop_reaper()


def test_exchange_lost_on_missing_task():
    """404 on a results pull means the producer task is GONE (worker
    restarted): a typed ExchangeLostError carrying the location, not a
    KeyError query failure."""
    from presto_tpu.common.errors import ExchangeLostError
    from presto_tpu.worker.exchange import pull_pages
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    try:
        loc = f"{w.uri}/v1/task/ghost.0.0/results/0"
        with pytest.raises(ExchangeLostError) as ei:
            list(pull_pages(loc, max_error_duration_s=0.5))
        assert ei.value.location == loc
    finally:
        w.close()


def test_exchange_budget_bounds_unreachable_source():
    """An unreachable exchange source retries with backoff only until the
    error budget expires, then surfaces ExchangeLostError."""
    import time
    from presto_tpu.common.errors import ExchangeLostError
    from presto_tpu.worker.exchange import pull_pages

    loc = "http://127.0.0.1:1/v1/task/gone.0.0/results/0"
    t0 = time.monotonic()
    with pytest.raises(ExchangeLostError):
        list(pull_pages(loc, max_error_duration_s=0.3))
    assert time.monotonic() - t0 < 10.0


def test_error_classifier_taxonomy():
    import urllib.error
    from presto_tpu.common.errors import (EXTERNAL, INSUFFICIENT_RESOURCES,
                                          INTERNAL_ERROR, USER_ERROR,
                                          classify_exception, is_retryable,
                                          parse_error_type,
                                          producer_task_from_text)

    assert classify_exception(ValueError("bad sql")) == USER_ERROR
    assert classify_exception(ConnectionRefusedError()) == EXTERNAL
    assert classify_exception(TimeoutError()) == EXTERNAL
    assert classify_exception(MemoryError()) == INSUFFICIENT_RESOURCES
    assert classify_exception(RuntimeError("boom")) == INTERNAL_ERROR
    assert classify_exception(
        urllib.error.HTTPError("u", 503, "busy", {}, None)) == EXTERNAL
    assert classify_exception(
        urllib.error.HTTPError("u", 400, "bad", {}, None)) == USER_ERROR
    # tags survive string-typed failure chains
    assert parse_error_type("task q.0.0 failed [USER_ERROR]: x") \
        == USER_ERROR
    assert not is_retryable(
        RuntimeError("remote said [USER_ERROR] bad query"))
    assert is_retryable(RuntimeError("remote said [EXTERNAL] net down"))
    # a malformed plan re-plans identically: PLAN_VALIDATION fails fast
    from presto_tpu.common.errors import PLAN_VALIDATION, PlanValidationError
    assert classify_exception(PlanValidationError("bad")) == PLAN_VALIDATION
    assert not is_retryable(PlanValidationError("bad"))
    assert parse_error_type(
        "task q.0.0 failed [PLAN_VALIDATION]: bad") == PLAN_VALIDATION
    assert producer_task_from_text(
        "exchange source http://h:1/v1/task/q1.0_0.1.r2/results/3 "
        "vanished") == "q1.0_0.1.r2"
