"""Distributed execution tests: fragmenter + in-process multi-task scheduler
vs the numpy reference interpreter (the analog of the reference's
DistributedQueryRunner-based AbstractTestDistributedQueries suites)."""
import pytest

from presto_tpu.exec.runner import DistributedQueryRunner
from presto_tpu.spi import plan as P

from test_queries import TPCH_Q1, TPCH_Q3, TPCH_Q5, TPCH_Q6


@pytest.fixture(scope="module")
def runner():
    # broadcast joins (everything under threshold at sf0.01)
    return DistributedQueryRunner("sf0.01", n_tasks=2)


@pytest.fixture(scope="module")
def part_runner():
    # force hash-partitioned joins + exchanges everywhere
    return DistributedQueryRunner("sf0.01", n_tasks=3, broadcast_threshold=0)


def check(r, sql, ordered=False):
    return r.assert_same_as_reference(sql, ordered=ordered)


# ---------------------------------------------------------------------------
# fragmentation shape
# ---------------------------------------------------------------------------

def test_group_by_splits_partial_final(runner):
    sub, _, _ = runner.plan_subplan(
        "select o_orderstatus, count(*) from orders group by o_orderstatus")
    frags = sub.all_fragments()
    assert len(frags) == 3  # root gather, final agg (hash), partial agg (source)
    parts = {f.fragment_id: f.partitioning for f in frags}
    assert parts["2"] == P.SOURCE_DISTRIBUTION
    assert parts["1"] == P.FIXED_HASH_DISTRIBUTION
    assert parts["0"] == P.SINGLE_DISTRIBUTION
    steps = [n.step for f in frags for n in P.walk_plan(f.root)
             if isinstance(n, P.AggregationNode)]
    assert sorted(steps) == [P.FINAL, P.PARTIAL]


def test_partitioned_join_repartitions_both_sides(part_runner):
    sub, _, _ = part_runner.plan_subplan(
        "select n_name, r_name from nation join region "
        "on n_regionkey = r_regionkey")
    frags = sub.all_fragments()
    hash_outputs = [f for f in frags
                    if f.output_partitioning_scheme.handle
                    == P.FIXED_HASH_DISTRIBUTION]
    assert len(hash_outputs) == 2


def test_broadcast_join_keeps_probe_in_place(runner):
    sub, _, _ = runner.plan_subplan(
        "select n_name, r_name from nation join region "
        "on n_regionkey = r_regionkey")
    frags = sub.all_fragments()
    bcast = [f for f in frags
             if f.output_partitioning_scheme.handle
             == P.FIXED_BROADCAST_DISTRIBUTION]
    assert len(bcast) == 1


# ---------------------------------------------------------------------------
# correctness vs reference
# ---------------------------------------------------------------------------

def test_global_agg(runner):
    check(runner, "select count(*), sum(l_quantity), avg(l_extendedprice), "
                  "min(l_discount), max(l_tax) from lineitem")


def test_group_by(runner):
    check(runner, "select o_orderstatus, count(*), sum(o_totalprice), "
                  "avg(o_totalprice) from orders group by o_orderstatus")


def test_group_by_high_cardinality(part_runner):
    check(part_runner, "select l_orderkey, count(*), sum(l_quantity) "
                       "from lineitem group by l_orderkey")


def test_join_broadcast(runner):
    check(runner, "select n_name, r_name from nation "
                  "join region on n_regionkey = r_regionkey")


def test_join_partitioned(part_runner):
    check(part_runner, "select c_custkey, o_orderkey from customer "
                       "join orders on c_custkey = o_custkey")


def test_left_join_partitioned(part_runner):
    check(part_runner, """
        select c_custkey, o_orderkey from customer
        left join orders on c_custkey = o_custkey
        where c_custkey < 50""")


def test_string_group_keys_cross_task(part_runner):
    # dictionary codes differ per producer task; exchange must hash values
    check(part_runner, "select c_mktsegment, count(*) from customer "
                       "group by c_mktsegment")


def test_order_by_limit(runner):
    check(runner, "select c_custkey, c_acctbal from customer "
                  "order by c_acctbal desc, c_custkey limit 20", ordered=True)


def test_distinct(part_runner):
    check(part_runner, "select distinct o_orderstatus from orders")


def test_tpch_q1(runner):
    res = check(runner, TPCH_Q1, ordered=True)
    assert len(res.rows) == 4


def test_tpch_q3(runner):
    res = check(runner, TPCH_Q3, ordered=True)
    assert len(res.rows) == 10


def test_tpch_q3_partitioned(part_runner):
    check(part_runner, TPCH_Q3, ordered=True)


def test_tpch_q5(runner):
    check(runner, TPCH_Q5, ordered=True)


def test_tpch_q5_partitioned(part_runner):
    check(part_runner, TPCH_Q5, ordered=True)


def test_tpch_q6(runner):
    check(runner, TPCH_Q6)


def test_left_join_empty_build_varchar(part_runner):
    # build side yields zero pages in a partition; varchar build columns must
    # null-extend with a valid dictionary (review regression)
    check(part_runner, """
        select c_custkey, o_orderstatus from customer
        left join (select o_custkey, o_orderstatus from orders
                   where o_totalprice < 0) t
        on c_custkey = o_custkey where c_custkey < 5""")


def test_window_repartitioned_by_partition_keys(part_runner):
    # WindowNode over a distributed source: fragmenter must hash-repartition
    # on the window partition keys so each task sees whole partitions
    check(part_runner, """
        select o_custkey, o_orderkey,
               row_number() over (partition by o_custkey order by o_orderkey),
               sum(o_totalprice) over (partition by o_custkey)
        from orders where o_custkey < 200""")


def test_window_no_partition_gathers_single(part_runner):
    check(part_runner, """
        select c_custkey,
               rank() over (order by c_acctbal desc)
        from customer where c_custkey < 100""")


def test_union_all_distributed(part_runner):
    check(part_runner, """
        select n_regionkey k from nation
        union all select r_regionkey from region
        union all select o_custkey from orders where o_orderkey < 50""")


def test_union_distinct_distributed(part_runner):
    check(part_runner, """
        select o_orderstatus from orders
        union select o_orderpriority from orders""")


def test_intersect_distributed(part_runner):
    check(part_runner, """
        select n_nationkey from nation
        intersect select c_nationkey from customer where c_custkey < 40""")


def test_partition_hash_matches_scalar_fnv():
    """The vectorized exchange-path string hash (one numpy pass per byte
    position) must equal the scalar FNV-1a spec byte for byte, and the
    dictionary path must agree with the flat path so both sides of an
    exchange partition identically."""
    import numpy as np

    from presto_tpu.common.block import (DictionaryBlock,
                                         VariableWidthBlock)
    from presto_tpu.common.types import VARCHAR
    from presto_tpu.exec.scheduler import _hash_block

    def scalar_fnv(data: bytes) -> int:
        h = 0xCBF29CE484222325
        for b in data:
            h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
        return h

    strings = ["", "a", "hello world", "x" * 200, "unicode: déjà vu",
               None, "PROMO BURNISHED"]
    flat = VariableWidthBlock.from_strings(strings)
    got = _hash_block(VARCHAR, flat, len(strings))
    for s, h in zip(strings, got):
        if s is not None:
            assert int(h) == scalar_fnv(s.encode("utf-8")), s
    entries = [s for s in strings if s is not None]
    ids = np.array([0, 2, 1, 4, 3, 0], dtype=np.int32)
    dict_block = DictionaryBlock(
        ids, VariableWidthBlock.from_strings(entries))
    got_d = _hash_block(VARCHAR, dict_block, len(ids))
    want = _hash_block(VARCHAR,
                       VariableWidthBlock.from_strings(
                           [entries[i] for i in ids]), len(ids))
    assert (got_d == want).all()


def test_varwidth_take_vectorized():
    from presto_tpu.common.block import VariableWidthBlock
    strings = ["alpha", "", "bravo charlie", "δ", "e" * 99]
    blk = VariableWidthBlock.from_strings(strings)
    import numpy as np
    taken = blk.take(np.array([4, 0, 2, 2, 1]))
    assert taken.to_pylist() == [strings[4], strings[0], strings[2],
                                 strings[2], strings[1]]


# ---------------------------------------------------------------------------
# fault tolerance over the HTTP task protocol (chaos tests)
# ---------------------------------------------------------------------------
# The analog of the reference's TestDistributedQueriesWithTaskRetries /
# presto-spark retry suites: inject worker death and task failures into a
# real loopback cluster and require oracle-correct, exactly-once output.

def _reference(sql, ordered=False):
    from presto_tpu.exec.runner import LocalQueryRunner
    return LocalQueryRunner("sf0.01").execute_reference(sql)


def _assert_same(got, sql, ordered=False):
    from presto_tpu.exec.runner import _assert_rows_equal
    _assert_rows_equal(got, _reference(sql), ordered)


def _metric(uri, name):
    import urllib.request
    with urllib.request.urlopen(uri + "/v1/metrics", timeout=5) as r:
        text = r.read().decode()
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return None


CHAOS_SQL = ("select o_orderstatus, count(*), sum(o_totalprice) "
             "from orders, customer where c_custkey = o_custkey "
             "group by o_orderstatus")


@pytest.fixture
def lock_validation():
    """Chaos runs double as runtime lock-order validation runs: the
    lock_validation=on session property (exec/pipeline.py) makes every
    task driver thread record its OrderedLock acquisition stack
    (common/locks.py), and the fixture requires the whole run — retries,
    worker death, drains and all — to finish with ZERO rank inversions."""
    from presto_tpu.common.locks import LOCK_METRICS
    before = LOCK_METRICS.snapshot()["violations"]
    yield
    after = LOCK_METRICS.snapshot()["violations"]
    assert after == before, \
        f"{after - before} lock-order violation(s) during chaos run"


def test_chaos_worker_killed_mid_query_recovers(lock_validation):
    """Kill a worker the moment it starts running a task: the coordinator
    must classify the loss as retryable, reschedule the lost lineages onto
    the survivors, and still return oracle-correct rows exactly once."""
    import threading
    from presto_tpu.common.errors import InjectedTaskFailure
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w1, w2, w3 = WorkerServer(), WorkerServer(), WorkerServer()
    killed = threading.Event()

    def kill_on_first_task(task_id):
        if not killed.is_set():
            killed.set()
            threading.Thread(target=w2.close, daemon=True).start()
            raise InjectedTaskFailure(
                f"chaos: worker dying under task {task_id}")

    w2.task_manager.fault_injector = kill_on_first_task
    try:
        r = HttpQueryRunner(
            [w1.uri, w2.uri, w3.uri], "sf0.01", n_tasks=2,
            session={"exchange_max_error_duration": "5s",
                     "lock_validation": "on"})
        got = r.execute(CHAOS_SQL)
        _assert_same(got, CHAOS_SQL)
        assert killed.is_set(), "chaos hook never fired"
        assert r.tasks_retried >= 1
        # retry attempts land on the survivors with .rN lineage ids and
        # show up in their metrics
        retried = sum(w.task_manager.tasks_retried for w in (w1, w3))
        assert retried >= 1
        assert any(_metric(w.uri, "presto_tpu_task_retries_total") >= 1
                   for w in (w1, w3))
    finally:
        for w in (w1, w2, w3):
            w.close()


def test_chaos_injected_failure_exactly_once(lock_validation):
    """A transient (retryable) injected task failure: the query output must
    match the oracle exactly — no dropped and no duplicated pages — and the
    failure/retry counters must be visible in /v1/metrics."""
    from presto_tpu.common.errors import InjectedTaskFailure
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w1, w2 = WorkerServer(), WorkerServer()
    flaked = []

    def flaky_once(task_id):
        if not flaked:
            flaked.append(task_id)
            raise InjectedTaskFailure(f"chaos: flaky task {task_id}")

    w1.task_manager.fault_injector = flaky_once
    w2.task_manager.fault_injector = flaky_once
    try:
        r = HttpQueryRunner([w1.uri, w2.uri], "sf0.01", n_tasks=2,
                            session={"lock_validation": "on"})
        got = r.execute(CHAOS_SQL)
        _assert_same(got, CHAOS_SQL)
        assert len(flaked) == 1
        assert r.tasks_retried >= 1
        failed = sum(_metric(w.uri, "presto_tpu_tasks_failed_total")
                     for w in (w1, w2))
        retried = sum(_metric(w.uri, "presto_tpu_task_retries_total")
                      for w in (w1, w2))
        assert failed >= 1 and retried >= 1
    finally:
        w1.close()
        w2.close()


def test_chaos_user_error_fails_fast_without_retry(lock_validation):
    """A USER_ERROR-shaped failure must fail the query immediately: no task
    retry attempts anywhere, and the typed error survives the HTTP hop."""
    from presto_tpu.common.errors import PrestoUserError
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    calls = []

    def user_bug(task_id):
        calls.append(task_id)
        raise ValueError("chaos: user's input is malformed")

    w.task_manager.fault_injector = user_bug
    try:
        r = HttpQueryRunner([w.uri], "sf0.01", n_tasks=1,
                            session={"lock_validation": "on"})
        with pytest.raises(PrestoUserError):
            r.execute("select count(*) from nation")
        assert r.tasks_retried == 0
        assert w.task_manager.tasks_retried == 0
        assert all(".r" not in t for t in calls)
    finally:
        w.close()


def test_chaos_retry_budget_exhausts(lock_validation):
    """A permanently failing task consumes its attempt budget and then
    fails the query with a typed error instead of retrying forever."""
    from presto_tpu.common.errors import (InjectedTaskFailure,
                                          PrestoQueryError)
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    calls = []

    def always_fail(task_id):
        calls.append(task_id)
        raise InjectedTaskFailure(f"chaos: permanent failure {task_id}")

    w.task_manager.fault_injector = always_fail
    try:
        r = HttpQueryRunner(
            [w.uri], "sf0.01", n_tasks=1,
            session={"remote_task_retry_attempts": "1",
                     "lock_validation": "on"})
        with pytest.raises(PrestoQueryError, match="retry attempt"):
            r.execute("select count(*) from region")
        # at least one budgeted retry reached the worker, and no lineage
        # was ever charged past its budget of 1.  (The exact worker-side
        # tasks_retried count depends on which failure event the status
        # watcher delivers first — a producer restart cascades an
        # UNcharged consumer restart — so assert the budget invariant,
        # not the event ordering.)
        assert w.task_manager.tasks_retried >= 1
        budget_used = r.last_execution.budget_used
        assert budget_used and max(budget_used.values()) == 1
        # bounded: permanent failure must not retry beyond budget+cascades
        assert len(calls) <= 6
    finally:
        w.close()


def test_probabilistic_fault_injection_session_property(lock_validation):
    """fault_injection_probability=1.0 via session property trips the
    deterministic sha256 roll on every attempt; with retry disabled the
    query fails on the first injected fault."""
    from presto_tpu.common.errors import PrestoQueryError
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    try:
        r = HttpQueryRunner(
            [w.uri], "sf0.01", n_tasks=1,
            session={"fault_injection_probability": "1.0",
                     "remote_task_retry_attempts": "0",
                     "lock_validation": "on"})
        with pytest.raises(PrestoQueryError):
            r.execute("select count(*) from region")
        assert w.task_manager.tasks_failed >= 1
    finally:
        w.close()


# ---------------------------------------------------------------------------
# adaptive execution under chaos (dynamic filters are advisory, never load-
# bearing: every failure mode must degrade to "scan ran unfiltered", with
# rows still oracle-exact)
# ---------------------------------------------------------------------------

# `+ 0` keeps the predicate opaque to the stats calculator; zones finer
# than the table (storage_zone_rows) give the runtime filter chunks to prune
AQE_CHAOS_SQL = ("select sum(l_extendedprice), count(*) "
                 "from lineitem, orders "
                 "where l_orderkey = o_orderkey and o_orderkey + 0 < 30")

AQE_SESSION = {"lock_validation": "on", "storage_zone_rows": "4096"}


def _build_stage_paths(r, sql):
    """Task-id stage-path markers ('0_0' style) of every fragment that is
    a dynamic-filter SOURCE (the build stages)."""
    sub, _, _ = r.plan_subplan(sql)
    out = []

    def walk(sp, path):
        if sp.fragment.dynamic_filter_sources:
            out.append(path.replace(".", "_"))
        for i, c in enumerate(sp.children):
            walk(c, f"{path}.{i}")

    walk(sub, "0")
    return out


def test_chaos_build_worker_killed_scans_fall_back_unfiltered(
        lock_validation):
    """Kill the worker running the dynamic-filter BUILD task before it can
    summarize: downstream scans wait out dynamic-filtering.wait-timeout,
    proceed unfiltered, and the (retried) query still returns oracle-exact
    rows — losing the filter may cost pruning, never correctness."""
    import threading
    from presto_tpu.common.errors import InjectedTaskFailure
    from presto_tpu.exec.adaptive import ADAPTIVE_METRICS
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    workers = [WorkerServer() for _ in range(3)]
    killed = threading.Event()
    before = ADAPTIVE_METRICS.snapshot()
    try:
        r = HttpQueryRunner(
            [w.uri for w in workers], "sf0.01", n_tasks=2,
            session={**AQE_SESSION,
                     "dynamic_filtering_wait_timeout": "50ms",
                     "exchange_max_error_duration": "5s"})
        build_paths = _build_stage_paths(r, AQE_CHAOS_SQL)
        assert build_paths, "test premise broken: no dynamic-filter source"

        def kill_build(w):
            def injector(task_id):
                if killed.is_set():
                    return
                if any(f".{p}." in task_id for p in build_paths):
                    killed.set()
                    threading.Thread(target=w.close, daemon=True).start()
                    raise InjectedTaskFailure(
                        f"chaos: build worker dying under {task_id}")
            return injector

        for w in workers:
            w.task_manager.fault_injector = kill_build(w)
        got = r.execute(AQE_CHAOS_SQL)
        _assert_same(got, AQE_CHAOS_SQL)
        assert killed.is_set(), "chaos hook never saw a build task"
        assert r.tasks_retried >= 1
        after = ADAPTIVE_METRICS.snapshot()
        # probe scans started while the build was dying: the bounded wait
        # expired and they ran unfiltered (workers share this process, so
        # the registry sees their counters)
        assert after["filter_wait_timeouts"] > before["filter_wait_timeouts"]
    finally:
        for w in workers:
            w.close()


def test_chaos_late_dynamic_filter_is_ignored_not_fatal(lock_validation):
    """A summary pushed AFTER a task's wait expired (or after the task
    finished entirely) is metered as a late arrival and otherwise ignored:
    the coordinator pump racing task completion must never fail a query."""
    from presto_tpu.exec.adaptive import ADAPTIVE_METRICS
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    try:
        r = HttpQueryRunner([w.uri], "sf0.01", n_tasks=1,
                            session=dict(AQE_SESSION))
        got = r.execute(AQE_CHAOS_SQL)
        _assert_same(got, AQE_CHAOS_SQL)
        tasks = list(w.task_manager.tasks.values())
        assert tasks, "finished tasks already evicted"
        before = ADAPTIVE_METRICS.snapshot()["filter_late_arrivals"]
        tasks[0].deliver_dynamic_filters(
            {"df_late": {"filterId": "df_late", "rowCount": 1,
                         "min": 1, "max": 1}})
        after = ADAPTIVE_METRICS.snapshot()["filter_late_arrivals"]
        assert after == before + 1
    finally:
        w.close()


def test_chaos_lock_validation_over_adaptive_paths(lock_validation):
    """The new coordinator<->task surfaces (summary collection polls,
    TaskUpdateRequest filter pushes, task-side waits) run under
    lock_validation=on: oracle-exact rows, filters demonstrably collected
    AND applied, zero lock-order violations (fixture)."""
    from presto_tpu.exec.adaptive import ADAPTIVE_METRICS
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w1, w2 = WorkerServer(), WorkerServer()
    before = ADAPTIVE_METRICS.snapshot()
    try:
        r = HttpQueryRunner([w1.uri, w2.uri], "sf0.01", n_tasks=2,
                            session=dict(AQE_SESSION))
        got = r.execute(AQE_CHAOS_SQL)
        _assert_same(got, AQE_CHAOS_SQL)
        after = ADAPTIVE_METRICS.snapshot()
        assert after["filters_collected"] > before["filters_collected"]
        assert after["filters_applied"] > before["filters_applied"]
        # a summary landing before task creation prunes whole chunks; one
        # landing mid-scan prunes rows — either way something was dropped
        pruned = (after["filter_rows_pruned"] - before["filter_rows_pruned"]
                  + after["filter_chunks_skipped"]
                  - before["filter_chunks_skipped"])
        assert pruned > 0
        # the loopback workers also export the registry as prometheus text
        assert _metric(w1.uri,
                       "presto_tpu_adaptive_filters_applied_total") >= 1
    finally:
        w1.close()
        w2.close()


def test_task_manager_abort_hook_and_counters():
    from presto_tpu.worker.protocol import (OutputBuffersSpec,
                                            TaskUpdateRequest)
    from presto_tpu.worker.task import TaskManager

    tm = TaskManager()
    tm.create_or_update(TaskUpdateRequest(
        "qx.0.0", 0, None, [], OutputBuffersSpec("PARTITIONED", 1)))
    tm.abort("qx.0.0", "chaos abort")
    st = tm.get("qx.0.0").status()
    assert st.state == "FAILED"
    assert st.error_type == "INTERNAL_ERROR"
    counts = tm.counts()
    assert counts["failed"] == 1 and counts["retried"] == 0
    # retry-suffixed creations are counted as coordinator retry attempts
    tm.create_or_update(TaskUpdateRequest(
        "qx.0.0.r1", 0, None, [], OutputBuffersSpec("PARTITIONED", 1)))
    assert tm.counts()["retried"] == 1


def test_task_manager_periodic_reaper():
    """Terminal tasks are evicted by the background reaper even when no new
    create_or_update call ever arrives (PeriodicTaskManager analog)."""
    import time
    from presto_tpu.worker.protocol import (OutputBuffersSpec,
                                            TaskUpdateRequest)
    from presto_tpu.worker.task import TaskManager

    tm = TaskManager()
    tm.TASK_TTL_S = 0.05
    tm.create_or_update(TaskUpdateRequest(
        "qr.0.0", 0, None, [], OutputBuffersSpec("PARTITIONED", 1)))
    tm.abort("qr.0.0")
    tm.start_reaper(interval_s=0.05)
    try:
        deadline = time.time() + 5
        while "qr.0.0" in tm.tasks and time.time() < deadline:
            time.sleep(0.02)
        assert "qr.0.0" not in tm.tasks
    finally:
        tm.stop_reaper()


def test_exchange_lost_on_missing_task():
    """404 on a results pull means the producer task is GONE (worker
    restarted): a typed ExchangeLostError carrying the location, not a
    KeyError query failure."""
    from presto_tpu.common.errors import ExchangeLostError
    from presto_tpu.worker.exchange import pull_pages
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    try:
        loc = f"{w.uri}/v1/task/ghost.0.0/results/0"
        with pytest.raises(ExchangeLostError) as ei:
            list(pull_pages(loc, max_error_duration_s=0.5))
        assert ei.value.location == loc
    finally:
        w.close()


def test_exchange_budget_bounds_unreachable_source():
    """An unreachable exchange source retries with backoff only until the
    error budget expires, then surfaces ExchangeLostError."""
    import time
    from presto_tpu.common.errors import ExchangeLostError
    from presto_tpu.worker.exchange import pull_pages

    loc = "http://127.0.0.1:1/v1/task/gone.0.0/results/0"
    t0 = time.monotonic()
    with pytest.raises(ExchangeLostError):
        list(pull_pages(loc, max_error_duration_s=0.3))
    assert time.monotonic() - t0 < 10.0


def test_error_classifier_taxonomy():
    import urllib.error
    from presto_tpu.common.errors import (EXTERNAL, INSUFFICIENT_RESOURCES,
                                          INTERNAL_ERROR, USER_ERROR,
                                          classify_exception, is_retryable,
                                          parse_error_type,
                                          producer_task_from_text)

    assert classify_exception(ValueError("bad sql")) == USER_ERROR
    assert classify_exception(ConnectionRefusedError()) == EXTERNAL
    assert classify_exception(TimeoutError()) == EXTERNAL
    assert classify_exception(MemoryError()) == INSUFFICIENT_RESOURCES
    assert classify_exception(RuntimeError("boom")) == INTERNAL_ERROR
    assert classify_exception(
        urllib.error.HTTPError("u", 503, "busy", {}, None)) == EXTERNAL
    assert classify_exception(
        urllib.error.HTTPError("u", 400, "bad", {}, None)) == USER_ERROR
    # tags survive string-typed failure chains
    assert parse_error_type("task q.0.0 failed [USER_ERROR]: x") \
        == USER_ERROR
    assert not is_retryable(
        RuntimeError("remote said [USER_ERROR] bad query"))
    assert is_retryable(RuntimeError("remote said [EXTERNAL] net down"))
    # a malformed plan re-plans identically: PLAN_VALIDATION fails fast
    from presto_tpu.common.errors import PLAN_VALIDATION, PlanValidationError
    assert classify_exception(PlanValidationError("bad")) == PLAN_VALIDATION
    assert not is_retryable(PlanValidationError("bad"))
    assert parse_error_type(
        "task q.0.0 failed [PLAN_VALIDATION]: bad") == PLAN_VALIDATION
    assert producer_task_from_text(
        "exchange source http://h:1/v1/task/q1.0_0.1.r2/results/3 "
        "vanished") == "q1.0_0.1.r2"


# ---------------------------------------------------------------------------
# concurrent exchange client (ExchangeClient)
# ---------------------------------------------------------------------------
# The tentpole of the concurrent-shuffle round: pulls from all upstream
# locations at once into a bounded arrival-order buffer.  These tests run
# it against a scriptable fake buffer server (per-location delay / stall /
# injected failure) and against real loopback clusters.

def _page_bytes(values):
    from presto_tpu.common.block import long_array_block
    from presto_tpu.common.page import Page
    from presto_tpu.common.serde import serialize_page
    return serialize_page(Page([long_array_block(values)]))


class _FakeBufferServer:
    """Minimal results-protocol producer with scriptable per-task behavior:
    specs maps task_id -> {"pages": [serialized bytes], "delay_s": float
    (per results GET), "stall_s": float (first GET only), "fail": (code,
    body) served instead of data}."""

    def __init__(self, specs):
        import http.server
        import re
        import threading
        import time as _t

        self.specs = specs
        rx = re.compile(
            r"^/v1/task/(?P<task>[^/]+)/results/(?P<buffer>\d+)"
            r"(?:/(?P<token>\d+)(?P<ack>/acknowledge)?)?$")
        stalled = {}
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, body=b"", headers=()):
                self.send_response(code)
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                m = rx.match(self.path.split("?")[0])
                if not m:
                    return self._reply(404)
                spec = outer.specs.get(m.group("task"))
                if spec is None:
                    return self._reply(404)
                if m.group("ack"):
                    return self._reply(200)
                if spec.get("fail"):
                    code, msg = spec["fail"]
                    return self._reply(code, msg.encode())
                if spec.get("stall_s") and not stalled.get(m.group("task")):
                    stalled[m.group("task")] = True
                    _t.sleep(spec["stall_s"])
                if spec.get("delay_s"):
                    _t.sleep(spec["delay_s"])
                pages = spec["pages"]
                token = int(m.group("token"))
                per_round = spec.get("per_round", 1)
                body = b"".join(pages[token:token + per_round])
                nxt = min(len(pages), token + per_round)
                return self._reply(200, body, [
                    ("X-Presto-Page-Sequence-Id", str(token)),
                    ("X-Presto-Page-End-Sequence-Id", str(nxt)),
                    ("X-Presto-Buffer-Complete",
                     "true" if nxt >= len(pages) else "false"),
                ])

            def do_DELETE(self):
                self._reply(200)

        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                      Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    def location(self, task_id, buffer_id=0):
        return (f"http://127.0.0.1:{self.port}/v1/task/{task_id}"
                f"/results/{buffer_id}")

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def test_concurrent_client_beats_sequential_with_slow_producers():
    """Acceptance: with 4 upstream producers each charging an artificial
    per-request latency, the concurrent client's end-to-end drain wall
    beats the sequential baseline by roughly the producer count."""
    import time
    from presto_tpu.worker.exchange import ExchangeClient, pull_pages

    specs = {f"t{i}": {"pages": [_page_bytes([i * 10 + j]) for j in range(3)],
                       "delay_s": 0.1} for i in range(4)}
    srv = _FakeBufferServer(specs)
    try:
        locations = [srv.location(f"t{i}") for i in range(4)]
        t0 = time.perf_counter()
        seq_values = []
        for loc in locations:
            for page in pull_pages(loc):
                seq_values.append(page.blocks[0].values[0])
        seq_wall = time.perf_counter() - t0

        t0 = time.perf_counter()
        client = ExchangeClient(locations, client_threads=4)
        conc_values = [p.blocks[0].values[0] for p in client.pages()]
        conc_wall = time.perf_counter() - t0

        assert sorted(conc_values) == sorted(seq_values)
        assert len(conc_values) == 12
        # 4 producers x 3 rounds x 0.1s sequentially vs ~3 rounds overlapped
        assert conc_wall < seq_wall * 0.6, (conc_wall, seq_wall)
    finally:
        srv.close()


def test_stalled_producer_does_not_starve_other_pullers():
    """Chaos: one producer stalls its first response; pages from the other
    producers must keep flowing through the shared buffer meanwhile."""
    import time
    from presto_tpu.worker.exchange import ExchangeClient

    specs = {"slow": {"pages": [_page_bytes([999])], "stall_s": 1.5}}
    for i in range(3):
        specs[f"fast{i}"] = {
            "pages": [_page_bytes([i * 10 + j]) for j in range(2)]}
    srv = _FakeBufferServer(specs)
    try:
        locations = [srv.location(t) for t in specs]
        client = ExchangeClient(locations, client_threads=4)
        t0 = time.perf_counter()
        arrivals = [(p.blocks[0].values[0], time.perf_counter() - t0)
                    for p in client.pages()]
        values = {v for v, _ in arrivals}
        assert values == {0, 1, 10, 11, 20, 21, 999}
        fast_done = max(at for v, at in arrivals if v != 999)
        slow_done = max(at for v, at in arrivals if v == 999)
        assert fast_done < 1.0, arrivals   # not starved behind the stall
        assert slow_done >= 1.0, arrivals  # the stall really happened
    finally:
        srv.close()


def test_exchange_client_backpressure_bounds_buffered_bytes():
    """Chaos: a fast producer against a slow consumer must park at the
    buffer bound — resident bytes stay <= exchange.max-buffer-size."""
    import time
    from presto_tpu.worker.exchange import ExchangeClient

    pages = [_page_bytes(list(range(k * 256, (k + 1) * 256)))
             for k in range(48)]          # ~2KB serialized each
    page_size = len(pages[0])
    limit = 4 * page_size                 # room for ~4 pages
    srv = _FakeBufferServer({"t0": {"pages": pages, "per_round": 2}})
    try:
        client = ExchangeClient([srv.location("t0")], client_threads=2,
                                max_buffer_bytes=limit)
        got = 0
        for _ in client.pages():
            got += 1
            time.sleep(0.005)             # slow consumer: queue fills
        assert got == len(pages)
        assert client.buffered_peak <= limit, (client.buffered_peak, limit)
        assert client.buffered_peak >= 2 * page_size  # it DID buffer ahead
    finally:
        srv.close()


def test_failed_sibling_aborts_client_promptly():
    """A failing producer surfaces its typed error through the concurrent
    client immediately — a stalled sibling location cannot delay failure
    propagation (the sequential client would sit in the stall first)."""
    import time
    from presto_tpu.common.errors import RemoteTaskError
    from presto_tpu.worker.exchange import ExchangeClient

    srv = _FakeBufferServer({
        "stalled": {"pages": [_page_bytes([1])], "stall_s": 5.0},
        "failing": {"pages": [], "fail": (
            500, "task failing failed [INTERNAL_ERROR]: boom")},
    })
    try:
        client = ExchangeClient(
            [srv.location("stalled"), srv.location("failing")],
            client_threads=2)
        t0 = time.perf_counter()
        with pytest.raises(RemoteTaskError, match="INTERNAL_ERROR"):
            list(client.pages())
        assert time.perf_counter() - t0 < 2.5
    finally:
        srv.close()


def test_failed_task_aborts_worker_remote_source_promptly():
    """Regression (the should_abort bug): a worker task's remote source
    must stop pulling as soon as the task turns terminal — e.g. a FAILED
    sibling propagated by the coordinator — even while its producer is
    stalled and would otherwise hold the puller for seconds."""
    import threading
    import time
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.worker.exchange import (ExchangeAbortedError,
                                            remote_page_reader)
    from presto_tpu.worker.task import TpuTask

    srv = _FakeBufferServer(
        {"slow": {"pages": [_page_bytes([1])], "stall_s": 10.0}})
    task = TpuTask("q.1.0", "http://127.0.0.1:0", ExecutionConfig())
    outcome = []

    def consume():
        # the exact reader wiring TpuTask.start() builds for remote splits
        reader = remote_page_reader([srv.location("slow")],
                                    should_abort=task._exchange_abort)
        try:
            list(reader())
            outcome.append("drained")
        except ExchangeAbortedError:
            outcome.append("aborted")

    t = threading.Thread(target=consume, daemon=True)
    try:
        t.start()
        time.sleep(0.3)                  # puller is inside the 10s stall
        task.fail("chaos: sibling task failed")
        t.join(timeout=3.0)
        assert not t.is_alive(), "remote source kept draining a dead task"
        assert outcome == ["aborted"]
    finally:
        srv.close()


def test_chaos_worker_kill_exactly_once_with_four_producers(lock_validation):
    """Worker death mid-pull with >= 4 upstream producers per consumer:
    the concurrent client + retained-buffer replay must still deliver
    oracle-correct rows exactly once."""
    import threading
    from presto_tpu.common.errors import InjectedTaskFailure
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w1, w2, w3 = WorkerServer(), WorkerServer(), WorkerServer()
    killed = threading.Event()

    def kill_on_first_task(task_id):
        if not killed.is_set():
            killed.set()
            threading.Thread(target=w2.close, daemon=True).start()
            raise InjectedTaskFailure(
                f"chaos: worker dying under task {task_id}")

    w2.task_manager.fault_injector = kill_on_first_task
    try:
        r = HttpQueryRunner(
            [w1.uri, w2.uri, w3.uri], "sf0.01", n_tasks=4,
            session={"exchange_max_error_duration": "5s",
                     "lock_validation": "on"})
        got = r.execute(CHAOS_SQL)
        _assert_same(got, CHAOS_SQL)
        assert killed.is_set(), "chaos hook never fired"
        assert r.tasks_retried >= 1
    finally:
        for w in (w1, w2, w3):
            w.close()


def test_exchange_metrics_and_buffer_bound_via_http():
    """Acceptance: the /v1/metrics exchange section reports pages/bytes
    moved, and the buffered-bytes peak stays under the session's
    exchange.max-buffer-size while a shuffle query runs."""
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.exchange import EXCHANGE_METRICS
    from presto_tpu.worker.server import WorkerServer

    w1, w2 = WorkerServer(), WorkerServer()
    try:
        EXCHANGE_METRICS.reset()
        r = HttpQueryRunner(
            [w1.uri, w2.uri], "sf0.01", n_tasks=2,
            session={"exchange_max_buffer_size": "1MB",
                     "exchange_max_response_size": "64kB"})
        got = r.execute(CHAOS_SQL)
        _assert_same(got, CHAOS_SQL)
        assert _metric(w1.uri, "presto_tpu_exchange_pages_total") > 0
        assert _metric(w1.uri, "presto_tpu_exchange_bytes_total") > 0
        assert _metric(w1.uri, "presto_tpu_exchange_clients_total") > 0
        peak = _metric(w1.uri, "presto_tpu_exchange_buffered_bytes_peak")
        assert 0 < peak <= 1 << 20, peak
        # every client is closed: the live gauge must drain back to zero
        assert _metric(w1.uri, "presto_tpu_exchange_buffered_bytes") == 0
    finally:
        w1.close()
        w2.close()


def test_exchange_runtime_stats_surfaced():
    """The root pull's per-client walls/bytes land in the query result's
    runtime stats (and per-task clients land in TaskInfo)."""
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    try:
        r = HttpQueryRunner([w.uri], "sf0.01", n_tasks=2)
        got = r.execute(CHAOS_SQL)
        _assert_same(got, CHAOS_SQL)
        stats = got.runtime_stats or {}
        assert stats["exchangeClientPages"]["sum"] > 0
        assert stats["exchangeClientBytes"]["sum"] > 0
        assert stats["exchangeClientPullWallNanos"]["sum"] > 0
        assert stats["exchangeClientDrainWallNanos"]["sum"] > 0
    finally:
        w.close()


# ---------------------------------------------------------------------------
# fault-tolerant execution mode (retry-policy=task): durable spooled
# exchange, task-granular retry, graceful decommission, query deadlines
# ---------------------------------------------------------------------------

_RETRY_SUFFIX_RX = None


def _base_lineage(task_id):
    import re
    global _RETRY_SUFFIX_RX
    if _RETRY_SUFFIX_RX is None:
        _RETRY_SUFFIX_RX = re.compile(r"\.r\d+$")
    return _RETRY_SUFFIX_RX.sub("", task_id)


def test_chaos_task_retry_policy_retries_only_failed_task(lock_validation):
    """Tentpole: under retry-policy=task a transient task failure retries
    ONLY the failed lineage — ancestors' spooled output replays, so no
    ancestor stage gets a .rN re-run — and rows stay oracle-exact."""
    from presto_tpu.common.errors import InjectedTaskFailure
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer
    from presto_tpu.worker.spooling import SPOOL_METRICS

    w1, w2 = WorkerServer(), WorkerServer()
    flaked = []

    def flaky_once(task_id):
        if not flaked:
            flaked.append(task_id)
            raise InjectedTaskFailure(f"chaos: flaky task {task_id}")

    w1.task_manager.fault_injector = flaky_once
    w2.task_manager.fault_injector = flaky_once
    SPOOL_METRICS.reset()
    try:
        r = HttpQueryRunner([w1.uri, w2.uri], "sf0.01", n_tasks=2,
                            session={"retry_policy": "task",
                                     "lock_validation": "on"})
        got = r.execute(CHAOS_SQL)
        _assert_same(got, CHAOS_SQL)
        assert len(flaked) == 1
        assert r.tasks_retried >= 1
        exe = r.last_execution
        failed_lineage = _base_lineage(flaked[0])
        # ONLY the failed lineage was charged against the attempt budget
        assert dict(exe.budget_used) == {failed_lineage: 1}
        # ...and every .rN attempt anywhere in the cluster belongs to it:
        # no ancestor stage was restarted
        retry_ids = [t.task_id for t in exe.all_tasks
                     if _base_lineage(t.task_id) != t.task_id]
        assert retry_ids, "no retry attempt was placed"
        assert {_base_lineage(t) for t in retry_ids} == {failed_lineage}
        # the durable spool actually carried stage output
        snap = SPOOL_METRICS.snapshot()
        assert snap["spooled_pages"] > 0 and snap["spooled_bytes"] > 0
        assert _metric(w1.uri, "presto_tpu_spool_spooled_bytes_total") > 0
    finally:
        w1.close()
        w2.close()


def test_chaos_worker_killed_task_policy_no_ancestor_rerun(lock_validation):
    """Tentpole acceptance: kill a worker mid-query under
    retry-policy=task.  Recovery re-runs only the lineages that were
    placed on the dead worker (their consumers redirect to the
    replacements' spooled buffers) and the rows are oracle-exact."""
    import threading
    from presto_tpu.common.errors import InjectedTaskFailure
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w1, w2, w3 = WorkerServer(), WorkerServer(), WorkerServer()
    killed = threading.Event()

    def kill_on_first_task(task_id):
        if not killed.is_set():
            killed.set()
            threading.Thread(target=w2.close, daemon=True).start()
            raise InjectedTaskFailure(
                f"chaos: worker dying under task {task_id}")

    w2.task_manager.fault_injector = kill_on_first_task
    try:
        r = HttpQueryRunner(
            [w1.uri, w2.uri, w3.uri], "sf0.01", n_tasks=2,
            session={"retry_policy": "task",
                     "exchange_max_error_duration": "10s",
                     "lock_validation": "on"})
        got = r.execute(CHAOS_SQL)
        _assert_same(got, CHAOS_SQL)
        assert killed.is_set(), "chaos hook never fired"
        assert r.tasks_retried >= 1
        exe = r.last_execution
        dead_lineages = {_base_lineage(t.task_id) for t in exe.all_tasks
                         if t.worker_uri == w2.uri}
        # every charged lineage and every .rN attempt traces back to a
        # task that was on the dead worker: survivors never re-ran
        assert set(exe.budget_used) <= dead_lineages
        retried = {_base_lineage(t.task_id) for t in exe.all_tasks
                   if _base_lineage(t.task_id) != t.task_id}
        assert retried and retried <= dead_lineages
        for t in exe.all_tasks:
            if _base_lineage(t.task_id) != t.task_id:
                assert t.worker_uri != w2.uri  # retries land on survivors
    finally:
        for w in (w1, w2, w3):
            w.close()


def test_chaos_graceful_drain_zero_failures(lock_validation):
    """PUT /v1/info/state SHUTTING_DOWN on a worker while queries are in
    flight: every query completes with oracle-exact rows (its spooled
    output survives until consumed), the scheduler stops placing tasks on
    the draining worker, and the process exits cleanly."""
    import threading
    import time
    import urllib.request
    from presto_tpu.worker.auth import outbound_headers
    from presto_tpu.worker.coordinator import (HeartbeatFailureDetector,
                                               HttpQueryRunner)
    from presto_tpu.worker.server import WorkerServer

    w1, w2, w3 = WorkerServer(), WorkerServer(), WorkerServer()
    uris = [w1.uri, w2.uri, w3.uri]
    det = HeartbeatFailureDetector(uris, interval_s=0.1)
    session = {"retry_policy": "task", "lock_validation": "on"}
    runners = [HttpQueryRunner(uris, "sf0.01", n_tasks=2,
                               failure_detector=det, session=session)
               for _ in range(2)]
    results, errors = [], []

    def run_one(runner):
        try:
            results.append(runner.execute(CHAOS_SQL))
        except Exception as e:  # noqa: BLE001 — the test asserts on it
            errors.append(e)

    try:
        # warm both runners so tasks have landed on every worker and the
        # pipelines are compiled before the chaos window opens
        for r in runners:
            _assert_same(r.execute(CHAOS_SQL), CHAOS_SQL)
        threads = [threading.Thread(target=run_one, args=(r,))
                   for r in runners]
        for t in threads:
            t.start()
        time.sleep(0.1)                    # queries are mid-flight
        req = urllib.request.Request(
            w3.uri + "/v1/info/state", data=b'"SHUTTING_DOWN"',
            method="PUT", headers={"Content-Type": "application/json",
                                   **outbound_headers()})
        urllib.request.urlopen(req, timeout=5).close()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)
        assert not errors, errors          # zero query failures
        assert len(results) == 2
        for got in results:
            _assert_same(got, CHAOS_SQL)
        # the detector observes the drain and excludes w3 from placement
        deadline = time.time() + 5
        while time.time() < deadline and \
                det.snapshot()[w3.uri]["draining"] is not True:
            time.sleep(0.05)
        assert det.snapshot()[w3.uri]["draining"] is True
        created_before = w3.task_manager.counts()["created"]
        _assert_same(runners[0].execute(CHAOS_SQL), CHAOS_SQL)
        assert w3.task_manager.counts()["created"] == created_before, \
            "draining worker was given new tasks"
        # drained output is consumed, so the server exits on its own
        deadline = time.time() + 45
        while time.time() < deadline and not w3._closed:
            time.sleep(0.2)
        assert w3._closed, "graceful drain never completed"
    finally:
        det.close()
        for w in (w1, w2, w3):
            w.close()


def test_chaos_query_deadline_typed_error_no_retry(lock_validation):
    """query.max-execution-time mints a typed, NON-retryable
    EXCEEDED_TIME_LIMIT user error at the coordinator: no task retry is
    attempted anywhere and the failure surfaces promptly."""
    import time
    from presto_tpu.common.errors import (PrestoUserError,
                                          QueryDeadlineExceededError)
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    try:
        r = HttpQueryRunner(
            [w.uri], "sf0.01", n_tasks=2,
            session={"query_max_execution_time": "50ms",
                     "lock_validation": "on"})
        t0 = time.monotonic()
        with pytest.raises(QueryDeadlineExceededError,
                           match="EXCEEDED_TIME_LIMIT"):
            r.execute(CHAOS_SQL)
        elapsed = time.monotonic() - t0
        assert elapsed < 15.0, elapsed     # enforced, not TTL'd out
        assert r.tasks_retried == 0
        assert w.task_manager.tasks_retried == 0
        # typed USER_ERROR: the classifier must never call this retryable
        from presto_tpu.common.errors import is_retryable
        assert issubclass(QueryDeadlineExceededError, PrestoUserError)
        assert not is_retryable(QueryDeadlineExceededError(1.0, 0.05))
    finally:
        w.close()


def test_chaos_poison_split_quarantined(lock_validation):
    """A split that fails with the SAME internal error signature on two
    distinct workers is poison: the query fails fast with the split
    identity in the typed error instead of burning the whole attempt
    budget re-running a crasher."""
    from presto_tpu.common.errors import (InjectedTaskFailure,
                                          PoisonSplitError)
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w1, w2 = WorkerServer(), WorkerServer()
    target = []

    def poison(task_id):
        base = _base_lineage(task_id)
        if not target:
            target.append(base)
        if base == target[0]:
            raise InjectedTaskFailure("chaos: poison split crash")

    w1.task_manager.fault_injector = poison
    w2.task_manager.fault_injector = poison
    try:
        r = HttpQueryRunner(
            [w1.uri, w2.uri], "sf0.01", n_tasks=2,
            session={"remote_task_retry_attempts": "4",
                     "lock_validation": "on"})
        with pytest.raises(PoisonSplitError, match="POISON_SPLIT") as ei:
            r.execute(CHAOS_SQL)
        # the split identity is in the message, and quarantine fired well
        # inside the 4-attempt budget (one charge, then two distinct
        # workers had seen the signature)
        assert target[0] in str(ei.value)
        exe = r.last_execution
        assert exe.budget_used.get(target[0], 0) <= 2
    finally:
        w1.close()
        w2.close()


def test_producer_coalesces_small_pages_per_response():
    """Producer-side exchange.max-response-size: many tiny pages come back
    in few coalesced pull rounds, but an X-Presto-Max-Size cap well below
    the coalesce target still bounds each response."""
    from presto_tpu.worker.buffers import PageBuffer

    tiny = _page_bytes([1, 2, 3])
    buf = PageBuffer(coalesce_target_bytes=len(tiny) * 4)
    for _ in range(10):
        buf.add(tiny)
    buf.set_complete()
    pages, nxt, done = buf.get(0, max_wait_s=0.1)
    # 10 tiny adds -> 3 coalesced entries (4 + 4 + final 2), not 10 rounds
    assert [len(p) // len(tiny) for p in pages] == [4, 4, 2]
    assert done and nxt == 3
    # consumer byte cap takes precedence over the coalesced batch count
    capped, nxt2, done2 = buf.get(0, max_wait_s=0.1,
                                  max_bytes=len(tiny) * 4)
    assert len(capped) == 1 and not done2 and nxt2 == 1
