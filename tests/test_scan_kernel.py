"""Pallas fused scan kernel (presto_tpu/exec/kernels): parity fuzz vs
the XLA fused chain and the numpy reference oracle, decline-reason
coverage for the kernelDeclined{reason} counters, and operator-stats
fidelity on the kernel path.

The kernel runs through kernels/shim.py, which flips interpret=True
off-TPU, so these tests execute the REAL kernel body (late decode ->
predicate -> Blelloch prefix-sum compaction -> subtile partial agg)
on CPU.  Integer aggregates and row counters must match the XLA chain
exactly; TPC-H money columns are unscaled int64 decimals, so the money
sums and averages are exact too, not merely close."""
import numpy as np
import pytest

from presto_tpu.exec.kernels import KERNEL_DECLINE_REASONS
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner, _assert_rows_equal

Q6 = """
    select sum(l_extendedprice * l_discount) as revenue from lineitem
    where l_shipdate >= date '1994-01-01'
      and l_shipdate < date '1995-01-01'
      and l_discount between 0.05 and 0.07 and l_quantity < 24
"""

Q1 = """
    select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
           sum(l_extendedprice) as sum_base_price,
           sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
           avg(l_quantity) as avg_qty, min(l_quantity) as min_qty,
           max(l_extendedprice) as max_price, count(*) as count_order
    from lineitem where l_shipdate <= date '1998-09-02'
    group by l_returnflag, l_linestatus
    order by l_returnflag, l_linestatus
"""


def _kernel_programs(res) -> int:
    return int((res.runtime_stats or {}).get(
        "kernelScanPrograms", {}).get("sum", 0))


def _declined(res) -> dict:
    return {k[len("kernelDeclined"):]: int(v.get("sum", 0))
            for k, v in (res.runtime_stats or {}).items()
            if k.startswith("kernelDeclined")}


@pytest.fixture(scope="module")
def pallas():
    return LocalQueryRunner(
        "sf0.01", config=ExecutionConfig(scan_kernel="pallas"))


@pytest.fixture(scope="module")
def xla():
    return LocalQueryRunner(
        "sf0.01", config=ExecutionConfig(scan_kernel="xla"))


# ---------------------------------------------------------------------------
# the kernel actually runs, and matches the oracle
# ---------------------------------------------------------------------------

def test_q6_kernel_engages_and_matches_oracle(pallas):
    res = pallas.assert_same_as_reference(Q6)
    assert _kernel_programs(res) >= 1, _declined(res)


def test_q1_grouped_kernel_matches_oracle(pallas):
    # dict-encoded group keys (returnflag/linestatus) through the
    # in-kernel stride-code accumulators, incl. min/max/avg/count(*)
    res = pallas.assert_same_as_reference(Q1, ordered=True)
    assert _kernel_programs(res) >= 1, _declined(res)


def test_rle_decode_path_matches_oracle(pallas):
    # l_orderkey is monotone -> RLE resident encoding: the predicate
    # forces the kernel's binary-search run decode (and zone pruning
    # folded into the aligned grid)
    sql = ("select count(*), sum(l_extendedprice), max(l_orderkey) "
           "from lineitem where l_orderkey < 150")
    res = pallas.assert_same_as_reference(sql)
    assert _kernel_programs(res) >= 1, _declined(res)
    from presto_tpu.storage.store import get_store
    kinds = {k[2]: e.column.kind for k, e in get_store().entries.items()
             if k[1] == "lineitem"}
    assert kinds.get("orderkey") == "rle", kinds


# ---------------------------------------------------------------------------
# parity fuzz: randomized predicates x encodings x agg shapes, Pallas
# output vs the XLA chain (and, each seed, vs the reference oracle)
# ---------------------------------------------------------------------------

_AGGS = ["count(*)", "sum(l_quantity)", "sum(l_extendedprice)",
         "sum(l_extendedprice * l_discount)", "min(l_quantity)",
         "max(l_extendedprice)", "avg(l_discount)"]
_GROUPS = ["", "l_returnflag", "l_returnflag, l_linestatus"]


def _fuzz_sql(seed: int) -> str:
    rng = np.random.default_rng(seed)
    conj = [f"l_quantity < {int(rng.integers(5, 45))}"]
    if rng.integers(2):
        lo = int(rng.integers(0, 7)) / 100.0
        hi = lo + int(rng.integers(1, 4)) / 100.0
        conj.append(f"l_discount between {lo:.2f} and {hi:.2f}")
    if rng.integers(2):
        y = int(rng.integers(1992, 1998))
        conj.append(f"l_shipdate >= date '{y}-01-01' "
                    f"and l_shipdate < date '{y + 1}-07-01'")
    if rng.integers(2):
        # RLE column + zone pruning on the kernel's aligned grid
        conj.append(f"l_orderkey < {int(rng.integers(100, 20_000))}")
    n_aggs = int(rng.integers(2, 5))
    aggs = [_AGGS[i] for i in rng.choice(len(_AGGS), n_aggs,
                                         replace=False)]
    group = _GROUPS[int(rng.integers(len(_GROUPS)))]
    sql = (f"select {group + ', ' if group else ''}{', '.join(aggs)} "
           f"from lineitem where {' and '.join(conj)}")
    if group:
        sql += f" group by {group}"
    return sql


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_parity_fuzz_pallas_vs_xla_vs_oracle(pallas, xla, seed):
    sql = _fuzz_sql(seed)
    pres = pallas.execute(sql)
    xres = xla.execute(sql)
    _assert_rows_equal(pres, xres, ordered=False)
    assert _kernel_programs(pres) >= 1, (sql, _declined(pres))
    assert _kernel_programs(xres) == 0
    assert _declined(xres).get("Disabled", 0) >= 1
    # reference oracle on the same query (row-at-a-time numpy engine)
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


# grouped fuzz: G randomized across the direct/span/hash slot-mode
# boundaries (6 direct, 168 span, open-domain + computed-modulus hash),
# same encodings and predicate shapes as the direct fuzz
_GROUPED_KEYS = [
    "l_returnflag, l_linestatus",                           # direct, G=6
    "l_returnflag, l_linestatus, l_shipmode, l_shipinstruct",  # span, G=168
    "l_orderkey",                                           # hash, open int
]


@pytest.mark.parametrize("seed", [11, 12, 13, 14])
def test_grouped_parity_fuzz(pallas, xla, seed):
    rng = np.random.default_rng(seed)
    n_aggs = int(rng.integers(2, 5))
    aggs = [_AGGS[i] for i in rng.choice(len(_AGGS), n_aggs,
                                         replace=False)]
    qty = int(rng.integers(10, 45))
    if seed % 2:
        group = _GROUPED_KEYS[int(rng.integers(len(_GROUPED_KEYS)))]
        sql = (f"select {group}, {', '.join(aggs)} from lineitem "
               f"where l_quantity < {qty} group by {group}")
    else:
        # randomized G through a computed modulus key: always an open
        # int64 domain, so the hashed slot mode carries it
        g = int(rng.integers(65, 20_000))
        aggs = [a.replace("l_", "") for a in aggs]
        sql = (f"select gkey, {', '.join(aggs)} from "
               f"(select orderkey % {g} as gkey, quantity, "
               f"extendedprice, discount from lineitem) "
               f"where quantity < {qty} group by gkey")
    pres = pallas.execute(sql)
    xres = xla.execute(sql)
    _assert_rows_equal(pres, xres, ordered=False)
    assert _kernel_programs(pres) >= 1, (sql, _declined(pres))
    assert _kernel_programs(xres) == 0
    assert _declined(xres).get("Disabled", 0) >= 1
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


def test_dma_double_buffer_parity():
    # scan.kernel-dma = double stages block k+1's slabs into the
    # alternate VMEM buffer while block k computes: identical results,
    # plus the overlap-fraction stat (absent in single mode)
    import dataclasses
    base = ExecutionConfig(scan_kernel="pallas", batch_rows=8192)
    sql = ("select l_orderkey, sum(l_quantity), count(*) from lineitem "
           "where l_orderkey < 3000 group by l_orderkey")
    single = LocalQueryRunner("sf0.01", config=base)
    double = LocalQueryRunner("sf0.01", config=dataclasses.replace(
        base, scan_kernel_dma="double"))
    res_s = single.execute(sql)
    res_d = double.execute(sql)
    _assert_rows_equal(res_s, res_d, ordered=False)
    assert _kernel_programs(res_s) >= 1, _declined(res_s)
    assert _kernel_programs(res_d) >= 1, _declined(res_d)
    ov = (res_d.runtime_stats or {}).get("kernelDmaOverlapFraction")
    assert ov and ov["count"] >= 1
    # batch_rows=8192 splits sf0.01 lineitem into a multi-block grid:
    # every block after the first was prefetched
    assert 0.0 < ov["max"] <= 1.0
    assert "kernelDmaOverlapFraction" not in (res_s.runtime_stats or {})
    _assert_rows_equal(res_d, double.execute_reference(sql),
                       ordered=False)


def test_row_counters_match_xla_chain(pallas, xla):
    # the device-side counters feed the operator-stats spine: rows per
    # plan node (scan -> filter -> agg) must be identical across the
    # two scan implementations, not just the final result rows
    sql = "EXPLAIN ANALYZE " + Q6.strip()
    pallas.execute(sql)
    xla.execute(sql)
    prows = {nid: s.get("rows")
             for nid, s in (pallas.last_operator_stats or {}).items()}
    xrows = {nid: s.get("rows")
             for nid, s in (xla.last_operator_stats or {}).items()}
    assert prows and prows == xrows


# ---------------------------------------------------------------------------
# decline reasons: every ineligible shape is metered, never mis-run
# ---------------------------------------------------------------------------

def test_decline_disabled(xla):
    res = xla.assert_same_as_reference(Q6)
    assert _kernel_programs(res) == 0
    assert _declined(res).get("Disabled", 0) >= 1


def test_grouped_hash_kernel_engages(pallas):
    # high-cardinality open-domain group key: runs in-kernel via the
    # hashed open-addressing slot mode (kernels/grouped.py) — the shape
    # that used to decline as AggShape
    res = pallas.assert_same_as_reference(
        "select l_orderkey, count(*) from lineitem group by l_orderkey")
    assert _kernel_programs(res) >= 1, _declined(res)
    assert not _declined(res)


def test_grouped_span_kernel_engages(pallas):
    # 3*2*7*4 = 168 groups: over the direct accumulator grid (G <= 64)
    # but inside the span gate, so the combined stride code addresses
    # the accumulator stacks directly in-kernel
    res = pallas.assert_same_as_reference(
        "select l_returnflag, l_linestatus, l_shipmode, l_shipinstruct, "
        "sum(l_quantity), avg(l_discount), count(*) from lineitem "
        "group by 1, 2, 3, 4")
    assert _kernel_programs(res) >= 1, _declined(res)
    assert not _declined(res)


def test_decline_agg_function_shape(pallas):
    # moment aggregates have no in-kernel accumulator shape: the miss
    # is metered under the split vocabulary (was AggShape)
    res = pallas.execute(
        "select l_returnflag, stddev(l_quantity) from lineitem "
        "group by l_returnflag")
    assert _kernel_programs(res) == 0
    assert _declined(res).get("AggFunctionShape", 0) >= 1


def test_decline_agg_group_cardinality(monkeypatch):
    # the capacity gate declines only truly huge G: shrink the slot cap
    # so the optimizer's group estimate overflows it
    from presto_tpu.exec.kernels import grouped as gk
    monkeypatch.setattr(gk, "KERNEL_HASH_MAX_SLOTS", 16)
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        scan_kernel="pallas"))
    res = r.assert_same_as_reference(
        "select l_orderkey, count(*) from lineitem group by l_orderkey")
    assert _kernel_programs(res) == 0
    assert _declined(res).get("AggGroupCardinality", 0) >= 1


def test_join_chain_runs_in_kernel(pallas):
    # PR 16: probe-side joins lower into the kernel body
    # (kernels/join.py) instead of declining as PlanShape — the shape
    # that used to be this file's PlanShape fixture now engages
    res = pallas.assert_same_as_reference(
        "select count(*) from lineitem, orders "
        "where l_orderkey = o_orderkey")
    assert _kernel_programs(res) >= 1, _declined(res)
    assert not _declined(res)


def test_decline_plan_shape():
    # uid steps (count(distinct)-style rewrites) stay outside the
    # kernel's step vocabulary even with joins allowed
    from presto_tpu.exec.kernels.scan_kernel import chain_eligible

    class _Chain:
        steps = [("uid", None)]
        scan_meta: dict = {}
    reasons = []
    assert not chain_eligible(_Chain(), (None,), reasons.append,
                              allow_joins=True)
    assert reasons == ["PlanShape"]


def test_decline_columns_not_resident():
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        scan_kernel="pallas", storage_enabled=False))
    res = r.assert_same_as_reference(Q6)
    assert _kernel_programs(res) == 0
    assert _declined(res).get("ColumnsNotResident", 0) >= 1


def test_misaligned_chunk_tail_padded():
    # non-power-of-two chunk capacities are padded up to the pow2 block
    # (tail lanes masked dead by the [lo, hi) live window) instead of
    # declining the whole scan; no decline of any kind may fire
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        scan_kernel="pallas", batch_rows=5000))
    res = r.assert_same_as_reference(Q6)
    assert _kernel_programs(res) >= 1, _declined(res)
    assert _declined(res) == {}


def test_decline_backend_auto_off_tpu():
    # auto is a performance decision: off-TPU the kernel only runs in
    # interpret-mode emulation, so auto takes the XLA chain and meters
    # Backend; explicit scan_kernel="pallas" pins the kernel (the other
    # fixtures in this file) so CI still executes the real kernel body
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        scan_kernel="auto"))
    res = r.assert_same_as_reference(Q6)
    assert _kernel_programs(res) == 0
    assert _declined(res).get("Backend", 0) >= 1


def test_decline_reasons_are_closed():
    # the reason vocabulary is the EXPLAIN ANALYZE contract: keep it
    # closed
    assert set(KERNEL_DECLINE_REASONS) == {
        "Disabled", "AggFunctionShape", "AggGroupCardinality",
        "Backend", "PlanShape", "ColumnsNotResident",
        "JoinShape", "JoinBuildSize",
        "WindowFunctionShape", "WindowKeyShape", "WindowInputSize"}


# ---------------------------------------------------------------------------
# observability on the kernel path
# ---------------------------------------------------------------------------

def test_explain_analyze_footer_reports_kernel(pallas, xla):
    text = pallas.execute("EXPLAIN ANALYZE " + Q6.strip()).rows[0][0]
    assert "Pallas scan kernels: 1" in text
    ops = pallas.last_operator_stats or {}
    scan = [s for nid, s in ops.items() if nid.startswith("scan")]
    aggs = [s for nid, s in ops.items() if nid.startswith("agg")]
    assert scan and scan[0]["rows"] > 0
    assert aggs and aggs[-1]["rows"] >= 1
    xtext = xla.execute("EXPLAIN ANALYZE " + Q6.strip()).rows[0][0]
    assert "Scan kernel declined" in xtext and "Disabled" in xtext
