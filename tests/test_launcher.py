"""External-worker launcher (VERDICT r2 #10): the
setExternalWorkerLauncher-shaped entry a Java DistributedQueryRunner uses
to spawn TPU workers (DistributedQueryRunner.java:190-215,
PrestoNativeQueryRunnerUtils.java:434-520).  The Java-coordinator parity
test runs whenever PRESTO_JAVA_COORDINATOR_URI is set and skips otherwise
— the moment a Java coordinator exists in the environment, the suite
exercises it with zero code changes.
"""
import json
import os
import time
import urllib.request

import pytest

from presto_tpu.worker.launcher import launch_worker, write_etc_dir


def test_write_etc_dir_layout(tmp_path):
    etc = write_etc_dir(3, "http://127.0.0.1:9999", base_dir=str(tmp_path))
    from presto_tpu.worker.properties import load_properties
    cfg = load_properties(os.path.join(etc, "config.properties"))
    assert cfg["discovery.uri"] == "http://127.0.0.1:9999"
    assert cfg["http-server.http.port"] == "0"
    node = load_properties(os.path.join(etc, "node.properties"))
    assert node["node.environment"] == "testing"
    assert os.path.exists(
        os.path.join(etc, "catalog", "tpchstandard.properties"))


def test_launcher_spawns_announcing_worker(tmp_path):
    """launch_worker(index, discoveryUri) -> a worker that announces to
    the coordinator's discovery and serves queries (the exact contract
    the Java harness relies on)."""
    from presto_tpu.worker import HttpQueryRunner, WorkerServer
    coordinator = WorkerServer(coordinator=True, environment="testing")
    proc = None
    try:
        proc = launch_worker(0, coordinator.uri, base_dir=str(tmp_path))
        deadline = time.time() + 60
        while not coordinator.worker_uris() and time.time() < deadline:
            time.sleep(0.1)
        uris = coordinator.worker_uris()
        assert uris, "worker never announced"
        r = HttpQueryRunner(uris, "sf0.01", n_tasks=1)
        res = r.execute("select count(*) from nation")
        assert res.rows == [[25]]
    finally:
        if proc is not None:
            proc.terminate()
            proc.wait(timeout=10)
        coordinator.close()


def test_launcher_exec_form(tmp_path):
    """`python -m presto_tpu.worker.launcher <index> <discoveryUri>` — the
    ProcessBuilder form for the Java side; the Process handle IS the
    worker (terminate kills it)."""
    import subprocess
    import sys
    from presto_tpu.worker import WorkerServer
    coordinator = WorkerServer(coordinator=True, environment="testing")
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "-m", "presto_tpu.worker.launcher",
         "1", coordinator.uri],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, env=env)
    try:
        deadline = time.time() + 60
        while not coordinator.worker_uris() and time.time() < deadline:
            time.sleep(0.1)
        assert coordinator.worker_uris(), "exec-form worker never announced"
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        coordinator.close()


JAVA_URI = os.environ.get("PRESTO_JAVA_COORDINATOR_URI")


@pytest.mark.skipif(not JAVA_URI, reason
                    ="PRESTO_JAVA_COORDINATOR_URI not set (no Java "
                      "coordinator in this environment)")
def test_java_coordinator_parity():
    """Drive a real Java coordinator (whose workers are TPU workers
    spawned via the launcher) through the statement protocol and compare
    against the local engine."""
    from presto_tpu.exec.runner import LocalQueryRunner
    for sql in ("select count(*) from nation",
                "select l_returnflag, l_linestatus, sum(l_quantity) "
                "from lineitem group by l_returnflag, l_linestatus "
                "order by l_returnflag, l_linestatus"):
        req = urllib.request.Request(
            JAVA_URI.rstrip("/") + "/v1/statement", data=sql.encode(),
            headers={"X-Presto-User": "parity",
                     "X-Presto-Catalog": "tpchstandard",
                     "X-Presto-Schema": "sf0.01"})
        d = json.loads(urllib.request.urlopen(req, timeout=30).read())
        rows = list(d.get("data", []))
        deadline = time.time() + 300
        while "nextUri" in d and time.time() < deadline:
            d = json.loads(urllib.request.urlopen(
                d["nextUri"], timeout=30).read())
            rows.extend(d.get("data", []))
        assert "error" not in d, d.get("error")
        local = LocalQueryRunner("sf0.01").execute(sql).rows
        assert [[*map(str, r)] for r in rows] == \
            [[*map(str, r)] for r in local]
