"""Connector SPI (spi/connector.py — Plugin.java:42 /
ConnectorMetadata.java:73 / ConnectorSplitManager.java:23 /
ConnectorPageSource.java:23 analogs).

Both directions: the SPI view over built-in catalogs, and a third-party
connector written ONLY against the interfaces registered through
register_plugin and driven end-to-end by SQL."""
from typing import Dict, List

import pytest

from presto_tpu.common.block import block_from_values, block_to_values
from presto_tpu.common.page import Page
from presto_tpu.common.types import BIGINT, DOUBLE, VarcharType
from presto_tpu.connectors import catalog
from presto_tpu.exec.runner import LocalQueryRunner
from presto_tpu.spi.connector import (Connector, ConnectorFactory,
                                      ConnectorMetadata, ConnectorPageSource,
                                      ConnectorPageSourceProvider,
                                      ConnectorSplitManager, Plugin,
                                      RowRangeSplit, module_connector,
                                      register_plugin)


# ---------------------------------------------------------------------------
# SPI view over the built-in catalogs
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cid,table,expect_rows",
                         [("tpch", "nation", 25),
                          ("tpcds", "item", None)])
def test_module_connector_spi_view(cid, table, expect_rows):
    conn = module_connector(cid)
    meta = conn.get_metadata()
    assert table in meta.list_tables()
    cols = meta.get_columns(table)
    assert cols and all(len(c) == 2 for c in cols)
    splits = conn.get_split_manager().get_splits(table, 0.01, 4)
    assert splits and all(isinstance(s, RowRangeSplit) for s in splits)
    total = sum(s.end - s.start for s in splits)
    if expect_rows is not None:
        assert total == expect_rows
    # page source streams real pages with the declared column order
    first_col = cols[0][0]
    src = conn.get_page_source_provider().create_page_source(
        splits[0], [first_col], 0.01)
    pages = list(src.pages())
    assert pages and pages[0].position_count > 0
    vals = block_to_values(cols[0][1], pages[0].blocks[0])
    assert len(vals) == pages[0].position_count


def test_module_connector_statistics():
    meta = module_connector("tpch").get_metadata()
    st = meta.get_table_statistics("orders", "orderkey", 0.01)
    assert st is not None and st.low == 1


# ---------------------------------------------------------------------------
# a third-party connector written purely against the SPI
# ---------------------------------------------------------------------------

_ROWS = [
    (1, "alpha", 1.5),
    (2, "beta", 2.5),
    (3, "gamma", None),
    (4, "alpha", 4.0),
    (5, None, 0.25),
]


class _LettersMetadata(ConnectorMetadata):
    def list_tables(self):
        return ["letters"]

    def get_columns(self, table):
        if table != "letters":
            raise KeyError(table)
        return [("id", BIGINT), ("name", VarcharType(8)),
                ("score", DOUBLE)]


class _LettersSplits(ConnectorSplitManager):
    def get_splits(self, table, scale_factor, desired_splits):
        n = len(_ROWS)
        half = (n + 1) // 2
        return [RowRangeSplit(table, 0, half),
                RowRangeSplit(table, half, n)]


class _LettersPageSource(ConnectorPageSource):
    def __init__(self, split, columns):
        self._split, self._columns = split, columns

    def pages(self):
        idx = {"id": 0, "name": 1, "score": 2}
        types = {"id": BIGINT, "name": VarcharType(8), "score": DOUBLE}
        rows = _ROWS[self._split.start:self._split.end]
        cols = self._columns or ["id", "name", "score"]
        blocks = [block_from_values(types[c], [r[idx[c]] for r in rows])
                  for c in cols]
        yield Page(blocks, len(rows))


class _LettersProvider(ConnectorPageSourceProvider):
    def create_page_source(self, split, columns, scale_factor):
        return _LettersPageSource(split, columns)


class _LettersConnector(Connector):
    def get_metadata(self):
        return _LettersMetadata()

    def get_split_manager(self):
        return _LettersSplits()

    def get_page_source_provider(self):
        return _LettersProvider()


class _LettersFactory(ConnectorFactory):
    name = "letters"

    def create(self, catalog_name: str, config: Dict[str, str]):
        return _LettersConnector()


class LettersPlugin(Plugin):
    def get_connector_factories(self) -> List[ConnectorFactory]:
        return [_LettersFactory()]


@pytest.fixture
def letters_catalog():
    names = register_plugin(LettersPlugin())
    try:
        yield names[0]
    finally:
        for n in names:
            catalog.unregister_connector(n)


def test_plugin_connector_end_to_end_sql(letters_catalog):
    """The full engine path over an SPI-only connector: plan, scan via
    the page-source shim, aggregate, with NULL handling intact."""
    r = LocalQueryRunner("sf0.01", catalog=letters_catalog)
    res = r.execute("select count(*), sum(id) from letters")
    assert res.rows == [[5, 15]]
    res = r.execute("select name, count(*) c from letters "
                    "where score is not null group by name order by name")
    # ASC default is NULLS LAST (Presto ORDER BY semantics)
    assert res.rows == [["alpha", 2], ["beta", 1], [None, 1]]
    res = r.execute("select id from letters where name = 'alpha' "
                    "order by id")
    assert [row[0] for row in res.rows] == [1, 4]


def test_plugin_connector_joins_builtin_catalog(letters_catalog):
    """Cross-catalog join: the SPI connector's table joins a generated
    tpch table in one query."""
    r = LocalQueryRunner("sf0.01", catalog=letters_catalog)
    res = r.execute(
        "select l.name, n.n_name from letters l "
        "join nation n on l.id = n.n_nationkey where l.id <= 2 "
        "order by l.id")
    assert res.rows == [["alpha", "ARGENTINA"], ["beta", "BRAZIL"]]


def test_generate_values_at_coalesces_contiguous_runs(letters_catalog,
                                                      monkeypatch):
    """Lazy row-id gathers must issue one ranged _read per contiguous id
    run, not one call per row."""
    shim = catalog._CONNECTORS[letters_catalog]
    calls = []
    real_read = type(shim)._read

    def spying_read(self, table, columns, sf, start, count):
        calls.append((start, count))
        return real_read(self, table, columns, sf, start, count)

    monkeypatch.setattr(type(shim), "_read", spying_read)

    vals = shim.generate_values_at("letters", "name", 0.01, [0, 1, 2, 4])
    assert vals == [r[1] for r in _ROWS[:3]] + [_ROWS[4][1]]
    assert calls == [(0, 3), (4, 1)]

    calls.clear()
    vals = shim.generate_values_at("letters", "id", 0.01, [3])
    assert vals == [_ROWS[3][0]]
    assert calls == [(3, 1)]

    calls.clear()
    vals = shim.generate_values_at("letters", "id", 0.01,
                                   list(range(5)))
    assert vals == [r[0] for r in _ROWS]
    assert calls == [(0, 5)]
