"""Wire-format tests for the SerializedPage / Block encodings.

Golden byte layouts are hand-derived from the reference encoders
(presto-common/.../block/*BlockEncoding.java, EncoderUtil.java,
presto-spi/.../page/PagesSerdeUtil.java) so any drift from the reference wire
format fails loudly, not just round-trip-consistently.
"""
import io
import struct

import numpy as np
import pytest

from presto_tpu.common import (
    BIGINT, DOUBLE, INTEGER, VARCHAR, DecimalType,
    ArrayBlock, DictionaryBlock, FixedWidthBlock, Int128Block, Page, RowBlock,
    RunLengthBlock, VariableWidthBlock, block_from_values, block_to_values,
    deserialize_page, deserialize_pages, int_array_block, long_array_block,
    serialize_page, serialize_pages,
)
from presto_tpu.common.serde import read_block, write_block


def roundtrip_block(block):
    out = io.BytesIO()
    write_block(out, block)
    got, pos = read_block(memoryview(out.getvalue()))
    assert pos == len(out.getvalue())
    return got


# ---------------------------------------------------------------------------
# golden layouts
# ---------------------------------------------------------------------------

def test_long_array_no_nulls_golden():
    block = long_array_block([1, 2, 3])
    out = io.BytesIO()
    write_block(out, block)
    expect = (
        struct.pack("<i", 10) + b"LONG_ARRAY"
        + struct.pack("<i", 3)          # positionCount
        + b"\x00"                        # mayHaveNull = false
        + struct.pack("<qqq", 1, 2, 3)   # values
    )
    assert out.getvalue() == expect


def test_long_array_nulls_golden():
    # positions 0..8, nulls at 1 and 8 -> bitmap MSB-first: 0b01000000, 0b10000000
    vals = list(range(9))
    nulls = [False] * 9
    nulls[1] = nulls[8] = True
    block = FixedWidthBlock(np.array(vals, dtype=np.int64),
                            np.array(nulls, dtype=bool))
    out = io.BytesIO()
    write_block(out, block)
    nonnull = [v for v, n in zip(vals, nulls) if not n]
    expect = (
        struct.pack("<i", 10) + b"LONG_ARRAY"
        + struct.pack("<i", 9)
        + b"\x01" + bytes([0b01000000, 0b10000000])
        + struct.pack("<7q", *nonnull)   # non-null values only
    )
    assert out.getvalue() == expect
    got = roundtrip_block(block)
    assert got.to_pylist() == [None if n else v for v, n in zip(vals, nulls)]


def test_variable_width_golden():
    block = VariableWidthBlock.from_strings(["ab", "", "cde"])
    out = io.BytesIO()
    write_block(out, block)
    expect = (
        struct.pack("<i", 14) + b"VARIABLE_WIDTH"
        + struct.pack("<i", 3)
        + struct.pack("<iii", 2, 2, 5)   # cumulative end offsets
        + b"\x00"                         # no nulls
        + struct.pack("<i", 5) + b"abcde"
    )
    assert out.getvalue() == expect


def test_page_header_golden():
    page = Page([long_array_block([7])])
    data = serialize_page(page, checksummed=False)
    position_count, markers, uncomp, size, checksum = struct.unpack_from(
        "<ibiiq", data, 0)
    assert position_count == 1
    assert markers == 0
    assert checksum == 0
    assert uncomp == size == len(data) - 21
    # body: channelCount then the block
    (channels,) = struct.unpack_from("<i", data, 21)
    assert channels == 1


def test_page_checksum_detects_corruption():
    page = Page([long_array_block([7, 8, 9])])
    data = bytearray(serialize_page(page, checksummed=True))
    deserialize_page(bytes(data))  # ok
    data[-1] ^= 0xFF
    with pytest.raises(ValueError, match="checksum"):
        deserialize_page(bytes(data))


# ---------------------------------------------------------------------------
# round trips
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.int8, np.int16, np.int32, np.int64])
def test_fixed_width_roundtrip(dtype):
    rng = np.random.default_rng(0)
    vals = rng.integers(-100, 100, size=1000).astype(dtype)
    nulls = rng.random(1000) < 0.3
    got = roundtrip_block(FixedWidthBlock(vals, nulls))
    assert np.array_equal(got.null_mask(), nulls)
    assert np.array_equal(got.values[~nulls], vals[~nulls])


def test_double_bits_roundtrip():
    vals = np.array([1.5, -2.25, float("nan"), float("inf")], dtype=np.float64)
    block = FixedWidthBlock(vals)
    got = roundtrip_block(block)
    assert np.array_equal(got.values.view(np.float64), vals, equal_nan=True)


def test_int128_roundtrip():
    vals = np.array([[1, 2], [-3, 4], [0, 0]], dtype=np.int64)
    nulls = np.array([False, True, False])
    got = roundtrip_block(Int128Block(vals, nulls))
    assert np.array_equal(got.values[~nulls], vals[~nulls])
    assert np.array_equal(got.null_mask(), nulls)


def test_varchar_nulls_roundtrip():
    block = VariableWidthBlock.from_strings(["hello", None, "", "wörld"])
    got = roundtrip_block(block)
    assert got.to_pylist() == ["hello", None, "", "wörld"]


def test_dictionary_roundtrip():
    dictionary = VariableWidthBlock.from_strings(["A", "F", "N", "O", "R"])
    ids = np.array([0, 1, 1, 4, 2], dtype=np.int32)
    got = roundtrip_block(DictionaryBlock(ids, dictionary))
    assert got.to_pylist() == ["A", "F", "F", "R", "N"]


def test_dictionary_compacts_on_write():
    dictionary = VariableWidthBlock.from_strings(["A", "B", "C", "D"])
    ids = np.array([3, 3, 1], dtype=np.int32)
    got = roundtrip_block(DictionaryBlock(ids, dictionary))
    assert got.to_pylist() == ["D", "D", "B"]
    assert got.dictionary.position_count == 2  # compacted


def test_rle_roundtrip():
    got = roundtrip_block(RunLengthBlock(long_array_block([42]), 7))
    assert got.to_pylist() == [42] * 7


def test_array_roundtrip():
    elements = long_array_block([1, 2, 3, 4, 5, 6])
    offsets = np.array([0, 2, 2, 6], dtype=np.int32)
    nulls = np.array([False, True, False])
    got = roundtrip_block(ArrayBlock(offsets, elements, nulls))
    assert got.to_pylist() == [[1, 2], None, [3, 4, 5, 6]]


def test_row_roundtrip():
    block = RowBlock.from_fields([
        long_array_block([1, 2, 3]),
        VariableWidthBlock.from_strings(["x", "y", "z"]),
    ])
    got = roundtrip_block(block)
    assert got.to_pylist() == [[1, "x"], [2, "y"], [3, "z"]]


def test_multi_page_stream():
    pages = [
        Page([long_array_block([1, 2]), int_array_block([10, 20])]),
        Page([long_array_block([3]), int_array_block([30])]),
    ]
    buf = serialize_pages(pages)
    got = deserialize_pages(buf)
    assert len(got) == 2
    assert got[0].block(0).to_pylist() == [1, 2]
    assert got[1].block(1).to_pylist() == [30]


# ---------------------------------------------------------------------------
# typed value round trips
# ---------------------------------------------------------------------------

def test_typed_values_roundtrip():
    from decimal import Decimal
    cases = [
        (BIGINT, [1, None, -5]),
        (INTEGER, [7, 8, None]),
        (DOUBLE, [1.5, None, -0.25]),
        (VARCHAR, ["a", None, "bc"]),
        (DecimalType(12, 2), [Decimal("1.23"), None, Decimal("-4.50")]),
    ]
    for typ, values in cases:
        if isinstance(typ, DecimalType):
            scaled = [None if v is None else int(v.scaleb(typ.scale)) for v in values]
            block = block_from_values(typ, scaled)
        else:
            block = block_from_values(typ, values)
        got = roundtrip_block(block)
        assert block_to_values(typ, got) == values, typ.signature


# ---------------------------------------------------------------------------
# regression tests from review findings
# ---------------------------------------------------------------------------

def test_long_decimal_sign_magnitude_layout():
    """Reference layout (UnscaledDecimal128Arithmetic.java:33-39): word0=low64
    of |v|, word1=high63 | sign bit."""
    block = Int128Block.from_ints([1, -1, 2**64 + 5, -(2**100)])
    assert block.values[0, 0] == 1 and block.values[0, 1] == 0
    assert block.values[1, 0] == 1 and np.uint64(block.values[1, 1]) == np.uint64(1 << 63)
    assert block.to_pylist() == [1, -1, 2**64 + 5, -(2**100)]
    got = roundtrip_block(block)
    assert got.to_pylist() == [1, -1, 2**64 + 5, -(2**100)]


def test_long_decimal_typed_roundtrip_negative():
    from decimal import Decimal
    typ = DecimalType(38, 2)
    scaled = [-123, None, 10**20, -(10**30)]
    block = block_from_values(typ, scaled)
    got = roundtrip_block(block)
    assert block_to_values(typ, got) == [
        Decimal("-1.23"), None, Decimal(10**20) / 100, -Decimal(10**30) / 100]


def test_concat_pages_nonzero_offset_varwidth():
    from presto_tpu.common import concat_pages
    # data with unreferenced prefix bytes: offsets start at 2
    vb = VariableWidthBlock(np.array([2, 4, 6], dtype=np.int32),
                            np.frombuffer(b"xxabcd", dtype=np.uint8).copy())
    assert vb.to_pylist() == ["ab", "cd"]
    p2 = Page([VariableWidthBlock.from_strings(["ZZ", "WW"])])
    got = concat_pages([Page([vb]), p2])
    assert got.block(0).to_pylist() == ["ab", "cd", "ZZ", "WW"]


def test_row_block_take_with_sparse_nulls():
    # Reference sparse layout: null rows occupy no field entries
    rb = RowBlock([long_array_block([10, 20])],
                  np.array([0, 1, 2, 2], dtype=np.int32),
                  np.array([False, False, True]))
    assert rb.take(np.array([2])).to_pylist() == [None]
    assert rb.take(np.array([2, 0, 1])).to_pylist() == [None, [10], [20]]
    got = roundtrip_block(rb)
    assert got.to_pylist() == [[10], [20], None]


def test_parse_type_row_keyword_field_names():
    from presto_tpu.common import parse_type
    t = parse_type("row(date date, timestamp timestamp, x bigint)")
    assert t.names == ("date", "timestamp", "x")
    assert [x.signature for x in t.types] == ["date", "timestamp", "bigint"]


def test_compressed_page_round_trip():
    """COMPRESSED marker (PageCodecMarker.java:27): deflated body,
    uncompressedSize field holds the raw size, checksum covers the wire
    (compressed) bytes."""
    from presto_tpu.common.serde import (COMPRESSED, PAGE_METADATA_SIZE,
                                         deserialize_page, serialize_page)
    from presto_tpu.common.block import block_from_values
    from presto_tpu.common.page import Page
    from presto_tpu.common.types import BIGINT, VARCHAR
    import struct

    n = 4096
    page = Page([
        block_from_values(BIGINT, [i % 7 for i in range(n)]),
        block_from_values(VARCHAR, [f"value-{i % 3}" for i in range(n)]),
    ], n)
    raw = serialize_page(page)
    wire = serialize_page(page, compress=True)
    assert len(wire) < len(raw) // 2, "compressible page did not shrink"
    _pc, markers, unc, size, _ck = struct.unpack_from("<ibiiq", wire, 0)
    assert markers & COMPRESSED
    assert unc > size
    got, pos = deserialize_page(wire)
    assert pos == PAGE_METADATA_SIZE + size
    assert got.position_count == page.position_count
    from presto_tpu.common.block import block_to_values
    for t, a, b in zip((BIGINT, VARCHAR), got.blocks, page.blocks):
        assert block_to_values(t, a) == block_to_values(t, b)


def test_incompressible_page_stays_raw():
    import os
    import struct
    from presto_tpu.common.serde import COMPRESSED, serialize_page
    from presto_tpu.common.block import block_from_values
    from presto_tpu.common.page import Page
    from presto_tpu.common.types import BIGINT

    rnd = [int.from_bytes(os.urandom(8), "little", signed=True)
           for _ in range(2048)]
    page = Page([block_from_values(BIGINT, rnd)], 2048)
    wire = serialize_page(page, compress=True)
    _pc, markers, _unc, _size, _ck = struct.unpack_from("<ibiiq", wire, 0)
    assert not (markers & COMPRESSED), "random data should stay raw"


def _codec_page(n=4096):
    from presto_tpu.common.block import block_from_values
    from presto_tpu.common.page import Page
    from presto_tpu.common.types import BIGINT, VARCHAR
    return Page([
        block_from_values(BIGINT, [i % 7 for i in range(n)]),
        block_from_values(VARCHAR, [f"value-{i % 3}" for i in range(n)]),
    ], n)


def test_every_reference_codec_round_trips():
    """PagesSerdeFactory.java:69-108 codec set (minus dead LZO): each codec
    compresses and round-trips; serializer and deserializer share the codec
    as cluster config, the wire only carries the COMPRESSED bit."""
    import struct
    from presto_tpu.common import compression
    from presto_tpu.common.serde import (COMPRESSED, deserialize_page,
                                         serialize_page)
    from presto_tpu.common.block import block_to_values
    from presto_tpu.common.types import BIGINT, VARCHAR

    page = _codec_page()
    codecs = [c for c in compression.supported_codecs() if c != "NONE"]
    assert {"LZ4", "SNAPPY", "ZSTD", "GZIP", "ZLIB"} <= set(codecs)
    for codec in codecs:
        wire = serialize_page(page, compress=True, codec=codec)
        _pc, markers, _unc, _size, _ck = struct.unpack_from("<ibiiq", wire, 0)
        assert markers & COMPRESSED, codec
        got, _ = deserialize_page(wire, codec=codec)
        for t, a, b in zip((BIGINT, VARCHAR), got.blocks, page.blocks):
            assert block_to_values(t, a) == block_to_values(t, b), codec


def test_lz4_page_body_decodes_with_independent_decoder():
    """The compressed body must be raw LZ4 *block* format (what airlift
    aircompressor Lz4Compressor/Lz4Decompressor speak, PagesSerdeFactory
    .java:75-76) — verified with a from-the-spec pure-Python decoder that
    shares no code with the production codec."""
    import struct
    from presto_tpu.common.compression import lz4_block_decompress
    from presto_tpu.common.serde import (COMPRESSED, PAGE_METADATA_SIZE,
                                         serialize_page)

    page = _codec_page()
    raw = serialize_page(page, compress=False)
    body_raw = raw[PAGE_METADATA_SIZE:]
    wire = serialize_page(page, compress=True, codec="LZ4")
    _pc, markers, unc, size, _ck = struct.unpack_from("<ibiiq", wire, 0)
    assert markers & COMPRESSED
    body = wire[PAGE_METADATA_SIZE:PAGE_METADATA_SIZE + size]
    assert lz4_block_decompress(body, unc) == body_raw


def test_lz4_golden_block_decodes():
    """Hand-derived LZ4 block golden (spec v1.5.1): token 0x1A = 1 literal
    + match len 10+4, offset 1 (overlapping run), trailing token 0x50 =
    5 literals -> 'a' * 20."""
    from presto_tpu.common.compression import decompress, lz4_block_decompress
    golden = bytes.fromhex("1a610100506161616161")
    assert lz4_block_decompress(golden, 20) == b"a" * 20
    assert decompress("LZ4", golden, 20) == b"a" * 20


def test_compression_ratio_gate_is_reference_value():
    from presto_tpu.common.serde import MINIMUM_COMPRESSION_RATIO
    assert MINIMUM_COMPRESSION_RATIO == 0.9  # PagesSerde.java:44


def test_deserialize_accepts_memoryview_zero_copy():
    """The exchange client walks response bodies as memoryviews; serde
    must accept buffer input end-to-end (checksummed, compressed, and
    plain) without requiring a bytes copy of the body."""
    pages = [Page([long_array_block([1, 2, 3]), int_array_block([7, 8, 9])]),
             Page([long_array_block(list(range(4096)))])]
    for compress in (False, True):
        wire = serialize_pages(pages, compress=compress)
        for buf in (memoryview(wire), bytearray(wire), wire):
            got = deserialize_pages(buf)
            assert len(got) == 2
            assert got[0].blocks[0].to_pylist() == [1, 2, 3]
            assert got[1].blocks[0].to_pylist() == list(range(4096))
    # offset deserialization over a view slices without materializing
    wire = serialize_pages(pages)
    view = memoryview(wire)
    first, pos = deserialize_page(view, 0)
    second, end = deserialize_page(view, pos)
    assert end == len(wire)
    assert second.blocks[0].position_count == 4096
