"""Encoding-per-encoding SerializedPage wire goldens (VERDICT r3 next #7).

Two independent layers of evidence:

1. JAVA-PRODUCED bytes: every base64 "valueBlock" in the reference tree's
   checked-in JSON fixtures (presto_cpp/main/types/tests/data/,
   presto_cpp/presto_protocol/tests/data/ — bytes written by the Java
   BlockEncodings via Jackson) must decode through the repo serde AND
   re-encode byte-identically.  This covers INT_ARRAY, LONG_ARRAY,
   BYTE_ARRAY, VARIABLE_WIDTH and a nested ARRAY[VARIABLE_WIDTH].

2. Hand-derived FULL-PAGE goldens for the encodings the fixtures do not
   reach (dictionary, RLE, nulled var-width, INT128), built field-by-field
   in this file from the reference encoder sources, cited per field:
     header        PagesSerdeUtil.java:64-88 (21 bytes: positionCount:i32,
                   codecMarkers:u8, uncompressedSize:i32, size:i32,
                   checksum:i64, all LE)
     checksum      PagesSerdeUtil.java:102-119 (CRC32 over pageData,
                   markers byte, positionCount LE32, uncompressedSize LE32)
     raw page      PagesSerdeUtil.writeRawPage:45-51 (channelCount then
                   writeBlock per channel)
     block framing BlockEncodingManager.java:79-99 (i32 name length,
                   UTF-8 name, payload)
     nulls         EncoderUtil.java (mayHaveNull byte; MSB-first bitmap,
                   1 == null; fixed-width payloads carry non-null values
                   only)
     DICTIONARY    DictionaryBlockEncoding.java:38-53 (positionCount,
                   nested dictionary block, i32 ids, 24-byte instance id:
                   msb/lsb/sequenceId longs)
     RLE           RunLengthBlockEncoding.java:31-41 (positionCount, then
                   the single-position value block)
     VARIABLE_WIDTH VariableWidthBlockEncoding.java:37-58 (positionCount,
                   cumulative end offsets incl. null positions, nulls,
                   totalLength, bytes)
     INT128_ARRAY  Int128ArrayBlockEncoding.java (positionCount, nulls,
                   16-byte values for non-null positions)

The LZ4 page test cross-checks the compressed body against the repo's
INDEPENDENT pure-python LZ4 block decoder (common/compression.py) rather
than the encoder's own inverse.
"""
import base64
import glob
import io
import json
import os
import re
import struct
import zlib

import numpy as np
import pytest

from presto_tpu.common import (
    DictionaryBlock, FixedWidthBlock, Int128Block, Page, RunLengthBlock,
    VariableWidthBlock, deserialize_page, serialize_page,
)
from presto_tpu.common.serde import read_block, write_block

REF_FIXTURE_DIRS = [
    "/root/reference/presto-native-execution/presto_cpp/main/types/tests/data",
    "/root/reference/presto-native-execution/presto_cpp/presto_protocol/tests/data",
    "/root/reference/presto-native-execution/presto_cpp/main/tests/data",
]

needs_reference = pytest.mark.skipif(
    not os.path.isdir(REF_FIXTURE_DIRS[0]), reason="reference tree absent")


def _scavenge_valueblocks():
    """Every distinct base64 valueBlock in the reference JSON fixtures."""
    found = set()
    for d in REF_FIXTURE_DIRS:
        for path in glob.glob(os.path.join(d, "*.json")):
            with open(path) as f:
                text = f.read()
            for m in re.finditer(r'"valueBlock"\s*:\s*"([^"]+)"', text):
                found.add(m.group(1))
    return sorted(found)


@needs_reference
def test_java_produced_blocks_roundtrip_byte_identical():
    samples = _scavenge_valueblocks()
    assert len(samples) >= 6, "expected Java-produced samples in fixtures"
    encodings = set()
    for b64 in samples:
        raw = base64.b64decode(b64)
        block, pos = read_block(memoryview(raw), 0)
        assert pos == len(raw), "trailing bytes after Java block"
        out = io.BytesIO()
        write_block(out, block)
        assert out.getvalue() == raw, \
            f"re-encode of Java bytes differs for {b64[:24]}…"
        encodings.add(block.encoding)
    # the fixture population must actually exercise several encodings
    assert {"INT_ARRAY", "LONG_ARRAY", "BYTE_ARRAY",
            "VARIABLE_WIDTH", "ARRAY"} <= encodings


# ---------------------------------------------------------------------------
# hand-derived full-page goldens
# ---------------------------------------------------------------------------

def _enc_name(name: str) -> bytes:
    # BlockEncodingManager.java:79-84: i32 length + UTF-8 name
    return struct.pack("<i", len(name)) + name.encode()


def _page_golden(body: bytes, position_count: int) -> bytes:
    """21-byte header + body with CHECKSUMMED marker, every field built
    here independently of presto_tpu.common.serde."""
    markers = 0x04                              # PageCodecMarker CHECKSUMMED
    crc = zlib.crc32(body)
    crc = zlib.crc32(bytes([markers]), crc)
    crc = zlib.crc32(struct.pack("<i", position_count), crc)
    crc = zlib.crc32(struct.pack("<i", len(body)), crc)
    return struct.pack("<ibiiq", position_count, markers, len(body),
                       len(body), crc & 0xFFFFFFFF) + body


def test_dictionary_page_golden():
    """DICTIONARY[VARIABLE_WIDTH] page: ids [0,1,0,0] over dict
    ["aa","b"], layout per DictionaryBlockEncoding.java:38-53."""
    dict_block = (
        _enc_name("VARIABLE_WIDTH")
        + struct.pack("<i", 2)                  # dictionary positionCount
        + struct.pack("<ii", 2, 3)              # cumulative end offsets
        + b"\x00"                               # no nulls
        + struct.pack("<i", 3) + b"aab"         # totalLength + bytes
    )
    body = (
        struct.pack("<i", 1)                    # channelCount
        + _enc_name("DICTIONARY")
        + struct.pack("<i", 4)                  # positionCount
        + dict_block                            # nested dictionary
        + struct.pack("<4i", 0, 1, 0, 0)        # ids
        + struct.pack("<qqq", 7, 8, 9)          # instance id msb/lsb/seq
    )
    golden = _page_golden(body, 4)
    page, pos = deserialize_page(golden)
    assert pos == len(golden)
    (blk,) = page.blocks
    assert isinstance(blk, DictionaryBlock)
    assert blk.to_pylist() == ["aa", "b", "aa", "aa"]
    assert tuple(blk.source_id) == (7, 8, 9)
    # encode side: same page must serialize back to the same bytes
    assert serialize_page(page, checksummed=True) == golden


def test_rle_page_golden():
    """RLE page: 5 x BIGINT 42, layout per
    RunLengthBlockEncoding.java:31-41."""
    body = (
        struct.pack("<i", 1)
        + _enc_name("RLE")
        + struct.pack("<i", 5)                  # run length
        + _enc_name("LONG_ARRAY")               # single-position value
        + struct.pack("<i", 1) + b"\x00" + struct.pack("<q", 42)
    )
    golden = _page_golden(body, 5)
    page, pos = deserialize_page(golden)
    assert pos == len(golden)
    (blk,) = page.blocks
    assert isinstance(blk, RunLengthBlock)
    assert blk.to_pylist() == [42] * 5
    assert serialize_page(page, checksummed=True) == golden


def test_varwidth_nulls_page_golden():
    """VARIABLE_WIDTH with a null at position 1: offsets STILL advance one
    slot per position (VariableWidthBlockEncoding.java:45-50 writes the
    cumulative length for every position; a null contributes 0)."""
    body = (
        struct.pack("<i", 1)
        + _enc_name("VARIABLE_WIDTH")
        + struct.pack("<i", 3)                  # positionCount
        + struct.pack("<iii", 2, 2, 5)          # ends: "ab", null, "cde"
        + b"\x01" + bytes([0b01000000])         # nulls bitmap, MSB-first
        + struct.pack("<i", 5) + b"abcde"
    )
    golden = _page_golden(body, 3)
    page, pos = deserialize_page(golden)
    assert pos == len(golden)
    (blk,) = page.blocks
    assert isinstance(blk, VariableWidthBlock)
    assert blk.to_pylist() == ["ab", None, "cde"]
    assert serialize_page(page, checksummed=True) == golden


def test_int128_nulls_page_golden():
    """INT128_ARRAY (long decimals): 3 positions, null at 2; non-null
    values only, (high, low) long pairs per Int128ArrayBlockEncoding."""
    body = (
        struct.pack("<i", 1)
        + _enc_name("INT128_ARRAY")
        + struct.pack("<i", 3)
        + b"\x01" + bytes([0b00100000])         # null at position 2
        + struct.pack("<qq", 0, 1)              # value 1  (high, low)
        + struct.pack("<qq", -1, -2)            # value -2 sign-extended
    )
    golden = _page_golden(body, 3)
    page, pos = deserialize_page(golden)
    assert pos == len(golden)
    (blk,) = page.blocks
    assert isinstance(blk, Int128Block)
    got = np.asarray(blk.values)
    assert got[0].tolist() == [0, 1]
    assert got[1].tolist() == [-1, -2]
    assert blk.null_mask().tolist() == [False, False, True]
    assert serialize_page(page, checksummed=True) == golden


def test_fixed_width_nulls_page_golden():
    """LONG_ARRAY with nulls inside a multi-channel page: channelCount
    per PagesSerdeUtil.writeRawPage:45-51, fixed-width non-null packing
    per EncoderUtil.encodeNullsAsBits + LongArrayBlockEncoding."""
    ch0 = (_enc_name("LONG_ARRAY") + struct.pack("<i", 3)
           + b"\x01" + bytes([0b01000000])      # null at position 1
           + struct.pack("<qq", 10, 30))        # non-null values only
    ch1 = (_enc_name("INT_ARRAY") + struct.pack("<i", 3)
           + b"\x00" + struct.pack("<iii", 1, 2, 3))
    body = struct.pack("<i", 2) + ch0 + ch1
    golden = _page_golden(body, 3)
    page, pos = deserialize_page(golden)
    assert pos == len(golden)
    a, b = page.blocks
    assert a.to_pylist() == [10, None, 30]
    assert b.to_pylist() == [1, 2, 3]
    assert serialize_page(page, checksummed=True) == golden


def test_lz4_compressed_page_against_independent_decoder():
    """A >4KiB page serialized with compress=True: COMPRESSED|CHECKSUMMED
    markers (PageCodecMarker.java:27-29), uncompressedSize != size, and
    the compressed body must decode with the repo's independent
    pure-python LZ4 block decoder (common/compression.py:47) to exactly
    the raw body bytes — proving the wire bytes are real LZ4 block format
    (aircompressor-compatible, PagesSerdeFactory.java:75-76), not merely
    self-consistent."""
    from presto_tpu.common.compression import lz4_block_decompress
    values = np.arange(4096, dtype=np.int64) % 17       # compressible
    page = Page([FixedWidthBlock(values, None)])
    raw = serialize_page(page, checksummed=True, compress=False)
    comp = serialize_page(page, checksummed=True, compress=True)
    pc, markers, uncomp, size, _crc = struct.unpack_from("<ibiiq", comp, 0)
    assert markers & 0x01, "COMPRESSED marker missing"
    assert markers & 0x04, "CHECKSUMMED marker missing"
    assert size < uncomp == len(raw) - 21
    body = lz4_block_decompress(comp[21:21 + size], uncomp)
    assert bytes(body) == raw[21:]
    # and the normal path agrees
    got, _ = deserialize_page(comp)
    assert got.blocks[0].to_pylist() == values.tolist()
