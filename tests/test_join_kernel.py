"""In-kernel join probe (presto_tpu/exec/kernels/join.py): engagement
and parity vs the XLA fused chain and the numpy reference oracle,
randomized fuzz across encodings x predicates x NULL probe keys x
fanout, the Join* decline gates, and the MemoryContext reservation
discipline for build-table operands.

Build operands ride the scan kernel launch as whole-block VMEM
operands; the applier math is copied operation-for-operation from
ops.direct_lookup / fused.probe_unique, so every comparison here is
exact equality, not approximate."""
import numpy as np
import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner, _assert_rows_equal


def _kernel_programs(res) -> int:
    return int((res.runtime_stats or {}).get(
        "kernelScanPrograms", {}).get("sum", 0))


def _declined(res) -> dict:
    return {k[len("kernelDeclined"):]: int(v.get("sum", 0))
            for k, v in (res.runtime_stats or {}).items()
            if k.startswith("kernelDeclined")}


@pytest.fixture(scope="module")
def pallas():
    return LocalQueryRunner(
        "sf0.01", config=ExecutionConfig(scan_kernel="pallas"))


@pytest.fixture(scope="module")
def xla():
    return LocalQueryRunner(
        "sf0.01", config=ExecutionConfig(scan_kernel="xla"))


Q3_SHAPE = """
    select o_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
           count(*) as cnt
    from lineitem, orders
    where l_orderkey = o_orderkey
      and o_orderdate < date '1995-03-15'
      and l_shipdate > date '1995-03-15'
    group by o_orderkey
"""

Q18_SHAPE = """
    select l_orderkey, max(o_totalprice) as price, sum(l_quantity) as qty
    from lineitem, orders
    where l_orderkey = o_orderkey
    group by l_orderkey
"""


# ---------------------------------------------------------------------------
# engagement: the probe chain actually lowers into the kernel
# ---------------------------------------------------------------------------

def test_q3_shape_join_kernel_engages(pallas, xla):
    # the acceptance shape: decode -> filter -> probe -> compact -> agg
    # in one launch, bit-identical to the XLA chain and the oracle
    pres = pallas.execute(Q3_SHAPE)
    assert _kernel_programs(pres) >= 1, _declined(pres)
    assert not _declined(pres)
    xres = xla.execute(Q3_SHAPE)
    assert _kernel_programs(xres) == 0
    _assert_rows_equal(pres, xres, ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(Q3_SHAPE),
                       ordered=False)


def test_q18_shape_join_kernel_engages(pallas, xla):
    pres = pallas.execute(Q18_SHAPE)
    assert _kernel_programs(pres) >= 1, _declined(pres)
    xres = xla.execute(Q18_SHAPE)
    _assert_rows_equal(pres, xres, ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(Q18_SHAPE),
                       ordered=False)


def test_semi_join_in_kernel(pallas, xla):
    # IN-subquery lowers to a semi step; the three-valued marker
    # (NULL build side / NULL probe key) is computed in-kernel
    sql = ("select count(*) from lineitem "
           "where l_orderkey in (select o_orderkey from orders "
           "where o_orderdate < date '1995-01-01')")
    pres = pallas.execute(sql)
    assert _kernel_programs(pres) >= 1, _declined(pres)
    _assert_rows_equal(pres, xla.execute(sql), ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


def test_multi_probe_chain_in_kernel(pallas, xla):
    # probe -> probe: two build tables resident in one launch
    sql = ("select count(*), sum(l_quantity) from lineitem, orders, "
           "customer where l_orderkey = o_orderkey "
           "and o_custkey = c_custkey and c_nationkey < 10")
    pres = pallas.execute(sql)
    xres = xla.execute(sql)
    _assert_rows_equal(pres, xres, ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)
    assert _kernel_programs(pres) >= 1, _declined(pres)


# ---------------------------------------------------------------------------
# randomized parity fuzz: encodings x predicates x NULL probe keys x
# join forms, pallas vs xla vs numpy oracle
# ---------------------------------------------------------------------------

_JOIN_AGGS = ["count(*)", "sum(l_quantity)", "sum(l_extendedprice)",
              "max(o_totalprice)", "min(l_quantity)", "avg(l_discount)"]


def _join_fuzz_sql(seed: int) -> str:
    rng = np.random.default_rng(seed)
    conj = ["l_orderkey = o_orderkey",
            f"l_quantity < {int(rng.integers(10, 45))}"]
    if rng.integers(2):
        y = int(rng.integers(1992, 1998))
        conj.append(f"l_shipdate >= date '{y}-01-01'")
    if rng.integers(2):
        # build-side filter: the probe runs against a sparse key domain
        y = int(rng.integers(1993, 1998))
        conj.append(f"o_orderdate < date '{y}-06-01'")
    if rng.integers(2):
        # RLE probe-key column + zone pruning under the kernel grid
        conj.append(f"l_orderkey < {int(rng.integers(1000, 30_000))}")
    n_aggs = int(rng.integers(2, 4))
    aggs = [_JOIN_AGGS[i] for i in rng.choice(len(_JOIN_AGGS), n_aggs,
                                              replace=False)]
    group = ["", "o_orderkey", "l_returnflag"][int(rng.integers(3))]
    sql = (f"select {group + ', ' if group else ''}{', '.join(aggs)} "
           f"from lineitem, orders where {' and '.join(conj)}")
    if group:
        sql += f" group by {group}"
    return sql


@pytest.mark.parametrize("seed", [21, 22, 23, 24, 25])
def test_join_parity_fuzz(pallas, xla, seed):
    sql = _join_fuzz_sql(seed)
    pres = pallas.execute(sql)
    xres = xla.execute(sql)
    _assert_rows_equal(pres, xres, ordered=False)
    assert _kernel_programs(pres) >= 1, (sql, _declined(pres))
    assert _kernel_programs(xres) == 0
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


def test_null_probe_keys_parity(pallas, xla):
    # NULL probe keys never match (reference LookupJoinOperator); the
    # in-kernel probe must apply the probe-side null mask to the hit
    sql = ("select count(*) from "
           "(select case when l_orderkey % 3 = 0 then null "
           "else l_orderkey end as k, l_quantity from lineitem) "
           "join orders on k = o_orderkey where l_quantity < 30")
    pres = pallas.execute(sql)
    xres = xla.execute(sql)
    _assert_rows_equal(pres, xres, ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


def test_semi_null_probe_keys_parity(pallas, xla):
    # three-valued IN: NULL probe keys mark NULL, filtered to false
    sql = ("select count(*) from "
           "(select case when custkey % 3 = 0 then null "
           "else custkey end as k from orders) "
           "where k in (select custkey from customer "
           "where nationkey < 10)")
    pres = pallas.execute(sql)
    xres = xla.execute(sql)
    _assert_rows_equal(pres, xres, ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


# ---------------------------------------------------------------------------
# Join* decline gates: ineligible shapes meter, never mis-run
# ---------------------------------------------------------------------------

def test_fanout_join_declines_join_shape(pallas, xla):
    # customer |x| orders on custkey expands rows (fanout-k): the
    # kernel's fixed block geometry cannot follow the expansion, so the
    # chain declines JoinShape and the XLA fused chain runs it
    sql = ("select c_mktsegment, count(*) from customer, orders "
           "where c_custkey = o_custkey group by c_mktsegment")
    pres = pallas.execute(sql)
    assert _declined(pres).get("JoinShape", 0) >= 1
    _assert_rows_equal(pres, xla.execute(sql), ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


def test_residual_on_filter_declines_join_shape():
    # a residual ON predicate (beyond the equi-criteria) stays with the
    # XLA chain's post-probe filter
    from presto_tpu.exec.kernels.join import plan_join_layout

    class _Node:
        filter = object()           # residual ON condition present
    reasons = []
    plan = plan_join_layout([("join", _Node())], (None, object()), (1,),
                            reasons.append)
    assert plan is None and reasons == ["JoinShape"]


def test_build_size_gate_declines(pallas, monkeypatch):
    # shrink the operand-byte cap so the orders build overflows it: the
    # launch declines JoinBuildSize and the XLA chain takes over
    from presto_tpu.exec.kernels import join as jk
    monkeypatch.setattr(jk, "KERNEL_JOIN_MAX_BUILD_BYTES", 64)
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        scan_kernel="pallas"))
    res = r.execute(Q3_SHAPE)
    assert _kernel_programs(res) == 0
    assert _declined(res).get("JoinBuildSize", 0) >= 1
    _assert_rows_equal(res, pallas.execute(Q3_SHAPE), ordered=False)


# ---------------------------------------------------------------------------
# MemoryContext reservation: build operands are charged revocation-
# exempt, and arbitration still works around them
# ---------------------------------------------------------------------------

def test_build_operands_reserve_revocation_exempt():
    # a revocable holder fills the budget; admitting the build operands
    # must arbitrate (revoke the holder), and the admitted reservation
    # itself must be exempt from later revocation passes
    from presto_tpu.exec.kernels.join import reserve_build_operands
    from presto_tpu.exec.memory import MemoryPool

    pool = MemoryPool(budget=1000)
    state = {"held": 800}

    def revoke() -> int:
        freed, state["held"] = state["held"], 0
        h.free(freed)
        return freed

    h = pool.register_revocable("agg/state", revoke)
    assert h.try_reserve(800)
    # 800/1000 held revocably: the 400-byte build cannot fit without
    # arbitration, and MUST NOT fail
    assert reserve_build_operands(pool, 400)
    assert pool.revocations >= 1
    assert pool.reserved >= 400          # non-revocable = exempt
    # a later arbitration pass finds nothing revocable to take from the
    # build: requesting more than the remaining headroom now fails
    # instead of spilling the in-flight kernel operands
    assert not pool.try_reserve(700)
    pool.free(400)
    assert reserve_build_operands(None, 123)      # poolless runners
    assert reserve_build_operands(pool, 0)        # empty join plan


def test_constrained_q18_shape_arbitrates_with_join_kernel():
    # engine-level: the same q18 shape once with the join kernel engaged
    # (unconstrained) and once under a tight budget — the budgeted run
    # keeps the streaming build/spill discipline (fusion declines
    # BudgetedPool, so no kernel) yet returns identical rows, and the
    # arbitration counters prove the pool actually worked for it
    from presto_tpu.exec.memory import MEMORY_METRICS
    free = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        scan_kernel="pallas"))
    fres = free.execute(Q18_SHAPE)
    assert _kernel_programs(fres) >= 1, _declined(fres)
    peak = fres.peak_memory_bytes or 0
    assert peak > 0
    MEMORY_METRICS.reset()
    constrained = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        scan_kernel="pallas", spill_enabled=True,
        memory_budget_bytes=max(1, peak // 4)))
    cres = constrained.execute(Q18_SHAPE)
    _assert_rows_equal(cres, fres, ordered=False)
    m = MEMORY_METRICS.snapshot()
    assert m["arbitrations"] + m["revocations"] >= 1
