"""HBM-resident columnar storage (presto_tpu/storage): encoding
round-trips, zone-map construction, conservative chunk pruning, LRU
eviction under a tight budget, and end-to-end result identity vs the
numpy reference oracle with pruning active.

The correctness obligations tested here mirror the design contract:
encodings are EXACT (late decode reproduces the plain column bit-for-
bit), pruning is CONSERVATIVE (a skipped chunk provably holds no
passing row), and the storage budget degrades throughput only — a
column that cannot fit is regenerated on the fly, never
MemoryExceededError."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu.common.types import (BIGINT, BOOLEAN, DATE, DOUBLE,
                                     DecimalType)
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner
from presto_tpu.spi.expr import (VariableReferenceExpression, call, constant,
                                 special)
from presto_tpu.storage import (STORAGE_METRICS, ResidentColumn,
                                ResidentStore, build_zone_maps, encode_column,
                                entry_unsatisfiable, extract_pushdown,
                                prune_chunks)


def _padded(body, pad=64):
    body = jnp.asarray(body)
    return jnp.concatenate([body, jnp.zeros(pad, dtype=body.dtype)])


def _np(x):
    return np.asarray(jax.device_get(x))


# ---------------------------------------------------------------------------
# encoding round-trips (late decode must be exact)
# ---------------------------------------------------------------------------

def test_plain_roundtrip():
    rng = np.random.default_rng(0)
    body = rng.standard_normal(1000)
    col = encode_column(_padded(body), 1000)
    assert col.kind == "plain"
    np.testing.assert_array_equal(_np(col.decode_full())[:1000], body)


def test_dict_roundtrip_int8_codes():
    rng = np.random.default_rng(1)
    body = rng.integers(0, 11, size=1 << 14, dtype=np.int64)
    col = encode_column(_padded(body), len(body))
    assert col.kind == "dict"
    codes, values = col.arrays
    assert codes.dtype == jnp.int8          # ndv 11 <= 127
    assert int(values.shape[0]) == 11
    np.testing.assert_array_equal(_np(col.decode_full())[:len(body)], body)
    # chunk decode at an unaligned offset
    got = _np(col.slice_decode(jnp.int64(1234), 512))
    np.testing.assert_array_equal(got, body[1234:1234 + 512])
    assert col.nbytes < col.logical_nbytes


def test_dict_roundtrip_int16_codes():
    rng = np.random.default_rng(2)
    body = rng.integers(0, 300, size=1 << 14, dtype=np.int64)
    col = encode_column(_padded(body), len(body))
    assert col.kind == "dict"
    assert col.arrays[0].dtype == jnp.int16  # 127 < ndv <= 32767
    np.testing.assert_array_equal(_np(col.decode_full())[:len(body)], body)


def test_rle_roundtrip_monotone():
    n = 1 << 14
    body = (np.arange(n, dtype=np.int64) // 64) + 1   # 256 runs of 64
    col = encode_column(_padded(body), n)
    assert col.kind == "rle"
    run_values, run_starts = col.arrays
    # 256 runs + the zero-valued sentinel run covering the tail padding
    assert int(run_starts.shape[0]) == 257
    assert int(run_starts[0]) == 0 and int(run_starts[-1]) == n
    np.testing.assert_array_equal(_np(col.decode_full())[:n], body)
    got = _np(col.slice_decode(jnp.int64(63), 130))   # spans 3 runs
    np.testing.assert_array_equal(got, body[63:63 + 130])
    assert col.nbytes < col.logical_nbytes


def test_rle_hint_lowers_the_compression_bar():
    n = 1 << 14
    body = (np.arange(n, dtype=np.int64) // 8) + 1    # 2048 runs: only ~8x
    unhinted = encode_column(_padded(body), n)
    hinted = encode_column(_padded(body), n, hint="rle")
    assert unhinted.kind != "rle"   # 8x < RLE_MIN_COMPRESSION
    assert hinted.kind == "rle"     # >= RLE_HINT_COMPRESSION
    np.testing.assert_array_equal(_np(hinted.decode_full())[:n], body)


def test_encodings_disabled_forces_plain():
    body = np.zeros(1 << 12, dtype=np.int64)   # trivially compressible
    col = encode_column(_padded(body), len(body), encodings=False)
    assert col.kind == "plain"


def test_resident_column_is_a_pytree():
    body = np.arange(1 << 12, dtype=np.int64) // 64
    col = encode_column(_padded(body), len(body))
    leaves, treedef = jax.tree_util.tree_flatten(col)
    back = jax.tree_util.tree_unflatten(treedef, leaves)
    assert back.kind == col.kind and back.n_rows == col.n_rows
    np.testing.assert_array_equal(_np(back.decode_full()),
                                  _np(col.decode_full()))


# ---------------------------------------------------------------------------
# zone maps
# ---------------------------------------------------------------------------

def test_zone_map_bounds_exact_with_ragged_tail():
    body = np.arange(100, dtype=np.int64)
    zm = build_zone_maps(_padded(body), 100, zone_rows=16)
    assert len(zm.zmin) == 7                      # ceil(100 / 16)
    np.testing.assert_array_equal(zm.zmin, np.arange(7) * 16)
    # ragged last zone covers rows 96..99 only; the identity padding
    # must not leak the zero tail into its min
    assert zm.zmax[-1] == 99 and zm.zmin[-1] == 96
    assert zm.chunk_bounds(32, 20) == (32, 63)    # zones 2..3
    assert zm.chunk_bounds(0, 100) == (0, 99)


def test_zone_map_float_identity_padding():
    body = np.full(10, -5.0)
    zm = build_zone_maps(_padded(body), 10, zone_rows=16)
    assert zm.zmin[0] == -5.0 and zm.zmax[0] == -5.0


# ---------------------------------------------------------------------------
# pruning: conservative vs a brute-force oracle
# ---------------------------------------------------------------------------

_OPS = {"eq": np.equal, "lt": np.less, "lte": np.less_equal,
        "gt": np.greater, "gte": np.greater_equal}


@pytest.mark.parametrize("layout", ["sorted", "random", "clustered"])
def test_prune_chunks_never_skips_a_passing_row(layout):
    rng = np.random.default_rng(hash(layout) % (1 << 31))
    n = 2000
    if layout == "sorted":
        vals = np.sort(rng.integers(0, 1000, size=n))
    elif layout == "clustered":
        vals = (np.arange(n) // 250) * 100 + rng.integers(0, 40, size=n)
    else:
        vals = rng.integers(0, 1000, size=n)
    zm = build_zone_maps(jnp.asarray(vals), n, zone_rows=64)
    chunks = [(p, min(128, n - p)) for p in range(0, n, 128)]
    for _ in range(40):
        k = int(rng.integers(1, 4))
        pd = [{"column": "c",
               "op": str(rng.choice(list(_OPS))),
               "value": int(rng.integers(-50, 1100))} for _ in range(k)]
        kept, skipped = prune_chunks(chunks, {"c": zm}, pd)
        assert len(kept) + skipped == len(chunks)
        assert kept                                # never empties the scan
        kept_set = set(kept)
        for pos, count in chunks:
            if (pos, count) in kept_set:
                continue
            seg = vals[pos:pos + count]
            mask = np.ones(len(seg), dtype=bool)
            for e in pd:
                mask &= _OPS[e["op"]](seg, e["value"])
            assert not mask.any(), \
                f"pruned a chunk with passing rows: {pd}"


def test_entry_unsatisfiable_edges():
    # zone holds [10, 20]
    assert entry_unsatisfiable("eq", 9, 10, 20)
    assert not entry_unsatisfiable("eq", 10, 10, 20)
    assert entry_unsatisfiable("lt", 10, 10, 20)
    assert not entry_unsatisfiable("lte", 10, 10, 20)
    assert entry_unsatisfiable("gt", 20, 10, 20)
    assert not entry_unsatisfiable("gte", 20, 10, 20)
    # all-null zone carries identity bounds (min > max): any comparison
    # is unsatisfiable, matching NULL-never-passes filter semantics
    assert entry_unsatisfiable("lte", 1 << 60, 10, -10)


# ---------------------------------------------------------------------------
# pushdown extraction: unit-safe literal handling
# ---------------------------------------------------------------------------

_V2C = {"x_0": "x", "d_1": "d", "q_2": "q"}


def test_extract_plain_comparison_and_flip():
    x = VariableReferenceExpression("x_0", BIGINT)
    lt = call("lt", BOOLEAN, x, constant(5, BIGINT))
    assert extract_pushdown(lt, _V2C) == [
        {"column": "x", "op": "lt", "value": 5}]
    flipped = call("gt", BOOLEAN, constant(5, BIGINT), x)   # 5 > x == x < 5
    assert extract_pushdown(flipped, _V2C) == [
        {"column": "x", "op": "lt", "value": 5}]


def test_extract_between_and_conjunction():
    x = VariableReferenceExpression("x_0", DOUBLE)
    bt = call("between", BOOLEAN, x, constant(1.5, DOUBLE),
              constant(2.5, DOUBLE))
    ge = call("gte", BOOLEAN, x, constant(0.0, DOUBLE))
    both = special("AND", BOOLEAN, bt, ge)
    assert extract_pushdown(both, _V2C) == [
        {"column": "x", "op": "gte", "value": 1.5},
        {"column": "x", "op": "lte", "value": 2.5},
        {"column": "x", "op": "gte", "value": 0.0}]


def test_extract_date_constant_becomes_epoch_days():
    d = VariableReferenceExpression("d_1", DATE)
    ge = call("gte", BOOLEAN, d, constant("1994-01-01", DATE))
    assert extract_pushdown(ge, _V2C) == [
        {"column": "d", "op": "gte", "value": 8766}]


def test_extract_decimal_requires_matching_scale():
    from decimal import Decimal
    q = VariableReferenceExpression("q_2", DecimalType(12, 2))
    ok = call("lt", BOOLEAN, q, constant(Decimal("24"), DecimalType(38, 2)))
    # stored columns are UNSCALED at the column's scale: 24.00 -> 2400
    assert extract_pushdown(ok, _V2C) == [
        {"column": "q", "op": "lt", "value": 2400}]
    # scale mismatch would be a silent 10x unit error: must NOT extract
    bad = call("lt", BOOLEAN, q, constant(Decimal("24"), DecimalType(38, 3)))
    assert extract_pushdown(bad, _V2C) == []
    # a raw int against an unscaled decimal column is off by 10^scale
    raw = call("lt", BOOLEAN, q, constant(24, BIGINT))
    assert extract_pushdown(raw, _V2C) == []


def test_extract_rejects_non_range_shapes():
    x = VariableReferenceExpression("x_0", BIGINT)
    y = VariableReferenceExpression("y_9", BIGINT)
    assert extract_pushdown(call("lt", BOOLEAN, x, y), _V2C) == []
    assert extract_pushdown(
        call("eq", BOOLEAN, x, constant(True, BOOLEAN)), _V2C) == []
    assert extract_pushdown(
        call("neq", BOOLEAN, x, constant(5, BIGINT)), _V2C) == []
    # unmapped variable (not a bare scan column)
    assert extract_pushdown(
        call("lt", BOOLEAN, VariableReferenceExpression("expr_3", BIGINT),
             constant(5, BIGINT)), _V2C) == []


# ---------------------------------------------------------------------------
# resident store: LRU eviction, budget rejection
# ---------------------------------------------------------------------------

def _metrics_snapshot():
    return dict(STORAGE_METRICS)


def _metric_delta(before, key):
    return STORAGE_METRICS[key] - before[key]


def test_store_lru_evicts_under_tight_budget():
    # measure the two columns' encoded sizes, then size the budget so
    # they provably cannot coexist: the second build must evict the
    # first, and re-requesting the first must rebuild it (miss, not an
    # error)
    probe = ResidentStore(budget=1 << 30, max_column_bytes=1 << 30)
    pa = probe.get_or_build("tpch", "lineitem", "quantity", 0.01,
                            10_000, 256, False)
    pb = probe.get_or_build("tpch", "lineitem", "extendedprice", 0.01,
                            10_000, 256, False)
    st = ResidentStore(budget=pa.nbytes + pb.nbytes - 1,
                       max_column_bytes=1 << 30)
    before = _metrics_snapshot()
    a = st.get_or_build("tpch", "lineitem", "quantity", 0.01,
                        10_000, 256, False)
    assert a is not None
    b = st.get_or_build("tpch", "lineitem", "extendedprice", 0.01,
                        10_000, 256, False)
    assert b is not None
    assert _metric_delta(before, "evictions") == 1
    assert len(st.entries) == 1
    a2 = st.get_or_build("tpch", "lineitem", "quantity", 0.01,
                         10_000, 256, False)
    assert a2 is not None
    assert _metric_delta(before, "cache_hits") == 0


def test_store_rejects_oversized_column_gracefully():
    st = ResidentStore(budget=1 << 20, max_column_bytes=1 << 10)
    before = _metrics_snapshot()
    ent = st.get_or_build("tpch", "lineitem", "quantity", 0.01,
                          10_000, 256, False)
    assert ent is None                       # too big to ever cache
    assert _metric_delta(before, "build_rejected") == 1
    assert not st.entries


def test_store_hit_reuses_entry():
    st = ResidentStore(budget=1 << 24, max_column_bytes=1 << 30)
    before = _metrics_snapshot()
    e1 = st.get_or_build("tpch", "lineitem", "quantity", 0.01,
                         10_000, 256, False)
    e2 = st.get_or_build("tpch", "lineitem", "quantity", 0.01,
                         10_000, 128, False)   # smaller pad: still a hit
    assert e1 is e2
    assert _metric_delta(before, "cache_hits") == 1
    # a LARGER pad must rebuild (chunk slices may not clamp)
    e3 = st.get_or_build("tpch", "lineitem", "quantity", 0.01,
                         10_000, 512, False)
    assert e3 is not e1 and e3.pad == 512


# ---------------------------------------------------------------------------
# end-to-end: results identical to the oracle with storage active
# ---------------------------------------------------------------------------

Q6 = """
    select sum(l_extendedprice * l_discount) as revenue from lineitem
    where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01'
      and l_discount between 0.05 and 0.07 and l_quantity < 24
"""

Q1 = """
    select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
           sum(l_extendedprice) as sum_base_price,
           sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
           avg(l_quantity) as avg_qty, count(*) as count_order
    from lineitem where l_shipdate <= date '1998-09-02'
    group by l_returnflag, l_linestatus
    order by l_returnflag, l_linestatus
"""


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01")


def test_q1_matches_oracle_with_resident_storage(runner):
    runner.assert_same_as_reference(Q1, ordered=True)


def test_q6_matches_oracle_with_resident_storage(runner):
    before = _metrics_snapshot()
    runner.assert_same_as_reference(Q6)
    # the date/decimal conjuncts must have reached the pruning path
    assert _metric_delta(before, "chunks_total") > 0


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_randomized_range_constants_match_oracle(runner, seed):
    rng = np.random.default_rng(seed)
    cutoff = int(rng.integers(50, 15_000))
    lo = rng.integers(0, 6) / 100.0
    hi = lo + rng.integers(1, 4) / 100.0
    sql = (f"select count(*), sum(l_quantity) from lineitem "
           f"where l_orderkey < {cutoff} "
           f"and l_discount between {lo:.2f} and {hi:.2f}")
    runner.assert_same_as_reference(sql)


def test_selective_orderkey_predicate_skips_chunks():
    # dedicated store (distinct budget => distinct registry key) with
    # fine zones so the sf0.01 table spans many zones; l_orderkey is
    # monotone (RLE-hinted), so a low cutoff makes later chunks provably
    # unsatisfiable
    cfg = ExecutionConfig(storage_budget_bytes=(6 << 30) + 4096,
                          storage_zone_rows=1 << 10)
    r = LocalQueryRunner("sf0.01", config=cfg)
    before = _metrics_snapshot()
    r.assert_same_as_reference(
        "select count(*), sum(l_extendedprice) from lineitem "
        "where l_orderkey < 150")
    assert _metric_delta(before, "chunks_skipped") > 0


def test_tiny_storage_budget_falls_back_without_error():
    # every column is larger than the whole budget: nothing caches, the
    # scan regenerates on the fly, and the query still matches the
    # oracle — MemoryExceededError must never surface from storage
    cfg = ExecutionConfig(storage_budget_bytes=1 << 12)
    r = LocalQueryRunner("sf0.01", config=cfg)
    before = _metrics_snapshot()
    r.assert_same_as_reference(Q6)
    assert _metric_delta(before, "build_rejected") > 0
    assert _metric_delta(before, "columns_built") == 0


def test_storage_disabled_still_matches_oracle():
    r = LocalQueryRunner("sf0.01",
                         config=ExecutionConfig(storage_enabled=False))
    r.assert_same_as_reference(Q6)


def test_encodings_disabled_still_matches_oracle():
    cfg = ExecutionConfig(storage_budget_bytes=(6 << 30) + 8192,
                          storage_encodings=False)
    r = LocalQueryRunner("sf0.01", config=cfg)
    r.assert_same_as_reference(Q6)
