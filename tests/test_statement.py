"""Statement protocol (/v1/statement), dispatch queueing + resource groups,
StatementClient, and the CLI formatter — the client-layer analog of the
reference's QueuedStatementResource/ExecutingStatementResource +
StatementClientV1 + presto-cli (SURVEY.md §2.4, §2.11, L6)."""
import threading
import time

import pytest

from presto_tpu.cli import format_table, run_statement
from presto_tpu.client import QueryError, StatementClient
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.worker import WorkerServer
from presto_tpu.worker.statement import (DispatchManager, FAILED, FINISHED,
                                         QUEUED, ResourceGroupManager,
                                         ResourceGroupSpec, RUNNING,
                                         Selector)


@pytest.fixture(scope="module")
def coordinator():
    server = WorkerServer(coordinator=True, environment="test",
                          config=ExecutionConfig(batch_rows=1 << 13))
    yield server
    server.close()


@pytest.fixture(scope="module")
def client(coordinator):
    return StatementClient(coordinator.uri, schema="sf0.01")


def test_select_round_trip(client):
    r = client.execute("SELECT returnflag, count(*) c FROM lineitem "
                       "GROUP BY returnflag ORDER BY returnflag")
    assert r.column_names == ["returnflag", "c"]
    assert len(r.rows) == 3
    assert r.stats["state"] == "FINISHED"


def test_decimal_and_null_decode(client):
    r = client.execute("SELECT sum(extendedprice*discount) rev, "
                       "CAST(NULL AS bigint) n FROM lineitem "
                       "WHERE quantity < 2")
    from decimal import Decimal
    assert isinstance(r.rows[0][0], Decimal)
    assert r.rows[0][1] is None


def test_multi_chunk_paging(coordinator, client):
    old = DispatchManager.RESULT_CHUNK_ROWS
    DispatchManager.RESULT_CHUNK_ROWS = 10
    try:
        r = client.execute("SELECT orderkey FROM orders "
                           "WHERE orderkey <= 120 ORDER BY orderkey")
    finally:
        DispatchManager.RESULT_CHUNK_ROWS = old
    assert len(r.rows) > 10                     # paged across several chunks
    assert r.rows == sorted(r.rows)


def test_error_propagates(client):
    with pytest.raises(QueryError):
        client.execute("SELECT no_such_column FROM lineitem")


def test_session_properties_flow(coordinator):
    c = StatementClient(coordinator.uri, schema="sf0.01",
                        session={"task_batch_rows": "4096"})
    r = c.execute("SELECT count(*) c FROM lineitem")
    assert r.rows[0][0] > 0


def test_cancel_requires_slug(coordinator, client):
    import urllib.error
    import urllib.request
    r = client.execute("SELECT 1 x")
    # DELETE without the per-query slug must not cancel (404: no such route)
    req = urllib.request.Request(
        f"{coordinator.uri}/v1/statement/{r.query_id}", method="DELETE")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 404
    # wrong slug on the full path is rejected too
    req = urllib.request.Request(
        f"{coordinator.uri}/v1/statement/queued/{r.query_id}/badslug/0",
        method="DELETE")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 404


def test_query_info_endpoint(coordinator, client):
    r = client.execute("SELECT 1 x")
    import json
    import urllib.request
    with urllib.request.urlopen(
            f"{coordinator.uri}/v1/query/{r.query_id}") as resp:
        info = json.loads(resp.read())
    assert info["state"] == "FINISHED"
    assert "resourceGroups" in info
    with urllib.request.urlopen(f"{coordinator.uri}/v1/query") as resp:
        listing = json.loads(resp.read())
    assert any(q["queryId"] == r.query_id for q in listing)


def test_statement_over_http_workers():
    """Full stack: client -> coordinator statement protocol -> distributed
    scheduling over announced HTTP workers (task protocol + exchange)."""
    coordinator = WorkerServer(coordinator=True, environment="test",
                               config=ExecutionConfig(batch_rows=1 << 13))
    workers = [WorkerServer(discovery_uri=coordinator.uri,
                            announce_interval_s=0.1, environment="test",
                            config=ExecutionConfig(batch_rows=1 << 13))
               for _ in range(2)]
    try:
        deadline = time.time() + 10
        while len(coordinator.worker_uris()) < 2 and time.time() < deadline:
            time.sleep(0.05)
        c = StatementClient(coordinator.uri, schema="sf0.01")
        r = c.execute("SELECT returnflag, sum(quantity) sq FROM lineitem "
                      "GROUP BY returnflag ORDER BY returnflag")
        assert len(r.rows) == 3
        assert r.stats["state"] == "FINISHED"
    finally:
        for w in workers:
            w.close()
        coordinator.close()


# ---------------------------------------------------------------------------
# dispatch / resource groups (unit level, fake executor)
# ---------------------------------------------------------------------------

class _FakeResult:
    column_names = ["x"]
    column_types = ["bigint"]
    rows = [[1]]


def _slow_executor(release: threading.Event):
    def run(q):
        release.wait(5)
        return _FakeResult()
    return run


def test_queueing_and_release():
    gate = threading.Event()
    rgm = ResourceGroupManager(
        [ResourceGroupSpec("g", hard_concurrency_limit=1, max_queued=1)],
        [Selector(group="g")])
    d = DispatchManager(_slow_executor(gate), rgm)
    q1 = d.submit("s1")
    q2 = d.submit("s2")
    time.sleep(0.1)
    assert q1.state == RUNNING
    assert q2.state == QUEUED
    # queue full -> immediate failure (QUERY_QUEUE_FULL analog)
    q3 = d.submit("s3")
    assert q3.state == FAILED and "queued" in q3.error.lower()
    gate.set()
    assert q1.done.wait(5) and q1.state == FINISHED
    assert q2.done.wait(5) and q2.state == FINISHED


def test_cancel_queued():
    gate = threading.Event()
    rgm = ResourceGroupManager(
        [ResourceGroupSpec("g", hard_concurrency_limit=1, max_queued=5)],
        [Selector(group="g")])
    d = DispatchManager(_slow_executor(gate), rgm)
    q1 = d.submit("s1")
    q2 = d.submit("s2")
    d.cancel(q2.query_id)
    assert q2.state == "CANCELED"
    gate.set()
    assert q1.done.wait(5)


def test_cancel_queued_does_not_over_admit():
    """Cancelling a QUEUED query must not free a slot it never held."""
    gate = threading.Event()
    rgm = ResourceGroupManager(
        [ResourceGroupSpec("g", hard_concurrency_limit=1, max_queued=5)],
        [Selector(group="g")])
    d = DispatchManager(_slow_executor(gate), rgm)
    q1 = d.submit("s1")
    q2 = d.submit("s2")
    q3 = d.submit("s3")
    d.cancel(q2.query_id)
    time.sleep(0.1)
    info = rgm.info()["g"]
    assert info["running"] <= 1
    assert q3.state == QUEUED          # q3 must not start while q1 runs
    gate.set()
    assert q1.done.wait(5) and q3.done.wait(5)


def test_canceled_query_reports_error():
    gate = threading.Event()
    rgm = ResourceGroupManager(
        [ResourceGroupSpec("g", hard_concurrency_limit=1, max_queued=5)],
        [Selector(group="g")])
    d = DispatchManager(_slow_executor(gate), rgm)
    q1 = d.submit("s1")
    q2 = d.submit("s2")
    d.cancel(q2.query_id)
    resp = d.executing_response(q2, 0, "http://x")
    assert resp["error"]["errorName"] == "USER_CANCELED"
    gate.set()
    q1.done.wait(5)


def test_selector_routing():
    rgm = ResourceGroupManager(
        [ResourceGroupSpec("etl"), ResourceGroupSpec("adhoc")],
        [Selector(group="etl", source="etl-.*"),
         Selector(group="adhoc")])
    assert rgm.select("alice", "etl-nightly") == "etl"
    assert rgm.select("alice", "dashboard") == "adhoc"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_format_table():
    out = format_table(["a", "bb"], [[1, "xy"], [None, "z"]])
    lines = out.splitlines()
    assert lines[0].split("|")[0].strip() == "a"
    assert "NULL" in lines[3]
    assert len({len(l) for l in lines}) == 1    # aligned widths


def test_cli_run_statement(client, capsys):
    import io
    buf = io.StringIO()
    ok = run_statement(client, "SELECT 1 one, 2 two", out=buf)
    assert ok
    text = buf.getvalue()
    assert "one" in text and "1 row" in text
    assert not run_statement(client, "SELECT bogus FROM lineitem", out=buf)
