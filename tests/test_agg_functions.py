"""Statistical aggregate breadth (VERDICT missing #7): stddev/variance
family, corr/covar, approx_distinct, approx_percentile — engine vs the
independent numpy oracle, plus hand-computed anchors (python statistics)
so a shared misunderstanding cannot hide.

Reference: presto-main-base/.../operator/aggregation/ (112 files;
VarianceAggregation, CovarianceAggregation, ApproximateCountDistinct,
ApproximateLongPercentileAggregations).
"""
import statistics

import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13, join_out_capacity=1 << 15))


QUERIES = [
    "SELECT stddev(totalprice) s, variance(totalprice) v FROM orders",
    "SELECT stddev_pop(totalprice) s, var_pop(totalprice) v FROM orders",
    """SELECT orderpriority, stddev_samp(totalprice) s,
              var_samp(totalprice) v
       FROM orders GROUP BY orderpriority ORDER BY orderpriority""",
    "SELECT corr(totalprice, custkey) c FROM orders",
    """SELECT covar_pop(totalprice, custkey) a,
              covar_samp(totalprice, custkey) b FROM orders""",
    "SELECT approx_percentile(totalprice, 0.5) m FROM orders",
    """SELECT orderpriority, approx_percentile(totalprice, 0.9) p
       FROM orders GROUP BY orderpriority ORDER BY orderpriority""",
    """SELECT o.orderpriority, stddev(l.extendedprice) s
       FROM lineitem l JOIN orders o ON l.orderkey = o.orderkey
       GROUP BY o.orderpriority ORDER BY o.orderpriority""",
]


@pytest.mark.parametrize("i", range(len(QUERIES)))
def test_agg_differential(runner, i):
    runner.assert_same_as_reference(QUERIES[i])


def test_scatter_path_variance_stability():
    """The streaming scatter-table accumulator (agg_update/agg_merge) must
    not catastrophically cancel when |mean| >> spread.  The raw
    sum-of-squares form collapses var(1e9 + {0,1,2,...}) to 0; the Chan
    central-moment state keeps full precision.  Exercised directly because
    query-level tests route through the (already stable) sort path."""
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.exec.batch import Batch, Column
    from presto_tpu.exec.operators import (
        AggSpec, agg_finalize, agg_init, agg_merge, agg_update)

    base = 1e9
    vals = np.arange(20, dtype=np.float64)         # var_samp = 35.0
    specs = (AggSpec("var_samp", "v", is_float=True),
             AggSpec("stddev", "s", is_float=True),
             AggSpec("corr", "c", is_float=True))
    slots = 16

    def mk_state(chunk):
        st = agg_init(slots, specs, ("k",), (jnp.int64,))
        x = Column(jnp.asarray(base + chunk))
        y = Column(jnp.asarray(2.0 * chunk - base))  # corr(x, y) == 1
        k = Column(jnp.zeros(len(chunk), dtype=jnp.int64))
        b = Batch({"k": k}, jnp.ones(len(chunk), dtype=bool))
        return agg_update(st, b, [k], {"v": x, "s": x, "c": x},
                          specs, slots, 0, ("k",),
                          agg_inputs2={"c": y})

    merged = agg_merge(mk_state(vals[:7]), mk_state(vals[7:]),
                       specs, ("k",), slots)
    out = agg_finalize(merged, specs, ("k",), {})
    m = np.asarray(out.mask)
    var = float(np.asarray(out.columns["v"].values)[m][0])
    sd = float(np.asarray(out.columns["s"].values)[m][0])
    cr = float(np.asarray(out.columns["c"].values)[m][0])
    assert abs(var - 35.0) < 1e-6, var
    assert abs(sd - 35.0 ** 0.5) < 1e-6, sd
    assert abs(cr - 1.0) < 1e-9, cr


def test_stddev_anchor(runner):
    """Both implementations vs python statistics over the same values."""
    vals = [float(r[0]) for r in runner.execute(
        "SELECT totalprice FROM orders WHERE orderkey < 400").rows]
    got = runner.execute(
        "SELECT stddev(totalprice) s, var_pop(totalprice) v "
        "FROM orders WHERE orderkey < 400").rows[0]
    assert abs(float(got[0]) - statistics.stdev(vals)) \
        <= 1e-6 * statistics.stdev(vals)
    assert abs(float(got[1]) - statistics.pvariance(vals)) \
        <= 1e-6 * statistics.pvariance(vals)


def test_approx_distinct_small_cardinality_exact(runner):
    # 5 distinct values: HLL linear counting is exact at tiny cardinality
    got = runner.execute(
        "SELECT approx_distinct(orderpriority) FROM orders").rows[0][0]
    exact = runner.execute(
        "SELECT count(DISTINCT orderpriority) FROM orders").rows[0][0]
    assert got == exact == 5


def test_percentile_anchor(runner):
    vals = sorted(float(r[0]) for r in runner.execute(
        "SELECT totalprice FROM orders WHERE orderkey < 400").rows)
    got = float(runner.execute(
        "SELECT approx_percentile(totalprice, 0.5) FROM orders "
        "WHERE orderkey < 400").rows[0][0])
    import math
    want = vals[int(math.floor(0.5 * (len(vals) - 1) + 0.5))]
    assert abs(got - want) < 1e-9
