"""Event-listener pipeline + properties-file configuration (VERDICT r2 #9).

Reference analogs: QueryMonitor.java:106 (created/completed events to
every registered EventListener), EventListenerManager (listener failure
isolation), Configs.h / NodeConfig (config.properties / node.properties),
CatalogManager (etc/catalog/*.properties connector mounts).
"""
import json
import os

import pytest

from presto_tpu.worker.events import (EventListener, EventListenerManager,
                                      FileEventListener)
from presto_tpu.worker.properties import (execution_config_from_properties,
                                          load_properties,
                                          register_catalogs_from_etc,
                                          server_kwargs_from_etc)


class _Recorder(EventListener):
    def __init__(self):
        self.created = []
        self.completed = []

    def query_created(self, e):
        self.created.append(e)

    def query_completed(self, e):
        self.completed.append(e)


class _Broken(EventListener):
    def query_created(self, e):
        raise RuntimeError("listener bug")

    def query_completed(self, e):
        raise RuntimeError("listener bug")


# ---------------------------------------------------------------------------
# properties parsing
# ---------------------------------------------------------------------------

def test_load_properties_format(tmp_path):
    p = tmp_path / "config.properties"
    p.write_text(
        "# comment\n"
        "! also comment\n"
        "coordinator=true\n"
        "colon.key: colon value\n"
        "spaced.key =  trimmed  \n"
        "continued.key=one\\\n"
        "two\n"
        "bare-flag\n")
    props = load_properties(str(p))
    assert props["coordinator"] == "true"
    assert props["colon.key"] == "colon value"
    assert props["spaced.key"] == "trimmed"
    assert props["continued.key"] == "onetwo"
    assert props["bare-flag"] == ""


def test_execution_config_mapping():
    cfg = execution_config_from_properties({
        "query.max-memory-per-node": "512MB",
        "experimental.spill-enabled": "false",
        "exchange.compression-enabled": "true",
        "exchange.compression-codec": "zstd",
        "task.batch-rows": "8192",
        "coordinator-only.key": "ignored",
    })
    assert cfg.memory_budget_bytes == 512 << 20
    assert cfg.spill_enabled is False
    assert cfg.exchange_compression is True
    assert cfg.exchange_compression_codec == "ZSTD"
    assert cfg.batch_rows == 8192
    with pytest.raises(ValueError, match="LZO"):
        execution_config_from_properties(
            {"exchange.compression-codec": "LZO"})


def _write_etc(tmp_path, extra_catalogs=()):
    etc = tmp_path / "etc"
    (etc / "catalog").mkdir(parents=True)
    (etc / "config.properties").write_text(
        "coordinator=true\n"
        "http-server.http.port=0\n"
        "query.max-memory-per-node=1GB\n")
    (etc / "node.properties").write_text(
        "node.environment=staging\n"
        "node.id=node-cfg-1\n")
    (etc / "catalog" / "mem.properties").write_text(
        "connector.name=memory\n")
    for name, body in extra_catalogs:
        (etc / "catalog" / f"{name}.properties").write_text(body)
    return str(etc)


def test_server_kwargs_from_etc(tmp_path):
    etc = _write_etc(tmp_path)
    kwargs, props = server_kwargs_from_etc(etc)
    assert kwargs["coordinator"] is True
    assert kwargs["port"] == 0
    assert kwargs["environment"] == "staging"
    assert kwargs["node_id"] == "node-cfg-1"
    assert kwargs["config"].memory_budget_bytes == 1 << 30
    assert props["node.environment"] == "staging"


def test_register_catalogs_from_etc(tmp_path):
    from presto_tpu.connectors import catalog as registry
    etc = _write_etc(tmp_path)
    mounted = register_catalogs_from_etc(etc)
    assert mounted == {"mem": "memory"}
    assert registry.module("mem") is not None
    registry.unregister_connector("mem")


def test_unknown_connector_rejected(tmp_path):
    etc = _write_etc(tmp_path, extra_catalogs=[
        ("bad", "connector.name=not-a-connector\n")])
    with pytest.raises(ValueError, match="not-a-connector"):
        register_catalogs_from_etc(etc)


# ---------------------------------------------------------------------------
# event pipeline
# ---------------------------------------------------------------------------

def _drain(dispatch, q, timeout=120):
    """Walk the statement protocol like a client (streaming results only
    complete when drained); returns accumulated data rows."""
    import time as _time
    rows = []
    deadline = _time.time() + timeout
    token = 0
    while _time.time() < deadline and not q.done.is_set():
        if q.state == "QUEUED":
            dispatch.queued_response(q, 0, "http://test")
            continue
        resp = dispatch.executing_response(q, token, "http://test")
        rows.extend(resp.get("data", []))
        if "nextUri" in resp:
            token = int(resp["nextUri"].rsplit("/", 1)[1])
        elif not q.done.is_set():
            break
    return rows


def test_dispatch_fires_created_and_completed():
    from presto_tpu.worker.server import WorkerServer
    rec = _Recorder()
    mgr = EventListenerManager()
    mgr.register(rec)
    w = WorkerServer(coordinator=True, events=mgr)
    try:
        q = w.dispatch.submit("select count(*) from nation",
                              user="alice", source="cli")
        assert _drain(w.dispatch, q) == [[25]]
        assert q.done.wait(60)
        assert [e.query_id for e in rec.created] == [q.query_id]
        assert rec.created[0].user == "alice"
        assert rec.created[0].sql == "select count(*) from nation"
        done = [e for e in rec.completed if e.query_id == q.query_id]
        assert len(done) == 1
        assert done[0].state == "FINISHED"
        assert done[0].error is None
        assert done[0].wall_time_s >= 0
    finally:
        w.close()


def test_failed_query_event_carries_error():
    from presto_tpu.worker.server import WorkerServer
    rec = _Recorder()
    mgr = EventListenerManager()
    mgr.register(rec)
    w = WorkerServer(coordinator=True, events=mgr)
    try:
        q = w.dispatch.submit("select no_such_column from nation")
        assert q.done.wait(60)
        done = [e for e in rec.completed if e.query_id == q.query_id]
        assert done[0].state == "FAILED"
        assert done[0].error
    finally:
        w.close()


def test_listener_failure_isolated():
    """A throwing listener must not fail the query nor starve the next
    listener (EventListenerManager dispatch isolation)."""
    rec = _Recorder()
    mgr = EventListenerManager()
    mgr.register(_Broken())
    mgr.register(rec)
    from presto_tpu.worker.server import WorkerServer
    w = WorkerServer(coordinator=True, events=mgr)
    try:
        q = w.dispatch.submit("select count(*) from region")
        _drain(w.dispatch, q)
        assert q.done.wait(60)
        assert q.state == "FINISHED"
        assert len(rec.created) == 1 and len(rec.completed) >= 1
        assert mgr.dispatch_errors >= 2
    finally:
        w.close()


def test_file_event_listener(tmp_path):
    path = str(tmp_path / "events.jsonl")
    lst = FileEventListener(path)
    mgr = EventListenerManager()
    mgr.register(lst)
    from presto_tpu.worker.server import WorkerServer
    w = WorkerServer(coordinator=True, events=mgr)
    try:
        q = w.dispatch.submit("select count(*) from nation")
        _drain(w.dispatch, q)
        assert q.done.wait(60)
    finally:
        w.close()
    lines = [json.loads(l) for l in open(path)]
    kinds = [l["event"] for l in lines]
    assert "query_created" in kinds and "query_completed" in kinds
    assert all(l["query_id"] == q.query_id for l in lines)


def test_worker_boots_from_etc_dir(tmp_path):
    """End to end: `python -m presto_tpu.worker --etc-dir etc/` boots a
    coordinator from the file layout, serves a statement query, and the
    configured file event listener records it."""
    import re
    import subprocess
    import sys
    import time
    import urllib.request

    etc = _write_etc(tmp_path)
    events_path = os.path.join(str(tmp_path), "events.jsonl")
    with open(os.path.join(etc, "event-listener.properties"), "w") as f:
        f.write("event-listener.name=file\n"
                f"event-listener.path={events_path}\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "-m", "presto_tpu.worker", "--etc-dir", etc],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    try:
        line = proc.stdout.readline()
        m = re.search(r"(node-cfg-1) listening on (http://[\d.:]+)", line)
        assert m, f"node.id from node.properties not used: {line!r}"
        uri = m.group(2)
        req = urllib.request.Request(
            uri + "/v1/statement", data=b"select count(*) from region",
            headers={"X-Presto-User": "etc-test"})
        with urllib.request.urlopen(req, timeout=30) as r:
            d = json.loads(r.read())
        data = list(d.get("data", []))
        deadline = time.time() + 60
        while "nextUri" in d and time.time() < deadline:
            with urllib.request.urlopen(d["nextUri"], timeout=30) as r:
                d = json.loads(r.read())
            data.extend(d.get("data", []))
        assert data == [[5]], (data, d)
        deadline = time.time() + 10
        while time.time() < deadline:
            if os.path.exists(events_path) and any(
                    json.loads(l)["event"] == "query_completed"
                    for l in open(events_path)):
                break
            time.sleep(0.2)
        lines = [json.loads(l) for l in open(events_path)]
        assert any(l["event"] == "query_created"
                   and l["user"] == "etc-test" for l in lines)
        assert any(l["event"] == "query_completed"
                   and l["state"] == "FINISHED" for l in lines)
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_queue_full_rejection_emits_completed_event():
    """A query rejected at admission must emit query_completed (FAILED),
    not dangle as created-only in the event stream."""
    from presto_tpu.worker.statement import (DispatchManager,
                                             ResourceGroupManager,
                                             ResourceGroupSpec)
    import threading
    rec = _Recorder()
    mgr = EventListenerManager()
    mgr.register(rec)
    gate = threading.Event()

    def blocking_executor(q):
        gate.wait(30)
        class R:  # minimal QueryResult shape
            column_names, column_types, rows = ["c"], ["bigint"], [[1]]
        return R()

    from presto_tpu.worker.statement import Selector
    rg = ResourceGroupManager(
        [ResourceGroupSpec("tiny", hard_concurrency_limit=1, max_queued=0)],
        selectors=[Selector("tiny")])
    d = DispatchManager(blocking_executor, rg, events=mgr)
    q1 = d.submit("select 1")           # occupies the only slot
    q2 = d.submit("select 2")           # queue full -> rejected
    gate.set()
    assert q2.done.wait(10)
    assert q2.state == "FAILED"
    done = [e for e in rec.completed if e.query_id == q2.query_id]
    assert len(done) == 1 and done[0].state == "FAILED" and done[0].error
    q1.done.wait(10)


def test_trailing_continuation_line(tmp_path):
    p = tmp_path / "c.properties"
    p.write_text("plugin.bundles=/a/b,\\")
    assert load_properties(str(p)) == {"plugin.bundles": "/a/b,"}


def test_literal_lz4_fallback_large_input():
    """The pyarrow-less literal-only LZ4 encoder must produce one
    spec-valid sequence even beyond 1MiB (non-final sequences require a
    match part, so multi-sequence literal-only output is invalid)."""
    from presto_tpu.common.compression import (_lz4_literal_compress,
                                               lz4_block_decompress)
    import os
    data = os.urandom((1 << 20) + 12345)
    packed = _lz4_literal_compress(data)
    assert lz4_block_decompress(packed, len(data)) == data


def test_colon_separator_with_equals_in_value(tmp_path):
    p = tmp_path / "c.properties"
    p.write_text("launcher.args: -Dfoo=bar\n")
    assert load_properties(str(p)) == {"launcher.args": "-Dfoo=bar"}


def test_etc_config_keeps_tuned_defaults(tmp_path):
    """An etc dir with no execution keys must keep the worker's tuned
    ExecutionConfig defaults, not regress to the bare dataclass ones."""
    etc = _write_etc(tmp_path)
    kwargs, _ = server_kwargs_from_etc(etc)
    assert kwargs["config"].batch_rows == 1 << 16
    assert kwargs["config"].join_out_capacity == 1 << 18


# ---------------------------------------------------------------------------
# round 4: typed SystemConfig accessor + worker task-level events
# ---------------------------------------------------------------------------

def test_system_config_typed_accessors():
    from presto_tpu.worker.properties import SystemConfig
    cfg = SystemConfig({"http-server.http.port": "9090",
                        "experimental.spill-enabled": "false",
                        "task.max-drivers-per-task": "8",
                        "node.pool": "LEAF"})
    assert cfg.get("http-server.http.port") == 9090
    assert cfg.get("experimental.spill-enabled") is False
    assert cfg.get("task.max-drivers-per-task") == 8
    assert cfg.get("node.pool") == "LEAF"
    # defaults (Configs.h-style typed defaults) for absent keys
    assert cfg.get("exchange.compression-codec") == "LZ4"
    assert cfg.get("shutdown-onset-sec") == 10
    assert cfg.get("coordinator") is False
    # surface breadth: the most-used Configs.h key set is mapped
    assert len(cfg.known_keys()) >= 40
    import pytest as _pytest
    with _pytest.raises(KeyError):
        cfg.get("no.such.key")
    d = cfg.to_dict()
    assert d["http-server.http.port"] == 9090


def test_announcement_interval_key_mapped(tmp_path):
    etc = _write_etc(tmp_path)
    with open(f"{etc}/config.properties", "a") as f:
        f.write("announcement-interval-ms=250\n")
    kwargs, _ = server_kwargs_from_etc(etc)
    assert kwargs["announce_interval_s"] == 0.25


def test_task_completed_event_fires_from_worker_path():
    """Task-level events come from the WORKER task execution path
    (QueryMonitor.java:106 per-task stats), not only the statement
    protocol: a task run through TaskManager fires task_completed with
    the task's output counters."""
    import base64
    import json as _json
    import time as _time

    from presto_tpu.sql.planner import Planner
    from presto_tpu.spi import plan as P
    from presto_tpu.worker.events import EventListenerManager, EventListener
    from presto_tpu.worker.protocol import (OutputBuffersSpec,
                                            TaskUpdateRequest)
    from presto_tpu.worker.task import TaskManager

    got = []

    class L(EventListener):
        def task_completed(self, event):
            got.append(event)

    events = EventListenerManager()
    events.register(L())
    tm = TaskManager("http://127.0.0.1:0", events=events)
    out = Planner(default_schema="sf0.01", default_catalog="tpch") \
        .plan("SELECT count(*) FROM nation")
    frag = P.PlanFragment(
        "0", out, P.SOURCE_DISTRIBUTION,
        P.PartitioningScheme(P.SINGLE_DISTRIBUTION, [],
                             list(out.output_variables)),
        [n.id for n in P.walk_plan(out)
         if isinstance(n, P.TableScanNode)])
    from presto_tpu.connectors import catalog as cat
    splits = [s.to_dict() for s in cat.make_splits("nation", 0.01, 1)]
    from presto_tpu.worker.protocol import TaskSource
    upd = TaskUpdateRequest.make(
        "evq.0.0.0.0", 0, frag,
        [TaskSource.from_dict({"planNodeId": sid, "splits": splits,
                               "noMoreSplits": True})
         for sid in frag.partitioned_sources],
        OutputBuffersSpec("PARTITIONED", 1))
    tm.create_or_update(upd)
    deadline = _time.time() + 60
    while not got and _time.time() < deadline:
        _time.sleep(0.05)
    assert got, "no task_completed event fired"
    ev = got[0]
    assert ev.task_id == "evq.0.0.0.0"
    assert ev.state == "FINISHED"
    assert ev.output_rows == 1
    assert ev.wall_time_s >= 0
