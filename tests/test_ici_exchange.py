"""ICI partitioned exchange wired into the distributed executor
(exec/scheduler.py + parallel/exchange.py): hashed stage edges whose task
count equals the mesh size run as a jitted all_to_all over the device
mesh — the TPU-native replacement for the HTTP pull shuffle
(PartitionedOutputOperator.java:58 -> ExchangeClient.java:72).

Runs on the 8-device virtual CPU mesh (tests/conftest.py sets
xla_force_host_platform_device_count=8).
"""
import jax
import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import DistributedQueryRunner, LocalQueryRunner
from presto_tpu.exec.runner import _assert_rows_equal

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


def make_mesh():
    from presto_tpu.parallel.mesh import WORKER_AXIS
    return jax.sharding.Mesh(jax.devices()[:8], (WORKER_AXIS,))


def runners():
    cfg = ExecutionConfig(batch_rows=1 << 13, join_out_capacity=1 << 15)
    dist = DistributedQueryRunner("sf0.01", config=cfg, n_tasks=8,
                                  mesh=make_mesh())
    local = LocalQueryRunner("sf0.01", config=cfg)
    return dist, local


Q3 = """
SELECT l.orderkey, sum(l.extendedprice * (1 - l.discount)) AS revenue,
       o.orderdate, o.shippriority
FROM customer c, orders o, lineitem l
WHERE c.mktsegment = 'BUILDING' AND c.custkey = o.custkey
  AND l.orderkey = o.orderkey
  AND o.orderdate < DATE '1995-03-15' AND l.shipdate > DATE '1995-03-15'
GROUP BY l.orderkey, o.orderdate, o.shippriority
ORDER BY revenue DESC, o.orderdate
LIMIT 10
"""

Q5 = """
SELECT n.name, sum(l.extendedprice * (1 - l.discount)) AS revenue
FROM customer c, orders o, lineitem l, supplier s, nation n, region r
WHERE c.custkey = o.custkey AND l.orderkey = o.orderkey
  AND l.suppkey = s.suppkey AND c.nationkey = s.nationkey
  AND s.nationkey = n.nationkey AND n.regionkey = r.regionkey
  AND r.name = 'ASIA' AND o.orderdate >= DATE '1994-01-01'
  AND o.orderdate < DATE '1995-01-01'
GROUP BY n.name
ORDER BY revenue DESC
"""

GROUPBY = """
SELECT o.custkey, count(*) AS c, sum(o.totalprice) AS s
FROM orders o GROUP BY o.custkey
"""


def check(dist, local, sql, ordered=False):
    got = dist.execute(sql)
    exp = local.assert_same_as_reference(sql, ordered=ordered)
    _assert_rows_equal(got, exp, ordered)


@pytest.mark.parametrize("name,sql,ordered", [
    ("q3", Q3, True), ("q5", Q5, True), ("groupby", GROUPBY, False)])
def test_ici_distributed_parity(name, sql, ordered):
    dist, local = runners()
    check(dist, local, sql, ordered)


def test_ici_path_engaged():
    """The hashed exchange must actually go through the mesh all_to_all,
    not silently fall back to host page splitting."""
    from presto_tpu.exec import scheduler as S
    engaged = {"n": 0}
    orig = S.InProcessScheduler._ici_exchange

    def spy(self, stage, task_batches, keys):
        r = orig(self, stage, task_batches, keys)
        if r and stage.device_out is not None:
            engaged["n"] += 1
        return r
    S.InProcessScheduler._ici_exchange = spy
    try:
        dist, local = runners()
        check(dist, local, GROUPBY)
    finally:
        S.InProcessScheduler._ici_exchange = orig
    assert engaged["n"] >= 1, "ICI exchange never engaged"
