"""Iterative rule optimizer (sql/rules.py — the IterativeOptimizer.java:62
analog): per-rule rewrites, fixpoint driving, hit stats in EXPLAIN."""
import pytest

from presto_tpu.common.types import BIGINT, BOOLEAN
from presto_tpu.spi import plan as P
from presto_tpu.spi.expr import (ConstantExpression,
                                 VariableReferenceExpression, call, constant,
                                 variable)
from presto_tpu.sql.rules import DEFAULT_RULES, IterativeOptimizer
from presto_tpu.exec.runner import LocalQueryRunner


def _v(name):
    return variable(name, BIGINT)


def _scan(*cols):
    vs = [_v(c) for c in cols]
    return P.TableScanNode(
        "scan", P.TableHandle("tpch", "tpch", "nation",
                              (("scaleFactor", 0.01),)),
        vs, {v: P.ColumnHandle(c.split("_", 1)[-1], BIGINT)
             for v, c in zip(vs, cols)})


def _opt(root, stats=None):
    return IterativeOptimizer(DEFAULT_RULES).run(root, stats)


def test_merge_filters_and_trivial():
    scan = _scan("n_nationkey")
    pred = call("gt", BOOLEAN, _v("n_nationkey"), constant(3, BIGINT))
    plan = P.FilterNode("f1", P.FilterNode("f2", scan, pred),
                        constant(True, BOOLEAN))
    stats = {}
    out = _opt(plan, stats)
    # TRUE-filter removed, leaving the single real filter
    assert isinstance(out, P.FilterNode)
    assert out.source is scan
    assert stats.get("RemoveTrivialFilters") == 1


def test_false_filter_becomes_empty_values():
    plan = P.FilterNode("f", _scan("n_nationkey"),
                        constant(False, BOOLEAN))
    out = _opt(plan)
    assert isinstance(out, P.ValuesNode) and out.rows == []


def test_merge_limits_and_zero_limit():
    scan = _scan("n_nationkey")
    out = _opt(P.LimitNode("l1", P.LimitNode("l2", scan, 5), 10))
    assert isinstance(out, P.LimitNode) and out.count == 5
    assert out.source is scan
    out = _opt(P.LimitNode("l", scan, 0))
    assert isinstance(out, P.ValuesNode)


def test_create_topn():
    scan = _scan("n_nationkey")
    scheme = P.OrderingScheme([(_v("n_nationkey"), "ASC_NULLS_LAST")])
    out = _opt(P.LimitNode("l", P.SortNode("s", scan, scheme), 7))
    assert isinstance(out, P.TopNNode)
    assert out.count == 7 and out.source is scan


def test_push_limit_through_project():
    scan = _scan("n_nationkey")
    proj = P.ProjectNode("p", scan, {_v("x"): call(
        "add", BIGINT, _v("n_nationkey"), constant(1, BIGINT))})
    out = _opt(P.LimitNode("l", proj, 3))
    assert isinstance(out, P.ProjectNode)
    assert isinstance(out.source, P.LimitNode)


def test_remove_identity_projection():
    scan = _scan("n_nationkey", "n_regionkey")
    ident = P.ProjectNode("p", scan,
                          {v: v for v in scan.output_variables})
    out = _opt(P.LimitNode("l", ident, 3))
    assert isinstance(out.source, P.TableScanNode)


def test_inline_rename_projections():
    scan = _scan("n_nationkey")
    inner = P.ProjectNode("p1", scan, {_v("a"): _v("n_nationkey")})
    outer = P.ProjectNode("p2", inner, {_v("b"): call(
        "add", BIGINT, _v("a"), constant(1, BIGINT))})
    out = _opt(outer)
    assert isinstance(out, P.ProjectNode) and out.source is scan
    (v, e), = out.assignments.items()
    assert v.name == "b"
    assert e.arguments[0].name == "n_nationkey"   # substituted through


def test_push_filter_through_rename_project():
    scan = _scan("n_nationkey")
    proj = P.ProjectNode("p", scan, {_v("a"): _v("n_nationkey")})
    pred = call("gt", BOOLEAN, _v("a"), constant(3, BIGINT))
    out = _opt(P.FilterNode("f", proj, pred))
    assert isinstance(out, P.ProjectNode)
    assert isinstance(out.source, P.FilterNode)
    assert out.source.predicate.arguments[0].name == "n_nationkey"


def test_swap_join_sides_puts_small_build_right():
    big = _scan("l_orderkey")
    big.table = P.TableHandle("tpch", "tpch", "lineitem",
                              (("scaleFactor", 0.01),))
    small = P.TableScanNode(
        "scan2", P.TableHandle("tpch", "tpch", "nation",
                               (("scaleFactor", 0.01),)),
        [_v("n_nationkey")],
        {_v("n_nationkey"): P.ColumnHandle("nationkey", BIGINT)})
    join = P.JoinNode("j", P.INNER, small, big,
                      [(_v("n_nationkey"), _v("l_orderkey"))],
                      [_v("n_nationkey"), _v("l_orderkey")])
    stats = {}
    out = _opt(join, stats)
    assert stats.get("SwapJoinSides") == 1
    assert out.right.table.table_name == "nation"   # small side builds


def test_merge_limit_with_distinct():
    scan = _scan("n_regionkey")
    agg = P.AggregationNode("a", scan, {}, [_v("n_regionkey")])
    out = _opt(P.LimitNode("l", agg, 3))
    assert isinstance(out, P.DistinctLimitNode)
    assert out.count == 3


def test_fixpoint_chains_rules():
    """Limit(Limit(Project-identity(Sort))) collapses through three rules
    in one run."""
    scan = _scan("n_nationkey")
    scheme = P.OrderingScheme([(_v("n_nationkey"), "ASC_NULLS_LAST")])
    sort = P.SortNode("s", scan, scheme)
    ident = P.ProjectNode("p", sort, {v: v for v in sort.output_variables})
    plan = P.LimitNode("l1", P.LimitNode("l2", ident, 9), 4)
    stats = {}
    out = _opt(plan, stats)
    assert isinstance(out, P.TopNNode) and out.count == 4
    assert out.source is scan
    assert stats.get("CreateTopN") == 1
    assert sum(stats.values()) >= 3   # ident-project, topn, limit-merge


def test_explain_reports_rule_hits():
    r = LocalQueryRunner("sf0.01")
    res = r.execute("explain select * from "
                    "(select n_name from nation order by n_name) limit 3")
    text = res.rows[0][0]
    assert "Optimizer rules fired:" in text


def test_rules_preserve_query_results():
    r = LocalQueryRunner("sf0.01")
    for sql, ordered in [
        ("select n_name from nation where n_nationkey > 3 "
         "order by n_name limit 4", True),
        # limit >= the distinct count so the row SET is deterministic
        ("select distinct o_orderstatus from orders limit 5", False),
        ("select c_custkey + 1 from customer where c_custkey < 10", False),
    ]:
        r.assert_same_as_reference(sql, ordered=ordered)
