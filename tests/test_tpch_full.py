"""Full TPC-H conformance: all 22 queries, TPU engine vs numpy reference
(differential testing per SURVEY.md §4.3 — the reference runs its shared SQL
suites against H2 / the Java engine; our oracle is exec/reference.py).

The query corpus lives in presto_tpu/benchmarks/tpch_queries.py, shared
with the benchmark driver (benchmarks/ analog)."""
import pytest

from presto_tpu.benchmarks.tpch_queries import ALL, ORDERED
from presto_tpu.exec.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01")


def check(runner, sql, ordered=False):
    return runner.assert_same_as_reference(sql, ordered=ordered)


@pytest.mark.parametrize("qnum", sorted(ALL))
def test_tpch(runner, qnum):
    check(runner, ALL[qnum], ordered=qnum in ORDERED)
