"""DB-API 2.0 driver over the statement protocol (the presto-jdbc analog)."""
import pytest

import presto_tpu.dbapi as dbapi
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.worker import WorkerServer


@pytest.fixture(scope="module")
def server():
    s = WorkerServer(coordinator=True, environment="test",
                     config=ExecutionConfig(batch_rows=1 << 13))
    yield s
    s.close()


@pytest.fixture(scope="module")
def conn(server):
    with dbapi.connect(server.uri, schema="sf0.01") as c:
        yield c


def test_module_globals():
    assert dbapi.apilevel == "2.0"
    assert dbapi.paramstyle == "qmark"


def test_cursor_fetch(conn):
    cur = conn.cursor()
    cur.execute("SELECT returnflag, count(*) c FROM lineitem "
                "GROUP BY returnflag ORDER BY returnflag")
    assert [d[0] for d in cur.description] == ["returnflag", "c"]
    assert cur.rowcount == 3
    first = cur.fetchone()
    assert first[0] == "A"
    rest = cur.fetchall()
    assert len(rest) == 2
    assert cur.fetchone() is None


def test_iteration_and_fetchmany(conn):
    cur = conn.cursor()
    cur.execute("SELECT orderkey FROM orders WHERE orderkey <= 40 "
                "ORDER BY orderkey")
    two = cur.fetchmany(2)
    assert [r[0] for r in two] == [1, 2]
    remaining = list(cur)
    assert remaining[0][0] > 2


def test_qmark_parameters(conn):
    cur = conn.cursor()
    cur.execute("SELECT count(*) c FROM orders WHERE orderkey <= ? "
                "AND orderstatus = ?", (100, "F"))
    n = cur.fetchone()[0]
    cur.execute("SELECT count(*) c FROM orders WHERE orderkey <= 100 "
                "AND orderstatus = 'F'")
    assert cur.fetchone()[0] == n
    with pytest.raises(dbapi.ProgrammingError):
        cur.execute("SELECT ? + ?", (1,))


def test_qmark_inside_string_literal(conn):
    cur = conn.cursor()
    cur.execute("SELECT count(*) c FROM orders WHERE orderstatus <> 'a?b' "
                "AND orderkey <= ?", (50,))
    assert cur.fetchone()[0] == 50


def test_errors(conn):
    cur = conn.cursor()
    with pytest.raises(dbapi.ProgrammingError):
        cur.execute("SELECT no_such FROM lineitem")
    conn2 = dbapi.connect("http://127.0.0.1:1", schema="sf0.01")
    with pytest.raises(dbapi.OperationalError):
        conn2.cursor().execute("SELECT 1")
