"""Host-sync lint conformance (tier-1): the shipped tree is clean — every
device->host transfer is an acknowledged, pragma'd sync point — and each
hazard shape is detected on a fixture.

The lint is the second prong of the PlanCheck work: plan validation
catches the coordinator inserting a malformed stage; this catches the
executor silently serialising the pipeline with an implicit transfer.
"""
import os
import subprocess
import sys

import pytest

from presto_tpu.analysis.lint import (ALL_LINT_CODES, KERNEL_INTERPRET,
                                      MEM_PRAGMA, MEM_UNCHARGED_STAGING,
                                      NET_NO_TIMEOUT, NET_PRAGMA, PRAGMA,
                                      SYNC_ASARRAY, SYNC_BRANCH, SYNC_CAST,
                                      SYNC_EXPLICIT, SYNC_NETWORK,
                                      SYNC_WALLCLOCK, TELEM_UNBOUNDED_QUEUE,
                                      WALL_PRAGMA, lint_or_raise, lint_paths,
                                      lint_source)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(findings):
    return {f.code for f in findings}


# ---------------------------------------------------------------------------
# the tier-1 gate: shipped tree is clean
# ---------------------------------------------------------------------------

def test_shipped_tree_is_clean():
    findings = lint_paths([os.path.join(REPO, "presto_tpu")])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_module_entry_point_exit_codes(tmp_path):
    """`python -m presto_tpu.analysis.lint` is the CI surface: 0 on the
    shipped tree, nonzero on a traced-.item() fixture."""
    clean = subprocess.run(
        [sys.executable, "-m", "presto_tpu.analysis.lint", "presto_tpu"],
        cwd=REPO, capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    fixture = tmp_path / "bad.py"
    fixture.write_text(
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    return jnp.sum(x).item()\n")
    bad = subprocess.run(
        [sys.executable, "-m", "presto_tpu.analysis.lint", str(fixture)],
        cwd=REPO, capture_output=True, text=True)
    assert bad.returncode == 1
    assert "SYNC001" in bad.stdout


def test_ci_entry_point_exits_clean(tmp_path):
    """`python -m presto_tpu.analysis.ci` is the single gate CI runs:
    lint + concurrency + a PlanChecker sweep, exit 0 on a clean tree and
    a JSON report with the expected shape."""
    import json
    report_path = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "presto_tpu.analysis.ci",
         "--max-plans", "3", "--json", str(report_path)],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(report_path.read_text())
    assert report["clean"] is True
    assert report["total_findings"] == 0
    assert report["files_scanned"] > 0
    assert report["plan_sweep"]["queries"] == 3
    assert report["lint"]["findings"] == []
    assert report["concurrency"]["findings"] == []


# ---------------------------------------------------------------------------
# hazard shapes
# ---------------------------------------------------------------------------

def test_item_call_flagged():
    findings = lint_source(
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    s = jnp.sum(x)\n"
        "    return s.item()\n")
    assert _codes(findings) == {SYNC_EXPLICIT}


def test_device_get_flagged():
    findings = lint_source(
        "import jax\n"
        "def f(x):\n"
        "    return jax.device_get(x)\n")
    assert _codes(findings) == {SYNC_EXPLICIT}


def test_block_until_ready_flagged():
    findings = lint_source(
        "def f(x):\n"
        "    return x.block_until_ready()\n")
    assert _codes(findings) == {SYNC_EXPLICIT}


def test_cast_of_traced_value_flagged():
    findings = lint_source(
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    return float(jnp.mean(x)), int(jnp.sum(x))\n")
    assert _codes(findings) == {SYNC_CAST}
    assert len(findings) == 2


def test_cast_tracks_assigned_names():
    findings = lint_source(
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    total = jnp.sum(x) + 1\n"
        "    return int(total)\n")
    assert _codes(findings) == {SYNC_CAST}


def test_np_asarray_on_device_value_flagged():
    findings = lint_source(
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    y = jnp.where(x > 0, x, 0)\n"
        "    return np.asarray(y)\n")
    assert _codes(findings) == {SYNC_ASARRAY}


def test_branch_on_device_bool_flagged():
    findings = lint_source(
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    if jnp.any(x > 0):\n"
        "        return 1\n"
        "    while jnp.all(x):\n"
        "        pass\n")
    assert _codes(findings) == {SYNC_BRANCH}
    assert len(findings) == 2


_NET_FIXTURE = ("import urllib.request\n"
                "def fetch(url):\n"
                "    return urllib.request.urlopen(url).read()\n")


def test_network_call_in_compute_module_flagged():
    findings = lint_source(_NET_FIXTURE,
                           path="presto_tpu/exec/bad_net.py")
    assert _codes(findings) == {SYNC_NETWORK}


def test_network_call_outside_compute_paths_not_flagged():
    # worker-layer code (incl. the sanctioned exchange client) may do
    # blocking HTTP; the lint scopes SYNC005 to pipeline compute packages.
    # NET001 still applies there (the fixture omits timeout=) — assert
    # only that SYNC005 stays out of the worker layer.
    for path in ("presto_tpu/worker/exchange.py",
                 "presto_tpu/worker/coordinator.py"):
        assert _codes(lint_source(_NET_FIXTURE, path=path)) == \
            {NET_NO_TIMEOUT}
    assert lint_source(_NET_FIXTURE, path="tools/fetch.py") == []


def test_network_parse_and_error_usage_not_flagged():
    # urllib.parse / urllib.error are metadata, not blocking I/O — they
    # appear legitimately in exec/lowering.py and common/errors.py
    findings = lint_source(
        "from urllib.parse import urlparse\n"
        "import urllib.error\n"
        "def f(u):\n"
        "    try:\n"
        "        return urlparse(u).netloc\n"
        "    except urllib.error.URLError:\n"
        "        return ''\n",
        path="presto_tpu/exec/lowering.py")
    assert findings == []


def test_network_pragma_suppresses():
    findings = lint_source(
        "import urllib.request\n"
        "def fetch(url):\n"
        "    return urllib.request.urlopen(url)  # lint: allow-host-sync\n",
        path="presto_tpu/common/whatever.py")
    assert findings == []


_WALL_FIXTURE = ("import time\n"
                 "def drive(batches):\n"
                 "    t0 = time.perf_counter()\n"
                 "    n = sum(1 for _ in batches)\n"
                 "    return n, time.perf_counter() - t0\n")


def test_wall_clock_in_exec_flagged():
    findings = lint_source(_WALL_FIXTURE,
                           path="presto_tpu/exec/bad_timer.py")
    assert _codes(findings) == {SYNC_WALLCLOCK}
    assert len(findings) == 2


def test_wall_clock_outside_exec_not_flagged():
    # the rule is scoped to the execution layer; worker/bench/storage code
    # times freely
    for path in ("presto_tpu/worker/task.py", "presto_tpu/storage/store.py",
                 "bench.py"):
        assert lint_source(_WALL_FIXTURE, path=path) == []


def test_wall_clock_pragma_suppresses():
    findings = lint_source(
        "import time\n"
        "def drive(stats):\n"
        "    t0 = time.perf_counter()  # lint: allow-wall-clock\n"
        "    stats.record_wall(time.perf_counter() - t0)"
        "  # lint: allow-wall-clock\n",
        path="presto_tpu/exec/scheduler.py")
    assert findings == []


def test_pragmas_are_not_interchangeable():
    # a host-sync acknowledgement must not silence SYNC006 (and vice
    # versa): each code checks only its own pragma's line set
    findings = lint_source(
        "import time\n"
        "def f():\n"
        "    return time.perf_counter()  # lint: allow-host-sync\n",
        path="presto_tpu/exec/whatever.py")
    assert _codes(findings) == {SYNC_WALLCLOCK}
    findings = lint_source(
        "import jax\n"
        "def f(x):\n"
        "    return jax.device_get(x)  # lint: allow-wall-clock\n",
        path="presto_tpu/exec/whatever.py")
    assert _codes(findings) == {SYNC_EXPLICIT}


# ---------------------------------------------------------------------------
# precision: host values and metadata must NOT be flagged
# ---------------------------------------------------------------------------

def test_device_get_result_is_host():
    """device_get moves the value to host: casting/branching on its
    result is the sanctioned pattern, only the device_get itself needs
    the pragma."""
    findings = lint_source(
        "import jax\n"
        "def f(x):\n"
        "    v = jax.device_get(x)  # lint: allow-host-sync\n"
        "    if int(v) > 0:\n"
        "        return float(v)\n")
    assert findings == []


def test_dtype_metadata_is_host():
    findings = lint_source(
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    if jnp.issubdtype(x.dtype, jnp.floating):\n"
        "        return int(x.shape[0]) + int(jnp.iinfo(x.dtype).max)\n")
    assert findings == []


def test_plain_python_casts_not_flagged():
    findings = lint_source(
        "def f(args):\n"
        "    return int(args[1].value), float('3')\n")
    assert findings == []


def test_pragma_suppresses():
    findings = lint_source(
        "import jax\n"
        "def f(x):\n"
        "    return bool(jax.device_get(x))  # lint: allow-host-sync\n")
    assert findings == []


def test_pragma_covers_multiline_statement():
    findings = lint_source(
        "import jax\n"
        "def f(x, y):\n"
        "    return jax.device_get(  # lint: allow-host-sync\n"
        "        (x, y))\n")
    assert findings == []


def test_syntax_error_is_reported_not_raised():
    findings = lint_source("def f(:\n")
    assert [f.code for f in findings] == ["SYNTAX"]


def test_lint_routes_through_error_taxonomy(tmp_path):
    """lint_or_raise fails through the same non-retryable PLAN_VALIDATION
    channel as the plan checker."""
    from presto_tpu.common.errors import PlanValidationError, is_retryable
    fixture = tmp_path / "bad.py"
    fixture.write_text("import jax.numpy as jnp\n"
                       "def f(x):\n"
                       "    return jnp.sum(x).item()\n")
    with pytest.raises(PlanValidationError) as ei:
        lint_or_raise([str(fixture)])
    assert ei.value.diagnostics
    assert not is_retryable(ei.value)
    lint_or_raise([os.path.join(REPO, "presto_tpu")])  # clean: no raise


def test_interpret_literal_flagged_outside_shim():
    """KERNEL001: an interpret=True literal outside the CPU-fallback shim
    would make a TPU build silently run Pallas kernels interpreted."""
    src = ("from jax.experimental import pallas as pl\n"
           "def f(kernel, spec, shapes):\n"
           "    return pl.pallas_call(kernel, grid_spec=spec,\n"
           "                          out_shape=shapes, interpret=True)\n")
    findings = lint_source(src, "presto_tpu/exec/kernels/scan_kernel.py")
    assert KERNEL_INTERPRET in _codes(findings)
    # ...and there is no pragma escape
    src2 = ("from jax.experimental import pallas as pl\n"
            "def f(kernel, spec, shapes):\n"
            "    return pl.pallas_call(\n"
            "        kernel, grid_spec=spec,  # lint: allow-host-sync\n"
            "        out_shape=shapes,\n"
            "        interpret=True)  # lint: allow-wall-clock\n")
    findings = lint_source(src2, "presto_tpu/exec/kernels/scan_kernel.py")
    assert KERNEL_INTERPRET in _codes(findings)


def test_interpret_kwargs_store_flagged():
    findings = lint_source(
        "def f(kwargs):\n"
        "    kwargs['interpret'] = True\n",
        "presto_tpu/exec/pipeline.py")
    assert KERNEL_INTERPRET in _codes(findings)


def test_interpret_allowed_in_shim_only():
    src = ("def pallas_call(kernel, **kwargs):\n"
           "    kwargs['interpret'] = True\n"
           "    return kernel(**kwargs)\n")
    assert lint_source(src, "presto_tpu/exec/kernels/shim.py") == []
    assert lint_source(src, "presto_tpu/exec/kernels/other.py") != []


def test_kernels_package_is_sync_and_wall_scoped():
    """exec/kernels/ files fall under the SYNC + wall-clock rules (the
    path markers cover presto_tpu/exec/ recursively)."""
    findings = lint_source(
        "import time\n"
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    t0 = time.perf_counter()\n"
        "    return jnp.sum(x).item(), t0\n",
        "presto_tpu/exec/kernels/scan_kernel.py")
    assert {SYNC_EXPLICIT, SYNC_WALLCLOCK} <= _codes(findings)


@pytest.mark.parametrize("path", [
    "presto_tpu/exec/kernels/join.py",
    "presto_tpu/exec/kernels/window.py",
])
def test_new_kernel_files_fall_under_kernel_rules(path):
    """The PR 16 kernel files (in-kernel join probe, prefix-sum window
    aggregation) sit under the same KERNEL001 + SYNC + wall-clock scope
    as scan_kernel.py — an interpret literal or a host sync added there
    must fail tier-1 exactly like in the original kernel."""
    src = ("from jax.experimental import pallas as pl\n"
           "def f(kernel, shapes):\n"
           "    return pl.pallas_call(kernel, out_shape=shapes,\n"
           "                          interpret=True)\n")
    assert KERNEL_INTERPRET in _codes(lint_source(src, path))
    src2 = ("import time\n"
            "import jax.numpy as jnp\n"
            "def f(x):\n"
            "    t0 = time.perf_counter()\n"
            "    return jnp.sum(x).item(), t0\n")
    assert {SYNC_EXPLICIT, SYNC_WALLCLOCK} <= _codes(
        lint_source(src2, path))


def test_unbounded_queue_in_telemetry_flagged():
    """TELEM001: queue.Queue() with no / zero maxsize and SimpleQueue()
    are unbounded buffers; the telemetry package must bound every
    queue so a stalled sink drops instead of growing until OOM."""
    src = ("import queue\n"
           "a = queue.Queue()\n"
           "b = queue.Queue(maxsize=0)\n"
           "c = queue.SimpleQueue()\n"
           "ok1 = queue.Queue(maxsize=256)\n"
           "ok2 = queue.Queue(128)\n"
           "ok3 = queue.Queue(maxsize=bound)\n")
    findings = lint_source(src, "presto_tpu/telemetry/export.py")
    assert _codes(findings) == {TELEM_UNBOUNDED_QUEUE}
    assert [f.line for f in findings] == [2, 3, 4]


def test_unbounded_queue_outside_telemetry_not_flagged():
    src = "import queue\nq = queue.Queue()\n"
    for path in ("presto_tpu/worker/exchange.py",
                 "presto_tpu/exec/local_exchange.py"):
        assert lint_source(src, path) == []


def test_telemetry_queue_has_no_pragma_escape():
    findings = lint_source(
        "import queue\n"
        "q = queue.Queue()  # lint: allow-host-sync\n",
        "presto_tpu/telemetry/export.py")
    assert _codes(findings) == {TELEM_UNBOUNDED_QUEUE}


def test_telemetry_network_scoping():
    """telemetry/ is network-scoped (SYNC005) except export.py, whose
    OTLP POSTs run on the exporter's background flush thread.  NET001
    (missing timeout=) applies to the whole package, export.py
    included — a flush thread wedged on a dead collector never drains."""
    findings = lint_source(_NET_FIXTURE,
                           path="presto_tpu/telemetry/export.py")
    assert _codes(findings) == {NET_NO_TIMEOUT}
    findings = lint_source(_NET_FIXTURE,
                           path="presto_tpu/telemetry/history.py")
    assert _codes(findings) == {SYNC_NETWORK, NET_NO_TIMEOUT}


def test_urllib_without_timeout_in_worker_flagged():
    """NET001: a urllib request in worker/ or telemetry/ without an
    explicit timeout= can block its thread forever on a dead peer —
    the exact hang the fault-tolerant mode exists to survive."""
    findings = lint_source(_NET_FIXTURE,
                           path="presto_tpu/worker/server.py")
    assert _codes(findings) == {NET_NO_TIMEOUT}
    # urlopen_internal (worker/auth.py wrapper) is held to the same rule
    findings = lint_source(
        "from .auth import urlopen_internal\n"
        "def probe(req):\n"
        "    return urlopen_internal(req)\n",
        path="presto_tpu/worker/coordinator.py")
    assert _codes(findings) == {NET_NO_TIMEOUT}


def test_urllib_with_timeout_not_flagged():
    src = ("import urllib.request\n"
           "def fetch(url):\n"
           "    return urllib.request.urlopen(url, timeout=5).read()\n")
    assert lint_source(src, path="presto_tpu/worker/server.py") == []
    # a **kwargs splat is trusted to carry the caller's bound
    src2 = ("import urllib.request\n"
            "def fetch(url, **kw):\n"
            "    return urllib.request.urlopen(url, **kw).read()\n")
    assert lint_source(src2, path="presto_tpu/worker/server.py") == []


def test_urllib_timeout_scope_and_pragma():
    # the rule is scoped to worker/ + telemetry/; elsewhere urllib calls
    # answer only to SYNC005's compute-module scoping
    assert lint_source(_NET_FIXTURE, path="presto_tpu/sql/planner.py") == []
    suppressed = lint_source(
        "import urllib.request\n"
        "def fetch(url):\n"
        "    return urllib.request.urlopen(url)  # lint: allow-no-timeout\n",
        path="presto_tpu/worker/server.py")
    assert suppressed == []
    # ...and the net pragma is its own line set: a host-sync pragma does
    # not silence NET001
    findings = lint_source(
        "import urllib.request\n"
        "def fetch(url):\n"
        "    return urllib.request.urlopen(url)  # lint: allow-host-sync\n",
        path="presto_tpu/worker/server.py")
    assert _codes(findings) == {NET_NO_TIMEOUT}


_MEM_FIXTURE = ("class BucketStager:\n"
                "    def __init__(self):\n"
                "        self.pending_pages = []\n"
                "        self._chunks: dict = {}\n"
                "    def add(self, b):\n"
                "        self.pending_pages.append(b)\n")


def test_uncharged_staging_class_flagged():
    """MEM001: a class in exec//worker/ that stages rows in unbounded
    host collections but never touches the memory-accounting API is
    invisible to the arbitrator — exactly the PR 2 retained-buffer
    leak this rule fossilizes."""
    findings = lint_source(_MEM_FIXTURE, path="presto_tpu/exec/stager.py")
    assert _codes(findings) == {MEM_UNCHARGED_STAGING}
    assert [f.line for f in findings] == [3, 4]
    findings = lint_source(_MEM_FIXTURE, path="presto_tpu/worker/stager.py")
    assert _codes(findings) == {MEM_UNCHARGED_STAGING}


def test_charged_staging_class_not_flagged():
    # any reference to the charging API in the class body vouches for it
    src = _MEM_FIXTURE.replace(
        "    def add(self, b):\n",
        "    def add(self, b, ctx):\n"
        "        ctx.try_reserve(len(b))\n")
    assert lint_source(src, path="presto_tpu/exec/stager.py") == []
    src2 = _MEM_FIXTURE.replace(
        "    def add(self, b):\n",
        "    def attach(self, memory_context):\n"
        "        self.memory_context = memory_context\n"
        "    def add(self, b):\n")
    assert lint_source(src2, path="presto_tpu/worker/stager.py") == []


def test_staging_outside_memory_scope_not_flagged():
    # the rule is scoped to exec/ and worker/; sql- and storage-layer
    # collections hold plans and metadata, not row data
    for path in ("presto_tpu/sql/planner.py",
                 "presto_tpu/storage/store.py", "bench.py"):
        assert lint_source(_MEM_FIXTURE, path=path) == []


def test_bounded_and_copy_constructors_not_flagged():
    src = ("import collections\n"
           "class RingStager:\n"
           "    def __init__(self, pages):\n"
           "        self.pending_pages = collections.deque(maxlen=8)\n"
           "        self.page_copy = list(pages)\n")
    assert lint_source(src, path="presto_tpu/exec/ring.py") == []


def test_uncharged_staging_pragma_suppresses():
    src = _MEM_FIXTURE.replace(
        "self.pending_pages = []",
        "self.pending_pages = []  # lint: allow-uncharged-staging").replace(
        "self._chunks: dict = {}",
        "self._chunks: dict = {}  # lint: allow-uncharged-staging")
    assert lint_source(src, path="presto_tpu/exec/stager.py") == []
    # ...but the memory pragma is its own line set: a host-sync pragma
    # does not silence MEM001
    src2 = _MEM_FIXTURE.replace(
        "self.pending_pages = []",
        "self.pending_pages = []  # lint: allow-host-sync")
    findings = lint_source(src2, path="presto_tpu/exec/stager.py")
    assert MEM_UNCHARGED_STAGING in _codes(findings)


def test_all_codes_are_exercised_above():
    assert set(ALL_LINT_CODES) == {SYNC_EXPLICIT, SYNC_CAST, SYNC_ASARRAY,
                                   SYNC_BRANCH, SYNC_NETWORK, SYNC_WALLCLOCK,
                                   KERNEL_INTERPRET, TELEM_UNBOUNDED_QUEUE,
                                   MEM_UNCHARGED_STAGING, NET_NO_TIMEOUT}
    assert PRAGMA == "lint: allow-host-sync"
    assert WALL_PRAGMA == "lint: allow-wall-clock"
    assert MEM_PRAGMA == "lint: allow-uncharged-staging"
    assert NET_PRAGMA == "lint: allow-no-timeout"
