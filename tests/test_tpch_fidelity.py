"""TPC-H data-fidelity properties (VERDICT r2 missing #7): the generator
is counter-hash (self-consistent, not dbgen-bit-identical), but the value
domains the benchmark queries FILTER on must match the spec or whole
queries run hollow — q9 ('%green%') and q20 ('forest%') matched zero parts
and q18 (sum(l_quantity) > 300) could never fire with a fixed 4-line
fanout.  Reference: dbgen dists.dss colors list, spec 4.2.3 (P_NAME = 5
words), spec table layouts (O_ORDERKEY 1..7 lineitems).
"""
import numpy as np
import pytest

from presto_tpu.connectors import device_gen as D
from presto_tpu.connectors import tpch as H

SF = 0.01


def test_part_names_use_spec_word_list():
    n = H._table_rows("part", SF)
    names = H._gen_part("name", np.arange(n, dtype=np.int64), SF)
    assert len(H.P_NAME_WORDS) == 92  # dbgen dists.dss colors
    assert all(len(x.split()) == 5 for x in names[:100])
    assert all(w in H.P_NAME_WORDS for x in names[:100] for w in x.split())
    assert all(len(x) <= 55 for x in names)  # VarcharType(55)
    # q9-class selectivity: P(contains 'green') = 1-(91/92)^5 ~ 5.3%
    frac = sum("green" in x for x in names) / n
    assert 0.03 < frac < 0.08, frac
    # q20-class prefix selectivity ~ 5/92 * 1/5 = 1.1%
    frac = sum(x.startswith("forest") for x in names) / n
    assert 0.004 < frac < 0.025, frac


def test_lineitem_fanout_one_to_seven():
    n_li = H._table_rows("lineitem", SF)
    n_orders = H._table_rows("orders", SF)
    idx = np.arange(n_li, dtype=np.int64)
    ok, ln = H._li_order_map(idx, SF)
    assert ok.min() == 1 and ok.max() == n_orders
    assert (np.diff(ok) >= 0).all()          # ROWID_ORDERED contract
    cnt = np.bincount(ok)[1:]
    assert cnt.sum() == n_li                 # row count exactly 4x orders
    assert cnt.min() >= 1 and cnt.max() == 7  # spec: 1..7 lines per order
    # linenumber is 1..cnt within each order
    for o in (1, 7, 8, 12345, n_orders):
        rows = np.where(ok == o)[0]
        assert list(ln[rows]) == list(range(1, len(rows) + 1))


def test_device_host_order_map_parity():
    import jax.numpy as jnp
    idx = np.arange(H._table_rows("lineitem", SF), dtype=np.int64)
    ok_h, ln_h = H._li_order_map(idx, SF)
    ok_d, ln_d = D._li_order_map(jnp.asarray(idx), SF)
    assert (np.asarray(ok_d) == ok_h).all()
    assert (np.asarray(ln_d) == ln_h).all()


def test_q18_shape_is_satisfiable():
    """Orders with sum(l_quantity) > 300 must be rare-but-possible: 7-line
    orders exist and the max possible sum is 350."""
    n_li = H._table_rows("lineitem", SF)
    idx = np.arange(n_li, dtype=np.int64)
    ok, _ = H._li_order_map(idx, SF)
    qty = H._gen_lineitem("quantity", idx, SF) // 100
    sums = np.bincount(ok, weights=qty)[1:]
    assert sums.max() > 250                  # the tail exists
    assert sums.max() <= 350                 # 7 * 50 spec ceiling


def test_benchmark_queries_not_hollow():
    """q9 and q20 must select real rows now (they returned 0 for two
    rounds because the filters matched nothing)."""
    from presto_tpu.exec.runner import LocalQueryRunner
    r = LocalQueryRunner(f"sf{SF:g}")
    green = r.execute(
        "select count(*) from part where name like '%green%'").rows[0][0]
    assert green > 50, green
    q9ish = r.execute(
        "select count(*) from lineitem l, part p "
        "where p.partkey = l.partkey and p.name like '%green%'").rows[0][0]
    assert q9ish > 1000, q9ish
