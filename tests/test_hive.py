"""Hive/Parquet connector: CTAS + INSERT + DROP through the SQL surface,
scan parity vs the numpy reference, null round-trips, commit semantics.
(Reference analog: presto-hive + presto-parquet + TableWriter/TableFinish
operators; SURVEY.md §2.8/§2.9.)"""
import os

import pytest

from presto_tpu.connectors import catalog, hive
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner


@pytest.fixture
def runner(tmp_path):
    conn = hive.HiveConnector(str(tmp_path / "warehouse"))
    catalog.register_connector("hive", conn)
    try:
        yield LocalQueryRunner("sf0.01", config=ExecutionConfig(
            batch_rows=1 << 13))
    finally:
        catalog.unregister_connector("hive")


def test_ctas_and_scan_parity(runner):
    r = runner.execute("""
        CREATE TABLE hv_lineitem AS
        SELECT orderkey, quantity, extendedprice, discount, shipdate,
               returnflag
        FROM lineitem WHERE orderkey < 500""")
    written = r.rows[0][0]
    assert written > 0

    # row count round-trips
    got = runner.execute("SELECT count(*) c FROM hv_lineitem")
    assert got.rows[0][0] == written

    # full differential: engine over parquet vs numpy reference over parquet
    runner.assert_same_as_reference("""
        SELECT returnflag, sum(quantity) sq, sum(extendedprice*discount) rev,
               count(*) c
        FROM hv_lineitem
        WHERE shipdate >= DATE '1994-01-01'
        GROUP BY returnflag ORDER BY returnflag""", ordered=True)

    # values match the original generated table exactly
    a = runner.execute("""
        SELECT orderkey, quantity, extendedprice FROM hv_lineitem
        ORDER BY orderkey, quantity, extendedprice""")
    b = runner.execute("""
        SELECT orderkey, quantity, extendedprice FROM lineitem
        WHERE orderkey < 500
        ORDER BY orderkey, quantity, extendedprice""")
    assert a.rows == b.rows


def test_strings_round_trip(runner):
    runner.execute("""
        CREATE TABLE hv_cust AS
        SELECT custkey, mktsegment, nationkey FROM customer
        WHERE custkey <= 200""")
    a = runner.execute(
        "SELECT mktsegment, count(*) c FROM hv_cust GROUP BY mktsegment")
    b = runner.execute(
        "SELECT mktsegment, count(*) c FROM customer WHERE custkey <= 200 "
        "GROUP BY mktsegment")
    assert a.sorted_rows() == b.sorted_rows()
    # string predicate over the parquet-backed dictionary column
    a = runner.execute("SELECT count(*) c FROM hv_cust "
                       "WHERE mktsegment = 'BUILDING'")
    b = runner.execute("SELECT count(*) c FROM customer WHERE custkey <= 200 "
                       "AND mktsegment = 'BUILDING'")
    assert a.rows == b.rows


def test_nulls_round_trip(runner):
    runner.execute("""
        CREATE TABLE hv_nulls AS
        SELECT orderkey,
               CASE WHEN quantity < 2500 THEN NULL ELSE quantity END q
        FROM lineitem WHERE orderkey < 200""")
    runner.assert_same_as_reference(
        "SELECT count(*) c, count(q) cq, sum(q) sq FROM hv_nulls")
    got = runner.execute("SELECT count(*) n FROM hv_nulls WHERE q IS NULL")
    assert got.rows[0][0] > 0


def test_insert_appends(runner):
    runner.execute("CREATE TABLE hv_t AS SELECT orderkey FROM orders "
                   "WHERE orderkey < 100")
    before = runner.execute("SELECT count(*) c FROM hv_t").rows[0][0]
    r = runner.execute("INSERT INTO hv_t SELECT orderkey FROM orders "
                       "WHERE orderkey >= 100 AND orderkey < 200")
    after = runner.execute("SELECT count(*) c FROM hv_t").rows[0][0]
    assert after == before + r.rows[0][0]


def test_insert_uses_target_schema_names(runner):
    """INSERT is positional: aliased SELECT outputs land in the target
    schema's columns, and arity/type mismatches are rejected."""
    runner.execute("CREATE TABLE hv_pos AS SELECT orderkey, totalprice "
                   "FROM orders WHERE orderkey < 50")
    runner.execute("INSERT INTO hv_pos SELECT orderkey + 1000000 AS weird, "
                   "totalprice AS other FROM orders WHERE orderkey < 10")
    got = runner.execute("SELECT count(orderkey) c FROM hv_pos")
    all_rows = runner.execute("SELECT count(*) c FROM hv_pos")
    assert got.rows[0][0] == all_rows.rows[0][0]   # no schema fork
    with pytest.raises(ValueError):
        runner.execute("INSERT INTO hv_pos SELECT orderkey FROM orders "
                       "WHERE orderkey < 5")       # arity mismatch
    with pytest.raises(ValueError):
        runner.execute("INSERT INTO hv_pos SELECT orderkey, orderkey "
                       "FROM orders WHERE orderkey < 5")  # type mismatch


def test_if_not_exists_ignores_readonly_catalogs(runner):
    """A generated tpch table of the same name must not make
    CREATE TABLE IF NOT EXISTS silently no-op."""
    r = runner.execute("CREATE TABLE IF NOT EXISTS nation AS "
                       "SELECT orderkey FROM orders WHERE orderkey < 20")
    assert r.rows[0][0] > 0
    runner.execute("DROP TABLE nation")


def test_drop_invalidates_plan_cache(runner):
    runner.execute("CREATE TABLE hv_gone AS SELECT orderkey FROM orders "
                   "WHERE orderkey < 30")
    runner.execute("SELECT count(*) c FROM hv_gone")   # plan gets cached
    runner.execute("DROP TABLE hv_gone")
    with pytest.raises(Exception) as ei:
        runner.execute("SELECT count(*) c FROM hv_gone")
    assert "hv_gone" in str(ei.value)


def test_joins_over_hive(runner):
    runner.execute("CREATE TABLE hv_orders AS SELECT orderkey, custkey, "
                   "totalprice FROM orders WHERE orderkey < 1000")
    runner.assert_same_as_reference("""
        SELECT c.mktsegment, count(*) c, sum(o.totalprice) tp
        FROM hv_orders o JOIN customer c ON o.custkey = c.custkey
        GROUP BY c.mktsegment""")


def test_empty_ctas_defines_schema(runner):
    """CTAS over an empty result still creates a queryable table with the
    SELECT's schema (a zero-row part file pins the columns)."""
    r = runner.execute("CREATE TABLE hv_empty AS SELECT orderkey, totalprice "
                       "FROM orders WHERE orderkey < 0")
    assert r.rows[0][0] == 0
    got = runner.execute("SELECT count(*) c, sum(totalprice) s FROM hv_empty")
    assert got.rows[0][0] == 0
    runner.execute("DROP TABLE hv_empty")


def test_create_if_not_exists_and_drop(runner):
    runner.execute("CREATE TABLE hv_x AS SELECT orderkey FROM orders "
                   "WHERE orderkey < 50")
    # duplicate create fails; IF NOT EXISTS is a no-op
    with pytest.raises(ValueError):
        runner.execute("CREATE TABLE hv_x AS SELECT orderkey FROM orders "
                       "WHERE orderkey < 50")
    r = runner.execute("CREATE TABLE IF NOT EXISTS hv_x AS "
                       "SELECT orderkey FROM orders WHERE orderkey < 50")
    assert r.rows[0][0] == 0
    runner.execute("DROP TABLE hv_x")
    with pytest.raises(Exception):
        runner.execute("SELECT count(*) c FROM hv_x")
    # DROP IF EXISTS on a missing table is a no-op
    runner.execute("DROP TABLE IF EXISTS hv_x")


def test_external_parquet_without_metadata(runner, tmp_path):
    """Files written by other engines (no presto_type metadata) map from
    their arrow types, incl. decimal128 -> scaled int64."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from decimal import Decimal
    tdir = tmp_path / "warehouse" / "ext"
    os.makedirs(tdir)
    tbl = pa.table({
        "k": pa.array([1, 2, 3], type=pa.int64()),
        "price": pa.array([Decimal("1.50"), Decimal("2.25"), None],
                          type=pa.decimal128(10, 2)),
        "name": pa.array(["a", "b", "a"], type=pa.string()),
    })
    pq.write_table(tbl, tdir / "part-0.parquet")
    catalog.module("hive").refresh()
    runner.assert_same_as_reference(
        "SELECT name, count(*) c, sum(price) p FROM ext GROUP BY name")
    got = runner.execute("SELECT sum(price) p FROM ext")
    assert str(got.rows[0][0]) == "3.75"


# ---------------------------------------------------------------------------
# round 4: ORC storage format (presto-orc analog; VERDICT r3 missing #8)
# ---------------------------------------------------------------------------

@pytest.fixture
def orc_runner(tmp_path):
    conn = hive.HiveConnector(str(tmp_path / "warehouse"),
                              storage_format="ORC")
    catalog.register_connector("hive", conn)
    try:
        yield LocalQueryRunner("sf0.01", config=ExecutionConfig(
            batch_rows=1 << 13))
    finally:
        catalog.unregister_connector("hive")


def test_orc_ctas_and_scan_parity(orc_runner):
    orc_runner.execute(
        "CREATE TABLE lineitem_orc AS SELECT l_orderkey, l_quantity, "
        "l_extendedprice, l_shipdate, l_returnflag FROM lineitem "
        "WHERE l_orderkey < 2000")
    # parts on disk are .orc files
    conn = catalog.module("hive")
    tdir = os.path.join(conn.warehouse, "lineitem_orc")
    assert all(f.endswith(".orc") for f in os.listdir(tdir))
    orc_runner.assert_same_as_reference(
        "SELECT l_returnflag, count(*), sum(l_quantity), "
        "sum(l_extendedprice) FROM lineitem_orc GROUP BY l_returnflag")
    # decimals round-trip exactly through decimal128 (ORC keeps no arrow
    # field metadata, so the logical type rides in-band)
    a = orc_runner.execute("SELECT sum(l_extendedprice) FROM lineitem_orc")
    b = orc_runner.execute("SELECT sum(l_extendedprice) FROM lineitem "
                           "WHERE l_orderkey < 2000")
    assert a.rows == b.rows


def test_orc_dates_and_filters(orc_runner):
    orc_runner.execute(
        "CREATE TABLE orders_orc AS SELECT o_orderkey, o_orderdate, "
        "o_totalprice FROM orders WHERE o_orderkey < 4000")
    orc_runner.assert_same_as_reference(
        "SELECT count(*) FROM orders_orc "
        "WHERE o_orderdate < date '1995-01-01'")


def test_external_orc_file(orc_runner, tmp_path):
    """ORC files written by another engine (plain arrow types) read
    through the connector."""
    import pyarrow as pa
    from pyarrow import orc as pa_orc
    from decimal import Decimal
    tdir = tmp_path / "warehouse" / "extorc"
    os.makedirs(tdir)
    tbl = pa.table({
        "k": pa.array([1, 2, 3], type=pa.int64()),
        "price": pa.array([Decimal("1.50"), Decimal("2.25"), None],
                          type=pa.decimal128(10, 2)),
        "name": pa.array(["a", "b", "a"], type=pa.string()),
    })
    pa_orc.write_table(tbl, str(tdir / "part-0.orc"))
    catalog.module("hive").refresh()
    orc_runner.assert_same_as_reference(
        "SELECT name, count(*) c, sum(price) p FROM extorc GROUP BY name")
    got = orc_runner.execute("SELECT sum(price) FROM extorc")
    assert str(got.rows[0][0]) == "3.75"


def test_orc_insert_appends(orc_runner):
    orc_runner.execute("CREATE TABLE t_orc AS SELECT n_nationkey, n_name "
                       "FROM nation WHERE n_nationkey < 5")
    orc_runner.execute("INSERT INTO t_orc SELECT n_nationkey, n_name "
                       "FROM nation WHERE n_nationkey >= 20")
    got = orc_runner.execute("SELECT count(*) FROM t_orc")
    assert got.rows == [[10]]
