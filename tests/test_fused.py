"""Fused join-chain execution (exec/fused.py): chain assembly, fanout
expansion, span aggregation, and NULL join-key semantics — each checked
differentially against the numpy oracle on BOTH the fused path and the
streaming fallback (fuse_pipelines=False), so the two executors cannot
drift apart (the round-1 review's NULL=NULL divergence class).

Reference fixture: exec/reference.py _exec_JoinNode (NULL keys never
match, presto-main-base LookupJoinOperator semantics).
"""
import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner


def runner_pair():
    fused = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 14, join_out_capacity=1 << 16))
    streaming = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 14, join_out_capacity=1 << 16,
        fuse_pipelines=False))
    return fused, streaming


FANOUT1_JOIN_AGG = """
SELECT o.orderpriority, count(*) AS c, sum(l.extendedprice) AS s
FROM lineitem l JOIN orders o ON l.orderkey = o.orderkey
WHERE o.orderdate < DATE '1995-06-01'
GROUP BY o.orderpriority
"""

EXPANSION_JOIN = """
SELECT c.mktsegment, count(*) AS c
FROM customer c JOIN orders o ON c.custkey = o.custkey
GROUP BY c.mktsegment
"""

SPAN_AGG = """
SELECT l.orderkey, sum(l.quantity) AS q, count(*) AS c
FROM lineitem l
GROUP BY l.orderkey
"""

LEFT_JOIN_FILTER = """
SELECT c.custkey, count(o.orderkey) AS c
FROM customer c LEFT JOIN orders o
  ON c.custkey = o.custkey AND o.totalprice > 100000
GROUP BY c.custkey
"""

NULL_KEY_JOIN = """
SELECT count(*) AS c
FROM (SELECT CASE WHEN custkey % 3 = 0 THEN NULL ELSE custkey END AS k
      FROM orders) o
JOIN customer c ON o.k = c.custkey
"""

NULL_KEY_LEFT = """
SELECT count(*) AS total, count(c.name) AS matched
FROM (SELECT CASE WHEN custkey % 3 = 0 THEN NULL ELSE custkey END AS k
      FROM orders) o
LEFT JOIN customer c ON o.k = c.custkey
"""

SEMI_NULL = """
SELECT count(*) AS c
FROM (SELECT CASE WHEN custkey % 3 = 0 THEN NULL ELSE custkey END AS k
      FROM orders) o
WHERE o.k IN (SELECT custkey FROM customer WHERE nationkey < 10)
"""


@pytest.mark.parametrize("name,sql", [
    ("fanout1_join_agg", FANOUT1_JOIN_AGG),
    ("expansion_join", EXPANSION_JOIN),
    ("span_agg", SPAN_AGG),
    ("left_join_filter", LEFT_JOIN_FILTER),
    ("null_key_join", NULL_KEY_JOIN),
    ("null_key_left", NULL_KEY_LEFT),
    ("semi_null", SEMI_NULL),
])
def test_fused_vs_streaming_vs_oracle(name, sql):
    fused, streaming = runner_pair()
    fused.assert_same_as_reference(sql)
    streaming.assert_same_as_reference(sql)


def test_chain_assembles_for_join_query():
    """The fused path must actually engage for the canonical join+agg
    shape (guards against silent universal fallback)."""
    from presto_tpu.exec import fused as F
    engaged = {"n": 0}
    orig = F.FusedChain.prep

    def spy(self):
        r = orig(self)
        if r is not None:
            engaged["n"] += 1
        return r
    F.FusedChain.prep = spy
    try:
        # isolated plan cache: the process-global one may hold a warm
        # compiler for this exact shape (prep legitimately skipped)
        from presto_tpu.serving import PlanCache
        r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
            batch_rows=1 << 14, join_out_capacity=1 << 16),
            plan_cache=PlanCache())
        r.assert_same_as_reference(FANOUT1_JOIN_AGG)
    finally:
        F.FusedChain.prep = orig
    assert engaged["n"] >= 1, "fused chain never engaged on join+agg query"
