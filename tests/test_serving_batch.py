"""Serving-plane micro-batching (serving/batching.py + serving/batched.py),
the persistent executable cache (serving/persist.py), and fragment-level
executable sharing (serving/fragments.py).

The load-bearing property throughout: a batched EXECUTE..USING produces
ROWS BIT-IDENTICAL to its solo run — the vmapped program replays the
sequential fused direct path's exact update sequence per lane — and one
lane's bind error never fails its batchmates."""
import random
import threading

import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner
from presto_tpu.serving import (FRAGMENT_JIT_CACHE, GLOBAL_PLAN_CACHE,
                                MicroBatcher, PREPARED_REGISTRY,
                                PlanCache, PlanCacheSidecar,
                                SERVING_METRICS)


@pytest.fixture(autouse=True)
def _reset_serving():
    SERVING_METRICS.reset()
    PREPARED_REGISTRY.clear()
    FRAGMENT_JIT_CACHE.invalidate_all()
    yield


def _snapshot():
    return SERVING_METRICS.snapshot()


def _runner(schema="sf0.01", **cfg):
    config = ExecutionConfig(**cfg) if cfg else None
    return LocalQueryRunner(schema, config=config, plan_cache=PlanCache())


# ---------------------------------------------------------------------------
# MicroBatcher unit behavior
# ---------------------------------------------------------------------------

def test_micro_batcher_disabled_runs_inline():
    b = MicroBatcher(window_ms=50, max_batch=1)
    assert not b.enabled
    calls = []
    out = b.run("k", 1, lambda items: [i * 10 for i in items],
                lambda item: calls.append(item) or item + 100)
    assert out == 101 and calls == [1]


def test_micro_batcher_groups_concurrent_items():
    b = MicroBatcher(window_ms=200, max_batch=8)
    results, solo = {}, []

    def worker(i):
        results[i] = b.run(
            "k", i, lambda items: [x * 10 for x in items],
            lambda item: solo.append(item) or item)
    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results == {0: 0, 1: 10, 2: 20, 3: 30}
    assert solo == []       # everyone rode the batch


def test_micro_batcher_full_batch_short_circuits_window():
    b = MicroBatcher(window_ms=10_000, max_batch=2)
    results = {}

    def worker(i):
        results[i] = b.run("k", i, lambda items: [x + 1 for x in items],
                           lambda item: -item)
    ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in ts), "window was not cut short"
    assert results == {0: 1, 1: 2}


def test_micro_batcher_none_lane_falls_back_isolated():
    b = MicroBatcher(window_ms=200, max_batch=8)
    results = {}

    def execute_batch(items):
        # lane for item 1 'fails' inside the drain
        return [None if x == 1 else x * 10 for x in items]

    def worker(i):
        results[i] = b.run("k", i, execute_batch, lambda item: -item)
    ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results[1] == -1             # solo fallback, on its own thread
    assert results[0] == 0 and results[2] == 20
    assert _snapshot()["servingBatchFallbacks"] == 1


def test_micro_batcher_batch_exception_everyone_falls_back():
    b = MicroBatcher(window_ms=200, max_batch=8)
    results = {}

    def worker(i):
        results[i] = b.run(
            "k", i, lambda items: (_ for _ in ()).throw(RuntimeError()),
            lambda item: item + 100)
    ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results == {0: 100, 1: 101, 2: 102}
    assert _snapshot()["servingBatchFallbacks"] == 3


def test_micro_batcher_single_item_runs_solo():
    b = MicroBatcher(window_ms=1, max_batch=8)
    batches = []
    out = b.run("k", 7, lambda items: batches.append(items) or [70],
                lambda item: item)
    assert out == 7 and batches == []   # occupancy-1: never drained


# ---------------------------------------------------------------------------
# batched execution: bit-identity vs sequential
# ---------------------------------------------------------------------------

Q6_TEMPLATE = ("select sum(l_extendedprice * l_discount) as revenue "
               "from lineitem where l_shipdate >= ? and l_shipdate < ? "
               "and l_discount between ? and ? and l_quantity < ?")
GROUPED_TEMPLATE = ("select l_returnflag, count(*) as c, "
                    "sum(l_quantity) as q, min(l_extendedprice) as lo, "
                    "max(l_extendedprice) as hi from lineitem "
                    "where l_quantity < ? group by l_returnflag")


def _rows_equal(a, b):
    return sorted(map(tuple, a)) == sorted(map(tuple, b))


def test_batched_q6_bit_identical_to_sequential():
    r = _runner()
    r.execute(f"prepare q6 from {Q6_TEMPLATE}")
    binds = [
        "execute q6 using date '1994-01-01', date '1995-01-01', "
        "0.05, 0.07, 24",
        "execute q6 using date '1994-01-01', date '1995-01-01', "
        "0.04, 0.06, 30",
        "execute q6 using date '1995-01-01', date '1996-01-01', "
        "0.01, 0.03, 10",
    ]
    seq = [r.execute(s).rows for s in binds]
    out = r.execute_prepared_batch(binds)
    assert out is not None
    for a, b in zip(seq, out):
        assert b is not None and a == b.rows    # exact, order and all
    sv = _snapshot()
    assert sv["servingBatches"] == 1
    assert sv["servingBatchQueries"] == 3
    assert sv["servingBatchLaunchesSaved"] == 2
    assert sv["servingBatchOccupancy"] == {"3": 1}
    assert sv["servingBatchPaddedLanes"] == 1   # 3 lanes -> width 4


def test_batched_grouped_bit_identical():
    r = _runner()
    r.execute(f"prepare sp from {GROUPED_TEMPLATE}")
    binds = [f"execute sp using {v}" for v in (11, 24, 37, 50)]
    seq = [r.execute(s).rows for s in binds]
    out = r.execute_prepared_batch(binds)
    assert out is not None
    for a, b in zip(seq, out):
        assert b is not None and _rows_equal(a, b.rows)


def test_batched_bind_error_lane_is_isolated():
    r = _runner()
    r.execute(f"prepare sp from {GROUPED_TEMPLATE}")
    binds = ["execute sp using 24",
             "execute sp using 'not a number'",     # bad bind mid-batch
             "execute sp using 30"]
    want0 = r.execute(binds[0]).rows
    want2 = r.execute(binds[2]).rows
    out = r.execute_prepared_batch(binds)
    assert out is not None
    assert out[1] is None                   # caller re-runs it solo
    assert _rows_equal(out[0].rows, want0)
    assert _rows_equal(out[2].rows, want2)


def test_batched_null_bind_lane_is_isolated():
    r = _runner()
    r.execute(f"prepare sp from {GROUPED_TEMPLATE}")
    binds = ["execute sp using 24", "execute sp using null",
             "execute sp using 30"]
    out = r.execute_prepared_batch(binds)
    if out is None:
        pytest.skip("NULL binds to a typed slot on this build")
    assert out[0] is not None and out[2] is not None


def test_batched_declines_mixed_templates_and_cold_cache():
    r = _runner()
    r.execute(f"prepare q6 from {Q6_TEMPLATE}")
    r.execute(f"prepare sp from {GROUPED_TEMPLATE}")
    # cold: no solo execution recorded the fast path yet
    assert r.execute_prepared_batch(
        ["execute sp using 1", "execute sp using 2"]) is None
    r.execute("execute sp using 24")
    # mixed templates are not one batch
    assert r.execute_prepared_batch(
        ["execute sp using 24",
         "execute q6 using date '1994-01-01', date '1995-01-01', "
         "0.05, 0.07, 24"]) is None
    # fewer than two bindable lanes
    assert r.execute_prepared_batch(["execute sp using 24"]) is None


def test_batched_fuzz_concurrent_mixed_binds():
    """Randomized concurrent EXECUTE..USING traffic through the batcher:
    mixed templates, bad binds mid-batch; every batched result must be
    bit-identical to the solo run of the same statement."""
    rng = random.Random(20260807)
    r = _runner()
    r.execute(f"prepare q6 from {Q6_TEMPLATE}")
    r.execute(f"prepare sp from {GROUPED_TEMPLATE}")

    def q6_stmt():
        y0 = rng.choice(["1993", "1994", "1995"])
        lo = rng.choice(["0.01", "0.03", "0.05"])
        q = rng.randint(5, 49)
        return (f"execute q6 using date '{y0}-01-01', "
                f"date '{int(y0) + 1}-01-01', {lo}, "
                f"{float(lo) + 0.02:.2f}, {q}")

    def sp_stmt():
        if rng.random() < 0.15:
            return "execute sp using 'bogus'"        # bind error lane
        return f"execute sp using {rng.randint(1, 50)}"

    stmts = [q6_stmt() if rng.random() < 0.5 else sp_stmt()
             for _ in range(24)]
    expected = []
    for s in stmts:
        try:
            expected.append(r.execute(s).rows)
        except Exception as e:    # noqa: BLE001 — bind errors expected
            expected.append(type(e).__name__)

    batcher = MicroBatcher(window_ms=150, max_batch=8)
    got = [None] * len(stmts)

    def template_of(s):
        return s.split()[1]

    def serve(i):
        s = stmts[i]

        def run_one(item):
            try:
                return r.execute(item).rows
            except Exception as e:  # noqa: BLE001
                return type(e).__name__

        def run_batch(items):
            out = r.execute_prepared_batch(items)
            return None if out is None else [
                (o.rows if o is not None else None) for o in out]
        got[i] = batcher.run((template_of(s),), s, run_batch, run_one)

    threads = [threading.Thread(target=serve, args=(i,))
               for i in range(len(stmts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, (want, have) in enumerate(zip(expected, got)):
        if isinstance(want, str):
            assert have == want, f"lane {i}: error class changed"
        else:
            assert _rows_equal(want, have), f"lane {i} diverged"
    assert _snapshot()["servingBatches"] >= 1, "no batch ever formed"


def test_batched_results_stable_across_widths():
    """The same statement must produce identical rows whatever batch it
    rides in (pow2 padding, different batchmates)."""
    r = _runner()
    r.execute(f"prepare sp from {GROUPED_TEMPLATE}")
    pin = "execute sp using 24"
    want = r.execute(pin).rows
    others = [f"execute sp using {v}" for v in (5, 11, 17, 29, 35, 41)]
    for width in (2, 3, 5, 7):
        batch = [pin] + others[:width - 1]
        out = r.execute_prepared_batch(batch)
        assert out is not None and out[0] is not None
        assert out[0].rows == want, f"width {width} changed lane 0"


# ---------------------------------------------------------------------------
# compiler-pool contention metering
# ---------------------------------------------------------------------------

def test_checkout_contention_metrics():
    cache = PlanCache()
    r = LocalQueryRunner("sf0.01", plan_cache=cache)
    sql = "select count(*) from lineitem where l_quantity < 24"
    r.execute(sql)
    key = [k for k in cache._entries][0]
    held = [cache.checkout(key) for _ in range(6)]   # drain the pool
    sv = _snapshot()
    assert sv["compilerCheckouts"] >= 6
    assert sv["compilerPoolExhausted"] >= 1         # pool is 4 deep
    assert sv["compilerCheckoutDepthPeak"] >= 6
    info = cache.info()
    assert info["poolExhausted"] >= 1
    assert info["checkedOut"] == 6
    for _t, _s, comp in held:
        cache.checkin(key, comp)    # None = rebuilt-and-dropped checkout
    assert cache.info()["checkedOut"] == 0


# ---------------------------------------------------------------------------
# persistent plan-cache sidecar
# ---------------------------------------------------------------------------

def test_sidecar_record_dedup_load_clear(tmp_path):
    p = tmp_path / "plans.jsonl"
    sc = PlanCacheSidecar(str(p))
    prepared = {"q6": Q6_TEMPLATE}
    assert sc.record("execute q6 using 1", prepared, "tpch", "sf0.01")
    # same template, different binding -> dedup'd
    assert not sc.record("execute q6 using 2", prepared, "tpch", "sf0.01")
    # different schema is a different entry
    assert sc.record("execute q6 using 1", prepared, "tpch", "sf1")
    # no prepared map: dedup by statement text
    assert sc.record("select 1", None, "tpch", "sf0.01")
    assert not sc.record("select 1", None, "tpch", "sf0.01")
    recs = sc.load()
    assert len(recs) == 3
    assert recs[0]["prepared"] == prepared

    # a fresh instance re-reads the file (restart)
    sc2 = PlanCacheSidecar(str(p))
    assert not sc2.record("execute q6 using 9", prepared, "tpch", "sf0.01")
    sc2.clear()
    assert sc2.load() == [] and not p.exists()


def test_sidecar_tolerates_torn_tail(tmp_path):
    p = tmp_path / "plans.jsonl"
    sc = PlanCacheSidecar(str(p))
    sc.record("select 1", None, "tpch", "sf0.01")
    with open(p, "a", encoding="utf-8") as f:
        f.write('{"sql": "select 2", "cat')    # torn write at crash
    assert [r["sql"] for r in PlanCacheSidecar(str(p)).load()] == \
        ["select 1"]


def test_enable_compilation_cache(tmp_path):
    import jax
    from presto_tpu.serving import enable_compilation_cache
    prev = jax.config.jax_compilation_cache_dir
    try:
        d = tmp_path / "xla-cache"
        assert enable_compilation_cache(str(d))
        assert d.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(d)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


# ---------------------------------------------------------------------------
# fragment-level executable sharing
# ---------------------------------------------------------------------------

def test_fragment_share_across_different_plans():
    """Two DIFFERENT full plans whose scan->filter subchain is structurally
    identical (same columns, same predicate, different aggregations above)
    share fragment-jit entries; a fresh runner (fresh PlanCompiler, own
    plan cache) shares them too; results match the unshared config."""
    r1 = _runner()
    sql_a = ("select sum(l_extendedprice) from lineitem "
             "where l_quantity < 24")
    sql_b = ("select min(l_extendedprice), max(l_extendedprice) "
             "from lineitem where l_quantity < 24")
    rows_a = r1.execute(sql_a).rows
    misses_after_a = _snapshot()["fragmentJitMisses"]
    rows_b = r1.execute(sql_b).rows
    sv = _snapshot()
    assert misses_after_a > 0, "fragment cache never engaged"
    assert sv["fragmentJitHits"] > 0, \
        "plans sharing a scan fragment did not share jits"

    # a different runner instance (new compilers) hits the global cache
    hits_before = sv["fragmentJitHits"]
    r2 = _runner()
    assert r2.execute(sql_a).rows == rows_a
    assert _snapshot()["fragmentJitHits"] > hits_before

    # same statements with sharing off: identical rows
    r3 = _runner(fragment_share=False)
    assert r3.execute(sql_a).rows == rows_a
    assert r3.execute(sql_b).rows == rows_b


def test_fragment_share_off_uses_no_global_cache():
    FRAGMENT_JIT_CACHE.invalidate_all()
    SERVING_METRICS.reset()
    r = _runner(fragment_share=False)
    r.execute("select count(*) from lineitem where l_quantity < 24")
    sv = _snapshot()
    assert sv["fragmentJitMisses"] == 0 and sv["fragmentJitHits"] == 0
    assert FRAGMENT_JIT_CACHE.info()["entries"] == 0


def test_fragment_cache_invalidated_by_ddl():
    runner = LocalQueryRunner("sf0.01", plan_cache=PlanCache())
    runner.execute("select count(*) from lineitem where l_quantity < 24")
    assert FRAGMENT_JIT_CACHE.info()["entries"] > 0
    runner._invalidate_plans()
    assert FRAGMENT_JIT_CACHE.info()["entries"] == 0


def test_fragment_share_key_isolates_configs():
    """The fragment key fingerprints the FULL execution config: the same
    plan under a different config must not share artifacts."""
    import dataclasses
    from presto_tpu.exec.pipeline import tuned_config
    r1 = _runner()
    sql = ("select sum(l_extendedprice) from lineitem "
           "where l_quantity < 24")
    want = r1.execute(sql).rows
    hits0 = _snapshot()["fragmentJitHits"]
    base = tuned_config()
    other = dataclasses.replace(base, batch_rows=base.batch_rows * 2)
    r2 = LocalQueryRunner("sf0.01", config=other, plan_cache=PlanCache())
    assert r2.execute(sql).rows == want
    assert _snapshot()["fragmentJitHits"] == hits0, \
        "different configs shared a compiled fragment"


# ---------------------------------------------------------------------------
# end to end over HTTP: the server-side batch intercept
# ---------------------------------------------------------------------------

def test_http_concurrent_executes_one_launch():
    from presto_tpu.client import StatementClient
    from presto_tpu.worker.server import WorkerServer
    srv = WorkerServer(coordinator=True, batch_window_ms=150,
                       max_batch_size=8)
    try:
        c = StatementClient(srv.uri, schema="sf0.01")
        c.execute(f"prepare q6 from {Q6_TEMPLATE}")
        stmts = ["execute q6 using date '1994-01-01', "
                 f"date '1995-01-01', 0.05, 0.07, {20 + i}"
                 for i in range(4)]
        c.execute(stmts[0])     # warm the template's fast path
        SERVING_METRICS.reset()

        results = [None] * 4

        def go(i):
            cc = StatementClient(srv.uri, schema="sf0.01")
            cc.prepared = dict(c.prepared)
            results[i] = cc.execute(stmts[i]).rows
        ts = [threading.Thread(target=go, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(r for r in results)
        sv = _snapshot()
        assert sv["servingBatchQueries"] >= 2, "no batch formed over HTTP"
        assert sv["servingBatchLaunchesSaved"] >= 1
        # batched lanes must equal solo re-runs (occupancy-1 = solo path)
        for i, s in enumerate(stmts):
            assert c.execute(s).rows == results[i], f"lane {i} diverged"
    finally:
        srv.close()


def test_http_batching_disabled_by_property():
    from presto_tpu.client import StatementClient
    from presto_tpu.worker.server import WorkerServer
    srv = WorkerServer(coordinator=True, max_batch_size=1)
    try:
        assert not srv._batcher.enabled
        c = StatementClient(srv.uri, schema="sf0.01")
        c.execute(f"prepare q6 from {Q6_TEMPLATE}")
        r = c.execute("execute q6 using date '1994-01-01', "
                      "date '1995-01-01', 0.05, 0.07, 24")
        assert r.rows
        assert _snapshot()["servingBatches"] == 0
    finally:
        srv.close()


def _write_etc(tmp_path, extra=""):
    etc = tmp_path / "etc"
    etc.mkdir(exist_ok=True)
    (etc / "config.properties").write_text(
        "coordinator=true\nhttp-server.http.port=0\n" + extra)
    return str(etc)


def test_server_properties_map_serving_keys(tmp_path):
    from presto_tpu.worker.properties import server_kwargs_from_etc
    etc = _write_etc(tmp_path,
                     "serving.batch-window-ms=7.5\n"
                     "serving.max-batch-size=32\n"
                     "serving.compilation-cache-dir=/tmp/x\n"
                     "serving.plan-cache-path=/tmp/p.jsonl\n")
    kw, _props = server_kwargs_from_etc(etc)
    assert kw["batch_window_ms"] == 7.5
    assert kw["max_batch_size"] == 32
    assert kw["compilation_cache_dir"] == "/tmp/x"
    assert kw["plan_cache_path"] == "/tmp/p.jsonl"
    with pytest.raises(ValueError):
        server_kwargs_from_etc(
            _write_etc(tmp_path, "serving.max-batch-size=0\n"))
    with pytest.raises(ValueError):
        server_kwargs_from_etc(
            _write_etc(tmp_path, "serving.batch-window-ms=-1\n"))


# ---------------------------------------------------------------------------
# warm restart through the sidecar + compilation cache
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_warm_restart_zero_recompiles(tmp_path):
    import jax
    from presto_tpu.client import StatementClient
    from presto_tpu.worker.server import WorkerServer
    prev_dir = jax.config.jax_compilation_cache_dir
    kw = {"compilation_cache_dir": str(tmp_path / "xla"),
          "plan_cache_path": str(tmp_path / "plans.jsonl")}
    try:
        srv = WorkerServer(coordinator=True, **kw)
        try:
            c = StatementClient(srv.uri, schema="sf0.01")
            c.execute(f"prepare q6 from {Q6_TEMPLATE}")
            stmt = ("execute q6 using date '1994-01-01', "
                    "date '1995-01-01', 0.05, 0.07, 24")
            want = c.execute(stmt).rows
        finally:
            srv.close()
        assert (tmp_path / "plans.jsonl").exists()

        # 'restart': drop every in-memory serving artifact
        GLOBAL_PLAN_CACHE.invalidate_all()
        PREPARED_REGISTRY.clear()
        FRAGMENT_JIT_CACHE.invalidate_all()

        srv = WorkerServer(coordinator=True, **kw)   # replays the sidecar
        try:
            SERVING_METRICS.reset()
            c2 = StatementClient(srv.uri, schema="sf0.01")
            c2.prepared["q6"] = Q6_TEMPLATE
            assert c2.execute(stmt).rows == want
            sv = _snapshot()
            assert sv["planCacheMisses"] == 0, "reload missed the cache"
            assert sv["preparedReplans"] == 0, "reload replanned"
        finally:
            srv.close()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)


def test_ddl_clears_sidecar(tmp_path):
    from presto_tpu.connectors import catalog
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.worker.server import WorkerServer
    from presto_tpu.client import StatementClient
    catalog.register_connector("memory", MemoryConnector())
    kw = {"plan_cache_path": str(tmp_path / "plans.jsonl")}
    srv = WorkerServer(coordinator=True, **kw)
    try:
        c = StatementClient(srv.uri, schema="sf0.01")
        c.execute("select count(*) from lineitem where l_quantity < 24")
        assert srv._sidecar.info()["entries"] == 1
        cm = StatementClient(srv.uri, catalog="memory", schema="sf0.01")
        cm.execute("create table t_sidecar as select 1 as x")
        assert srv._sidecar.info()["entries"] == 0
        cm.execute("drop table t_sidecar")
    finally:
        srv.close()
        catalog.unregister_connector("memory")


# ---------------------------------------------------------------------------
# client re-PREPARE after coordinator restart (satellite fix)
# ---------------------------------------------------------------------------

def test_client_replays_prepare_on_unknown_statement(monkeypatch):
    from presto_tpu.client import StatementClient
    from presto_tpu.worker.server import WorkerServer
    srv = WorkerServer(coordinator=True)
    try:
        c = StatementClient(srv.uri, schema="sf0.01")
        c.execute(f"prepare q6 from {Q6_TEMPLATE}")
        assert "q6" in c.prepared
        stmt = ("execute q6 using date '1994-01-01', "
                "date '1995-01-01', 0.05, 0.07, 24")
        want = c.execute(stmt).rows

        # simulate a restarted coordinator that lost its registry: the
        # next resolution fails once, then the client's transparent
        # re-PREPARE must recover without surfacing an error
        real = LocalQueryRunner._prepared_text
        state = {"failed": False}

        def flaky(self, name, prepared):
            if not state["failed"]:
                state["failed"] = True
                raise KeyError(
                    f"prepared statement {name!r} does not exist")
            return real(self, name, prepared)
        monkeypatch.setattr(LocalQueryRunner, "_prepared_text", flaky)
        assert c.execute(stmt).rows == want
        assert state["failed"], "fault was never exercised"
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# distributed peak-memory rollup (satellite fix)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_distributed_peak_memory_recorded():
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer
    w = WorkerServer()
    try:
        r = HttpQueryRunner([w.uri], "sf0.01", n_tasks=1)
        res = r.execute("select l_returnflag, count(*) from lineitem "
                        "group by l_returnflag")
        assert res.rows
        assert res.peak_memory_bytes > 0, \
            "distributed run still records 0 peak memory"
        snap = r.last_execution.query_info_snapshot()
        assert snap["peakMemoryBytes"] > 0
        assert all("peakMemoryBytes" in st for st in snap["stages"])
    finally:
        w.close()
