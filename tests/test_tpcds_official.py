"""Official TPC-DS query conformance (VERDICT r3 next #5: >= 40
official-text queries, differential).

Query texts are read AT TEST TIME from the reference tree's product-test
corpus — the Presto-formatted official 99 (quoted identifiers, DECIMAL
typed literals, set operations):
  presto-product-tests/src/main/resources/sql-tests/testcases/tpcds/qNN.sql
Each query runs on the engine and on the numpy oracle
(exec/reference.py) over the identical generated sf0.01 catalog and the
row sets must match (the H2-differential strategy of
QueryAssertions.java:52 / presto-native-tests).

ALL 103 official query files run by default (103/103 pass since round 5
fixed the narrow-int NULLS_LAST sort sentinel — see
tests/test_queries.py::test_sort_narrow_int_nulls_last).  Set
PRESTO_TPU_TPCDS_FAST=1 to run only the fast half (~5 min) during local
iteration.
"""
import os

import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner, _assert_rows_equal

CORPUS = ("/root/reference/presto-product-tests/src/main/resources/"
          "sql-tests/testcases/tpcds")

needs_corpus = pytest.mark.skipif(
    not os.path.isdir(CORPUS), reason="reference corpus not present")

# fastest ~50 of the sweep-validated set (sequential warm timings)
DEFAULT_BANK = [
    "q01", "q03", "q06", "q08", "q12", "q13", "q15", "q17", "q19", "q20",
    "q21", "q24_1", "q24_2", "q25", "q29", "q32", "q34", "q36", "q37",
    "q38", "q39_1", "q40", "q42", "q43", "q44", "q45", "q46", "q48",
    "q50", "q51", "q52", "q53", "q54", "q55", "q56", "q61", "q62", "q63",
    "q68", "q73", "q76", "q79", "q82", "q83", "q86", "q89", "q92", "q93",
]

# the rest of the corpus (slower: big CTE unions, rollups, windowed rank
# queries)
FULL_BANK = [
    "q02", "q04", "q05", "q07", "q09", "q10", "q11", "q14_1", "q14_2",
    "q16", "q18", "q22", "q23_1", "q23_2", "q26", "q27", "q28", "q30",
    "q31", "q33", "q35", "q39_2", "q47", "q49", "q57", "q58", "q59",
    "q60", "q64", "q65", "q66", "q67", "q69", "q70", "q71", "q72", "q74",
    "q75", "q77", "q78", "q80", "q81", "q84", "q85", "q87", "q88", "q91",
    "q94", "q95", "q96", "q97", "q98", "q99", "q41", "q90",
]

_FAST = os.environ.get("PRESTO_TPU_TPCDS_FAST") == "1"
BANK = DEFAULT_BANK + ([] if _FAST else FULL_BANK)


@pytest.fixture(scope="module")
def runner():
    # start the bank from a clean compile history: executables
    # accumulated by EARLIER test modules otherwise count toward the
    # ~55-compile XLA:CPU segfault this file's periodic clear works
    # around (see test_tpcds_official_query)
    import jax
    jax.clear_caches()
    return LocalQueryRunner("sf0.01", catalog="tpcds",
                            config=ExecutionConfig(
                                batch_rows=1 << 14,
                                join_out_capacity=1 << 16))


def _load(name: str) -> str:
    with open(os.path.join(CORPUS, f"{name}.sql")) as f:
        return f.read().strip().rstrip(";")


_ran = [0]


@needs_corpus
@pytest.mark.parametrize("name", BANK)
def test_tpcds_official_query(runner, name):
    # XLA:CPU deterministically segfaults compiling a later query after
    # ~55 of these have compiled in one process (jax compile-history
    # corruption; reproduced bisected — any single query passes alone).
    # Dropping the accumulated executables every few queries keeps the
    # full 103-query bank green in ONE pytest process.
    _ran[0] += 1
    if _ran[0] % 8 == 0:
        import jax
        jax.clear_caches()
    sql = _load(name)
    got = runner.execute(sql)
    exp = runner.execute_reference(sql)
    _assert_rows_equal(got, exp, False)


@needs_corpus
def test_bank_covers_verdict_target():
    # >= 40 official-text queries differentially, per the round-3 ask
    assert len(DEFAULT_BANK) >= 40
    assert len(set(DEFAULT_BANK) & set(FULL_BANK)) == 0
