"""SQL conformance tests: TPU engine vs numpy reference executor on identical
generated TPC-H data (differential testing in the style of the reference's
AbstractTestQueries / QueryAssertions-vs-H2, presto-tests/.../QueryAssertions.java:52).
"""
import pytest

from presto_tpu.exec.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01")


def check(runner, sql, ordered=False):
    return runner.assert_same_as_reference(sql, ordered=ordered)


# ---------------------------------------------------------------------------
# scans / filters / projections
# ---------------------------------------------------------------------------

def test_scan_limit(runner):
    res = runner.execute("select n_name, n_regionkey from nation limit 5")
    assert len(res.rows) == 5


def test_filter_arith(runner):
    check(runner, "select n_nationkey + 1, n_nationkey * 2 from nation "
                  "where n_nationkey >= 10 and n_nationkey < 15")


def test_string_predicates(runner):
    check(runner, "select n_name from nation where n_name like 'A%'")
    check(runner, "select count(*) from customer "
                  "where c_mktsegment in ('BUILDING', 'MACHINERY')")


def test_case_expression(runner):
    check(runner, """
        select n_regionkey,
               case when n_regionkey < 2 then 'west' else 'east' end
        from nation""")


def test_date_functions(runner):
    check(runner, "select o_orderkey, year(o_orderdate), month(o_orderdate) "
                  "from orders where o_orderkey < 100")


def test_distinct(runner):
    check(runner, "select distinct o_orderstatus from orders")


def test_order_by_limit(runner):
    check(runner, "select c_custkey, c_acctbal from customer "
                  "order by c_acctbal desc, c_custkey limit 20", ordered=True)


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

def test_global_agg(runner):
    check(runner, "select count(*), sum(l_quantity), min(l_discount), "
                  "max(l_tax), avg(l_extendedprice) from lineitem")


def test_group_by_small(runner):
    check(runner, "select o_orderstatus, count(*), sum(o_totalprice) "
                  "from orders group by o_orderstatus")


def test_group_by_high_cardinality(runner):
    # forces table growth beyond the initial slot count
    check(runner, "select l_orderkey, count(*), sum(l_quantity) "
                  "from lineitem group by l_orderkey")


def test_having(runner):
    check(runner, "select c_nationkey, count(*) as c from customer "
                  "group by c_nationkey having count(*) > 50")


def test_group_by_expression(runner):
    check(runner, "select year(o_orderdate), count(*) from orders "
                  "group by year(o_orderdate)")


# ---------------------------------------------------------------------------
# joins
# ---------------------------------------------------------------------------

def test_inner_join(runner):
    check(runner, """
        select n_name, r_name from nation
        join region on n_regionkey = r_regionkey""")


def test_left_join(runner):
    check(runner, """
        select c_custkey, o_orderkey from customer
        left join orders on c_custkey = o_custkey
        where c_custkey < 50""")


def test_join_with_agg(runner):
    check(runner, """
        select r_name, count(*) from nation, region
        where n_regionkey = r_regionkey group by r_name""")


def test_three_way_join(runner):
    check(runner, """
        select s_name, n_name, r_name from supplier, nation, region
        where s_nationkey = n_nationkey and n_regionkey = r_regionkey
        and s_suppkey < 20""")


# ---------------------------------------------------------------------------
# TPC-H benchmark queries
# ---------------------------------------------------------------------------

TPCH_Q1 = """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
  sum(l_extendedprice) as sum_base_price,
  sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
  sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
  avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
  avg(l_discount) as avg_disc, count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

TPCH_Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
  o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10
"""

TPCH_Q5 = """
select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation, region
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey and n_regionkey = r_regionkey
  and r_name = 'ASIA' and o_orderdate >= date '1994-01-01'
  and o_orderdate < date '1995-01-01'
group by n_name
order by revenue desc
"""

TPCH_Q6 = """
select sum(l_extendedprice * l_discount) as revenue from lineitem
where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07 and l_quantity < 24
"""


def test_tpch_q1(runner):
    res = check(runner, TPCH_Q1, ordered=True)
    assert len(res.rows) == 4


def test_tpch_q3(runner):
    res = check(runner, TPCH_Q3, ordered=True)
    assert len(res.rows) == 10


def test_tpch_q5(runner):
    res = check(runner, TPCH_Q5, ordered=True)
    assert len(res.rows) > 0


def test_tpch_q6(runner):
    res = check(runner, TPCH_Q6)
    assert res.rows[0][0] is not None


# ---------------------------------------------------------------------------
# regression tests from review findings
# ---------------------------------------------------------------------------

def test_left_join_on_filter_null_extends(runner):
    # ON-clause extra conjuncts filter PAIRS, then unmatched rows null-extend
    res = check(runner, """
        select c_custkey, o_orderkey from customer
        left join orders on c_custkey = o_custkey and o_orderkey < 10
        where c_custkey < 30""")
    custs = {r[0] for r in res.rows}
    assert custs == set(range(1, 30))  # every customer survives


def test_customers_without_orders_exist(runner):
    # generator spec: custkeys % 3 == 0 never get orders; others can
    res = runner.execute(
        "select count(*) from orders where o_custkey % 3 = 0")
    assert res.rows[0][0] == 0
    res2 = runner.execute(
        "select count(*) from orders where o_custkey % 3 = 1")
    assert res2.rows[0][0] > 0


def test_like_literal_metachars():
    from presto_tpu.exec.lowering import like_matcher
    assert like_matcher("50*%")("50*abc")
    assert not like_matcher("50*%")("50abc")
    assert like_matcher("a[b]_")("a[b]c")
    assert not like_matcher("a[b]_")("ab")
    assert like_matcher("%special%requests%")("xx special yy requests zz")


def test_not_in_three_valued(runner):
    """NOT IN under SQL three-valued logic (reference HashSemiJoinOperator):
    a NULL in the subquery makes every non-matching row UNKNOWN (dropped),
    and a NULL probe key is UNKNOWN regardless of the build side.
    Hand-checked counts — the oracle shares the semi-join semantics, so a
    differential test alone cannot anchor this."""
    # build = {NULL,1,2,3,4}: matches are definite FALSE for NOT IN, all
    # other rows UNKNOWN -> zero rows survive
    r = runner.execute(
        "SELECT count(*) FROM nation WHERE n_nationkey NOT IN "
        "(SELECT nullif(r_regionkey, 0) FROM region)")
    assert int(r.rows[0][0]) == 0
    # build = {1,2,3,4}, no NULL: plain anti-join, 25 - 4
    r = runner.execute(
        "SELECT count(*) FROM nation WHERE n_nationkey NOT IN "
        "(SELECT r_regionkey FROM region WHERE r_regionkey > 0)")
    assert int(r.rows[0][0]) == 21
    # NULL probe key (nationkey=3) is UNKNOWN even without build NULLs
    r = runner.execute(
        "SELECT count(*) FROM nation WHERE nullif(n_nationkey, 3) NOT IN "
        "(SELECT r_regionkey FROM region WHERE r_regionkey > 0)")
    assert int(r.rows[0][0]) == 21
    # positive IN: matches still found, misses vs NULL-bearing build drop
    r = runner.execute(
        "SELECT count(*) FROM nation WHERE n_nationkey IN "
        "(SELECT nullif(r_regionkey, 0) FROM region)")
    assert int(r.rows[0][0]) == 4
    runner.assert_same_as_reference(
        "SELECT count(*) FROM nation WHERE n_nationkey NOT IN "
        "(SELECT nullif(r_regionkey, 0) FROM region)")


def test_nullif_null_argument(runner):
    res = runner.execute(
        "select nullif(n_nationkey, null), nullif(0, 0) from nation "
        "where n_nationkey = 0")
    assert res.rows[0][0] == 0      # NULLIF(0, NULL) = 0
    assert res.rows[0][1] is None   # NULLIF(0, 0) = NULL


def test_month_interval_clamps():
    from presto_tpu.sql.planner import Planner
    import presto_tpu.sql.parser as A
    p = Planner()
    e = p.plan_expr(A.parse_sql(
        "select date '1996-01-31' + interval '1' month from nation"
    ).select_items[0].expr, __import__(
        "presto_tpu.sql.planner", fromlist=["Scope"]).Scope([]))
    assert e.value == "1996-02-29"


def test_cte_referenced_twice(runner):
    res = check(runner, """
        with t as (select n_nationkey k, n_regionkey r from nation)
        select a.k, b.k from t a, t b
        where a.r = b.r and a.k < b.k and a.k < 5""")
    assert len(res.rows) > 0


def test_generator_process_deterministic():
    import subprocess, sys
    code = ("from presto_tpu.connectors import tpch;"
            "print(tpch.generate_column('orders','custkey',0.01,0,5).tolist())")
    outs = {subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, cwd="/root/repo").stdout for _ in range(2)}
    assert len(outs) == 1 and "[" in outs.pop()


def test_left_join_where_on_right_side_not_pushed(runner):
    # WHERE on the null-producing side applies AFTER null-extension: no
    # null-extended row may survive o_orderkey < 10.
    res = check(runner, """
        select c_custkey, o_orderkey from customer
        left join orders on c_custkey = o_custkey
        where o_orderkey < 10 and c_custkey < 100""")
    assert all(r[1] is not None and r[1] < 10 for r in res.rows)


def test_cte_where_survives_second_reference(runner):
    res = check(runner, """
        with t as (select n_nationkey k from nation where n_nationkey < 3)
        select a.k, b.k from t a, t b""")
    assert len(res.rows) == 9


def test_left_join_null_string_column(runner):
    # NULL varchar values must round-trip through the dictionary block.
    res = check(runner, """
        select c_custkey, n_name from customer
        left join nation on c_custkey = n_nationkey
        where c_custkey between 23 and 27""")
    by_key = {r[0]: r[1] for r in res.rows}
    assert by_key[23] is not None
    assert by_key[25] is None and by_key[26] is None


def test_group_by_same_column_name_two_tables(runner):
    res = check(runner, """
        select a.n_regionkey, b.n_regionkey, count(*) from nation a, nation b
        where a.n_nationkey + 1 = b.n_nationkey
        group by a.n_regionkey, b.n_regionkey""")
    assert any(r[0] != r[1] for r in res.rows)


def test_group_by_small_pool_lazy_column(runner):
    # orders.clerk is open-domain but drawn from a small pool (sf*1000
    # values): grouping must be by value, not by row identity
    res = check(runner, "select o_clerk, count(*) from orders group by o_clerk")
    assert len(res.rows) <= 10 * 3  # sf0.01 -> 10 clerks


def test_scalar_subquery_multi_row_raises(runner):
    import pytest as _pytest
    with _pytest.raises(Exception, match="more than one row"):
        runner.execute("select count(*) from region where r_regionkey = "
                       "(select n_regionkey from nation where n_regionkey < 2)")


# ---------------------------------------------------------------------------
# window functions (reference: WindowOperator.java:69, AbstractTestWindowQueries)
# ---------------------------------------------------------------------------

def test_window_row_number(runner):
    check(runner, """
        select o_custkey, o_orderkey,
               row_number() over (partition by o_custkey order by o_orderkey)
        from orders where o_custkey < 100""")


def test_window_rank_dense_rank_ties(runner):
    # l_quantity has heavy ties within a partition
    check(runner, """
        select l_suppkey, l_quantity,
               rank() over (partition by l_suppkey order by l_quantity),
               dense_rank() over (partition by l_suppkey order by l_quantity)
        from lineitem where l_suppkey < 20""")


def test_window_running_sum(runner):
    check(runner, """
        select l_orderkey, l_linenumber,
               sum(l_quantity) over (partition by l_orderkey
                                     order by l_linenumber)
        from lineitem where l_orderkey < 200""")


def test_window_running_agg_includes_peers(runner):
    # RANGE default frame: rows tied on the order key share the aggregate
    check(runner, """
        select l_suppkey, l_quantity,
               sum(l_extendedprice) over (partition by l_suppkey
                                          order by l_quantity),
               count(l_quantity) over (partition by l_suppkey
                                       order by l_quantity)
        from lineitem where l_suppkey < 10""")


def test_window_partition_only_aggs(runner):
    # no ORDER BY -> frame is the whole partition
    check(runner, """
        select o_orderkey, o_totalprice,
               avg(o_totalprice) over (partition by o_orderstatus),
               count(*) over (partition by o_orderstatus),
               min(o_totalprice) over (partition by o_orderstatus),
               max(o_totalprice) over (partition by o_orderstatus)
        from orders where o_orderkey < 500""")


def test_window_no_partition(runner):
    check(runner, """
        select n_nationkey,
               sum(n_nationkey) over (order by n_nationkey),
               row_number() over (order by n_nationkey desc)
        from nation""")


def test_window_desc_order(runner):
    check(runner, """
        select c_nationkey, c_custkey,
               rank() over (partition by c_nationkey order by c_acctbal desc)
        from customer where c_custkey < 300""")


def test_window_string_partition(runner):
    # partition key is a lazy open-domain string column (encode path)
    check(runner, """
        select o_clerk, o_orderkey,
               row_number() over (partition by o_clerk order by o_orderkey)
        from orders where o_orderkey < 300""")


def test_window_over_grouped_aggregation(runner):
    # window over the result of a GROUP BY; sum(count(*)) over (...)
    check(runner, """
        select o_orderpriority, count(*) cnt,
               sum(count(*)) over (order by o_orderpriority)
        from orders group by o_orderpriority""")


def test_window_in_order_by_and_topn(runner):
    check(runner, """
        select c_custkey,
               row_number() over (order by c_acctbal desc) rn
        from customer
        order by rn limit 10""", ordered=True)


def test_window_two_specs_one_query(runner):
    check(runner, """
        select l_orderkey, l_linenumber,
               row_number() over (partition by l_orderkey
                                  order by l_linenumber),
               sum(l_quantity) over (partition by l_suppkey
                                     order by l_extendedprice)
        from lineitem where l_orderkey < 100""")


def test_window_distinct_rejected(runner):
    import pytest as _pytest
    with _pytest.raises(Exception, match="DISTINCT"):
        runner.execute("select count(distinct o_orderstatus) over "
                       "(partition by o_custkey) from orders")


def test_window_lazy_rowid_distinct_partition_key(runner):
    # c_phone is ROWID_DISTINCT but not usable as a sort key via row ids:
    # must be dictionary-encoded before the window sort
    check(runner, """
        select c_custkey,
               row_number() over (partition by c_phone order by c_custkey)
        from customer where c_custkey < 50""")


def test_window_min_varchar_reference(runner):
    # min/max over strings: reference must not hit the sum accumulator
    from presto_tpu.exec.reference import execute_reference
    from presto_tpu.exec.runner import LocalQueryRunner as _R
    plan = runner.plan("select min(n_name) over (partition by n_regionkey) "
                       "from nation")
    rows = execute_reference(plan)
    assert all(isinstance(r[0], str) for r in rows)


# ---------------------------------------------------------------------------
# set operations (reference: SetOperationNode, ImplementIntersectAsUnion)
# ---------------------------------------------------------------------------

def test_union_all(runner):
    res = check(runner, """
        select n_regionkey from nation where n_nationkey < 5
        union all select r_regionkey from region""")
    assert len(res.rows) == 10


def test_union_distinct(runner):
    check(runner, "select n_regionkey from nation "
                  "union select r_regionkey from region")


def test_union_strings_merged_dictionaries(runner):
    check(runner, """
        select n_name from nation where n_nationkey < 5
        union all select r_name from region""")


def test_union_type_coercion(runner):
    # bigint union double -> double on both branches
    check(runner, """
        select n_nationkey from nation where n_nationkey < 3
        union all select c_acctbal from customer where c_custkey < 3""")


def test_union_order_limit(runner):
    check(runner, """
        select n_name from nation where n_nationkey < 2
        union select r_name from region order by 1 limit 4""", ordered=True)


def test_union_three_way_aggregated(runner):
    check(runner, """
        select count(*), sum(k) from (
          select n_nationkey k from nation
          union all select r_regionkey from region
          union all select o_orderkey from orders where o_orderkey < 10) t""")


def test_intersect(runner):
    check(runner, """
        select n_regionkey from nation
        intersect select r_regionkey from region where r_regionkey < 3""")


def test_except(runner):
    check(runner, """
        select n_nationkey from nation
        except select o_custkey from orders""")


def test_intersect_binds_tighter_than_union(runner):
    # a union (b intersect c): intersect of region 0..4 with 0..2 is 0..2
    res = check(runner, """
        select n_regionkey from nation where n_nationkey = 0
        union select r_regionkey from region
        intersect select n_regionkey from nation where n_regionkey < 3""")
    assert sorted(r[0] for r in res.rows) == [0, 1, 2]


def test_union_in_subquery(runner):
    check(runner, """
        select count(*) from customer where c_nationkey in
          (select n_nationkey from nation where n_regionkey = 0
           union select n_nationkey from nation where n_regionkey = 1)""")


def test_union_in_cte(runner):
    check(runner, """
        with keys as (select n_regionkey k from nation
                      union select r_regionkey from region)
        select count(*) from keys""")


def test_union_aliased_branch_names(runner):
    # output names come from the first branch
    res = runner.execute("select n_nationkey as id from nation where "
                         "n_nationkey < 2 union all select r_regionkey "
                         "from region where r_regionkey < 1")
    assert res.column_names == ["id"]


def test_intersect_all_rejected(runner):
    import pytest as _pytest
    with _pytest.raises(Exception, match="not supported"):
        runner.execute("select n_regionkey from nation intersect all "
                       "select r_regionkey from region")


def test_window_min_max_varchar_engine(runner):
    # dictionary-encoded strings: min/max must compare lexically, not by code
    check(runner, """
        select n_regionkey, n_name,
               min(n_name) over (partition by n_regionkey),
               max(n_name) over (partition by n_regionkey)
        from nation""")


def test_window_min_lazy_string(runner):
    # customer.name is ROWID_ORDERED: min over row ids, late-materialized
    check(runner, """
        select c_nationkey,
               min(c_name) over (partition by c_nationkey)
        from customer where c_custkey < 100""")
    # clerk is NOT rowid-ordered: must be dictionary-encoded first
    check(runner, """
        select o_orderstatus,
               max(o_clerk) over (partition by o_orderstatus)
        from orders where o_orderkey < 200""")


def test_union_order_by_after_parenthesized_branch(runner):
    res = check(runner, """
        select n_regionkey from nation where n_nationkey < 2
        union (select r_regionkey from region) order by 1 limit 3""",
        ordered=True)
    assert len(res.rows) == 3


def test_scalar_subquery_union_multi_column_rejected(runner):
    import pytest as _pytest
    with _pytest.raises(Exception, match="one column"):
        runner.execute("""
            select count(*) from region where r_regionkey =
              (select n_regionkey, n_nationkey from nation where n_nationkey = 1
               union select n_regionkey, n_nationkey from nation
               where n_nationkey = 1)""")


# ---------------------------------------------------------------------------
# EXPLAIN / EXPLAIN ANALYZE (reference PlanPrinter, ExplainAnalyzeOperator)
# ---------------------------------------------------------------------------

def test_explain_plan_text(runner):
    res = runner.execute("explain select o_orderstatus, count(*) from orders "
                         "where o_orderkey < 100 group by o_orderstatus")
    assert res.column_names == ["Query Plan"]
    text = res.rows[0][0]
    assert "TableScan" in text and "Aggregation" in text
    assert "tpch.orders" in text and "o_orderstatus" in text


def test_explain_analyze_has_stats(runner):
    res = runner.execute("explain analyze select count(*) from nation")
    text = res.rows[0][0]
    assert "rows:" in text and "wall:" in text
    assert "rows: 25" in text  # the scan's output rows


def test_explain_distributed_fragments():
    from presto_tpu.exec.runner import DistributedQueryRunner
    d = DistributedQueryRunner("sf0.01", n_tasks=2)
    text = d.execute("explain select o_orderstatus, count(*) from orders "
                     "group by o_orderstatus").rows[0][0]
    assert "Fragment 0 [SINGLE]" in text
    assert "PARTIAL" in text and "FINAL" in text
    assert "RemoteSource" in text


def test_explain_window_and_join_details(runner):
    text = runner.execute("""
        explain select n_name, r_name,
               row_number() over (partition by r_name order by n_name)
        from nation join region on n_regionkey = r_regionkey""").rows[0][0]
    assert "Window" in text and "partitionBy" in text
    assert "Join" in text and "criteria" in text


# ---------------------------------------------------------------------------
# GROUPING SETS / ROLLUP / CUBE (reference GroupIdOperator + GroupingSetAnalysis)
# ---------------------------------------------------------------------------

def test_rollup(runner):
    res = check(runner, """
        select o_orderstatus, o_orderpriority, count(*), sum(o_totalprice)
        from orders group by rollup(o_orderstatus, o_orderpriority)""")
    # 3 statuses x 5 priorities + 3 subtotals + 1 grand total
    n_detail = len([r for r in res.rows if r[1] is not None])
    assert any(r[0] is None and r[1] is None for r in res.rows)
    assert n_detail >= 3


def test_cube(runner):
    res = check(runner, """
        select n_regionkey, n_nationkey, count(*)
        from nation group by cube(n_regionkey, n_nationkey)""")
    # 25 detail + 5 region subtotals + 25 nation subtotals + 1 total
    assert len(res.rows) == 56


def test_grouping_sets_explicit(runner):
    check(runner, """
        select o_orderstatus, o_orderpriority, count(*)
        from orders
        group by grouping sets ((o_orderstatus), (o_orderpriority), ())""")


def test_rollup_with_join_and_distinct_agg(runner):
    check(runner, """
        select n_regionkey, r_name, count(distinct n_nationkey), count(*)
        from nation join region on n_regionkey = r_regionkey
        group by rollup(n_regionkey, r_name)""")


def test_mixed_plain_and_rollup_cross_product(runner):
    check(runner, """
        select o_orderstatus, year(o_orderdate) y, count(*)
        from orders group by o_orderstatus, rollup(y)""")


def test_rollup_having_and_order(runner):
    check(runner, """
        select o_orderstatus, o_orderpriority, count(*) c
        from orders group by rollup(o_orderstatus, o_orderpriority)
        having count(*) > 100
        order by c desc limit 5""", ordered=True)


def test_sort_narrow_int_nulls_last():
    """Regression (round-5 / q14_1): a narrow-int (int32) nullable sort
    key must honor NULLS LAST — the INT64_MAX null sentinel used to wrap
    to -1 when jnp.where cast it into the int32 key, so rollup-NULL rows
    sorted FIRST under ASC (Presto default is NULLS LAST, ORDER BY docs /
    TopNOperator.java:32)."""
    import jax.numpy as jnp

    from presto_tpu.exec import operators as ops
    from presto_tpu.exec.operators import Batch, Column

    vals = jnp.asarray([5, 3, 0, 8], dtype=jnp.int32)   # 0 is a null row
    nulls = jnp.asarray([False, False, True, False])
    b = Batch({"k": Column(vals, nulls)}, jnp.ones(4, dtype=bool))
    out = ops.topn(b, [("k", "ASC_NULLS_LAST")], 4)
    got = [(int(v), bool(n)) for v, n in
           zip(out.columns["k"].values, out.columns["k"].null_mask())]
    assert got == [(3, False), (5, False), (8, False), (0, True)]
    out = ops.topn(b, [("k", "DESC_NULLS_FIRST")], 4)
    got = [(int(v), bool(n)) for v, n in
           zip(out.columns["k"].values, out.columns["k"].null_mask())]
    assert got == [(0, True), (8, False), (5, False), (3, False)]
    # DESC negates the key: -INT32_MIN wraps at the narrow width, so
    # non-null narrow ints must also promote under DESC
    vals = jnp.asarray([5, -2147483648, 7, 0], dtype=jnp.int32)
    b = Batch({"k": Column(vals)}, jnp.ones(4, dtype=bool))
    out = ops.topn(b, [("k", "DESC_NULLS_LAST")], 4)
    assert [int(v) for v in out.columns["k"].values] \
        == [7, 5, 0, -2147483648]


# ---------------------------------------------------------------------------
# arrays / UNNEST (round-5; reference ArrayFunctions.java,
# ArraySubscriptOperator.java, UnnestOperator.java)
# ---------------------------------------------------------------------------

def test_array_literal_and_subscript(runner):
    check(runner, "select array[1, 2, 3][2], array[10, 20][1]")
    check(runner, "select array[n_nationkey, n_regionkey][1] from nation "
                  "where n_nationkey < 5")


def test_array_functions(runner):
    check(runner, "select cardinality(array[1,2,3]), "
                  "element_at(array[10,20], 2), "
                  "element_at(array[10,20], 7)")
    check(runner, "select contains(array[1,2,3], n_regionkey), "
                  "array_max(array[n_nationkey, n_regionkey]), "
                  "array_min(array[n_nationkey, n_regionkey]), "
                  "array_position(array[2,4,6], n_regionkey * 2) "
                  "from nation")


def test_unnest_basic(runner):
    check(runner, "select x from unnest(array[3,1,2]) as u(x)")
    check(runner, "select x from unnest(sequence(1, 6)) as u(x) "
                  "where x % 2 = 0")


def test_unnest_zip_null_pads(runner):
    # multiple arrays align by position; the shorter null-extends
    check(runner, "select x, y from unnest(array[1,2], "
                  "array[10,20,30]) as u(x, y)")


def test_unnest_lateral_with_ordinality(runner):
    check(runner, """
        select n_name, x, i from nation
        cross join unnest(array[n_nationkey, n_regionkey])
            with ordinality as u(x, i)
        where n_nationkey < 5 order by n_name, i""", ordered=True)


def test_unnest_feeds_aggregation(runner):
    check(runner, """
        select sum(x), count(*) from nation
        cross join unnest(array[n_nationkey, n_regionkey, 7]) as u(x)""")


def test_array_output_column(runner):
    check(runner, "select n_name, array[n_nationkey, n_regionkey] "
                  "from nation where n_nationkey < 4")


# ---------------------------------------------------------------------------
# RIGHT / FULL OUTER joins
# ---------------------------------------------------------------------------

def test_right_join(runner):
    check(runner, """
        select n_name, r_name from region right join nation
        on n_regionkey = r_regionkey""")


def test_right_join_null_extension(runner):
    # customers without orders survive with null order columns
    res = check(runner, """
        select c_custkey, o_orderkey from orders
        right join customer on c_custkey = o_custkey
        where c_custkey < 100""")
    assert any(r[1] is None for r in res.rows)


def test_full_outer_join(runner):
    res = check(runner, """
        select a.n_nationkey, b.k from nation a
        full outer join (select n_nationkey + 20 k from nation) b
        on a.n_nationkey = b.k""")
    # 25 left rows (5 matched) + 20 unmatched right rows
    assert len(res.rows) == 45
    assert any(r[0] is None for r in res.rows)
    assert any(r[1] is None for r in res.rows)


def test_full_join_distributed():
    from presto_tpu.exec.runner import DistributedQueryRunner
    d = DistributedQueryRunner("sf0.01", n_tasks=3, broadcast_threshold=0)
    d.assert_same_as_reference("""
        select a.n_nationkey, b.k from nation a
        full outer join (select n_nationkey + 20 k from nation) b
        on a.n_nationkey = b.k""")


def test_full_join_under_spill_budget():
    from presto_tpu.exec.pipeline import ExecutionConfig
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 14, join_out_capacity=1 << 16,
        memory_budget_bytes=200_000, spill_partitions=4))
    r.assert_same_as_reference("""
        select c_custkey, o_orderkey from customer
        full outer join orders on c_custkey = o_custkey
        where c_custkey < 500 or c_custkey is null""")


def test_join_overflow_split_after_exhaustion():
    """Recursive-halving overflow retry must still run when the overflow
    is detected AFTER the probe iterator is exhausted (regression: the
    windowed-drain refill loop must pull split pieces unconditionally).
    supplier x supplier on nationkey has fanout ~4 at sf0.01; a tiny
    join_out_capacity forces every probe batch to overflow and split."""
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.exec.runner import LocalQueryRunner
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 12, join_out_capacity=128))
    res = r.execute("""
        SELECT count(*) FROM supplier s1 JOIN supplier s2
        ON s1.s_nationkey = s2.s_nationkey""")
    # exact pair count cross-checked with the oracle
    exp = r.execute_reference("""
        SELECT count(*) FROM supplier s1 JOIN supplier s2
        ON s1.s_nationkey = s2.s_nationkey""")
    assert int(res.rows[0][0]) == int(exp.rows[0][0])
