"""Native C++ kernels vs pure-Python oracles (differential testing of the
host data-plane hot loops, mirroring how the reference's native worker is
validated against the Java engine's results)."""
import numpy as np
import pytest

from presto_tpu import native
from presto_tpu.exec.lowering import like_matcher
from presto_tpu.exec.operators import hash_columns


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    if lib is None:
        pytest.skip("no native toolchain available")
    return lib


STRINGS = ["hello world", "", "a", "%literal%", "special requests here",
           "forestgreen", "forest", "fore", "Customer Complaints dept",
           "under_score", "xx.yy", "a" * 200 + "green" + "b" * 200,
           "endswith%", "multi\nline green\ntext"]

PATTERNS = [
    ("%green%", None), ("forest%", None), ("%requests%", None),
    ("%special%requests%", None), ("a", None), ("_", None), ("%", None),
    ("", None), ("%score", None), ("under%", None), ("xx.yy", None),
    ("x%.%y", None), ("%Customer%Complaints%", None),
    ("100!%%", "!"), ("a!_b", "!"), ("__", None), ("%\nline%", None),
]


def test_like_matches_python_matcher(lib):
    for pattern, escape in PATTERNS:
        got = native.like_match(STRINGS, pattern, escape)
        assert got is not None
        ref = like_matcher(pattern, escape)
        exp = np.array([ref(s) for s in STRINGS])
        assert (got == exp).all(), f"pattern {pattern!r}: {got} != {exp}"


def test_like_non_ascii_falls_back(lib):
    assert native.like_match(["héllo"], "h%") is None


def test_substr_dict_encode(lib):
    strings = ["13-123-4567", "31-999-0000", "17-000-1111", "13-zzz"]
    cdict = tuple(sorted({s[:2] for s in strings}))
    codes = native.substr_dict_encode(strings, 1, 2, cdict)
    assert [cdict[c] for c in codes] == ["13", "31", "17", "13"]


def test_substr_whole_string(lib):
    strings = ["beta", "alpha", "gamma", "alpha"]
    cdict = tuple(sorted(set(strings)))
    codes = native.substr_dict_encode(strings, 1, None, cdict)
    assert [cdict[c] for c in codes] == strings


def test_substr_missing_raises(lib):
    with pytest.raises(KeyError):
        native.substr_dict_encode(["zz"], 1, None, ("aa", "bb"))


def test_substr_negative_start(lib):
    strings = ["hello", "ab"]
    cdict = tuple(sorted({s[-2:] for s in strings}))
    codes = native.substr_dict_encode(strings, -2, None, cdict)
    assert [cdict[c] for c in codes] == ["lo", "ab"]


def test_hash_combine_matches_device_hash(lib):
    """ptn_hash_combine must produce the same hashes as the jitted
    splitmix64/hash_columns path (partitioning consistency across the
    native and device paths)."""
    import ctypes

    from presto_tpu.exec.batch import Column
    import jax.numpy as jnp

    vals = np.array([0, 1, -1, 2**62, -2**62, 12345], dtype=np.int64)
    expected = np.asarray(hash_columns([Column(jnp.asarray(vals))], salt=0))

    acc = np.full(len(vals), 1, dtype=np.uint64)  # salt+1, as hash_columns
    lib.ptn_hash_combine(
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), None,
        len(vals), acc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    assert (acc == expected.astype(np.uint64)).all()


def test_substr_python_slice_parity(lib):
    """Native substr must mirror _py_substr exactly, including the Python
    slice semantics of a still-negative adjusted start (s[-3:-1] on 'ab')."""
    from presto_tpu.exec.pipeline import _py_substr

    strings = ["ab", "hello", "", "x", "abcdef"]
    for start, length in [(-5, 2), (-2, 1), (-1, None), (1, 3), (3, None),
                          (-10, 4), (2, 0), (-3, 2)]:
        expected = [_py_substr(s, start, length) for s in strings]
        cdict = tuple(sorted(set(expected)))
        codes = native.substr_dict_encode(strings, start,
                                          length, cdict)
        assert [cdict[c] for c in codes] == expected, (start, length)
