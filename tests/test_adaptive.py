"""Adaptive query execution (ISSUE 19; reference DynamicFilterService +
AdaptivePlanOptimizer analogs): runtime dynamic filters summarized from
completed build stages and pushed into probe-side zone-map pruning,
cardinality-driven exchange decisions at stage boundaries, and
history-based sizing from prior runs of the same plan template.

Correctness bar throughout: rows bit-identical to the numpy reference
oracle with adaptivity on, off, and under the wait-timeout fallback —
every adaptive move is advisory, never semantic.
"""
import dataclasses

import pytest

from presto_tpu.exec.adaptive import (ADAPTIVE_METRICS,
                                      DynamicFilterCollector,
                                      DynamicFilterSummary, decide_exchange,
                                      decide_side_swap,
                                      reset_adaptive_metrics,
                                      summaries_to_runtime,
                                      summarize_key_column)
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import (DistributedQueryRunner, LocalQueryRunner,
                                    _assert_rows_equal)
from presto_tpu.spi import plan as P
from presto_tpu.storage.pushdown import (entry_unsatisfiable, is_dyn_marker,
                                         prune_chunks, resolve_entry_value)


@pytest.fixture(autouse=True)
def _fresh_metrics():
    reset_adaptive_metrics()
    yield


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------

def test_summarize_key_column_bounds_and_set():
    import numpy as np
    s = summarize_key_column("df_0", np.array([7, 3, 3, 9]), None, 16)
    assert (s.min, s.max, s.row_count) == (3, 9, 4)
    assert s.values == (3, 7, 9)


def test_summarize_key_column_mask_excludes_rows():
    import numpy as np
    s = summarize_key_column("df_0", np.array([1, 100, 2]),
                             np.array([True, False, True]), 16)
    assert (s.min, s.max, s.row_count) == (1, 2, 2)


def test_summarize_key_column_empty_is_prune_everything():
    import numpy as np
    s = summarize_key_column("df_0", np.array([], dtype=np.int64), None, 16)
    assert s.empty and s.row_count == 0 and not s.bounded


def test_summarize_key_column_float_gets_no_bounds():
    import numpy as np
    s = summarize_key_column("df_0", np.array([1.5, 2.5]), None, 16)
    assert s.row_count == 2 and not s.bounded


def test_summarize_respects_distinct_cap():
    import numpy as np
    s = summarize_key_column("df_0", np.arange(100), None, 8)
    assert s.values is None          # over the cap: bounds only
    assert (s.min, s.max) == (0, 99)


def test_summary_merge_widens_and_unions():
    a = DynamicFilterSummary("df_0", 1, 5, (1, 3, 5), 3)
    b = DynamicFilterSummary("df_0", 4, 9, (4, 9), 2)
    m = a.merge(b, max_distinct=16)
    assert (m.min, m.max, m.row_count) == (1, 9, 5)
    assert m.values == (1, 3, 4, 5, 9)
    # union over the cap drops the exact set but keeps bounds
    m2 = a.merge(b, max_distinct=4)
    assert m2.values is None and (m2.min, m2.max) == (1, 9)


def test_summary_merge_with_empty_side_keeps_other_bounds():
    a = DynamicFilterSummary("df_0", 2, 8, (2, 8), 2)
    e = DynamicFilterSummary("df_0", row_count=0)
    m = a.merge(e, max_distinct=16)
    assert (m.min, m.max, m.row_count) == (2, 8, 2)


def test_summary_wire_round_trip():
    s = DynamicFilterSummary("df_1", 3, 7, (3, 7), 2)
    assert DynamicFilterSummary.from_dict(s.to_dict()) == s
    e = DynamicFilterSummary("df_2", row_count=0)
    assert DynamicFilterSummary.from_dict(e.to_dict()).empty


def test_collector_merges_partials_per_filter_id():
    c = DynamicFilterCollector(max_distinct=16)
    c.publish(DynamicFilterSummary("df_0", 1, 4, (1, 4), 2))
    c.publish(DynamicFilterSummary("df_0", 6, 9, (6, 9), 2))
    got = c.get("df_0")
    assert (got.min, got.max, got.row_count) == (1, 9, 4)
    wire = summaries_to_runtime({"df_0": got})
    assert wire["df_0"]["min"] == 1 and wire["df_0"]["rowCount"] == 4


# ---------------------------------------------------------------------------
# exchange decisions
# ---------------------------------------------------------------------------

def test_decide_exchange_flip_needs_big_estimate_gap():
    assert decide_exchange(planned_rows=10_000, observed_rows=100,
                           broadcast_threshold=5_000)
    # observed close to plan: the planner was right, keep partitioned
    assert not decide_exchange(planned_rows=10_000, observed_rows=4_000,
                               broadcast_threshold=5_000)
    # observed over the threshold never broadcasts, whatever the plan said
    assert not decide_exchange(planned_rows=10_000_000, observed_rows=6_000,
                               broadcast_threshold=5_000)
    # absent estimate counts as a wrong estimate
    assert decide_exchange(planned_rows=None, observed_rows=10,
                           broadcast_threshold=5_000)


def test_decide_side_swap():
    assert decide_side_swap(left_rows=100, right_rows=500)
    assert not decide_side_swap(left_rows=500, right_rows=100)
    assert not decide_side_swap(left_rows=None, right_rows=100)
    assert not decide_side_swap(left_rows=0, right_rows=0)


# ---------------------------------------------------------------------------
# dyn marker resolution + zone pruning
# ---------------------------------------------------------------------------

WIRE = {"df_0": {"filterId": "df_0", "rowCount": 3,
                 "min": 10, "max": 20, "values": [10, 15, 20]}}


def test_resolve_dyn_markers():
    assert resolve_entry_value(["dyn", "df_0", "min"], None, WIRE) == 10
    assert resolve_entry_value(["dyn", "df_0", "max"], None, WIRE) == 20
    assert resolve_entry_value(["dyn", "df_0", "set"], None, WIRE) \
        == (10, 15, 20)
    # unknown filter id / no summaries: unresolved, prune nothing
    assert resolve_entry_value(["dyn", "df_9", "min"], None, WIRE) is None
    assert resolve_entry_value(["dyn", "df_0", "min"], None, None) is None
    # zero-row summary resolves nothing here (empty-build pruning is the
    # scan's own convention, not a comparison value)
    empty = {"df_0": {"filterId": "df_0", "rowCount": 0}}
    assert resolve_entry_value(["dyn", "df_0", "min"], None, empty) is None
    assert is_dyn_marker(["dyn", "df_0", "min"])
    assert not is_dyn_marker(["param", 0])


def test_in_set_unsatisfiable_is_membership_over_zone_range():
    val = (10, 15, 20)
    assert entry_unsatisfiable("eq", val, 21, 30)       # all outside
    assert not entry_unsatisfiable("eq", val, 14, 16)   # 15 inside
    # non-eq ops never use set semantics
    assert not entry_unsatisfiable("lt", val, 21, 30)


class _Zones:
    """chunk_bounds stub: key = row index (identity layout)."""

    def chunk_bounds(self, pos, count):
        return (pos, pos + count - 1)


DYN_PD = [{"column": "k", "op": "gte", "value": ["dyn", "df_0", "min"]},
          {"column": "k", "op": "lte", "value": ["dyn", "df_0", "max"]},
          {"column": "k", "op": "eq", "value": ["dyn", "df_0", "set"]}]


def test_prune_chunks_dyn_attribution():
    chunks = [(0, 100), (100, 100), (200, 100)]   # df_0 covers [10, 20]
    detail = {}
    kept, skipped = prune_chunks(chunks, {"k": _Zones()}, DYN_PD,
                                 None, WIRE, detail=detail)
    assert kept == [(0, 100)] and skipped == 2
    assert detail["dyn_engaged"]
    assert detail["dyn_chunks_pruned"] == 2
    assert detail["dyn_rows_pruned"] == 200
    # callers passing detail own the metering: the registry is untouched
    assert ADAPTIVE_METRICS.snapshot()["filter_chunks_skipped"] == 0


def test_prune_chunks_without_summaries_keeps_everything():
    chunks = [(0, 100), (100, 100)]
    kept, skipped = prune_chunks(chunks, {"k": _Zones()}, DYN_PD, None, None)
    assert kept == chunks and skipped == 0


def test_prune_chunks_keep_one_floor_vs_streaming():
    chunks = [(100, 100), (200, 100)]             # nothing overlaps [10,20]
    kept, _ = prune_chunks(chunks, {"k": _Zones()}, DYN_PD, None, WIRE)
    assert kept == [(100, 100)]                   # fused floor: one survivor
    reset_adaptive_metrics()
    kept, skipped = prune_chunks(chunks, {"k": _Zones()}, DYN_PD, None, WIRE,
                                 keep_one=False)
    assert kept == [] and skipped == 2            # streaming: empty is fine
    assert ADAPTIVE_METRICS.snapshot()["filter_chunks_skipped"] == 2


# ---------------------------------------------------------------------------
# planning: which join types get dynamic filters (and in which direction)
# ---------------------------------------------------------------------------

def _plan(sql):
    return LocalQueryRunner("sf0.01").plan(sql)


def _join_filters(root, cls=P.JoinNode):
    return [n for n in P.walk_plan(root) if isinstance(n, cls)]


def test_inner_join_probe_receives_build_domain():
    root = _plan("SELECT count(*) FROM lineitem, orders "
                 "WHERE l_orderkey = o_orderkey")
    joins = [j for j in _join_filters(root) if j.dynamic_filters]
    assert joins, "INNER join lost its dynamic filter annotation"
    j = joins[0]
    left_names = {v.name for v in j.left.output_variables}
    assert set(j.dynamic_filters) <= left_names, \
        "INNER receiving side must be the probe (left)"


def test_left_join_build_receives_probe_domain():
    root = _plan("SELECT count(*) FROM orders LEFT JOIN lineitem "
                 "ON o_orderkey = l_orderkey")
    joins = [j for j in _join_filters(root) if j.join_type == P.LEFT]
    assert joins
    j = joins[0]
    right_names = {v.name for v in j.right.output_variables}
    assert j.dynamic_filters, "LEFT join build side is prunable"
    assert set(j.dynamic_filters) <= right_names, \
        "LEFT may only ever filter the non-preserved (build) side"


def test_right_join_normalized_and_annotated():
    root = _plan("SELECT count(*) FROM lineitem RIGHT JOIN orders "
                 "ON l_orderkey = o_orderkey")
    joins = _join_filters(root)
    assert joins and all(j.join_type != P.RIGHT for j in joins), \
        "RIGHT joins are normalized to LEFT-with-swapped-sides"
    annotated = [j for j in joins if j.dynamic_filters]
    assert annotated, "normalized RIGHT join keeps a dynamic filter"
    j = annotated[0]
    right_names = {v.name for v in j.right.output_variables}
    assert set(j.dynamic_filters) <= right_names


def test_full_join_gets_no_dynamic_filter():
    root = _plan("SELECT count(*) FROM lineitem FULL JOIN orders "
                 "ON l_orderkey = o_orderkey")
    fulls = [j for j in _join_filters(root) if j.join_type == P.FULL]
    assert fulls
    assert all(not j.dynamic_filters for j in fulls), \
        "both FULL sides are preserved: no filter is safe"


def test_semi_join_positive_membership_annotated():
    root = _plan("SELECT count(*) FROM lineitem WHERE l_orderkey IN "
                 "(SELECT o_orderkey FROM orders WHERE o_orderkey < 50)")
    semis = _join_filters(root, P.SemiJoinNode)
    assert semis
    assert any(s.dynamic_filters for s in semis), \
        "bare positive IN membership may prune the source"


def test_semi_join_negated_membership_not_annotated():
    root = _plan("SELECT count(*) FROM lineitem WHERE l_orderkey NOT IN "
                 "(SELECT o_orderkey FROM orders WHERE o_orderkey < 50)")
    semis = _join_filters(root, P.SemiJoinNode)
    assert semis
    assert all(not s.dynamic_filters for s in semis), \
        "NOT IN survivors are exactly the out-of-domain rows"


def test_runtime_filter_pushdown_reaches_probe_scan():
    root = _plan("SELECT count(*) FROM lineitem, orders "
                 "WHERE l_orderkey = o_orderkey AND o_orderkey < 50")
    scans = {n.table.table_name: n for n in P.walk_plan(root)
             if isinstance(n, P.TableScanNode)}
    li = scans["lineitem"]
    assert li.runtime_filters, "probe scan not annotated"
    fid = li.runtime_filters[0]["id"]
    bounds = {tuple(e["value"]) for e in li.pushdown
              if is_dyn_marker(e["value"])}
    assert bounds == {("dyn", fid, "min"), ("dyn", fid, "max"),
                      ("dyn", fid, "set")}


# ---------------------------------------------------------------------------
# checker: dyn markers must re-derive from the scan's own annotation
# ---------------------------------------------------------------------------

def _dyn_scan_plan(pushdown, runtime_filters):
    from presto_tpu.common.types import BigintType
    from presto_tpu.spi.expr import VariableReferenceExpression as V
    v = V("l_orderkey_0", BigintType())
    scan = P.TableScanNode(
        "s0", P.TableHandle("tpch", "tpch", "lineitem",
                            (("scaleFactor", 0.01),)),
        [v], {v: P.ColumnHandle("orderkey", BigintType())},
        list(pushdown), list(runtime_filters))
    return P.OutputNode("o0", scan, ["l_orderkey"], [v])


def test_checker_accepts_rederivable_dyn_markers():
    from presto_tpu.analysis import check_plan
    out = _dyn_scan_plan(
        [{"column": "orderkey", "op": "gte", "value": ["dyn", "df_0", "min"]},
         {"column": "orderkey", "op": "lte", "value": ["dyn", "df_0", "max"]},
         {"column": "orderkey", "op": "eq", "value": ["dyn", "df_0", "set"]}],
        [{"id": "df_0", "column": "orderkey"}])
    assert check_plan(out) == []


def test_checker_rejects_unannotated_dyn_marker():
    from presto_tpu.analysis import check_plan
    out = _dyn_scan_plan(
        [{"column": "orderkey", "op": "gte",
          "value": ["dyn", "df_9", "min"]}],
        [{"id": "df_0", "column": "orderkey"}])
    diags = check_plan(out)
    assert any("does not re-derive" in d.message for d in diags)


def test_checker_rejects_wrong_op_for_bound():
    from presto_tpu.analysis import check_plan
    out = _dyn_scan_plan(
        [{"column": "orderkey", "op": "lt",
          "value": ["dyn", "df_0", "min"]}],   # min must claim gte
        [{"id": "df_0", "column": "orderkey"}])
    diags = check_plan(out)
    assert any("does not re-derive" in d.message for d in diags)


def test_optimizer_dyn_annotations_validate_clean():
    r = LocalQueryRunner("sf0.01")
    res = r.execute("EXPLAIN (TYPE VALIDATE) SELECT count(*) "
                    "FROM lineitem, orders WHERE l_orderkey = o_orderkey "
                    "AND o_orderkey < 40")
    assert "plan validation PASSED" in res.rows[0][0]


# ---------------------------------------------------------------------------
# end to end: the adaptive path must never change answers
# ---------------------------------------------------------------------------

# the `+ 0` hides the range from the stats calculator
# (UNKNOWN_FILTER_COEFFICIENT), so the PLANNED build (~0.9 x orders) sits
# far above the OBSERVED 29 rows — the flip-to-broadcast setup
AQE_SQL = """
SELECT sum(l_extendedprice * (1 - l_discount)) AS revenue, count(*) AS cnt
FROM lineitem, orders
WHERE l_orderkey = o_orderkey AND o_orderkey + 0 < 30
"""

_AQE_CFG = dict(batch_rows=1 << 14, storage_zone_rows=4096)


def _dist_runner(**over):
    cfg = ExecutionConfig(**{**_AQE_CFG, **over})
    return DistributedQueryRunner("sf0.01", config=cfg, n_tasks=2,
                                  broadcast_threshold=5000)


def test_adaptive_on_off_fallback_bit_identical():
    oracle = LocalQueryRunner("sf0.01").execute_reference(AQE_SQL)
    on = _dist_runner().execute(AQE_SQL)
    _assert_rows_equal(on, oracle, ordered=False)
    m = ADAPTIVE_METRICS.snapshot()
    assert m["filters_collected"] > 0
    assert m["filters_applied"] > 0
    assert m["filter_rows_pruned"] > 0 or m["filter_chunks_skipped"] > 0

    reset_adaptive_metrics()
    off = _dist_runner(dynamic_filtering=False,
                       adaptive_exchange=False).execute(AQE_SQL)
    _assert_rows_equal(off, oracle, ordered=False)
    assert not any(ADAPTIVE_METRICS.snapshot().values()), \
        "adaptive=off must leave no adaptive footprint"

    # wait-timeout fallback: a 0s wait means scans may run unfiltered —
    # results must be identical anyway (pruning is advisory)
    fb = _dist_runner(dynamic_filtering_wait_timeout_s=0.0).execute(AQE_SQL)
    _assert_rows_equal(fb, oracle, ordered=False)


def test_underestimated_build_flips_partitioned_to_broadcast():
    """Build observed (29) >= 10x below planned (~13.5k): the consumer
    stage must launch against a broadcast edge, visible in the metrics
    registry AND the EXPLAIN ANALYZE footer."""
    r = _dist_runner()
    sub, _names, _types = r.plan_subplan(AQE_SQL)
    joins = [n for s in _walk_stages(sub) for n in P.walk_plan(s.root)
             if isinstance(n, P.JoinNode)]
    assert any(j.distribution == P.PARTITIONED for j in joins), \
        "test premise broken: the join must PLAN partitioned"
    res = r.execute(AQE_SQL)
    oracle = LocalQueryRunner("sf0.01").execute_reference(AQE_SQL)
    _assert_rows_equal(res, oracle, ordered=False)
    assert ADAPTIVE_METRICS.snapshot()["exchange_broadcast_flips"] >= 1

    analyzed = r.execute("EXPLAIN ANALYZE " + AQE_SQL).rows[0][0]
    assert "flipped to broadcast" in analyzed
    assert "Dynamic filters:" in analyzed


def _walk_stages(subplan):
    yield subplan.fragment
    for c in subplan.children:
        yield from _walk_stages(c)


def test_explain_analyze_footer_reports_prune_fraction():
    r = _dist_runner()
    text = r.execute("EXPLAIN ANALYZE " + AQE_SQL).rows[0][0]
    line = next(ln for ln in text.splitlines()
                if ln.startswith("Dynamic filters:"))
    # "Dynamic filters: N collected, M applied, X% rows pruned"
    assert "collected" in line and "applied" in line \
        and "rows pruned" in line
    pct = float(line.split("applied,")[1].split("%")[0])
    assert pct > 0.0, line


# ---------------------------------------------------------------------------
# history-based sizing
# ---------------------------------------------------------------------------

AGG_SQL = "SELECT o_orderstatus, count(*) FROM orders GROUP BY o_orderstatus"


def test_local_repeat_run_sizes_from_history():
    from presto_tpu.telemetry.history import QueryHistoryStore
    hist = QueryHistoryStore()
    cfg = ExecutionConfig(adaptive_history_sizing=True)
    r = LocalQueryRunner("sf0.01", config=cfg, history=hist)
    first = r.execute(AGG_SQL)
    rec = hist.list()[0]
    assert rec["planTemplate"] and rec["aggGroups"] == len(first.rows)

    reset_adaptive_metrics()
    second = r.execute(AGG_SQL)
    assert second.rows == first.rows
    assert ADAPTIVE_METRICS.snapshot()["history_sized_queries"] >= 1
    # the sized config is what the compiler actually sees: 3 observed
    # groups -> 256-slot floor instead of the 4096 default estimate path
    sized = r._history_sized_config()
    assert sized.history_agg_groups == len(first.rows)
    assert sized.history_agg_groups != cfg.history_agg_groups


def test_history_sizing_off_by_default():
    from presto_tpu.telemetry.history import QueryHistoryStore
    hist = QueryHistoryStore()
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(), history=hist)
    r.execute(AGG_SQL)
    r.execute(AGG_SQL)
    # recording still happens (the store was attached), but nothing is
    # CONSUMED unless adaptive.history-sizing is on
    assert hist.list()
    assert ADAPTIVE_METRICS.snapshot()["history_sized_queries"] == 0


def test_distributed_repeat_run_seeds_task_count():
    from presto_tpu.sql import parser as A
    from presto_tpu.telemetry.history import QueryHistoryStore
    hist = QueryHistoryStore()
    cfg = ExecutionConfig(adaptive_history_sizing=True)
    r = DistributedQueryRunner("sf0.01", config=cfg, n_tasks=4,
                               history=hist)
    first = r.execute(AGG_SQL)
    assert hist.list(), "distributed run must record its template"

    ast = A.parse_sql(AGG_SQL)
    restore = r._apply_history_sizing(ast)
    try:
        assert r.config.history_agg_groups == len(first.rows)
        # 3 observed result rows: one hash task is plenty (vs n_tasks=4)
        assert r._history_tasks == 1
        assert r._scheduler_config().hash_tasks == 1
    finally:
        restore()
    assert r.config.history_agg_groups is None
    second = r.execute(AGG_SQL)
    assert sorted(second.rows) == sorted(first.rows)


def test_plan_cache_rekeys_on_history_hint():
    """history_agg_groups is part of the config fingerprint: a repeat run
    with a fresh hint must not reuse the unhinted compiled plan."""
    from presto_tpu.sql.canonical import cache_key_from_parts
    cfg = ExecutionConfig()
    hinted = dataclasses.replace(cfg, history_agg_groups=512)
    assert cache_key_from_parts("t", cfg, "tpch", "sf0.01") \
        != cache_key_from_parts("t", hinted, "tpch", "sf0.01")
