"""HTTP worker protocol tests: real loopback HTTP between a coordinator-side
runner and N worker servers hosted in one process — the analog of the
reference's DistributedQueryRunner booting N TestingPrestoServers in one JVM
with embedded discovery (presto-tests/.../DistributedQueryRunner.java:108,
TestingPrestoServer.java:143)."""
import json
import time
import urllib.request

import pytest

from presto_tpu.exec.runner import LocalQueryRunner
from presto_tpu.worker import HttpQueryRunner, WorkerServer

from test_queries import TPCH_Q1, TPCH_Q3, TPCH_Q6


@pytest.fixture(scope="module")
def cluster():
    coordinator = WorkerServer(coordinator=True, environment="test")
    workers = [WorkerServer(discovery_uri=coordinator.uri,
                            announce_interval_s=0.1,
                            environment="test") for _ in range(2)]
    deadline = time.time() + 10
    while len(coordinator.worker_uris()) < 2 and time.time() < deadline:
        time.sleep(0.05)
    yield coordinator, workers
    for w in workers:
        w.close()
    coordinator.close()


@pytest.fixture(scope="module")
def runner(cluster):
    coordinator, _ = cluster
    uris = coordinator.worker_uris()
    assert len(uris) == 2, "workers failed to announce"
    return HttpQueryRunner(uris, "sf0.01", n_tasks=2)


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


# ---------------------------------------------------------------------------
# discovery / announcement protocol
# ---------------------------------------------------------------------------

def test_announcement_discovery(cluster):
    coordinator, workers = cluster
    services = _get_json(f"{coordinator.uri}/v1/service")["services"]
    uris = {s["properties"]["http"] for s in services}
    assert {w.uri for w in workers} <= uris
    assert all(s["properties"]["pool_type"] == "TPU" for s in services)


def test_node_info(cluster):
    _, workers = cluster
    info = _get_json(f"{workers[0].uri}/v1/info")
    assert info["coordinator"] is False
    state = _get_json(f"{workers[0].uri}/v1/info/state")
    assert state == "ACTIVE"


def test_unknown_task_404(cluster):
    _, workers = cluster
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(f"{workers[0].uri}/v1/task/nope/status",
                               timeout=10)
    assert e.value.code == 404


# ---------------------------------------------------------------------------
# end-to-end queries over HTTP exchange
# ---------------------------------------------------------------------------

def _check(runner, sql, ordered=False):
    got = runner.execute(sql)
    exp = LocalQueryRunner("sf0.01").execute_reference(sql)
    from presto_tpu.exec.runner import _assert_rows_equal
    _assert_rows_equal(got, exp, ordered)
    return got


def test_http_scan_filter(runner):
    res = _check(runner, "select n_name, n_regionkey from nation "
                         "where n_regionkey = 2", ordered=False)
    assert len(res.rows) == 5


def test_http_q6(runner):
    _check(runner, TPCH_Q6)


def test_http_q1(runner):
    _check(runner, TPCH_Q1, ordered=True)


def test_http_q3_partitioned_exchange(runner):
    _check(runner, TPCH_Q3, ordered=True)


def test_http_join_group(runner):
    _check(runner, """
        select o_orderstatus, count(*), sum(o_totalprice)
        from orders, customer where c_custkey = o_custkey
          and c_mktsegment = 'BUILDING'
        group by o_orderstatus order by o_orderstatus""", ordered=True)


def test_http_failure_propagates(runner):
    with pytest.raises(Exception):
        runner.execute("select unknown_column from nation")


def test_task_status_long_poll(cluster, runner):
    """The status endpoint blocks while the state is unchanged and returns
    promptly once the task reaches a terminal state."""
    _, workers = cluster
    runner.execute("select count(*) from region")
    tm = workers[0].task_manager
    if not tm.tasks:
        tm = workers[1].task_manager
    task_id = next(iter(tm.tasks))
    t0 = time.time()
    status = _get_json(
        f"{tm.tasks[task_id].self_uri}/status?maxWaitMs=2000")
    assert time.time() - t0 < 1.5  # terminal state: no full wait
    assert status["state"] in ("FINISHED", "CANCELED")


def test_external_worker_process(cluster):
    """Spawn a real worker subprocess via `python -m presto_tpu.worker` (the
    reference's external-worker-launcher pattern,
    PrestoNativeQueryRunnerUtils.java:253-267) and run a query on it."""
    import os
    import re
    import subprocess
    import sys

    coordinator, _ = cluster
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "-m", "presto_tpu.worker", "--environment", "test",
         "--discovery-uri", coordinator.uri],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    try:
        line = proc.stdout.readline()
        m = re.search(r"listening on (http://[\d.:]+)", line)
        assert m, f"no startup line: {line!r}"
        uri = m.group(1)
        r = HttpQueryRunner([uri], "sf0.01", n_tasks=1)
        res = r.execute("select r_name from region order by r_name")
        assert [row[0] for row in res.rows] == [
            "AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
        # it must also have announced itself to the coordinator's discovery
        deadline = time.time() + 10
        while uri not in coordinator.worker_uris() and time.time() < deadline:
            time.sleep(0.05)
        assert uri in coordinator.worker_uris()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_status_and_metrics_endpoints():
    import json as _json
    import urllib.request
    from presto_tpu.worker.server import WorkerServer
    w = WorkerServer()
    try:
        with urllib.request.urlopen(w.uri + "/v1/status", timeout=5) as r:
            st = _json.loads(r.read())
        assert st["nodeId"] == w.node_id and st["state"] == "ACTIVE"
        with urllib.request.urlopen(w.uri + "/v1/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "presto_tpu_uptime_seconds" in text
        assert "presto_tpu_tasks_created_total 0" in text
    finally:
        w.close()


def test_graceful_shutdown_refuses_new_tasks():
    import json as _json
    import urllib.request
    import urllib.error
    from presto_tpu.worker.server import WorkerServer
    w = WorkerServer()
    try:
        req = urllib.request.Request(
            w.uri + "/v1/info/state",
            data=_json.dumps("SHUTTING_DOWN").encode(), method="PUT",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as r:
            assert _json.loads(r.read()) == "SHUTTING_DOWN"
        with urllib.request.urlopen(w.uri + "/v1/info/state", timeout=5) as r:
            assert _json.loads(r.read()) == "SHUTTING_DOWN"
        # new task creation now refused with 503
        try:
            urllib.request.urlopen(urllib.request.Request(
                w.uri + "/v1/task/q.0.0", data=b"{}", method="POST"),
                timeout=5)
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
    finally:
        w.close()


def test_failure_detector_drops_dead_and_draining_workers():
    import json as _json
    import time
    import urllib.request
    from presto_tpu.worker.coordinator import (HeartbeatFailureDetector,
                                               HttpQueryRunner)
    from presto_tpu.worker.server import WorkerServer
    w1, w2, w3 = WorkerServer(), WorkerServer(), WorkerServer()
    det = HeartbeatFailureDetector(
        [w1.uri, w2.uri, w3.uri], interval_s=0.1, threshold=2)
    try:
        deadline = time.time() + 5
        while time.time() < deadline and len(det.alive()) != 3:
            time.sleep(0.1)
        assert sorted(det.alive()) == sorted([w1.uri, w2.uri, w3.uri])
        # kill one, drain another
        w3.close()
        req = urllib.request.Request(
            w2.uri + "/v1/info/state",
            data=_json.dumps("SHUTTING_DOWN").encode(), method="PUT",
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=5).close()
        deadline = time.time() + 10
        while time.time() < deadline and det.alive() != [w1.uri]:
            time.sleep(0.1)
        assert det.alive() == [w1.uri]
        assert det.failed() == [w3.uri]
        # queries keep running on the surviving worker
        r = HttpQueryRunner([w1.uri, w2.uri, w3.uri], "sf0.01",
                            failure_detector=det, n_tasks=2)
        res = r.execute("select count(*) from nation")
        assert res.rows == [[25]]
    finally:
        det.close()
        w1.close()
        w2.close()


def test_draining_worker_task_rerouted():
    # a 503 from a draining worker must send the task to a live one
    import json as _json
    import urllib.request
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer
    w1, w2 = WorkerServer(), WorkerServer()
    try:
        req = urllib.request.Request(
            w2.uri + "/v1/info/state",
            data=_json.dumps("SHUTTING_DOWN").encode(), method="PUT",
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=5).close()
        # no failure detector: scheduler hits the draining worker and must
        # fall back on the 503
        r = HttpQueryRunner([w2.uri, w1.uri], "sf0.01", n_tasks=2)
        assert r.execute("select count(*) from nation").rows == [[25]]
    finally:
        w1.close()
        w2.close()


def test_session_properties_applied():
    # session overrides reach the task's ExecutionConfig (the analog of the
    # reference's session property -> QueryConfig mapping)
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.worker.protocol import (apply_session_properties,
                                            parse_data_size)
    assert parse_data_size("512MB") == 512 << 20
    assert parse_data_size("1GB") == 1 << 30
    assert parse_data_size(12345) == 12345
    cfg = apply_session_properties(ExecutionConfig(), {
        "query_max_memory_per_node": "64MB",
        "spill_enabled": "false",
        "task_batch_rows": "4096",
        "unknown_property": "ignored",
    })
    assert cfg.memory_budget_bytes == 64 << 20
    assert cfg.spill_enabled is False
    assert cfg.batch_rows == 4096


def test_session_properties_over_http():
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer
    w = WorkerServer()
    try:
        r = HttpQueryRunner([w.uri], "sf0.01", n_tasks=1,
                            session={"task_batch_rows": "8192"})
        assert r.execute("select count(*) from nation").rows == [[25]]
    finally:
        w.close()


def test_malformed_session_property_fails_task():
    import json as _json
    import time
    import urllib.request
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer
    w = WorkerServer()
    try:
        r = HttpQueryRunner([w.uri], "sf0.01", n_tasks=1,
                            session={"task_batch_rows": "not-a-number"})
        try:
            r.execute("select count(*) from nation")
            assert False, "expected failure"
        except RuntimeError as e:
            assert "failed" in str(e).lower()
        # task is terminal (FAILED), not stranded in PLANNED
        counts = w.task_manager.counts()["by_state"]
        assert counts.get("PLANNED", 0) == 0
    finally:
        w.close()


def test_exchange_compression_over_http():
    """exchange_compression session property: pages crossing the HTTP
    exchange carry the COMPRESSED marker (LZ4 body) and results match the
    uncompressed run — the analog of the reference's exchange.compression
    (PagesSerdeFactory wired into OutputBuffers + ExchangeClient)."""
    import struct
    import presto_tpu.worker.task as task_mod
    from presto_tpu.common.serde import COMPRESSED
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    # wide pass-through rows so exchange pages clear the 4KiB compression
    # floor (post-aggregation pages at sf0.01 are tiny and stay raw)
    sql = ("select orderkey, orderpriority, comment from orders "
           "where orderkey < 20000 order by orderkey limit 2000")
    compressed_pages = [0]
    real = task_mod.serialize_page

    def recording(page, checksummed=True, compress=False, codec="LZ4"):
        data = real(page, checksummed=checksummed, compress=compress,
                    codec=codec)
        if struct.unpack_from("<ibiiq", data, 0)[1] & COMPRESSED:
            compressed_pages[0] += 1
        return data

    w1, w2 = WorkerServer(), WorkerServer()
    task_mod.serialize_page = recording
    try:
        plain = HttpQueryRunner([w1.uri, w2.uri], "sf0.01", n_tasks=2)
        expect = plain.execute(sql).rows
        assert compressed_pages[0] == 0
        r = HttpQueryRunner([w1.uri, w2.uri], "sf0.01", n_tasks=2,
                            session={"exchange_compression": "true"})
        assert r.execute(sql).rows == expect
        assert compressed_pages[0] > 0, "no page was actually compressed"
    finally:
        task_mod.serialize_page = real
        w1.close()
        w2.close()


def test_exchange_compression_non_default_codec():
    """Non-default codec from the session reaches both the producer and
    every consumer (workers' exchange pulls AND the coordinator's result
    pull) — guards the coordinator-side decode path."""
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer
    sql = ("select orderkey, orderpriority, comment from orders "
           "where orderkey < 20000 order by orderkey limit 2000")
    w1, w2 = WorkerServer(), WorkerServer()
    try:
        expect = HttpQueryRunner([w1.uri, w2.uri], "sf0.01",
                                 n_tasks=2).execute(sql).rows
        r = HttpQueryRunner(
            [w1.uri, w2.uri], "sf0.01", n_tasks=2,
            session={"exchange_compression": "true",
                     "exchange_compression_codec": "ZSTD"})
        assert r.execute(sql).rows == expect
    finally:
        w1.close()
        w2.close()


def test_unsupported_codec_rejected_at_task_start():
    import pytest
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.worker.protocol import apply_session_properties
    with pytest.raises(ValueError, match="LZO"):
        apply_session_properties(
            ExecutionConfig(), {"exchange_compression_codec": "LZO"})
