"""Reference-PlanFragment -> engine-IR translation (the
PrestoToVeloxQueryPlan analog, VERDICT round-2 missing #1).

Three layers of proof, strongest first:
 1. JAVA-PRODUCED golden fixtures — plan/fragment JSON checked into the
    reference tree (presto_cpp/main/types/tests/data/,
    presto_cpp/presto_protocol/tests/data/), read at test time and parsed
    by the translator.  These bytes were serialized by the Java
    coordinator's Jackson bindings, not by this repo.
 2. Round-trip execution parity — repo-planned TPC-H queries re-shaped
    into coordinator JSON (tests/reference_shapes.py), translated back,
    executed, and compared against direct execution.
 3. Live-worker interop — a reference-shaped TaskUpdateRequest whose
    fragment and splits are BOTH reference JSON (TpchSplit with
    partNumber/totalParts) drives the HTTP worker end to end.
"""
import base64
import json
import os
import threading
import time
import urllib.request

import pytest

from presto_tpu.spi import plan as P
from presto_tpu.worker import plan_translation as T

import reference_shapes as RS

TYPES_FIXTURES = ("/root/reference/presto-native-execution/presto_cpp/"
                  "main/types/tests/data")
PROTO_FIXTURES = ("/root/reference/presto-native-execution/presto_cpp/"
                  "presto_protocol/tests/data")

needs_fixtures = pytest.mark.skipif(
    not os.path.isdir(TYPES_FIXTURES), reason="reference tree not present")


def _load(path, name):
    with open(os.path.join(path, name)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# 1. Java-produced fixtures
# ---------------------------------------------------------------------------

@needs_fixtures
def test_scan_agg_fragment_fixture():
    """ScanAgg.json: hive scan -> project -> partial agg, FIXED/HASH
    output partitioning — produced by the Java planner."""
    frag = T.translate_fragment(_load(TYPES_FIXTURES, "ScanAgg.json"))
    assert frag.fragment_id == "2"
    agg = frag.root
    assert isinstance(agg, P.AggregationNode)
    assert agg.step == "PARTIAL"
    assert [v.name for v in agg.grouping_keys] == ["regionkey"]
    (var, a), = agg.aggregations.items()
    assert var.name == "sum_9"
    assert a.call.display_name == "sum"
    proj = agg.source
    assert isinstance(proj, P.ProjectNode)
    # the Java-serialized bigint constant decodes through the repo's block
    # serde: expr := BIGINT 1
    const = {v.name: e for v, e in proj.assignments.items()}["expr"]
    assert const.value == 1 and const.type.signature == "bigint"
    scan = proj.source
    assert isinstance(scan, P.TableScanNode)
    assert scan.table.connector_id == "hive"
    assert scan.table.table_name == "nation"
    assert frag.partitioning == P.SOURCE_DISTRIBUTION
    scheme = frag.output_partitioning_scheme
    assert scheme.handle == P.FIXED_HASH_DISTRIBUTION
    assert [a.name for a in scheme.arguments] == ["regionkey"]
    assert frag.partitioned_sources == ["0"]


@needs_fixtures
def test_final_agg_fragment_fixture():
    """FinalAgg.json: remote source -> local exchange -> FINAL agg."""
    frag = T.translate_fragment(_load(TYPES_FIXTURES, "FinalAgg.json"))
    agg = frag.root
    assert isinstance(agg, P.AggregationNode)
    assert agg.step == "FINAL"
    ex = agg.source
    assert isinstance(ex, P.ExchangeNode)
    assert ex.scope == "LOCAL"
    rs = ex.exchange_sources[0]
    assert isinstance(rs, P.RemoteSourceNode)
    assert rs.source_fragment_ids


@needs_fixtures
def test_output_fragment_fixture():
    frag = T.translate_fragment(_load(TYPES_FIXTURES, "Output.json"))
    out = frag.root
    assert isinstance(out, P.OutputNode)
    assert out.column_names
    assert isinstance(out.source, P.RemoteSourceNode) or out.source


@needs_fixtures
def test_offset_limit_fragment_fixture():
    """OffsetLimit.json: OutputNode over project/filter/row_number/limit
    chain with a LOCAL round-robin exchange."""
    frag = T.translate_fragment(_load(TYPES_FIXTURES, "OffsetLimit.json"))
    kinds = {type(n).__name__ for n in P.walk_plan(frag.root)}
    assert "LimitNode" in kinds and "FilterNode" in kinds
    # RowNumberNode arrives as a WindowNode carrying row_number()
    assert "WindowNode" in kinds


@needs_fixtures
@pytest.mark.parametrize("name", ["PartitionedOutput.json",
                                  "ScanAggBatch.json",
                                  "ScanAggCustomConnectorId.json"])
def test_more_fragment_fixtures_parse(name):
    frag = T.translate_fragment(_load(TYPES_FIXTURES, name))
    assert frag.root is not None
    assert any(isinstance(n, P.TableScanNode) for n in P.walk_plan(frag.root))


@needs_fixtures
def test_plan_node_fixtures_parse():
    for name, expect in [("FilterNode.json", P.FilterNode),
                         ("ExchangeNode.json", P.ExchangeNode),
                         ("OutputNode.json", P.OutputNode),
                         ("ValuesNode.json", P.ValuesNode)]:
        node = T.translate_node(_load(PROTO_FIXTURES, name))
        assert isinstance(node, expect), name


@needs_fixtures
def test_task_update_request_fixture_fragment():
    """TaskUpdateRequest.1: a REAL captured coordinator update (base64
    fragment, hive scan + partial agg with hash variables) parses through
    the full worker path: envelope DTO -> fragment translation."""
    from presto_tpu.worker.protocol import from_reference_update
    with open(os.path.join(PROTO_FIXTURES, "TaskUpdateRequest.1")) as f:
        d = json.load(f)
    upd = from_reference_update("q.1.0.3.0", d)
    assert upd.task_index == 3
    frag = upd.fragment()
    kinds = {type(n).__name__ for n in P.walk_plan(frag.root)}
    assert "TableScanNode" in kinds
    assert "AggregationNode" in kinds


@needs_fixtures
def test_constant_decodes_java_bytes():
    """The valueBlock bytes in the fixtures were written by Java
    BlockEncodings; decoding them through the repo serde proves wire-level
    block compatibility in the coordinator->worker direction."""
    c = T.decode_constant({"@type": "constant", "type": "bigint",
                           "valueBlock":
                           "CgAAAExPTkdfQVJSQVkBAAAAAAEAAAAAAAAA"})
    assert c.value == 1
    c = T.decode_constant({"@type": "constant", "type": "boolean",
                           "valueBlock": "CgAAAEJZVEVfQVJSQVkBAAAAAAE="})
    assert c.value is True


# ---------------------------------------------------------------------------
# 2. round-trip execution parity (repo plan -> reference JSON -> IR -> run)
# ---------------------------------------------------------------------------

PARITY_QUERIES = {
    "q6_shape": """
        SELECT sum(l_extendedprice * l_discount) AS revenue
        FROM lineitem
        WHERE l_shipdate >= date '1994-01-01'
          AND l_shipdate < date '1995-01-01'
          AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24""",
    "q1_shape": """
        SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty,
               sum(l_extendedprice) AS sum_base_price,
               avg(l_discount) AS avg_disc, count(*) AS count_order
        FROM lineitem WHERE l_shipdate <= date '1998-09-02'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus""",
    "q3_shape": """
        SELECT o_orderkey, sum(l_extendedprice * (1 - l_discount)) AS rev
        FROM customer, orders, lineitem
        WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
          AND l_orderkey = o_orderkey AND o_orderdate < date '1995-03-15'
          AND l_shipdate > date '1995-03-15'
        GROUP BY o_orderkey ORDER BY rev DESC LIMIT 10""",
}


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.exec.runner import LocalQueryRunner
    return LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 14, join_out_capacity=1 << 16))


@pytest.mark.parametrize("name", sorted(PARITY_QUERIES))
def test_reference_shaped_execution_parity(runner, name):
    """Plan with the repo planner, re-shape to coordinator JSON, translate
    back through plan_translation, execute — results must match direct
    execution."""
    from presto_tpu.exec.pipeline import PlanCompiler, TaskContext
    from presto_tpu.exec.runner import pages_to_result

    sql = PARITY_QUERIES[name]
    direct = runner.execute(sql)
    out = runner.plan(sql)                      # OutputNode plan root
    frag = P.PlanFragment("0", out, P.SOURCE_DISTRIBUTION,
                          P.PartitioningScheme(
                              P.SINGLE_DISTRIBUTION, [],
                              list(out.output_variables)),
                          [n.id for n in P.walk_plan(out)
                           if isinstance(n, P.TableScanNode)])
    ref_json = RS.fragment_json(frag)
    # the reference shape must be detected and fully translated
    assert T.is_reference_fragment(ref_json)
    back = T.translate_fragment(json.loads(json.dumps(ref_json)))
    comp = PlanCompiler(TaskContext(config=runner.config))
    translated = pages_to_result(comp.run_to_pages(back.root),
                                 back.root.column_names,
                                 [v.type for v in back.root.outputs])
    assert [tuple(r) for r in translated.rows] \
        == [tuple(r) for r in direct.rows], name


# ---------------------------------------------------------------------------
# 2b. hand-authored wire samples for round-3 gap nodes (field layouts
# copied from presto_protocol_core.h structs, cited per test) — each is
# translated and EXECUTED, with a plain-SQL oracle on the same data
# ---------------------------------------------------------------------------

def _run_node(runner, node, out_names=None):
    from presto_tpu.exec.pipeline import PlanCompiler, TaskContext
    from presto_tpu.exec.runner import pages_to_result
    comp = PlanCompiler(TaskContext(config=runner.config))
    names = out_names or [v.name for v in node.output_variables]
    return pages_to_result(comp.run_to_pages(node), names,
                           [v.type for v in node.output_variables])


def _nation_scan_json(cols):
    """Reference TableScanNode JSON over tpch nation (shape as in
    ScanAgg.json / presto_protocol_core.h TableScanNode)."""
    return {
        "@type": ".TableScanNode", "id": "scan",
        "table": {"connectorId": "tpch",
                  "connectorHandle": {"@type": "tpch",
                                      "tableName": "nation",
                                      "scaleFactor": 0.01},
                  "transaction": {"@type": "tpch", "instance": "test"}},
        "outputVariables": [{"@type": "variable", "name": n,
                             "type": "bigint"} for n in cols],
        "assignments": {f"{n}<bigint>": {"@type": "tpch",
                                         "columnName": n.split("_", 1)[1],
                                         "type": "bigint"}
                        for n in cols}}


def _vj(name, typ="bigint"):
    return {"@type": "variable", "name": name, "type": typ}


def _count_call(arg):
    return {"@type": "call", "displayName": "count",
            "functionHandle": {"@type": "$static", "signature": {
                "name": "presto.default.count", "kind": "AGGREGATE",
                "returnType": "bigint", "argumentTypes": ["bigint"],
                "typeVariableConstraints": [],
                "longVariableConstraints": [], "variableArity": False}},
            "returnType": "bigint", "arguments": [_vj(arg)]}


def test_table_writer_finish_wire_sample(tmp_path):
    """A coordinator-shaped WRITE: TableWriterNode fragment executed with
    TaskUpdateRequest.tableWriteInfo carrying the CreateHandle target
    (presto_protocol_core.h:2279-2292 / :726, TableWriterOperator.java:78),
    then a TableFinishNode (TableFinishNode.java:46-52) committing the
    staged fragment via the connector's staged-rename path — after which
    the written table scans back correctly."""
    from presto_tpu.connectors import catalog as cat
    from presto_tpu.connectors import hive
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.exec.runner import LocalQueryRunner
    from presto_tpu.worker.plan_translation import translate_fragment

    conn = hive.HiveConnector(str(tmp_path / "warehouse"))
    cat.register_connector("hive", conn)
    try:
        runner = LocalQueryRunner("sf0.01", config=ExecutionConfig(
            batch_rows=1 << 13))
        writer = {
            "@type": "com.facebook.presto.sql.planner.plan.TableWriterNode",
            "id": "writer",
            "source": _nation_scan_json(["n_nationkey", "n_regionkey"]),
            "rowCountVariable": _vj("rows"),
            "fragmentVariable": _vj("frag", "varchar"),
            "tableCommitContextVariable": _vj("ctx", "varchar"),
            "columns": [_vj("n_nationkey"), _vj("n_regionkey")],
            "columnNames": ["nationkey", "regionkey"],
            "notNullColumnVariables": []}
        frag = {
            "id": "1", "root": writer,
            "partitioning": {"connectorId": "$remote", "connectorHandle": {
                "@type": "$remote", "partitioning": "SOURCE",
                "function": "UNKNOWN"}},
            "tableScanSchedulingOrder": ["scan"],
            "partitioningScheme": {
                "partitioning": {
                    "handle": {"connectorId": "$remote",
                               "connectorHandle": {
                                   "@type": "$remote",
                                   "partitioning": "SINGLE",
                                   "function": "UNKNOWN"}},
                    "arguments": []},
                "outputLayout": [_vj("rows"), _vj("frag", "varchar"),
                                 _vj("ctx", "varchar")]}}
        twi = {"writerTarget": {
            "@type": "CreateHandle",
            "handle": {"connectorId": "hive",
                       "transactionHandle": {"@type": "hive"},
                       "connectorHandle": {"@type": "hive",
                                           "tableName": "wt_nation"}},
            "schemaTableName": {"schema": "default", "table": "wt_nation"}}}
        tfrag = translate_fragment(json.loads(json.dumps(frag)), twi)
        wnode = tfrag.root
        assert isinstance(wnode, P.TableWriterNode)
        assert wnode.connector_id == "hive"
        assert wnode.table_name == "wt_nation"

        # finish over the writer (the LogicalPlanner's
        # createTableWriterPlan shape, collapsed into one task here):
        # translated as a wire TableFinishNode with the writer as source
        finish = {
            "@type": "com.facebook.presto.spi.plan.TableFinishNode",
            "id": "finish", "source": writer,
            "rowCountVariable": _vj("total")}
        frag2 = dict(frag)
        frag2["root"] = finish
        frag2["partitioningScheme"] = {
            "partitioning": frag["partitioningScheme"]["partitioning"],
            "outputLayout": [_vj("total")]}
        fnode = translate_fragment(json.loads(json.dumps(frag2)), twi).root
        assert isinstance(fnode, P.TableFinishNode)
        got = _run_node(runner, fnode)
        assert got.rows[0][0] == 25
        # the committed table scans back (staged rename happened)
        scanned = runner.execute("select count(*), sum(nationkey) "
                                 "from wt_nation")
        assert scanned.rows[0] == [25, 300]
    finally:
        cat.unregister_connector("hive")


def test_unnest_node_wire_sample(runner):
    """UnnestNode wire layout per presto_protocol_core.h:2431-2438
    (replicateVariables, unnestVariables as a "name<type>"-keyed map,
    ordinalityVariable), under the projection building the array the way
    the coordinator plans CROSS JOIN UNNEST.  Oracle: the engine's own
    UNNEST SQL."""
    arr_call = {"@type": "call", "displayName": "ARRAY_CONSTRUCTOR",
                "functionHandle": {"@type": "$static", "signature": {
                    "name": "presto.default.array_constructor",
                    "kind": "SCALAR", "returnType": "array(bigint)",
                    "argumentTypes": ["bigint", "bigint"],
                    "typeVariableConstraints": [],
                    "longVariableConstraints": [], "variableArity": True}},
                "returnType": "array(bigint)",
                "arguments": [_vj("n_nationkey"), _vj("n_regionkey")]}
    proj = {"@type": ".ProjectNode", "id": "mkarr",
            "source": _nation_scan_json(["n_nationkey", "n_regionkey"]),
            "assignments": {"assignments": {
                "n_nationkey<bigint>": _vj("n_nationkey"),
                "arr<array(bigint)>": arr_call}},
            "locality": "LOCAL"}
    unnest = {
        "@type": "com.facebook.presto.spi.plan.UnnestNode",
        "id": "unnest", "source": proj,
        "replicateVariables": [_vj("n_nationkey")],
        "unnestVariables": {"arr<array(bigint)>": [_vj("x")]},
        "ordinalityVariable": _vj("ord")}
    node = T.translate_node(json.loads(json.dumps(unnest)))
    assert isinstance(node, P.UnnestNode)
    assert node.ordinality_variable is not None
    got = _run_node(runner, node)
    want = runner.execute(
        "SELECT n_nationkey, x, i FROM nation CROSS JOIN "
        "UNNEST(ARRAY[n_nationkey, n_regionkey]) WITH ORDINALITY "
        "AS u(x, i)")
    key = lambda r: tuple((v is None, v) for v in r)   # noqa: E731
    assert sorted((tuple(r) for r in got.rows), key=key) \
        == sorted((tuple(r) for r in want.rows), key=key)


def test_group_id_node_wire_sample(runner):
    """GroupIdNode wire layout per presto_protocol_core.h:1340-1349
    (groupingSets: List<List<Variable>>, groupingColumns: Map with
    "name<type>" keys, aggregationArguments, groupIdVariable), paired with
    the grouping AggregationNode above it the way the coordinator plans
    ROLLUP.  Oracle: the engine's own ROLLUP SQL."""
    gid = {
        "@type": "com.facebook.presto.sql.planner.plan.GroupIdNode",
        "id": "groupid",
        "source": _nation_scan_json(["n_regionkey", "n_nationkey"]),
        "groupingSets": [[_vj("n_regionkey$gid")], []],
        "groupingColumns": {"n_regionkey$gid<bigint>": _vj("n_regionkey")},
        "aggregationArguments": [_vj("n_nationkey")],
        "groupIdVariable": _vj("groupid")}
    agg = {
        "@type": ".AggregationNode", "id": "agg", "source": gid,
        "aggregations": {"cnt<bigint>": {"call": _count_call("n_nationkey"),
                                         "distinct": False}},
        "groupingSets": {"groupingKeys": [_vj("n_regionkey$gid"),
                                          _vj("groupid")],
                         "groupingSetCount": 1, "globalGroupingSets": []},
        "preGroupedVariables": [], "step": "SINGLE"}
    node = T.translate_node(json.loads(json.dumps(agg)))
    assert isinstance(node, P.AggregationNode)
    assert isinstance(node.source, P.GroupIdNode)
    got = _run_node(runner, node)
    # project away groupid, as the coordinator's enclosing projection would
    key = lambda r: tuple((v is None, v) for v in r)   # noqa: E731
    got_rows = sorted(((r[0], r[2]) for r in got.rows), key=key)
    want = runner.execute("SELECT n_regionkey, count(n_nationkey) "
                          "FROM nation GROUP BY ROLLUP(n_regionkey)")
    assert got_rows == sorted((tuple(r) for r in want.rows), key=key)


def test_filter_aggregate_wire_sample(runner):
    """Aggregation.filter (presto_protocol_core.h:434-442: filter is a
    RowExpression next to call/mask) — both the expression form and the
    pre-bound variable form.  Oracle: WHERE-equivalent SQL."""
    gt_call = {"@type": "call", "displayName": "GREATER_THAN",
               "functionHandle": {"@type": "$static", "signature": {
                   "name": "presto.default.$operator$greater_than",
                   "kind": "SCALAR", "returnType": "boolean",
                   "argumentTypes": ["bigint", "bigint"],
                   "typeVariableConstraints": [],
                   "longVariableConstraints": [], "variableArity": False}},
               "returnType": "boolean",
               "arguments": [_vj("n_regionkey"),
                             {"@type": "constant", "type": "bigint",
                              "valueBlock":
                              "CgAAAExPTkdfQVJSQVkBAAAAAAIAAAAAAAAA"}]}
    agg = {
        "@type": ".AggregationNode", "id": "agg",
        "source": _nation_scan_json(["n_regionkey", "n_nationkey"]),
        "aggregations": {"cnt<bigint>": {"call": _count_call("n_nationkey"),
                                         "filter": gt_call,
                                         "distinct": False}},
        "groupingSets": {"groupingKeys": [], "groupingSetCount": 1,
                         "globalGroupingSets": []},
        "preGroupedVariables": [], "step": "SINGLE"}
    node = T.translate_node(json.loads(json.dumps(agg)))
    assert isinstance(node, P.AggregationNode)
    (_, a), = node.aggregations.items()
    assert a.mask is not None      # filter lowered to the engine's mask
    got = _run_node(runner, node)
    want = runner.execute("SELECT count(n_nationkey) FROM nation "
                          "WHERE n_regionkey > 2")
    assert got.rows[0][0] == want.rows[0][0]


def test_filter_plus_mask_aggregate_executes(runner):
    """An aggregate carrying BOTH a mask variable and a FILTER expression
    (the coordinator's count(DISTINCT x) FILTER (WHERE p) shape) must
    combine them and execute — regression for the inline-AND translation."""
    gt_call = {"@type": "call", "displayName": "GREATER_THAN",
               "functionHandle": {"@type": "$static", "signature": {
                   "name": "presto.default.$operator$greater_than",
                   "kind": "SCALAR", "returnType": "boolean",
                   "argumentTypes": ["bigint", "bigint"],
                   "typeVariableConstraints": [],
                   "longVariableConstraints": [], "variableArity": False}},
               "returnType": "boolean",
               "arguments": [_vj("n_regionkey"),
                             {"@type": "constant", "type": "bigint",
                              "valueBlock":
                              "CgAAAExPTkdfQVJSQVkBAAAAAAIAAAAAAAAA"}]}
    # mask variable bound below: m = n_nationkey < 20
    lt_call = {"@type": "call", "displayName": "LESS_THAN",
               "functionHandle": {"@type": "$static", "signature": {
                   "name": "presto.default.$operator$less_than",
                   "kind": "SCALAR", "returnType": "boolean",
                   "argumentTypes": ["bigint", "bigint"],
                   "typeVariableConstraints": [],
                   "longVariableConstraints": [], "variableArity": False}},
               "returnType": "boolean",
               "arguments": [_vj("n_nationkey"),
                             {"@type": "constant", "type": "bigint",
                              "valueBlock": base64.b64encode(
                                  b"\x0a\x00\x00\x00LONG_ARRAY"
                                  b"\x01\x00\x00\x00\x00"
                                  b"\x14\x00\x00\x00\x00\x00\x00\x00"
                              ).decode()}]}
    proj = {"@type": ".ProjectNode", "id": "bindmask",
            "source": _nation_scan_json(["n_regionkey", "n_nationkey"]),
            "assignments": {"assignments": {
                "n_regionkey<bigint>": _vj("n_regionkey"),
                "n_nationkey<bigint>": _vj("n_nationkey"),
                "m<boolean>": lt_call}},
            "locality": "LOCAL"}
    agg = {
        "@type": ".AggregationNode", "id": "agg", "source": proj,
        "aggregations": {"cnt<bigint>": {"call": _count_call("n_nationkey"),
                                         "filter": gt_call,
                                         "mask": _vj("m", "boolean"),
                                         "distinct": False}},
        "groupingSets": {"groupingKeys": [], "groupingSetCount": 1,
                         "globalGroupingSets": []},
        "preGroupedVariables": [], "step": "SINGLE"}
    node = T.translate_node(json.loads(json.dumps(agg)))
    got = _run_node(runner, node)
    want = runner.execute("SELECT count(n_nationkey) FROM nation "
                          "WHERE n_regionkey > 2 AND n_nationkey < 20")
    assert got.rows[0][0] == want.rows[0][0]


def test_range_frame_with_offsets_rejected():
    """RANGE frames with value offsets must fail at TRANSLATE time (the
    executor implements offset bounds for ROWS only)."""
    win = {"@type": "com.facebook.presto.sql.planner.plan.WindowNode",
           "id": "win",
           "source": _nation_scan_json(["n_regionkey", "n_nationkey"]),
           "specification": {
               "partitionBy": [],
               "orderingScheme": {"orderBy": [
                   {"variable": _vj("n_nationkey"),
                    "sortOrder": "ASC_NULLS_LAST"}]}},
           "windowFunctions": {"s<bigint>": {
               "functionCall": _count_call("n_nationkey"),
               "frame": {"type": "RANGE", "startType": "PRECEDING",
                         "originalStartValue": "2",
                         "startValue": _vj("$off"),
                         "endType": "CURRENT_ROW"},
               "ignoreNulls": False}},
           "prePartitionedInputs": [], "preSortedOrderPrefix": 0}
    with pytest.raises(T.PlanTranslationError, match="RANGE"):
        T.translate_node(json.loads(json.dumps(win)))


def test_topn_row_number_wire_sample(runner):
    """TopNRowNumberNode (presto_protocol_core.h:2417-2426: specification
    + rowNumberVariable + maxRowCountPerPartition + partial).  Oracle: the
    row_number()-subquery SQL the node is an optimization of."""
    d = {"@type":
         "com.facebook.presto.sql.planner.plan.TopNRowNumberNode",
         "id": "topnrn",
         "source": _nation_scan_json(["n_regionkey", "n_nationkey"]),
         "specification": {
             "partitionBy": [_vj("n_regionkey")],
             "orderingScheme": {"orderBy": [
                 {"variable": _vj("n_nationkey"),
                  "sortOrder": "DESC_NULLS_LAST"}]}},
         "rowNumberVariable": _vj("rn"),
         "maxRowCountPerPartition": 2, "partial": False}
    node = T.translate_node(json.loads(json.dumps(d)))
    got = _run_node(runner, node)
    got_rows = sorted((r[0], r[1]) for r in got.rows)
    want = runner.execute(
        "SELECT * FROM (SELECT n_regionkey, n_nationkey, row_number() "
        "OVER (PARTITION BY n_regionkey ORDER BY n_nationkey DESC) rn "
        "FROM nation) t WHERE rn <= 2")
    assert got_rows == sorted((r[0], r[1]) for r in want.rows)


def test_window_value_offset_frame_wire_sample(runner):
    """Frame startValue/endValue as variable refs bound to constants by
    the projection below (presto_protocol_core.h:1314-1326) — the
    coordinator's actual shape for ROWS k PRECEDING.  Also exercises the
    originalStartValue fallback text.  Oracle: the same frame in SQL."""
    proj = {"@type": ".ProjectNode", "id": "bindoffsets",
            "source": _nation_scan_json(["n_regionkey", "n_nationkey"]),
            "assignments": {"assignments": {
                "n_regionkey<bigint>": _vj("n_regionkey"),
                "n_nationkey<bigint>": _vj("n_nationkey"),
                "$off<bigint>": {"@type": "constant", "type": "bigint",
                                 "valueBlock":
                                 "CgAAAExPTkdfQVJSQVkBAAAAAAIAAAAAAAAA"}}},
            "locality": "LOCAL"}
    sum_call = {"@type": "call", "displayName": "sum",
                "functionHandle": {"@type": "$static", "signature": {
                    "name": "presto.default.sum", "kind": "WINDOW",
                    "returnType": "bigint", "argumentTypes": ["bigint"],
                    "typeVariableConstraints": [],
                    "longVariableConstraints": [],
                    "variableArity": False}},
                "returnType": "bigint", "arguments": [_vj("n_nationkey")]}
    win = {"@type": "com.facebook.presto.sql.planner.plan.WindowNode",
           "id": "win", "source": proj,
           "specification": {
               "partitionBy": [],
               "orderingScheme": {"orderBy": [
                   {"variable": _vj("n_nationkey"),
                    "sortOrder": "ASC_NULLS_LAST"}]}},
           "windowFunctions": {"s<bigint>": {
               "functionCall": sum_call,
               "frame": {"type": "ROWS",
                         "startType": "PRECEDING",
                         "startValue": _vj("$off"),
                         "originalStartValue": "2",
                         "endType": "CURRENT_ROW"},
               "ignoreNulls": False}},
           "prePartitionedInputs": [], "preSortedOrderPrefix": 0}
    node = T.translate_node(json.loads(json.dumps(win)))
    assert isinstance(node, P.WindowNode)
    (_, wf), = node.window_functions.items()
    assert wf.frame == {"type": "ROWS", "startKind": "PRECEDING",
                        "startOffset": 2, "endKind": "CURRENT",
                        "endOffset": None}
    got = _run_node(runner, node)
    got_rows = sorted((r[1], r[3]) for r in got.rows)
    want = runner.execute(
        "SELECT n_nationkey, sum(n_nationkey) OVER (ORDER BY n_nationkey "
        "ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) s FROM nation")
    assert got_rows == sorted(tuple(r) for r in want.rows)


def test_mark_distinct_executes(runner):
    """MarkDistinctNode now has an execution path (round-3 latent gap: it
    translated but could not compile).  Round-trip through the
    reference-shaped emitter; oracle = count(distinct)."""
    from presto_tpu.common.types import BOOLEAN
    scan = T.translate_node(
        json.loads(json.dumps(_nation_scan_json(["n_regionkey",
                                                 "n_nationkey"]))))
    from presto_tpu.spi.expr import VariableReferenceExpression as V
    md = P.MarkDistinctNode("md", scan, V("marker", BOOLEAN),
                            [V("n_regionkey", scan.outputs[0].type)])
    back = T.translate_node(json.loads(json.dumps(RS.node_json(md))))
    assert isinstance(back, P.MarkDistinctNode)
    got = _run_node(runner, back)
    marked = [r for r in got.rows if r[2]]
    want = runner.execute("SELECT count(DISTINCT n_regionkey) FROM nation")
    assert len(marked) == want.rows[0][0]


def test_group_id_round_trip_via_emitter(runner):
    """Repo GroupIdNode IR -> reference JSON (tests/reference_shapes.py)
    -> translate -> same IR shape."""
    scan = T.translate_node(
        json.loads(json.dumps(_nation_scan_json(["n_regionkey",
                                                 "n_nationkey"]))))
    from presto_tpu.spi.expr import VariableReferenceExpression as V
    rk = V("n_regionkey$gid", scan.outputs[0].type)
    gid = P.GroupIdNode("gid", scan, [[rk], []],
                        {rk: scan.outputs[0]}, [scan.outputs[1]],
                        V("groupid", scan.outputs[0].type))
    back = T.translate_node(json.loads(json.dumps(RS.node_json(gid))))
    assert isinstance(back, P.GroupIdNode)
    assert [[v.name for v in s] for s in back.grouping_sets] \
        == [["n_regionkey$gid"], []]
    assert {o.name: i.name for o, i in back.grouping_columns.items()} \
        == {"n_regionkey$gid": "n_regionkey"}
    assert back.group_id_variable.name == "groupid"


# ---------------------------------------------------------------------------
# 3. live worker driven by a fully reference-shaped update
# ---------------------------------------------------------------------------

def test_worker_runs_reference_fragment_end_to_end():
    """The interop claim: POST an update whose envelope, FRAGMENT, and
    SPLITS are all reference-shaped JSON (the exact HttpRemoteTask wire
    shapes) and read SerializedPage results back."""
    from presto_tpu.common.block import block_to_values
    from presto_tpu.common.serde import deserialize_page
    from presto_tpu.common.types import BIGINT
    from presto_tpu.sql.planner import Planner
    from presto_tpu.worker import presto_protocol as PP
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    t = threading.Thread(target=w.httpd.serve_forever, daemon=True)
    t.start()
    try:
        out = Planner(default_schema="sf0.01", default_catalog="tpch") \
            .plan("SELECT count(*) AS n, sum(n_regionkey) AS s FROM nation "
                  "WHERE n_nationkey < 20")
        frag = P.PlanFragment(
            "0", out, P.SOURCE_DISTRIBUTION,
            P.PartitioningScheme(P.SINGLE_DISTRIBUTION, [],
                                 list(out.output_variables)),
            [n.id for n in P.walk_plan(out)
             if isinstance(n, P.TableScanNode)])
        ref_json = RS.fragment_json(frag)
        scan_ids = frag.partitioned_sources
        body = {
            "session": PP.SessionRepresentation(
                queryId="q_ref", user="test").to_json(),
            "extraCredentials": {},
            "fragment": base64.b64encode(
                json.dumps(ref_json).encode()).decode(),
            "sources": [
                {"planNodeId": sid,
                 "splits": [{"sequenceId": i, "planNodeId": sid,
                             "split": RS.tpch_split_json(
                                 "nation", 0.01, i, 2)}
                            for i in range(2)],
                 "noMoreSplits": True} for sid in scan_ids],
            "outputIds": PP.OutputBuffers(
                "PARTITIONED", 0, True, {"0": 0}).to_json(),
        }
        req = urllib.request.Request(
            f"{w.uri}/v1/task/q_ref.0.0.0.0",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        st = json.load(urllib.request.urlopen(req))
        assert st["state"] in ("PLANNED", "RUNNING", "FINISHED"), st
        rows = []
        token = 0
        deadline = time.time() + 120
        while time.time() < deadline:
            r = urllib.request.urlopen(
                f"{w.uri}/v1/task/q_ref.0.0.0.0/results/0/{token}")
            data = r.read()
            complete = r.headers.get("X-Presto-Buffer-Complete") == "true"
            nxt = r.headers.get("X-Presto-Page-End-Sequence-Id")
            if data:
                pos = 0
                while pos < len(data):
                    page, pos = deserialize_page(data, pos)
                    rows.append([block_to_values(BIGINT, b)[0]
                                 for b in page.blocks])
            if complete:
                break
            token = int(nxt) if nxt else token + 1
            time.sleep(0.05)
        assert rows, "no pages returned"
    finally:
        w.httpd.shutdown()
    # nation rows 0..19: count=20; regionkey sum checked against the
    # local runner for exactness
    from presto_tpu.exec.runner import LocalQueryRunner
    lr = LocalQueryRunner("sf0.01")
    want = lr.execute("SELECT count(*), sum(n_regionkey) FROM nation "
                      "WHERE n_nationkey < 20").rows[0]
    assert rows[0][0] == int(want[0])
    assert rows[0][1] == int(want[1])
