"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding is exercised without TPU hardware (SURVEY.md §7 / driver dryrun
contract).

NOTE: this image's axon TPU plugin ignores JAX_PLATFORMS, so we set
JAX_PLATFORM_NAME and the jax_platforms config explicitly.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True, scope="module")
def _close_leaked_worker_servers():
    """Sweep worker/coordinator HTTP servers a module leaves open.

    Autouse module fixtures are set up before a module's own fixtures, so
    this teardown runs AFTER theirs (LIFO): properly closed clusters are
    unaffected, while leaked serve_forever threads — which accumulated
    into the hundreds over a full run and starved later tests — are
    closed at each module boundary (reference test pattern:
    DistributedQueryRunner.java:108 is closeable)."""
    yield
    from presto_tpu.worker.server import WorkerServer
    WorkerServer.close_all_live()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy end-to-end cases excluded from the tier-1 budget "
        "(run with -m slow)")
