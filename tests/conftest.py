"""Test configuration: force an 8-device virtual CPU mesh so multi-chip sharding
is exercised without TPU hardware (see SURVEY.md §7 / driver dryrun contract)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
