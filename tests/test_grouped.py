"""Grouped (lifespan) execution tests (exec/grouped.py).

Reference semantics: Lifespan.java:30-37, GroupedExecutionTagger.java,
session grouped_execution (SystemSessionProperties.java:105) — a join
stage over co-bucketed tables executes one bucket at a time, bounding
peak memory to ~1/K of the whole-table build.
"""
import numpy as np
import pytest

from presto_tpu.connectors import catalog, tpch
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner, _assert_rows_equal

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""


# ---------------------------------------------------------------------------
# connector bucket layout invariants
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sf,k", [(0.01, 1), (0.01, 4), (0.01, 7),
                                  (0.1, 16), (1.0, 13)])
def test_bucket_layout_tiles_tables(sf, k):
    layout = tpch.bucket_layout(sf, k)
    assert 1 <= len(layout) <= k
    for table in ("orders", "lineitem"):
        pos = 0
        for b in layout:
            lo, hi = b.rows[table]
            assert lo == pos
            assert hi > lo
            pos = hi
        assert pos == tpch.table_row_count(table, sf)
    # key ranges tile [1, n_orders+1)
    pos = 1
    for b in layout:
        assert b.key_lo == pos
        pos = b.key_hi
    assert pos == tpch.table_row_count("orders", sf) + 1


def test_bucket_rows_match_key_ranges():
    """Every row the layout assigns to a bucket must carry an orderkey
    inside that bucket's key range — for orders AND for the block-mapped
    lineitem rows (incl. the fixed-fanout tail)."""
    sf = 0.01
    layout = tpch.bucket_layout(sf, 5)
    for b in layout:
        o_lo, o_hi = b.rows["orders"]
        ok = tpch.generate_column("orders", "orderkey", sf, o_lo,
                                  o_hi - o_lo)
        assert ok.min() >= b.key_lo and ok.max() < b.key_hi
        l_lo, l_hi = b.rows["lineitem"]
        lk = tpch.generate_column("lineitem", "orderkey", sf, l_lo,
                                  l_hi - l_lo)
        assert lk.min() >= b.key_lo and lk.max() < b.key_hi


def test_catalog_bucket_metadata():
    assert catalog.bucket_column("lineitem", "tpch") == "orderkey"
    assert catalog.bucket_column("orders", "tpch") == "orderkey"
    assert catalog.bucket_column("customer", "tpch") is None
    assert catalog.bucket_layout(0.01, 4, "tpch") is not None


# ---------------------------------------------------------------------------
# engine execution
# ---------------------------------------------------------------------------

def _spy_runs(monkeypatch):
    from presto_tpu.exec import grouped as G
    calls = []
    orig = G.GroupedRunner.run

    def spy(self):
        calls.append(self)
        return orig(self)
    monkeypatch.setattr(G.GroupedRunner, "run", spy)
    return calls


def test_q3_grouped_parity(monkeypatch):
    calls = _spy_runs(monkeypatch)
    r = LocalQueryRunner("sf0.01",
                         config=ExecutionConfig(grouped_lifespans=4))
    oracle = LocalQueryRunner("sf0.01")
    got = r.execute(Q3)
    exp = oracle.execute_reference(Q3)
    _assert_rows_equal(got, exp, True)
    assert len(calls) == 1 and len(calls[0].layout) == 4
    # warm re-execution reuses the runner (no recompile) and stays correct
    got2 = r.execute(Q3)
    _assert_rows_equal(got2, exp, True)


def test_q18_shape_grouped_parity(monkeypatch):
    calls = _spy_runs(monkeypatch)
    sql = """
    select l_orderkey, o_orderdate, o_totalprice, sum(l_quantity) q
    from lineitem join orders on l_orderkey = o_orderkey
    group by l_orderkey, o_orderdate, o_totalprice
    having sum(l_quantity) > 150
    order by o_totalprice desc, o_orderdate limit 20
    """
    r = LocalQueryRunner("sf0.01",
                         config=ExecutionConfig(grouped_lifespans=3))
    oracle = LocalQueryRunner("sf0.01")
    _assert_rows_equal(r.execute(sql), oracle.execute_reference(sql), True)
    assert calls


def test_non_dependent_grouping_keys(monkeypatch):
    """Grouping keys NOT functionally dependent on the anchor (l_partkey
    varies within an orderkey): the per-bucket sort aggregation is fully
    general over key tuples, so the grouped result must still match."""
    calls = _spy_runs(monkeypatch)
    sql = ("select l_orderkey, l_partkey, sum(l_quantity) "
           "from lineitem group by l_orderkey, l_partkey")
    r = LocalQueryRunner("sf0.01",
                         config=ExecutionConfig(grouped_lifespans=3))
    oracle = LocalQueryRunner("sf0.01")
    _assert_rows_equal(r.execute(sql), oracle.execute_reference(sql), False)
    assert calls


def test_auto_mode_stays_off_at_small_scale(monkeypatch):
    """grouped_lifespans=0 (auto) must not engage below the span
    threshold — sf0.01's 15k-order keyspace is far under it."""
    calls = _spy_runs(monkeypatch)
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig())
    assert r.config.grouped_lifespans == 0
    oracle = LocalQueryRunner("sf0.01")
    _assert_rows_equal(r.execute(Q3), oracle.execute_reference(Q3), True)
    assert not calls


def test_partial_split_coverage_not_grouped():
    """A task owning a split subset (distributed stage) must not re-bucket
    it (exec/grouped.py _full_coverage)."""
    from presto_tpu.exec.grouped import _full_coverage
    full = catalog.make_splits("lineitem", 0.01, 4, "tpch")
    assert _full_coverage(full, "lineitem", 0.01, "tpch")
    assert not _full_coverage(full[:2], "lineitem", 0.01, "tpch")
    assert not _full_coverage(full[1:], "lineitem", 0.01, "tpch")


def test_grouped_peak_build_rows_bounded(monkeypatch):
    """The point of lifespans: no single bucketed build materialization
    covers more than ~1/K of the build table."""
    from presto_tpu.exec import grouped as G
    seen = []
    orig = G.GroupedRunner._bucket_aux

    def spy(self, bucket):
        o_lo, o_hi = bucket.rows["orders"]
        seen.append(o_hi - o_lo)
        return orig(self, bucket)
    monkeypatch.setattr(G.GroupedRunner, "_bucket_aux", spy)
    r = LocalQueryRunner("sf0.01",
                         config=ExecutionConfig(grouped_lifespans=4))
    r.execute(Q3)
    total = tpch.table_row_count("orders", 0.01)
    assert seen and max(seen) <= -(-total // 4) + 7


# ---------------------------------------------------------------------------
# prefetch (double-buffering) + lifespan sharding
# ---------------------------------------------------------------------------

def test_prefetch_defaults_on():
    assert ExecutionConfig().grouped_prefetch_depth == 1
    assert ExecutionConfig().grouped_lifespan_sharding is True


@pytest.mark.slow
@pytest.mark.parametrize("depth", [0, 1, 2])
def test_q3_grouped_prefetch_depths(monkeypatch, depth):
    """Parity is depth-invariant: depth 0 is the strictly serial bucket
    loop, depth >= 1 stages the next lifespan's generation + transfer
    while the current one computes."""
    calls = _spy_runs(monkeypatch)
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        grouped_lifespans=4, grouped_prefetch_depth=depth))
    oracle = LocalQueryRunner("sf0.01")
    _assert_rows_equal(r.execute(Q3), oracle.execute_reference(Q3), True)
    assert len(calls) == 1 and len(calls[0].layout) == 4


@pytest.mark.slow
def test_lifespan_sharding_distributed(monkeypatch):
    """A grouped SOURCE stage with n_tasks > 1 hands each task a disjoint
    round-robin subset of lifespans over the FULL split set."""
    from presto_tpu.exec import grouped as G
    from presto_tpu.exec.runner import DistributedQueryRunner
    shards = []
    orig = G.GroupedRunner.run

    def spy(self):
        shards.append(getattr(self.compiler.ctx, "grouped_shard", None))
        return orig(self)
    monkeypatch.setattr(G.GroupedRunner, "run", spy)
    r = DistributedQueryRunner("sf0.01", config=ExecutionConfig(
        grouped_lifespans=4), n_tasks=2)
    oracle = LocalQueryRunner("sf0.01")
    _assert_rows_equal(r.execute(Q3), oracle.execute_reference(Q3), True)
    assert sorted(s for s in shards if s is not None) == [(0, 2), (1, 2)]


@pytest.mark.slow
def test_sharded_fallback_when_grouped_declines(monkeypatch):
    """If the sharding predictor said yes but make_grouped_runner declines
    at runtime, shard 0 runs the ordinary full-split path and the other
    shards produce nothing — no duplicated rows either way."""
    from presto_tpu.exec import grouped as G
    from presto_tpu.exec.runner import DistributedQueryRunner
    monkeypatch.setattr(G, "make_grouped_runner", lambda *a, **k: None)
    r = DistributedQueryRunner("sf0.01", config=ExecutionConfig(
        grouped_lifespans=4), n_tasks=2)
    oracle = LocalQueryRunner("sf0.01")
    _assert_rows_equal(r.execute(Q3), oracle.execute_reference(Q3), True)


def test_stage_shards_lifespans_predictor():
    from presto_tpu.exec.grouped import stage_shards_lifespans
    from presto_tpu.sql.parser import parse_sql
    from presto_tpu.sql.planner import Planner

    def root_for(sql):
        out = Planner(default_schema="sf0.01") \
            .plan_query_to_output(parse_sql(sql))
        return out.source

    cfg = ExecutionConfig(grouped_lifespans=4)
    grouped_sql = ("select l_orderkey, sum(l_quantity) q from lineitem "
                   "group by l_orderkey")
    assert stage_shards_lifespans(root_for(grouped_sql), cfg)
    # non-bucket grouping key -> no
    assert not stage_shards_lifespans(root_for(
        "select l_partkey, sum(l_quantity) q from lineitem "
        "group by l_partkey"), cfg)
    # global aggregation (no grouping keys) -> no
    assert not stage_shards_lifespans(root_for(
        "select sum(l_quantity) q from lineitem"), cfg)
    # distinct aggregate -> no
    assert not stage_shards_lifespans(root_for(
        "select l_orderkey, count(distinct l_partkey) c from lineitem "
        "group by l_orderkey"), cfg)
    # knob off -> no
    assert not stage_shards_lifespans(
        root_for(grouped_sql),
        ExecutionConfig(grouped_lifespans=4,
                        grouped_lifespan_sharding=False))
    # lifespans forced off -> no
    assert not stage_shards_lifespans(
        root_for(grouped_sql), ExecutionConfig(grouped_lifespans=1))
