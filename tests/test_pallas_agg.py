"""Parity tests for the Pallas direct grouped-aggregation kernel
(ops/pallas_agg.py) against a numpy oracle, incl. mod-2^64 wraparound,
nulls, masks, padding (N not a multiple of the tile), and G in {1, 6, 64},
plus end-to-end query parity with ExecutionConfig(pallas_agg=True).
Runs under the Pallas interpreter on CPU."""
import numpy as np
import jax.numpy as jnp
import pytest

from presto_tpu.ops import pallas_agg
from presto_tpu.exec.runner import LocalQueryRunner
from presto_tpu.exec.pipeline import ExecutionConfig


def _oracle(cols, codes, mask, G):
    C = len(cols)
    sums = np.zeros((C, G), dtype=np.uint64)
    counts = np.zeros((C, G), dtype=np.int64)
    gcount = np.zeros(G, dtype=np.int64)
    for g in range(G):
        sel = mask & (codes == g)
        gcount[g] = sel.sum()
        for c, (v, nulls) in enumerate(cols):
            ok = sel if nulls is None else sel & ~nulls
            counts[c, g] = ok.sum()
            sums[c, g] = np.sum(v[ok].astype(np.uint64), dtype=np.uint64)
    return sums.astype(np.int64), counts, gcount


@pytest.mark.parametrize("G,N,seed", [(1, 2048, 0), (6, 4096, 1),
                                      (64, 5000, 2), (6, 100, 3)])
def test_grouped_sums_parity(G, N, seed):
    rng = np.random.default_rng(seed)
    v1 = rng.integers(-10**12, 10**12, N, dtype=np.int64)
    v2 = rng.integers(np.iinfo(np.int64).min, np.iinfo(np.int64).max, N,
                      dtype=np.int64)    # exercises mod-2^64 wraparound
    n2 = rng.random(N) < 0.3
    v3 = rng.integers(0, 100, N, dtype=np.int64)
    codes = rng.integers(0, G, N, dtype=np.int64)
    mask = rng.random(N) < 0.8
    cols = [(v1, None), (v2, n2), (v3, None)]

    sums, counts, gcount = pallas_agg.grouped_sums(
        [(jnp.asarray(v), None if n is None else jnp.asarray(n))
         for v, n in cols],
        jnp.asarray(codes), jnp.asarray(mask), G, interpret=True)

    esums, ecounts, egcount = _oracle(cols, codes, mask, G)
    np.testing.assert_array_equal(np.asarray(sums), esums)
    np.testing.assert_array_equal(np.asarray(counts), ecounts)
    np.testing.assert_array_equal(np.asarray(gcount), egcount)


def test_empty_mask():
    N, G = 2048, 4
    sums, counts, gcount = pallas_agg.grouped_sums(
        [(jnp.arange(N, dtype=jnp.int64), None)],
        jnp.zeros(N, dtype=jnp.int64), jnp.zeros(N, dtype=bool), G,
        interpret=True)
    assert not np.asarray(sums).any()
    assert not np.asarray(gcount).any()


# --- end-to-end: pallas_agg=True must match the default engine ------------

PALLAS_QUERIES = [
    # grouped integer sums/avg/count (Q1 shape)
    """SELECT returnflag, linestatus, sum(quantity) sq, avg(quantity) aq,
              count(*) c
       FROM lineitem GROUP BY returnflag, linestatus
       ORDER BY returnflag, linestatus""",
    # global aggregation (Q6 shape)
    """SELECT sum(extendedprice * discount) rev FROM lineitem
       WHERE discount BETWEEN 0.05 AND 0.07 AND quantity < 24""",
    # count(*)-only: no kernel input columns (regression: empty spec list
    # must fall back to the XLA path, not crash)
    "SELECT count(*) c FROM lineitem WHERE quantity < 10",
    "SELECT returnflag, count(*) c FROM lineitem GROUP BY returnflag "
    "ORDER BY returnflag",
]


@pytest.mark.parametrize("fuse", [True, False])
@pytest.mark.parametrize("sql", PALLAS_QUERIES)
def test_pallas_query_parity(sql, fuse):
    base = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13, fuse_pipelines=fuse))
    pall = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13, fuse_pipelines=fuse, pallas_agg=True))
    a = base.execute(sql)
    b = pall.execute(sql)
    assert a.sorted_rows() == b.sorted_rows()
