"""Thread-safety analysis conformance (tier-1): the shipped tree is
clean under the class-granular concurrency pass, every LOCK code fires
on a violating fixture and stays quiet on the compliant twin, and the
runtime half (common/locks.py OrderedLock rank validation + contention
metering) enforces at execution time exactly what LOCK004 proves
statically.

The static and dynamic halves are one feature: the checker extracts the
lock-order graph the OrderedLock ranks declare, and
``debug.lock-validation=on`` raises LockOrderError on any inversion the
checker would have flagged.
"""
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from presto_tpu.analysis.concurrency import (ALL_CONCURRENCY_CODES,
                                             LOCK_BLOCKING_HELD,
                                             LOCK_IN_CALLBACK, LOCK_ORDER,
                                             LOCK_UNGUARDED, check_or_raise,
                                             check_paths, check_source)
from presto_tpu.analysis.lint import ALL_LINT_CODES
from presto_tpu.common.errors import PlanValidationError
from presto_tpu.common.locks import (LOCK_METRICS, LockOrderError,
                                     OrderedCondition, OrderedLock,
                                     set_validation, validation_enabled,
                                     validation_scope)

REPO = Path(__file__).resolve().parent.parent


def _codes(findings):
    return {f.code for f in findings}


# ---------------------------------------------------------------------------
# the tier-1 gate: shipped tree is clean
# ---------------------------------------------------------------------------

def test_shipped_tree_is_clean():
    findings = check_paths([str(REPO / "presto_tpu")])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_module_entry_point_exit_codes(tmp_path):
    clean = subprocess.run(
        [sys.executable, "-m", "presto_tpu.analysis.concurrency",
         "presto_tpu"],
        cwd=REPO, capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    fixture = tmp_path / "bad.py"
    fixture.write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0  # lint: guarded-by(_lock)\n"
        "    def bump(self):\n"
        "        self.n += 1\n")
    bad = subprocess.run(
        [sys.executable, "-m", "presto_tpu.analysis.concurrency",
         str(fixture)],
        cwd=REPO, capture_output=True, text=True)
    assert bad.returncode == 1
    assert "LOCK001" in bad.stdout


def test_check_or_raise_routes_through_error_taxonomy(tmp_path):
    fixture = tmp_path / "bad.py"
    fixture.write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0  # lint: guarded-by(_lock)\n"
        "    def bump(self):\n"
        "        self.n += 1\n")
    with pytest.raises(PlanValidationError):
        check_or_raise([str(fixture)])


# ---------------------------------------------------------------------------
# closed vocabulary: the combined static-analysis code set
# ---------------------------------------------------------------------------

def test_concurrency_codes_are_closed_vocabulary():
    assert ALL_CONCURRENCY_CODES == ("LOCK001", "LOCK002", "LOCK003",
                                     "LOCK004")
    # lint and concurrency share one diagnostic namespace: no overlap,
    # and every code is unique across the combined vocabulary
    combined = tuple(ALL_LINT_CODES) + tuple(ALL_CONCURRENCY_CODES)
    assert len(set(combined)) == len(combined)
    assert set(ALL_LINT_CODES).isdisjoint(ALL_CONCURRENCY_CODES)


# ---------------------------------------------------------------------------
# LOCK001: guarded attribute written outside its lock
# ---------------------------------------------------------------------------

def test_unguarded_write_flagged():
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0  # lint: guarded-by(_lock)\n"
        "    def bump(self):\n"
        "        self.count += 1\n")
    assert _codes(findings) == {LOCK_UNGUARDED}


def test_guarded_write_compliant():
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0  # lint: guarded-by(_lock)\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.count += 1\n")
    assert findings == []


def test_class_form_guard_covers_all_writes():
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()  # lint: guarded-by(_lock)\n"
        "        self.a = 0\n"
        "        self.b = 0\n"
        "    def bump(self):\n"
        "        self.a += 1\n"
        "    def ok(self):\n"
        "        with self._lock:\n"
        "            self.b += 1\n")
    assert len(findings) == 1
    assert findings[0].code == LOCK_UNGUARDED
    assert "C.a" in findings[0].message


def test_locked_suffix_and_pragma_exempt():
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0  # lint: guarded-by(_lock)\n"
        "    def _bump_locked(self):\n"
        "        self.count += 1\n"
        "    def seed(self):\n"
        "        self.count = 0  # lint: allow-unguarded\n")
    assert findings == []


def test_single_lock_inference_flags_unguarded_write():
    """No annotation at all: one lock attr + an attribute written both
    under and outside it infers the guard."""
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "    def add(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "    def racy(self):\n"
        "        self.n += 1\n")
    assert _codes(findings) == {LOCK_UNGUARDED}


# ---------------------------------------------------------------------------
# LOCK002: blocking call under a held lock
# ---------------------------------------------------------------------------

def test_untimed_queue_get_under_lock_flagged():
    findings = check_source(
        "import queue\n"
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = queue.Queue()\n"
        "    def pull(self):\n"
        "        with self._lock:\n"
        "            return self._q.get()\n")
    assert _codes(findings) == {LOCK_BLOCKING_HELD}


def test_timed_queue_get_under_lock_compliant():
    findings = check_source(
        "import queue\n"
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = queue.Queue()\n"
        "    def pull(self):\n"
        "        with self._lock:\n"
        "            return self._q.get(timeout=0.5)\n")
    assert findings == []


def test_urlopen_and_device_sync_under_lock_flagged():
    findings = check_source(
        "import threading\n"
        "import urllib.request\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def fetch(self, req, x):\n"
        "        with self._lock:\n"
        "            urllib.request.urlopen(req, timeout=5)\n"
        "            return x.block_until_ready()\n")
    assert [f.code for f in findings] == [LOCK_BLOCKING_HELD,
                                          LOCK_BLOCKING_HELD]


def test_condition_wait_on_held_condition_is_exempt():
    """`cond.wait()` ON the held condition is the sanctioned CV shape."""
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "    def park(self):\n"
        "        with self._cond:\n"
        "            self._cond.wait()\n")
    assert findings == []


# ---------------------------------------------------------------------------
# LOCK003: lock acquisition in a non-blocking callback
# ---------------------------------------------------------------------------

def test_with_lock_in_registered_revoke_callback_flagged():
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self, memory):\n"
        "        self._lock = threading.Lock()\n"
        "        self._holder = memory.register_revocable(\n"
        "            'spool', self._revoke)\n"
        "    def _revoke(self):\n"
        "        with self._lock:\n"
        "            return 0\n")
    assert _codes(findings) == {LOCK_IN_CALLBACK}


def test_timed_decline_in_callback_compliant():
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self, memory):\n"
        "        self._lock = threading.Lock()\n"
        "        self._holder = memory.register_revocable(\n"
        "            'spool', self._revoke)\n"
        "    def _revoke(self):\n"
        "        if not self._lock.acquire(timeout=0.05):\n"
        "            return 0\n"
        "        try:\n"
        "            return 1\n"
        "        finally:\n"
        "            self._lock.release()\n")
    assert findings == []


def test_nonblocking_probe_in_callback_compliant():
    """acquire(blocking=False) is a bounded probe (the pipeline.py
    _RevocableBuildBuffer shape)."""
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self, memory):\n"
        "        self._lock = threading.Lock()\n"
        "        self._holder = memory.register_revocable(\n"
        "            'x', self._revoke)\n"
        "    def _revoke(self):\n"
        "        if not self._lock.acquire(blocking=False):\n"
        "            return 0\n"
        "        try:\n"
        "            return 1\n"
        "        finally:\n"
        "            self._lock.release()\n")
    assert findings == []


def test_pragma_marked_callback_flagged_without_registration():
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def on_event(self):  # lint: non-blocking-callback\n"
        "        with self._lock:\n"
        "            return 0\n")
    assert _codes(findings) == {LOCK_IN_CALLBACK}


# ---------------------------------------------------------------------------
# LOCK004: lock-order cycles / rank inversions
# ---------------------------------------------------------------------------

def test_rank_inversion_flagged():
    findings = check_source(
        "from presto_tpu.common.locks import OrderedLock\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._outer = OrderedLock('outer', 20)\n"
        "        self._inner = OrderedLock('inner', 10)\n"
        "    def run(self):\n"
        "        with self._outer:\n"
        "            with self._inner:\n"
        "                pass\n")
    assert _codes(findings) == {LOCK_ORDER}


def test_increasing_ranks_compliant():
    findings = check_source(
        "from presto_tpu.common.locks import OrderedLock\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._outer = OrderedLock('outer', 10)\n"
        "        self._inner = OrderedLock('inner', 20)\n"
        "    def run(self):\n"
        "        with self._outer:\n"
        "            with self._inner:\n"
        "                pass\n")
    assert findings == []


def test_cross_class_cycle_flagged():
    """A->B in one class, B->A in another: the edges only conflict in the
    globally combined graph."""
    findings = check_source(
        "import threading\n"
        "from presto_tpu.common.locks import OrderedLock\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._a = OrderedLock('shared-a', 10)\n"
        "        self._b = OrderedLock('shared-b', 10)\n"
        "    def run(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._b = OrderedLock('shared-b', 10)\n"
        "        self._a = OrderedLock('shared-a', 10)\n"
        "    def run(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n")
    assert LOCK_ORDER in _codes(findings)


def test_nonreentrant_self_nesting_flagged():
    findings = check_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            with self._lock:\n"
        "                pass\n")
    assert _codes(findings) == {LOCK_ORDER}


# ---------------------------------------------------------------------------
# runtime half: OrderedLock validation + metering (common/locks.py)
# ---------------------------------------------------------------------------

@pytest.fixture
def _validation_off_after():
    yield
    set_validation(False)
    LOCK_METRICS.reset()


def test_rank_inversion_raises_under_validation(_validation_off_after):
    outer = OrderedLock("t-outer", 20)
    inner = OrderedLock("t-inner", 10)
    LOCK_METRICS.reset()
    set_validation(True)
    with pytest.raises(LockOrderError) as ei:
        with outer:
            with inner:
                pass
    msg = str(ei.value)
    assert "t-inner" in msg and "t-outer" in msg
    assert "LOCK_ORDER_VIOLATION" in msg
    assert LOCK_METRICS.snapshot()["violations"] == 1
    # the raise happened BEFORE the inner lock was touched
    assert not inner.locked()
    assert not outer.locked()


def test_pass_through_when_validation_off(_validation_off_after):
    """The same seeded inversion is silent with validation off: zero
    bookkeeping on the production fast path."""
    outer = OrderedLock("t-outer2", 20)
    inner = OrderedLock("t-inner2", 10)
    LOCK_METRICS.reset()
    with outer:
        with inner:
            pass  # wrong order, nobody watching
    snap = LOCK_METRICS.snapshot()
    assert snap["violations"] == 0
    assert snap["acquisitions"] == 0


def test_validation_scope_composes(_validation_off_after):
    assert not validation_enabled()
    with validation_scope():
        assert validation_enabled()
        with validation_scope():
            assert validation_enabled()
        assert validation_enabled()
    assert not validation_enabled()


def test_reentrant_reacquisition_legal(_validation_off_after):
    lock = OrderedLock("t-reent", 30, reentrant=True)
    set_validation(True)
    with lock:
        with lock:
            assert lock.locked()


def test_ordered_condition_wait_drops_and_restores(_validation_off_after):
    """Condition.wait() releases the lock: a waiter must not poison its
    own thread's rank stack, and the notifier (taking the same rank-30
    lock) must pass."""
    cond = OrderedCondition("t-cond", 30)
    set_validation(True)
    hits = []

    def waiter():
        with cond:
            while not hits:
                cond.wait(timeout=1.0)
            hits.append("woke")

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:
        hits.append("notified")
        cond.notify_all()
    t.join(timeout=5)
    assert not t.is_alive()
    assert hits == ["notified", "woke"]
    assert LOCK_METRICS.snapshot()["violations"] == 0


def test_contention_counters_move_under_8_threads(_validation_off_after):
    """8 threads hammering one OrderedLock: acquisitions account for
    every entry, and holding the lock across real work forces contended
    acquisitions + contention wall to register."""
    LOCK_METRICS.reset()
    set_validation(True)
    lock = OrderedLock("t-contend", 10)
    n_threads, n_iters = 8, 25
    state = {"n": 0}

    def worker():
        for _ in range(n_iters):
            with lock:
                v = state["n"]
                time.sleep(0.0002)  # hold long enough to collide
                state["n"] = v + 1

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert state["n"] == n_threads * n_iters  # the lock actually excludes
    snap = LOCK_METRICS.snapshot()
    assert snap["acquisitions"] >= n_threads * n_iters
    assert snap["contended"] > 0
    assert snap["contention_wall_s"] > 0
    assert snap["hold_wall_s"] > 0
    assert snap["violations"] == 0
