"""SMILE binary transport (VERDICT r3 missing #6: the coordinator's
negotiated binary serde, application/x-jackson-smile).

Three layers: byte-level goldens hand-derived from the public SMILE
format specification (token values cited in worker/smile.py), exhaustive
round-trips over the protocol's value model, and a live worker driven
END TO END over SMILE — task update POSTed as SMILE, status/info read
back as SMILE."""
import base64
import json
import math
import threading
import time
import urllib.request

import pytest

from presto_tpu.worker import smile


# ---------------------------------------------------------------------------
# spec goldens (independent of the encoder: expected bytes written out
# longhand from the format spec)
# ---------------------------------------------------------------------------

def test_golden_simple_object():
    # header ':)\n' + flags 0x00; START_OBJECT; short-ASCII name len1
    # 'a' (0x80); small int 1 (zigzag 2 -> 0xC2); END_OBJECT
    golden = b":)\n\x00\xfa\x80a\xc2\xfb"
    assert smile.decode(golden) == {"a": 1}
    assert smile.encode({"a": 1}, shared_names=False) == golden


def test_golden_scalars():
    assert smile.decode(b":)\n\x00\x21") is None
    assert smile.decode(b":)\n\x00\x22") is False
    assert smile.decode(b":)\n\x00\x23") is True
    assert smile.decode(b":)\n\x00\x20") == ""
    # small ints: zigzag in the token byte (0xC0 + z)
    assert smile.decode(b":)\n\x00\xc0") == 0
    assert smile.decode(b":)\n\x00\xc1") == -1
    assert smile.decode(b":)\n\x00\xdf") == -16
    # 32-bit vint: 1000 -> zigzag 2000 = 0b11111010000; 7+6 split:
    # first byte 0b0011111 (0x1F), final 0b10 010000 | 0x80 = 0x90
    assert smile.decode(b":)\n\x00\x24\x1f\x90") == 1000
    # tiny ASCII value len 3: 0x42
    assert smile.decode(b":)\n\x00\x42abc") == "abc"
    # array of two values
    assert smile.decode(b":)\n\x00\xf8\xc2\xc4\xf9") == [1, 2]


def test_golden_double():
    # double 1.0: IEEE bits 0x3FF0000000000000 packed 7-bits-per-byte
    # big-endian into 10 bytes
    bits = 0x3FF0000000000000
    packed = bytes((bits >> (7 * i)) & 0x7F for i in reversed(range(10)))
    assert smile.decode(b":)\n\x00\x29" + packed) == 1.0
    assert smile.encode(1.0)[4:] == b"\x29" + packed


def test_golden_shared_names():
    # two objects in an array sharing the key 'ab': second occurrence is
    # a short shared-name reference 0x40 (index 0)
    doc = b":)\n\x01\xf8\xfa\x81ab\xc2\xfb\xfa\x40\xc4\xfb\xf9"
    assert smile.decode(doc) == [{"ab": 1}, {"ab": 2}]
    assert smile.encode([{"ab": 1}, {"ab": 2}], shared_names=True) == doc


# ---------------------------------------------------------------------------
# round trips over the protocol value model
# ---------------------------------------------------------------------------

CASES = [
    None, True, False, 0, 1, -1, 15, -16, 16, 63, 64, 1234567,
    -987654321, 2**31 - 1, -(2**31), 2**62, -(2**62), 2**70, -(2**70),
    0.0, 1.5, -3.25, math.pi, 1e300, -1e-300,
    "", "x", "a" * 32, "a" * 33, "a" * 64, "a" * 65, "a" * 500,
    "héllo", "ünïcode" * 12, "日本語テキスト",
    [], {}, [1, [2, [3, [4]]]], {"a": {"b": {"c": [None, True, 2.5]}}},
    {"taskId": "q.1.0.3.0", "fragment": base64.b64encode(
        b"PLAN" * 100).decode(),
     "sources": [{"planNodeId": "0", "splits": [
         {"sequenceId": i, "split": {"connectorId": "tpch"}}
         for i in range(5)], "noMoreSplits": True}],
     "outputIds": {"type": "PARTITIONED", "buffers": {"0": 0},
                   "noMoreBufferIds": True},
     "session": {"user": "test", "catalog": "tpch",
                 "systemProperties": {}}},
]


@pytest.mark.parametrize("shared", [True, False])
def test_round_trips(shared):
    for case in CASES:
        got = smile.decode(smile.encode(case, shared_names=shared))
        assert got == case, case


def test_shared_names_shrink_repetitive_payloads():
    doc = [{"columnName": "c", "typeSignature": "bigint"}] * 64
    shared = smile.encode(doc, shared_names=True)
    plain = smile.encode(doc, shared_names=False)
    assert smile.decode(shared) == doc == smile.decode(plain)
    assert len(shared) < len(plain) / 1.5


def test_shared_name_table_overflow_resets():
    # >1024 distinct names force a table reset mid-document; decode must
    # track the same reset the encoder performed
    doc = {f"k{i:04d}": i for i in range(1500)}
    assert smile.decode(smile.encode(doc, shared_names=True)) == doc


def test_json_compatibility_matrix():
    # anything JSON can say, SMILE must round-trip identically
    j = json.loads(json.dumps(CASES[-1]))
    assert smile.decode(smile.encode(j)) == j


# ---------------------------------------------------------------------------
# live worker over the binary transport
# ---------------------------------------------------------------------------

def test_worker_speaks_smile_end_to_end():
    """POST a task update AS SMILE, read TaskStatus/TaskInfo AS SMILE
    (Accept negotiation), pull SerializedPage results — the full binary-
    transport path a SMILE-enabled Java coordinator exercises."""
    from presto_tpu.common.block import block_to_values
    from presto_tpu.common.serde import deserialize_page
    from presto_tpu.common.types import BIGINT
    from presto_tpu.connectors import catalog as cat
    from presto_tpu.spi import plan as P
    from presto_tpu.sql.planner import Planner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    threading.Thread(target=w.httpd.serve_forever, daemon=True).start()
    try:
        out = Planner(default_schema="sf0.01", default_catalog="tpch") \
            .plan("SELECT count(*) AS n FROM nation")
        frag = P.PlanFragment(
            "0", out, P.SOURCE_DISTRIBUTION,
            P.PartitioningScheme(P.SINGLE_DISTRIBUTION, [],
                                 list(out.output_variables)),
            [n.id for n in P.walk_plan(out)
             if isinstance(n, P.TableScanNode)])
        body = {
            "taskId": "smq.0.0.0.0",
            "fragment": base64.b64encode(
                json.dumps(frag.to_dict()).encode()).decode(),
            "sources": [{"planNodeId": sid,
                         "splits": [s.to_dict() for s in
                                    cat.make_splits("nation", 0.01, 2)],
                         "noMoreSplits": True}
                        for sid in frag.partitioned_sources],
            "outputBuffers": {"type": "PARTITIONED", "nBuffers": 1,
                              "partitionKeys": []},
        }
        req = urllib.request.Request(
            f"{w.uri}/v1/task/smq.0.0.0.0",
            data=smile.encode(body), method="POST",
            headers={"Content-Type": smile.CONTENT_TYPE,
                     "Accept": smile.CONTENT_TYPE})
        resp = urllib.request.urlopen(req)
        assert resp.headers.get("Content-Type") == smile.CONTENT_TYPE
        st = smile.decode(resp.read())
        assert st["state"] in ("PLANNED", "RUNNING", "FINISHED"), st
        # long-poll status as SMILE until done
        deadline = time.time() + 120
        while time.time() < deadline:
            r = urllib.request.urlopen(urllib.request.Request(
                f"{w.uri}/v1/task/smq.0.0.0.0/status",
                headers={"Accept": smile.CONTENT_TYPE}))
            st = smile.decode(r.read())
            if st["state"] in ("FINISHED", "FAILED", "CANCELED"):
                break
            time.sleep(0.05)
        assert st["state"] == "FINISHED", st
        info = smile.decode(urllib.request.urlopen(urllib.request.Request(
            f"{w.uri}/v1/task/smq.0.0.0.0",
            headers={"Accept": smile.CONTENT_TYPE})).read())
        assert info["stats"]["outputPositions"] == 1
        # results stay SerializedPage binary regardless of transport
        data = urllib.request.urlopen(
            f"{w.uri}/v1/task/smq.0.0.0.0/results/0/0").read()
        page, _ = deserialize_page(data)
        assert block_to_values(BIGINT, page.blocks[0]) == [25]
    finally:
        w.httpd.shutdown()


def test_pack7_matches_jackson_alignment():
    """Trailing partial groups right-align per Jackson's
    _write7BitBinaryWithLength: one source byte b packs to
    [b>>1, b&0x01]; length vints carry the ORIGINAL byte count."""
    from presto_tpu.worker.smile import _pack7, _packed7_len, _unpack7
    assert _pack7(b"\x81") == bytes([0x40, 0x01])
    assert _unpack7(bytes([0x40, 0x01])) == b"\x81"
    for n in range(25):
        raw = bytes((i * 37 + 11) & 0xFF for i in range(n))
        assert len(_pack7(raw)) == _packed7_len(n)
        assert _unpack7(_pack7(raw))[:n] == raw
    # a 9-byte BigInteger magnitude must ship 11 packed bytes after a
    # length vint of 9 (the reviewer-confirmed Jackson wire shape)
    v = 2 ** 70 - 3
    enc = smile.encode(v)
    assert enc[4] == 0x26
    assert enc[5] == 0x80 | 9            # vint(9): single final byte
    assert len(enc) == 6 + 11
    assert smile.decode(enc) == v


def test_long_shared_value_refs_decode():
    """Tokens 0x2C-0x2F: 10-bit shared-string-value back references."""
    # craft a document with shared values enabled: 40 distinct strings
    # then a long ref to index 33
    body = bytearray(b":)\n\x02\xf8")
    vals = [f"s{i:02d}" for i in range(40)]
    for v in vals:
        body.append(0x40 + len(v) - 1)
        body.extend(v.encode())
    body.extend([0x2C | (33 >> 8), 33 & 0xFF])   # long ref -> vals[33]
    body.append(0xF9)
    got = smile.decode(bytes(body))
    assert got == vals + [vals[33]]
