"""Dynamic filtering (VERDICT item 5; reference
DynamicFilterSourceOperator + LocalDynamicFilter planning): inner hash
joins are annotated with per-key dynamic filters at plan time, and the
streaming executor narrows the probe stream to the build side's key
domain before probing.
"""
import pytest

from presto_tpu.exec.pipeline import ExecutionConfig, PlanCompiler, TaskContext
from presto_tpu.exec.runner import LocalQueryRunner

Q5ISH = """
SELECT n.name, sum(l.extendedprice * (1 - l.discount)) AS revenue
FROM customer c, orders o, lineitem l, supplier s, nation n, region r
WHERE c.custkey = o.custkey AND l.orderkey = o.orderkey
  AND l.suppkey = s.suppkey AND c.nationkey = s.nationkey
  AND s.nationkey = n.nationkey AND n.regionkey = r.regionkey
  AND r.name = 'ASIA' AND o.orderdate >= DATE '1994-01-01'
  AND o.orderdate < DATE '1995-01-01'
GROUP BY n.name ORDER BY revenue DESC
"""

Q17ISH = """
SELECT sum(l.extendedprice) AS total
FROM lineitem l, part p
WHERE p.partkey = l.partkey AND p.brand = 'Brand#23'
  AND p.container = 'MED BOX'
  AND l.quantity < (SELECT 0.2 * avg(l2.quantity) FROM lineitem l2
                    WHERE l2.partkey = l.partkey)
"""


def test_plan_shows_dynamic_filters():
    r = LocalQueryRunner("sf0.01")
    for sql in (Q5ISH, Q17ISH):
        plan = r.execute("EXPLAIN " + sql).rows[0][0]
        assert "dynamicFilters = [" in plan, plan


def test_streaming_probe_row_reduction():
    """With fusion off (streaming executor), the dynamic filter must both
    preserve results and measurably drop probe rows (EXPLAIN ANALYZE
    exposes dynamicFilterRowsDropped per join)."""
    cfg = ExecutionConfig(batch_rows=1 << 13, join_out_capacity=1 << 15,
                          fuse_pipelines=False)
    r = LocalQueryRunner("sf0.01", config=cfg)
    r.assert_same_as_reference(Q5ISH)

    # run the plan with per-node stats and inspect the counters
    plan = r.plan(Q5ISH)
    stats = {}
    compiler = PlanCompiler(TaskContext(config=cfg, stats=stats))
    for _ in compiler.run_to_pages(plan):
        pass
    dropped = sum(e.get("dynamicFilterRowsDropped", 0)
                  for e in stats.values())
    assert dropped > 0, f"no probe rows dropped: {stats}"
