"""Intra-task local exchange + driver concurrency (VERDICT r3 next #6;
reference LocalExchange.java:62, task_concurrency /
SqlTaskExecution.java:548 driver-per-split)."""
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu.exec.batch import Batch, Column
from presto_tpu.exec.local_exchange import (LocalExchange, background_drain, parallel_drain)
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner


def _batch(vals):
    v = np.asarray(vals, dtype=np.int64)
    return Batch({"k": Column(jnp.asarray(v))},
                 jnp.ones(len(v), dtype=bool))


def _live_keys(batches):
    out = []
    for b in batches:
        mask = np.asarray(b.mask)
        out.extend(np.asarray(b.columns["k"].values)[mask].tolist())
    return out


def test_round_robin_routes_all_batches():
    ex = LocalExchange(3, "ROUND_ROBIN")
    ex.add_producer()
    for i in range(7):
        ex.push(_batch([i]))
    ex.producer_finished()
    got = [sum(1 for _ in ex.consume(c)) for c in range(3)]
    assert sum(got) == 7
    assert max(got) - min(got) <= 1          # balanced


def test_hash_partitions_are_disjoint_and_complete():
    ex = LocalExchange(4, "HASH", keys=["k"])
    ex.add_producer()
    keys = list(range(100))
    ex.push(_batch(keys))
    ex.producer_finished()
    per_part = [set(_live_keys(ex.consume(c))) for c in range(4)]
    assert set().union(*per_part) == set(keys)
    for i in range(4):
        for j in range(i + 1, 4):
            assert not (per_part[i] & per_part[j])


def test_hash_routing_is_deterministic_per_key():
    # equal keys from DIFFERENT producers land on the same consumer —
    # the contract grouped downstreams rely on
    ex1 = LocalExchange(4, "HASH", keys=["k"])
    ex2 = LocalExchange(4, "HASH", keys=["k"])
    for ex in (ex1, ex2):
        ex.add_producer()
        ex.push(_batch(list(range(50))))
        ex.producer_finished()
    for c in range(4):
        assert sorted(_live_keys(ex1.consume(c))) \
            == sorted(_live_keys(ex2.consume(c)))


def test_broadcast_replicates():
    ex = LocalExchange(3, "BROADCAST")
    ex.add_producer()
    ex.push(_batch([1, 2]))
    ex.producer_finished()
    for c in range(3):
        assert _live_keys(ex.consume(c)) == [1, 2]


def test_parallel_drain_overlaps_sources():
    # Overlap is asserted structurally (peak simultaneous active sources
    # observed from inside the iterators), not via wall-clock
    # inequalities, which flaked under full-suite load.
    active = [0]
    peak = [0]
    lock = threading.Lock()

    def slow(n):
        def it():
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            try:
                for i in range(3):
                    time.sleep(0.05)
                    yield (n, i)
            finally:
                with lock:
                    active[0] -= 1
        return it
    stats = {}
    got = list(parallel_drain([slow(a) for a in range(4)], 4, stats))
    assert sorted(got) == sorted((a, i) for a in range(4) for i in range(3))
    assert peak[0] > 1                         # sources genuinely overlapped
    assert len(stats["driver_walls"]) == 4
    assert all(w > 0 for w in stats["driver_walls"])


def test_parallel_drain_propagates_errors():
    def boom():
        yield 1
        raise ValueError("driver failure")
    with pytest.raises(ValueError, match="driver failure"):
        list(parallel_drain([boom, boom], 2))


def test_scan_driver_concurrency_parity_and_stats():
    """task_concurrency > 1 drains scan splits on driver threads: results
    must match the serial engine, and EXPLAIN ANALYZE carries the
    per-driver walls (the measured-overlap surface)."""
    serial = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13, splits_per_scan=4))
    conc = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13, splits_per_scan=4, task_concurrency=4,
        fuse_pipelines=False))
    sql = ("select o_orderstatus, count(*), sum(o_totalprice) "
           "from orders group by o_orderstatus")
    assert conc.execute(sql).sorted_rows() \
        == serial.execute(sql).sorted_rows()
    plan = conc.execute("EXPLAIN ANALYZE " + sql).rows[0][0]
    assert "driver_walls" in plan


def test_worker_task_drain_overlap_stat():
    """A worker task with task_concurrency > 1 reports the drain-pipeline
    wall in TaskInfo — serialize overlapped it (local-exchange shape)."""
    import base64
    import json as _json
    import time as _time

    from presto_tpu.connectors import catalog as cat
    from presto_tpu.spi import plan as P
    from presto_tpu.sql.planner import Planner
    from presto_tpu.worker.protocol import (OutputBuffersSpec, TaskSource,
                                            TaskUpdateRequest)
    from presto_tpu.worker.task import TaskManager

    tm = TaskManager("http://127.0.0.1:0",
                     config=ExecutionConfig(batch_rows=1 << 13,
                                            task_concurrency=2))
    out = Planner(default_schema="sf0.01", default_catalog="tpch") \
        .plan("SELECT o_orderkey, o_totalprice FROM orders "
              "WHERE o_orderkey < 5000")
    frag = P.PlanFragment(
        "0", out, P.SOURCE_DISTRIBUTION,
        P.PartitioningScheme(P.SINGLE_DISTRIBUTION, [],
                             list(out.output_variables)),
        [n.id for n in P.walk_plan(out)
         if isinstance(n, P.TableScanNode)])
    splits = [s.to_dict() for s in cat.make_splits("orders", 0.01, 4)]
    upd = TaskUpdateRequest.make(
        "lxq.0.0.0.0", 0, frag,
        [TaskSource.from_dict({"planNodeId": sid, "splits": splits,
                               "noMoreSplits": True})
         for sid in frag.partitioned_sources],
        OutputBuffersSpec("PARTITIONED", 1))
    tm.create_or_update(upd)
    t = tm.get("lxq.0.0.0.0")
    deadline = _time.time() + 120
    while t.state not in ("FINISHED", "FAILED") and _time.time() < deadline:
        _time.sleep(0.05)
    assert t.state == "FINISHED", t.failures
    assert t.info()["stats"]["drainPipelineWallS"] > 0


def test_parallel_drain_early_consumer_exit_unblocks_drivers():
    """A consumer that stops pulling (downstream LIMIT) must not leave
    driver threads blocked on the exchange forever."""
    import threading
    before = threading.active_count()

    def source(n):
        def it():
            for i in range(100):
                yield (n, i)
        return it
    gen = parallel_drain([source(a) for a in range(4)], 4)
    got = [next(gen) for _ in range(3)]
    gen.close()                       # early exit
    assert len(got) == 3
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before + 1   # drivers exited


def test_background_drain_close_stops_producer():
    import threading
    before = threading.active_count()

    def it():
        for i in range(1000):
            yield i
    wall = [0.0]
    gen = background_drain(it(), wall_out=wall)
    assert next(gen) == 0
    gen.close()
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before + 1
