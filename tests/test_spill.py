"""Memory discipline + spill tests (SURVEY.md §7 build step 7; reference:
MemoryPool.java:46, spiller/, grouped-execution Lifespans).  A tiny HBM
budget forces the grace hash join and the partitioned (host-staged)
aggregation; results must stay identical to the unconstrained engine and
the numpy reference."""
import pytest

from presto_tpu.exec.memory import (MemoryExceededError, MemoryPool,
                                    batch_bytes)
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner

TINY = dict(batch_rows=1 << 14, join_out_capacity=1 << 16,
            memory_budget_bytes=200_000, spill_partitions=4)


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01", config=ExecutionConfig(**TINY))


def check(runner, sql, ordered=False):
    return runner.assert_same_as_reference(sql, ordered=ordered)


# ---------------------------------------------------------------------------
# pool accounting
# ---------------------------------------------------------------------------

def test_pool_reserve_free_peak():
    p = MemoryPool(budget=100)
    assert p.try_reserve(60) and p.try_reserve(40)
    assert not p.try_reserve(1)
    p.free(50)
    assert p.try_reserve(30)
    assert p.peak == 100
    with pytest.raises(MemoryExceededError):
        p.reserve(1000)


def test_pool_unlimited_tracks_peak():
    p = MemoryPool()
    assert p.try_reserve(10 ** 12)
    assert p.peak == 10 ** 12


# ---------------------------------------------------------------------------
# forced spill, engine vs reference
# ---------------------------------------------------------------------------

def test_grace_join_inner(runner):
    check(runner, """
        select l_orderkey, o_orderdate, l_quantity from lineitem
        join orders on l_orderkey = o_orderkey
        where l_orderkey < 1000""")


def test_grace_join_left_null_extension(runner):
    check(runner, """
        select c_custkey, o_orderkey from customer
        left join orders on c_custkey = o_custkey
        where c_custkey < 500""")


def test_grace_join_with_filter(runner):
    check(runner, """
        select l_orderkey, l_suppkey from lineitem
        join orders on l_orderkey = o_orderkey
        where o_orderdate < date '1995-01-01' and l_quantity > 45""")


def test_spilled_aggregation_small_groups(runner):
    check(runner, """
        select o_orderstatus, count(*), sum(o_totalprice), avg(o_totalprice)
        from orders group by o_orderstatus""")


def test_spilled_aggregation_high_cardinality(runner):
    check(runner, """
        select l_orderkey, count(*), sum(l_quantity)
        from lineitem group by l_orderkey""")


def test_spilled_aggregation_string_keys(runner):
    # lazy open-domain key (clerk) must be whole-column encoded BEFORE the
    # spill partitioner hashes it, or value groups split across buckets
    res = check(runner, """
        select o_clerk, count(*) from orders group by o_clerk""")
    assert len(res.rows) <= 30


def test_tpch_q3_under_budget(runner):
    check(runner, """
        select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
               o_orderdate, o_shippriority
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
          and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
          and l_shipdate > date '1995-03-15'
        group by l_orderkey, o_orderdate, o_shippriority
        order by revenue desc, o_orderdate limit 10""", ordered=True)


def test_tpcds_q95_under_budget():
    # BASELINE config 5: the spill-stressing shape on the tpcds connector
    r = LocalQueryRunner("sf0.01", catalog="tpcds",
                         config=ExecutionConfig(**TINY))
    r.assert_same_as_reference("""
        with ws_wh as
         (select ws1.ws_order_number
          from web_sales ws1, web_sales ws2
          where ws1.ws_order_number = ws2.ws_order_number
            and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
        select count(distinct ws_order_number),
               sum(ws_ext_ship_cost), sum(ws_net_profit)
        from web_sales ws1, date_dim, customer_address, web_site
        where d_date between date '1999-02-01' and date '2002-12-31'
          and ws1.ws_ship_date_sk = d_date_sk
          and ws1.ws_ship_addr_sk = ca_address_sk
          and ca_state = 'IL'
          and ws1.ws_web_site_sk = web_site_sk
          and ws1.ws_order_number in (select ws_order_number from ws_wh)
          and ws1.ws_order_number in
              (select wr_order_number from web_returns, ws_wh
               where wr_order_number = ws_wh.ws_order_number)
        order by 1 limit 100""")


def test_global_percentile_streams_under_budget(runner):
    # the input (lineitem.l_extendedprice at sf0.01, ~60k rows) exceeds
    # the 200KB budget: the streaming m-point quantile summary path must
    # produce the same nearest-rank answer as the unconstrained engine
    # (rank error 1/(2m) rounds away below m rows per batch)
    free = LocalQueryRunner("sf0.01")
    sql = ("select approx_percentile(l_extendedprice, 0.5), count(*), "
           "sum(l_quantity) from lineitem")
    got = runner.execute(sql)
    want = free.execute(sql)
    assert got.rows[0][1:] == want.rows[0][1:]
    assert abs(float(got.rows[0][0]) - float(want.rows[0][0])) \
        <= 1e-9 * abs(float(want.rows[0][0]))


def test_global_percentile_stream_composes_downstream(runner):
    # the streamed-percentile output batch must keep the engine's
    # uniform-capacity invariant so downstream operators (sort) compose
    free = LocalQueryRunner("sf0.01")
    sql = ("select approx_percentile(l_extendedprice, 0.5) p, count(*) c "
           "from lineitem order by p")
    got = runner.execute(sql)
    want = free.execute(sql)
    assert got.rows[0][1] == want.rows[0][1]
    assert abs(float(got.rows[0][0]) - float(want.rows[0][0])) \
        <= 1e-9 * abs(float(want.rows[0][0]))


def test_grouped_percentile_spills_exact(runner):
    # grouped percentile over budget: bucket-by-bucket sort aggregation
    # over the key-partitioned spill store is EXACT (disjoint key sets)
    free = LocalQueryRunner("sf0.01")
    sql = ("select l_returnflag, approx_percentile(l_quantity, 0.5), "
           "count(*) from lineitem group by l_returnflag")
    got = runner.execute(sql)
    want = free.execute(sql)
    assert got.sorted_rows() == want.sorted_rows()


def test_spill_disabled_raises():
    cfg = ExecutionConfig(batch_rows=1 << 14, memory_budget_bytes=50_000,
                          spill_enabled=False)
    r = LocalQueryRunner("sf0.01", config=cfg)
    with pytest.raises(MemoryExceededError):
        r.execute("select l_orderkey, o_orderdate from lineitem "
                  "join orders on l_orderkey = o_orderkey")


def test_worker_task_reports_memory():
    # TaskStatus carries the task's peak reservation
    # (reference TaskStatus.memoryReservationInBytes feeding the
    # coordinator's cluster memory manager)
    from presto_tpu.exec.runner import DistributedQueryRunner
    r = DistributedQueryRunner("sf0.01", n_tasks=2)
    res = r.execute("select count(*) from lineitem")
    assert res.rows[0][0] > 0


def test_no_reservation_leak_on_failure():
    # a failed over-budget run must not poison the pool for retries
    cfg = ExecutionConfig(batch_rows=1 << 14, memory_budget_bytes=150_000,
                          spill_enabled=False)
    r = LocalQueryRunner("sf0.01", config=cfg)
    sql = ("select c_custkey, o_orderkey from customer "
           "join orders on c_custkey = o_custkey")
    for _ in range(2):
        with pytest.raises(MemoryExceededError):
            r.execute(sql)
    # small queries still fit afterwards (pool fully freed)
    ok = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 14, memory_budget_bytes=150_000))
    assert ok.execute("select count(*) from region").rows == [[5]]


# ---------------------------------------------------------------------------
# revocable arbitration: budget < 25% of the measured unconstrained peak
# ---------------------------------------------------------------------------

# q18 core: lineitem<->orders hash join feeding a high-cardinality
# grouped aggregation — join build AND agg state scale with the data
Q18_SHAPE = """
    select l_orderkey, max(o_totalprice) as price, sum(l_quantity) as qty
    from lineitem join orders on l_orderkey = o_orderkey
    group by l_orderkey
    order by qty desc, l_orderkey limit 100"""

# q95 core: the ws_wh self-join (same order shipped from two warehouses)
# plus a grouped count — the spill-stressing shape of TPC-DS Q95
Q95_CORE = """
    select ws1.ws_order_number, count(*) as pairs
    from web_sales ws1, web_sales ws2
    where ws1.ws_order_number = ws2.ws_order_number
      and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk
    group by ws1.ws_order_number
    order by pairs desc, ws1.ws_order_number limit 100"""


def _constrained_vs_free(schema, sql, catalog="tpch", fraction=0.2):
    """Run unconstrained to measure the peak pool reservation, then re-run
    under fraction*peak (< the 25%% acceptance bar) and require the exact
    same rows — and the numpy reference's rows — from the arbitrated run."""
    import dataclasses
    base = ExecutionConfig(batch_rows=1 << 14, spill_partitions=4)
    free = LocalQueryRunner(schema, catalog=catalog, config=base)
    want = free.execute(sql)
    peak = want.peak_memory_bytes
    assert peak and peak > 0, "unconstrained run recorded no peak"
    budget = max(1, int(peak * fraction))
    con = LocalQueryRunner(schema, catalog=catalog,
                           config=dataclasses.replace(
                               base, memory_budget_bytes=budget))
    got = con.execute(sql)
    assert got.rows == want.rows
    con.assert_same_as_reference(sql, ordered=True)
    return got


def test_q18_shape_quarter_peak_bit_identical():
    from presto_tpu.exec.memory import MEMORY_METRICS
    before = MEMORY_METRICS.snapshot()
    _constrained_vs_free("sf0.01", Q18_SHAPE)
    after = MEMORY_METRICS.snapshot()
    # the budget actually forced eviction (not a silently-fitting run)
    assert after["spilled_bytes"] > before["spilled_bytes"]


def test_q95_core_quarter_peak_bit_identical():
    _constrained_vs_free("sf0.01", Q95_CORE, catalog="tpcds")


def test_join_build_revocation_under_cross_pressure():
    """An in-flight join build holds revocable memory; pressure from a
    CONCURRENT operator's non-revocable reserve makes the arbitrator
    revoke it — the build converts to its grace-join spill store instead
    of the reserve raising — and the build keeps accepting batches."""
    import jax.numpy as jnp

    from presto_tpu.exec.batch import Batch, Column
    from presto_tpu.exec.memory import MEMORY_METRICS
    from presto_tpu.exec.pipeline import (PlanCompiler, TaskContext,
                                          _RevocableBuildBuffer)

    before = MEMORY_METRICS.snapshot()
    ctx = TaskContext(config=ExecutionConfig(
        batch_rows=1 << 12, spill_partitions=4, spill_async_staging=False,
        memory_budget_bytes=300_000))
    compiler = PlanCompiler(ctx)
    pool = ctx.memory
    n = 4096

    def mk(i):
        v = jnp.arange(n, dtype=jnp.int64) + i * n
        return Batch({"k": Column(v)}, jnp.ones(n, dtype=bool))

    buf = _RevocableBuildBuffer(compiler, ["k"], spill_enabled=True)
    try:
        buf.add(mk(0))
        buf.add(mk(1))
        assert buf._holder.bytes > 0 and buf.spill is None
        # the other operator's reserve does not fit alongside the build:
        # arbitration revokes the build rather than raising
        pool.reserve(250_000)
        after = MEMORY_METRICS.snapshot()
        assert after["revocations"] > before["revocations"]
        assert after["revoked_bytes"] > before["revoked_bytes"]
        assert buf.spill is not None and buf._holder.bytes == 0
        # post-revocation adds route to the store; finish hands the
        # spilled rows to the grace-join path with nothing lost
        buf.add(mk(2))
        collected, spill = buf.finish()
        assert collected == [] and spill is not None
        assert sum(spill.rows) == 3 * n
        pool.free(250_000)
    finally:
        buf.close()
    assert pool.reserved == 0 and pool.revocable == 0


def test_engine_query_engages_arbitrator():
    """End-to-end: a budget-constrained join+agg actually drives the
    arbitration path (the counters move) while staying correct."""
    from presto_tpu.exec.memory import MEMORY_METRICS
    before = MEMORY_METRICS.snapshot()
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(**TINY))
    r.assert_same_as_reference("""
        select l_orderkey, max(o_totalprice), sum(l_quantity)
        from lineitem join orders on l_orderkey = o_orderkey
        group by l_orderkey""")
    after = MEMORY_METRICS.snapshot()
    assert after["arbitrations"] > before["arbitrations"]
    assert after["spilled_bytes"] > before["spilled_bytes"]


def test_async_staging_reports_nonzero_overlap():
    """Double-buffered eviction: with operator compute between adds the
    producer never blocks on the two staging slots, so the overlap
    fraction (1 - producer wait / staging wall) is positive."""
    import time

    import jax.numpy as jnp

    from presto_tpu.exec.batch import Batch, Column
    from presto_tpu.exec.memory import MEMORY_METRICS, PartitionedSpillStore

    before = MEMORY_METRICS.snapshot()
    store = PartitionedSpillStore(2, async_staging=True)
    for i in range(6):
        v = jnp.arange(4096, dtype=jnp.int64) + i
        store.add(Batch({"k": Column(v)}, jnp.ones(4096, dtype=bool)), ["k"])
        time.sleep(0.01)          # the "operator compute" between evictions
    store.drain()
    after = MEMORY_METRICS.snapshot()
    stage = after["spill_wall_s"] - before["spill_wall_s"]
    wait = after["spill_wait_wall_s"] - before["spill_wait_wall_s"]
    assert after["spilled_bytes"] > before["spilled_bytes"]
    assert stage > 0
    assert max(0.0, 1.0 - wait / stage) > 0


def test_spill_store_disk_tier_roundtrip(tmp_path):
    """Past the host budget the largest bucket overflows to LZ4 disk
    chunks; bucket_batches re-reads them bit-identical, in chunk order."""
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.exec.batch import Batch, Column
    from presto_tpu.exec.memory import PartitionedSpillStore

    store = PartitionedSpillStore(2, budget_bytes=40_000,
                                  spill_path=str(tmp_path))
    n = 2048
    for i in range(8):
        v = jnp.arange(n, dtype=jnp.int64) + i * n
        store.add(Batch({"k": Column(v)}, jnp.ones(n, dtype=bool)), ["k"])
    assert store.disk_bytes > 0, "host budget never overflowed to disk"
    got = sorted(int(x) for p in range(2)
                 for b in store.bucket_batches(p, 4096)
                 for x in np.asarray(b.columns["k"].values)[np.asarray(b.mask)])
    assert got == list(range(8 * n))
    assert store.unspilled_bytes > 0


def test_query_max_memory_is_typed_user_error():
    """query.max-memory is the fail-fast USER limit: no arbitration, no
    spill rescue — the typed EXCEEDED_MEMORY_LIMIT error surfaces even
    though spill is enabled and the pool itself is unlimited."""
    from presto_tpu.common.errors import is_retryable
    from presto_tpu.exec.memory import QueryMemoryLimitExceededError
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 14, memory_max_query_bytes=50_000))
    with pytest.raises(QueryMemoryLimitExceededError) as ei:
        r.execute("select l_orderkey, o_orderdate from lineitem "
                  "join orders on l_orderkey = o_orderkey")
    assert "EXCEEDED_MEMORY_LIMIT" in str(ei.value)
    assert not is_retryable(ei.value)


def test_pool_over_free_is_counted_not_clamped_silently():
    """Satellite: MemoryPool.free of more than reserved used to clamp to
    zero silently; now the mismatch is a counted accounting bug."""
    from presto_tpu.exec.memory import MEMORY_METRICS
    before = MEMORY_METRICS.snapshot()
    p = MemoryPool(budget=1000)
    assert p.try_reserve(100)
    p.free(250)
    assert p.reserved == 0                      # still clamped (no negatives)
    assert p.over_free_count == 1
    assert p.over_free_bytes == 150
    after = MEMORY_METRICS.snapshot()
    assert after["over_free"] - before["over_free"] == 1
    assert after["over_free_bytes"] - before["over_free_bytes"] == 150


def test_revocable_bytes_exempt_from_query_limit():
    """Revocable reservations are the engine's to reclaim — they must not
    count against the user's query.max-memory footprint."""
    from presto_tpu.exec.memory import MemoryContext
    ctx = MemoryContext(MemoryPool(), "query", max_bytes=100)
    h = ctx.register_revocable("build", lambda: 0)
    assert h.try_reserve(10_000)                # revocable: over the limit, OK
    with pytest.raises(MemoryExceededError):
        ctx.reserve(200)                        # reserved: limit enforced
    h.close()


def test_arbitration_stress_tiny_shared_pool_no_deadlock():
    """Many threads hammer one tiny pool with revocable holders whose
    callbacks take their own locks (the join-build shape) while others
    decline (the agg shape): every thread must finish — no deadlock —
    and the pool must drain back to zero."""
    import threading

    from presto_tpu.exec.memory import MemoryContext

    root = MemoryContext(MemoryPool(budget=64_000), "query")
    errors = []

    def worker(idx):
        try:
            ctx = root.new_child(f"task/{idx}")
            for round_no in range(30):
                state_lock = threading.Lock()
                state = {"bytes": 0}

                def revoke():
                    # join-build style: non-blocking self-lock, spill all
                    if not state_lock.acquire(blocking=False):
                        return 0
                    try:
                        freed = state["bytes"]
                        state["bytes"] = 0
                        return freed
                    finally:
                        state_lock.release()

                cb = revoke if idx % 2 == 0 else (lambda: 0)
                h = ctx.register_revocable(f"holder/{idx}", cb)
                for _ in range(10):
                    nb = 1000 + idx * 37
                    if h.try_reserve(nb):
                        with state_lock:
                            state["bytes"] += nb
                    if h.revoke_requested:
                        with state_lock:
                            gone = state["bytes"]
                            state["bytes"] = 0
                        h.free(gone)
                h.close()
        except Exception as e:                  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "arbitration deadlocked"
    assert not errors, errors
    assert root.pool.reserved == 0
    assert root.pool.revocable == 0


def test_concurrent_constrained_queries_no_deadlock():
    """Several budgeted queries spilling at once: all complete with
    reference-correct rows (process-wide metrics locks + per-query
    arbitration never interlock)."""
    import threading

    sqls = [
        "select l_orderkey, o_totalprice from lineitem "
        "join orders on l_orderkey = o_orderkey where l_quantity > 48",
        "select l_orderkey, count(*), sum(l_quantity) from lineitem "
        "group by l_orderkey",
        "select o_orderstatus, count(*) from orders group by o_orderstatus",
    ]
    errors = []

    def run(sql):
        try:
            r = LocalQueryRunner("sf0.01", config=ExecutionConfig(**TINY))
            r.assert_same_as_reference(sql)
        except Exception as e:
            errors.append((sql, e))

    threads = [threading.Thread(target=run, args=(s,)) for s in sqls]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads), "concurrent spill hung"
    assert not errors, errors


def test_plan_cache_not_poisoned_and_bounded():
    from presto_tpu.serving import PlanCache
    cache = PlanCache(max_entries=8)
    r = LocalQueryRunner("sf0.01", plan_cache=cache)
    for i in range(70):
        r.execute(f"select count(*) from region where r_regionkey < {i % 7}")
    info = cache.info()
    assert info["entries"] <= cache.max_entries
    # the literal is parameterized out, so all 70 share ONE canonical
    # entry: everything after the first execution is a hit
    assert info["hits"] >= 60
    # repeated executes reuse one compiler (warm path)
    a = r.execute("select count(*) from nation")
    b = r.execute("select count(*) from nation")
    assert a.rows == b.rows == [[25]]


# ---------------------------------------------------------------------------
# distributed: revocation observability + chaos mid-spill
# ---------------------------------------------------------------------------

SPILL_CHAOS_SQL = ("select l_orderkey, max(o_totalprice), sum(l_quantity) "
                   "from lineitem join orders on l_orderkey = o_orderkey "
                   "group by l_orderkey")

SPILL_SESSION = {"query_max_memory_per_node": "200kB",
                 "task_batch_rows": "16384",
                 "spill_partitions": "4"}


def _http_metric(uri, name):
    import urllib.request
    with urllib.request.urlopen(uri + "/v1/metrics", timeout=5) as r:
        text = r.read().decode()
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return None


def test_revocation_observable_over_http():
    """Acceptance: a revoked/spilled query is observable end to end — the
    per-task TaskInfo carries spilledBytes > 0, the EXPLAIN ANALYZE footer
    prints the Spilled line, and the worker's Prometheus surface exports
    presto_tpu_memory_spilled_bytes_total."""
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    try:
        r = HttpQueryRunner([w.uri], "sf0.01", n_tasks=1,
                            session=SPILL_SESSION)
        text = r.execute("EXPLAIN ANALYZE " + SPILL_CHAOS_SQL).rows[0][0]
        assert "Spilled:" in text
        info = r.last_query_info
        assert info is not None
        task_spilled = sum(
            t["stats"]["spilledBytes"]
            for st in info["stages"] for t in st["tasks"])
        assert task_spilled > 0
        assert _http_metric(
            w.uri, "presto_tpu_memory_spilled_bytes_total") > 0
    finally:
        w.close()


def test_chaos_worker_killed_mid_spill_recovers():
    """A worker dying AFTER eviction has started (memory-constrained
    session, every join task spills its build) must not lose or duplicate
    rows: the coordinator reschedules the dead worker's tasks on the
    survivors under .rN lineage ids, the retried tasks redo their spill
    from scratch, and the results match the oracle exactly once."""
    import threading
    import time

    from presto_tpu.common.errors import InjectedTaskFailure
    from presto_tpu.exec.memory import MEMORY_METRICS
    from presto_tpu.exec.runner import LocalQueryRunner as _LQR
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.server import WorkerServer

    w1, w2, w3 = WorkerServer(), WorkerServer(), WorkerServer()
    base_spilled = MEMORY_METRICS.snapshot()["spilled_bytes"]
    killed = threading.Event()

    def kill_once_spilling(task_id):
        # all three workers share this process, so the process-global
        # spill counter moving means some sibling task is mid-eviction;
        # wait (bounded) for that moment, then die under this task start
        if killed.is_set():
            return
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if MEMORY_METRICS.snapshot()["spilled_bytes"] > base_spilled:
                killed.set()
                threading.Thread(target=w2.close, daemon=True).start()
                raise InjectedTaskFailure(
                    f"chaos: worker dying mid-spill under task {task_id}")
            time.sleep(0.005)

    w2.task_manager.fault_injector = kill_once_spilling
    try:
        r = HttpQueryRunner(
            [w1.uri, w2.uri, w3.uri], "sf0.01", n_tasks=2,
            session={**SPILL_SESSION,
                     "exchange_max_error_duration": "5s"})
        got = r.execute(SPILL_CHAOS_SQL)
        assert killed.is_set(), "chaos hook never fired mid-spill"
        assert r.tasks_retried >= 1
        retried = sum(w.task_manager.tasks_retried for w in (w1, w3))
        assert retried >= 1
        want = _LQR("sf0.01").execute(SPILL_CHAOS_SQL)
        from presto_tpu.exec.runner import _assert_rows_equal
        _assert_rows_equal(got, want, False)
    finally:
        for w in (w1, w2, w3):
            w.close()
