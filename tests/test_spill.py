"""Memory discipline + spill tests (SURVEY.md §7 build step 7; reference:
MemoryPool.java:46, spiller/, grouped-execution Lifespans).  A tiny HBM
budget forces the grace hash join and the partitioned (host-staged)
aggregation; results must stay identical to the unconstrained engine and
the numpy reference."""
import pytest

from presto_tpu.exec.memory import (MemoryExceededError, MemoryPool,
                                    batch_bytes)
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner

TINY = dict(batch_rows=1 << 14, join_out_capacity=1 << 16,
            memory_budget_bytes=200_000, spill_partitions=4)


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01", config=ExecutionConfig(**TINY))


def check(runner, sql, ordered=False):
    return runner.assert_same_as_reference(sql, ordered=ordered)


# ---------------------------------------------------------------------------
# pool accounting
# ---------------------------------------------------------------------------

def test_pool_reserve_free_peak():
    p = MemoryPool(budget=100)
    assert p.try_reserve(60) and p.try_reserve(40)
    assert not p.try_reserve(1)
    p.free(50)
    assert p.try_reserve(30)
    assert p.peak == 100
    with pytest.raises(MemoryExceededError):
        p.reserve(1000)


def test_pool_unlimited_tracks_peak():
    p = MemoryPool()
    assert p.try_reserve(10 ** 12)
    assert p.peak == 10 ** 12


# ---------------------------------------------------------------------------
# forced spill, engine vs reference
# ---------------------------------------------------------------------------

def test_grace_join_inner(runner):
    check(runner, """
        select l_orderkey, o_orderdate, l_quantity from lineitem
        join orders on l_orderkey = o_orderkey
        where l_orderkey < 1000""")


def test_grace_join_left_null_extension(runner):
    check(runner, """
        select c_custkey, o_orderkey from customer
        left join orders on c_custkey = o_custkey
        where c_custkey < 500""")


def test_grace_join_with_filter(runner):
    check(runner, """
        select l_orderkey, l_suppkey from lineitem
        join orders on l_orderkey = o_orderkey
        where o_orderdate < date '1995-01-01' and l_quantity > 45""")


def test_spilled_aggregation_small_groups(runner):
    check(runner, """
        select o_orderstatus, count(*), sum(o_totalprice), avg(o_totalprice)
        from orders group by o_orderstatus""")


def test_spilled_aggregation_high_cardinality(runner):
    check(runner, """
        select l_orderkey, count(*), sum(l_quantity)
        from lineitem group by l_orderkey""")


def test_spilled_aggregation_string_keys(runner):
    # lazy open-domain key (clerk) must be whole-column encoded BEFORE the
    # spill partitioner hashes it, or value groups split across buckets
    res = check(runner, """
        select o_clerk, count(*) from orders group by o_clerk""")
    assert len(res.rows) <= 30


def test_tpch_q3_under_budget(runner):
    check(runner, """
        select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
               o_orderdate, o_shippriority
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
          and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
          and l_shipdate > date '1995-03-15'
        group by l_orderkey, o_orderdate, o_shippriority
        order by revenue desc, o_orderdate limit 10""", ordered=True)


def test_tpcds_q95_under_budget():
    # BASELINE config 5: the spill-stressing shape on the tpcds connector
    r = LocalQueryRunner("sf0.01", catalog="tpcds",
                         config=ExecutionConfig(**TINY))
    r.assert_same_as_reference("""
        with ws_wh as
         (select ws1.ws_order_number
          from web_sales ws1, web_sales ws2
          where ws1.ws_order_number = ws2.ws_order_number
            and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
        select count(distinct ws_order_number),
               sum(ws_ext_ship_cost), sum(ws_net_profit)
        from web_sales ws1, date_dim, customer_address, web_site
        where d_date between date '1999-02-01' and date '2002-12-31'
          and ws1.ws_ship_date_sk = d_date_sk
          and ws1.ws_ship_addr_sk = ca_address_sk
          and ca_state = 'IL'
          and ws1.ws_web_site_sk = web_site_sk
          and ws1.ws_order_number in (select ws_order_number from ws_wh)
          and ws1.ws_order_number in
              (select wr_order_number from web_returns, ws_wh
               where wr_order_number = ws_wh.ws_order_number)
        order by 1 limit 100""")


def test_global_percentile_streams_under_budget(runner):
    # the input (lineitem.l_extendedprice at sf0.01, ~60k rows) exceeds
    # the 200KB budget: the streaming m-point quantile summary path must
    # produce the same nearest-rank answer as the unconstrained engine
    # (rank error 1/(2m) rounds away below m rows per batch)
    free = LocalQueryRunner("sf0.01")
    sql = ("select approx_percentile(l_extendedprice, 0.5), count(*), "
           "sum(l_quantity) from lineitem")
    got = runner.execute(sql)
    want = free.execute(sql)
    assert got.rows[0][1:] == want.rows[0][1:]
    assert abs(float(got.rows[0][0]) - float(want.rows[0][0])) \
        <= 1e-9 * abs(float(want.rows[0][0]))


def test_global_percentile_stream_composes_downstream(runner):
    # the streamed-percentile output batch must keep the engine's
    # uniform-capacity invariant so downstream operators (sort) compose
    free = LocalQueryRunner("sf0.01")
    sql = ("select approx_percentile(l_extendedprice, 0.5) p, count(*) c "
           "from lineitem order by p")
    got = runner.execute(sql)
    want = free.execute(sql)
    assert got.rows[0][1] == want.rows[0][1]
    assert abs(float(got.rows[0][0]) - float(want.rows[0][0])) \
        <= 1e-9 * abs(float(want.rows[0][0]))


def test_grouped_percentile_spills_exact(runner):
    # grouped percentile over budget: bucket-by-bucket sort aggregation
    # over the key-partitioned spill store is EXACT (disjoint key sets)
    free = LocalQueryRunner("sf0.01")
    sql = ("select l_returnflag, approx_percentile(l_quantity, 0.5), "
           "count(*) from lineitem group by l_returnflag")
    got = runner.execute(sql)
    want = free.execute(sql)
    assert got.sorted_rows() == want.sorted_rows()


def test_spill_disabled_raises():
    cfg = ExecutionConfig(batch_rows=1 << 14, memory_budget_bytes=50_000,
                          spill_enabled=False)
    r = LocalQueryRunner("sf0.01", config=cfg)
    with pytest.raises(MemoryExceededError):
        r.execute("select l_orderkey, o_orderdate from lineitem "
                  "join orders on l_orderkey = o_orderkey")


def test_worker_task_reports_memory():
    # TaskStatus carries the task's peak reservation
    # (reference TaskStatus.memoryReservationInBytes feeding the
    # coordinator's cluster memory manager)
    from presto_tpu.exec.runner import DistributedQueryRunner
    r = DistributedQueryRunner("sf0.01", n_tasks=2)
    res = r.execute("select count(*) from lineitem")
    assert res.rows[0][0] > 0


def test_no_reservation_leak_on_failure():
    # a failed over-budget run must not poison the pool for retries
    cfg = ExecutionConfig(batch_rows=1 << 14, memory_budget_bytes=150_000,
                          spill_enabled=False)
    r = LocalQueryRunner("sf0.01", config=cfg)
    sql = ("select c_custkey, o_orderkey from customer "
           "join orders on c_custkey = o_custkey")
    for _ in range(2):
        with pytest.raises(MemoryExceededError):
            r.execute(sql)
    # small queries still fit afterwards (pool fully freed)
    ok = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 14, memory_budget_bytes=150_000))
    assert ok.execute("select count(*) from region").rows == [[5]]


def test_plan_cache_not_poisoned_and_bounded():
    from presto_tpu.serving import PlanCache
    cache = PlanCache(max_entries=8)
    r = LocalQueryRunner("sf0.01", plan_cache=cache)
    for i in range(70):
        r.execute(f"select count(*) from region where r_regionkey < {i % 7}")
    info = cache.info()
    assert info["entries"] <= cache.max_entries
    # the literal is parameterized out, so all 70 share ONE canonical
    # entry: everything after the first execution is a hit
    assert info["hits"] >= 60
    # repeated executes reuse one compiler (warm path)
    a = r.execute("select count(*) from nation")
    b = r.execute("select count(*) from nation")
    assert a.rows == b.rows == [[25]]
