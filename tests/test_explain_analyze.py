"""EXPLAIN ANALYZE / observability spine (tier-1).

Covers the four legs of the operator-stats work:
  - fused-path ANALYZE: the fused chain's device-side row counters agree
    with the interpreted (analyze_unfused) per-node instrumentation
  - distributed ANALYZE: every fragment of the 2-task plan is annotated
    from the task-rolled-up operator stats
  - tracer SPI: the query -> fragment -> task -> operator span hierarchy
    recorded by SimpleTracer
  - /v1/query/{id}: the QueryInfo surface over a real loopback cluster
    (trace token, stage/task/operator breakdown, process metrics)
"""
import json
import re
import time
import urllib.request

import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import DistributedQueryRunner, LocalQueryRunner
from presto_tpu.utils.runtime_stats import SimpleTracer, TracerProvider

from test_queries import TPCH_Q1, TPCH_Q6


# ---------------------------------------------------------------------------
# fused vs unfused ANALYZE parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sql", [TPCH_Q1, TPCH_Q6], ids=["q1", "q6"])
def test_fused_vs_unfused_analyze_row_parity(sql):
    """ANALYZE over the fused path reports the same per-node row counts as
    the old interpreted instrumentation — the device-side counters riding
    the jitted program are exact, not estimates."""
    cfg = dict(batch_rows=1 << 13)
    fused = LocalQueryRunner("sf0.01", config=ExecutionConfig(**cfg))
    unfused = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        analyze_unfused=True, **cfg))
    text_f = fused.execute("EXPLAIN ANALYZE " + sql).rows[0][0]
    text_u = unfused.execute("EXPLAIN ANALYZE " + sql).rows[0][0]
    assert "[fused]" in text_f          # the fused chain actually ran
    assert "[fused]" not in text_u      # the knob retains the old path
    sf, su = fused.last_operator_stats, unfused.last_operator_stats
    shared = set(sf) & set(su)
    assert shared, "no common instrumented nodes between the two paths"
    for nid in shared:
        assert sf[nid]["rows"] == su[nid]["rows"], nid
    for s in sf.values():
        assert s["rows"] >= 0 and s["wall_s"] >= 0 and s["batches"] >= 1


def test_analyze_footer_reports_fused_programs():
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13))
    text = r.execute("EXPLAIN ANALYZE " + TPCH_Q6).rows[0][0]
    assert "Fused program wall:" in text


# ---------------------------------------------------------------------------
# distributed ANALYZE
# ---------------------------------------------------------------------------

def test_distributed_analyze_annotates_every_fragment():
    r = DistributedQueryRunner("sf0.01", n_tasks=2,
                               config=ExecutionConfig(batch_rows=1 << 13))
    text = r.execute("EXPLAIN ANALYZE " + TPCH_Q1).rows[0][0]
    fragments = re.split(r"(?m)^Fragment ", text)
    header, fragments = fragments[0], fragments[1:]
    assert len(fragments) >= 2          # partial-agg + final-agg stages
    for frag in fragments:
        # every fragment carries rolled-up task stats on its nodes
        assert "rows:" in frag and "wall:" in frag, frag
    assert r.last_operator_stats       # the side channel fed the annotations


# ---------------------------------------------------------------------------
# span hierarchy
# ---------------------------------------------------------------------------

def test_span_tree_query_fragment_task_operator():
    tp = TracerProvider("simple")
    r = DistributedQueryRunner("sf0.01", n_tasks=2, tracer_provider=tp,
                               config=ExecutionConfig(batch_rows=1 << 13))
    sql = "EXPLAIN ANALYZE " + TPCH_Q6
    r.execute(sql)
    trace = tp.get_trace(sql)
    assert isinstance(trace, SimpleTracer)
    roots = [t for t in trace.span_tree() if t["name"] == "query"]
    assert len(roots) == 1
    fragments = roots[0]["children"]
    assert fragments
    assert all(f["name"].startswith("fragment ") for f in fragments)
    tasks = [t for f in fragments for t in f["children"]]
    assert tasks
    assert all(t["name"].startswith("task ") for t in tasks)
    operators = [o for t in tasks for o in t["children"]]
    assert operators
    for o in operators:
        assert o["name"].startswith("operator ")
        assert "rows" in o["attributes"] and "wall_s" in o["attributes"]


# ---------------------------------------------------------------------------
# /v1/query QueryInfo surface (loopback cluster)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    from presto_tpu.worker import WorkerServer
    coordinator = WorkerServer(coordinator=True, environment="test")
    workers = [WorkerServer(discovery_uri=coordinator.uri,
                            announce_interval_s=0.1,
                            environment="test") for _ in range(2)]
    deadline = time.time() + 10
    while len(coordinator.worker_uris()) < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert len(coordinator.worker_uris()) == 2, "workers failed to announce"
    yield coordinator, workers
    for w in workers:
        w.close()
    coordinator.close()


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def test_query_info_schema_golden(cluster):
    """GET /v1/query/{id} after a distributed run: the QueryInfo snapshot
    carries the trace token, per-stage task breakdown with per-operator
    stats, the cross-task operator rollup, and process metrics."""
    from presto_tpu.client import StatementClient
    coordinator, _ = cluster
    c = StatementClient(coordinator.uri, schema="sf0.01",
                        trace_token="trace-test-qinfo")
    r = c.execute(TPCH_Q6)
    assert r.rows

    listing = _get_json(f"{coordinator.uri}/v1/query")
    assert any(q["queryId"] == r.query_id for q in listing)

    info = _get_json(f"{coordinator.uri}/v1/query/{r.query_id}")
    # identity + terminal state
    assert info["queryId"] == r.query_id
    assert info["state"] == "FINISHED"
    # the client-supplied token survived dispatch and is the join key
    assert info["traceToken"] == "trace-test-qinfo"
    assert isinstance(info["peakMemoryBytes"], int)
    # metric-map shape (names differ between local and distributed paths)
    assert info["runtimeStats"]
    assert all({"sum", "count"} <= set(m)
               for m in info["runtimeStats"].values())

    # stage/task breakdown (terminal snapshot from the history ring)
    stages = info["stages"]
    assert len(stages) >= 2
    # stage ids are {execution id}.{stage path}: one shared execution id
    # (the runner's internal id, distinct from the statement query id),
    # one distinct path per stage
    assert len({s["stageId"].split(".", 1)[0] for s in stages}) == 1
    assert len({s["stageId"] for s in stages}) == len(stages)
    for stage in stages:
        assert stage["nTasks"] == len(stage["tasks"]) >= 1
        for task in stage["tasks"]:
            assert task["traceToken"] == "trace-test-qinfo"
            ops = task["pipelines"][0]["operators"]
            assert ops
            assert any("stats" in op for op in ops)

    # cross-task operator rollup: every entry has the stats-spine fields
    rollup = info["operatorStats"]
    assert rollup
    for s in rollup.values():
        assert s["rows"] >= 0 and s["wall_s"] >= 0 and s["batches"] >= 0

    # process metrics ride along for a single-snapshot health read
    assert set(info["processMetrics"]) == {"exchange", "fabric", "serving",
                                           "storage", "kernel", "memory",
                                           "adaptive"}
    assert "resident_bytes" in info["processMetrics"]["storage"]
    assert "spilled_bytes" in info["processMetrics"]["memory"]
    assert "filters_applied" in info["processMetrics"]["adaptive"]


def test_metrics_namespace_consistency(cluster):
    """/v1/metrics exposes the storage gauges alongside the other metric
    families under the one presto_tpu_ prefix."""
    coordinator, _ = cluster
    with urllib.request.urlopen(f"{coordinator.uri}/v1/metrics",
                                timeout=10) as resp:
        body = resp.read().decode()
    assert "presto_tpu_storage_resident_bytes" in body
    assert "presto_tpu_storage_cache_hits_total" in body
    for family in ("presto_tpu_exchange_", "presto_tpu_serving_"):
        assert family in body
