"""TPC-DS query conformance bank (VERDICT item 8): 20 official-shaped
queries over the full 24-table schema, engine vs numpy oracle at SF0.01
(differential strategy per SURVEY.md §4.3; reference suite:
presto-tpcds/ + presto-native-tests).

Query texts follow the official TPC-DS shapes with the standard
validation substitutions, adapted to the generated schema's column
subset (connectors/tpcds.py documents the layout).
"""
import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01", catalog="tpcds",
                            config=ExecutionConfig(
                                batch_rows=1 << 14,
                                join_out_capacity=1 << 16))


QUERIES = {
    "q03": """
        SELECT d_year, i_brand_id, i_brand,
               sum(ss_ext_sales_price) AS sum_agg
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manufact_id = 128 AND d_moy = 11
        GROUP BY d_year, i_brand_id, i_brand
        ORDER BY d_year, sum_agg DESC, i_brand_id
        LIMIT 100""",
    "q07": """
        SELECT i_item_id, avg(ss_quantity) AS agg1,
               avg(ss_list_price) AS agg2, avg(ss_coupon_amt) AS agg3,
               avg(ss_sales_price) AS agg4
        FROM store_sales, customer_demographics, date_dim, item, promotion
        WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
          AND ss_cdemo_sk = cd_demo_sk AND ss_promo_sk = p_promo_sk
          AND cd_gender = 'M' AND cd_marital_status = 'S'
          AND cd_education_status = 'College'
          AND (p_channel_email = 'N' OR p_channel_tv = 'N')
          AND d_year = 2000
        GROUP BY i_item_id ORDER BY i_item_id LIMIT 100""",
    "q19": """
        SELECT i_brand_id, i_brand, i_manufact_id,
               sum(ss_ext_sales_price) AS ext_price
        FROM date_dim, store_sales, item, customer, customer_address, store
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = 8 AND d_moy = 11 AND d_year = 1998
          AND ss_customer_sk = c_customer_sk
          AND c_current_addr_sk = ca_address_sk
          AND ss_store_sk = s_store_sk
          AND ca_state <> s_state
        GROUP BY i_brand_id, i_brand, i_manufact_id
        ORDER BY ext_price DESC, i_brand_id LIMIT 100""",
    "q26": """
        SELECT i_item_id, avg(cs_quantity) AS agg1,
               avg(cs_list_price) AS agg2, avg(cs_sales_price) AS agg3
        FROM catalog_sales, customer_demographics, date_dim, item
        WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
          AND cs_bill_cdemo_sk = cd_demo_sk
          AND cd_gender = 'F' AND cd_marital_status = 'W'
          AND cd_education_status = 'Primary' AND d_year = 2000
        GROUP BY i_item_id ORDER BY i_item_id LIMIT 100""",
    "q37": """
        SELECT i_item_id, i_current_price, count(*) AS cnt
        FROM item, inventory, date_dim, catalog_sales
        WHERE i_current_price BETWEEN 20 AND 50
          AND inv_item_sk = i_item_sk
          AND d_date_sk = inv_date_sk AND d_year = 2000
          AND inv_quantity_on_hand BETWEEN 100 AND 500
          AND cs_item_sk = i_item_sk
        GROUP BY i_item_id, i_current_price
        ORDER BY i_item_id LIMIT 100""",
    "q43": """
        SELECT s_store_name, s_store_id,
               sum(CASE WHEN d_day_name = 'Sunday'
                        THEN ss_sales_price ELSE NULL END) AS sun_sales,
               sum(CASE WHEN d_day_name = 'Monday'
                        THEN ss_sales_price ELSE NULL END) AS mon_sales,
               sum(CASE WHEN d_day_name = 'Friday'
                        THEN ss_sales_price ELSE NULL END) AS fri_sales
        FROM date_dim, store_sales, store
        WHERE d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
          AND d_year = 2000
        GROUP BY s_store_name, s_store_id
        ORDER BY s_store_name, s_store_id LIMIT 100""",
    "q52": """
        SELECT d_year, i_brand_id, i_brand,
               sum(ss_ext_sales_price) AS ext_price
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = 1 AND d_moy = 11 AND d_year = 2000
        GROUP BY d_year, i_brand_id, i_brand
        ORDER BY d_year, ext_price DESC, i_brand_id LIMIT 100""",
    "q55": """
        SELECT i_brand_id, i_brand, sum(ss_ext_sales_price) AS ext_price
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = 28 AND d_moy = 11 AND d_year = 1999
        GROUP BY i_brand_id, i_brand
        ORDER BY ext_price DESC, i_brand_id LIMIT 100""",
    "q62": """
        SELECT w_warehouse_name, sm_type, web_name,
               sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk <= 30
                        THEN 1 ELSE 0 END) AS d30,
               sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 30
                         AND ws_ship_date_sk - ws_sold_date_sk <= 60
                        THEN 1 ELSE 0 END) AS d60,
               sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 120
                        THEN 1 ELSE 0 END) AS dmore
        FROM web_sales, warehouse, ship_mode, web_site, date_dim
        WHERE d_month_seq BETWEEN 1200 AND 1211
          AND ws_ship_date_sk = d_date_sk
          AND ws_warehouse_sk = w_warehouse_sk
          AND ws_ship_mode_sk = sm_ship_mode_sk
          AND ws_web_site_sk = web_site_sk
        GROUP BY w_warehouse_name, sm_type, web_name
        ORDER BY w_warehouse_name, sm_type, web_name LIMIT 100""",
    "q65": """
        SELECT s_store_name, i_item_id, sb.revenue
        FROM store, item,
             (SELECT ss_store_sk AS store_sk, ss_item_sk AS item_sk,
                     sum(ss_sales_price) AS revenue
              FROM store_sales, date_dim
              WHERE ss_sold_date_sk = d_date_sk
                AND d_month_seq BETWEEN 1176 AND 1187
              GROUP BY ss_store_sk, ss_item_sk) sb
        WHERE sb.store_sk = s_store_sk AND sb.item_sk = i_item_sk
          AND sb.revenue > 490000
        ORDER BY s_store_name, i_item_id LIMIT 100""",
    "q82": """
        SELECT i_item_id, i_current_price, count(*) AS cnt
        FROM item, inventory, date_dim, store_sales
        WHERE i_current_price BETWEEN 30 AND 60
          AND inv_item_sk = i_item_sk
          AND d_date_sk = inv_date_sk AND d_year = 1999
          AND inv_quantity_on_hand BETWEEN 100 AND 500
          AND ss_item_sk = i_item_sk
        GROUP BY i_item_id, i_current_price
        ORDER BY i_item_id LIMIT 100""",
    "q84": """
        SELECT c_customer_id, c_last_name, c_first_name
        FROM customer, customer_address, customer_demographics,
             household_demographics, income_band, store_returns
        WHERE ca_city = 'Pleasant Hill'
          AND c_current_addr_sk = ca_address_sk
          AND ib_income_band_sk = hd_income_band_sk
          AND ib_lower_bound >= 30000 AND ib_upper_bound <= 70000
          AND cd_demo_sk = c_current_cdemo_sk
          AND hd_demo_sk = c_current_hdemo_sk
          AND sr_cdemo_sk = cd_demo_sk
        ORDER BY c_customer_id LIMIT 100""",
    "q89": """
        SELECT i_category, i_class, s_store_name, d_moy,
               sum(ss_sales_price) AS sum_sales,
               avg(sum(ss_sales_price)) OVER (
                   PARTITION BY i_category, i_class, s_store_name)
                   AS avg_monthly_sales
        FROM item, store_sales, date_dim, store
        WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
          AND ss_store_sk = s_store_sk AND d_year = 1999
          AND i_category IN ('Books', 'Electronics', 'Sports')
        GROUP BY i_category, i_class, s_store_name, d_moy
        ORDER BY i_category, i_class, s_store_name, d_moy LIMIT 100""",
    "q91": """
        SELECT cc_name, cc_manager, sum(cr_net_loss) AS net_loss
        FROM call_center, catalog_returns, date_dim, customer,
             customer_demographics, household_demographics
        WHERE cr_call_center_sk = cc_call_center_sk
          AND cr_returned_date_sk = d_date_sk
          AND cr_returning_customer_sk = c_customer_sk
          AND cd_demo_sk = c_current_cdemo_sk
          AND hd_demo_sk = c_current_hdemo_sk
          AND d_year = 1999 AND d_moy = 11
          AND cd_marital_status = 'M' AND cd_education_status = 'Unknown'
          AND hd_buy_potential LIKE 'Unknown%'
        GROUP BY cc_name, cc_manager
        ORDER BY net_loss DESC, cc_name""",
    "q96": """
        SELECT count(*) AS cnt
        FROM store_sales, household_demographics, time_dim, store
        WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
          AND ss_store_sk = s_store_sk
          AND t_hour = 20 AND t_minute >= 30 AND hd_dep_count = 7
          AND s_store_name = 'ese'""",
    "q98": """
        SELECT i_item_id, i_category, i_class, i_current_price,
               sum(ss_ext_sales_price) AS itemrevenue,
               sum(ss_ext_sales_price) * 100
                   / sum(sum(ss_ext_sales_price)) OVER
                     (PARTITION BY i_class) AS revenueratio
        FROM store_sales, item, date_dim
        WHERE ss_item_sk = i_item_sk
          AND i_category IN ('Sports', 'Books', 'Home')
          AND ss_sold_date_sk = d_date_sk
          AND d_date BETWEEN DATE '1999-02-22' AND DATE '1999-03-24'
        GROUP BY i_item_id, i_category, i_class, i_current_price
        ORDER BY i_category, i_class, i_item_id LIMIT 100""",
    "q99": """
        SELECT w_warehouse_name, sm_type, cc_name,
               sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk <= 30
                        THEN 1 ELSE 0 END) AS d30,
               sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 30
                         AND cs_ship_date_sk - cs_sold_date_sk <= 60
                        THEN 1 ELSE 0 END) AS d60,
               sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 120
                        THEN 1 ELSE 0 END) AS dmore
        FROM catalog_sales, warehouse, ship_mode, call_center, date_dim
        WHERE d_month_seq BETWEEN 1200 AND 1211
          AND cs_ship_date_sk = d_date_sk
          AND cs_warehouse_sk = w_warehouse_sk
          AND cs_ship_mode_sk = sm_ship_mode_sk
          AND cs_call_center_sk = cc_call_center_sk
        GROUP BY w_warehouse_name, sm_type, cc_name
        ORDER BY w_warehouse_name, sm_type, cc_name LIMIT 100""",
    "q25_shape": """
        SELECT i_item_id, i_item_sk, sum(ss_net_profit) AS store_profit,
               sum(sr_net_loss) AS return_loss
        FROM store_sales, store_returns, item
        WHERE ss_item_sk = i_item_sk AND sr_item_sk = i_item_sk
          AND ss_customer_sk = sr_customer_sk
          AND ss_ticket_number = sr_ticket_number
        GROUP BY i_item_id, i_item_sk
        ORDER BY i_item_id, i_item_sk LIMIT 100""",
    "q16_shape_exists": """
        SELECT count(DISTINCT cs_order_number) AS order_count,
               sum(cs_ext_ship_cost) AS total_ship
        FROM catalog_sales, date_dim, customer_address, call_center
        WHERE d_date >= DATE '2002-02-01' AND d_date < DATE '2002-04-01'
          AND cs_ship_date_sk = d_date_sk
          AND cs_ship_addr_sk = ca_address_sk AND ca_state = 'GA'
          AND cs_call_center_sk = cc_call_center_sk
          AND EXISTS (SELECT 1 FROM catalog_returns
                      WHERE cs_order_number = cr_order_number)""",
    "q42_full": """
        SELECT d_year, i_category_id, i_category,
               sum(ss_ext_sales_price) AS total
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = 1 AND d_moy = 11 AND d_year = 2000
        GROUP BY d_year, i_category_id, i_category
        ORDER BY total DESC, d_year, i_category_id LIMIT 100""",
    "q93_shape": """
        SELECT ss_customer_sk, sum(ss_sales_price) AS sumsales
        FROM store_sales
        JOIN store_returns ON ss_item_sk = sr_item_sk
                          AND ss_ticket_number = sr_ticket_number
        JOIN reason ON sr_reason_sk = r_reason_sk
        WHERE r_reason_desc = 'reason 28'
        GROUP BY ss_customer_sk
        ORDER BY sumsales DESC, ss_customer_sk LIMIT 100""",
}


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpcds_query(runner, name):
    runner.assert_same_as_reference(QUERIES[name])
