"""Cross-engine differential vs SQLite (connectors/sqlite_backend.py):
the external correctness anchor the round-1 verdict required — sqlite
shares NOTHING with this engine except the generated rows (its own
parser, planner, and executor), so a shared bug in our plan IR or
expression semantics cannot hide.

The reference's analog is its H2 differential suite
(presto-tests/.../QueryAssertions.java:52, H2QueryRunner.java:105).

Query texts are written in the common SQL subset; DATE literals are
templated ({d:ISO}) because sqlite stores our dates as epoch-day ints.
"""
import re
from decimal import Decimal

import pytest

from presto_tpu.connectors.sqlite_backend import SqliteRunner, day
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner

SF = 0.01


@pytest.fixture(scope="module")
def engines():
    engine = LocalQueryRunner(f"sf{SF}", config=ExecutionConfig(
        batch_rows=1 << 14, join_out_capacity=1 << 16))
    lite = SqliteRunner(SF)
    return engine, lite


def render(sql: str, dialect: str) -> str:
    def sub(m):
        iso = m.group(1)
        return f"DATE '{iso}'" if dialect == "engine" else str(day(iso))
    return re.sub(r"\{d:([0-9-]+)\}", sub, sql)


QUERIES = {
    "q6_revenue": """
        SELECT sum(extendedprice * discount) AS revenue
        FROM lineitem
        WHERE shipdate >= {d:1994-01-01} AND shipdate < {d:1995-01-01}
          AND discount BETWEEN 0.05 AND 0.07 AND quantity < 24""",
    "q1_aggregates": """
        SELECT returnflag, linestatus, sum(quantity) AS sum_qty,
               sum(extendedprice) AS sum_price, avg(discount) AS avg_disc,
               count(*) AS n
        FROM lineitem WHERE shipdate <= {d:1998-09-02}
        GROUP BY returnflag, linestatus
        ORDER BY returnflag, linestatus""",
    "q3_join_topn": """
        SELECT l.orderkey AS okey,
               sum(l.extendedprice * (1 - l.discount)) AS revenue
        FROM customer c, orders o, lineitem l
        WHERE c.mktsegment = 'BUILDING' AND c.custkey = o.custkey
          AND l.orderkey = o.orderkey
          AND o.orderdate < {d:1995-03-15} AND l.shipdate > {d:1995-03-15}
        GROUP BY l.orderkey ORDER BY revenue DESC, okey LIMIT 10""",
    "q4_exists": """
        SELECT o.orderpriority AS pri, count(*) AS n
        FROM orders o
        WHERE o.orderdate >= {d:1993-07-01} AND o.orderdate < {d:1993-10-01}
          AND EXISTS (SELECT 1 FROM lineitem l
                      WHERE l.orderkey = o.orderkey
                        AND l.commitdate < l.receiptdate)
        GROUP BY o.orderpriority ORDER BY pri""",
    "q5_six_way": """
        SELECT n.name AS nname,
               sum(l.extendedprice * (1 - l.discount)) AS revenue
        FROM customer c, orders o, lineitem l, supplier s, nation n, region r
        WHERE c.custkey = o.custkey AND l.orderkey = o.orderkey
          AND l.suppkey = s.suppkey AND c.nationkey = s.nationkey
          AND s.nationkey = n.nationkey AND n.regionkey = r.regionkey
          AND r.name = 'ASIA'
          AND o.orderdate >= {d:1994-01-01} AND o.orderdate < {d:1995-01-01}
        GROUP BY n.name ORDER BY revenue DESC""",
    "q10_returns": """
        SELECT c.custkey AS ck,
               sum(l.extendedprice * (1 - l.discount)) AS revenue
        FROM customer c, orders o, lineitem l
        WHERE c.custkey = o.custkey AND l.orderkey = o.orderkey
          AND o.orderdate >= {d:1993-10-01} AND o.orderdate < {d:1994-01-01}
          AND l.returnflag = 'R'
        GROUP BY c.custkey ORDER BY revenue DESC, ck LIMIT 20""",
    "left_join_counts": """
        SELECT c.custkey AS ck, count(o.orderkey) AS n
        FROM customer c LEFT JOIN orders o ON c.custkey = o.custkey
        GROUP BY c.custkey ORDER BY n DESC, ck LIMIT 25""",
    "in_subquery": """
        SELECT count(*) AS n FROM orders
        WHERE custkey IN (SELECT custkey FROM customer WHERE nationkey = 5)""",
    "scalar_subquery": """
        SELECT count(*) AS n FROM lineitem
        WHERE quantity < (SELECT avg(quantity) FROM lineitem)""",
    "distinct_count": """
        SELECT count(DISTINCT custkey) AS n, count(*) AS total
        FROM orders""",
    "having": """
        SELECT custkey AS ck, count(*) AS n FROM orders
        GROUP BY custkey HAVING count(*) >= 25 ORDER BY n DESC, ck""",
    "string_like": """
        SELECT count(*) AS n FROM part WHERE name LIKE '%green%'""",
    "union_all": """
        SELECT 'c' AS tag, count(*) AS n FROM customer
        UNION ALL SELECT 'o' AS tag, count(*) AS n FROM orders
        ORDER BY tag""",
    "case_sum": """
        SELECT sum(CASE WHEN discount > 0.05 THEN extendedprice ELSE 0 END)
               AS hi
        FROM lineitem WHERE shipdate < {d:1993-01-01}""",
    "min_max": """
        SELECT min(orderdate) AS lo, max(orderdate) AS hi,
               min(totalprice) AS plo, max(totalprice) AS phi
        FROM orders""",
}


def _num_eq(a, b) -> bool:
    if a is None or b is None:
        return a is None and b is None
    # engine DATE renders ISO; sqlite stores epoch days
    if isinstance(a, str) and isinstance(b, int) \
            and re.fullmatch(r"\d{4}-\d{2}-\d{2}", a):
        return day(a) == b
    if isinstance(b, str) and isinstance(a, int) \
            and re.fullmatch(r"\d{4}-\d{2}-\d{2}", b):
        return a == day(b)
    if isinstance(a, (int, float, Decimal)) and isinstance(
            b, (int, float, Decimal)):
        fa, fb = float(a), float(b)
        if fa == fb:
            return True
        # Presto decimal aggregates round to the column scale (e.g.
        # avg(decimal(12,2)) is a decimal(12,2)); sqlite computes in
        # float — allow half an ulp at the decimal's scale
        ulp = 0.0
        for v in (a, b):
            if isinstance(v, Decimal):
                ulp = max(ulp, 0.5 * 10.0 ** v.as_tuple().exponent)
        if ulp and abs(fa - fb) <= ulp * 1.0000001:
            return True
        return abs(fa - fb) / max(abs(fa), abs(fb), 1e-30) < 1e-9
    if isinstance(a, str) or isinstance(b, str):
        return str(a).rstrip() == str(b).rstrip()
    return a == b


def _date_to_days(v):
    import datetime
    if isinstance(v, datetime.date):
        return (v - datetime.date(1970, 1, 1)).days
    return v


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_cross_engine(engines, name):
    engine, lite = engines
    got = engine.execute(render(QUERIES[name], "engine"))
    exp = lite.execute(render(QUERIES[name], "sqlite"))
    grows = sorted(([_date_to_days(v) for v in r] for r in got.rows),
                   key=repr)
    erows = sorted(exp.rows, key=repr)
    assert len(grows) == len(erows), \
        f"row count: engine {len(grows)} vs sqlite {len(erows)}"
    for i, (gr, er) in enumerate(zip(grows, erows)):
        for j, (a, b) in enumerate(zip(gr, er)):
            assert _num_eq(a, b), (
                f"{name} row {i} col {j} ({got.column_names[j]}): "
                f"engine {a!r} vs sqlite {b!r}\n{gr}\n{er}")


def test_verifier_cross_engine(engines):
    """Drive the presto-verifier analog with sqlite as the control
    cluster (VERDICT weak #8: the verifier finally has a second engine)."""
    from presto_tpu import verifier as V
    engine, lite = engines
    queries = [render(QUERIES[n], "engine")
               for n in ("in_subquery", "distinct_count", "string_like")]
    sqlite_queries = {render(QUERIES[n], "engine"):
                      render(QUERIES[n], "sqlite")
                      for n in ("in_subquery", "distinct_count",
                                "string_like")}
    res = V.verify(lambda s: lite.execute(sqlite_queries[s]),
                   lambda s: engine.execute(s), queries)
    assert all(r.status == V.MATCH for r in res), \
        [f"{r.status}: {r.detail}" for r in res if r.status != V.MATCH]
