"""Window-function conformance bank (VERDICT item 7): ranking, value
functions (lag/lead/first_value/last_value/nth_value), ntile,
percent_rank/cume_dist, and explicit ROWS/RANGE frames — engine
(exec/operators.py window_batch) vs the independent numpy oracle
(exec/reference.py), per the reference's AbstractTestWindowQueries
differential strategy (SURVEY.md §4.3).

Reference semantics fixture: presto-main-base/.../operator/window/
(frames), WindowOperator.java:69.
"""
import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 14, join_out_capacity=1 << 16))


SHAPES = {
    "row_number": """
        SELECT custkey, orderkey,
               row_number() OVER (PARTITION BY custkey ORDER BY orderkey)
        FROM orders WHERE orderkey < 2000""",
    "rank_dense": """
        SELECT orderkey, rank() OVER (ORDER BY orderpriority),
               dense_rank() OVER (ORDER BY orderpriority)
        FROM orders WHERE orderkey < 400""",
    "running_sum": """
        SELECT custkey, orderkey,
               sum(totalprice) OVER (PARTITION BY custkey ORDER BY orderkey)
        FROM orders WHERE orderkey < 4000""",
    "global_agg": """
        SELECT orderkey, avg(totalprice) OVER () FROM orders
        WHERE orderkey < 500""",
    "lag_default": """
        SELECT custkey, orderkey,
               lag(orderkey) OVER (PARTITION BY custkey ORDER BY orderkey),
               lag(orderkey, 2, -1) OVER (PARTITION BY custkey
                                          ORDER BY orderkey)
        FROM orders WHERE orderkey < 4000""",
    "lead": """
        SELECT custkey, orderkey,
               lead(totalprice) OVER (PARTITION BY custkey ORDER BY orderkey)
        FROM orders WHERE orderkey < 4000""",
    "first_last_value": """
        SELECT custkey, orderkey,
               first_value(orderkey) OVER (PARTITION BY custkey
                                           ORDER BY orderkey),
               last_value(orderkey) OVER (PARTITION BY custkey
                                          ORDER BY orderkey)
        FROM orders WHERE orderkey < 4000""",
    "last_value_full_frame": """
        SELECT custkey, orderkey,
               last_value(orderkey) OVER (
                   PARTITION BY custkey ORDER BY orderkey
                   RANGE BETWEEN UNBOUNDED PRECEDING
                             AND UNBOUNDED FOLLOWING)
        FROM orders WHERE orderkey < 4000""",
    "nth_value": """
        SELECT custkey, orderkey,
               nth_value(orderkey, 2) OVER (
                   PARTITION BY custkey ORDER BY orderkey
                   ROWS BETWEEN UNBOUNDED PRECEDING
                            AND UNBOUNDED FOLLOWING)
        FROM orders WHERE orderkey < 4000""",
    "ntile": """
        SELECT orderkey, ntile(4) OVER (ORDER BY totalprice)
        FROM orders WHERE orderkey < 800""",
    "percent_rank": """
        SELECT orderkey, percent_rank() OVER (ORDER BY orderpriority),
               cume_dist() OVER (ORDER BY orderpriority)
        FROM orders WHERE orderkey < 400""",
    "rows_preceding": """
        SELECT custkey, orderkey,
               sum(totalprice) OVER (PARTITION BY custkey ORDER BY orderkey
                                     ROWS 2 PRECEDING)
        FROM orders WHERE orderkey < 4000""",
    "rows_between": """
        SELECT custkey, orderkey,
               sum(totalprice) OVER (
                   PARTITION BY custkey ORDER BY orderkey
                   ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING)
        FROM orders WHERE orderkey < 4000""",
    "rows_moving_min_max": """
        SELECT custkey, orderkey,
               min(totalprice) OVER (PARTITION BY custkey ORDER BY orderkey
                                     ROWS BETWEEN 2 PRECEDING
                                              AND CURRENT ROW),
               max(totalprice) OVER (PARTITION BY custkey ORDER BY orderkey
                                     ROWS BETWEEN 2 PRECEDING
                                              AND CURRENT ROW)
        FROM orders WHERE orderkey < 4000""",
    "rows_following_only": """
        SELECT custkey, orderkey,
               count(*) OVER (PARTITION BY custkey ORDER BY orderkey
                              ROWS BETWEEN 1 FOLLOWING AND 2 FOLLOWING)
        FROM orders WHERE orderkey < 4000""",
    "rows_unbounded_following": """
        SELECT custkey, orderkey,
               sum(totalprice) OVER (
                   PARTITION BY custkey ORDER BY orderkey
                   ROWS BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING)
        FROM orders WHERE orderkey < 4000""",
    "range_unbounded_both": """
        SELECT custkey, orderkey,
               count(*) OVER (PARTITION BY custkey ORDER BY orderkey
                              RANGE BETWEEN UNBOUNDED PRECEDING
                                        AND UNBOUNDED FOLLOWING)
        FROM orders WHERE orderkey < 4000""",
    "min_max_string": """
        SELECT orderkey,
               max(orderpriority) OVER (ORDER BY orderkey
                                        ROWS 3 PRECEDING)
        FROM orders WHERE orderkey < 800""",
    "window_over_join": """
        SELECT o.orderkey,
               rank() OVER (PARTITION BY o.custkey ORDER BY o.totalprice)
        FROM orders o JOIN customer c ON o.custkey = c.custkey
        WHERE c.nationkey < 5 AND o.orderkey < 4000""",
    "multi_specs": """
        SELECT orderkey,
               row_number() OVER (ORDER BY totalprice),
               sum(totalprice) OVER (PARTITION BY orderpriority
                                     ORDER BY orderkey)
        FROM orders WHERE orderkey < 800""",
    "empty_input": """
        SELECT orderkey, lag(totalprice) OVER (ORDER BY orderkey)
        FROM orders WHERE orderkey < 0""",
    "same_spec_different_frames": """
        SELECT custkey, orderkey,
               sum(totalprice) OVER (PARTITION BY custkey ORDER BY orderkey
                                     ROWS 1 PRECEDING),
               sum(totalprice) OVER (PARTITION BY custkey ORDER BY orderkey
                                     ROWS 3 PRECEDING)
        FROM orders WHERE orderkey < 4000""",
}


@pytest.mark.parametrize("name", sorted(SHAPES))
def test_window_shape(runner, name):
    runner.assert_same_as_reference(SHAPES[name])


def test_frames_not_deduped(runner):
    """Two window calls that differ ONLY in frame must produce distinct
    columns (the planner dedups by canonical text — the frame is part of
    it).  Hand-checked because the oracle runs the same planned IR and
    would inherit a planner-side dedup bug."""
    r = runner.execute("""
        SELECT orderkey,
               sum(orderkey) OVER (ORDER BY orderkey ROWS 1 PRECEDING),
               sum(orderkey) OVER (ORDER BY orderkey ROWS 3 PRECEDING)
        FROM orders WHERE orderkey IN (1, 2, 3, 4, 5, 6, 7)
    """)
    got = {int(a): (int(b), int(c)) for a, b, c in r.rows}
    keys = sorted(got)
    for i, k in enumerate(keys):
        want1 = sum(keys[max(0, i - 1):i + 1])
        want3 = sum(keys[max(0, i - 3):i + 1])
        assert got[k] == (want1, want3), (k, got[k], (want1, want3))


def test_hand_checked_frames(runner):
    """Anchor both implementations to hand-computed values (guards against
    a shared misunderstanding of frame semantics)."""
    r = runner.execute("""
        SELECT orderkey,
               sum(orderkey) OVER (ORDER BY orderkey
                                   ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING)
        FROM orders WHERE orderkey IN (1, 2, 3, 4, 5, 6)
    """)
    got = {int(a): int(b) for a, b in r.rows}
    # rows present: orderkeys 1..6 that exist in tpch data
    keys = sorted(got)
    for i, k in enumerate(keys):
        lo = max(0, i - 1)
        hi = min(len(keys) - 1, i + 1)
        assert got[k] == sum(keys[lo:hi + 1]), (k, got[k])


def test_ntile_hand_checked(runner):
    r = runner.execute("""
        SELECT orderkey, ntile(3) OVER (ORDER BY orderkey)
        FROM orders WHERE orderkey < 30
    """)
    rows = sorted((int(a), int(b)) for a, b in r.rows)
    n = len(rows)
    q, rem = divmod(n, 3)
    sizes = [q + 1] * rem + [q] * (3 - rem)
    want = []
    for b, sz in enumerate(sizes, 1):
        want += [b] * sz
    assert [b for _, b in rows] == want
