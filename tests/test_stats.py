"""Cost/stats layer: column stats, selectivity estimation, join build-side
selection, EXPLAIN estimates (reference cost module analog — SURVEY.md §2.3
StatsCalculator/CostCalculator + DetermineJoinDistributionType)."""
import pytest

from presto_tpu.spi import plan as P
from presto_tpu.sql.planner import Planner
from presto_tpu.sql.stats import StatsCalculator
from presto_tpu.exec.runner import LocalQueryRunner
from presto_tpu.exec.pipeline import ExecutionConfig


def _plan(sql, schema="sf0.01"):
    return Planner(schema).plan(sql)


def _actual_rows(runner, sql):
    return runner.execute(sql).rows[0][0]


def test_scan_estimate_matches_row_count():
    out = _plan("SELECT orderkey FROM orders")
    est = StatsCalculator().rows(out)
    assert est == 15000    # sf0.01 orders


@pytest.mark.parametrize("pred,expect_frac", [
    ("quantity < 24", 24 / 50),
    ("quantity >= 40", 10 / 50),
    ("discount BETWEEN 0.05 AND 0.07", 0.02 / 0.10),
    ("returnflag = 'A'", 1 / 3),
])
def test_filter_selectivity(pred, expect_frac):
    out = _plan(f"SELECT orderkey FROM lineitem WHERE {pred}")
    est = StatsCalculator().rows(out)
    assert est == pytest.approx(60175 * expect_frac, rel=0.15)


def test_selectivity_tracks_actual():
    """Estimated cardinality within 2x of actual for Q6-style conjunction."""
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13))
    sql = ("SELECT count(*) c FROM lineitem WHERE quantity < 24 "
           "AND discount BETWEEN 0.05 AND 0.07")
    actual = _actual_rows(r, sql)
    out = _plan("SELECT orderkey FROM lineitem WHERE quantity < 24 "
                "AND discount BETWEEN 0.05 AND 0.07")
    est = StatsCalculator().rows(out)
    assert actual / 2 <= est <= actual * 2


def test_join_estimate_fk_pk():
    out = _plan("SELECT o.orderkey FROM orders o "
                "JOIN customer c ON o.custkey = c.custkey")
    est = StatsCalculator().rows(out)
    # FK-PK join keeps the fact side's cardinality
    assert est == pytest.approx(15000, rel=0.5)


def test_group_count_capped_by_ndv():
    out = _plan("SELECT returnflag, linestatus, count(*) c FROM lineitem "
                "GROUP BY returnflag, linestatus")
    est = StatsCalculator().rows(out)
    assert est == 6.0      # 3 x 2 closed domains


def test_build_side_swap():
    """Inner join with the big table on the build (right) side gets its
    sides swapped; small build side stays."""
    out = _plan("SELECT c.custkey FROM customer c "
                "JOIN lineitem l ON c.custkey = l.orderkey")
    join = next(n for n in P.walk_plan(out) if isinstance(n, P.JoinNode))
    calc = StatsCalculator()
    assert calc.rows(join.right) <= calc.rows(join.left)

    out2 = _plan("SELECT c.custkey FROM lineitem l "
                 "JOIN customer c ON l.orderkey = c.custkey")
    join2 = next(n for n in P.walk_plan(out2) if isinstance(n, P.JoinNode))
    calc2 = StatsCalculator()
    assert calc2.rows(join2.right) <= calc2.rows(join2.left)


def test_swap_preserves_results():
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13))
    # build side (customer) much smaller than probe (lineitem via orders):
    # exercised both in written order and reversed
    for sql in [
        "SELECT c.mktsegment, count(*) n FROM customer c "
        "JOIN orders o ON c.custkey = o.custkey GROUP BY c.mktsegment",
        "SELECT c.mktsegment, count(*) n FROM orders o "
        "JOIN customer c ON o.custkey = c.custkey GROUP BY c.mktsegment",
    ]:
        r.assert_same_as_reference(sql)


def test_explain_includes_estimates():
    r = LocalQueryRunner("sf0.01")
    res = r.execute("EXPLAIN SELECT count(*) c FROM lineitem "
                    "WHERE quantity < 24")
    text = "\n".join(row[0] for row in res.rows)
    assert "rows≈" in text


def test_hive_external_decimal_stats_logical(tmp_path):
    """External decimal128 parquet stats are already logical — no double
    descale."""
    import os
    from decimal import Decimal
    import pyarrow as pa
    import pyarrow.parquet as pq
    from presto_tpu.connectors import hive
    os.makedirs(tmp_path / "ext2")
    pq.write_table(pa.table({
        "price": pa.array([Decimal("100.00"), Decimal("250.50")],
                          type=pa.decimal128(10, 2))}),
        tmp_path / "ext2" / "part-0.parquet")
    conn = hive.HiveConnector(str(tmp_path))
    cs = conn.column_stats("ext2", "price", 0.01)
    assert cs.low == 100.0 and cs.high == 250.5


def test_hive_parquet_stats(tmp_path):
    from presto_tpu.connectors import catalog, hive
    conn = hive.HiveConnector(str(tmp_path))
    catalog.register_connector("hive", conn)
    try:
        r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
            batch_rows=1 << 13))
        r.execute("CREATE TABLE st AS SELECT orderkey, totalprice "
                  "FROM orders WHERE orderkey <= 1000")
        cs = conn.column_stats("st", "orderkey", 0.01)
        assert cs.low == 1 and cs.high == 1000
        tp = conn.column_stats("st", "totalprice", 0.01)
        assert tp.low is not None and tp.high <= 500000.01
        # estimates flow into plans over hive tables
        out = _plan("SELECT orderkey FROM st WHERE orderkey <= 100")
        est = StatsCalculator().rows(out)
        assert est == pytest.approx(100, rel=0.2)
    finally:
        catalog.unregister_connector("hive")
