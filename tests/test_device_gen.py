"""Device-side generator parity: every (connector, table, column) supported
by connectors/device_gen.py must be bit-identical to the numpy host
generator (the scan may serve any column from either path)."""
import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu.connectors import catalog, device_gen


def _cases():
    out = []
    for (cid, table), (_fn, cols) in device_gen._TABLES.items():
        for c in sorted(cols):
            out.append((cid, table, c))
    return out


@pytest.mark.parametrize("cid,table,col", _cases())
def test_device_matches_host(cid, table, col):
    sf = 0.01
    n = catalog.table_row_count(table, sf, cid)
    for start, count in [(0, min(4096, n)), (max(0, n - 100), min(100, n))]:
        idx = jnp.arange(start, start + count, dtype=jnp.int64)
        dev = np.asarray(device_gen.column(cid, table, col, sf, idx))
        host = catalog.generate_column(table, col, sf, start, count, cid)
        if isinstance(host, tuple):
            codes, values = host
            assert device_gen.dictionary(cid, table, col) == tuple(values)
            np.testing.assert_array_equal(dev, codes)
        else:
            np.testing.assert_array_equal(dev, np.asarray(host))


def test_device_gen_under_jit():
    import jax
    f = jax.jit(lambda pos: device_gen.column(
        "tpch", "lineitem", "extendedprice", 0.01,
        pos + jnp.arange(1024, dtype=jnp.int64)))
    a = np.asarray(f(jnp.int64(0)))
    b = catalog.generate_column("lineitem", "extendedprice", 0.01, 0, 1024)
    np.testing.assert_array_equal(a, b)
