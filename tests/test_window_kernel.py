"""Prefix-sum window kernel (presto_tpu/exec/kernels/window.py):
engagement and parity vs the XLA segmented scans (operators.
window_batch) and the numpy reference oracle, randomized fuzz across
partition-key cardinalities (single-row and all-one-partition edges
included), and the Window* decline gates.

Everything the kernel accepts is integer/decimal arithmetic, so every
comparison is exact equality; float accumulation declines by design."""
import numpy as np
import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner, _assert_rows_equal


def _window_programs(res) -> int:
    return int((res.runtime_stats or {}).get(
        "kernelWindowPrograms", {}).get("sum", 0))


def _declined(res) -> dict:
    return {k[len("kernelDeclined"):]: int(v.get("sum", 0))
            for k, v in (res.runtime_stats or {}).items()
            if k.startswith("kernelDeclined")}


@pytest.fixture(scope="module")
def pallas():
    return LocalQueryRunner(
        "sf0.01", config=ExecutionConfig(scan_kernel="pallas"))


@pytest.fixture(scope="module")
def xla():
    return LocalQueryRunner(
        "sf0.01", config=ExecutionConfig(scan_kernel="xla"))


RUNNING_SUM = """
    select custkey, orderkey,
           sum(totalprice) over (partition by custkey
                                 order by orderkey) as running
    from orders where orderkey < 4000
"""


def test_running_sum_kernel_engages(pallas, xla):
    # the acceptance shape: running SUM over sorted partitions through
    # the in-kernel pairing scan, bit-identical to the XLA path
    pres = pallas.execute(RUNNING_SUM)
    assert _window_programs(pres) >= 1, _declined(pres)
    assert not _declined(pres)
    xres = xla.execute(RUNNING_SUM)
    assert _window_programs(xres) == 0
    assert _declined(xres).get("Disabled", 0) >= 1
    _assert_rows_equal(pres, xres, ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(RUNNING_SUM),
                       ordered=False)


def test_ranking_functions_in_kernel(pallas, xla):
    # row_number / rank / dense_rank share one (partition, order) spec:
    # one launch computes all three
    sql = ("select custkey, orderkey, "
           "row_number() over (partition by custkey order by orderdate, "
           "orderkey) as rn, "
           "rank() over (partition by custkey order by orderdate, "
           "orderkey) as rk, "
           "dense_rank() over (partition by custkey order by orderdate, "
           "orderkey) as dr "
           "from orders where orderkey < 4000")
    pres = pallas.execute(sql)
    assert _window_programs(pres) >= 1, _declined(pres)
    _assert_rows_equal(pres, xla.execute(sql), ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


def test_count_avg_in_kernel(pallas, xla):
    sql = ("select custkey, orderkey, "
           "count(*) over (partition by custkey order by orderkey) as c, "
           "avg(totalprice) over (partition by custkey "
           "order by orderkey) as a "
           "from orders where orderkey < 4000")
    pres = pallas.execute(sql)
    assert _window_programs(pres) >= 1, _declined(pres)
    _assert_rows_equal(pres, xla.execute(sql), ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


# ---------------------------------------------------------------------------
# randomized fuzz: partition-key cardinality x functions x order keys,
# pallas vs xla vs oracle.  orderkey is unique, so every function is
# deterministic under the shared sort.
# ---------------------------------------------------------------------------

_FUNCS = ["row_number()", "rank()", "dense_rank()", "count(*)",
          "count(totalprice)", "sum(totalprice)", "avg(totalprice)"]
# cardinality sweep: multi-row partitions, single-row partitions
# (partition key = the unique order key), one global partition, and a
# dictionary-encoded partition key
_PARTS = ["partition by custkey", "partition by orderkey", "",
          "partition by orderpriority"]


def _window_fuzz_sql(seed: int) -> str:
    rng = np.random.default_rng(seed)
    part = _PARTS[int(rng.integers(len(_PARTS)))]
    order = ["order by orderkey",
             "order by orderdate, orderkey"][int(rng.integers(2))]
    over = f"over ({part}{' ' if part else ''}{order})"
    n = int(rng.integers(2, 5))
    funcs = [_FUNCS[i] for i in rng.choice(len(_FUNCS), n, replace=False)]
    sel = ", ".join(f"{f} {over} as w{i}" for i, f in enumerate(funcs))
    hi = int(rng.integers(2000, 12_000))
    return (f"select custkey, orderkey, {sel} "
            f"from orders where orderkey < {hi}")


@pytest.mark.parametrize("seed", [31, 32, 33, 34, 35])
def test_window_parity_fuzz(pallas, xla, seed):
    sql = _window_fuzz_sql(seed)
    pres = pallas.execute(sql)
    xres = xla.execute(sql)
    _assert_rows_equal(pres, xres, ordered=False)
    assert _window_programs(pres) >= 1, (sql, _declined(pres))
    assert _window_programs(xres) == 0
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


def test_single_row_and_global_partition_edges(pallas, xla):
    # both edges in one query batch: every partition has exactly one
    # row (frame == the row itself), then no PARTITION BY at all (one
    # segment spans the whole live range)
    for sql in (
        "select orderkey, sum(totalprice) over (partition by orderkey "
        "order by orderkey) as s, count(*) over (partition by orderkey "
        "order by orderkey) as c from orders where orderkey < 3000",
        "select orderkey, sum(totalprice) over (order by orderkey) as s, "
        "rank() over (order by orderkey) as r "
        "from orders where orderkey < 3000",
    ):
        pres = pallas.execute(sql)
        assert _window_programs(pres) >= 1, (sql, _declined(pres))
        _assert_rows_equal(pres, xla.execute(sql), ordered=False)
        _assert_rows_equal(pres, pallas.execute_reference(sql),
                           ordered=False)


def test_null_arg_running_aggregates(pallas, xla):
    # NULL inputs: count skips them, sum carries them as non-contrib
    # rows, empty frames are NULL — the contrib mask in-kernel must
    # match window_batch exactly
    sql = ("select k, orderkey, sum(v) over (partition by k "
           "order by orderkey) as s, count(v) over (partition by k "
           "order by orderkey) as c from "
           "(select custkey % 7 as k, orderkey, "
           "case when orderkey % 3 = 0 then null else totalprice end as v "
           "from orders where orderkey < 6000)")
    pres = pallas.execute(sql)
    assert _window_programs(pres) >= 1, _declined(pres)
    _assert_rows_equal(pres, xla.execute(sql), ordered=False)
    _assert_rows_equal(pres, pallas.execute_reference(sql), ordered=False)


# ---------------------------------------------------------------------------
# Window* decline gates
# ---------------------------------------------------------------------------

def test_unsupported_function_declines(pallas, xla):
    # lag needs a shifted gather, not a prefix scan: stays on XLA
    sql = ("select orderkey, lag(totalprice) over (partition by custkey "
           "order by orderkey) as prev from orders where orderkey < 3000")
    pres = pallas.execute(sql)
    assert _window_programs(pres) == 0
    assert _declined(pres).get("WindowFunctionShape", 0) >= 1
    _assert_rows_equal(pres, xla.execute(sql), ordered=False)


def test_float_sum_declines(pallas):
    # float cumsum re-associates the reduction tree: bit-identity would
    # break, so float accumulation declines by design
    sql = ("select orderkey, sum(cast(totalprice as double)) over "
           "(partition by custkey order by orderkey) as s "
           "from orders where orderkey < 3000")
    res = pallas.execute(sql)
    assert _window_programs(res) == 0
    assert _declined(res).get("WindowFunctionShape", 0) >= 1
    pallas.assert_same_as_reference(sql)


def test_explicit_frame_declines(pallas, xla):
    sql = ("select orderkey, sum(totalprice) over (partition by custkey "
           "order by orderkey rows between 1 preceding and current row) "
           "as s from orders where orderkey < 3000")
    pres = pallas.execute(sql)
    assert _window_programs(pres) == 0
    assert _declined(pres).get("WindowFunctionShape", 0) >= 1
    _assert_rows_equal(pres, xla.execute(sql), ordered=False)


def test_lazy_key_declines_window_key_shape():
    # a late-materialized key column cannot feed in-kernel peer
    # detection: the row-id indirection would compare ids, not values
    import jax.numpy as jnp

    from presto_tpu.exec.batch import Batch, Column
    from presto_tpu.exec.kernels.window import try_window_kernel
    from presto_tpu.exec.operators import WindowSpec

    n = 8
    cols = {
        "k": Column(jnp.arange(n, dtype=jnp.int64), None, None,
                    ("rowid", "orders", "clerk", 1.0)),
        "v": Column(jnp.arange(n, dtype=jnp.int64), None),
    }
    batch = Batch(cols, jnp.ones(n, dtype=bool))
    reasons = []
    out = try_window_kernel(
        batch, ("k",), (("v", "ASC_NULLS_LAST"),),
        (WindowSpec("sum", "s", "v"),), declined=reasons.append)
    assert out is None and reasons == ["WindowKeyShape"]


def test_input_size_gate_declines(pallas, monkeypatch):
    from presto_tpu.exec.kernels import window as wk
    monkeypatch.setattr(wk, "KERNEL_WINDOW_MAX_BYTES", 64)
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        scan_kernel="pallas"))
    res = r.execute(RUNNING_SUM)
    assert _window_programs(res) == 0
    assert _declined(res).get("WindowInputSize", 0) >= 1
    _assert_rows_equal(res, pallas.execute(RUNNING_SUM), ordered=False)


def test_auto_off_tpu_declines_backend():
    r = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        scan_kernel="auto"))
    res = r.execute(RUNNING_SUM)
    assert _window_programs(res) == 0
    assert _declined(res).get("Backend", 0) >= 1


def test_explain_analyze_reports_window_kernel(pallas):
    text = pallas.execute(
        "EXPLAIN ANALYZE " + RUNNING_SUM.strip()).rows[0][0]
    assert "Pallas window kernels: 1" in text
