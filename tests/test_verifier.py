"""Verifier tests (reference presto-verifier AbstractVerification.java:74 +
checksum/): checksum-based A/B comparison between engines."""
from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import DistributedQueryRunner, LocalQueryRunner
from presto_tpu.verifier import (CONTROL_ERROR, MATCH, MISMATCH, TEST_ERROR,
                                 checksum_result, verify)

QUERIES = [
    "select count(*), sum(l_quantity) from lineitem",
    "select o_orderstatus, count(*) from orders group by o_orderstatus",
    "select n_name, r_name from nation join region "
    "on n_regionkey = r_regionkey",
    "select c_custkey, avg(o_totalprice) from customer "
    "left join orders on c_custkey = o_custkey group by c_custkey",
]


def test_engine_vs_reference_matches():
    r = LocalQueryRunner("sf0.01")
    results = verify(r.execute_reference, r.execute, QUERIES)
    assert [v.status for v in results] == [MATCH] * len(QUERIES)


def test_local_vs_distributed_matches():
    local = LocalQueryRunner("sf0.01")
    dist = DistributedQueryRunner("sf0.01", n_tasks=3, broadcast_threshold=0)
    results = verify(local.execute, dist.execute, QUERIES[:2])
    assert [v.status for v in results] == [MATCH, MATCH]


def test_spill_config_vs_default_matches():
    a = LocalQueryRunner("sf0.01")
    b = LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 14, join_out_capacity=1 << 16,
        memory_budget_bytes=200_000, spill_partitions=4))
    results = verify(a.execute, b.execute, QUERIES)
    assert [v.status for v in results] == [MATCH] * len(QUERIES)


def test_mismatch_detected():
    r = LocalQueryRunner("sf0.01")
    results = verify(
        lambda s: r.execute("select 1 k from region"),
        lambda s: r.execute("select 2 k from region"),
        ["q"])
    assert results[0].status == MISMATCH
    assert "k" in results[0].detail


def test_errors_classified():
    r = LocalQueryRunner("sf0.01")
    bad = "select * from no_such_table"
    good = "select count(*) from region"
    assert verify(r.execute, r.execute, [bad])[0].status == CONTROL_ERROR
    results = verify(lambda s: r.execute(good),
                     lambda s: r.execute(bad), ["q"])
    assert results[0].status == TEST_ERROR


def test_float_tolerance():
    r = LocalQueryRunner("sf0.01")
    a = r.execute("select avg(c_acctbal) from customer")
    b = r.execute_reference("select avg(c_acctbal) from customer")
    ca, cb = checksum_result(a), checksum_result(b)
    assert ca[0].matches(cb[0], rel_tol=1e-9)


def test_duplicate_column_names_not_collapsed():
    r = LocalQueryRunner("sf0.01")
    results = verify(
        lambda s: r.execute("select 1 a, 2 a from region"),
        lambda s: r.execute("select 1 a, 3 a from region"),
        ["q"])
    assert results[0].status == MISMATCH
