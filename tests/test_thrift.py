"""Thrift binary transport for the TaskStatus hot path (VERDICT r4 next
#9: the third negotiated transport — HttpRemoteTask.java:915-931,
TaskResource.cpp:218-224, presto_thrift.thrift:292-314).

Layers: byte-level goldens hand-derived from the public Thrift binary
protocol spec, schema round-trips (incl. the recursive
ExecutionFailureInfo), forward-compatible unknown-field skipping, and a
live worker serving TaskStatus three ways (JSON / SMILE / Thrift) from
one endpoint."""
import base64
import json
import struct
import threading
import time
import urllib.request

from presto_tpu.worker import smile, thrift


# ---------------------------------------------------------------------------
# spec goldens
# ---------------------------------------------------------------------------

def test_golden_minimal_status():
    # field 3 (version, i64): type 0x0A, id 0x0003, value 7;
    # field 4 (state enum/i32): type 0x08, id 0x0004, RUNNING=1; T_STOP
    raw = thrift.encode_struct(thrift.TASK_STATUS,
                               {"version": 7, "state": "RUNNING"})
    assert raw == (b"\x0a\x00\x03" + struct.pack(">q", 7)
                   + b"\x08\x00\x04" + struct.pack(">i", 1)
                   + b"\x00")


def test_golden_string_field():
    raw = thrift.encode_struct(thrift.TASK_STATUS, {"selfUri": "http://x"})
    assert raw == (b"\x0b\x00\x05" + struct.pack(">i", 8) + b"http://x"
                   + b"\x00")


def test_round_trip_full_status():
    d = {"taskInstanceIdLeastSignificantBits": 1,
         "taskInstanceIdMostSignificantBits": 2,
         "version": 42, "state": "FAILED", "selfUri": "http://w:8080/t",
         "completedDriverGroups": [{"grouped": True, "groupId": 3}],
         "failures": [{"type": "X", "message": "boom",
                       "stack": ["a", "b"],
                       "errorCode": {"code": 1, "name": "GENERIC",
                                     "type": "INTERNAL_ERROR",
                                     "retriable": False},
                       "cause": {"type": "Y", "message": "inner"}}],
         "queuedPartitionedDrivers": 4, "runningPartitionedDrivers": 5,
         "outputBufferUtilization": 0.25, "outputBufferOverutilized": True,
         "physicalWrittenDataSizeInBytes": 10,
         "memoryReservationInBytes": 11,
         "systemMemoryReservationInBytes": 12, "fullGcCount": 0,
         "fullGcTimeInMillis": 0,
         "peakNodeTotalMemoryReservationInBytes": 13,
         "totalCpuTimeInNanos": 14, "taskAgeInMillis": 15,
         "queuedPartitionedSplitsWeight": 16,
         "runningPartitionedSplitsWeight": 17}
    raw = thrift.encode_struct(thrift.TASK_STATUS, d)
    out, end = thrift.decode_struct(thrift.TASK_STATUS, memoryview(raw))
    assert end == len(raw)
    assert out["state"] == "FAILED"
    assert out["failures"][0]["cause"]["message"] == "inner"
    assert out["failures"][0]["errorCode"]["type"] == "INTERNAL_ERROR"
    assert out["completedDriverGroups"] == [{"grouped": True, "groupId": 3}]
    assert out["outputBufferUtilization"] == 0.25
    for k, v in d.items():
        if k not in ("failures", "completedDriverGroups"):
            assert out[k] == v, k


def test_unknown_fields_are_skipped():
    """Forward compatibility: bytes carrying a field id this schema does
    not know must decode cleanly (the reference's thrift evolution
    contract)."""
    known = thrift.encode_struct(thrift.TASK_STATUS, {"version": 9})
    # splice an unknown string field id 99 before the stop byte
    unknown = (b"\x0b\x00\x63" + struct.pack(">i", 3) + b"xyz")
    raw = known[:-1] + unknown + b"\x00"
    out, _ = thrift.decode_struct(thrift.TASK_STATUS, memoryview(raw))
    assert out == {"version": 9}


def test_json_bridge_maps_self_uri():
    d = {"version": 1, "state": "RUNNING", "self": "http://w/t",
         "failures": ["boom"], "memoryReservationInBytes": 5}
    raw = thrift.task_status_to_thrift(d)
    back = thrift.task_status_from_thrift(raw)
    assert back["self"] == "http://w/t"
    assert back["failures"][0]["message"] == "boom"
    assert back["memoryReservationInBytes"] == 5


# ---------------------------------------------------------------------------
# live worker: one endpoint, three transports
# ---------------------------------------------------------------------------

def test_task_status_negotiates_three_transports():
    from presto_tpu.connectors import catalog as cat
    from presto_tpu.spi import plan as P
    from presto_tpu.sql.planner import Planner
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    try:
        out = Planner(default_schema="sf0.01", default_catalog="tpch") \
            .plan("SELECT count(*) AS n FROM nation")
        frag = P.PlanFragment(
            "0", out, P.SOURCE_DISTRIBUTION,
            P.PartitioningScheme(P.SINGLE_DISTRIBUTION, [],
                                 list(out.output_variables)),
            [n.id for n in P.walk_plan(out)
             if isinstance(n, P.TableScanNode)])
        body = {
            "taskId": "thr.0.0.0.0",
            "fragment": base64.b64encode(
                json.dumps(frag.to_dict()).encode()).decode(),
            "sources": [{"planNodeId": sid,
                         "splits": [s.to_dict() for s in
                                    cat.make_splits("nation", 0.01, 2)],
                         "noMoreSplits": True}
                        for sid in frag.partitioned_sources],
            "outputBuffers": {"type": "PARTITIONED", "nBuffers": 1,
                              "partitionKeys": []},
        }
        req = urllib.request.Request(
            f"{w.uri}/v1/task/thr.0.0.0.0",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json",
                     "Accept": thrift.CONTENT_TYPE})
        resp = urllib.request.urlopen(req)
        assert resp.headers.get("Content-Type") == thrift.CONTENT_TYPE
        st = thrift.task_status_from_thrift(resp.read())
        assert st["state"] in ("PLANNED", "RUNNING", "FINISHED")

        deadline = time.time() + 120
        status_url = f"{w.uri}/v1/task/thr.0.0.0.0/status"
        while time.time() < deadline:
            r = urllib.request.urlopen(urllib.request.Request(
                status_url, headers={"Accept": thrift.CONTENT_TYPE}))
            st = thrift.task_status_from_thrift(r.read())
            if st["state"] in ("FINISHED", "FAILED", "CANCELED"):
                break
            time.sleep(0.05)
        assert st["state"] == "FINISHED"

        # the SAME endpoint three ways: field-for-field agreement
        as_json = json.loads(urllib.request.urlopen(urllib.request.Request(
            status_url, headers={"Accept": "application/json"})).read())
        as_smile = smile.decode(urllib.request.urlopen(
            urllib.request.Request(
                status_url,
                headers={"Accept": smile.CONTENT_TYPE})).read())
        assert as_json["state"] == as_smile["state"] == st["state"]
        assert as_json["version"] == as_smile["version"] == st["version"]
        assert as_json["self"] == as_smile["self"] == st["self"]
        assert as_json["memoryReservationInBytes"] \
            == st["memoryReservationInBytes"]
    finally:
        w.close()
