"""TPC-DS conformance: engine vs numpy reference on the tpcds connector
(reference: presto-tpcds connector + TestTpcdsQueries; BASELINE config 5 is
TPC-DS Q95)."""
import pytest

from presto_tpu.connectors import catalog, tpcds
from presto_tpu.exec.runner import DistributedQueryRunner, LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01", catalog="tpcds")


def check(runner, sql, ordered=False):
    return runner.assert_same_as_reference(sql, ordered=ordered)


# ---------------------------------------------------------------------------
# connector / catalog basics
# ---------------------------------------------------------------------------

def test_catalog_resolution_prefers_session_catalog(runner):
    # `customer` exists in both catalogs; tpcds session must get tpcds's
    res = runner.execute("select count(*) from customer")
    assert res.rows[0][0] == tpcds.table_row_count("customer", 0.01)
    tpch_runner = LocalQueryRunner("sf0.01")
    assert tpch_runner.execute("select count(*) from customer").rows \
        != res.rows or True  # row counts differ at this sf
    assert catalog.resolve_table("customer", "tpcds") == "tpcds"
    assert catalog.resolve_table("lineitem", "tpcds") == "tpch"


def test_cross_catalog_table_visible(runner):
    # tpch tables resolve from a tpcds session (no name clash)
    res = runner.execute("select count(*) from region")
    assert res.rows[0][0] == 5


def test_date_dim_calendar_consistency(runner):
    # d_date/d_year/d_moy/d_dom derived from one calendar
    check(runner, """
        select d_year, d_qoy, count(*) from date_dim
        where d_year between 1999 and 2000 group by d_year, d_qoy""")
    res = runner.execute(
        "select d_date, d_year, d_moy, d_dom, d_day_name from date_dim "
        "where d_date = date '2000-02-29'")
    assert res.rows == [["2000-02-29", 2000, 2, 29, "Tuesday"]]


def test_fact_dimension_join(runner):
    check(runner, """
        select ca_state, count(*)
        from web_sales, customer_address
        where ws_ship_addr_sk = ca_address_sk
        group by ca_state""")


# ---------------------------------------------------------------------------
# TPC-DS query shapes
# ---------------------------------------------------------------------------

def test_q3_shape(runner):
    # Q3: star join store_sales x date_dim x item, grouped report
    check(runner, """
        select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) sum_agg
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk
          and ss_item_sk = i_item_sk
          and i_manufact_id = 128
          and d_moy = 11
        group by d_year, i_brand_id, i_brand
        order by d_year, sum_agg desc, i_brand_id
        limit 100""", ordered=True)


def test_q42_shape(runner):
    # Q42: category report for one month
    check(runner, """
        select d_year, i_category_id, i_category, sum(ss_ext_sales_price)
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk
          and ss_item_sk = i_item_sk
          and i_manager_id = 1
          and d_moy = 11 and d_year = 2000
        group by d_year, i_category_id, i_category
        order by 4 desc, d_year, i_category_id, i_category
        limit 100""", ordered=True)


def test_q7_shape_promotion(runner):
    # Q7-like: average report with promotion channel filter (the modeled
    # channels: dmail/email/tv)
    check(runner, """
        select i_category, avg(ss_quantity), avg(ss_list_price),
               avg(ss_sales_price)
        from store_sales, item, promotion
        where ss_item_sk = i_item_sk
          and ss_promo_sk = p_promo_sk
          and (p_channel_email = 'N' or p_channel_tv = 'N')
        group by i_category
        order by i_category""", ordered=True)


Q95 = """
with ws_wh as
 (select ws1.ws_order_number
  from web_sales ws1, web_sales ws2
  where ws1.ws_order_number = ws2.ws_order_number
    and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
select count(distinct ws_order_number),
       sum(ws_ext_ship_cost),
       sum(ws_net_profit)
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '{end}'
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk
  and ca_state = 'IL'
  and ws1.ws_web_site_sk = web_site_sk
  {company}
  and ws1.ws_order_number in (select ws_order_number from ws_wh)
  and ws1.ws_order_number in (select wr_order_number from web_returns, ws_wh
                              where wr_order_number = ws_wh.ws_order_number)
order by 1 limit 100
"""


def test_q95_official_shape(runner):
    # the BASELINE config-5 query verbatim (60-day window; empty at sf0.01)
    sql = Q95.format(end="1999-04-02",
                     company="and web_company_name = 'pri'")
    res = check(runner, sql)
    assert len(res.rows) == 1


def test_q95_selective_window_nonzero(runner):
    # widened window so the intersection is non-empty at sf0.01: exercises
    # the self-join <>, both IN semi-joins, and mixed distinct aggregation
    sql = Q95.format(end="2002-12-31", company="")
    res = check(runner, sql)
    assert res.rows[0][0] > 0


def test_mixed_distinct_plain_aggregation(runner):
    check(runner, """
        select count(distinct ws_web_site_sk), count(*), sum(ws_quantity),
               min(ws_sales_price)
        from web_sales where ws_order_number < 500""")
    check(runner, """
        select ws_web_site_sk, count(distinct ws_warehouse_sk), count(*)
        from web_sales group by ws_web_site_sk""")


def test_returned_orders_semi_join(runner):
    check(runner, """
        select count(*) from web_sales
        where ws_order_number in (select wr_order_number from web_returns)""")


def test_tpcds_distributed_q3(runner):
    d = DistributedQueryRunner("sf0.01", n_tasks=3, broadcast_threshold=0,
                               catalog="tpcds")
    d.assert_same_as_reference("""
        select d_year, i_brand_id, sum(ss_ext_sales_price)
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and d_moy = 11
        group by d_year, i_brand_id""")


def test_q12_shape_window_ratio(runner):
    # Q12: revenue ratio within class via a window over grouped aggregation
    check(runner, """
        select i_item_id, i_category, i_class,
               sum(ws_ext_sales_price) as itemrevenue,
               sum(ws_ext_sales_price) * 100 /
                 sum(sum(ws_ext_sales_price)) over (partition by i_class)
                 as revenueratio
        from web_sales, item, date_dim
        where ws_item_sk = i_item_sk
          and i_category in ('Sports', 'Books', 'Men')
          and ws_sold_date_sk = d_date_sk
          and d_date between date '1999-02-22' and date '1999-06-22'
        group by i_item_id, i_category, i_class
        order by i_category, i_class, i_item_id, itemrevenue
        limit 100""", ordered=True)


def test_q51_shape_cumulative_windows(runner):
    # Q51-like: cumulative sums over date within item partitions
    check(runner, """
        select ss_item_sk, d_date, sum(ss_ext_sales_price) day_sales,
               sum(sum(ss_ext_sales_price))
                   over (partition by ss_item_sk order by d_date) cume
        from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk
          and d_date between date '2000-01-01' and date '2000-02-01'
          and ss_item_sk < 50
        group by ss_item_sk, d_date""")


# ---------------------------------------------------------------------------
# ws_order_number co-bucket layout + grouped (lifespan) execution of the
# Q95-core shapes (BASELINE config 5 blocker)
# ---------------------------------------------------------------------------

import numpy as np

from presto_tpu.exec.pipeline import ExecutionConfig


def _spy_runs(monkeypatch):
    from presto_tpu.exec import grouped as G
    calls = []
    orig = G.GroupedRunner.run

    def spy(self):
        calls.append(self)
        return orig(self)
    monkeypatch.setattr(G.GroupedRunner, "run", spy)
    return calls


@pytest.mark.parametrize("sf", [0.01])
@pytest.mark.parametrize("k", [1, 2, 4, 7])
def test_tpcds_bucket_layout_tiles_tables(sf, k):
    layout = tpcds.bucket_layout(sf, k)
    assert 1 <= len(layout) <= k
    n_ws = tpcds.table_row_count("web_sales", sf)
    n_wr = tpcds.table_row_count("web_returns", sf)
    n_keys = -(-n_ws // tpcds.LINES_PER_ORDER)
    assert layout[0].key_lo == 1
    assert layout[-1].key_hi == n_keys + 1
    assert layout[0].rows["web_sales"][0] == 0
    assert layout[-1].rows["web_sales"][1] == n_ws
    assert layout[0].rows["web_returns"][0] == 0
    assert layout[-1].rows["web_returns"][1] == n_wr
    for prev, cur in zip(layout, layout[1:]):
        assert cur.key_lo == prev.key_hi
        for t in ("web_sales", "web_returns"):
            assert cur.rows[t][0] == prev.rows[t][1]
    for b in layout:
        assert b.key_lo < b.key_hi
        lo, hi = b.rows["web_sales"]
        assert lo < hi                       # every bucket owns sales rows
        lo, hi = b.rows["web_returns"]
        assert lo <= hi                      # returns may be empty


@pytest.mark.parametrize("k", [2, 5])
def test_tpcds_bucket_rows_match_key_ranges(k):
    sf = 0.01
    for b in tpcds.bucket_layout(sf, k):
        for table, col in tpcds.BUCKET_COLUMNS.items():
            lo, hi = b.rows[table]
            if lo == hi:
                continue
            keys = tpcds.generate_column(table, col, sf, lo, hi - lo)
            assert keys.min() >= b.key_lo and keys.max() < b.key_hi


def test_tpcds_catalog_bucket_metadata():
    assert catalog.bucket_column("web_sales", "tpcds") == "ws_order_number"
    assert catalog.bucket_column("web_returns", "tpcds") == \
        "wr_order_number"
    assert catalog.bucket_column("store_sales", "tpcds") is None
    assert catalog.bucket_layout(0.01, 4, "tpcds") is not None


Q95_SEMI_CORE = """
select ws_order_number, count(*) c, sum(ws_ext_ship_cost) s
from web_sales
where ws_order_number in (select wr_order_number from web_returns)
group by ws_order_number
order by ws_order_number
"""

Q95_JOIN_CORE = """
select ws_order_number, sum(wr_return_amt) amt
from web_sales join web_returns on ws_order_number = wr_order_number
group by ws_order_number
order by ws_order_number
"""

Q95_SELF_JOIN_CORE = """
select ws1.ws_order_number, count(*) c
from web_sales ws1 join web_sales ws2
  on ws1.ws_order_number = ws2.ws_order_number
where ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk
group by ws1.ws_order_number
order by ws1.ws_order_number
"""


@pytest.mark.parametrize("sql", [Q95_SEMI_CORE, Q95_JOIN_CORE,
                                 Q95_SELF_JOIN_CORE],
                         ids=["semi", "join", "self_join"])
@pytest.mark.slow
def test_q95_core_grouped_parity(monkeypatch, sql):
    calls = _spy_runs(monkeypatch)
    r = LocalQueryRunner("sf0.01", catalog="tpcds",
                         config=ExecutionConfig(grouped_lifespans=4))
    got = r.execute(sql)
    exp = r.execute_reference(sql)
    from presto_tpu.exec.runner import _assert_rows_equal
    _assert_rows_equal(got, exp, True)
    assert len(calls) == 1 and len(calls[0].layout) == 4


@pytest.mark.slow
def test_q95_core_grouped_auto_engages(monkeypatch):
    # with thresholds shrunk to toy scale, auto mode (grouped_lifespans=0)
    # must pick a multi-bucket layout by itself
    from presto_tpu.exec import grouped as G
    calls = _spy_runs(monkeypatch)
    monkeypatch.setattr(G, "AUTO_SPAN_THRESHOLD", 1024)
    monkeypatch.setattr(G, "TARGET_BUCKET_SPAN", 512)
    r = LocalQueryRunner("sf0.01", catalog="tpcds",
                         config=ExecutionConfig(grouped_lifespans=0))
    got = r.execute(Q95_JOIN_CORE)
    exp = r.execute_reference(Q95_JOIN_CORE)
    from presto_tpu.exec.runner import _assert_rows_equal
    _assert_rows_equal(got, exp, True)
    assert len(calls) == 1 and len(calls[0].layout) >= 2


@pytest.mark.slow
def test_q95_official_stays_correct_with_forced_lifespans(runner):
    # the official Q95 carries count(distinct ...) so grouped execution
    # must decline, and the forced-lifespan config must not disturb it
    sql = Q95.format(end="2002-12-31", company="")
    r = LocalQueryRunner("sf0.01", catalog="tpcds",
                         config=ExecutionConfig(grouped_lifespans=4))
    r.assert_same_as_reference(sql, ordered=False)
