"""Emit repo plan IR as REFERENCE-shaped PlanFragment JSON — the exact
shapes a Java coordinator's HttpRemoteTask sends (struct layouts:
presto-native-execution/presto_cpp/presto_protocol/core/
presto_protocol_core.h; real examples: presto_cpp/main/types/tests/data/).

Test-side inverse of presto_tpu.worker.plan_translation: lets any repo-
planned query be re-shaped into coordinator JSON and pushed through the
translator + executor, and generates golden reference-shaped fixtures for
the live-worker interop test.  Conventions reproduced:
  * "@type" discriminators (".FilterNode" / full Java class names);
  * map keys "name<type>" for variable-keyed maps;
  * constants as base64 single-position Block wire bytes ("valueBlock");
  * "$static" BuiltInFunctionHandle with "presto.default.*" /
    "presto.default.$operator$*" signature names.
"""
import base64
import io

from presto_tpu.common.block import block_from_values
from presto_tpu.common.serde import write_block
from presto_tpu.common.types import DateType, DecimalType
from presto_tpu.exec.lowering import constant_device_value
from presto_tpu.spi import plan as P
from presto_tpu.spi.expr import (CallExpression, ConstantExpression,
                                 SpecialFormExpression,
                                 VariableReferenceExpression)

_JAVA = "com.facebook.presto.sql.planner.plan."

# repo canonical names -> reference operator signature names
_OPERATORS = {
    "add": "$operator$add", "subtract": "$operator$subtract",
    "multiply": "$operator$multiply", "divide": "$operator$divide",
    "modulus": "$operator$modulus", "negate": "$operator$negation",
    "eq": "$operator$equal", "neq": "$operator$not_equal",
    "lt": "$operator$less_than", "lte": "$operator$less_than_or_equal",
    "gt": "$operator$greater_than", "gte": "$operator$greater_than_or_equal",
    "between": "$operator$between", "cast": "$operator$cast",
}


def var_json(v):
    return {"@type": "variable", "name": v.name, "type": v.type.signature}


def map_key(v):
    return f"{v.name}<{v.type.signature}>"


def constant_json(c: ConstantExpression):
    value = c.value
    if value is not None and isinstance(c.type, (DateType, DecimalType)):
        # block storage wants days-since-epoch / the unscaled decimal int
        value = constant_device_value(value, c.type)
    out = io.BytesIO()
    write_block(out, block_from_values(c.type, [value]))
    return {"@type": "constant",
            "valueBlock": base64.b64encode(out.getvalue()).decode(),
            "type": c.type.signature}


def call_json(c: CallExpression):
    name = _OPERATORS.get(c.display_name.lower(), c.display_name.lower())
    return {
        "@type": "call", "displayName": c.display_name,
        "functionHandle": {
            "@type": "$static",
            "signature": {
                "name": f"presto.default.{name}", "kind": "SCALAR",
                "typeVariableConstraints": [], "longVariableConstraints": [],
                "returnType": c.type.signature,
                "argumentTypes": [a.type.signature for a in c.arguments],
                "variableArity": False}},
        "returnType": c.type.signature,
        "arguments": [expr_json(a) for a in c.arguments]}


def expr_json(e):
    if isinstance(e, VariableReferenceExpression):
        return var_json(e)
    if isinstance(e, ConstantExpression):
        return constant_json(e)
    if isinstance(e, CallExpression):
        return call_json(e)
    if isinstance(e, SpecialFormExpression):
        return {"@type": "special", "form": e.form,
                "returnType": e.type.signature,
                "arguments": [expr_json(a) for a in e.arguments]}
    raise NotImplementedError(type(e).__name__)


def ordering_json(scheme: P.OrderingScheme):
    return {"orderBy": [{"variable": var_json(v), "sortOrder": o}
                        for v, o in scheme.orderings]}


def _tpch_table_json(th: P.TableHandle):
    sf = float(dict(th.extra).get("scaleFactor", 1.0))
    return {
        "connectorId": th.connector_id,
        "connectorHandle": {"@type": "tpch", "tableName": th.table_name,
                            "scaleFactor": sf},
        "transaction": {"@type": "tpch", "instance": "test"},
    }


def node_json(n: P.PlanNode) -> dict:
    if isinstance(n, P.TableScanNode):
        return {"@type": ".TableScanNode", "id": n.id,
                "table": _tpch_table_json(n.table),
                "outputVariables": [var_json(v) for v in n.outputs],
                "assignments": {
                    map_key(v): {"@type": "tpch", "columnName": ch.name,
                                 "type": ch.type.signature}
                    for v, ch in n.assignments.items()}}
    if isinstance(n, P.FilterNode):
        return {"@type": ".FilterNode", "id": n.id,
                "source": node_json(n.source),
                "predicate": expr_json(n.predicate)}
    if isinstance(n, P.ProjectNode):
        return {"@type": ".ProjectNode", "id": n.id,
                "source": node_json(n.source),
                "assignments": {"assignments": {
                    map_key(v): expr_json(e)
                    for v, e in n.assignments.items()}},
                "locality": "LOCAL"}
    if isinstance(n, P.AggregationNode):
        aggs = {}
        for v, a in n.aggregations.items():
            cj = call_json(a.call)
            cj["functionHandle"]["signature"]["kind"] = "AGGREGATE"
            aggs[map_key(v)] = {
                "call": cj, "distinct": a.distinct,
                "arguments": cj["arguments"],
                "functionHandle": cj["functionHandle"],
                **({"mask": var_json(a.mask)} if a.mask else {})}
        return {"@type": ".AggregationNode", "id": n.id,
                "source": node_json(n.source),
                "aggregations": aggs,
                "groupingSets": {
                    "groupingKeys": [var_json(v) for v in n.grouping_keys],
                    "groupingSetCount": 1, "globalGroupingSets": []},
                "preGroupedVariables": [], "step": n.step}
    if isinstance(n, P.JoinNode):
        return {"@type": ".JoinNode", "id": n.id, "type": n.join_type,
                "left": node_json(n.left), "right": node_json(n.right),
                "criteria": [{"left": var_json(l), "right": var_json(r)}
                             for l, r in n.criteria],
                "outputVariables": [var_json(v) for v in n.outputs],
                **({"filter": expr_json(n.filter)} if n.filter else {}),
                **({"distributionType": n.distribution}
                   if n.distribution else {}),
                "dynamicFilters": {}}
    if isinstance(n, P.SemiJoinNode):
        return {"@type": ".SemiJoinNode", "id": n.id,
                "source": node_json(n.source),
                "filteringSource": node_json(n.filtering_source),
                "sourceJoinVariable": var_json(n.source_join_variable),
                "filteringSourceJoinVariable":
                    var_json(n.filtering_source_join_variable),
                "semiJoinOutput": var_json(n.semi_join_output),
                "dynamicFilters": {}}
    if isinstance(n, P.SortNode):
        return {"@type": ".SortNode", "id": n.id,
                "source": node_json(n.source),
                "orderingScheme": ordering_json(n.ordering_scheme),
                "isPartial": n.is_partial, "partitionBy": []}
    if isinstance(n, P.TopNNode):
        return {"@type": ".TopNNode", "id": n.id,
                "source": node_json(n.source), "count": n.count,
                "orderingScheme": ordering_json(n.ordering_scheme),
                "step": n.step}
    if isinstance(n, P.LimitNode):
        return {"@type": ".LimitNode", "id": n.id,
                "source": node_json(n.source), "count": n.count,
                "step": "FINAL" if n.step != P.PARTIAL else "PARTIAL"}
    if isinstance(n, P.DistinctLimitNode):
        return {"@type": ".DistinctLimitNode", "id": n.id,
                "source": node_json(n.source), "limit": n.count,
                "partial": False,
                "distinctVariables": [var_json(v)
                                      for v in n.distinct_variables],
                "timeoutMillis": 0}
    if isinstance(n, P.OutputNode):
        return {"@type": ".OutputNode", "id": n.id,
                "source": node_json(n.source),
                "columnNames": list(n.column_names),
                "outputVariables": [var_json(v) for v in n.outputs]}
    if isinstance(n, P.ValuesNode):
        return {"@type": ".ValuesNode", "id": n.id,
                "outputVariables": [var_json(v) for v in n.outputs],
                "rows": [[expr_json(e) for e in row] for row in n.rows]}
    if isinstance(n, P.MarkDistinctNode):
        return {"@type": ".MarkDistinctNode", "id": n.id,
                "source": node_json(n.source),
                "markerVariable": var_json(n.marker),
                "distinctVariables": [var_json(v)
                                      for v in n.distinct_variables]}
    if isinstance(n, P.EnforceSingleRowNode):
        return {"@type": _JAVA + "EnforceSingleRowNode", "id": n.id,
                "source": node_json(n.source)}
    if isinstance(n, P.AssignUniqueIdNode):
        return {"@type": _JAVA + "AssignUniqueId", "id": n.id,
                "source": node_json(n.source),
                "idVariable": var_json(n.id_variable)}
    if isinstance(n, P.GroupIdNode):
        return {"@type": _JAVA + "GroupIdNode", "id": n.id,
                "source": node_json(n.source),
                "groupingSets": [[var_json(v) for v in s]
                                 for s in n.grouping_sets],
                "groupingColumns": {map_key(o): var_json(i)
                                    for o, i in n.grouping_columns.items()},
                "aggregationArguments": [var_json(v)
                                         for v in n.aggregation_arguments],
                "groupIdVariable": var_json(n.group_id_variable)}
    if isinstance(n, P.WindowNode):
        funcs = {}
        for v, wf in n.window_functions.items():
            cj = call_json(wf.call)
            cj["functionHandle"]["signature"]["kind"] = "WINDOW"
            f = wf.frame
            if f is None:
                frame = {"type": "RANGE",
                         "startType": "UNBOUNDED_PRECEDING",
                         "endType": "CURRENT_ROW"}
            else:
                unbound = {"UNBOUNDED_PRECEDING": "UNBOUNDED_PRECEDING",
                           "UNBOUNDED_FOLLOWING": "UNBOUNDED_FOLLOWING",
                           "PRECEDING": "PRECEDING",
                           "FOLLOWING": "FOLLOWING",
                           "CURRENT": "CURRENT_ROW"}
                frame = {"type": f["type"],
                         "startType": unbound[f["startKind"]],
                         "endType": unbound[f["endKind"]]}
                # offsets ride as variable refs plus the original literal
                # text (Frame.originalStartValue, presto_protocol_core.h:
                # 1324-1325) — the coordinator binds the variable in a
                # projection below; the literal is the fallback
                if f.get("startOffset") is not None:
                    frame["startValue"] = var_json(
                        VariableReferenceExpression(
                            f"$frame_start_{n.id}", wf.call.type))
                    frame["originalStartValue"] = str(f["startOffset"])
                if f.get("endOffset") is not None:
                    frame["endValue"] = var_json(
                        VariableReferenceExpression(
                            f"$frame_end_{n.id}", wf.call.type))
                    frame["originalEndValue"] = str(f["endOffset"])
            funcs[map_key(v)] = {"functionCall": cj, "frame": frame,
                                 "ignoreNulls": False}
        return {"@type": _JAVA + "WindowNode", "id": n.id,
                "source": node_json(n.source),
                "specification": {
                    "partitionBy": [var_json(v) for v in n.partition_by],
                    **({"orderingScheme":
                        ordering_json(n.ordering_scheme)}
                       if n.ordering_scheme else {})},
                "windowFunctions": funcs,
                "prePartitionedInputs": [], "preSortedOrderPrefix": 0}
    if isinstance(n, P.RemoteSourceNode):
        return {"@type": _JAVA + "RemoteSourceNode", "id": n.id,
                "sourceFragmentIds": list(n.source_fragment_ids),
                "outputVariables": [var_json(v) for v in n.outputs],
                "ensureSourceOrdering": n.ensure_source_ordering,
                "exchangeType": "GATHER", "encoding": "COLUMNAR"}
    raise NotImplementedError(type(n).__name__)


_SYSTEM = {
    P.SOURCE_DISTRIBUTION: ("SOURCE", "UNKNOWN"),
    P.SINGLE_DISTRIBUTION: ("SINGLE", "SINGLE"),
    P.FIXED_HASH_DISTRIBUTION: ("FIXED", "HASH"),
    P.FIXED_ARBITRARY_DISTRIBUTION: ("FIXED", "ROUND_ROBIN"),
    P.FIXED_BROADCAST_DISTRIBUTION: ("FIXED", "BROADCAST"),
    P.SCALED_WRITER_DISTRIBUTION: ("SCALED", "ROUND_ROBIN"),
}


def _partitioning_handle_json(handle: str):
    part, func = _SYSTEM[handle]
    return {"connectorHandle": {"@type": "$remote", "partitioning": part,
                                "function": func}}


def fragment_json(frag: P.PlanFragment) -> dict:
    scheme = frag.output_partitioning_scheme
    variables = {}
    for n in P.walk_plan(frag.root):
        for v in n.output_variables:
            variables[map_key(v)] = v
    return {
        "id": frag.fragment_id,
        "root": node_json(frag.root),
        "variables": [var_json(v) for v in variables.values()],
        "partitioning": _partitioning_handle_json(frag.partitioning),
        "tableScanSchedulingOrder": list(frag.partitioned_sources),
        "partitioningScheme": {
            "partitioning": {
                "handle": _partitioning_handle_json(scheme.handle),
                "arguments": [var_json(a) for a in scheme.arguments]},
            "outputLayout": [var_json(v) for v in scheme.output_layout],
            "replicateNullsAndAny": False, "scaleWriters": False,
            "encoding": "COLUMNAR", "bucketToPartition": None},
        "stageExecutionDescriptor": {
            "stageExecutionStrategy": "UNGROUPED_EXECUTION",
            "groupedExecutionScanNodes": [], "totalLifespans": 1},
        "outputTableWriterFragment": False,
    }


def tpch_split_json(table: str, sf: float, part: int, nparts: int) -> dict:
    """Reference Split JSON wrapping a TpchSplit
    (presto_protocol_tpch.h:71: tableHandle/partNumber/totalParts)."""
    return {
        "connectorId": "tpch",
        "transactionHandle": {"@type": "tpch", "instance": "test"},
        "connectorSplit": {
            "@type": "tpch",
            "tableHandle": {"tableName": table, "scaleFactor": float(sf)},
            "partNumber": part, "totalParts": nparts,
            "addresses": [], "predicate": {"columnDomains": []}},
        "lifespan": "TaskWide",
        "splitContext": {"cacheable": False},
    }
