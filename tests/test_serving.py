"""Serving tier (presto_tpu/serving/): plan canonicalization, the
canonical plan/executable cache, prepared statements, and fair-share +
memory-headroom admission.

The reference analogs: QueryPreparer / ParameterRewriter (prepared
statements), the coordinator's plan cache discussion in
presto-main-base, InternalResourceGroupManager's WEIGHTED_FAIR policy,
and the cluster memory manager's admission headroom — collapsed onto the
TPU serving problem where the expensive artifact is the compiled XLA
executable, so the cache key must be the canonical (value-free) plan
structure plus the execution-config fingerprint."""
import threading
import time
import urllib.request

import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner
from presto_tpu.serving import (GLOBAL_PLAN_CACHE, PREPARED_REGISTRY,
                                PlanCache, SERVING_METRICS)
from presto_tpu.sql.canonical import (config_fingerprint, parameterize,
                                      plan_cache_key)


@pytest.fixture(autouse=True)
def _reset_serving():
    SERVING_METRICS.reset()
    PREPARED_REGISTRY.clear()
    yield


def _snapshot():
    return SERVING_METRICS.snapshot()


# ---------------------------------------------------------------------------
# canonicalization units
# ---------------------------------------------------------------------------

def _template_key(sql, schema="sf0.01"):
    from presto_tpu.spi import plan as P
    from presto_tpu.sql.parser import parse_sql
    from presto_tpu.sql.planner import Planner
    planner = Planner(default_schema=schema)
    unopt = planner.plan_query_unoptimized(parse_sql(sql))
    pp = parameterize(unopt)
    return P.structural_key(pp.template), pp


def test_parameterize_extracts_comparison_literals():
    k1, pp1 = _template_key(
        "select count(*) from lineitem where l_quantity < 24")
    k2, pp2 = _template_key(
        "select count(*) from lineitem where l_quantity < 30")
    assert k1 == k2                     # literal is out of the template
    assert [s.value for s in pp1.slots] != [s.value for s in pp2.slots]
    assert '"@type": "parameter"' in k1


def test_parameterize_keeps_structure_distinct():
    k1, _ = _template_key(
        "select count(*) from lineitem where l_quantity < 24")
    k2, _ = _template_key(
        "select count(*) from lineitem where l_quantity > 24")
    assert k1 != k2                     # operator is structure, not data


def test_parameterize_leaves_strings_in_template():
    # string literals are not extractable: the value stays in the key, so
    # different strings replan (correct, just uncached across values)
    k1, pp1 = _template_key(
        "select count(*) from orders where o_orderstatus = 'F'")
    k2, _ = _template_key(
        "select count(*) from orders where o_orderstatus = 'O'")
    assert k1 != k2
    assert all(not isinstance(s.value, str) or s.type.__class__.__name__
               == "DateType" for s in pp1.slots)


def test_config_fingerprint_covers_every_field():
    import dataclasses
    a = ExecutionConfig()
    for f in dataclasses.fields(ExecutionConfig):
        if f.name == "plan_validation":
            b = dataclasses.replace(a, plan_validation="off")
            assert config_fingerprint(a) != config_fingerprint(b)


def test_cache_key_changes_with_session_property():
    # satellite (b) regression: a session-property (config) change must
    # never serve the old entry
    from presto_tpu.sql.parser import parse_sql
    from presto_tpu.sql.planner import Planner
    import dataclasses
    sql = "select count(*) from nation where n_nationkey < 10"
    cfg_a = ExecutionConfig()
    cfg_b = dataclasses.replace(cfg_a, plan_validation="off")
    planner = Planner(default_schema="sf0.01")
    pp = parameterize(planner.plan_query_unoptimized(parse_sql(sql)))
    ka = plan_cache_key(pp.template, cfg_a, "tpch", "sf0.01")
    kb = plan_cache_key(pp.template, cfg_b, "tpch", "sf0.01")
    assert ka != kb
    kc = plan_cache_key(pp.template, cfg_a, "tpch", "sf0.1")
    assert ka != kc                     # schema is in the key too


# ---------------------------------------------------------------------------
# canonical cache through the runner
# ---------------------------------------------------------------------------

def test_canonical_cache_reuses_executable_across_constants():
    cache = PlanCache(max_entries=16)
    r = LocalQueryRunner("sf0.01", plan_cache=cache)
    a = r.execute("select count(*) from lineitem where l_quantity < 10")
    builds_after_first = _snapshot()["executableBuilds"]
    b = r.execute("select count(*) from lineitem where l_quantity < 20")
    s = _snapshot()
    # second constant: same canonical entry, NO new executable build —
    # parse/plan/optimize/compile all skipped (the acceptance gate)
    assert s["executableBuilds"] == builds_after_first
    assert s["planCacheHits"] >= 1
    # and the answers are the real per-constant answers
    assert a.rows == [[10803]] or a.rows[0][0] > 0
    assert b.rows[0][0] > a.rows[0][0]
    ref = LocalQueryRunner("sf0.01", plan_cache=PlanCache())
    assert b.rows == ref.execute_reference(
        "select count(*) from lineitem where l_quantity < 20").rows


def test_canonical_cache_results_match_reference_across_values():
    cache = PlanCache()
    r = LocalQueryRunner("sf0.01", plan_cache=cache)
    for q in (10, 25, 40):
        r.assert_same_as_reference(
            f"select l_returnflag, count(*), sum(l_extendedprice) "
            f"from lineitem where l_quantity < {q} group by l_returnflag")
    assert cache.info()["hits"] >= 2


def test_session_property_change_never_serves_stale_plan():
    # same SQL, two configs sharing one cache: each must get its own entry
    import dataclasses
    cache = PlanCache()
    cfg = ExecutionConfig()
    r1 = LocalQueryRunner("sf0.01", config=cfg, plan_cache=cache)
    r2 = LocalQueryRunner(
        "sf0.01", config=dataclasses.replace(cfg, plan_validation="off"),
        plan_cache=cache)
    sql = "select count(*) from region where r_regionkey < 3"
    assert r1.execute(sql).rows == [[3]]
    misses = cache.info()["misses"]
    assert r2.execute(sql).rows == [[3]]
    assert cache.info()["misses"] == misses + 1   # not a (stale) hit


def test_ddl_invalidates_plan_cache():
    from presto_tpu.connectors import catalog
    from presto_tpu.connectors.memory import MemoryConnector
    catalog.register_connector("memory", MemoryConnector())
    try:
        cache = PlanCache()
        r = LocalQueryRunner("sf0.01", catalog="memory", plan_cache=cache)
        r.execute("create table t1 as select 1 as x")
        r.execute("select count(*) from t1 where x < 5")
        assert cache.info()["entries"] >= 1
        r.execute("drop table t1")
        info = cache.info()
        assert info["entries"] == 0
        assert info["invalidations"] >= 1
    finally:
        catalog.unregister_connector("memory")


def test_plan_cache_lru_evicts_and_counts():
    cache = PlanCache(max_entries=2)
    r = LocalQueryRunner("sf0.01", plan_cache=cache)
    r.execute("select count(*) from region")
    r.execute("select count(*) from nation")
    r.execute("select count(*) from supplier")
    info = cache.info()
    assert info["entries"] == 2
    assert info["evictions"] >= 1


# ---------------------------------------------------------------------------
# prepared statements
# ---------------------------------------------------------------------------

Q6ISH = ("select sum(l_extendedprice * l_discount) from lineitem "
         "where l_discount between ? - 0.01 and ? + 0.01 "
         "and l_quantity < ?")


def test_prepare_execute_fast_path_skips_parse_and_plan():
    r = LocalQueryRunner("sf0.01", plan_cache=PlanCache())
    res = r.execute(f"prepare q6 from {Q6ISH}")
    assert res.added_prepare == ("q6", Q6ISH)
    r.execute("execute q6 using 0.06, 0.06, 24")     # compiles + records
    builds = _snapshot()["executableBuilds"]
    out = r.execute("execute q6 using 0.05, 0.05, 30")
    s = _snapshot()
    assert s["preparedFastPath"] >= 1
    assert s["executableBuilds"] == builds           # no recompile
    want = r.execute_reference(
        "select sum(l_extendedprice * l_discount) from lineitem "
        "where l_discount between 0.04 and 0.06 and l_quantity < 30")
    assert out.rows == want.rows


def test_execute_null_parameter_replans():
    r = LocalQueryRunner("sf0.01", plan_cache=PlanCache())
    r.execute("prepare pn from select count(*) from lineitem "
              "where l_quantity < ?")
    r.execute("execute pn using 24")
    # NULL cannot ride the fast path (BindError) — full replan, and the
    # replan folds `x < NULL` correctly
    out = r.execute("execute pn using null")
    assert _snapshot()["preparedReplans"] >= 1
    assert out.rows == [[0]]


def test_execute_wrong_arity_raises():
    r = LocalQueryRunner("sf0.01", plan_cache=PlanCache())
    r.execute("prepare pa from select count(*) from region "
              "where r_regionkey < ?")
    with pytest.raises(ValueError, match="parameter"):
        r.execute("execute pa using 1, 2")


def test_deallocate_removes_statement():
    r = LocalQueryRunner("sf0.01", plan_cache=PlanCache())
    r.execute("prepare pd from select count(*) from region")
    res = r.execute("deallocate prepare pd")
    assert res.deallocated_prepare == "pd"
    with pytest.raises(KeyError):
        r.execute("execute pd")


def test_prepared_header_map_is_stateless():
    # the statement text arrives via the header map each request — a
    # different runner (fresh coordinator) serves it without prior PREPARE
    r = LocalQueryRunner("sf0.01", plan_cache=PlanCache())
    out = r.execute("execute h1 using 3",
                    prepared={"h1": "select count(*) from region "
                                    "where r_regionkey < ?"})
    assert out.rows == [[3]]


# ---------------------------------------------------------------------------
# fair-share + headroom admission
# ---------------------------------------------------------------------------

def _mq(qid, group, est=None):
    from presto_tpu.worker.statement import ManagedQuery
    q = ManagedQuery(qid, "select 1", "u", "s", {}, "tpch", "sf0.01")
    q.resource_group = group
    q.memory_estimate = est
    return q


def test_weighted_fair_share_interleaves_by_weight():
    from presto_tpu.worker.statement import (ResourceGroupManager,
                                             ResourceGroupSpec)
    m = ResourceGroupManager(
        [ResourceGroupSpec("a", hard_concurrency_limit=10, weight=3.0),
         ResourceGroupSpec("b", hard_concurrency_limit=10, weight=1.0)],
        [], total_concurrency=1)
    first = _mq("q0", "a")
    assert m.admit(first)
    queued = []
    for i in range(12):
        q = _mq(f"qa{i}", "a")
        assert not m.admit(q)
        queued.append(q)
    for i in range(12):
        q = _mq(f"qb{i}", "b")
        assert not m.admit(q)
        queued.append(q)
    # drain one slot at a time; weight-3 group should win ~3 of every 4
    order = []
    cur = first
    for _ in range(16):
        nxt = m.release(cur)
        assert len(nxt) == 1            # one slot frees one admission
        cur = nxt[0]
        order.append(cur.resource_group)
    a_share = order.count("a") / len(order)
    assert 0.6 <= a_share <= 0.85       # ~0.75 for weights 3:1


def test_memory_headroom_rejects_impossible_and_queues_tight():
    from presto_tpu.exec.memory import MemoryPool
    from presto_tpu.worker.statement import (QueryMemoryLimitError,
                                             ResourceGroupManager,
                                             ResourceGroupSpec)
    pool = MemoryPool(budget=1000)
    m = ResourceGroupManager(
        [ResourceGroupSpec("g", hard_concurrency_limit=10)], [],
        memory_pool=pool, headroom_fraction=0.8,
        query_memory_estimate=300)
    # 300 + 300 <= 800: two admit; the third queues (temporarily blocked)
    q1, q2, q3 = _mq("m1", "g"), _mq("m2", "g"), _mq("m3", "g")
    assert m.admit(q1) and m.admit(q2)
    assert not m.admit(q3)
    # an estimate that can NEVER fit rejects immediately
    with pytest.raises(QueryMemoryLimitError):
        m.admit(_mq("huge", "g", est=900))
    # releasing the claim admits the queued query
    admitted = m.release(q1)
    assert admitted == [q3]
    info = m.info()["__admission"]
    assert info["memoryAdmittedBytes"] == 600
    assert info["memoryHeadroomBytes"] == 800


def test_release_admits_multiple_when_memory_gated():
    from presto_tpu.exec.memory import MemoryPool
    from presto_tpu.worker.statement import (ResourceGroupManager,
                                             ResourceGroupSpec)
    pool = MemoryPool(budget=1000)
    m = ResourceGroupManager(
        [ResourceGroupSpec("g", hard_concurrency_limit=10)], [],
        memory_pool=pool, headroom_fraction=1.0,
        query_memory_estimate=100)
    big = _mq("big", "g", est=1000)
    assert m.admit(big)
    small = [_mq(f"s{i}", "g") for i in range(4)]
    for q in small:
        assert not m.admit(q)
    # one release (the 1000-byte claim) unblocks all four 100-byte queries
    assert m.release(big) == small


def test_resource_group_manager_backward_compat():
    # pre-serving positional construction and single-group FIFO behavior
    from presto_tpu.worker.statement import (QueryQueueFullError,
                                             ResourceGroupManager,
                                             ResourceGroupSpec, Selector)
    m = ResourceGroupManager(
        [ResourceGroupSpec("g", hard_concurrency_limit=1, max_queued=1)],
        [Selector("g", user="u.*")])
    assert m.select("user", "") == "g"
    q1, q2 = _mq("c1", "g"), _mq("c2", "g")
    assert m.admit(q1)
    assert not m.admit(q2)
    with pytest.raises(QueryQueueFullError):
        m.admit(_mq("c3", "g"))
    assert m.release(q1) == [q2]


# ---------------------------------------------------------------------------
# end to end over HTTP
# ---------------------------------------------------------------------------

@pytest.fixture
def coordinator():
    from presto_tpu.worker.server import WorkerServer
    s = WorkerServer(coordinator=True)
    yield s
    s.close()


def test_http_concurrent_parameterized_serving(coordinator):
    """N threads hammer repeated parameterized shapes: every result must
    match the reference and the cache must be absorbing the repeats."""
    from presto_tpu.client import StatementClient
    ref = LocalQueryRunner("sf0.01", plan_cache=PlanCache())
    shapes = [
        ("cq", "select count(*) from lineitem where l_quantity < ?",
         ["10", "20", "30"]),
        ("sq", "select sum(l_extendedprice) from lineitem "
               "where l_orderkey < ?",
         ["500", "1500", "2500"]),
    ]
    want = {}
    for name, template, values in shapes:
        for v in values:
            want[(name, v)] = ref.execute_reference(
                template.replace("?", v)).rows
    # warm one compile per shape through the real protocol
    warm = StatementClient(coordinator.uri)
    warm.prepared = {n: t for n, t, _ in shapes}
    for name, _t, values in shapes:
        warm.execute(f"execute {name} using {values[0]}")
    SERVING_METRICS.reset()

    errors = []

    def worker(tid):
        c = StatementClient(coordinator.uri, source=f"t{tid}")
        c.prepared = {n: t for n, t, _ in shapes}
        for i in range(6):
            name, _t, values = shapes[(tid + i) % len(shapes)]
            v = values[(tid * 7 + i) % len(values)]
            got = c.execute(f"execute {name} using {v}").rows
            if [list(r) for r in got] != \
                    [list(r) for r in want[(name, v)]]:
                errors.append((name, v, got, want[(name, v)]))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    assert SERVING_METRICS.hit_rate() > 0.0
    s = _snapshot()
    assert s["planCacheHits"] > 0


def test_http_fair_share_across_groups():
    """Two groups under total_concurrency=1: completions interleave
    rather than one group draining first."""
    from presto_tpu.worker.server import WorkerServer
    from presto_tpu.worker.statement import (ResourceGroupManager,
                                             ResourceGroupSpec, Selector)
    from presto_tpu.client import StatementClient
    rgm = ResourceGroupManager(
        [ResourceGroupSpec("ga", hard_concurrency_limit=4, weight=1.0),
         ResourceGroupSpec("gb", hard_concurrency_limit=4, weight=1.0)],
        [Selector("ga", source="src-a"), Selector("gb", source="src-b")],
        total_concurrency=1)
    s = WorkerServer(coordinator=True, resource_groups=rgm)
    try:
        done = []
        lock = threading.Lock()

        def run(source, n):
            c = StatementClient(s.uri, source=source)
            for _ in range(n):
                c.execute("select count(*) from region")
                with lock:
                    done.append(source)

        threads = [threading.Thread(target=run, args=("src-a", 4)),
                   threading.Thread(target=run, args=("src-b", 4))]
        # stagger starts so group a enqueues a backlog first
        threads[0].start()
        time.sleep(0.05)
        threads[1].start()
        for t in threads:
            t.join()
        # fair share: group b finishes work before group a fully drains
        first_half = done[:4]
        assert "src-b" in first_half, done
        info = s.dispatch.resource_groups.info()
        assert info["ga"]["virtualTime"] > 0
        assert info["gb"]["virtualTime"] > 0
    finally:
        s.close()


def test_http_admission_rejects_when_headroom_exhausted():
    from presto_tpu.exec.memory import MemoryPool
    from presto_tpu.worker.server import WorkerServer
    from presto_tpu.worker.statement import (ResourceGroupManager,
                                             ResourceGroupSpec)
    from presto_tpu.client import QueryError, StatementClient
    rgm = ResourceGroupManager(
        [ResourceGroupSpec("global", hard_concurrency_limit=8)], [],
        memory_pool=MemoryPool(budget=1 << 20), headroom_fraction=0.5,
        query_memory_estimate=1 << 10)
    s = WorkerServer(coordinator=True, resource_groups=rgm)
    try:
        c = StatementClient(s.uri)
        # fits: runs normally
        assert c.execute("select count(*) from region").rows == [[5]]
        # session-declared estimate beyond the headroom: rejected outright
        big = StatementClient(
            s.uri, session={"query_memory_bytes": str(1 << 30)})
        with pytest.raises(QueryError, match="headroom"):
            big.execute("select count(*) from region")
    finally:
        s.close()


def test_dbapi_server_side_binding_hits_cache(coordinator):
    import presto_tpu.dbapi as dbapi
    conn = dbapi.connect(coordinator.uri)
    cur = conn.cursor()
    cur.execute("select count(*) from region where r_regionkey < ?", (3,))
    assert cur.fetchall() == [(3,)]
    SERVING_METRICS.reset()
    cur.execute("select count(*) from region where r_regionkey < ?", (4,))
    assert cur.fetchall() == [(4,)]
    s = _snapshot()
    assert s["preparedFastPath"] >= 1       # bound server-side, cached
    # explicit fallback: textual substitution still works
    conn2 = dbapi.connect(coordinator.uri, server_side_binding=False)
    cur2 = conn2.cursor()
    cur2.execute("select count(*) from region where r_regionkey < ?", (2,))
    assert cur2.fetchall() == [(2,)]


def test_status_and_metrics_expose_serving_section(coordinator):
    import json
    c_url = coordinator.uri
    from presto_tpu.client import StatementClient
    StatementClient(c_url).execute("select count(*) from region")
    status = json.loads(
        urllib.request.urlopen(c_url + "/v1/status").read())
    assert "serving" in status
    sv = status["serving"]
    assert {"planCache", "preparedStatements", "metrics",
            "resourceGroups"} <= set(sv)
    assert "global" in sv["resourceGroups"]
    mets = urllib.request.urlopen(c_url + "/v1/metrics").read().decode()
    assert "presto_tpu_serving_plan_cache_hits_total" in mets
    assert 'presto_tpu_serving_group_running{group="global"' in mets
