"""Expression fuzzer (SURVEY §5.2's prescription, VERDICT item 10):
random typed RowExpression trees evaluated by BOTH the XLA lowering
(exec/lowering.py) and the independent numpy interpreter
(exec/reference.py _eval) over random null-bearing data, Velox
expression-fuzzer style.  Seeded and deterministic; expressions hitting
an unimplemented corner in either engine are skipped but counted — the
run fails if too few comparisons actually execute.
"""
import random

import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu.common.types import (BIGINT, BOOLEAN, DOUBLE, VarcharType)
from presto_tpu.exec.batch import Batch, Column
from presto_tpu.exec.lowering import Lowering
from presto_tpu.exec import reference as R
from presto_tpu.spi.expr import (CallExpression, ConstantExpression,
                                 SpecialFormExpression,
                                 VariableReferenceExpression)

N = 64
DICT = ("alpha", "beta", "gamma", "delta", "")
VARCHAR = VarcharType(10)


def make_data(seed: int):
    rng = np.random.RandomState(seed)
    cols = {
        "i1": (rng.randint(-50, 50, N).astype(np.int64),
               rng.rand(N) < 0.15),
        "i2": (rng.randint(-5, 5, N).astype(np.int64),
               rng.rand(N) < 0.15),
        "d1": (np.round(rng.randn(N) * 10, 3), rng.rand(N) < 0.15),
        "b1": (rng.rand(N) < 0.5, rng.rand(N) < 0.15),
        "s1": (rng.randint(0, len(DICT), N).astype(np.int32),
               rng.rand(N) < 0.15),
    }
    batch_cols = {}
    for name, (vals, nulls) in cols.items():
        batch_cols[name] = Column(
            jnp.asarray(vals), jnp.asarray(nulls),
            DICT if name == "s1" else None)
    batch = Batch(batch_cols, jnp.ones(N, dtype=bool))
    tcols = {}
    for name, (vals, nulls) in cols.items():
        if name == "s1":
            tcols[name] = (np.array([DICT[c] for c in vals], dtype=object),
                           nulls.copy())
        else:
            tcols[name] = (vals.copy(), nulls.copy())
    table = R.Table(tcols, N)
    return batch, table


VARS = {
    "i1": BIGINT, "i2": BIGINT, "d1": DOUBLE, "b1": BOOLEAN, "s1": VARCHAR,
}


def gen_expr(rng: random.Random, typ, depth: int):
    """Random expression of SQL type class `typ` in {'int','double','bool',
    'string'}."""
    if depth <= 0 or rng.random() < 0.25:
        # leaf
        if typ == "int":
            if rng.random() < 0.5:
                return VariableReferenceExpression(
                    rng.choice(["i1", "i2"]), BIGINT)
            return ConstantExpression(rng.randint(-20, 20), BIGINT)
        if typ == "double":
            if rng.random() < 0.5:
                return VariableReferenceExpression("d1", DOUBLE)
            return ConstantExpression(
                round(rng.uniform(-20, 20), 3), DOUBLE)
        if typ == "bool":
            if rng.random() < 0.5:
                return VariableReferenceExpression("b1", BOOLEAN)
            return ConstantExpression(rng.random() < 0.5, BOOLEAN)
        if rng.random() < 0.7:
            return VariableReferenceExpression("s1", VARCHAR)
        return ConstantExpression(rng.choice(DICT), VARCHAR)

    d = depth - 1
    if typ == "bool":
        kind = rng.choice(["cmp_i", "cmp_d", "cmp_s", "and", "or", "not",
                           "isnull", "between", "in", "like"])
        if kind == "cmp_i":
            op = rng.choice(["eq", "neq", "lt", "lte", "gt", "gte"])
            return CallExpression(op, BOOLEAN,
                                  [gen_expr(rng, "int", d),
                                   gen_expr(rng, "int", d)])
        if kind == "cmp_d":
            op = rng.choice(["lt", "gt", "lte", "gte"])
            return CallExpression(op, BOOLEAN,
                                  [gen_expr(rng, "double", d),
                                   gen_expr(rng, "double", d)])
        if kind == "cmp_s":
            op = rng.choice(["eq", "neq"])
            return CallExpression(op, BOOLEAN,
                                  [gen_expr(rng, "string", d),
                                   gen_expr(rng, "string", d)])
        if kind in ("and", "or"):
            return SpecialFormExpression(
                kind.upper(), BOOLEAN,
                [gen_expr(rng, "bool", d), gen_expr(rng, "bool", d)])
        if kind == "not":
            return CallExpression("not", BOOLEAN, [gen_expr(rng, "bool", d)])
        if kind == "isnull":
            inner = rng.choice(["int", "double", "string"])
            return SpecialFormExpression(
                "IS_NULL", BOOLEAN, [gen_expr(rng, inner, d)])
        if kind == "between":
            return CallExpression(
                "between", BOOLEAN,
                [gen_expr(rng, "int", d), gen_expr(rng, "int", 0),
                 gen_expr(rng, "int", 0)])
        if kind == "in":
            vals = sorted({rng.randint(-20, 20) for _ in range(3)})
            return SpecialFormExpression(
                "IN", BOOLEAN,
                [gen_expr(rng, "int", d)]
                + [ConstantExpression(v, BIGINT) for v in vals])
        pattern = rng.choice(["a%", "%a", "%et%", "_eta", "%", "x%"])
        return CallExpression(
            "like", BOOLEAN,
            [VariableReferenceExpression("s1", VARCHAR),
             ConstantExpression(pattern, VARCHAR)])
    if typ == "int":
        kind = rng.choice(["arith", "neg", "abs", "if", "coalesce",
                           "greatest"])
        if kind == "arith":
            op = rng.choice(["add", "subtract", "multiply"])
            return CallExpression(op, BIGINT,
                                  [gen_expr(rng, "int", d),
                                   gen_expr(rng, "int", d)])
        if kind == "neg":
            return CallExpression("negate", BIGINT,
                                  [gen_expr(rng, "int", d)])
        if kind == "abs":
            return CallExpression("abs", BIGINT, [gen_expr(rng, "int", d)])
        if kind == "if":
            return SpecialFormExpression(
                "IF", BIGINT,
                [gen_expr(rng, "bool", d), gen_expr(rng, "int", d),
                 gen_expr(rng, "int", d)])
        if kind == "coalesce":
            return SpecialFormExpression(
                "COALESCE", BIGINT,
                [gen_expr(rng, "int", d), gen_expr(rng, "int", d)])
        return CallExpression("greatest", BIGINT,
                              [gen_expr(rng, "int", d),
                               gen_expr(rng, "int", d)])
    if typ == "double":
        kind = rng.choice(["arith", "abs", "if", "sqrt_abs", "floor"])
        if kind == "arith":
            op = rng.choice(["add", "subtract", "multiply"])
            return CallExpression(op, DOUBLE,
                                  [gen_expr(rng, "double", d),
                                   gen_expr(rng, "double", d)])
        if kind == "abs":
            return CallExpression("abs", DOUBLE, [gen_expr(rng, "double", d)])
        if kind == "if":
            return SpecialFormExpression(
                "IF", DOUBLE,
                [gen_expr(rng, "bool", d), gen_expr(rng, "double", d),
                 gen_expr(rng, "double", d)])
        if kind == "sqrt_abs":
            return CallExpression(
                "sqrt", DOUBLE,
                [CallExpression("abs", DOUBLE,
                                [gen_expr(rng, "double", d)])])
        return CallExpression("floor", DOUBLE, [gen_expr(rng, "double", d)])
    # string
    return VariableReferenceExpression("s1", VARCHAR)


def eval_engine(expr, batch):
    import jax
    low = Lowering()
    col = jax.jit(lambda b: low.eval(expr, b))(batch)
    vals = np.asarray(col.values)
    nulls = (np.zeros(len(vals), dtype=bool) if col.nulls is None
             else np.asarray(col.nulls))
    if col.dictionary is not None:
        out = [None if n else col.dictionary[int(v)]
               for v, n in zip(vals, nulls)]
    else:
        out = [None if n else v.item() for v, n in zip(vals, nulls)]
    return out


def eval_oracle(expr, table):
    vals, nulls = R._eval(expr, table)
    if nulls is None:
        nulls = np.zeros(len(vals), dtype=bool)
    return [None if n else (v.item() if isinstance(v, np.generic) else v)
            for v, n in zip(vals, nulls)]


def _same(a, b) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if np.isnan(fa) or np.isnan(fb):
            return np.isnan(fa) and np.isnan(fb)
        return abs(fa - fb) <= 1e-9 * max(abs(fa), abs(fb), 1.0)
    if isinstance(a, (bool, np.bool_)) or isinstance(b, (bool, np.bool_)):
        return bool(a) == bool(b)
    return a == b


# seeds 0..7 = the regression corpus; each runs 40 random expressions
@pytest.mark.parametrize("seed", range(8))
def test_fuzz_expressions(seed):
    rng = random.Random(seed)
    batch, table = make_data(seed)
    ran = skipped = 0
    for i in range(40):
        typ = rng.choice(["bool", "int", "double", "bool"])
        expr = gen_expr(rng, typ, 3)
        try:
            got = eval_engine(expr, batch)
        except NotImplementedError:
            skipped += 1
            continue
        try:
            exp = eval_oracle(expr, table)
        except NotImplementedError:
            skipped += 1
            continue
        for row, (a, b) in enumerate(zip(got, exp)):
            assert _same(a, b), (
                f"seed {seed} expr #{i} row {row}: engine {a!r} vs "
                f"oracle {b!r}\nexpr: {expr.to_dict()}")
        ran += 1
    assert ran >= 25, f"only {ran} comparisons ran ({skipped} skipped)"
