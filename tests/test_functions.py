"""Scalar function library: math / string / date functions differential-
tested against the independent numpy/datetime reference interpreter
(reference analog: presto-main-base/.../operator/scalar/ MathFunctions,
StringFunctions, DateTimeFunctions — SURVEY.md §2.5 function registry)."""
import pytest

from presto_tpu.exec.pipeline import ExecutionConfig
from presto_tpu.exec.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner("sf0.01", config=ExecutionConfig(
        batch_rows=1 << 13))


MATH_QUERIES = [
    "SELECT orderkey, sqrt(totalprice) s FROM orders WHERE orderkey < 50",
    "SELECT orderkey, exp(discount) e, ln(extendedprice) l FROM lineitem "
    "WHERE orderkey < 30",
    "SELECT orderkey, power(quantity, 2) p, cbrt(extendedprice) c "
    "FROM lineitem WHERE orderkey < 30",
    "SELECT orderkey, log2(totalprice) a, log10(totalprice) b FROM orders "
    "WHERE orderkey < 30",
    "SELECT orderkey, sin(discount) s, cos(discount) c, tan(discount) t "
    "FROM lineitem WHERE orderkey < 30",
    "SELECT orderkey, asin(discount) s, acos(discount) c, atan(tax) t "
    "FROM lineitem WHERE orderkey < 30",
    "SELECT orderkey, degrees(discount) d, radians(quantity) r "
    "FROM lineitem WHERE orderkey < 30",
    "SELECT orderkey, ceiling(totalprice) c, floor(totalprice) f, "
    "sign(acctbal) s FROM orders, customer "
    "WHERE orderkey < 10 AND custkey < 10",
    "SELECT orderkey, truncate(totalprice / 7.0) t FROM orders "
    "WHERE orderkey < 30",
    "SELECT orderkey, round(totalprice / 7.0) r0, "
    "round(totalprice / 7.0, 2) r2 FROM orders WHERE orderkey < 30",
    "SELECT orderkey, greatest(quantity, discount * 100) g, "
    "least(quantity, tax * 100) l FROM lineitem WHERE orderkey < 30",
    "SELECT orderkey, mod(orderkey, 7) m FROM orders WHERE orderkey < 30",
    "SELECT count(*) c FROM orders WHERE totalprice > pi() * 10000",
]


@pytest.mark.parametrize("sql", MATH_QUERIES)
def test_math_functions(runner, sql):
    runner.assert_same_as_reference(sql)


STRING_QUERIES = [
    "SELECT lower(mktsegment) l, upper(mktsegment) u, count(*) c "
    "FROM customer GROUP BY 1, 2",
    "SELECT reverse(shipmode) r, count(*) c FROM lineitem "
    "WHERE orderkey < 200 GROUP BY 1",
    "SELECT replace(shipmode, ' ', '_') r, count(*) c FROM lineitem "
    "WHERE orderkey < 200 GROUP BY 1",
    "SELECT strpos(mktsegment, 'U') p, count(*) c FROM customer "
    "GROUP BY 1 ORDER BY 1",
    "SELECT count(*) c FROM customer WHERE starts_with(mktsegment, 'BU')",
    "SELECT lpad(linestatus, 3, 'x') l, rpad(returnflag, 4, 'y') r, "
    "count(*) c FROM lineitem WHERE orderkey < 100 GROUP BY 1, 2",
    "SELECT concat(returnflag, linestatus) k, count(*) c FROM lineitem "
    "WHERE orderkey < 300 GROUP BY 1 ORDER BY 1",
    "SELECT concat(returnflag, '_', linestatus) k, count(*) c "
    "FROM lineitem WHERE orderkey < 300 GROUP BY 1 ORDER BY 1",
    "SELECT trim(rpad(returnflag, 3, ' ')) t, count(*) c FROM lineitem "
    "WHERE orderkey < 100 GROUP BY 1",
]


@pytest.mark.parametrize("sql", STRING_QUERIES)
def test_string_functions(runner, sql):
    runner.assert_same_as_reference(sql)


DATE_QUERIES = [
    "SELECT date_trunc('month', orderdate) m, count(*) c FROM orders "
    "WHERE orderkey < 2000 GROUP BY 1 ORDER BY 1",
    "SELECT date_trunc('quarter', orderdate) q, date_trunc('year', "
    "orderdate) y, count(*) c FROM orders WHERE orderkey < 2000 "
    "GROUP BY 1, 2 ORDER BY 1, 2",
    "SELECT date_trunc('week', shipdate) w, count(*) c FROM lineitem "
    "WHERE orderkey < 500 GROUP BY 1 ORDER BY 1",
    "SELECT orderkey, day_of_week(orderdate) dw, day_of_year(orderdate) dy,"
    " week(orderdate) w FROM orders WHERE orderkey < 400",
    "SELECT orderkey, date_add('day', 40, orderdate) a, "
    "date_add('month', 3, orderdate) b, date_add('year', -2, orderdate) c "
    "FROM orders WHERE orderkey < 200",
    # end-of-month clamping: Jan 31 + 1 month = Feb 28/29
    "SELECT orderkey, date_add('month', 1, date_trunc('month', orderdate)) "
    "a FROM orders WHERE orderkey < 200",
    "SELECT l.orderkey, date_diff('day', orderdate, shipdate) dd, "
    "date_diff('week', orderdate, shipdate) dw FROM orders o, lineitem l "
    "WHERE o.orderkey = l.orderkey AND o.orderkey < 100",
    "SELECT orderkey, date_diff('month', orderdate, "
    "DATE '1995-06-17') dm, date_diff('year', orderdate, "
    "DATE '1995-06-17') dy FROM orders WHERE orderkey < 300",
]


@pytest.mark.parametrize("sql", DATE_QUERIES)
def test_date_functions(runner, sql):
    runner.assert_same_as_reference(sql)


def test_pad_semantics(runner):
    """lpad pads cycling from the START of the fill string (Presto
    semantics) — asserted against literal expected values, not just the
    oracle, since both sides share the helper shape."""
    r = runner.execute("SELECT lpad(linestatus, 5, 'ab') l, "
                       "rpad(linestatus, 5, 'ab') r FROM lineitem "
                       "WHERE orderkey = 1 AND linenumber = 1")
    l, rr = r.rows[0]
    assert l == "ababO" and rr == "Oabab"
    runner.assert_same_as_reference(
        "SELECT lpad(linestatus, 5, 'ab') l, count(*) c FROM lineitem "
        "WHERE orderkey < 50 GROUP BY 1")


def test_week_year_boundaries(runner):
    """ISO week numbers around Jan 1 (the w=0 / w=53 wrap cases)."""
    runner.assert_same_as_reference(
        "SELECT orderdate, week(orderdate) w FROM orders "
        "WHERE month(orderdate) = 1 AND day(orderdate) <= 4 "
        "AND orderkey < 20000")
    runner.assert_same_as_reference(
        "SELECT orderdate, week(orderdate) w FROM orders "
        "WHERE month(orderdate) = 12 AND day(orderdate) >= 28 "
        "AND orderkey < 20000")


# ---------------------------------------------------------------------------
# round-5 breadth: regexp / URL / JSON / split (RegexpFunctions,
# UrlFunctions.java, JsonFunctions.java), math/bitwise
# (MathFunctions.java, BitwiseFunctions.java)
# ---------------------------------------------------------------------------

BREADTH_QUERIES = [
    # regexp over a dictionary column
    "SELECT shipmode, regexp_like(shipmode, '^A|L$') m FROM lineitem "
    "WHERE orderkey < 30",
    "SELECT regexp_extract(shipmode, '([A-Z]+) ?.*', 1) x, count(*) c "
    "FROM lineitem WHERE orderkey < 200 GROUP BY 1",
    "SELECT regexp_replace(shipmode, '[AEIOU]', '_') r FROM lineitem "
    "WHERE orderkey < 30",
    "SELECT split_part(shipinstruct, ' ', 1) a, "
    "split_part(shipinstruct, ' ', 9) b FROM lineitem WHERE orderkey < 30",
    "SELECT ends_with(shipmode, 'AIR') e, codepoint(returnflag) c "
    "FROM lineitem WHERE orderkey < 30",
    # math / bitwise
    "SELECT log(2.0, quantity) l, atan2(discount, tax + 0.01) a "
    "FROM lineitem WHERE orderkey < 30",
    "SELECT sinh(discount) s, cosh(discount) c, tanh(discount) t "
    "FROM lineitem WHERE orderkey < 30",
    "SELECT is_nan(discount / discount) n, is_finite(extendedprice) f "
    "FROM lineitem WHERE orderkey < 30",
    "SELECT bitwise_and(orderkey, 255) a, bitwise_or(orderkey, 16) o, "
    "bitwise_xor(orderkey, partkey) x, bitwise_not(orderkey) n "
    "FROM lineitem WHERE orderkey < 30",
    "SELECT bitwise_left_shift(orderkey, 3) l, "
    "bitwise_right_shift(orderkey, 1) r, "
    "bitwise_arithmetic_shift_right(0 - orderkey, 2) ar "
    "FROM lineitem WHERE orderkey < 30",
    "SELECT width_bucket(totalprice, 0.0, 600000.0, 10) w, count(*) c "
    "FROM orders WHERE orderkey < 2000 GROUP BY 1",
]


@pytest.mark.parametrize("sql", BREADTH_QUERIES)
def test_function_breadth(runner, sql):
    runner.assert_same_as_reference(sql)


def test_url_and_json_literals(runner):
    runner.assert_same_as_reference(
        "SELECT url_extract_protocol('https://api.example.com:8443/v1/q"
        "?x=1#frag') p, url_extract_host('https://api.example.com:8443/"
        "v1/q?x=1') h, url_extract_port('https://api.example.com:8443/') "
        "n, url_extract_path('https://api.example.com:8443/v1/q') pa, "
        "url_extract_query('https://e.com/p?a=1&b=2') q")
    runner.assert_same_as_reference(
        "SELECT json_extract_scalar('{\"a\": {\"b\": [1, 2, 3]}}', "
        "'$.a.b[1]') x, json_extract_scalar('{\"s\": \"hi\"}', '$.s') y, "
        "json_extract_scalar('{\"t\": true}', '$.t') z, "
        "json_extract_scalar('{\"a\": 1}', '$.missing') w")


def test_regexp_on_lazy_comment_column(runner):
    """regexp functions over a late-materialized (open-domain) column take
    the host-hoist path (_HOIST_XFORM/_HOIST_PRED)."""
    runner.assert_same_as_reference(
        "SELECT count(*) FROM orders WHERE orderkey < 2000 "
        "AND regexp_like(comment, 'furious|pend')")
    runner.assert_same_as_reference(
        "SELECT regexp_replace(comment, '[aeiou]', '') r, count(*) c "
        "FROM orders WHERE orderkey < 300 GROUP BY 1")


# ---------------------------------------------------------------------------
# int64 shift edge semantics (MathFunctions.java bitwiseLeftShift /
# bitwiseRightShift / bitwiseRightShiftArithmetic): counts >= 64 shift
# everything out, negative counts follow the error->NULL relaxation —
# mirrored engine (exec/lowering.py) and oracle (exec/reference.py)
# ---------------------------------------------------------------------------

SHIFT_EDGE_QUERIES = [
    # counts at and past the width
    "SELECT orderkey, bitwise_left_shift(orderkey, 64) a, "
    "bitwise_left_shift(orderkey, 100) b FROM orders WHERE orderkey < 30",
    "SELECT orderkey, bitwise_right_shift(orderkey, 64) a, "
    "bitwise_right_shift(0 - orderkey, 70) b FROM orders "
    "WHERE orderkey < 30",
    "SELECT orderkey, bitwise_arithmetic_shift_right(0 - orderkey, 64) a, "
    "bitwise_arithmetic_shift_right(orderkey, 65) b FROM orders "
    "WHERE orderkey < 30",
    # negative counts -> NULL
    "SELECT orderkey, bitwise_left_shift(orderkey, -1) a, "
    "bitwise_right_shift(orderkey, -2) b, "
    "bitwise_arithmetic_shift_right(orderkey, -3) c FROM orders "
    "WHERE orderkey < 30",
    # per-row mixed signs / magnitudes through a column count
    "SELECT orderkey, bitwise_left_shift(orderkey, orderkey - 15) s "
    "FROM orders WHERE orderkey < 40",
    "SELECT orderkey, bitwise_right_shift(orderkey, orderkey * 3) s "
    "FROM orders WHERE orderkey < 40",
]


@pytest.mark.parametrize("sql", SHIFT_EDGE_QUERIES)
def test_shift_edge_semantics(runner, sql):
    runner.assert_same_as_reference(sql)


def test_shift_edge_values(runner):
    res = runner.execute(
        "SELECT bitwise_left_shift(orderkey, 64) a, "
        "bitwise_right_shift(orderkey, 64) b, "
        "bitwise_arithmetic_shift_right(0 - orderkey, 64) c, "
        "bitwise_left_shift(orderkey, -1) d "
        "FROM orders WHERE orderkey = 7")
    assert res.rows == [[0, 0, -1, None]]


def test_repeat_negative_count_clamps_to_empty(runner):
    runner.assert_same_as_reference(
        "SELECT orderkey, cardinality(repeat(orderkey, -3)) c "
        "FROM orders WHERE orderkey < 10")
    res = runner.execute(
        "SELECT cardinality(repeat(orderkey, -1)) a, "
        "cardinality(repeat(orderkey, 0)) b, "
        "cardinality(repeat(orderkey, 2)) c "
        "FROM orders WHERE orderkey = 3")
    assert res.rows == [[0, 0, 2]]


def test_compact_and_concat_preserve_array_lengths():
    """ops.compact and pipeline._concat_batches must carry Column.lengths
    (ARRAY columns) alongside values/nulls."""
    import jax.numpy as jnp
    from presto_tpu.exec import operators as ops
    from presto_tpu.exec.batch import Batch, Column
    from presto_tpu.exec.pipeline import _concat_batches

    vals = jnp.arange(12, dtype=jnp.int64).reshape(6, 2)
    lens = jnp.array([2, 1, 2, 0, 1, 2], dtype=jnp.int32)
    mask = jnp.array([True, False, True, True, False, True])
    b = Batch({"a": Column(vals, None, None, None, lens)}, mask)

    out = ops.compact(b)
    assert out.columns["a"].lengths is not None
    live = [int(x) for x in out.columns["a"].lengths[:int(mask.sum())]]
    assert live == [2, 2, 0, 2]

    cat = _concat_batches([b, out])
    assert cat.columns["a"].lengths is not None
    assert cat.columns["a"].lengths.shape == (12,)
    assert [int(x) for x in cat.columns["a"].lengths[:6]] == \
        [int(x) for x in lens]

    # scalar columns stay lengths-free through both paths
    s = Batch({"x": Column(jnp.arange(6, dtype=jnp.int64))}, mask)
    assert ops.compact(s).columns["x"].lengths is None
    assert _concat_batches([s, s]).columns["x"].lengths is None
