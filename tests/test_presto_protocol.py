"""Reference-shaped protocol DTO conformance (VERDICT item 4): the DTOs
in worker/presto_protocol.py round-trip the REFERENCE's own JSON test
fixtures (presto-native-execution/presto_cpp/main/tests/data/), read
from the reference tree at test time, and an HttpRemoteTask-shaped
TaskUpdateRequest drives a live worker end to end.
"""
import base64
import json
import os
import time

import pytest

from presto_tpu.worker import presto_protocol as PP

FIXTURES = ("/root/reference/presto-native-execution/presto_cpp/"
            "main/tests/data")

needs_fixtures = pytest.mark.skipif(
    not os.path.isdir(FIXTURES), reason="reference fixtures not present")


@needs_fixtures
def test_task_status_round_trips_reference_fixture():
    with open(os.path.join(FIXTURES, "TaskInfo.json")) as f:
        ref = json.load(f)
    status = PP.TaskStatus.from_json(ref["taskStatus"])
    out = status.to_json()
    for k, v in ref["taskStatus"].items():
        assert out[k] == v, (k, out.get(k), v)
    assert set(out) == set(ref["taskStatus"])


def test_update_request_round_trip():
    req = PP.TaskUpdateRequest(
        session=PP.SessionRepresentation(
            queryId="q1", user="alice", catalog="tpch", schema="sf0.01",
            systemProperties={"query_max_memory": "1GB"}),
        extraCredentials={"token": "t"},
        fragment=base64.b64encode(b"{}").decode(),
        sources=[PP.TaskSource("scan.0", [
            PP.ScheduledSplit(7, "scan.0",
                              {"connectorId": "tpch",
                               "connectorSplit": {"table": "lineitem",
                                                  "sf": 0.01,
                                                  "start": 0, "end": 10}})])],
        outputIds=PP.OutputBuffers("PARTITIONED", 3, True,
                                   {"0": 0, "1": 1}))
    d = req.to_json()
    back = PP.TaskUpdateRequest.from_json(d)
    assert back.to_json() == d
    assert back.session.systemProperties == {"query_max_memory": "1GB"}
    assert back.sources[0].splits[0].sequenceId == 7


def test_broadcast_buffer_count_from_ids():
    """OutputBuffers maps bufferId -> partition; BROADCAST repeats
    partition 0 for every consumer, so the buffer count must come from the
    ids, not the partition values."""
    from presto_tpu.worker.protocol import from_reference_update
    body = {
        "session": PP.SessionRepresentation(queryId="q", user="u").to_json(),
        "extraCredentials": {},
        "fragment": base64.b64encode(b"{}").decode(),
        "sources": [],
        "outputIds": PP.OutputBuffers(
            "BROADCAST", 0, True, {"0": 0, "1": 0, "2": 0}).to_json(),
    }
    upd = from_reference_update("q.0.0.0.0", body)
    assert upd.output_buffers.n_buffers == 3
    assert upd.output_buffers.type == "BROADCAST"


def test_worker_accepts_reference_envelope_with_repo_fragment():
    """POST a reference-shaped TaskUpdateRequest ENVELOPE (session/sources/
    outputIds/fragment, HttpRemoteTask.java:883-936) carrying a repo-IR
    fragment payload and pull SerializedPage results.  This validates the
    envelope and results protocol only; the full interop test — a
    REFERENCE-shaped fragment with reference TpchSplit splits — is
    test_plan_translation.py::test_worker_runs_reference_fragment_end_to_end."""
    import threading
    import urllib.request
    from presto_tpu.common.serde import deserialize_page
    from presto_tpu.common.block import block_to_values
    from presto_tpu.common.types import BIGINT
    from presto_tpu.sql.planner import Planner
    from presto_tpu.sql.fragmenter import FragmenterConfig, plan_distributed
    from presto_tpu.worker.server import WorkerServer

    w = WorkerServer()
    t = threading.Thread(target=w.httpd.serve_forever, daemon=True)
    t.start()
    try:
        out = Planner(default_schema="sf0.01", default_catalog="tpch") \
            .plan("SELECT count(*) AS n FROM nation")
        sub = plan_distributed(out, FragmenterConfig())
        # leaf fragment of the subplan tree
        frag = (sub.children[0].fragment if sub.children else sub.fragment)
        from presto_tpu.connectors import catalog as cat
        scans = [n for n in __import__(
            "presto_tpu.spi.plan", fromlist=["walk_plan"]).walk_plan(
                frag.root) if type(n).__name__ == "TableScanNode"]
        sources = []
        for sc in scans:
            splits = cat.make_splits(sc.table.table_name, 0.01, 1,
                                     sc.table.connector_id)
            sources.append(PP.TaskSource(sc.id, [
                PP.ScheduledSplit(i, sc.id, {
                    "connectorId": sp.connector,
                    "connectorSplit": sp.to_dict()})
                for i, sp in enumerate(splits)]).to_json())
        body = {
            "session": PP.SessionRepresentation(
                queryId="q_interop", user="test").to_json(),
            "extraCredentials": {},
            "fragment": base64.b64encode(
                json.dumps(frag.to_dict()).encode()).decode(),
            "sources": sources,
            "outputIds": PP.OutputBuffers(
                "PARTITIONED", 0, True, {"0": 0}).to_json(),
        }
        req = urllib.request.Request(
            f"{w.uri}/v1/task/q_interop.0.0.0.0",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        st = json.load(urllib.request.urlopen(req))
        assert st["state"] in ("PLANNED", "RUNNING", "FINISHED")
        assert "taskInstanceIdLeastSignificantBits" in st  # reference shape
        # pull pages until the buffer completes
        rows = []
        token = 0
        deadline = time.time() + 60
        while time.time() < deadline:
            r = urllib.request.urlopen(
                f"{w.uri}/v1/task/q_interop.0.0.0.0/results/0/{token}")
            data = r.read()
            complete = r.headers.get("X-Presto-Buffer-Complete") == "true"
            nxt = r.headers.get("X-Presto-Page-End-Sequence-Id")
            if data:
                pos = 0
                while pos < len(data):
                    page, pos = deserialize_page(data, pos)
                    rows += block_to_values(BIGINT, page.blocks[0])
            if complete:
                break
            token = int(nxt) if nxt else token + 1
            time.sleep(0.05)
        assert rows, "no pages returned"
    finally:
        w.httpd.shutdown()
